"""Fused scenario lattice: bootstrap × λ-grid × SV draws × stress shocks as
ONE donated, mesh-shardable program (docs/DESIGN.md §14; ROADMAP item 4).

The uncertainty workloads this repo inherited run as separate single-purpose
drivers — BASELINE config 5 (block bootstrap over a λ-decay grid,
``bootstrap_lambda_grid``), config 3 (SV particle-filter draw sweeps,
``estimate_sv``'s objective), and the serving layer's per-request scenario
fans — each paying its own dispatch, transfer, and allocation round.  This
module evaluates an arbitrary cross-product of

- **resample axis** (R): moving-block bootstrap index sets — generated
  IN-PROGRAM from ``key`` with the same stream as ``bootstrap_lambda_grid``,
  or passed explicitly (the mesh-sharded path),
- **λ-grid axis** (G): decay drivers, riding the MXU-fused grid-loss core
  (``bootstrap.grid_loss_core``) with the R axis on the TPU lanes,
- **SV-draw axis** (D): common-random-numbers particle-filter logliks for a
  (D, P) parameter-draw batch (``ops/particle.draw_loglik_core``),
- **shock axis** (S): a stress fan (parallel shift, twist, vol regime) of
  h-step predictive densities + sampled paths from the panel's filtered
  terminal state (``ops/forecast.density_fan``,
  ``models/simulate.simulate(start_state=)``),

in one jitted program: compile-once, launch-once, alloc-light.  The large
recurring buffers are **donated** (``donate_argnums``), and every donated
buffer's VALUES flow into an output of matching shape/dtype that aliases it
— XLA silently drops a donated argument whose contents are dead (no
aliasing, no memory reuse), so value-use + matched output is the invariant,
pinned by tests/test_scenario.py:

    resample index sets  →  gathered, then the ``resample_idx`` output
                            (R, T) integer (explicit-index path — the
                            mesh-sharded driver and recycled sweeps)
    SV draw state        →  filtered, then the ``sv_draws`` output (D, P)
    per-cell accumulator →  zeroed scan carry, then the ``losses`` output
                            (R, G)

Feeding one launch's outputs back as the next launch's inputs
(``resample_idx=prev["resample_idx"]``, ``sv_draws=prev["sv_draws"]``,
``recycle=prev`` for the accumulator) recycles exactly those buffers: the
draw batch and index sets stay device-resident across rounds with zero
re-transfer, and the loss plane reuses one allocation.

Sentinel discipline (CLAUDE.md): inside the program failures stay coded —
−Inf loss cells, −Inf PF draws, NaN-poisoned fan moments on a failed filter
pass; the only exceptions here are trace-time ``ValueError`` validations at
the driver.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import make_trace_counter, register_engine_cache
from ..models.specs import ModelSpec
from .bootstrap import (grid_loss_core, grid_stats, lambda_to_gamma,
                        moving_block_indices, resolve_grid_engine)

# trace counters (config.make_trace_counter): incremented INSIDE traced
# bodies, so they count actual (re)compilations — the no-recompile tests pin
# them across recycled launches
trace_counts, note_trace, reset_trace_counts = make_trace_counter()


# ---------------------------------------------------------------------------
# shocks
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ShockSpec:
    """One stress scenario applied at the filtered state (frozen + hashable —
    shock tuples are static builder keys).

    ``beta_shift``: state-space displacement added to the filtered mean
    (padded with zeros to the state dim; factor 0 is level, factor 1 slope
    for the DNS/AFNS orderings).  ``vol_scale`` multiplies the filtered
    covariance (the analytic density's vol regime; the shock decays through
    the Φ P Φᵀ + Ω recursion as it should).  ``sv_phi``/``sv_sigma`` arm the
    log-vol AR(1) on SAMPLED paths only (models/simulate.py's SV extension —
    the Gaussian density face has no closed form under SV)."""

    name: str
    beta_shift: Tuple[float, ...] = ()
    vol_scale: float = 1.0
    sv_phi: float = 0.0
    sv_sigma: float = 0.0


def standard_fan(spec: ModelSpec, shift: float = 0.5) -> Tuple[ShockSpec, ...]:
    """The canonical six-scenario stress fan: baseline, parallel ±``shift``
    on the level factor, steepener/flattener ±``shift`` on the slope factor,
    and a doubled-vol regime with SV-sampled paths.  ``shift`` is in yield
    units (percent, like the panels)."""
    Ms = spec.state_dim

    def e(i, s):
        return tuple(s if j == i else 0.0 for j in range(Ms))

    return (
        ShockSpec("baseline"),
        ShockSpec("parallel_up", e(0, shift)),
        ShockSpec("parallel_down", e(0, -shift)),
        ShockSpec("steepener", e(1, shift)),
        ShockSpec("flattener", e(1, -shift)),
        ShockSpec("vol_regime", vol_scale=2.0, sv_phi=0.95, sv_sigma=0.3),
    )


def _shock_arrays(shocks: Tuple[ShockSpec, ...], Ms: int, dtype):
    """(S, Ms) shifts, (S,) vol scales / sv params from static shock specs."""
    shifts = np.zeros((len(shocks), Ms))
    for i, s in enumerate(shocks):
        if len(s.beta_shift) > Ms:
            raise ValueError(
                f"shock {s.name!r} shifts {len(s.beta_shift)} factors but the "
                f"state dim is {Ms}")
        shifts[i, :len(s.beta_shift)] = s.beta_shift
    return (jnp.asarray(shifts, dtype=dtype),
            jnp.asarray([s.vol_scale for s in shocks], dtype=dtype),
            jnp.asarray([s.sv_phi for s in shocks], dtype=dtype),
            jnp.asarray([s.sv_sigma for s in shocks], dtype=dtype))


# ---------------------------------------------------------------------------
# PRNG streams — ONE documented derivation shared by the program and the
# parity tests: the resample stream is ``key`` ITSELF, so a lattice seeded
# with ``key`` reproduces ``bootstrap_lambda_grid(key=key)`` cell-for-cell.
# ---------------------------------------------------------------------------

def face_keys(key):
    """(resample, pf, paths) PRNG keys derived from the master ``key``."""
    key = jnp.asarray(key)
    return key, jax.random.fold_in(key, 1), jax.random.fold_in(key, 2)


# ---------------------------------------------------------------------------
# the fan core (shock axis): densities + sampled paths from one state
# ---------------------------------------------------------------------------

def _fan_core(spec: ModelSpec, shocks: Tuple[ShockSpec, ...], horizon: int,
              n_paths: int):
    """Plain callable ``(params, kp, beta, P, key) -> dict``: the whole
    shock fan as one vmapped density scan + one (S × n) simulate batch —
    inlined both by the lattice program and the serving fan program
    (``_jitted_fan``)."""
    from ..models.simulate import simulate
    from ..ops.forecast import density_fan

    def fan(params, kp, beta, P, key):
        shifts, vols, phis, sigs = _shock_arrays(shocks, spec.state_dim,
                                                 beta.dtype)
        out = density_fan(spec, kp, beta, P, shifts, vols, horizon)
        out = {"means": out["means"], "covs": out["covs"],
               "state_means": out["state_means"],
               "state_covs": out["state_covs"],
               "codes": out["codes"]}  # (S,) int32 per-shock taxonomy
        if n_paths > 0:
            def one_shock(shift, vol, phi_h, sig_h, k):
                start = (beta + shift, P * (vol * vol))
                return jax.vmap(
                    lambda kk: simulate(spec, params, horizon, kk,
                                        sv_phi=phi_h, sv_sigma=sig_h,
                                        start_state=start)["data"],
                    out_axes=-1)(jax.random.split(k, n_paths))

            keys = jax.random.split(key, len(shocks))
            out["paths"] = jax.vmap(one_shock)(shifts, vols, phis, sigs,
                                               keys)  # (S, N, h, n)
        return out

    return fan


# ---------------------------------------------------------------------------
# the lattice program
# ---------------------------------------------------------------------------

@register_engine_cache
@lru_cache(maxsize=16)
def _jitted_lattice(static_spec: Optional[ModelSpec],
                    kalman_spec: Optional[ModelSpec],
                    T: int, R: int, G: int, D: int,
                    shocks: Tuple[ShockSpec, ...], horizon: int, n_paths: int,
                    n_particles: int, sv_phi: float, sv_sigma: float,
                    block_len: int, grid_engine: str, gen_idx: bool,
                    moment_engine: str, with_stats: bool, donate: bool):
    """Build (and cache) ONE lattice program for a static configuration.
    Absent faces (R/D/S of 0) are simply not traced — the degenerate 1×1×1
    lattice is the same program shape as the full sweep.  ``donate`` keys a
    separate program so the bit-identical donated-vs-not parity test can
    hold both."""
    from ..ops.particle import draw_loglik_core

    S = len(shocks)

    def run(key, idx, gammas, static_params, kalman_params, data, sv_draws,
            acc):
        note_trace("lattice")
        k_idx, k_pf, k_paths = face_keys(key)
        out = {}
        if R > 0:
            idx_arr = (moving_block_indices(k_idx, T, block_len, R)
                       if gen_idx else idx)
            core = grid_loss_core(static_spec, T, grid_engine)
            losses = core(gammas, idx_arr, static_params, data, acc)
            out["losses"] = losses
            out["resample_idx"] = idx_arr  # pass-through: aliases donated idx
        if D > 0:
            pf = draw_loglik_core(kalman_spec, n_particles, sv_phi, sv_sigma)
            out["pf_logliks"] = pf(sv_draws, data, k_pf)
            out["sv_draws"] = sv_draws     # pass-through: aliases donated draws
        if R > 0 and with_stats:
            out["ci_low"], out["ci_high"], out["selection_freq"] = \
                grid_stats(out["losses"], G)
        if S > 0:
            from ..ops.smoother import forward_moments

            kp, outs = forward_moments(kalman_spec, kalman_params, data,
                                       0, T, moment_engine)
            beta, P = outs["beta_upd"][-1], outs["P_upd"][-1]
            ok = jnp.all(outs["ll"] > -jnp.inf)
            fan = _fan_core(kalman_spec, shocks, horizon, n_paths)(
                kalman_params, kp, beta, P, k_paths)
            nan = jnp.asarray(jnp.nan, dtype=beta.dtype)
            # failed filter pass → NaN-poisoned fan + state (sentinel; the
            # driver layer owns the error policy, CLAUDE.md conventions).
            # The int32 per-shock codes can't carry NaN — they pick up the
            # filter failure as a NAN_STATE bit instead.
            codes = fan.pop("codes")
            out["fan"] = {k: jnp.where(ok, v, nan) for k, v in fan.items()}
            from ..robustness import taxonomy as tax
            out["fan"]["codes"] = jnp.where(ok, codes,
                                            codes | jnp.int32(tax.NAN_STATE))
            out["state_beta"] = jnp.where(ok, beta, nan)
            out["state_P"] = jnp.where(ok, P, nan)
        return out

    donate_argnums = []
    if donate:
        if R > 0 and not gen_idx:
            donate_argnums.append(1)   # idx ← resample_idx output (R, T)
        if D > 0:
            donate_argnums.append(6)   # sv_draws ← sv_draws output (D, P)
        if R > 0 and grid_engine == "fused":
            donate_argnums.append(7)   # acc ← losses output (R, G); the
            # scan core never reads acc (XLA drops dead donated args)
    return jax.jit(run, donate_argnums=tuple(donate_argnums))


def _recycled(recycle, key_path, shape, dtype):
    """Fetch a recyclable buffer from a previous launch's result dict:
    shape/dtype must match the current configuration and the buffer must not
    already be consumed (a dict can only be recycled once) — anything else
    falls back to a fresh zero buffer of the right signature."""
    buf = recycle
    for k in key_path:
        buf = buf.get(k) if isinstance(buf, dict) else None
        if buf is None:
            break
    if (buf is not None and isinstance(buf, jax.Array)
            and not buf.is_deleted()
            and buf.shape == shape and buf.dtype == jnp.dtype(dtype)):
        return buf
    return jnp.zeros(shape, dtype=dtype)


def evaluate_lattice(
    data,
    *,
    static_spec: Optional[ModelSpec] = None,
    static_params=None,
    lambda_grid=None,
    n_resamples: int = 0,
    block_len: int = 12,
    resample_idx=None,
    grid_engine: str = "auto",
    kalman_spec: Optional[ModelSpec] = None,
    kalman_params=None,
    sv_draws=None,
    n_particles: int = 200,
    sv_phi: float = 0.95,
    sv_sigma: float = 0.2,
    shocks: Tuple[ShockSpec, ...] = (),
    horizon: int = 12,
    n_paths: int = 0,
    key=None,
    donate: bool = True,
    recycle: Optional[dict] = None,
    with_stats: bool = True,
) -> dict:
    """Evaluate a (resample × λ × SV-draw × shock) scenario lattice in ONE
    program launch.  Every axis is optional; present faces return:

    - bootstrap face (``static_spec`` + ``static_params`` + ``lambda_grid``
      + ``n_resamples``/``resample_idx``): ``losses`` (R, G),
      ``resample_idx`` (R, T), and — under ``with_stats`` — the
      ``bootstrap_lambda_grid`` CI/selection stats.  Seeding with ``key``
      reproduces ``bootstrap_lambda_grid(key=key)`` cell-for-cell (same
      index stream, same engine dispatch).
    - SV-draw face (``kalman_spec`` + ``sv_draws`` (D, P) constrained):
      ``pf_logliks`` (D,) — the common-random-numbers PF logliks
      ``estimation/sv.pf_draw_logliks`` computes, at ``face_keys(key)[1]``.
    - shock face (``kalman_spec`` + ``kalman_params`` + ``shocks``): the
      panel is filtered once in-program and ``fan`` carries per-shock
      ``means`` (S, h, N) / ``covs`` (S, h, N, N) predictive densities plus
      — with ``n_paths`` — sampled ``paths`` (S, N, h, n); ``state_beta``/
      ``state_P`` return the filtered origin state.  A failed filter pass
      NaN-poisons the fan (sentinel), never raises.

    ``donate=True`` (default) donates the recurring buffers (module
    docstring): an explicitly passed device-array ``resample_idx`` or
    ``sv_draws`` is CONSUMED by the launch (its values come back as the
    same-named output — re-feed that next round; pass NumPy if the caller
    keeps a copy), and ``recycle=`` takes a previous launch's result to
    reuse its loss-plane allocation as this launch's accumulator.
    ``with_stats=False`` skips the in-program CI/selection stats (the
    mesh-sharded driver trims padding first and redoes them host-side).
    """
    faces = []
    # ---- bootstrap face -------------------------------------------------
    R = G = 0
    gammas = idx_arg = None
    gen_idx = resample_idx is None
    if lambda_grid is not None or n_resamples or resample_idx is not None:
        if static_spec is None or static_params is None or lambda_grid is None:
            raise ValueError(
                "the bootstrap face needs static_spec, static_params AND "
                "lambda_grid (plus n_resamples or resample_idx)")
        if not gen_idx:
            # keep the caller's integer dtype: a forced cast would silently
            # COPY the buffer and the copy, not the caller's array, would be
            # donated — breaking the consume-and-recycle contract
            idx_arg = jnp.asarray(resample_idx)
            if not jnp.issubdtype(idx_arg.dtype, jnp.integer):
                raise ValueError(
                    f"resample_idx must be integer time indices, got "
                    f"{idx_arg.dtype}")
            R = int(idx_arg.shape[0])
        else:
            R = int(n_resamples)
        if R < 1:
            raise ValueError("the bootstrap face needs n_resamples >= 1 "
                             "or an explicit resample_idx")
        G = int(np.shape(lambda_grid)[0])
        faces.append("bootstrap")
    # ---- SV-draw face ---------------------------------------------------
    D = 0
    if sv_draws is not None:
        if kalman_spec is None:
            raise ValueError("the SV-draw face needs kalman_spec")
        sv_draws = jnp.asarray(sv_draws, dtype=kalman_spec.dtype)
        if sv_draws.ndim == 1:
            sv_draws = sv_draws[None, :]
        D = int(sv_draws.shape[0])
        faces.append("sv")
    # ---- shock face -----------------------------------------------------
    shocks = tuple(shocks)
    if shocks:
        if kalman_spec is None or kalman_params is None:
            raise ValueError("the shock face needs kalman_spec and "
                             "kalman_params")
        if not kalman_spec.is_kalman:
            raise ValueError(
                f"the shock face needs a Kalman family with a filtered "
                f"state; {kalman_spec.family!r} has none")
        if int(horizon) < 1:
            raise ValueError(f"horizon must be >= 1, got {horizon}")
        faces.append("fan")
    if not faces:
        raise ValueError("empty lattice: give at least one of the bootstrap "
                         "(lambda_grid), SV-draw (sv_draws) or shock "
                         "(shocks) axes")
    if static_spec is not None and kalman_spec is not None \
            and static_spec.dtype != kalman_spec.dtype:
        raise ValueError("static_spec and kalman_spec dtypes differ — the "
                         "lattice shares one panel")

    spec0 = kalman_spec if kalman_spec is not None else static_spec
    dtype = spec0.dtype
    data = jnp.asarray(data, dtype=dtype)
    T = int(data.shape[1])
    if key is None:
        key = jax.random.PRNGKey(0)

    # static resolutions (eager — concrete data; baked into the trace)
    resolved_engine = (resolve_grid_engine(static_spec, data, grid_engine)
                      if R else "scan")
    from .. import config
    moment_engine = config.kalman_engine()
    if moment_engine not in ("joint", "univariate"):
        moment_engine = "univariate"  # loglik-only engines have no moments

    if R:
        gammas = lambda_to_gamma(jnp.asarray(lambda_grid, dtype=dtype))
        static_params = jnp.asarray(static_params, dtype=dtype)
    if shocks:
        kalman_params = jnp.asarray(kalman_params, dtype=dtype)

    recycle = recycle or {}
    acc = None
    if R and donate and resolved_engine == "fused":
        acc = _recycled(recycle, ("losses",), (R, G), dtype)

    fn = _jitted_lattice(static_spec, kalman_spec, T, R, G, D, shocks,
                         int(horizon), int(n_paths), int(n_particles),
                         float(sv_phi), float(sv_sigma), int(block_len),
                         resolved_engine, bool(gen_idx) if R else True,
                         moment_engine, bool(with_stats), bool(donate))
    return fn(jnp.asarray(key), idx_arg, gammas, static_params,
              kalman_params, data, sv_draws, acc)


# ---------------------------------------------------------------------------
# the serving fan program (one launch per stress-fan request)
# ---------------------------------------------------------------------------

@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_fan(spec: ModelSpec, shocks: Tuple[ShockSpec, ...], horizon: int,
                n_paths: int):
    """The serving-side shock fan: ``(params, beta, P, key) -> fan dict``
    from an ALREADY-FILTERED state (a :class:`~..serving.snapshot.
    ServingSnapshot`'s moments) — one launch for the whole fan instead of
    one scenario program per shock (``serving/service.py`` routes
    ``scenarios(shocks=...)`` here).  No donation: the serving state is
    long-lived and must survive the call."""
    from ..models.params import unpack_kalman

    core = _fan_core(spec, shocks, horizon, n_paths)

    def fan(params, beta, P, key):
        note_trace("fan")
        kp = unpack_kalman(spec, params)
        return core(params, kp, beta, P, key)

    return jax.jit(fan)


def stress_fan(spec: ModelSpec, params, beta, P,
               shocks: Tuple[ShockSpec, ...], horizon: int, n_paths: int,
               key=None) -> dict:
    """One-launch stress fan from filtered moments (β, P): per-shock
    predictive densities (+ sampled paths with ``n_paths``).  The serving
    entry (``YieldCurveService.scenarios(shocks=...)``) and the QUICKSTART
    walkthrough both come through here."""
    shocks = tuple(shocks)
    if not shocks:
        raise ValueError("stress_fan needs at least one ShockSpec")
    if key is None:
        key = jax.random.PRNGKey(0)
    fn = _jitted_fan(spec, shocks, int(horizon), int(n_paths))
    return fn(jnp.asarray(params, dtype=spec.dtype),
              jnp.asarray(beta, dtype=spec.dtype),
              jnp.asarray(P, dtype=spec.dtype), jnp.asarray(key))


# ---------------------------------------------------------------------------
# historical replay episodes: shocks read FROM a panel
# ---------------------------------------------------------------------------

def replay_episodes(spec: ModelSpec, params, panel, episodes, *,
                    name_prefix: str = "replay", engine=None
                    ) -> Tuple[ShockSpec, ...]:
    """Compile historical stress episodes into :class:`ShockSpec`\\ s: for
    each ``(start, end)`` column pair the panel is filtered once and the
    episode's factor move ``β_{end|end} − β_{start|start}`` becomes that
    shock's ``beta_shift`` — "replay the 2013 taper tantrum on today's
    curve" as a first-class fan member (DESIGN §23).  ``episodes`` is an
    iterable of ``(start, end)`` (0-based column indices, ``end``
    inclusive) or ``(start, end, name)``; driver layer, so a failed filter
    pass raises a loud ``ValueError`` (first-iteration structural failure)
    rather than returning a poisoned shock dictionary."""
    from ..ops.smoother import forward_moments

    if not spec.is_kalman:
        raise ValueError(
            f"replay_episodes needs a Kalman family with a filtered state "
            f"path; {spec.family!r} has none")
    data = jnp.asarray(panel, dtype=spec.dtype)
    T = int(data.shape[1])
    _, outs = forward_moments(spec, jnp.asarray(params, dtype=spec.dtype),
                              data, 0, T, engine)
    if not bool(jnp.all(outs["ll"] > -jnp.inf)):
        raise ValueError("replay_episodes: the filter pass over the episode "
                         "panel failed — cannot read factor moves from a "
                         "broken state path")
    beta_path = np.asarray(outs["beta_upd"])  # (T, Ms)
    shocks = []
    for ep in episodes:
        if len(ep) == 3:
            start, end, name = ep
        else:
            (start, end), name = ep, None
        start, end = int(start), int(end)
        if not (0 <= start < end < T):
            raise ValueError(
                f"replay episode ({start}, {end}) out of range for a "
                f"T={T} panel (need 0 <= start < end < T)")
        shift = beta_path[end] - beta_path[start]
        shocks.append(ShockSpec(
            name or f"{name_prefix}_{start}_{end}",
            beta_shift=tuple(float(v) for v in shift)))
    if not shocks:
        raise ValueError("replay_episodes: no episodes given")
    return tuple(shocks)


# ---------------------------------------------------------------------------
# the refit column: per-resample re-estimation (bootstrap-refit workload)
# ---------------------------------------------------------------------------

@register_engine_cache
@lru_cache(maxsize=16)
def _jitted_refit_column(spec: ModelSpec, T: int, max_iters: int,
                         g_tol: float, f_abstol: float):
    """(R, S)-batched multi-start LBFGS over resampled panels — every
    resample's whole start batch optimizes in ONE jitted program (the
    refit analogue of the lattice's evaluation plane)."""
    from .optimize import _finite_objective, _run_lbfgs

    def single(x0, panel):
        fun = lambda p: _finite_objective(spec, panel, p, 0, T)
        return _run_lbfgs(fun, x0, max_iters, g_tol, f_abstol)

    over_starts = jax.vmap(single, in_axes=(0, None))      # starts
    over_resamples = jax.vmap(over_starts, in_axes=(None, 0))  # resamples
    return jax.jit(over_resamples)


@register_engine_cache
@lru_cache(maxsize=16)
def _jitted_refit_column_warm(spec: ModelSpec, T: int, max_iters: int,
                              g_tol: float, f_abstol: float):
    """The amortized-warm-start twin of :func:`_jitted_refit_column`: each
    resample brings its OWN start matrix (the surrogate's per-panel warm
    starts, docs/DESIGN.md §20), so the start axis is vmapped per resample
    instead of shared — X0 is (R, S, P) rather than (S, P)."""
    from .optimize import _finite_objective, _run_lbfgs

    def single(x0, panel):
        fun = lambda p: _finite_objective(spec, panel, p, 0, T)
        return _run_lbfgs(fun, x0, max_iters, g_tol, f_abstol)

    over_starts = jax.vmap(single, in_axes=(0, None))      # starts
    over_resamples = jax.vmap(over_starts, in_axes=(0, 0))  # resamples
    return jax.jit(over_resamples)


@register_engine_cache
@lru_cache(maxsize=16)
def _jitted_refit_polish(spec: ModelSpec, T: int, max_iters: int,
                         g_tol: float, f_abstol: float, mode: str):
    """Resample-vmapped trust-region Newton-CG polish for the refit column
    (the cascade's second phase, ops/newton.polish)."""
    from ..ops import newton as _newton

    def one(X0, panel):
        return _newton.polish(spec, X0, panel, 0, T, max_iters=max_iters,
                              g_tol=g_tol, f_abstol=f_abstol, mode=mode)

    return jax.jit(jax.vmap(one, in_axes=(0, 0)))


def refit_column(spec: ModelSpec, data, resample_idx, raw_starts, *,
                 max_iters: int = 100, g_tol: float = 1e-6,
                 f_abstol: float = 1e-6, second_order=None, warm_start=None):
    """Re-ESTIMATE the model on every bootstrap resample — the lattice's
    refit column (parameter-uncertainty CIs, vs the fixed-parameter loss
    plane ``evaluate_lattice`` evaluates).

    ``resample_idx`` (R, T) integer index sets (``moving_block_indices`` or
    a recycled ``resample_idx`` output of :func:`evaluate_lattice`);
    ``raw_starts`` (S, P) unconstrained starts shared by every resample.
    All R×S optimizations run as one jitted program; ``second_order``
    (None = the ``YFM_NEWTON`` knob, as in ``optimize.estimate``) arms the
    coarse-LBFGS → Newton-polish cascade per resample.  ``warm_start``
    (None = the ``YFM_AMORT`` knob) replaces the shared spray with
    PER-RESAMPLE amortized starts: ONE batched surrogate forward pass over
    all R resampled panels, each resample's amortized point + jittered
    neighbors (+ the caller's first start as anchor) — the warm twin
    program vmaps the start axis per resample (docs/DESIGN.md §20).

    Returns ``(params (R, S, P) unconstrained, logliks (R, S))`` — pick
    per-resample winners with argmax, same contract as
    ``optimize.estimate_windows``.
    """
    from .optimize import (_NEWTON_COARSE_G_TOL, _NEWTON_COARSE_ITERS,
                           _NEWTON_POLISH_ITERS, _resolve_second_order,
                           _resolve_warm_start)

    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    idx = jnp.asarray(resample_idx, dtype=jnp.int32)
    if idx.ndim != 2 or idx.shape[1] != T:
        raise ValueError(f"resample_idx must be (R, T); got {idx.shape} "
                         f"for T={T}")
    panels = jnp.swapaxes(data[:, idx], 0, 1)  # (R, N, T)
    X0 = jnp.asarray(raw_starts, dtype=spec.dtype)
    so_mode = _resolve_second_order(second_order)
    if so_mode:
        p1 = (min(max_iters, _NEWTON_COARSE_ITERS),
              max(g_tol, _NEWTON_COARSE_G_TOL), f_abstol)
    else:
        p1 = (max_iters, g_tol, f_abstol)
    am = _resolve_warm_start(spec, warm_start)
    if am is not None:
        raw_np = np.asarray(raw_starts, dtype=np.float64)
        R = int(panels.shape[0])
        warm = am.starts_batch(np.asarray(panels), fallback_raw=raw_np[0])
        anchor = np.broadcast_to(raw_np[None, :1], (R, 1, raw_np.shape[1]))
        X0 = jnp.asarray(np.concatenate([warm, anchor], axis=1),
                         dtype=spec.dtype)               # (R, S_w, P)
        runner = _jitted_refit_column_warm(spec, T, *p1)
    else:
        runner = _jitted_refit_column(spec, T, *p1)
    xs, fs, its, convs = runner(X0, panels)
    if so_mode:
        polish = _jitted_refit_polish(spec, T, _NEWTON_POLISH_ITERS,
                                      g_tol, f_abstol, so_mode)
        res = polish(xs, panels)
        took = np.asarray((res.iters > 0) | res.converged)
        xs = np.where(took[:, :, None], np.asarray(res.x, dtype=np.float64),
                      np.asarray(xs, dtype=np.float64))
        return xs, np.where(took, -np.asarray(res.f, dtype=np.float64),
                            -np.asarray(fs, dtype=np.float64))
    return xs, -fs
