"""Asymptotic inference for MLE fits: observed-information standard errors.

Beyond-reference capability (the reference reports point estimates only —
optimization.jl surfaces the loglik and parameters, never a covariance).
Everything is exact AD: the observed information is ``-jax.hessian`` of the
loglik in the UNCONSTRAINED space (where the optimizers run and where the
quadratic approximation is best behaved), and the covariance is transported
to the constrained space by the delta method through the bijection pytree,

    cov_θ = J cov_raw Jᵀ,   J = ∂ transform(raw) / ∂ raw |_raŵ.

Jittable end to end; vmap over a batch of fits for draw-level inference.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..config import register_engine_cache
from ..models import api
from ..models.params import transform_params, untransform_params
from ..models.specs import ModelSpec

#: engines whose forward pass emits per-step loglik contributions ∂ℓ_t
#: (the joint form's per-step Cholesky decomposition and its Cholesky-free
#: univariate twin).  "sqrt" accumulates the loglik inside the Potter carry
#: and "assoc" computes it from composed moments — neither exposes the
#: per-step decomposition the sandwich B-matrix needs.
PER_STEP_LL_ENGINES = ("joint", "univariate")


class PerStepContributionsUnavailable(ValueError):
    """Per-step loglik contributions were requested from a loglik-only
    engine.  Structured (``engine``/``supported`` attributes) so drivers can
    branch on it instead of string-matching, and a ``ValueError`` so generic
    config-validation handlers still catch it."""

    def __init__(self, engine: str, what: str = "per-step loglik "
                 "contributions"):
        self.engine = engine
        self.supported = PER_STEP_LL_ENGINES
        super().__init__(
            f"engine {engine!r} has no per-step loglik decomposition — "
            f"{what} are available from the "
            f"{' and '.join(repr(e) for e in PER_STEP_LL_ENGINES)} engines "
            f"only; pass engine= explicitly or "
            f"config.set_kalman_engine('univariate')")


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_information(spec: ModelSpec, T: int):
    def info(raw, data, start, end):
        def nll(r):
            return -api.get_loss(spec, transform_params(spec, r), data, start, end)

        H = jax.hessian(nll)(raw)                       # observed information
        J = jax.jacobian(lambda r: transform_params(spec, r))(raw)
        return H, J

    return jax.jit(info)


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_score_contributions(spec: ModelSpec, T: int, engine: str):
    """(T, P) per-step score matrix ∂ℓ_t/∂raw for the sandwich B-matrix —
    Kalman families only (their per-step outs['ll'] ARE loglik contributions;
    the prediction-error families' per-t losses are MSE terms, for which the
    QMLE sandwich is not the standard estimator).

    ``engine``: "joint" (per-step Cholesky) or "univariate" (Cholesky-free
    sequential updates — same per-step ll decomposition, Koopman–Durbin).
    Any other engine ("sqrt"/"assoc" don't emit per-step contributions)
    raises :class:`PerStepContributionsUnavailable` HERE, at the builder —
    the guard is enforced for every caller, not promised in a comment
    (``mle_standard_errors`` re-checks earlier only to fail before paying
    the Hessian).  A failed f32 factorization surfaces as NaN scores,
    guarded by the caller; rerun in float64 in that case.

    jacfwd, not jacrev: the map is R^P → R^T with T ≫ P, so P forward JVPs
    beat T backward scan passes (and skip the O(T) residual stash)."""
    from ..models import kalman as K
    from ..ops import univariate_kf

    if engine not in PER_STEP_LL_ENGINES:
        raise PerStepContributionsUnavailable(engine)

    def scores(raw, data, start, end):
        def contribs(r):
            if engine == "univariate":
                _, outs = univariate_kf.filter_moments(
                    spec, transform_params(spec, r), data, start, end)
            else:
                _, _, _, outs = K._scan_filter(
                    spec, transform_params(spec, r), data, start, end)
            mask = K.loglik_contrib_mask(start, end, data.shape[1])
            return jnp.where(mask, outs["ll"], 0.0)

        return jax.jacfwd(contribs)(raw)

    return jax.jit(scores)


def mle_standard_errors(spec: ModelSpec, params_hat, data, start=0, end=None,
                        rcond: float = 1e-10, kind: str = "hessian",
                        engine=None):
    """Standard errors and covariance of a fitted CONSTRAINED parameter vector.

    ``kind="hessian"`` (default): observed-information covariance H⁻¹.
    ``kind="sandwich"``: the QMLE-robust Bollerslev–Wooldridge estimator
    H⁻¹ B H⁻¹ with B = Σ_t s_t s_tᵀ from the per-step score contributions
    (Kalman families only — valid under misspecified innovation densities).

    ``engine`` (sandwich only): forward engine for the per-step score
    decomposition — ``None`` reads ``config.kalman_engine()``; "joint" and
    "univariate" are supported ("sqrt"/"assoc" don't emit per-step ll
    contributions and raise).  The Hessian half always honors the configured
    loglik engine through ``api.get_loss``.

    Returns ``(se, cov, cov_raw)``: delta-method standard errors (P,) and
    covariance (P, P) in the constrained space, plus the raw-space covariance.

    Flat/indefinite handling (per-direction, via the eigendecomposition of
    the information matrix): eigendirections with eigenvalue ≤ rcond · λ_max
    (numerically unidentified) or ≤ 0 (not at a maximum) are excluded from
    the pseudo-inverse, and every parameter with non-negligible loading on an
    excluded direction gets ``se = NaN`` — near-singular information would
    otherwise pass ``np.linalg.inv`` by float64 luck and surface as
    astronomically large but finite "standard errors".
    """
    if kind not in ("hessian", "sandwich"):
        raise ValueError(f"kind must be 'hessian' or 'sandwich', got {kind!r}")
    if kind == "sandwich" and not spec.is_kalman:
        raise ValueError(
            "kind='sandwich' needs per-step loglik contributions — Kalman "
            "families only (the prediction-error families' per-t terms are "
            "MSE contributions, not scores of a likelihood)")
    if kind == "sandwich":
        from .. import config

        eng = engine or config.kalman_engine()
        if eng not in PER_STEP_LL_ENGINES:
            raise PerStepContributionsUnavailable(
                eng, what="sandwich (QMLE-robust) standard errors")
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    if end is None:
        end = T
    raw = untransform_params(spec, jnp.asarray(params_hat, dtype=spec.dtype))
    H, J = _jitted_information(spec, T)(raw, data, jnp.asarray(start),
                                        jnp.asarray(end))
    H = np.asarray(H, dtype=np.float64)
    J = np.asarray(J, dtype=np.float64)
    P = H.shape[0]
    Hs = 0.5 * (H + H.T)
    if not np.isfinite(Hs).all():
        nanm = np.full((P, P), np.nan)
        return np.full(P, np.nan), nanm, nanm
    w, V = np.linalg.eigh(Hs)
    good = w > rcond * max(w.max(), 0.0)
    inv_w = np.where(good, 1.0 / np.where(good, w, 1.0), 0.0)
    Ainv = (V * inv_w) @ V.T                       # pseudo-inverse over good
    if kind == "sandwich":
        S = np.asarray(_jitted_score_contributions(spec, T, eng)(
            raw, data, jnp.asarray(start), jnp.asarray(end)), dtype=np.float64)
        if not np.isfinite(S).all():   # failed f32 forward pass
            nanm = np.full((P, P), np.nan)
            return np.full(P, np.nan), nanm, nanm
        B = S.T @ S                                # Σ_t s_t s_tᵀ  (s_t = ∂ℓ_t)
        cov_raw = Ainv @ B @ Ainv
    else:
        cov_raw = Ainv
    cov_raw = 0.5 * (cov_raw + cov_raw.T)
    # a parameter is unidentified iff it loads on any excluded direction.
    # The loading test is separate from the eigenvalue rcond (ADVICE r2): the
    # old rule (squared loadings summed > rcond = 1e-10, i.e. |V| ≳ 1e-5) let
    # a small-but-real loading (e.g. 1e-6) escape the mask — and since the
    # pseudo-inverse zeroes excluded directions, the escaped parameter's
    # variance is UNDERestimated: a falsely confident finite SE.  The per-
    # component threshold 1e-6 on |V| catches that while staying above eigh's
    # eigenvector mixing noise (~eps·λmax/gap) for near-degenerate pairs
    # straddling the rcond cutoff, which sqrt(eps) ≈ 1.5e-8 would not.
    load_tol = 1e-6
    bad_load = (np.abs(V[:, ~good]) >= load_tol).any(axis=1)
    cov = J @ cov_raw @ J.T
    cov = 0.5 * (cov + cov.T)
    var = np.diagonal(cov).copy()
    # transport the unidentified mask through the (elementwise) bijections:
    # J is diagonal-dominant per construction, mark any constrained param
    # whose raw source is unidentified
    bad_c = (np.abs(J[:, bad_load]) > 0).any(axis=1) if bad_load.any() else \
        np.zeros(var.shape[0], dtype=bool)
    var[bad_c] = np.nan
    var[var < 0] = np.nan
    return np.sqrt(var), cov, cov_raw
