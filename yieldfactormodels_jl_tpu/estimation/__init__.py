from . import optimize, neldermead

__all__ = ["optimize", "neldermead"]
