from . import optimize, neldermead

__all__ = ["optimize", "neldermead", "bootstrap", "sv", "inference",
           "scenario", "amortize"]


def __getattr__(name):
    # lazy: bootstrap/sv/scenario pull in the particle filter / grid engines
    if name in __all__:
        import importlib

        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)
