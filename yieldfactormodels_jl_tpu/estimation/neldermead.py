"""Jittable Nelder–Mead simplex optimizer.

The reference uses Optim.jl's ``NelderMead()`` for parameter groups "1"/"4"
(/root/reference/src/optimization.jl:476-494).  Optim.jl has no JAX
counterpart, so this is a from-scratch implementation of the same algorithm
family with Optim.jl's documented conventions (SURVEY.md §7 "optimizer parity
… documented, tested replacements rather than bit-parity"):

- adaptive parameters α=1, β=1+2/n, γ=0.75−1/(2n), δ=1−1/n,
- affine initial simplex x_j = x0 + (0.025 + 0.05·x0_j)·e_j,
- convergence when the simplex f-value standard deviation < ``f_tol``.

Implemented as a ``lax.while_loop`` so the whole optimization jits; the shrink
branch is a ``lax.cond`` and stays cheap when the function is a scan loss.
Minimizes ``fun``.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class NMState(NamedTuple):
    simplex: jnp.ndarray  # (n+1, n)
    fvals: jnp.ndarray    # (n+1,)
    it: jnp.ndarray       # ()
    n_fev: jnp.ndarray    # ()


def _initial_simplex(x0, step=None):
    n = x0.shape[0]
    pts = jnp.broadcast_to(x0, (n, n))
    if step is None:
        step = 0.025 + 0.05 * x0
    pts = pts + jnp.diag(step * jnp.ones_like(x0))
    return jnp.concatenate([x0[None, :], pts], axis=0)


def nelder_mead(
    fun: Callable,
    x0,
    max_iters: int = 500,
    f_tol: float = 1e-8,
    step=None,
):
    """Returns (x_best, f_best, n_iters).

    ``step``: optional scalar or (n,) per-coordinate initial simplex offsets.
    The default (0.025 + 0.05·x₀) suits parameters already near scale 1; a
    coordinate that must travel far (e.g. the SV hyperparameters' raw
    bijection values, estimation/sv.py) needs a commensurate step or the
    simplex spends its budget expanding."""
    n = x0.shape[0]
    alpha = 1.0
    beta = 1.0 + 2.0 / n
    gamma = 0.75 - 1.0 / (2.0 * n)
    delta = 1.0 - 1.0 / n

    simplex0 = _initial_simplex(x0, step)
    fvals0 = jax.vmap(fun)(simplex0)
    state0 = NMState(simplex0, fvals0, jnp.zeros((), jnp.int32), jnp.asarray(n + 1, jnp.int32))

    def cond(state):
        # NaN-safe: a simplex full of NaN stops via the std test being False
        fstd = jnp.std(jnp.nan_to_num(state.fvals, nan=jnp.inf, posinf=1e30))
        return (state.it < max_iters) & (fstd > f_tol)

    def body(state):
        order = jnp.argsort(state.fvals)
        simplex = state.simplex[order]
        fvals = state.fvals[order]
        best, worst = simplex[0], simplex[-1]
        f_best, f_second, f_worst = fvals[0], fvals[-2], fvals[-1]
        centroid = jnp.mean(simplex[:-1], axis=0)

        xr = centroid + alpha * (centroid - worst)
        fr = fun(xr)

        def do_expand(_):
            xe = centroid + beta * (xr - centroid)
            fe = fun(xe)
            x_new, f_new = lax.cond(fe < fr, lambda: (xe, fe), lambda: (xr, fr))
            return simplex.at[-1].set(x_new), fvals.at[-1].set(f_new), jnp.asarray(1, jnp.int32)

        def do_reflect(_):
            return simplex.at[-1].set(xr), fvals.at[-1].set(fr), jnp.asarray(0, jnp.int32)

        def do_contract_or_shrink(_):
            def outside(_):
                xc = centroid + gamma * (xr - centroid)
                fc = fun(xc)
                ok = fc <= fr
                return xc, fc, ok

            def inside(_):
                xc = centroid - gamma * (xr - centroid)
                fc = fun(xc)
                ok = fc < f_worst
                return xc, fc, ok

            xc, fc, ok = lax.cond(fr < f_worst, outside, inside, operand=None)

            def accept(_):
                return simplex.at[-1].set(xc), fvals.at[-1].set(fc), jnp.asarray(1, jnp.int32)

            def shrink(_):
                new_simplex = best[None, :] + delta * (simplex - best[None, :])
                new_simplex = new_simplex.at[0].set(best)
                new_f = jax.vmap(fun)(new_simplex)
                new_f = new_f.at[0].set(f_best)
                return new_simplex, new_f, jnp.asarray(n, jnp.int32)

            return lax.cond(ok, accept, shrink, operand=None)

        new_simplex, new_fvals, extra = lax.cond(
            fr < f_best,
            do_expand,
            lambda _: lax.cond(fr < f_second, do_reflect, do_contract_or_shrink, operand=None),
            operand=None,
        )
        return NMState(new_simplex, new_fvals, state.it + 1, state.n_fev + 1 + extra)

    final = lax.while_loop(cond, body, state0)
    i_best = jnp.argmin(final.fvals)
    return final.simplex[i_best], final.fvals[i_best], final.it
