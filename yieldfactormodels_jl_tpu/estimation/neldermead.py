"""Jittable Nelder–Mead simplex optimizer.

The reference uses Optim.jl's ``NelderMead()`` for parameter groups "1"/"4"
(/root/reference/src/optimization.jl:476-494).  Optim.jl has no JAX
counterpart, so this is a from-scratch implementation of the same algorithm
family with Optim.jl's documented conventions (SURVEY.md §7 "optimizer parity
… documented, tested replacements rather than bit-parity"):

- adaptive parameters α=1, β=1+2/n, γ=0.75−1/(2n), δ=1−1/n,
- affine initial simplex x_j = x0 + (0.025 + 0.05·x0_j)·e_j,
- convergence when the simplex f-value standard deviation < ``f_tol``.

Implemented as a ``lax.while_loop`` so the whole optimization jits; the shrink
branch is a ``lax.cond`` and stays cheap when the function is a scan loss.
Minimizes ``fun``.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class NMState(NamedTuple):
    simplex: jnp.ndarray  # (n+1, n)
    fvals: jnp.ndarray    # (n+1,)
    it: jnp.ndarray       # ()
    n_fev: jnp.ndarray    # ()


def _initial_simplex(x0, step=None):
    n = x0.shape[0]
    pts = jnp.broadcast_to(x0, (n, n))
    if step is None:
        step = 0.025 + 0.05 * x0
    pts = pts + jnp.diag(step * jnp.ones_like(x0))
    return jnp.concatenate([x0[None, :], pts], axis=0)


def nelder_mead(
    fun: Callable,
    x0,
    max_iters: int = 500,
    f_tol: float = 1e-8,
    step=None,
):
    """Returns (x_best, f_best, n_iters).

    ``step``: optional scalar or (n,) per-coordinate initial simplex offsets.
    The default (0.025 + 0.05·x₀) suits parameters already near scale 1; a
    coordinate that must travel far (e.g. the SV hyperparameters' raw
    bijection values, estimation/sv.py) needs a commensurate step or the
    simplex spends its budget expanding."""
    n = x0.shape[0]
    alpha = 1.0
    beta = 1.0 + 2.0 / n
    gamma = 0.75 - 1.0 / (2.0 * n)
    delta = 1.0 - 1.0 / n

    simplex0 = _initial_simplex(x0, step)
    fvals0 = jax.vmap(fun)(simplex0)
    state0 = NMState(simplex0, fvals0, jnp.zeros((), jnp.int32), jnp.asarray(n + 1, jnp.int32))

    def cond(state):
        # NaN-safe: a simplex full of NaN stops via the std test being False
        fstd = jnp.std(jnp.nan_to_num(state.fvals, nan=jnp.inf, posinf=1e30))
        return (state.it < max_iters) & (fstd > f_tol)

    def body(state):
        order = jnp.argsort(state.fvals)
        simplex = state.simplex[order]
        fvals = state.fvals[order]
        best, worst = simplex[0], simplex[-1]
        f_best, f_second, f_worst = fvals[0], fvals[-2], fvals[-1]
        centroid = jnp.mean(simplex[:-1], axis=0)

        xr = centroid + alpha * (centroid - worst)
        fr = fun(xr)

        def do_expand(_):
            xe = centroid + beta * (xr - centroid)
            fe = fun(xe)
            x_new, f_new = lax.cond(fe < fr, lambda: (xe, fe), lambda: (xr, fr))
            return simplex.at[-1].set(x_new), fvals.at[-1].set(f_new), jnp.asarray(1, jnp.int32)

        def do_reflect(_):
            return simplex.at[-1].set(xr), fvals.at[-1].set(fr), jnp.asarray(0, jnp.int32)

        def do_contract_or_shrink(_):
            def outside(_):
                xc = centroid + gamma * (xr - centroid)
                fc = fun(xc)
                ok = fc <= fr
                return xc, fc, ok

            def inside(_):
                xc = centroid - gamma * (xr - centroid)
                fc = fun(xc)
                ok = fc < f_worst
                return xc, fc, ok

            xc, fc, ok = lax.cond(fr < f_worst, outside, inside, operand=None)

            def accept(_):
                return simplex.at[-1].set(xc), fvals.at[-1].set(fc), jnp.asarray(1, jnp.int32)

            def shrink(_):
                new_simplex = best[None, :] + delta * (simplex - best[None, :])
                new_simplex = new_simplex.at[0].set(best)
                new_f = jax.vmap(fun)(new_simplex)
                new_f = new_f.at[0].set(f_best)
                return new_simplex, new_f, jnp.asarray(n, jnp.int32)

            return lax.cond(ok, accept, shrink, operand=None)

        new_simplex, new_fvals, extra = lax.cond(
            fr < f_best,
            do_expand,
            lambda _: lax.cond(fr < f_second, do_reflect, do_contract_or_shrink, operand=None),
            operand=None,
        )
        return NMState(new_simplex, new_fvals, state.it + 1, state.n_fev + 1 + extra)

    final = lax.while_loop(cond, body, state0)
    i_best = jnp.argmin(final.fvals)
    return final.simplex[i_best], final.fvals[i_best], final.it


class NMBatchState(NamedTuple):
    simplex: jnp.ndarray  # (S, n+1, n)
    fvals: jnp.ndarray    # (S, n+1)
    it: jnp.ndarray       # ()
    iters: jnp.ndarray    # (S,) iteration count at freeze time
    done: jnp.ndarray     # (S,)


def nelder_mead_batched(batch_fun: Callable, X0, max_iters: int = 500,
                        f_tol: float = 1e-8, step=None):
    """Lockstep-batched Nelder–Mead: S independent simplexes advance together
    and EVERY candidate evaluation across the batch is one ``batch_fun`` call.

    ``batch_fun``: (S, K, n) → (S, K) — the leading axis is the start, so an
    objective that embeds each start's sub-vector into its own full parameter
    row knows which row a candidate belongs to.  Identical decision logic to
    :func:`nelder_mead` — fed the same objective it follows the same
    trajectory per start (tests/test_pallas_ssd.py::test_nelder_mead_batched_trajectory_parity) — but candidate points are evaluated
    speculatively per case and selected afterwards, so a fused-kernel
    objective (ops/pallas_ssd.batched_loss) amortizes its launch across the
    whole batch: 2 batched calls per iteration plus a cond-gated third when
    some start shrinks.  Converged starts freeze (their rows stop updating)
    until all are done or ``max_iters``.

    Returns (X_best (S, n), f_best (S,), iters (S,)).
    """
    S, n = X0.shape
    alpha = 1.0
    beta = 1.0 + 2.0 / n
    gamma = 0.75 - 1.0 / (2.0 * n)
    delta = 1.0 - 1.0 / n

    simplex0 = jax.vmap(lambda x: _initial_simplex(x, step))(X0)  # (S, n+1, n)
    fvals0 = batch_fun(simplex0)

    def fstd(fv):
        return jnp.std(jnp.nan_to_num(fv, nan=jnp.inf, posinf=1e30), axis=-1)

    state0 = NMBatchState(simplex0, fvals0, jnp.zeros((), jnp.int32),
                          jnp.zeros((S,), jnp.int32),
                          fstd(fvals0) <= f_tol)

    def cond(st):
        return (st.it < max_iters) & jnp.any(~st.done)

    def body(st):
        order = jnp.argsort(st.fvals, axis=1)
        simplex = jnp.take_along_axis(st.simplex, order[:, :, None], axis=1)
        fvals = jnp.take_along_axis(st.fvals, order, axis=1)
        best = simplex[:, 0]                        # (S, n)
        worst = simplex[:, -1]
        f_best, f_second, f_worst = fvals[:, 0], fvals[:, -2], fvals[:, -1]
        centroid = jnp.mean(simplex[:, :-1], axis=1)

        xr = centroid + alpha * (centroid - worst)
        fr = batch_fun(xr[:, None, :])[:, 0]        # call 1

        # speculative second candidate per start (exact sequential parity:
        # each case's point is what nelder_mead would evaluate there)
        expand = fr < f_best
        reflect = (~expand) & (fr < f_second)
        outside = (~expand) & (~reflect) & (fr < f_worst)
        xe = centroid + beta * (xr - centroid)
        xc_out = centroid + gamma * (xr - centroid)
        xc_in = centroid - gamma * (xr - centroid)
        x2 = jnp.where(expand[:, None], xe,
                       jnp.where(outside[:, None], xc_out, xc_in))
        f2 = batch_fun(x2[:, None, :])[:, 0]        # call 2

        # accepted replacement for the worst vertex, or shrink
        # predicate-select like the sequential cond (NaN f2 ⇒ keep (xr, fr);
        # jnp.minimum would propagate the NaN and detach f from its point)
        exp_take = f2 < fr
        exp_x = jnp.where(exp_take[:, None], x2, xr)
        exp_f = jnp.where(exp_take, f2, fr)
        ok_contract = jnp.where(outside, f2 <= fr, f2 < f_worst)
        shrink = (~expand) & (~reflect) & (~ok_contract)
        new_x = jnp.where(expand[:, None], exp_x,
                          jnp.where(reflect[:, None], xr, x2))
        new_f = jnp.where(expand, exp_f, jnp.where(reflect, fr, f2))
        repl_simplex = simplex.at[:, -1].set(new_x)
        repl_fvals = fvals.at[:, -1].set(new_f)

        def with_shrink(_):
            shr = best[:, None, :] + delta * (simplex - best[:, None, :])
            shr = shr.at[:, 0].set(best)
            shr_f = batch_fun(shr)
            shr_f = shr_f.at[:, 0].set(f_best)
            sm = jnp.where(shrink[:, None, None], shr, repl_simplex)
            fv = jnp.where(shrink[:, None], shr_f, repl_fvals)
            return sm, fv

        new_simplex, new_fvals = lax.cond(
            jnp.any(shrink & ~st.done), with_shrink,
            lambda _: (repl_simplex, repl_fvals), operand=None)

        # frozen (converged) starts keep their state
        new_simplex = jnp.where(st.done[:, None, None], st.simplex, new_simplex)
        new_fvals = jnp.where(st.done[:, None], st.fvals, new_fvals)
        now_done = fstd(new_fvals) <= f_tol
        iters = jnp.where(st.done, st.iters, st.it + 1)
        return NMBatchState(new_simplex, new_fvals, st.it + 1, iters,
                            st.done | now_done)

    final = lax.while_loop(cond, body, state0)
    i_best = jnp.argmin(final.fvals, axis=1)
    X_best = jnp.take_along_axis(final.simplex, i_best[:, None, None],
                                 axis=1)[:, 0]
    f_best = jnp.take_along_axis(final.fvals, i_best[:, None], axis=1)[:, 0]
    return X_best, f_best, final.iters
