"""Rolling-window forecast/backtest driver.

Parity with /root/reference/src/forecasting.jl:

- ``run_rolling_forecasts`` dispatches on window_type ∈ {both, expanding,
  moving, no_windowing, simulation} (:16-51),
- the per-origin loop shuffles tasks so concurrent workers start at different
  places (:86-88), skips existing shards (:128-131), takes a per-task mkdir
  lock (:133-136), optionally warm-starts from a simpler model's merged DB
  (:139), re-estimates (or reuses params when ``reestimate=False``), forecasts
  by appending ``forecast_horizon−1`` NaN columns (:141,161) and saves a
  SQLite shard; when all shards exist they merge and export CSVs (:203-221).
- Reference quirk kept: re-estimation uses the *expanding* sample
  ``data[:, :task_id]`` even for moving windows (forecasting.jl:165 passes the
  full data with in_sample_end = task_id); only the forecast pass uses the
  moving span.

TPU fast path: ``run_forecast_window_batched`` replaces the per-origin process
farm with ONE jitted (windows × starts) LBFGS batch (leading-NaN masking ==
truncation, see models/kalman.py), then writes the identical shard artifacts.
The crash-only shard/lock protocol is retained for multi-host (DCN) farming.
"""

from __future__ import annotations

import os
import secrets
from functools import lru_cache
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .config import register_engine_cache
from .estimation import optimize as opt
from .models import api
from .models.params import transform_params, untransform_params
from .models.specs import ModelSpec
from .orchestration import chaos
from .orchestration.checkpoint import WindowCheckpoint
from .parallel.multihost import sweep_stale_locks
from .persistence import database as db
from .persistence.locks import (acquire_task_lock, break_stale_lock,
                                release_task_lock, task_lock_path)
from .utils.profiling import StageTimer


def _forecast_db_base(spec: ModelSpec, window_type: str) -> str:
    return os.path.join(spec.results_location, "db", f"forecasts_{window_type}.sqlite3")


def _merged_path(spec: ModelSpec, window_type: str) -> str:
    return os.path.join(spec.results_location, "db", f"forecasts_{window_type}_merged.sqlite3")


def _lockroot(spec: ModelSpec) -> str:
    return os.path.join(spec.results_location, "db", "locks")


def default_checkpoint_root(spec: ModelSpec) -> str:
    return os.path.join(spec.results_location, "db", "checkpoints")


def _lock_ttl(stale_lock_ttl: float | None) -> float | None:
    """Effective TTL for breaking a held-but-stale task lock: the driver's
    ``stale_lock_ttl`` argument, else ``YFM_LOCK_TTL`` (seconds), else None
    (legacy behavior: a held lock is always trusted)."""
    if stale_lock_ttl is not None:
        return stale_lock_ttl
    env = os.environ.get("YFM_LOCK_TTL", "")
    return float(env) if env else None


def _estimate_for_window(spec: ModelSpec, data, task_id: int, all_params,
                         param_groups, max_group_iters, group_tol,
                         checkpoint: WindowCheckpoint | None = None,
                         second_order=None):
    """run_estimation! equivalent on the expanding sample data[:, :task_id].

    ``checkpoint``: per-window multi-start resume state (orchestration
    layer); only the block-coordinate path has iteration boundaries to
    checkpoint — plain multi-start LBFGS is one jitted program.
    """
    if param_groups:
        _, loss, params, _ = opt.estimate_steps(
            spec, data, all_params, param_groups,
            max_group_iters=max_group_iters, tol=group_tol,
            start=0, end=task_id, checkpoint=checkpoint,
            second_order=second_order,
        )
    else:
        _, loss, params, _ = opt.estimate(spec, data, all_params, start=0,
                                          end=task_id,
                                          second_order=second_order)
    return loss, params


def run_single_window_task(
    spec: ModelSpec, data, thread_id: str, task_id: int, window_type: str,
    in_sample_end: int, in_sample_start: int, forecast_horizon: int,
    all_params, *, param_groups=(), max_group_iters: int = 10,
    group_tol: float = 1e-8, reestimate: bool = True,
    timer: StageTimer | None = None, checkpoint_root: str | None = None,
    sentinel_policy: str = "save", second_order=None,
) -> str:
    """ONE origin's estimate → forecast → shard write; returns the shard path.

    The unit of work both drivers share: the in-process loop in
    :func:`run_forecast_window_database` and the leased-queue supervisor
    (``orchestration/supervisor.py``).  Idempotent by the artifact contract
    (re-running overwrites the same keyed row).  With ``checkpoint_root``
    set, multi-start estimation progress is persisted per group iteration
    and resumed after a crash; the checkpoint is cleared only after the
    shard is durably written.  ``sentinel_policy="retry"`` turns a
    non-finite estimated loss into a :class:`~..orchestration.retry.
    SentinelFailure` instead of saving it (the queue's retry/quarantine
    path); ``"save"`` keeps the reference behavior of persisting the NULL
    loss.
    """
    data = np.asarray(data, dtype=np.float64)
    base = _forecast_db_base(spec, window_type)
    cur = db.read_static_params_from_db(spec, task_id, all_params,
                                        window_type=window_type)
    ckpt = None
    if reestimate:
        if checkpoint_root is not None and param_groups:
            ckpt = WindowCheckpoint(checkpoint_root, window_type, task_id)
        from contextlib import nullcontext

        with (timer.stage("estimation") if timer is not None
              else nullcontext()):
            loss, params = _estimate_for_window(
                spec, data, task_id, cur, param_groups, max_group_iters,
                group_tol, checkpoint=ckpt, second_order=second_order)
        if sentinel_policy == "retry" and not np.isfinite(loss):
            from .orchestration.retry import SentinelFailure
            from .robustness import taxonomy

            # decode WHY before surfacing: prefer the multi-start report's
            # ladder diagnosis (estimate_steps ran it when YFM_ESCALATE is
            # armed), else one coded scan-engine eval at the returned point
            code = 0
            for t in opt.last_multistart_report().get("ladder", ()):
                code |= int(t.get("code", 0))
            if code == 0:
                try:
                    _, code = taxonomy.diagnose(spec, params, data,
                                                start=0, end=task_id)
                except Exception:  # noqa: BLE001 — diagnosis must not mask
                    code = 0       # the original failure
            raise SentinelFailure(
                f"estimation for {window_type} window {task_id} returned a "
                f"non-finite loss sentinel ({loss})",
                seam="estimate", code=code)
    else:
        params = db.read_params_from_db(spec, task_id, cur,
                                        window_type=window_type)[:, 0]
        loss = np.nan
    chaos.maybe_fail("shard_write")
    fdata = _window_forecast_data(spec, data, task_id, window_type,
                                  in_sample_end, in_sample_start,
                                  forecast_horizon)
    results = api.predict(spec, jnp.asarray(params, dtype=spec.dtype),
                          jnp.asarray(fdata, dtype=spec.dtype))
    path = db.save_oos_forecast_sharded(base, spec.model_string, thread_id,
                                        window_type, task_id, results, loss,
                                        params,
                                        forecast_horizon=forecast_horizon)
    if ckpt is not None:
        ckpt.clear()  # shard durable; a crash before this just replays fast
    return path


def merge_and_export(spec: ModelSpec, thread_id: str, tasks, window_type: str):
    """Shared final stage: fold shards into the merged DB, export CSVs.

    The ``merge`` chaos seam lives here so both drivers (lock-loop and
    supervisor) exercise crash-during-merge recovery: the merge is
    idempotent until the final rename, so a killed merger's successor just
    re-runs it."""
    chaos.maybe_fail("merge")
    base = _forecast_db_base(spec, window_type)
    result = db.merge_forecast_shards(base, task_ids=list(tasks),
                                      delete_shards=True)
    db.export_all_csv(spec, thread_id, list(tasks), window_type=window_type)
    return result


def run_rolling_forecasts(
    spec: ModelSpec,
    data,
    thread_id: str,
    in_sample_end: int,
    in_sample_start: int,
    forecast_horizon: int,
    init_params,
    window_type: str = "both",
    param_groups: Sequence[str] = (),
    max_group_iters: int = 10,
    group_tol: float = 1e-8,
    reestimate: bool = True,
    batched: bool = False,
    stale_lock_ttl: float | None = None,
    second_order=None,
) -> None:
    window_fn = run_forecast_window_batched if batched else run_forecast_window_database
    kw = dict(
        param_groups=param_groups, max_group_iters=max_group_iters,
        group_tol=group_tol, reestimate=reestimate, stale_lock_ttl=stale_lock_ttl,
        second_order=second_order,
    )
    if window_type == "both":
        window_fn(spec, data, thread_id, in_sample_end, in_sample_start,
                  forecast_horizon, "expanding", init_params, **kw)
        window_fn(spec, data, thread_id, in_sample_end, in_sample_start,
                  forecast_horizon, "moving", init_params, **kw)
    elif window_type in ("expanding", "moving"):
        window_fn(spec, data, thread_id, in_sample_end, in_sample_start,
                  forecast_horizon, window_type, init_params, **kw)
    elif window_type in ("no_windowing", "simulation"):
        run_forecast_no_window_database(
            spec, data, thread_id, in_sample_end, in_sample_start,
            forecast_horizon, window_type, init_params, **kw)
    else:
        raise ValueError("Invalid window type")


def _window_lo(task_id: int, window_type: str, in_sample_end: int,
               in_sample_start: int) -> int:
    """First data column of the window (0-based): 0 for expanding; for moving,
    span−1 with span = task_id − (in_sample_end − in_sample_start)
    (forecasting.jl:158).  The single source of the window arithmetic shared
    by the per-task and batched paths."""
    if window_type == "expanding":
        return 0
    if window_type == "moving":
        span = task_id - (in_sample_end - in_sample_start)
        if span < 1:  # guard the Julia 1-based precondition (in_sample_start >= 1)
            raise ValueError(
                f"moving window span={span} < 1; in_sample_start is 1-based "
                f"(got in_sample_start={in_sample_start}, in_sample_end={in_sample_end})")
        return span - 1
    raise ValueError("Invalid window type")


def _window_forecast_data(spec: ModelSpec, data, task_id: int, window_type: str,
                          in_sample_end: int, in_sample_start: int,
                          forecast_horizon: int):
    N = data.shape[0]
    pad = np.full((N, forecast_horizon - 1), np.nan)
    lo = _window_lo(task_id, window_type, in_sample_end, in_sample_start)
    return np.concatenate([data[:, lo:task_id], pad], axis=1)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_predict_windows(spec: ModelSpec, T_ext: int):
    """``predict`` for a batch of windows over ONE shared NaN-padded panel:
    each window masks columns outside its [lo, hi) span to NaN (transition-
    only steps).  Exactly equivalent to per-window truncation because the
    initial filter state is a fixed point of the transition (models/kalman.py
    docstring; γ₀=ω, β₀=δ for the score-driven families), and NaN columns
    after ``hi`` hide post-window data while emitting the h-step forecasts.
    This fuses the per-origin host predict loop (VERDICT round 1, item 2)
    into one vmapped device program."""

    def one(p, lo, hi, data_ext):
        t = jnp.arange(T_ext)
        masked = jnp.where(((t >= lo) & (t < hi))[None, :], data_ext, jnp.nan)
        return api.predict(spec, p, masked)

    return jax.jit(jax.vmap(one, in_axes=(0, 0, 0, None)))


def _batched_window_predicts(spec: ModelSpec, data, task_ids, window_type: str,
                             in_sample_end: int, in_sample_start: int,
                             forecast_horizon: int, params_batch):
    """Per-origin predict artifact dicts, computed in one device program.

    Returns a list (one dict per task) sliced to end at column task_id+h−2,
    so ``save_oos_forecast_sharded``'s last-h-columns convention picks
    columns identical to the per-task truncated call.  (For moving windows
    the arrays keep ``lo`` leading transition-only columns the truncated call
    would not have — only the trailing h columns are the contract.)"""
    N, T = data.shape
    h = forecast_horizon
    data_ext = np.concatenate([np.asarray(data, dtype=np.float64),
                               np.full((N, h - 1), np.nan)], axis=1)
    his = np.asarray(list(task_ids), dtype=np.int64)
    los = np.asarray([_window_lo(int(t), window_type, in_sample_end,
                                 in_sample_start) for t in his], dtype=np.int64)
    runner = _jitted_predict_windows(spec, T + h - 1)
    outs = runner(jnp.asarray(params_batch, dtype=spec.dtype),
                  jnp.asarray(los), jnp.asarray(his),
                  jnp.asarray(data_ext, dtype=spec.dtype))
    outs = {k: np.asarray(v) for k, v in outs.items()}
    return [{k: v[i][:, : int(tid) + h - 1] for k, v in outs.items()}
            for i, tid in enumerate(his)]


def _acquire_or_break(lockroot: str, window_type: str, task_id: int,
                      ttl: float | None):
    """Task lock acquire with dead-worker recovery: a held lock whose mtime
    is older than ``ttl`` is broken (``break_stale_lock``) and re-acquired
    atomically, fixing the forever-leaked-lock bug on worker crash.  With
    ``ttl=None`` a held lock is trusted (legacy behavior)."""
    lockdir = acquire_task_lock(lockroot, window_type, task_id)
    if lockdir is not None or ttl is None:
        return lockdir
    if break_stale_lock(task_lock_path(lockroot, window_type, task_id), ttl):
        return acquire_task_lock(lockroot, window_type, task_id)
    return None


def run_forecast_window_database(
    spec: ModelSpec, data, thread_id: str, in_sample_end: int, in_sample_start: int,
    forecast_horizon: int, window_type: str, init_params,
    param_groups=(), max_group_iters: int = 10, group_tol: float = 1e-8,
    reestimate: bool = True, printing: bool = True,
    stale_lock_ttl: float | None = None,
    checkpoint_root: str | None = None,
    second_order=None,
) -> None:
    data = np.asarray(data, dtype=np.float64)
    T = data.shape[1]
    tasks = list(range(in_sample_end, T + 1))
    rng = np.random.default_rng(secrets.randbits(63))  # RandomDevice shuffle (:88)
    rng.shuffle(tasks)

    base = _forecast_db_base(spec, window_type)
    merged = _merged_path(spec, window_type)
    lockroot = _lockroot(spec)
    ttl = _lock_ttl(stale_lock_ttl)
    if stale_lock_ttl is not None:  # crash recovery (SURVEY.md §5.3 weakness)
        sweep_stale_locks(lockroot, ttl_seconds=stale_lock_ttl)

    if os.path.isfile(merged):
        forecast_csv = db._legacy_path(
            spec.results_location, spec.model_string, thread_id, window_type, "forecasts")
        if os.path.isfile(forecast_csv):
            return
        lockdir = acquire_task_lock(lockroot, window_type, 0)
        if lockdir is None:
            return
        try:
            db.export_all_csv(spec, thread_id, tasks, window_type=window_type)
        finally:
            release_task_lock(lockdir)
        return

    all_params = np.asarray(init_params, dtype=np.float64)
    if all_params.ndim == 1:
        all_params = all_params[:, None]

    timer = StageTimer()
    for task_id in tasks:
        if os.path.isfile(db.forecast_path(base, task_id)):
            continue
        lockdir = _acquire_or_break(lockroot, window_type, task_id, ttl)
        if lockdir is None:
            continue
        try:
            run_single_window_task(
                spec, data, thread_id, task_id, window_type, in_sample_end,
                in_sample_start, forecast_horizon, all_params,
                param_groups=param_groups, max_group_iters=max_group_iters,
                group_tol=group_tol, reestimate=reestimate, timer=timer,
                checkpoint_root=checkpoint_root, second_order=second_order)
            if printing and timer.counts["estimation"]:
                print(f"Thread {thread_id}: {timer.counts['estimation']} estimations, "
                      f"avg {timer.mean('estimation'):.2f}s/task")
        finally:
            release_task_lock(lockdir)

    if all(os.path.isfile(db.forecast_path(base, t)) for t in tasks):
        lockdir = _acquire_or_break(lockroot, window_type, 0, ttl)
        if lockdir is None:
            return
        try:
            merge_and_export(spec, thread_id, tasks, window_type)
        finally:
            release_task_lock(lockdir)


def run_forecast_window_batched(
    spec: ModelSpec, data, thread_id: str, in_sample_end: int, in_sample_start: int,
    forecast_horizon: int, window_type: str, init_params,
    param_groups=(), max_group_iters: int = 10, group_tol: float = 1e-8,
    reestimate: bool = True, printing: bool = True,
    stale_lock_ttl: float | None = None,
    second_order=None,
) -> None:
    """All missing origins re-estimated in ONE (windows × starts) device batch,
    then written through the identical shard/merge/export pipeline.

    Uses multi-start LBFGS on the full parameter vector (the batched analogue
    of estimate!); the sequential block-coordinate path remains available via
    ``run_forecast_window_database``.
    """
    data = np.asarray(data, dtype=np.float64)
    T = data.shape[1]
    tasks = list(range(in_sample_end, T + 1))
    base = _forecast_db_base(spec, window_type)
    merged = _merged_path(spec, window_type)
    lockroot = _lockroot(spec)
    if stale_lock_ttl is not None:
        sweep_stale_locks(lockroot, ttl_seconds=stale_lock_ttl)
    if os.path.isfile(merged):
        return run_forecast_window_database(
            spec, data, thread_id, in_sample_end, in_sample_start,
            forecast_horizon, window_type, init_params,
            param_groups=param_groups, reestimate=reestimate, printing=printing)

    all_params = np.asarray(init_params, dtype=np.float64)
    if all_params.ndim == 1:
        all_params = all_params[:, None]

    todo = [t for t in tasks if not os.path.isfile(db.forecast_path(base, t))]
    ttl = _lock_ttl(stale_lock_ttl)
    locks = {}
    claimed = []
    for t in todo:
        ld = _acquire_or_break(lockroot, window_type, t, ttl)
        if ld is not None:
            locks[t] = ld
            claimed.append(t)
    try:
        if claimed and reestimate:
            raw0 = np.stack(
                [np.asarray(untransform_params(spec, jnp.asarray(c)))
                 for c in all_params.T], axis=0)  # (S, P)
            raw0[~np.isfinite(raw0)] = 0.0
            w_ends = np.asarray(claimed)
            w_starts = np.zeros_like(w_ends)  # estimation quirk: expanding sample
            xs, lls = opt.estimate_windows(spec, data, raw0, w_starts, w_ends,
                                           second_order=second_order)
            xs = np.asarray(xs)    # (W, S, P)
            lls = np.asarray(lls)  # (W, S)
            best = np.nanargmax(np.where(np.isfinite(lls), lls, -np.inf), axis=1)
        if claimed:
            params_rows, losses = [], []
            for i, task_id in enumerate(claimed):
                if reestimate:
                    raw_best = xs[i, best[i]]
                    params = np.asarray(
                        transform_params(spec, jnp.asarray(raw_best, dtype=spec.dtype)))
                    loss = float(lls[i, best[i]])
                else:
                    cur = db.read_static_params_from_db(spec, task_id, all_params,
                                                        window_type=window_type)
                    params = db.read_params_from_db(spec, task_id, cur,
                                                    window_type=window_type)[:, 0]
                    loss = np.nan
                params_rows.append(np.asarray(params, dtype=np.float64))
                losses.append(loss)
            # ALL origins' forecasts in one vmapped device program
            results_all = _batched_window_predicts(
                spec, data, claimed, window_type, in_sample_end,
                in_sample_start, forecast_horizon, np.stack(params_rows))
            for i, task_id in enumerate(claimed):
                db.save_oos_forecast_sharded(base, spec.model_string, thread_id,
                                             window_type, task_id,
                                             results_all[i], losses[i],
                                             params_rows[i],
                                             forecast_horizon=forecast_horizon)
    finally:
        for ld in locks.values():
            release_task_lock(ld)

    if all(os.path.isfile(db.forecast_path(base, t)) for t in tasks):
        lockdir = _acquire_or_break(lockroot, window_type, 0, ttl)
        if lockdir is None:
            return
        try:
            merge_and_export(spec, thread_id, tasks, window_type)
        finally:
            release_task_lock(lockdir)


def run_forecast_no_window_database(
    spec: ModelSpec, data, thread_id: str, in_sample_end: int, in_sample_start: int,
    forecast_horizon: int, window_type: str, init_params,
    param_groups=(), max_group_iters: int = 10, group_tol: float = 1e-8,
    reestimate: bool = True, stale_lock_ttl: float | None = None,
    second_order=None,
) -> None:
    """Estimate once, forecast every origin, single legacy CSV
    (forecasting.jl:228-283)."""
    data = np.asarray(data, dtype=np.float64)
    T = data.shape[1]
    all_params = np.asarray(init_params, dtype=np.float64)
    if all_params.ndim == 1:
        all_params = all_params[:, None]
    # single estimation on the in-sample span (forecasting.jl:233)
    loss, params = _estimate_for_window(
        spec, data, in_sample_end, all_params, param_groups, max_group_iters,
        group_tol, second_order=second_order)

    tasks = list(range(in_sample_end, T + 1))
    M, L, N = spec.M, spec.L, spec.N
    H = forecast_horizon
    all_results = np.zeros((2 + M + L + N, H * len(tasks)))
    # every origin's forecast in ONE vmapped device program (shared params)
    results_all = _batched_window_predicts(
        spec, data, tasks, "expanding", in_sample_end, in_sample_start, H,
        np.tile(np.asarray(params, dtype=np.float64), (len(tasks), 1)))
    for k, task_id in enumerate(tasks):
        res = results_all[k]
        cols = slice(k * H, (k + 1) * H)
        all_results[0, cols] = task_id
        all_results[1, cols] = np.arange(1, H + 1) + task_id
        all_results[2:2 + M, cols] = np.asarray(res["factors"])[:, -H:]
        all_results[2 + M:2 + M + L, cols] = np.asarray(res["states"])[:, -H:]
        all_results[2 + M + L:, cols] = np.asarray(res["preds"])[:, -H:]

    order = np.lexsort((all_results[1], all_results[0]))
    all_results = np.round(all_results[:, order], 3)

    res_full = api.predict(spec, jnp.asarray(params, dtype=spec.dtype),
                           jnp.asarray(data, dtype=spec.dtype))
    factors_oos = np.round(
        np.concatenate([np.asarray(res_full["factors"]),
                        np.asarray(res_full["states"])], axis=0), 3)

    folder = spec.results_location
    os.makedirs(folder, exist_ok=True)
    ms = spec.model_string
    # reference hardcodes "expanding" in this filename (forecasting.jl:267)
    np.savetxt(os.path.join(
        folder, f"{ms}__thread_id__{thread_id}__expanding_window_forecasts.csv"),
        all_results.T, delimiter=",", fmt="%.18g")
    np.savetxt(os.path.join(
        folder, f"{ms}__thread_id__{thread_id}__out_params.csv"),
        np.asarray(params, dtype=np.float64), delimiter=",")
    np.savetxt(os.path.join(
        folder, f"{ms}__thread_id__{thread_id}__factors_filtered_outofsample.csv"),
        factors_oos, delimiter=",", fmt="%.18g")
