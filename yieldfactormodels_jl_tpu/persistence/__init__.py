from . import database, io, locks

__all__ = ["database", "io", "locks"]
