"""In-sample result CSVs (parity with /root/reference/src/io.jl:4-31)."""

from __future__ import annotations

import os

import numpy as np


def savetxt_atomic(path: str, rows, **kwargs) -> str:
    """``np.savetxt`` through a writer-unique tmp + ``os.replace`` publish:
    a reader (or a concurrent thread re-exporting the same model string)
    never observes a torn CSV — the same discipline as the forecast shards
    (graftlint YFM005).  The suffix carries the thread id, not just the pid:
    the orchestrator's in-process workers share a pid."""
    import threading

    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    np.savetxt(tmp, rows, **kwargs)
    os.replace(tmp, path)
    return path


def save_results(spec, results: dict, loss: float, params, thread_id: str,
                 data_type: str) -> None:
    """Write filtered factors/states, fitted ŷ, loading columns, loss, params."""
    folder = spec.results_location
    os.makedirs(folder, exist_ok=True)
    ms = spec.model_string

    def path(suffix):
        return os.path.join(folder, f"{ms}__thread_id__{thread_id}__{suffix}.csv")

    factors = np.asarray(results["factors"], dtype=np.float64)
    states = np.asarray(results["states"], dtype=np.float64)
    savetxt_atomic(path(f"factors_filtered_{data_type}"),
                   np.concatenate([factors, states], axis=0).T, delimiter=",")
    savetxt_atomic(path(f"fit_filtered_{data_type}"),
                   np.asarray(results["preds"], dtype=np.float64).T, delimiter=",")
    savetxt_atomic(path(f"factor_loadings_1_filtered_{data_type}"),
                   np.asarray(results["factor_loadings_1"], dtype=np.float64).T,
                   delimiter=",")
    savetxt_atomic(path(f"factor_loadings_2_filtered_{data_type}"),
                   np.asarray(results["factor_loadings_2"], dtype=np.float64).T,
                   delimiter=",")
    savetxt_atomic(path("loss"), np.asarray([loss], dtype=np.float64),
                   delimiter=",")
    savetxt_atomic(path("out_params"), np.asarray(params, dtype=np.float64),
                   delimiter=",")
