"""In-sample result CSVs (parity with /root/reference/src/io.jl:4-31)."""

from __future__ import annotations

import os

import numpy as np


def save_results(spec, results: dict, loss: float, params, thread_id: str,
                 data_type: str) -> None:
    """Write filtered factors/states, fitted ŷ, loading columns, loss, params."""
    folder = spec.results_location
    os.makedirs(folder, exist_ok=True)
    ms = spec.model_string

    def path(suffix):
        return os.path.join(folder, f"{ms}__thread_id__{thread_id}__{suffix}.csv")

    factors = np.asarray(results["factors"], dtype=np.float64)
    states = np.asarray(results["states"], dtype=np.float64)
    np.savetxt(path(f"factors_filtered_{data_type}"),
               np.concatenate([factors, states], axis=0).T, delimiter=",")
    np.savetxt(path(f"fit_filtered_{data_type}"),
               np.asarray(results["preds"], dtype=np.float64).T, delimiter=",")
    np.savetxt(path(f"factor_loadings_1_filtered_{data_type}"),
               np.asarray(results["factor_loadings_1"], dtype=np.float64).T, delimiter=",")
    np.savetxt(path(f"factor_loadings_2_filtered_{data_type}"),
               np.asarray(results["factor_loadings_2"], dtype=np.float64).T, delimiter=",")
    np.savetxt(path("loss"), np.asarray([loss], dtype=np.float64), delimiter=",")
    np.savetxt(path("out_params"), np.asarray(params, dtype=np.float64), delimiter=",")
