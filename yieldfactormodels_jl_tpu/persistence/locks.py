"""Filesystem coordination primitives.

Parity with /root/reference/src/forecasting.jl:53-79: the entire multi-process
"communication backend" of the reference is atomic ``mkdir`` task locks plus
idempotent shard files — a crash-only design that fits preemptible TPU jobs,
so it is kept as the cross-host (DCN-level) coordination layer here while
within-host parallelism moves onto the device mesh.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional


def acquire_task_lock(lockroot: str, window_type: str, task_id: int) -> Optional[str]:
    """Atomic mkdir lock; returns the lock dir if acquired, None if held."""
    lockdir = os.path.join(lockroot, window_type, f"task_{task_id}.lock")
    os.makedirs(os.path.dirname(lockdir), exist_ok=True)
    try:
        os.mkdir(lockdir)
        return lockdir
    except FileExistsError:
        return None


def release_task_lock(lockdir: Optional[str]) -> None:
    """Best-effort removal (forecasting.jl:73-79)."""
    if not lockdir:
        return
    try:
        if os.path.isdir(lockdir):
            shutil.rmtree(lockdir, ignore_errors=True)
    except OSError:
        pass
