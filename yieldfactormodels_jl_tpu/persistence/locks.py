"""Filesystem coordination primitives.

Parity with /root/reference/src/forecasting.jl:53-79: the entire multi-process
"communication backend" of the reference is atomic ``mkdir`` task locks plus
idempotent shard files — a crash-only design that fits preemptible TPU jobs,
so it is kept as the cross-host (DCN-level) coordination layer here while
within-host parallelism moves onto the device mesh.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Optional


def task_lock_path(lockroot: str, window_type: str, task_id: int) -> str:
    """The one place the lock-dir naming convention lives."""
    return os.path.join(lockroot, window_type, f"task_{task_id}.lock")


def acquire_task_lock(lockroot: str, window_type: str, task_id: int) -> Optional[str]:
    """Atomic mkdir lock; returns the lock dir if acquired, None if held."""
    lockdir = task_lock_path(lockroot, window_type, task_id)
    os.makedirs(os.path.dirname(lockdir), exist_ok=True)
    try:
        os.mkdir(lockdir)
        return lockdir
    except FileExistsError:
        return None


def release_task_lock(lockdir: Optional[str]) -> None:
    """Best-effort removal (forecasting.jl:73-79)."""
    if not lockdir:
        return
    try:
        if os.path.isdir(lockdir):
            shutil.rmtree(lockdir, ignore_errors=True)
    except OSError:
        pass


def break_stale_lock(lockdir: str, ttl_seconds: float) -> bool:
    """Remove ``lockdir`` when its mtime is older than ``ttl_seconds``;
    True if removed.

    This is the crash-recovery primitive the reference lacks: a SIGKILLed
    worker's lock dir otherwise starves its task forever (SURVEY §5.3).
    Live holders defend a lock by touching its mtime (the orchestration
    queue's degraded mode heartbeats via ``os.utime``); ``os.rmdir`` only
    removes EMPTY dirs and is atomic, so two sweepers racing lose nothing,
    and the follow-up ``mkdir`` re-acquire stays atomic.  Worst case of an
    aggressive TTL is duplicated work on an idempotent shard — never
    corruption.
    """
    try:
        if os.path.isdir(lockdir) and \
                time.time() - os.path.getmtime(lockdir) > ttl_seconds:
            os.rmdir(lockdir)
            return True
    except OSError:
        pass
    return False
