"""SQLite shard persistence for out-of-sample forecasts.

Same artifact contract as /root/reference/src/databaseoperations/
databaseoperations.jl: one SQLite file per (window, task) shard with a
``forecasts`` table keyed (model, thread, window, task_id) holding loss,
params and the five result blobs; WAL mode, busy_timeout, IMMEDIATE
transactions; shards merge into ``forecasts_<window>_merged.sqlite3``
(:195-364).  Values are rounded to 3 decimals before saving (:251-255).

One deliberate change: blobs are ``numpy .npy`` bytes instead of Julia
``Serialization`` bytes — a portable, documented format with identical
array content (the reference's blobs are Julia-version-locked).
"""

from __future__ import annotations

import io as _io
import os
import sqlite3
import threading
from typing import Dict, Optional, Sequence

import numpy as np

_DB_INIT_LOCK = threading.Lock()
_DB_INIT_LOCKS: Dict[str, threading.Lock] = {}

SCHEMA = """
    CREATE TABLE IF NOT EXISTS forecasts(
        model  TEXT NOT NULL,
        thread TEXT NOT NULL,
        window TEXT NOT NULL,
        task_id INTEGER NOT NULL,
        loss   REAL,
        params BLOB NOT NULL,
        preds  BLOB NOT NULL,
        fl1    BLOB NOT NULL,
        fl2    BLOB NOT NULL,
        factors BLOB NOT NULL,
        states  BLOB NOT NULL,
        PRIMARY KEY(model,thread,window,task_id)
    );
"""


def ser(arr) -> bytes:
    buf = _io.BytesIO()
    np.save(buf, np.asarray(arr, dtype=np.float64))
    return buf.getvalue()


def deser(blob: bytes) -> np.ndarray:
    return np.load(_io.BytesIO(blob))


def forecast_path(base: str, k: int) -> str:
    """databaseoperations.jl:245: shard path for task k (k=0 → base)."""
    return base if k == 0 else base.replace(".sqlite3", f"_{k}.sqlite3")


def init_forecast_db(path: str) -> sqlite3.Connection:
    """WAL + busy_timeout + schema, one initializer per path at a time
    (databaseoperations.jl:195-243)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with _DB_INIT_LOCK:
        lock = _DB_INIT_LOCKS.setdefault(path, threading.Lock())
    with lock:
        db = sqlite3.connect(path, timeout=10.0)
        db.execute("PRAGMA busy_timeout=10000;")
        db.execute("PRAGMA temp_store=MEMORY;")
        mode = db.execute("PRAGMA journal_mode=WAL;").fetchone()[0]
        if str(mode).lower() != "wal":
            db.execute("PRAGMA journal_mode=DELETE;")
        db.execute("PRAGMA synchronous=NORMAL;")
        db.execute(SCHEMA)
        db.commit()
        return db


def save_oos_forecast_sharded(
    base: str,
    model_string: str,
    thread: str,
    window: str,
    task_id: int,
    results: dict,
    loss: float,
    params,
    forecast_horizon: int,
) -> str:
    """Round, slice the last ``forecast_horizon`` columns, INSERT OR REPLACE in
    an IMMEDIATE transaction (databaseoperations.jl:247-293)."""
    h = forecast_horizon
    rounded = {k: np.round(np.asarray(v, dtype=np.float64), 3) for k, v in results.items()}
    p = rounded["preds"][:, -h:]
    f = rounded["factors"][:, -h:]
    s = rounded["states"][:, -h:]
    fl1 = rounded["factor_loadings_1"][:, -h:]
    fl2 = rounded["factor_loadings_2"][:, -h:]

    path = forecast_path(base, task_id)
    db = init_forecast_db(path)
    try:
        db.execute("BEGIN IMMEDIATE;")
        db.execute(
            "INSERT OR REPLACE INTO forecasts("
            "model,thread,window,task_id,loss,params,preds,fl1,fl2,factors,states"
            ") VALUES(?,?,?,?,?,?,?,?,?,?,?)",
            (
                model_string, thread, window, int(task_id),
                float(loss) if np.isfinite(loss) else None,
                ser(params), ser(p), ser(fl1), ser(fl2), ser(f), ser(s),
            ),
        )
        db.commit()
        return path
    except Exception:
        db.rollback()
        raise
    finally:
        db.close()


def merge_forecast_shards(
    base: str,
    task_ids: Sequence[int],
    out: Optional[str] = None,
    delete_shards: bool = False,
) -> str:
    """Fold shards into the first, rename to _merged
    (databaseoperations.jl:295-364)."""
    if out is None:
        out = base.replace(".sqlite3", "_merged.sqlite3")
    task_ids = list(task_ids)
    src_path = forecast_path(base, task_ids[0])
    for task_id in task_ids[1:]:
        shard = forecast_path(base, task_id)
        if not os.path.isfile(shard):
            continue
        src = sqlite3.connect(src_path, timeout=10.0)
        new = sqlite3.connect(shard, timeout=10.0)
        rows = new.execute(
            "SELECT model,thread,window,task_id,loss,params,preds,fl1,fl2,factors,states "
            "FROM forecasts WHERE task_id = ?", (int(task_id),)
        ).fetchall()
        for row in rows:
            src.execute(
                "INSERT OR REPLACE INTO forecasts("
                "model,thread,window,task_id,loss,params,preds,fl1,fl2,factors,states"
                ") VALUES(?,?,?,?,?,?,?,?,?,?,?)", row
            )
        src.commit()
        new.close()
        src.close()
    os.replace(src_path, out)
    if delete_shards:
        for task_id in task_ids:
            shard = forecast_path(base, task_id)
            if os.path.isfile(shard):
                os.remove(shard)
    return out


# ---------------------------------------------------------------------------
# warm-start / parameter-reuse reads (databaseoperations.jl:5-72)
# ---------------------------------------------------------------------------

def _merged_db_path(results_folder: str, model_name: str, window_type: str) -> str:
    # results_folder is ".../results/thread_id__X/<model>/"; the sibling model's
    # DB lives at ".../results/thread_id__X/<model_name>/db/" (databaseoperations.jl:8)
    results_dir = os.path.dirname(results_folder.rstrip("/"))
    return os.path.join(results_dir, model_name, "db", f"forecasts_{window_type}_merged.sqlite3")


def read_all_task_params(db_path: str) -> Dict[int, np.ndarray]:
    """Every task's fitted params from a merged DB in ONE query and one
    deserialization pass — the serving snapshot-registry warm-boot read
    (serving/snapshot.py), replacing a per-task ``read_task_params`` SELECT
    loop.  Returns {task_id: flat float64 params}; {} when the DB is absent."""
    if not os.path.isfile(db_path):
        return {}
    db = sqlite3.connect(db_path, timeout=10.0)
    try:
        rows = db.execute("SELECT task_id, params FROM forecasts").fetchall()
    finally:
        db.close()
    return {int(task_id): deser(blob).reshape(-1) for task_id, blob in rows}


def read_task_params(db_path: str, task_id: int) -> Optional[np.ndarray]:
    if not os.path.isfile(db_path):
        return None
    db = sqlite3.connect(db_path, timeout=10.0)
    try:
        row = db.execute(
            "SELECT params FROM forecasts WHERE task_id = ?", (int(task_id),)
        ).fetchone()
    finally:
        db.close()
    if row is None:
        return None
    return deser(row[0]).reshape(-1)


def read_static_params_from_db(spec, task_id: int, all_params: np.ndarray,
                               window_type: str = "expanding") -> np.ndarray:
    """Warm-start MSED params from the simpler static model's merged DB for the
    same task (databaseoperations.jl:5-34)."""
    from ..models.api import get_static_model_type
    from ..models.params import initialize_with_static_params

    if not spec.is_msed:
        return all_params
    static_name = get_static_model_type(spec)
    db_path = _merged_db_path(spec.results_location, static_name, window_type)
    static_params = read_task_params(db_path, task_id)
    if static_params is None:
        return all_params
    all_params = np.asarray(all_params, dtype=np.float64).copy()
    all_params[:, 0] = initialize_with_static_params(spec, all_params[:, 0], static_params)
    return all_params


def read_params_from_db(spec, task_id: int, all_params: np.ndarray,
                        window_type: str = "expanding") -> np.ndarray:
    """Reuse this model's own past fitted params when reestimate=false
    (databaseoperations.jl:36-72)."""
    db_path = _merged_db_path(spec.results_location, spec.model_string, window_type)
    params = read_task_params(db_path, task_id)
    if params is None:
        return all_params
    all_params = np.asarray(all_params, dtype=np.float64).copy()
    all_params[:, 0] = params
    return all_params


# ---------------------------------------------------------------------------
# legacy CSV export (databaseoperations.jl:391-661)
# ---------------------------------------------------------------------------

def _legacy_path(results_folder, model_string, thread_id, window_type, kind):
    return os.path.join(
        results_folder,
        f"{model_string}__thread_id__{thread_id}__{window_type}_window_{kind}.csv",
    )


def _write_csv(path: str, rows: np.ndarray) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savetxt(path, rows, delimiter=",", fmt="%.18g")
    return path


def _export_wide(db_path, results_folder, model_string, thread_id, tasks,
                 window_type, column, kind):
    """(origin, target, values...) long format, sorted by target then origin."""
    rows = []
    db = sqlite3.connect(db_path, timeout=10.0)
    try:
        for task_id in tasks:
            row = db.execute(
                f"SELECT task_id, {column} FROM forecasts WHERE task_id = ?",
                (int(task_id),),
            ).fetchone()
            if row is None:
                continue
            P = deser(row[1])
            K, H = P.shape
            for h in range(H):
                rows.append([float(task_id), float(task_id + h + 1)] + list(P[:, h]))
    finally:
        db.close()
    arr = np.asarray(rows, dtype=np.float64)
    if arr.size:
        arr = arr[np.lexsort((arr[:, 1],))]
        arr = arr[np.lexsort((arr[:, 0],))]
    return _write_csv(_legacy_path(results_folder, model_string, thread_id, window_type, kind), arr)


def _export_params(db_path, results_folder, model_string, thread_id, tasks, window_type):
    rows = []
    db = sqlite3.connect(db_path, timeout=10.0)
    try:
        for task_id in tasks:
            row = db.execute(
                "SELECT task_id, params FROM forecasts WHERE task_id = ?", (int(task_id),)
            ).fetchone()
            if row is None:
                continue
            p = deser(row[1]).reshape(-1)
            rows.append([float(task_id)] + list(p))
    finally:
        db.close()
    arr = np.asarray(rows, dtype=np.float64)
    if arr.size:
        arr = arr[np.argsort(arr[:, 0], kind="stable")]
    return _write_csv(
        _legacy_path(results_folder, model_string, thread_id, window_type, "fitted_params"), arr
    )


def export_all_csv(spec, thread_id: str, tasks: Sequence[int],
                   window_type: str = "expanding") -> dict:
    """forecasts / fitted_params / fl1 / fl2 / factors / states CSVs in the
    reference's legacy layout (databaseoperations.jl:654-661)."""
    folder = spec.results_location
    db_path = os.path.join(folder, "db", f"forecasts_{window_type}_merged.sqlite3")
    ms = spec.model_string
    return {
        "forecasts": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "preds", "forecasts"),
        "fitted_params": _export_params(db_path, folder, ms, thread_id, tasks, window_type),
        "fl1": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "fl1", "fl1"),
        "fl2": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "fl2", "fl2"),
        "factors": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "factors", "factors"),
        "states": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "states", "states"),
    }
