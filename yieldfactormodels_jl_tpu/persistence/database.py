"""SQLite shard persistence for out-of-sample forecasts.

Same artifact contract as /root/reference/src/databaseoperations/
databaseoperations.jl: one SQLite file per (window, task) shard with a
``forecasts`` table keyed (model, thread, window, task_id) holding loss,
params and the five result blobs; WAL mode, busy_timeout, IMMEDIATE
transactions; shards merge into ``forecasts_<window>_merged.sqlite3``
(:195-364).  Values are rounded to 3 decimals before saving (:251-255).

One deliberate change: blobs are ``numpy .npy`` bytes instead of Julia
``Serialization`` bytes — a portable, documented format with identical
array content (the reference's blobs are Julia-version-locked).
"""

from __future__ import annotations

import io as _io
import os
import sqlite3
import threading
import uuid
from typing import Dict, Optional, Sequence

import numpy as np

_DB_INIT_LOCK = threading.Lock()
_DB_INIT_LOCKS: Dict[str, threading.Lock] = {}

SCHEMA = """
    CREATE TABLE IF NOT EXISTS forecasts(
        model  TEXT NOT NULL,
        thread TEXT NOT NULL,
        window TEXT NOT NULL,
        task_id INTEGER NOT NULL,
        loss   REAL,
        params BLOB NOT NULL,
        preds  BLOB NOT NULL,
        fl1    BLOB NOT NULL,
        fl2    BLOB NOT NULL,
        factors BLOB NOT NULL,
        states  BLOB NOT NULL,
        PRIMARY KEY(model,thread,window,task_id)
    );
"""


def ser(arr) -> bytes:
    buf = _io.BytesIO()
    np.save(buf, np.asarray(arr, dtype=np.float64))
    return buf.getvalue()


def deser(blob: bytes) -> np.ndarray:
    return np.load(_io.BytesIO(blob))


def forecast_path(base: str, k: int) -> str:
    """databaseoperations.jl:245: shard path for task k (k=0 → base)."""
    return base if k == 0 else base.replace(".sqlite3", f"_{k}.sqlite3")


def open_wal_db(path: str, timeout: float = 10.0) -> sqlite3.Connection:
    """The one concurrent-SQLite open discipline (shards, merged DBs, the
    orchestration queue journal): busy_timeout, WAL with a DELETE fallback
    for filesystems that refuse it, synchronous=NORMAL."""
    db = sqlite3.connect(path, timeout=timeout)
    db.execute("PRAGMA busy_timeout=10000;")
    mode = db.execute("PRAGMA journal_mode=WAL;").fetchone()[0]
    if str(mode).lower() != "wal":
        db.execute("PRAGMA journal_mode=DELETE;")
    db.execute("PRAGMA synchronous=NORMAL;")
    return db


def init_forecast_db(path: str) -> sqlite3.Connection:
    """WAL + busy_timeout + schema, one initializer per path at a time
    (databaseoperations.jl:195-243)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with _DB_INIT_LOCK:
        lock = _DB_INIT_LOCKS.setdefault(path, threading.Lock())
    with lock:
        db = open_wal_db(path)
        db.execute("PRAGMA temp_store=MEMORY;")
        db.execute(SCHEMA)
        db.commit()
        return db


def save_oos_forecast_sharded(
    base: str,
    model_string: str,
    thread: str,
    window: str,
    task_id: int,
    results: dict,
    loss: float,
    params,
    forecast_horizon: int,
) -> str:
    """Round, slice the last ``forecast_horizon`` columns, INSERT OR REPLACE in
    an IMMEDIATE transaction (databaseoperations.jl:247-293)."""
    h = forecast_horizon
    rounded = {k: np.round(np.asarray(v, dtype=np.float64), 3) for k, v in results.items()}
    p = rounded["preds"][:, -h:]
    f = rounded["factors"][:, -h:]
    s = rounded["states"][:, -h:]
    fl1 = rounded["factor_loadings_1"][:, -h:]
    fl2 = rounded["factor_loadings_2"][:, -h:]

    # build the shard in a writer-unique temp file and publish it with one
    # atomic rename: ``os.path.isfile(shard)`` then IMPLIES a fully committed
    # shard, so concurrent mergers never observe a created-but-uncommitted DB
    # (an empty file with no ``forecasts`` table yet) and misread it as
    # corrupt; the unique suffix keeps a stalled writer and the thief that
    # stole its lease from interleaving in one temp file (last publish wins —
    # both hold identical rows)
    path = forecast_path(base, task_id)
    tmp = f"{path}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    db = init_forecast_db(tmp)
    try:
        db.execute("BEGIN IMMEDIATE;")
        db.execute(
            "INSERT OR REPLACE INTO forecasts("
            "model,thread,window,task_id,loss,params,preds,fl1,fl2,factors,states"
            ") VALUES(?,?,?,?,?,?,?,?,?,?,?)",
            (
                model_string, thread, window, int(task_id),
                float(loss) if np.isfinite(loss) else None,
                ser(params), ser(p), ser(fl1), ser(fl2), ser(f), ser(s),
            ),
        )
        db.commit()
    except Exception:
        db.rollback()
        db.close()
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    db.close()  # checkpoints + removes the -wal sidecar before the rename
    os.replace(tmp, path)
    with _DB_INIT_LOCK:  # tmp paths are single-use; don't accumulate locks
        _DB_INIT_LOCKS.pop(tmp, None)
    return path


class MergeResult(str):
    """The merged-DB path (still a plain ``str`` for every existing caller),
    carrying the merge summary: ``.merged`` (task ids folded in) and
    ``.skipped`` (``[(task_id, reason), ...]`` for corrupt/missing shards)."""

    merged: list
    skipped: list

    def __new__(cls, path: str, merged, skipped):
        self = super().__new__(cls, path)
        self.merged = list(merged)
        self.skipped = list(skipped)
        return self


def _shard_rows(shard: str, task_id: int):
    """All of one shard's rows for ``task_id``; raises sqlite3.DatabaseError
    on a truncated/corrupt file (detected on read, not just on connect).
    Opened read-only via URI so a reader NEVER creates a file at the shard
    path — a plain connect materializes an empty DB for a path that just
    went missing, which a later reader would misread as a corrupt shard."""
    from urllib.request import pathname2url

    new = sqlite3.connect(f"file:{pathname2url(os.path.abspath(shard))}"
                          "?mode=ro", uri=True, timeout=10.0)
    try:
        return new.execute(
            "SELECT model,thread,window,task_id,loss,params,preds,fl1,fl2,factors,states "
            "FROM forecasts WHERE task_id = ?", (int(task_id),)
        ).fetchall()
    finally:
        new.close()


def merge_forecast_shards(
    base: str,
    task_ids: Sequence[int],
    out: Optional[str] = None,
    delete_shards: bool = False,
) -> MergeResult:
    """Fold shards into the merged DB (databaseoperations.jl:295-364).

    Hardened for crash-tolerant fleets, where the same merge may run twice
    (a stalled merger's lease can be stolen while it is still alive):

    - The merged DB is BUILT in a merger-unique temp file from read-only
      shard opens, and PUBLISHED at most once: ``os.link`` to the final
      path fails if a concurrent merger already published, so a slow loser
      can never overwrite a complete merged DB with a partial one.  Shards
      are deleted only after a successful publish (or when the merged DB
      already exists — post-crash cleanup), so concurrent readers always
      find every row somewhere.
    - A truncated/corrupt shard (a worker killed mid-write on a non-WAL
      filesystem) is SKIPPED with a warning and recorded in the returned
      :class:`MergeResult` summary instead of aborting the whole merge —
      and corrupt shards are never deleted, so the data stays on disk for
      repair.
    """
    import sys as _sys

    if out is None:
        out = base.replace(".sqlite3", "_merged.sqlite3")
    task_ids = list(task_ids)
    skipped: list = []
    merged: list = []

    tmp = f"{out}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    dst = init_forecast_db(tmp)
    try:
        dst.execute("BEGIN IMMEDIATE;")
        for task_id in task_ids:
            shard = forecast_path(base, task_id)
            if not os.path.isfile(shard):
                skipped.append((task_id, "missing shard"))
                continue
            try:
                rows = _shard_rows(shard, task_id)
            except sqlite3.DatabaseError as e:
                skipped.append((task_id, f"corrupt shard: {e}"))
                _sys.stderr.write(f"# merge: skipping corrupt shard for task "
                                  f"{task_id} ({e}); file kept for repair\n")
                continue
            for row in rows:
                dst.execute(
                    "INSERT OR REPLACE INTO forecasts("
                    "model,thread,window,task_id,loss,params,preds,fl1,fl2,factors,states"
                    ") VALUES(?,?,?,?,?,?,?,?,?,?,?)", row
                )
            merged.append(task_id)
        dst.commit()
    except BaseException:
        dst.close()
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    dst.close()
    with _DB_INIT_LOCK:
        _DB_INIT_LOCKS.pop(tmp, None)

    if not merged and not os.path.isfile(out):
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise RuntimeError(
            f"merge_forecast_shards: no healthy shard among {len(task_ids)} "
            f"tasks of {base} — skipped: {skipped}")
    try:
        os.link(tmp, out)  # at-most-once publish: first merger wins
    except FileExistsError:
        # a concurrent/previous merger already published a complete merged
        # DB; ours (possibly partial — it may have read shards after the
        # winner deleted them) is discarded
        merged = []
    except OSError:
        os.replace(tmp, out)  # no-hardlink filesystem: atomic, last-wins
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
    if delete_shards:
        corrupt = {t for t, why in skipped if "corrupt" in why}
        for task_id in task_ids:
            shard = forecast_path(base, task_id)
            if task_id not in corrupt and os.path.isfile(shard):
                for side in ("", "-wal", "-shm"):  # WAL sidecars too
                    try:
                        os.remove(shard + side)
                    except OSError:
                        pass
    if skipped and merged:
        _sys.stderr.write(f"# merge: {len(merged)} shards merged into {out}, "
                          f"{len(skipped)} skipped: {skipped}\n")
    return MergeResult(out, merged, skipped)


# ---------------------------------------------------------------------------
# warm-start / parameter-reuse reads (databaseoperations.jl:5-72)
# ---------------------------------------------------------------------------

def _merged_db_path(results_folder: str, model_name: str, window_type: str) -> str:
    # results_folder is ".../results/thread_id__X/<model>/"; the sibling model's
    # DB lives at ".../results/thread_id__X/<model_name>/db/" (databaseoperations.jl:8)
    results_dir = os.path.dirname(results_folder.rstrip("/"))
    return os.path.join(results_dir, model_name, "db", f"forecasts_{window_type}_merged.sqlite3")


def read_all_task_params(db_path: str) -> Dict[int, np.ndarray]:
    """Every task's fitted params from a merged DB in ONE query and one
    deserialization pass — the serving snapshot-registry warm-boot read
    (serving/snapshot.py), replacing a per-task ``read_task_params`` SELECT
    loop.  Returns {task_id: flat float64 params}; {} when the DB is absent."""
    if not os.path.isfile(db_path):
        return {}
    db = sqlite3.connect(db_path, timeout=10.0)
    try:
        rows = db.execute("SELECT task_id, params FROM forecasts").fetchall()
    finally:
        db.close()
    return {int(task_id): deser(blob).reshape(-1) for task_id, blob in rows}


def read_task_params(db_path: str, task_id: int) -> Optional[np.ndarray]:
    if not os.path.isfile(db_path):
        return None
    db = sqlite3.connect(db_path, timeout=10.0)
    try:
        row = db.execute(
            "SELECT params FROM forecasts WHERE task_id = ?", (int(task_id),)
        ).fetchone()
    finally:
        db.close()
    if row is None:
        return None
    return deser(row[0]).reshape(-1)


def read_static_params_from_db(spec, task_id: int, all_params: np.ndarray,
                               window_type: str = "expanding") -> np.ndarray:
    """Warm-start MSED params from the simpler static model's merged DB for the
    same task (databaseoperations.jl:5-34)."""
    from ..models.api import get_static_model_type
    from ..models.params import initialize_with_static_params

    if not spec.is_msed:
        return all_params
    static_name = get_static_model_type(spec)
    db_path = _merged_db_path(spec.results_location, static_name, window_type)
    static_params = read_task_params(db_path, task_id)
    if static_params is None:
        return all_params
    all_params = np.asarray(all_params, dtype=np.float64).copy()
    all_params[:, 0] = initialize_with_static_params(spec, all_params[:, 0], static_params)
    return all_params


def read_params_from_db(spec, task_id: int, all_params: np.ndarray,
                        window_type: str = "expanding") -> np.ndarray:
    """Reuse this model's own past fitted params when reestimate=false
    (databaseoperations.jl:36-72)."""
    db_path = _merged_db_path(spec.results_location, spec.model_string, window_type)
    params = read_task_params(db_path, task_id)
    if params is None:
        return all_params
    all_params = np.asarray(all_params, dtype=np.float64).copy()
    all_params[:, 0] = params
    return all_params


# ---------------------------------------------------------------------------
# legacy CSV export (databaseoperations.jl:391-661)
# ---------------------------------------------------------------------------

def _legacy_path(results_folder, model_string, thread_id, window_type, kind):
    return os.path.join(
        results_folder,
        f"{model_string}__thread_id__{thread_id}__{window_type}_window_{kind}.csv",
    )


def _write_csv(path: str, rows: np.ndarray) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # torn-file-proof publish (YFM005); pid+tid: worker THREADS share a pid
    tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
    np.savetxt(tmp, rows, delimiter=",", fmt="%.18g")
    os.replace(tmp, path)
    return path


def _export_wide(db_path, results_folder, model_string, thread_id, tasks,
                 window_type, column, kind):
    """(origin, target, values...) long format, sorted by target then origin."""
    rows = []
    db = sqlite3.connect(db_path, timeout=10.0)
    try:
        for task_id in tasks:
            row = db.execute(
                f"SELECT task_id, {column} FROM forecasts WHERE task_id = ?",
                (int(task_id),),
            ).fetchone()
            if row is None:
                continue
            P = deser(row[1])
            K, H = P.shape
            for h in range(H):
                rows.append([float(task_id), float(task_id + h + 1)] + list(P[:, h]))
    finally:
        db.close()
    arr = np.asarray(rows, dtype=np.float64)
    if arr.size:
        arr = arr[np.lexsort((arr[:, 1],))]
        arr = arr[np.lexsort((arr[:, 0],))]
    return _write_csv(_legacy_path(results_folder, model_string, thread_id, window_type, kind), arr)


def _export_params(db_path, results_folder, model_string, thread_id, tasks, window_type):
    rows = []
    db = sqlite3.connect(db_path, timeout=10.0)
    try:
        for task_id in tasks:
            row = db.execute(
                "SELECT task_id, params FROM forecasts WHERE task_id = ?", (int(task_id),)
            ).fetchone()
            if row is None:
                continue
            p = deser(row[1]).reshape(-1)
            rows.append([float(task_id)] + list(p))
    finally:
        db.close()
    arr = np.asarray(rows, dtype=np.float64)
    if arr.size:
        arr = arr[np.argsort(arr[:, 0], kind="stable")]
    return _write_csv(
        _legacy_path(results_folder, model_string, thread_id, window_type, "fitted_params"), arr
    )


def export_all_csv(spec, thread_id: str, tasks: Sequence[int],
                   window_type: str = "expanding") -> dict:
    """forecasts / fitted_params / fl1 / fl2 / factors / states CSVs in the
    reference's legacy layout (databaseoperations.jl:654-661)."""
    folder = spec.results_location
    db_path = os.path.join(folder, "db", f"forecasts_{window_type}_merged.sqlite3")
    ms = spec.model_string
    return {
        "forecasts": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "preds", "forecasts"),
        "fitted_params": _export_params(db_path, folder, ms, thread_id, tasks, window_type),
        "fl1": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "fl1", "fl1"),
        "fl2": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "fl2", "fl2"),
        "factors": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "factors", "factors"),
        "states": _export_wide(db_path, folder, ms, thread_id, tasks, window_type, "states", "states"),
    }
