"""Jit-compatible failure taxonomy: int32 bitmask beside the sentinels.

The sentinel convention (CLAUDE.md, docs/DESIGN.md §4) keeps failures silent
inside jitted code — loss → −Inf, moments → NaN, PF draws → −Inf.  That tells
a driver *that* a start died but never *why*, and the only recovery is "drop
it and hope another start lands".  This module adds a self-describing channel
with the same discipline: kernels accumulate an ``int32`` bitmask through the
scan carries they already thread (``ok`` flags, −Inf gates), nothing raises,
and only driver-layer code decodes the mask into names
(:func:`decode`/:func:`describe`).

The bits are OR-combinable (one evaluation can hit several causes) and shared
by every layer — filter kernels (``ops/``, ``models/``), the online serving
update (``serving/online.py``), the escalation ladder
(``robustness/ladder.py``) and the task-boundary failures
(``orchestration/retry.SentinelFailure``).

Healthy-path cost is zero by construction: the code rides carries that
already exist, is pure int arithmetic, and XLA dead-code-eliminates it from
callers that only consume the loss (the same mechanism that prunes the unused
moment stacks from ``univariate_kf.get_loss`` — pinned by ``BENCH_ROBUST=1``
in bench.py).
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp

from ..config import register_engine_cache

#: dtype every in-jit code rides as (bitwise-or friendly, cheap on TPU)
CODE_DTYPE = jnp.int32

OK = 0
#: a scalar innovation variance f = zᵀPz + σ² came out ≤ 0 (indefinite P or
#: invalid σ²) — the univariate/joint engines' non-PD failure
NONPSD_INNOVATION = 1
#: a Cholesky/QR factorization produced non-finite entries (Ω_state, P₀, or
#: the joint form's innovation factor)
CHOL_BREAKDOWN = 2
#: a state/innovation/likelihood quantity went non-finite mid-recursion
#: (overflowed transition, NaN-poisoned carry)
STATE_EXPLODED = 4
#: non-finite entries in the (constrained) parameter vector itself — an
#: overflowed bijection (exp of a huge raw value) before the filter ever ran
TRANSFORM_OVERFLOW = 8
#: the estimation window contributed zero observations (all-NaN columns or a
#: degenerate [start, end) span) — the loss is vacuous, not just invalid
MISSING_ALL_OBS = 16
#: a covariance watched by the serving health monitor lost positive
#: semi-definiteness (min eigenvalue below tolerance)
NONPSD_COV = 32
#: a serving state carried non-finite entries (the NaN-poisoned-update class)
NAN_STATE = 64
#: the second-order polish saw a non-PSD/indefinite model Hessian — negative
#: curvature in the CG subproblem or a non-finite HVP (a contributing F_t
#: failed to factorize) — and fell back to the damped/steepest-descent path
#: (ops/newton.py damping table, docs/DESIGN.md §17)
NONPSD_HESSIAN = 128

#: bit → name, in bit order (the decode vocabulary; keep sorted by value)
NAMES = (
    (NONPSD_INNOVATION, "NONPSD_INNOVATION"),
    (CHOL_BREAKDOWN, "CHOL_BREAKDOWN"),
    (STATE_EXPLODED, "STATE_EXPLODED"),
    (TRANSFORM_OVERFLOW, "TRANSFORM_OVERFLOW"),
    (MISSING_ALL_OBS, "MISSING_ALL_OBS"),
    (NONPSD_COV, "NONPSD_COV"),
    (NAN_STATE, "NAN_STATE"),
    (NONPSD_HESSIAN, "NONPSD_HESSIAN"),
)


# ---------------------------------------------------------------------------
# in-jit helpers (pure jnp; safe inside scan bodies)
# ---------------------------------------------------------------------------

def bit(cond, flag: int):
    """``cond ? flag : 0`` as an int32 — the one idiom kernels use to raise a
    taxonomy bit inside jit (branchless, like every other mask here)."""
    return jnp.where(cond, jnp.int32(flag), jnp.int32(0))


def zero_code():
    return jnp.zeros((), dtype=CODE_DTYPE)


def combine(codes):
    """Bitwise-OR reduce an array of per-step codes to one scalar int32 —
    jit-safe (a static unroll of one ``any`` per known flag, so it lowers to
    a handful of reductions regardless of array length)."""
    codes = jnp.asarray(codes, dtype=CODE_DTYPE)
    out = jnp.zeros((), dtype=CODE_DTYPE)
    for flag, _ in NAMES:
        out = out | bit(jnp.any((codes & flag) != 0), flag)
    return out


def params_code(params):
    """TRANSFORM_OVERFLOW if the constrained parameter vector is non-finite —
    evaluated once at kernel entry, before any filter arithmetic."""
    return bit(~jnp.all(jnp.isfinite(params)), TRANSFORM_OVERFLOW)


# ---------------------------------------------------------------------------
# driver-layer decoding (host-side; never called inside jit)
# ---------------------------------------------------------------------------

def decode(code) -> tuple:
    """Bitmask → tuple of names, e.g. ``decode(3) ==
    ('NONPSD_INNOVATION', 'CHOL_BREAKDOWN')``.  ``decode(0) == ()``."""
    c = int(code)
    return tuple(name for flag, name in NAMES if c & flag)


def describe(code) -> str:
    """Human/log form: ``'NONPSD_INNOVATION|CHOL_BREAKDOWN'`` or ``'OK'``."""
    names = decode(code)
    return "|".join(names) if names else "OK"


def coded_loss_fn(spec):
    """The family's ``get_loss_coded`` (scan engine): Kalman → the univariate
    sequential-update kernel, score-driven/static → their coded losses."""
    from ..models import score_driven, static_model
    from ..ops import univariate_kf

    if spec.is_kalman:
        return univariate_kf.get_loss_coded
    if spec.is_msed:
        return score_driven.get_loss_coded
    return static_model.get_loss_coded


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_diagnose(spec, T: int):
    """Jitted coded scan-engine loss — the repo-standard trace-time builder
    idiom (`@register_engine_cache` + `@lru_cache`, CLAUDE.md) so the cache
    participates in engine-switch invalidation like every other
    (spec, T)-keyed program.  (The coded kernels are pinned to the scan
    engine by construction; registration keeps the cache discipline uniform
    rather than being load-bearing.)"""
    import jax

    fn = coded_loss_fn(spec)
    return jax.jit(lambda p, d, s, e: fn(spec, p, d, s, e))


def diagnose(spec, params, data, start=0, end=None):
    """One coded scan-engine evaluation at CONSTRAINED ``params`` — the
    driver-layer entry point for "why did this start die?".  Returns
    ``(loglik, code)`` as Python scalars."""
    import jax.numpy as jnp_  # local: keep module import light

    data = jnp_.asarray(data, dtype=spec.dtype)
    params = jnp_.asarray(params, dtype=spec.dtype)
    T = int(data.shape[1])
    if end is None:
        end = T
    runner = _jitted_diagnose(spec, T)
    ll, code = runner(params, data, jnp_.asarray(start), jnp_.asarray(end))
    return float(ll), int(code)
