"""Closed-loop sustained-load harness for the serving gateway (DESIGN §12).

Drives a sustained MIX of traffic — O(1) updates, predictive-density
forecasts, scenario fans — through a :class:`~..serving.gateway.ServingGateway`
at a controlled offered QPS and measures the request path end to end:
per-request latency from submit to collected answer (p50/p99/p999), achieved
vs offered throughput, shed rate, degraded-answer rate, and (via
:func:`measure_capacity`) the max sustained QPS the closed loop completes.

Closed loop, single thread: the caller's thread IS the worker loop
(submit a burst → ``pump()`` → collect), so chaos seams
(``queue_stall``/``slow_update``, orchestration/chaos.py) fire reproducibly
and every request's outcome is accounted — an unhandled exception anywhere
in the request path fails the harness, which is the acceptance bar: under
chaos every failure must surface as a shed, degraded, or structured-error
response, never a crash.

The request LEDGER (offered = ok + degraded + shed + errors + abandoned) is
reconciled against the gateway's :class:`~..serving.service.RequestCounters`
by tests/test_gateway.py — the load generator and the operator's ``health()``
report must be two views of the same numbers.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Tuple

import numpy as np

from ..serving.snapshot import ServingError
from ..utils.profiling import _nearest_rank


@dataclasses.dataclass
class LoadReport:
    """One sustained-load run, ledger + latency percentiles (ms)."""

    offered: int
    ok: int
    degraded: int
    shed: int
    errors: int
    abandoned: int          # still outstanding after the drain rounds
    wall_s: float
    offered_qps: float      # the controlled target rate
    achieved_qps: float     # answered (ok + degraded) per wall second
    p50_ms: float
    p99_ms: float
    p999_ms: float
    max_sustained_qps: float = float("nan")  # from measure_capacity()

    @property
    def shed_rate(self) -> float:
        return self.shed / self.offered if self.offered else 0.0

    @property
    def degraded_rate(self) -> float:
        return self.degraded / self.offered if self.offered else 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["shed_rate"] = round(self.shed_rate, 6)
        d["degraded_rate"] = round(self.degraded_rate, 6)
        return d


def _percentiles_ms(latencies) -> Tuple[float, float, float]:
    if not latencies:
        return 0.0, 0.0, 0.0
    s = sorted(latencies)
    return tuple(1e3 * _nearest_rank(s, q) for q in (0.50, 0.99, 0.999))


def zipf_weights(n: int, s: float = 1.2) -> np.ndarray:
    """Normalized Zipf(s) popularity over ``n`` keys (weight ∝ 1/rank^s) —
    the LRU-friendly skewed key mix the tiered-store working-set column
    drives (DESIGN §21): a small head of keys carries most of the traffic,
    so a hot tier smaller than the working set can still keep the hit rate
    high.  Rank order follows key order (rank 1 = first key)."""
    if n < 1:
        raise ValueError(f"need n >= 1 keys, got {n}")
    w = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** float(s)
    return w / w.sum()


class _MixedTraffic:
    """Seeded request generator: kind by cumulative mix, curves by column.
    With ``keys`` (a sequence of state-store keys) each request addresses a
    drawn key — uniform by default, or by the ``key_weights`` popularity
    vector (e.g. :func:`zipf_weights`) — the multi-user traffic shape the
    sharded gateway routes across the mesh (DESIGN §16, §21)."""

    def __init__(self, gateway, curves, mix, horizon, n_scenarios,
                 quantiles, seed, keys=None, key_weights=None):
        self.gateway = gateway
        self.curves = np.asarray(curves)
        self.cum = np.cumsum(np.asarray(mix, dtype=np.float64))
        if self.cum.shape != (3,) or abs(self.cum[-1] - 1.0) > 1e-9:
            raise ValueError(f"mix must be 3 weights summing to 1, got {mix}")
        self.horizon = int(horizon)
        self.n_scenarios = int(n_scenarios)
        self.quantiles = quantiles
        self.rng = np.random.default_rng(seed)
        self.keys = list(keys) if keys is not None else None
        self.key_weights = None
        if key_weights is not None:
            if self.keys is None:
                raise ValueError("key_weights given without keys")
            w = np.asarray(key_weights, dtype=np.float64)
            if w.shape != (len(self.keys),) or np.any(w < 0) or w.sum() <= 0:
                raise ValueError(
                    f"key_weights must be {len(self.keys)} non-negative "
                    f"weights with positive mass, got shape {w.shape}")
            self.key_weights = w / w.sum()
        self.i = 0

    def _kw(self) -> dict:
        if self.keys is None:
            return {}
        if self.key_weights is None:
            return {"key": self.keys[self.rng.integers(len(self.keys))]}
        return {"key": self.keys[self.rng.choice(len(self.keys),
                                                 p=self.key_weights)]}

    def submit_one(self) -> int:
        """Submit the next mixed request; returns its ticket (a shed raises
        the gateway's structured admission error through to the caller)."""
        i, u = self.i, self.rng.random()
        self.i += 1
        gw, T = self.gateway, self.curves.shape[1]
        if u < self.cum[0]:
            return gw.submit_update(i, self.curves[:, i % T], **self._kw())
        if u < self.cum[1]:
            return gw.submit_forecast(self.horizon, self.quantiles,
                                      **self._kw())
        return gw.submit_scenarios(self.n_scenarios, self.horizon, seed=i,
                                   **self._kw())


def run_load(gateway, curves, *, duration_s: float = 2.0,
             offered_qps: float = 100.0,
             mix: Tuple[float, float, float] = (0.6, 0.3, 0.1),
             horizon: int = 8, n_scenarios: int = 8,
             quantiles: Optional[Tuple[float, ...]] = None,
             burst: int = 4, seed: int = 0,
             drain_rounds: int = 200, keys=None,
             key_weights=None) -> LoadReport:
    """Drive ``duration_s`` of mixed traffic at ``offered_qps`` through the
    gateway, closed-loop (each burst is submitted, pumped, then collected —
    outstanding tickets are re-polled after later pumps, so a stalled cycle
    shows up as tail latency, not lost requests).  After the run the queue is
    drained for up to ``drain_rounds`` extra pumps; anything still
    outstanding is reported ``abandoned`` (only a permanently-stalled worker
    leaves any)."""
    traffic = _MixedTraffic(gateway, curves, mix, horizon, n_scenarios,
                            quantiles, seed, keys=keys,
                            key_weights=key_weights)
    latencies, outstanding = [], []
    ok = degraded = shed = errors = 0
    t_start = time.perf_counter()

    def collect():
        nonlocal ok, degraded, errors
        still = []
        for ticket, t0 in outstanding:
            try:
                out = gateway.poll(ticket)
            except ServingError:
                errors += 1
                latencies.append(time.perf_counter() - t0)
                continue
            if out is None:
                still.append((ticket, t0))
                continue
            latencies.append(time.perf_counter() - t0)
            if out.get("degraded"):
                degraded += 1
            else:
                ok += 1
        outstanding[:] = still

    while time.perf_counter() - t_start < duration_s:
        # pace the next burst at the offered rate; a loop that has fallen
        # behind schedule submits immediately (saturation, not sleep debt)
        t_sched = t_start + traffic.i / offered_qps
        wait = t_sched - time.perf_counter()
        if wait > 0:
            time.sleep(wait)
        for _ in range(burst):
            t0 = time.perf_counter()
            try:
                outstanding.append((traffic.submit_one(), t0))
            except ServingError:
                shed += 1
        gateway.pump()
        collect()
    for _ in range(drain_rounds):
        if not outstanding and len(gateway) == 0:
            break
        gateway.pump()
        collect()
    wall = time.perf_counter() - t_start
    p50, p99, p999 = _percentiles_ms(latencies)
    return LoadReport(
        offered=traffic.i, ok=ok, degraded=degraded, shed=shed,
        errors=errors, abandoned=len(outstanding), wall_s=round(wall, 4),
        offered_qps=float(offered_qps),
        achieved_qps=round((ok + degraded) / wall, 2) if wall else 0.0,
        p50_ms=round(p50, 3), p99_ms=round(p99, 3), p999_ms=round(p999, 3))


def measure_capacity(gateway, curves, *, n: int = 128,
                     mix: Tuple[float, float, float] = (0.6, 0.3, 0.1),
                     horizon: int = 8, n_scenarios: int = 8,
                     burst: int = 8, seed: int = 1, keys=None,
                     key_weights=None) -> float:
    """Max sustained QPS: the UNPACED closed-loop completion rate — bursts
    submitted back-to-back with the service always busy, queue depth bounded
    by the burst, nothing shed.  This is the saturation throughput the paced
    ``run_load`` offered rate is set against (chaos should be DISARMED here;
    arm it for the measured run, not the yardstick)."""
    traffic = _MixedTraffic(gateway, curves, mix, horizon, n_scenarios,
                            None, seed, keys=keys, key_weights=key_weights)
    answered = 0
    t0 = time.perf_counter()
    while traffic.i < n:
        tickets = []
        for _ in range(min(burst, n - traffic.i)):
            try:
                tickets.append(traffic.submit_one())
            except ServingError:
                pass  # unexpected at saturation depth ≤ burst, but bounded
        gateway.pump()
        for t in tickets:
            try:
                if gateway.poll(t) is not None:
                    answered += 1
            except ServingError:
                pass
    wall = time.perf_counter() - t0
    return answered / wall if wall > 0 else float("inf")


def mesh_scaling(gateway_factory, curves, *,
                 mesh_sizes: Tuple[int, ...] = (1, 2, 4, 8),
                 n: int = 256, burst: int = 64,
                 mix: Tuple[float, float, float] = (1.0, 0.0, 0.0),
                 duration_s: float = 0.0, seed: int = 1) -> dict:
    """The MESH-SIZE dimension of the sustained-load ledger (DESIGN §16):
    for each mesh size ``m``, build a fresh sharded gateway via
    ``gateway_factory(m) -> (gateway, keys)`` (a :class:`~..serving.gateway.
    ShardedGateway` over a store whose TOTAL capacity is held fixed, so a
    bigger mesh means smaller shards — the production scaling shape), then
    measure the unpaced closed-loop capacity (:func:`measure_capacity`) and,
    optionally (``duration_s > 0``), a paced :func:`run_load` pass for the
    latency percentiles at ~80% of that capacity.

    Returns one ledger record::

        {"mesh_sizes": [...], "capacity_qps": [...],
         "p50_ms": [...], "p99_ms": [...],            # NaN when unpaced
         "scaling": capacity[largest] / capacity[smallest]}

    This is how the "throughput scales with the mesh" claim becomes a
    MEASURED line (BASELINE.md discipline: both sides of every claim), on
    the 8-virtual-device CPU harness today and on real chips unchanged.
    """
    sizes = sorted(set(int(m) for m in mesh_sizes))
    caps, p50s, p99s = [], [], []
    for m in sizes:
        gateway, keys = gateway_factory(m)
        cap = measure_capacity(gateway, curves, n=n, mix=mix, burst=burst,
                               seed=seed, keys=keys)
        caps.append(round(cap, 2))
        if duration_s > 0:
            rep = run_load(gateway, curves, duration_s=duration_s,
                           offered_qps=0.8 * cap, mix=mix, burst=burst,
                           seed=seed, keys=keys)
            p50s.append(rep.p50_ms)
            p99s.append(rep.p99_ms)
        else:
            p50s.append(float("nan"))
            p99s.append(float("nan"))
    return {
        "mesh_sizes": sizes,
        "capacity_qps": caps,
        "p50_ms": p50s,
        "p99_ms": p99s,
        "scaling": round(caps[-1] / caps[0], 3) if caps and caps[0] else
        float("nan"),
    }


@dataclasses.dataclass
class FanLoadReport:
    """One streaming-subscription load run (DESIGN §23): sustained fan
    answers per second over a stream of accepted online updates, the
    per-update refresh wall (update + delta wave, p50/p99), answer-time
    staleness p99, and the degraded-answer rate."""

    updates: int
    subscriptions: int
    fans: int               # fan answers collected (updates × subscriptions)
    wall_s: float
    fans_per_s: float
    refresh_p50_ms: float   # accepted update + its delta-refresh wave
    refresh_p99_ms: float
    stale_p99_ms: float     # answer-time age of the promoted fan
    degraded: int

    @property
    def degraded_rate(self) -> float:
        return self.degraded / self.fans if self.fans else 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["degraded_rate"] = round(self.degraded_rate, 6)
        return d


@dataclasses.dataclass
class RecoveryReport:
    """One shard-kill recovery run under sustained keyed updates
    (DESIGN §24): the request ledger across the loss window, the rebuild
    ledger (kills, rebuild waves, journal replays, gapped keys), MTTR
    percentiles (detection → rebuilt, from the store timer's ``recover``
    stage), and the ZERO-LOST-ACCEPTED-UPDATES verdict — every ungapped
    key's post-run resident state bit-identical to a fault-free twin fed
    exactly the accepted stream."""

    rounds: int
    updates_offered: int
    updates_accepted: int
    updates_degraded: int
    shed: int
    errors: int
    kills: int
    rebuilds: int
    replayed_updates: int
    gapped_keys: int
    wall_s: float
    mttr_p50_s: float
    mttr_p99_s: float
    parity_checked: int     # ungapped keys bit-compared against the twin
    lost_accepted: int      # ungapped keys whose bits diverged — MUST be 0

    @property
    def degraded_rate(self) -> float:
        return self.updates_degraded / self.updates_offered \
            if self.updates_offered else 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["degraded_rate"] = round(self.degraded_rate, 6)
        return d


def run_recovery_load(gateway, store, twin, curves, keys, *,
                      rounds: int = 40, kill_at=(),
                      chaos_kill_rounds=(),
                      poll_rounds: int = 200) -> RecoveryReport:
    """Drive ``rounds`` of one-update-per-key traffic through a sharded
    ``gateway`` while shards die mid-stream, and verify the failure-domain
    contract end to end (DESIGN §24).

    ``kill_at`` is ``[(round, shard), ...]`` explicit kills
    (``store.mark_shard_lost`` fired just before that round's submissions).
    ``chaos_kill_rounds`` kills through the ``shard_lost`` chaos seam
    instead: the harness arms ``shard_lost:@1`` for exactly that round's
    store dispatch and disarms it before the twin feed — the seam's
    counters are process-global, so leaving it armed across the round
    boundary could fire inside the fault-free TWIN and poison the parity
    baseline (the harness owns the seam during those rounds).  ``twin`` is
    a fault-free store with the SAME keys registered from the SAME
    snapshots: after each round the twin is fed exactly the updates the
    gateway ACCEPTED, so at the end every ungapped key must be
    bit-identical across the two stores — any divergence is a lost
    accepted update (``lost_accepted``), the one number that must be zero.
    Closed loop, single thread: every submitted ticket is pumped/polled to
    an answer (bounded by ``poll_rounds``) — an unhandled exception
    anywhere fails the harness."""
    from ..orchestration import chaos

    kill_at = {int(r): int(s) for r, s in kill_at}
    chaos_kill_rounds = {int(r) for r in chaos_kill_rounds}
    curves = np.asarray(curves)
    T = curves.shape[1]
    offered = accepted = degraded = shed = errors = kills = 0
    t_start = time.perf_counter()
    for r in range(rounds):
        s = kill_at.get(r)
        if s is not None:
            store.mark_shard_lost(s, "load-harness kill")
            kills += 1
        armed = r in chaos_kill_rounds
        if armed:
            chaos.configure("shard_lost:@1")
        y = curves[:, r % T]
        tickets = []
        for k in keys:
            offered += 1
            try:
                tickets.append((k, gateway.submit_update(r, y, key=k)))
            except ServingError:
                shed += 1       # admission control, never a lost accept
        outstanding = dict(tickets)
        accepted_now = []
        for _ in range(poll_rounds):
            gateway.pump()
            for k in list(outstanding):
                try:
                    out = gateway.poll(outstanding[k])
                except ServingError:
                    errors += 1
                    del outstanding[k]
                    continue
                if out is None:
                    continue
                del outstanding[k]
                if out.get("error") is not None:
                    errors += 1
                elif out.get("degraded"):
                    degraded += 1
                else:
                    accepted += 1
                    accepted_now.append(k)
            if not outstanding:
                break
        errors += len(outstanding)      # permanently stalled = harness bug
        if armed:
            kills += chaos.fired("shard_lost")
            chaos.reset()               # never leave the seam armed for the
            # twin feed below — its counters are process-global
        if accepted_now:
            # mirror THIS round's accepted stream into the fault-free twin
            # (per-key recursion order is all that matters for parity)
            twin.update_batch([(k, y) for k in accepted_now])
    wall = time.perf_counter() - t_start
    checked = lost = 0
    gapped = set(getattr(store, "_gapped_keys", ()))
    for k in keys:
        if k in gapped:
            continue
        a, b = store.snapshot_of(k), twin.snapshot_of(k)
        checked += 1
        same = (a.meta.version == b.meta.version
                and np.array_equal(np.asarray(a.beta), np.asarray(b.beta))
                and np.array_equal(np.asarray(a.P), np.asarray(b.P)))
        lost += not same
    rec = store.recovery
    mttr = sorted(store.timer.samples.get("recover", ()))
    return RecoveryReport(
        rounds=rounds, updates_offered=offered, updates_accepted=accepted,
        updates_degraded=degraded, shed=shed, errors=errors, kills=kills,
        rebuilds=rec.rebuilt_shards, replayed_updates=rec.replayed_updates,
        gapped_keys=len(gapped), wall_s=round(wall, 4),
        mttr_p50_s=round(_nearest_rank(mttr, 0.50), 6) if mttr else 0.0,
        mttr_p99_s=round(_nearest_rank(mttr, 0.99), 6) if mttr else 0.0,
        parity_checked=checked, lost_accepted=lost)


def run_fan_load(hub, service, curves, dates) -> FanLoadReport:
    """Drive a :class:`~..serving.streams.ScenarioStreamHub` over ``service``
    with one accepted update per (date, curve) and collect EVERY
    subscription's fan answer after each — closed loop, the caller's thread
    is the update path, so the refresh wall includes exactly what a live
    subscriber waits on.  The full-recompute baseline this is compared
    against (``bench.py --load-fan-bench``) replaces the hub answers with
    per-subscription ``stress_fan`` recomputes over the same stream."""
    refresh_s, ages = [], []
    fans = degraded = 0
    keys = hub.subscriptions()
    t_start = time.perf_counter()
    for date, curve in zip(dates, curves):
        t0 = time.perf_counter()
        service.update(date, curve)
        refresh_s.append(time.perf_counter() - t0)
        for key in keys:
            ans = hub.fan(key)
            fans += 1
            degraded += bool(ans["degraded"])
            if ans["age_ms"] is not None:
                ages.append(ans["age_ms"] / 1e3)
    wall = time.perf_counter() - t_start
    _, r99, _ = _percentiles_ms(refresh_s)
    r50 = _percentiles_ms(refresh_s)[0]
    _, a99, _ = _percentiles_ms(ages)
    return FanLoadReport(
        updates=len(refresh_s), subscriptions=len(keys), fans=fans,
        wall_s=round(wall, 4),
        fans_per_s=round(fans / wall, 2) if wall else 0.0,
        refresh_p50_ms=round(r50, 3), refresh_p99_ms=round(r99, 3),
        stale_p99_ms=round(a99, 3), degraded=degraded)
