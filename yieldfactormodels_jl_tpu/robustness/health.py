"""Serving-state health: covariance watch, PSD scrub, refresh cadence.

The online service (``serving/service.py``) advances one covariance through
thousands of O(1) updates; nothing in that recursion re-validates the state,
so drift (f32 rank-1 downdates), a poisoned update, or an operator mistake
can leave the in-memory (β, P) silently broken until every later request
fails.  This module is the driver-side watch (CLAUDE.md: loud checks belong
at the driver, sentinels inside jit):

- :func:`state_health` — min-eigenvalue / condition / finiteness of the
  current :class:`~..serving.online.OnlineState`, as taxonomy bits
  (robustness/taxonomy.py: ``NAN_STATE``, ``NONPSD_COV``);
- :func:`refresh_state` — the periodic square-root scrub
  (``YFM_SERVE_REFRESH``): symmetrize + eigenvalue-clip the covariance (or
  re-triangularize the sqrt factor), the cheap cousin of re-freezing a
  snapshot;
- :func:`serve_refresh_every` — the env-gated cadence.

Everything here is host-side NumPy on Ms ≤ 5 matrices (micro-seconds per
update, no extra device programs); the jitted update kernels stay untouched.
"""

from __future__ import annotations

import os

import numpy as np

from . import taxonomy as tax

#: relative tolerance for "non-PSD": min eigenvalue below −EIG_TOL·max(1, λmax)
EIG_TOL = 1e-8


def serve_refresh_every(override=None) -> int:
    """Updates between square-root refreshes of the online covariance:
    the ``refresh_every`` constructor argument, else ``YFM_SERVE_REFRESH``
    (int, seconds-free — it counts updates), else 0 = off."""
    if override is not None:
        return int(override)
    env = os.environ.get("YFM_SERVE_REFRESH", "")
    return int(env) if env else 0


def _cov_matrix(cov, engine: str) -> np.ndarray:
    """P itself for the univariate engine; S Sᵀ for the sqrt engine."""
    c = np.asarray(cov, dtype=np.float64)
    return c @ c.T if engine == "sqrt" else c


def state_health(beta, cov, engine: str = "univariate") -> dict:
    """Health report for one online state: taxonomy ``code`` (0 = healthy)
    plus the numbers behind it (``min_eig``, ``cond``).  Never raises."""
    b = np.asarray(beta, dtype=np.float64)
    c = np.asarray(cov, dtype=np.float64)
    if not (np.all(np.isfinite(b)) and np.all(np.isfinite(c))):
        return dict(code=tax.NAN_STATE, min_eig=float("nan"),
                    cond=float("nan"))
    P = _cov_matrix(c, engine)
    P = 0.5 * (P + P.T)
    w = np.linalg.eigvalsh(P)
    min_eig, max_eig = float(w[0]), float(w[-1])
    cond = float(max_eig / min_eig) if min_eig > 0 else float("inf")
    # NB the sqrt engine's S Sᵀ is PSD for ANY finite S, so this watch can
    # only catch non-finite factors there — a finite-but-wrong factor is
    # invisible by construction, which is why the serving driver forces a
    # restore when it KNOWS the state was corrupted (chaos seams,
    # service._heal_state(force=True))
    nonpsd = min_eig < -EIG_TOL * max(1.0, abs(max_eig))
    return dict(code=tax.NONPSD_COV if nonpsd else tax.OK,
                min_eig=min_eig, cond=cond)


def state_health_batch(betas, covs, engine: str = "univariate") -> np.ndarray:
    """Vectorized :func:`state_health` for a micro-batch of states — ``betas``
    (Ms, B), ``covs`` (Ms, Ms, B) per the lane rule — returning an int32
    taxonomy-code vector (B,).  One batched ``eigvalsh`` instead of B host
    calls: the sharded store's per-request watch must stay O(batch) cheap
    (serving/store.py), and the verdicts match :func:`state_health` bit for
    bit (pinned in tests/test_store.py)."""
    b = np.asarray(betas, dtype=np.float64)
    c = np.asarray(covs, dtype=np.float64)
    B = b.shape[-1]
    P = np.moveaxis(c, -1, 0)                      # (B, Ms, Ms)
    if engine == "sqrt":
        P = P @ np.swapaxes(P, -1, -2)
    P = 0.5 * (P + np.swapaxes(P, -1, -2))
    codes = np.zeros(B, dtype=np.int32)
    finite = np.isfinite(b).all(axis=0) & np.isfinite(P).all(axis=(1, 2))
    codes[~finite] = tax.NAN_STATE
    if finite.any():
        w = np.linalg.eigvalsh(np.where(finite[:, None, None], P,
                                        np.eye(P.shape[-1])[None]))
        nonpsd = w[:, 0] < -EIG_TOL * np.maximum(1.0, np.abs(w[:, -1]))
        codes[finite & nonpsd] = tax.NONPSD_COV
    return codes


def refresh_state(beta, cov, engine: str = "univariate", floor: float = 0.0):
    """The periodic square-root refresh: return a scrubbed ``cov``.

    - ``"univariate"``: P ← PSD projection of sym(P) (eigendecompose, clip
      eigenvalues at ``floor``) — removes the asymmetry/indefiniteness the
      rank-1 downdates accumulate, exactly the drift the long-horizon
      regression test measures (tests/test_robustness.py);
    - ``"sqrt"``: S ← chol of the projected S Sᵀ — re-triangularizes a factor
      whose columns have rotated over many Potter updates.

    Pure host-side float64 on an Ms×Ms matrix; β passes through untouched.
    """
    c = np.asarray(cov, dtype=np.float64)
    P0 = _cov_matrix(c, engine)
    P = 0.5 * (P0 + P0.T)
    w, V = np.linalg.eigh(P)
    w = np.maximum(w, floor)
    P = (V * w) @ V.T
    if engine == "sqrt":
        # chol needs strictly PD; pad only if the clip left exact zeros
        if not np.all(w > 0):
            P = P + 1e-12 * np.trace(P) / P.shape[0] * np.eye(P.shape[0])
        return np.linalg.cholesky(P)
    return P
