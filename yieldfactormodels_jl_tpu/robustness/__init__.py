"""Numerics sentry (docs/DESIGN.md §11): diagnosed, recoverable failures.

Three layers over the repo-wide sentinel convention (CLAUDE.md / DESIGN §4):

- ``taxonomy``: a jit-compatible int32 failure bitmask threaded alongside the
  −Inf/NaN sentinels through every filter kernel — sentinels stay silent
  inside jit, but now say *why* once a driver decodes them;
- ``ladder``: a deterministic, env-gated (``YFM_ESCALATE``) escalation ladder
  that retries non-finite multi-start results through progressively more
  robust evaluations (scan re-eval → square-root filter → jittered covariance
  regularization → the reference's ×0.95 shrink) instead of dropping them;
- ``health``: online-serving state health — per-update min-eigenvalue watch,
  periodic square-root refresh (``YFM_SERVE_REFRESH``), and the PSD scrub the
  self-healing ``YieldCurveService`` rebuild path uses;
- ``loadgen``: the closed-loop sustained-load harness for the serving
  gateway (mixed traffic at controlled QPS, p50/p99/p999 + shed/degraded
  ledger, ``BENCH_LOAD=1`` in bench.py; docs/DESIGN.md §12).

Submodules and names are resolved lazily: the filter kernels import
``taxonomy`` at module load, so this package must not import them back at
import time (the ``ops/__init__`` idiom).
"""

from importlib import import_module

_SUBMODULES = ("taxonomy", "ladder", "health", "loadgen")

_EXPORTS = {
    "decode": "taxonomy",
    "describe": "taxonomy",
    "LadderTrace": "ladder",
    "escalation_enabled": "ladder",
    "LoadReport": "loadgen",
    "RecoveryReport": "loadgen",
    "run_load": "loadgen",
    "run_recovery_load": "loadgen",
    "measure_capacity": "loadgen",
}


def __getattr__(name):
    if name in _SUBMODULES:
        return import_module(f".{name}", __name__)
    if name in _EXPORTS:
        return getattr(import_module(f".{_EXPORTS[name]}", __name__), name)
    raise AttributeError(name)


__all__ = list(_SUBMODULES) + list(_EXPORTS)
