"""Engine escalation ladder: retry a dead start, don't drop it.

The reference rescues an invalid start with a blind ×0.95 shrink and moves on
(/root/reference/src/optimization.jl:173-184); the port kept that, so a start
whose optimized point comes back non-finite is simply dropped from the
multi-start comparison.  This module climbs a deterministic ladder of
progressively more robust evaluations instead — the square-root rung is the
numerically-safe fallback the literature prescribes for breakdown-prone
covariance recursions (Yaghoobi et al., arXiv:2207.00426), and the repo
already ships the engine (ops/sqrt_kf.py); it was just never reached
automatically:

1. ``scan``   one coded re-evaluation on the scan engine — recovers
   fused-kernel artifacts (the trust-but-verify class, DESIGN §7) and
   produces the taxonomy diagnosis every later rung reports;
2. ``assoc``  LONG panels only (T >= ``ASSOC_RESCUE_MIN_T``, constant-Z
   Kalman families): the associative-scan engine with PSD-*projected*
   composed moments (``assoc_scan.get_loss_coded(psd_floor=...)``,
   docs/DESIGN.md §13) — the same stabilized surrogate as the sqrt rung but
   at O(log T) span, so a dead 20k-step daily panel is re-evaluated in tree
   depth instead of another 20k sequential steps; parameters unchanged;
2b. ``slr``   the nonlinear twin of the assoc rung (same length gate): the
   iterated-SLR engine with PSD-*projected* moments
   (``slr_scan.get_loss_coded(psd_floor=...)``, docs/DESIGN.md §19) for the
   Kalman families whose measurement is state-dependent (TVλ) — a dead
   long-panel EKF start is re-evaluated at tree span too;
2c. ``score_tree`` the score-driven twin (same length gate): the capable
   score-driven specs (``spec.supports_score_tree``) re-evaluate a dead
   long-panel start on the O(log T) score-tree engine
   (``score_scan.get_loss_coded``, docs/DESIGN.md §19) — the tree's affine
   surrogate + exact refinement can return a finite loss where the fused
   sequential artifact died, and answers at tree depth;
3. ``sqrt``   the square-root filter with PSD-*projected* initial moments
   (``sqrt_kf.get_loss_coded(init_psd_floor=...)``): covariance breakdowns
   (NONPSD_INNOVATION / CHOL_BREAKDOWN) re-enter through a factorization
   that cannot go indefinite — parameters unchanged;
4. ``jitter`` covariance regularization in constrained space: the Ω_state
   Cholesky diagonal is inflated and the observation variance floored, then
   re-evaluated on the scan engine — parameters (slightly) changed, and the
   modified vector is carried back so downstream consumers see what was
   actually evaluated;
5. ``shrink`` the reference-parity ×0.95 raw shrink, up to 10 times.

Everything is deterministic (no RNG anywhere — "jitter" is a fixed
multiplicative inflation), so escalated runs replay bit-for-bit.  Arming is
env-gated: ``YFM_ESCALATE=1`` enables the ladder in
``estimation/optimize.estimate``/``estimate_steps``; the default ``0``
reproduces the historical drop-the-start behavior exactly.  The second-order
cascade (``second_order=``/``YFM_NEWTON``, docs/DESIGN.md §17) sits BEFORE
this ladder: a start the Newton polish could not move (dead at entry, or
every damped step rejected) keeps its −Inf/penalty sentinel and climbs these
same rungs — the polish raises the ``NONPSD_HESSIAN`` taxonomy bit so the
trace says the second-order phase saw broken curvature, not just "dead".  Per-start
outcomes (codes + rungs climbed) land in the multi-start report
(``optimize.last_multistart_report()``) and flow into the task boundary as
``orchestration.retry.SentinelFailure``'s decoded cause.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import NamedTuple, Optional, Tuple

import numpy as np

from ..config import register_engine_cache
from . import taxonomy as tax

#: eigenvalue floor for the sqrt rung's PSD projection (see ops/sqrt_kf.py)
SQRT_RESCUE_FLOOR = 1e-10
#: panel length at/above which the assoc rung runs: below it the sequential
#: sqrt rung is cheap and strictly more robust (per-step factorization);
#: above it the O(log T) stabilized tree is the rescue that answers while a
#: 10k-step sequential re-evaluation is still walking
ASSOC_RESCUE_MIN_T = 1024
#: multiplicative Ω-Cholesky-diagonal inflation + σ² floor for the jitter rung
JITTER_SCALE = 1.05
JITTER_ABS = 1e-6
OBS_VAR_FLOOR = 1e-8
#: reference parity: at most 10 ×0.95 shrinks (optimization.jl:173-184)
SHRINK_TRIES = 10

RUNGS = ("scan", "assoc", "slr", "score_tree", "sqrt", "jitter", "shrink")


def escalation_enabled() -> bool:
    """``YFM_ESCALATE=1`` arms the ladder (default off — today's behavior)."""
    return os.environ.get("YFM_ESCALATE", "0") not in ("0", "")


class RungResult(NamedTuple):
    rung: str     # which rung ran
    ll: float     # the loglik it produced (−inf = still dead)
    code: int     # taxonomy bitmask of that evaluation


class LadderTrace(NamedTuple):
    """One failed start's trip up the ladder — the multi-start report row."""

    start: int                        # index in the multi-start batch
    code: int                         # initial scan-engine diagnosis
    rungs: Tuple[RungResult, ...]     # every rung evaluated, in order
    recovered: bool
    rung: Optional[str]               # the rung that recovered it (or None)
    ll: float                         # recovered loglik (−inf if dead)
    engine: str                       # engine whose value ``ll`` is
    raw: Optional[np.ndarray]         # modified raw params (jitter/shrink
    #                                   rungs change the point; None = as-is)

    def as_dict(self) -> dict:
        """JSON-able report row with decoded code names."""
        return {
            "start": self.start,
            "code": self.code,
            "cause": tax.describe(self.code),
            "rungs": [{"rung": r.rung, "ll": r.ll, "code": r.code,
                       "cause": tax.describe(r.code)} for r in self.rungs],
            "recovered": self.recovered,
            "rung": self.rung,
            "ll": self.ll,
            "engine": self.engine,
        }


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_sqrt_rescue(spec, T: int):
    """The sqrt rung's jitted evaluator — standard trace-time builder idiom
    (`@register_engine_cache` + `@lru_cache`, CLAUDE.md)."""
    import jax

    from ..ops import sqrt_kf

    return jax.jit(lambda p, d, s, e: sqrt_kf.get_loss_coded(
        spec, p, d, s, e, init_psd_floor=SQRT_RESCUE_FLOOR))


def _sqrt_rescue(spec, cons, data, start, end):
    import jax.numpy as jnp

    runner = _jitted_sqrt_rescue(spec, int(data.shape[1]))
    ll, code = runner(cons, data, jnp.asarray(start), jnp.asarray(end))
    return float(ll), int(code)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_assoc_rescue(spec):
    """The assoc rung's jitted evaluator: the O(log T) associative-scan
    engine with PSD-projected composed moments (ops/assoc_scan, the same
    ``SQRT_RESCUE_FLOOR`` stabilization surface as the sqrt rung).  Keyed on
    spec alone — jit retraces per data shape, so a T key would only
    fragment the cache."""
    import jax

    from ..ops import assoc_scan

    return jax.jit(lambda p, d, s, e: assoc_scan.get_loss_coded(
        spec, p, d, s, e, psd_floor=SQRT_RESCUE_FLOOR))


def _assoc_rescue_applies(spec, T: int) -> bool:
    """Gate for the assoc rung: constant-measurement Kalman family (the
    associative form needs a constant Z) on a long panel."""
    return spec.has_constant_measurement and T >= ASSOC_RESCUE_MIN_T


def _assoc_rescue(spec, cons, data, start, end):
    import jax.numpy as jnp

    runner = _jitted_assoc_rescue(spec)
    ll, code = runner(cons, data, jnp.asarray(start), jnp.asarray(end))
    return float(ll), int(code)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_slr_rescue(spec):
    """The slr rung's jitted evaluator: the iterated-SLR engine
    (ops/slr_scan, docs/DESIGN.md §19) with PSD-projected moments — the
    assoc rung's twin for the Kalman families whose measurement is
    state-dependent.  Keyed on spec alone, like the assoc builder (jit
    retraces per data shape)."""
    import jax

    from ..ops import slr_scan

    return jax.jit(lambda p, d, s, e: slr_scan.get_loss_coded(
        spec, p, d, s, e, psd_floor=SQRT_RESCUE_FLOOR))


def _slr_rescue_applies(spec, T: int) -> bool:
    """Gate for the slr rung: a Kalman family WITHOUT a constant measurement
    (those take the assoc rung instead — config.engines_for keeps the two
    disjoint) on a long panel, same length gate as the assoc rung."""
    from .. import config

    return (spec.is_kalman and config.tree_engine_for(spec) == "slr"
            and T >= ASSOC_RESCUE_MIN_T)


def _slr_rescue(spec, cons, data, start, end):
    import jax.numpy as jnp

    runner = _jitted_slr_rescue(spec)
    ll, code = runner(cons, data, jnp.asarray(start), jnp.asarray(end))
    return float(ll), int(code)


@register_engine_cache
@lru_cache(maxsize=64)
def _jitted_score_rescue(spec):
    """The score_tree rung's jitted evaluator: the O(log T) score-tree
    engine (ops/score_scan, docs/DESIGN.md §19) for the capable
    score-driven specs — the assoc/slr rungs' twin on the MSED side.
    Keyed on spec alone, like the other tree builders (jit retraces per
    data shape)."""
    import jax

    from ..ops import score_scan

    return jax.jit(lambda p, d, s, e: score_scan.get_loss_coded(
        spec, p, d, s, e))


def _score_rescue_applies(spec, T: int) -> bool:
    """Gate for the score_tree rung: a score-driven spec the tree engine
    covers (``config.tree_engine_for`` — the same applicability seam as the
    T-switch and the time-sharded objective) on a long panel, same length
    gate as the assoc/slr rungs."""
    from .. import config

    return (config.tree_engine_for(spec) == "score_tree"
            and T >= ASSOC_RESCUE_MIN_T)


def _score_rescue(spec, cons, data, start, end):
    import jax.numpy as jnp

    runner = _jitted_score_rescue(spec)
    ll, code = runner(cons, data, jnp.asarray(start), jnp.asarray(end))
    return float(ll), int(code)


def _jittered_raw(spec, raw):
    """The jitter rung's regularized point: constrained-space Ω-Cholesky
    diagonal inflation + observation-variance floor, mapped back to raw."""
    import jax.numpy as jnp

    from ..models.params import transform_params, untransform_params

    cons = np.asarray(transform_params(
        spec, jnp.asarray(raw, dtype=jnp.float64)), dtype=np.float64).copy()
    a, _ = spec.layout["chol"]
    rows, cols = spec.chol_indices
    for k, (r, c) in enumerate(zip(rows, cols)):
        if r == c:
            cons[a + k] = cons[a + k] * JITTER_SCALE + JITTER_ABS
    ov = spec.layout["obs_var"][0]
    cons[ov] = max(cons[ov], OBS_VAR_FLOOR)
    return np.asarray(untransform_params(spec, jnp.asarray(cons)),
                      dtype=np.float64)


def escalate(spec, data, raw, start=0, end=None,
             start_index: int = 0) -> LadderTrace:
    """Climb the ladder for ONE dead start (unconstrained ``raw`` vector).

    Returns a :class:`LadderTrace`; on recovery ``ll`` is the first finite
    loglik found, ``engine`` names the engine that produced it (so a caller
    comparing starts knows a ``"sqrt"`` value came from the projected
    square-root surrogate), and ``raw`` carries the modified parameter point
    when a rung changed it (jitter/shrink) — ``None`` when the original
    point recovered as-is.
    """
    import jax.numpy as jnp

    from ..models.params import transform_params

    data = jnp.asarray(data, dtype=spec.dtype)
    T = int(data.shape[1])
    if end is None:
        end = T
    raw = np.asarray(raw, dtype=np.float64).reshape(-1)

    def cons_of(r):
        return jnp.asarray(np.asarray(
            transform_params(spec, jnp.asarray(r, dtype=jnp.float64)),
            dtype=np.float64), dtype=spec.dtype)

    rungs = []

    # rung 1 — scan re-eval + diagnosis (catches fused-kernel artifacts)
    ll, code0 = tax.diagnose(spec, cons_of(raw), data, start, end)
    rungs.append(RungResult("scan", ll, code0))
    if np.isfinite(ll):
        return LadderTrace(start_index, code0, tuple(rungs), True, "scan",
                           ll, "scan", None)

    # rung 2 — associative-scan engine with PSD-projected composed moments:
    # long constant-Z panels only, where re-walking the panel sequentially
    # is exactly the latency the O(log T) tree exists to avoid
    if _assoc_rescue_applies(spec, T):
        ll, code = _assoc_rescue(spec, cons_of(raw), data, start, end)
        rungs.append(RungResult("assoc", ll, code))
        if np.isfinite(ll):
            return LadderTrace(start_index, code0, tuple(rungs), True,
                               "assoc", ll, "assoc", None)

    # rung 2b — the nonlinear twin: iterated-SLR engine with PSD-projected
    # moments for the state-dependent-measurement Kalman families (TVλ) —
    # the same O(log T) answer-while-sequential-walks rescue, same gate
    if _slr_rescue_applies(spec, T):
        ll, code = _slr_rescue(spec, cons_of(raw), data, start, end)
        rungs.append(RungResult("slr", ll, code))
        if np.isfinite(ll):
            return LadderTrace(start_index, code0, tuple(rungs), True,
                               "slr", ll, "slr", None)

    # rung 2c — the score-driven twin: the O(log T) score-tree engine
    # (ops/score_scan) for the capable MSED specs, same length gate — the
    # tree's affine-surrogate + exact-refinement pass can come back finite
    # where the sequential artifact died, at tree depth
    if _score_rescue_applies(spec, T):
        ll, code = _score_rescue(spec, cons_of(raw), data, start, end)
        rungs.append(RungResult("score_tree", ll, code))
        if np.isfinite(ll):
            return LadderTrace(start_index, code0, tuple(rungs), True,
                               "score_tree", ll, "score_tree", None)

    # rung 3 — square-root filter from PSD-projected moments (Kalman only)
    if spec.is_kalman:
        ll, code = _sqrt_rescue(spec, cons_of(raw), data, start, end)
        rungs.append(RungResult("sqrt", ll, code))
        if np.isfinite(ll):
            return LadderTrace(start_index, code0, tuple(rungs), True,
                               "sqrt", ll, "sqrt", None)

    # rung 4 — jittered covariance regularization (Kalman only: the knobs
    # are the Ω Cholesky diagonal and σ²)
    if spec.is_kalman and "chol" in spec.layout:
        raw_j = _jittered_raw(spec, raw)
        ll, code = tax.diagnose(spec, cons_of(raw_j), data, start, end)
        rungs.append(RungResult("jitter", ll, code))
        if np.isfinite(ll):
            return LadderTrace(start_index, code0, tuple(rungs), True,
                               "jitter", ll, "scan", raw_j)

    # rung 5 — reference-parity ×0.95 shrink (optimization.jl:173-184)
    r = raw.copy()
    for _ in range(SHRINK_TRIES):
        r = r * 0.95
        ll, code = tax.diagnose(spec, cons_of(r), data, start, end)
        if np.isfinite(ll):
            rungs.append(RungResult("shrink", ll, code))
            return LadderTrace(start_index, code0, tuple(rungs), True,
                               "shrink", ll, "scan", r)
    rungs.append(RungResult("shrink", ll, code))
    return LadderTrace(start_index, code0, tuple(rungs), False, None,
                       float("-inf"), "scan", None)


def escalate_starts(spec, data, X, failed, start=0, end=None):
    """Ladder every failed row of an (S, P) raw multi-start batch.

    ``failed``: boolean (S,) mask.  Returns ``(traces, lls, X_new)`` —
    recovered rows get their ladder loglik in ``lls`` (np.nan elsewhere) and
    their possibly-modified raw vector written back into ``X_new``.
    """
    X = np.asarray(X, dtype=np.float64)
    traces, lls = [], np.full(X.shape[0], np.nan)
    X_new = X.copy()
    for j in np.flatnonzero(np.asarray(failed)):
        tr = escalate(spec, data, X[j], start, end, start_index=int(j))
        traces.append(tr)
        if tr.recovered:
            lls[j] = tr.ll
            if tr.raw is not None:
                X_new[j] = tr.raw
    return traces, lls, X_new
