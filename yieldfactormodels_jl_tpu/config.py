"""Global configuration.

The reference threads a ``float_type`` through every constructor
(/root/reference/src/YieldFactorModels.jl:227 ``float_type::Type=Float32``).
Here dtype lives on the :class:`~yieldfactormodels_jl_tpu.models.specs.ModelSpec`
and this module only provides the process-wide default (f32 — the TPU-native
precision; f64 is available for CPU oracle runs via ``jax_enable_x64``).
"""

from __future__ import annotations

import jax.numpy as jnp

_DEFAULT_DTYPE = jnp.float32


def default_dtype():
    return _DEFAULT_DTYPE


def set_default_dtype(dtype) -> None:
    global _DEFAULT_DTYPE
    _DEFAULT_DTYPE = jnp.dtype(dtype)
