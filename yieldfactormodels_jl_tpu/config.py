"""Global configuration.

The reference threads a ``float_type`` through every constructor
(/root/reference/src/YieldFactorModels.jl:227 ``float_type::Type=Float32``).
Here dtype lives on the :class:`~yieldfactormodels_jl_tpu.models.specs.ModelSpec`
and this module only provides the process-wide default (f32 — the TPU-native
precision; f64 is available for CPU oracle runs via ``jax_enable_x64``).
"""

from __future__ import annotations

import jax.numpy as jnp

_DEFAULT_DTYPE = jnp.float32

#: Kalman loglik engine used by ``api.get_loss``:
#:   "univariate"  sequential-observation scalar updates (production default)
#:   "sqrt"        Potter square-root form — PSD-by-construction in f32
#:   "joint"       textbook joint update with per-step Cholesky
#:   "assoc"       parallel-in-time associative scan (constant-Z families)
KALMAN_ENGINES = ("univariate", "sqrt", "joint", "assoc")
_KALMAN_ENGINE = "univariate"

# lru-cached builders of jitted losses register here (at import time) so an
# engine switch can invalidate every cache that traced api.get_loss — no
# hand-maintained list of distant private names
_ENGINE_CACHES: list = []


def register_engine_cache(fn):
    """Register an ``lru_cache``-wrapped builder whose traces read the engine
    choice; returns ``fn`` so it can be used as a decorator.  Must sit ABOVE
    ``@lru_cache`` (i.e. receive the cached wrapper) — anything else is a
    decorator-order mistake that would silently leave stale traces alive."""
    if not hasattr(fn, "cache_clear"):
        raise TypeError(
            "register_engine_cache must wrap an lru_cache-decorated function; "
            "put @register_engine_cache above @lru_cache")
    _ENGINE_CACHES.append(fn)
    return fn


def default_dtype():
    return _DEFAULT_DTYPE


def set_default_dtype(dtype) -> None:
    global _DEFAULT_DTYPE
    _DEFAULT_DTYPE = jnp.dtype(dtype)


def kalman_engine() -> str:
    return _KALMAN_ENGINE


def set_kalman_engine(name: str) -> None:
    """Select the Kalman loglik kernel (process-wide; per-call override via
    ``api.get_loss(..., engine=...)``).

    The choice is read at trace time, so the estimation layer's lru-cached
    jitted losses would otherwise keep running the engine they were traced
    with — those caches are cleared here so the next call re-traces."""
    global _KALMAN_ENGINE
    if name not in KALMAN_ENGINES:
        raise ValueError(f"unknown kalman engine {name!r}; pick from {KALMAN_ENGINES}")
    _KALMAN_ENGINE = name
    for fn in _ENGINE_CACHES:  # drop stale traced executables
        fn.cache_clear()
