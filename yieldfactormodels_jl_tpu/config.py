"""Global configuration.

The reference threads a ``float_type`` through every constructor
(/root/reference/src/YieldFactorModels.jl:227 ``float_type::Type=Float32``).
Here dtype lives on the :class:`~yieldfactormodels_jl_tpu.models.specs.ModelSpec`
and this module only provides the process-wide default (f32 — the TPU-native
precision; f64 is available for CPU oracle runs via ``jax_enable_x64``).
"""

from __future__ import annotations

import os

import jax.numpy as jnp

_DEFAULT_DTYPE = jnp.float32

#: Kalman loglik engine used by ``api.get_loss``:
#:   "univariate"  sequential-observation scalar updates (production default)
#:   "sqrt"        Potter square-root form — PSD-by-construction in f32
#:   "joint"       textbook joint update with per-step Cholesky
#:   "assoc"       parallel-in-time associative scan (constant-Z families)
#:   "slr"         iterated square-root SLR: posterior-linearized affine
#:                 surrogates on the same combine tree — the parallel-in-time
#:                 engine for the STATE-DEPENDENT measurement families
#:                 (TVλ EKF; ops/slr_scan.py, docs/DESIGN.md §19)
KALMAN_ENGINES = ("univariate", "sqrt", "joint", "assoc", "slr")
_KALMAN_ENGINE = "univariate"

#: SLR linearization rules used by the ``"slr"`` engine (ops/slr_scan.py):
#:   "ekf"  first-order Taylor (analytic EKF Jacobians) around the previous
#:          sweep's predicted-mean trajectory — the posterior-linearization
#:          rule whose fixed point is the sequential EKF
#:   "ukf"  sigma-point statistical linearization (arXiv:2207.00426's
#:          headline variant): the unscented cubature rule (2·Ms+1 points,
#:          all-positive weights, points on the trailing/lane axis) regressed
#:          into the same affine surrogate — fixed point is the sequential
#:          sigma-point (statistically linearized) filter, the better rule
#:          in curvature-heavy regimes where one Jacobian under-spans the
#:          posterior spread (docs/QUICKSTART.md has the chooser)
#: Every entry must have oracle-backed parity coverage — graftlint YFM007,
#: the same contract as KALMAN_ENGINES/NEWTON_ENGINES.
SLR_ENGINES = ("ekf", "ukf")

#: loss engines for the score-driven (MSED) families (models/score_driven.py
#: vs ops/score_scan.py, docs/DESIGN.md §19):
#:   "scan"        the sequential ``lax.scan`` recursion — reference parity,
#:                 the production default
#:   "score_tree"  the O(log T) parallel-in-time engine: per-step affine
#:                 surrogate of the score recursion composed on the combine
#:                 tree + K chunked TRUE-recursion refinement sweeps —
#:                 available where the spec's state is the plain gradient
#:                 recursion (``spec.supports_score_tree``; the EWMA
#:                 ``scale_grad`` lineage keeps the sequential scan)
#: Every entry must have oracle-backed parity coverage — graftlint YFM007,
#: the same contract as KALMAN_ENGINES.
MSED_ENGINES = ("scan", "score_tree")

#: second-order (Newton-polish) HVP engines used by ``ops/newton.py`` /
#: ``estimate(..., second_order=...)``:
#:   "fisher"  Gauss–Newton/Fisher curvature via the innovation tangent
#:             recursion (PSD, ≈3 filter passes/HVP — the cheap default)
#:   "exact"   true HVP as grad-of-directional-derivative through the scan
#: Every entry must have oracle-backed parity coverage — graftlint YFM007,
#: the same contract as KALMAN_ENGINES.
NEWTON_ENGINES = ("fisher", "exact")

#: amortized-estimation surrogate architectures (``estimation/amortize.py``,
#: docs/DESIGN.md §20):
#:   "deepset"  permutation/length-robust deep-set summary over the panel's
#:              time axis (masked mean/second-moment pooling of a shared
#:              per-step MLP on (yₜ, Δyₜ) pairs) + MLP/linear head onto the
#:              raw parameter vector in the steady-state target space
#: Every entry must have oracle-backed parity coverage — graftlint YFM007,
#: the same contract as KALMAN_ENGINES: the surrogate's forward/loss kernels
#: are pinned against independent NumPy loops in tests/oracle.py.
AMORTIZER_ENGINES = ("deepset",)


def engines_for(spec) -> tuple:
    """The loss-engine names valid for one model family — THE
    engine-applicability introspection seam (docs/DESIGN.md §19).

    ``api.get_loss`` validation, the ``YFM_LOGLIK_T_SWITCH`` long-panel
    dispatch, ``estimate(objective="time_sharded")`` and the serving
    ``refilter()`` gate all consult this one function instead of scattering
    per-family conditionals.  The engine matrix is TOTAL over the filtered
    families: Kalman families pick from ``KALMAN_ENGINES`` (the sequential
    engines cover every Kalman family; the parallel-in-time tree is
    ``"assoc"`` where the measurement is constant and ``"slr"`` — the
    iterated posterior-linearization superset — everywhere); the
    score-driven families pick from ``MSED_ENGINES`` (``"score_tree"``
    where the spec's capability flag ``supports_score_tree`` holds, the
    sequential ``"scan"`` always).  Only the static families — closed-form
    regressions with no state recursion to parallelize — take no engine
    choice and return ``()``.
    """
    if spec.is_kalman:
        if spec.has_constant_measurement:
            return KALMAN_ENGINES
        return tuple(e for e in KALMAN_ENGINES if e != "assoc")
    if getattr(spec, "is_msed", False):
        if spec.supports_score_tree:
            return MSED_ENGINES
        return tuple(e for e in MSED_ENGINES if e != "score_tree")
    return ()


def tree_engine_for(spec) -> str | None:
    """The O(log T) parallel-in-time engine for a family (``"assoc"`` for
    constant-Z Kalman, ``"slr"`` for state-dependent measurements,
    ``"score_tree"`` for the capable score-driven specs, ``None`` when the
    family has no tree engine) — what the ``YFM_LOGLIK_T_SWITCH`` policy
    upgrades long panels to (api.get_loss, the ladder's rescue rungs, the
    time-sharded objective and the serving re-filter all agree through
    this)."""
    valid = engines_for(spec)
    for name in ("assoc", "slr", "score_tree"):
        if name in valid:
            return name
    return None

# lru-cached builders of jitted losses register here (at import time) so an
# engine switch can invalidate every cache that traced api.get_loss — no
# hand-maintained list of distant private names
_ENGINE_CACHES: list = []


def register_engine_cache(fn):
    """Register an ``lru_cache``-wrapped builder whose traces read the engine
    choice; returns ``fn`` so it can be used as a decorator.  Must sit ABOVE
    ``@lru_cache`` (i.e. receive the cached wrapper) — anything else is a
    decorator-order mistake that would silently leave stale traces alive."""
    if not hasattr(fn, "cache_clear"):
        raise TypeError(
            "register_engine_cache must wrap an lru_cache-decorated function; "
            "put @register_engine_cache above @lru_cache")
    _ENGINE_CACHES.append(fn)
    return fn


def engine_cache_entries():
    """``(qualified_name, builder)`` pairs for every registered engine-cache
    builder, name = ``<module>.<qualname>`` with the package prefix stripped
    (``"estimation.optimize._jitted_loss"``).

    The introspection seam of the IR program auditor (``analysis/ir.py``,
    docs/DESIGN.md §18): tier 2 enumerates THIS list — after importing the
    package's modules — and audits each builder's lowered artifact at the
    shapes ``analysis/manifest.py`` declares, so coverage is defined by what
    actually registered at import time, never by a hand-maintained list.
    Names are stable across lru_cache wrapping (``functools.update_wrapper``
    preserves ``__module__``/``__qualname__``)."""
    prefix = __name__.rsplit(".", 1)[0] + "."
    out = []
    for fn in _ENGINE_CACHES:
        mod = getattr(fn, "__module__", "") or ""
        if mod.startswith(prefix):
            mod = mod[len(prefix):]
        qual = getattr(fn, "__qualname__", getattr(fn, "__name__", "?"))
        out.append((f"{mod}.{qual}", fn))
    return out


def make_trace_counter():
    """Per-module trace-counter triple ``(trace_counts, note_trace,
    reset_trace_counts)``: ``note_trace(kind)`` is called at the top of a
    to-be-jitted function body, so it runs once per (re)trace and the
    counter counts actual compilations — the no-recompile regression idiom
    shared by serving/online.py, parallel/mesh.py and
    estimation/scenario.py (one factory, per-module isolation)."""
    import collections

    counts: collections.Counter = collections.Counter()

    def note_trace(kind: str) -> None:
        counts[kind] += 1

    def reset_trace_counts() -> None:
        counts.clear()

    return counts, note_trace, reset_trace_counts


def default_dtype():
    return _DEFAULT_DTYPE


def set_default_dtype(dtype) -> None:
    global _DEFAULT_DTYPE
    _DEFAULT_DTYPE = jnp.dtype(dtype)


#: T-switch for the likelihood engine-dispatch policy (``api.get_loss``):
#: panels with T >= the threshold run the O(log T) associative-scan engine,
#: shorter ones keep the sequential production default.  ``None`` = not yet
#: resolved from the ``YFM_LOGLIK_T_SWITCH`` env knob; 0 = policy off.
_LOGLIK_T_SWITCH: int | None = None


def loglik_t_switch() -> int:
    """Panel length at/above which ``api.get_loss`` auto-dispatches a
    family to its O(log T) tree engine (:func:`tree_engine_for` — "assoc"
    for constant-Z Kalman, "slr" for TVλ, "score_tree" for the capable
    score-driven specs; 0 = off).

    Resolved lazily from ``YFM_LOGLIK_T_SWITCH`` so env-configured runs need
    no code; :func:`set_loglik_t_switch` overrides it process-wide.  Read at
    TRACE time inside the loglik kernels, so the setter must invalidate the
    registered engine caches — same contract as :func:`set_kalman_engine`.
    """
    global _LOGLIK_T_SWITCH
    if _LOGLIK_T_SWITCH is None:
        _LOGLIK_T_SWITCH = int(os.environ.get("YFM_LOGLIK_T_SWITCH", "0")
                               or 0)
    return _LOGLIK_T_SWITCH


def set_loglik_t_switch(T: int) -> None:
    """Set the engine-dispatch T-switch (0 disables the policy).

    Like :func:`set_kalman_engine`, the choice is read at trace time, so all
    registered lru-cached jitted-loss builders are cleared here — a stale
    trace would silently keep the engine the old threshold picked."""
    global _LOGLIK_T_SWITCH
    T = int(T)
    if T < 0:
        raise ValueError(f"loglik T-switch must be >= 0, got {T}")
    _LOGLIK_T_SWITCH = T
    for fn in _ENGINE_CACHES:  # drop stale traced executables
        fn.cache_clear()


def kalman_engine() -> str:
    return _KALMAN_ENGINE


def set_kalman_engine(name: str) -> None:
    """Select the Kalman loglik kernel (process-wide; per-call override via
    ``api.get_loss(..., engine=...)``).

    The choice is read at trace time, so the estimation layer's lru-cached
    jitted losses would otherwise keep running the engine they were traced
    with — those caches are cleared here so the next call re-traces."""
    global _KALMAN_ENGINE
    if name not in KALMAN_ENGINES:
        raise ValueError(f"unknown kalman engine {name!r}; pick from {KALMAN_ENGINES}")
    _KALMAN_ENGINE = name
    for fn in _ENGINE_CACHES:  # drop stale traced executables
        fn.cache_clear()
