"""IR-audit shape manifest: representative shapes per engine-cache builder.

Tier 2 of graftlint (``analysis/ir.py``, docs/DESIGN.md §18) audits the
*compiled artifacts* of every ``@register_engine_cache`` builder — donation
actually honored, dtype discipline, host round-trips, the lane rule, retrace
census.  This module is the declarative half: one :func:`case` per builder
saying HOW to build the jitted program and WHAT abstract shapes to lower it
at.  Coverage is a closed loop, not a convention:

- AST rule YFM011 (``rules.py``) statically requires a ``case``/``skip_case``
  registration here for every builder in the package, so tier-2 coverage
  grows with the code;
- the runtime census in ``ir.py`` cross-checks this manifest against
  ``config.engine_cache_entries()`` after importing the package, catching
  stale keys and builders the AST pass could not see.

A ``case``'s ``make()`` returns ``(jitted_program, [arg_tuple, ...])``; args
may be ``jax.ShapeDtypeStruct`` avals or small concrete arrays (PRNG keys,
host-staged buffers) — nothing is ever *executed*, only lowered.  Multiple
arg tuples audit staging parity: all of them must collapse to
``max_programs`` distinct lowerings (the PR-8 warmup-staging-mismatch bug
class).  ``donated=`` declares how many input buffers must come out ALIASED
in the lowered artifact — the check source-level YFM002 cannot make.
``skip_case`` keeps a builder on the coverage books without lowering it
(Pallas-fused programs lower only for the TPU backend; their on-chip checks
live in ``benchmarks/hw_verify.py``).

Deliberately jax-free at import (like the whole analysis package): every
helper imports jax inside the call, so the AST tier and the CLI stay
importable in ~100 ms.  Shapes are intentionally SMALL — lowering cost is
roughly shape-independent, and nothing here compiles or runs — except where
the lane-rule heuristic needs a visibly big batch axis (the batcher bucket,
the sharded store, the fused grid plane).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

#: builder key ("estimation.optimize._jitted_loss") → registered cases
MANIFEST: Dict[str, List["Case"]] = {}

#: the one reason string for every Pallas-fused skip (uniform, greppable)
PALLAS_SKIP = ("Pallas-fused program — lowers only for the TPU backend; "
               "on-chip verification lives in benchmarks/hw_verify.py")


@dataclasses.dataclass
class Case:
    """One auditable configuration of one builder."""

    builder: str                     # package-relative dotted builder name
    label: str                       # distinguishes cases of one builder
    make: Optional[Callable]         # () -> (jitted, [args, ...]); None=skip
    donated: int = 0                 # input buffers that MUST lower aliased
    max_programs: int = 1            # distinct lowerings allowed across args
    skip: Optional[str] = None       # reason: covered but not lowered


def case(builder: str, label: str = "default", donated: int = 0,
         max_programs: int = 1):
    """Register a lowering case for ``builder`` (decorator)."""
    def wrap(fn):
        MANIFEST.setdefault(builder, []).append(
            Case(builder, label, fn, donated, max_programs))
        return fn
    return wrap


def skip_case(builder: str, reason: str) -> None:
    """Register a coverage-only entry: the builder is on the books (YFM011
    and the runtime census count it) but its program is not lowered here."""
    MANIFEST.setdefault(builder, []).append(
        Case(builder, "skip", None, skip=reason))


# ---------------------------------------------------------------------------
# shared shapes + helpers (jax imported lazily inside each)
# ---------------------------------------------------------------------------

MATS = (3.0, 6.0, 12.0, 36.0, 60.0, 120.0)
N = len(MATS)      # maturities per curve
T = 16             # panel length (kept divisible by the 2-device meshes)
S = 4              # multi-start batch
W = 2              # rolling windows
R = 4              # bootstrap resamples (lattice faces)
G = 3              # λ-grid points
D = 2              # SV draws
NP = 8             # particles (audit-sized)
H = 3              # forecast horizon
CAP = 128          # store shard capacity (slot axis — lane-rule visible)
BUCKET = 8         # store update bucket


def sds(shape, dtype="float64"):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def f64(*shape):
    return sds(shape, "float64")


def i32(*shape):
    return sds(shape, "int32")


def i64(*shape):
    return sds(shape, "int64")


def boolean(*shape):
    return sds(shape, "bool")


def key0():
    import jax

    return jax.random.PRNGKey(0)


def keys(n: int):
    import jax
    import jax.numpy as jnp

    return jnp.asarray(jax.random.split(jax.random.PRNGKey(0), n),
                       dtype=jnp.uint32)


def spec(family: str = "kalman_dns", **kw):
    from ..models.specs import ModelSpec

    return ModelSpec(family=family, model_code=f"ir-{family}",
                     maturities=MATS, dtype_name="float64", **kw)


def npar(family: str = "kalman_dns", **kw) -> int:
    return spec(family, **kw).n_params


def mesh2(axis: str = "batch"):
    from ..parallel.mesh import make_mesh

    return make_mesh(2, axis_name=axis)


def shocks2():
    from ..estimation.scenario import ShockSpec

    return (ShockSpec("baseline"),
            ShockSpec("parallel_up", (0.5, 0.0, 0.0)))


#: L-BFGS/Newton audit budgets: tiny — tracing cost is iteration-independent
#: (lax.while_loop), tolerances only feed carried constants
ITERS, GT, FA = 2, 1e-4, 1e-6


# ---------------------------------------------------------------------------
# estimation.optimize
# ---------------------------------------------------------------------------

@case("estimation.optimize._jitted_loss")
def _m_loss():
    from ..estimation.optimize import _jitted_loss

    P = npar()
    return _jitted_loss(spec(), T), [(f64(P), f64(N, T), i64(), i64())]


skip_case("estimation.optimize._jitted_ssd_batch_loss", PALLAS_SKIP)
skip_case("estimation.optimize._jitted_fused_multistart", PALLAS_SKIP)
skip_case("estimation.optimize._jitted_fused_windows", PALLAS_SKIP)
skip_case("estimation.optimize._jitted_group_opt_ssd", PALLAS_SKIP)


@case("estimation.optimize._jitted_batch_loss")
def _m_batch_loss():
    from ..estimation.optimize import _jitted_batch_loss

    P = npar()
    return _jitted_batch_loss(spec(), T), [(f64(S, P), f64(N, T),
                                            i64(), i64())]


@case("estimation.optimize._jitted_newton_polish")
def _m_newton_polish():
    from ..estimation.optimize import _jitted_newton_polish

    P = npar()
    fn = _jitted_newton_polish(spec(), T, ITERS, GT, FA, "fisher")
    return fn, [(f64(S, P), f64(N, T), i64(), i64())]


@case("estimation.optimize._jitted_window_newton_polish")
def _m_window_newton_polish():
    from ..estimation.optimize import _jitted_window_newton_polish

    P = npar()
    fn = _jitted_window_newton_polish(spec(), T, ITERS, GT, FA, "fisher")
    return fn, [(f64(W, S, P), f64(N, T), i64(W), i64(W))]


@case("estimation.optimize._jitted_multistart_lbfgs")
def _m_multistart_lbfgs():
    from ..estimation.optimize import _jitted_multistart_lbfgs

    P = npar()
    fn = _jitted_multistart_lbfgs(spec(), T, ITERS, GT, FA)
    return fn, [(f64(S, P), f64(N, T), i64(), i64())]


@case("estimation.optimize._jitted_group_opt_batched")
def _m_group_opt_batched():
    from ..estimation.optimize import _jitted_group_opt_batched

    sp = spec("msed_lambda", duplicator=(0,))
    opts = (("max_iters", ITERS), ("g_tol", GT), ("f_abstol", FA))
    fn = _jitted_group_opt_batched(sp, T, (0, 1, 2), "lbfgs", opts)
    return fn, [(f64(S, sp.n_params), f64(N, T), i64(), i64())]


@case("estimation.optimize._jitted_group_opt_msed_closed")
def _m_group_opt_msed_closed():
    from ..estimation.optimize import _jitted_group_opt_msed_closed

    sp = spec("msed_lambda", duplicator=(0,))
    fn = _jitted_group_opt_msed_closed(sp, T)
    return fn, [(f64(S, sp.n_params), f64(N, T), i64(), i64())]


@case("estimation.optimize._jitted_window_multistart")
def _m_window_multistart():
    from ..estimation.optimize import _jitted_window_multistart

    P = npar()
    fn = _jitted_window_multistart(spec(), T, ITERS, GT, FA)
    return fn, [(f64(S, P), f64(N, T), i64(W), i64(W))]


# ---------------------------------------------------------------------------
# estimation.amortize — the amortized-estimation surrogate (DESIGN §20)
# ---------------------------------------------------------------------------

AB = 8  # amortizer lane batch (audit-sized)


def _amortizer_cfg_params():
    """(cfg, spec, concrete init params) for the amortizer cases — the
    params pytree is tiny and init is pure, so concrete arrays keep the
    case simple (the manifest contract allows small concrete inputs)."""
    import jax

    from ..estimation.amortize import AmortizerConfig, init_params

    sp = spec()
    cfg = AmortizerConfig()
    return cfg, sp, init_params(cfg, sp, jax.random.PRNGKey(0))


@case("estimation.amortize._jitted_sim_batch", label="donated", donated=1)
def _m_amort_sim():
    from ..estimation.amortize import _jitted_sim_batch

    sp = spec()
    fn = _jitted_sim_batch(sp, T, AB, True)
    # run(raw (P, B), keys); donated: raw → the "raw" pass-through output
    return fn, [(f64(sp.n_params, AB), keys(AB))]


@case("estimation.amortize._jitted_forward")
def _m_amort_forward():
    from ..estimation.amortize import _jitted_forward

    cfg, sp, params = _amortizer_cfg_params()
    fn = _jitted_forward(cfg, sp, T, AB)
    return fn, [(params, f64(N, T, AB))]


@case("estimation.amortize._jitted_train_step", label="donated", donated=2)
def _m_amort_train_step():
    import jax
    import optax

    from ..estimation.amortize import _jitted_train_step

    cfg, sp, params = _amortizer_cfg_params()
    opt_state = optax.adam(1e-3).init(params)
    fn = _jitted_train_step(cfg, sp, T, AB, 1e-3)
    # donated: params + opt_state pytrees (consumed, returned updated) —
    # declared as 2 buffers minimum; the aliasing check is a ≥ bound
    avals = jax.tree_util.tree_map(
        lambda a: sds(a.shape, str(a.dtype)), (params, opt_state))
    return fn, [(avals[0], avals[1], f64(N, T, AB),
                 f64(sp.n_params, AB))]


# ---------------------------------------------------------------------------
# estimation.sv / estimation.bootstrap / estimation.inference
# ---------------------------------------------------------------------------

skip_case("estimation.sv._jitted_sv_search_pallas", PALLAS_SKIP)


@case("estimation.sv._jitted_draw_logliks")
def _m_draw_logliks():
    from ..estimation.sv import _jitted_draw_logliks

    P = npar()
    fn = _jitted_draw_logliks(spec(), T, NP, 0.95, 0.2)
    return fn, [(f64(D, P), f64(N, T), key0())]


@case("estimation.sv._jitted_sv_search")
def _m_sv_search():
    from ..estimation.sv import _jitted_sv_search

    P = npar()
    fn = _jitted_sv_search(spec(), T, NP, 0.95, 0.2, ITERS, 1e-6)
    return fn, [(f64(2, P), f64(N, T), key0())]


@case("estimation.sv._jitted_sv_search_full")
def _m_sv_search_full():
    from ..estimation.sv import _jitted_sv_search_full

    P = npar()
    fn = _jitted_sv_search_full(spec(), T, NP, ITERS, 1e-6)
    return fn, [(f64(2, P + 2), f64(N, T), key0())]


@case("estimation.bootstrap._jitted_grid_loss")
def _m_grid_loss():
    from ..estimation.bootstrap import _jitted_grid_loss

    sp = spec("static_lambda")
    fn = _jitted_grid_loss(sp, T)
    return fn, [(f64(G), i32(R, T), f64(sp.n_params), f64(N, T))]


@case("estimation.bootstrap._jitted_grid_loss_fused")
def _m_grid_loss_fused():
    from ..estimation.bootstrap import _jitted_grid_loss_fused

    sp = spec("static_lambda")
    fn = _jitted_grid_loss_fused(sp, T)
    # R is the lane axis of the fused MXU formulation: audit it big enough
    # (≥ the lane-rule threshold) that a transposed re-formulation would trip
    # YFM104, not slip under the size gate
    Rbig = 600
    return fn, [(f64(G), i32(Rbig, T), f64(sp.n_params), f64(N, T))]


@case("estimation.inference._jitted_information")
def _m_information():
    from ..estimation.inference import _jitted_information

    P = npar()
    return _jitted_information(spec(), T), [(f64(P), f64(N, T),
                                             i64(), i64())]


@case("estimation.inference._jitted_score_contributions")
def _m_score_contributions():
    from ..estimation.inference import _jitted_score_contributions

    P = npar()
    fn = _jitted_score_contributions(spec(), T, "univariate")
    return fn, [(f64(P), f64(N, T), i64(), i64())]


# ---------------------------------------------------------------------------
# estimation.scenario — the flagship donated lattice
# ---------------------------------------------------------------------------

@case("estimation.scenario._jitted_lattice", label="donated-full", donated=3)
def _m_lattice():
    from ..estimation.scenario import _jitted_lattice

    st, ka = spec("static_lambda"), spec()
    fn = _jitted_lattice(st, ka, T, R, G, D, shocks2(), H, 2, NP, 0.95, 0.2,
                         4, "fused", False, "univariate", True, True)
    # run(key, idx, gammas, static_params, kalman_params, data, sv_draws,
    #     acc); donated: idx → resample_idx, sv_draws → sv_draws, acc → losses
    return fn, [(key0(), i32(R, T), f64(G), f64(st.n_params),
                 f64(ka.n_params), f64(N, T), f64(D, ka.n_params),
                 f64(R, G))]


@case("estimation.scenario._jitted_fan")
def _m_fan():
    from ..estimation.scenario import _jitted_fan

    sp = spec()
    fn = _jitted_fan(sp, shocks2(), H, 2)
    Ms = sp.state_dim
    return fn, [(f64(sp.n_params), f64(Ms), f64(Ms, Ms), key0())]


@case("estimation.scenario._jitted_refit_column")
def _m_refit_column():
    from ..estimation.scenario import _jitted_refit_column

    P = npar()
    fn = _jitted_refit_column(spec(), T, ITERS, GT, FA)
    return fn, [(f64(2, P), f64(R, N, T))]


@case("estimation.scenario._jitted_refit_column_warm")
def _m_refit_column_warm():
    from ..estimation.scenario import _jitted_refit_column_warm

    P = npar()
    fn = _jitted_refit_column_warm(spec(), T, ITERS, GT, FA)
    # per-resample start matrices: X0 is (R, S, P) — the amortized warm path
    return fn, [(f64(R, 2, P), f64(R, N, T))]


@case("estimation.scenario._jitted_refit_polish")
def _m_refit_polish():
    from ..estimation.scenario import _jitted_refit_polish

    P = npar()
    fn = _jitted_refit_polish(spec(), T, ITERS, GT, FA, "fisher")
    return fn, [(f64(R, 2, P), f64(R, N, T))]


# ---------------------------------------------------------------------------
# forecasting / serving
# ---------------------------------------------------------------------------

@case("forecasting._jitted_predict_windows")
def _m_predict_windows():
    from ..forecasting import _jitted_predict_windows

    P = npar()
    T_ext = T + H - 1
    fn = _jitted_predict_windows(spec(), T_ext)
    return fn, [(f64(W, P), i64(W), i64(W), f64(N, T_ext))]


@case("serving.batcher._jitted_forecast_bucket")
def _m_forecast_bucket():
    from ..serving.batcher import _jitted_forecast_bucket

    sp = spec()
    B = 1024  # the lane-rule flagship: batch axis LAST at visible size
    fn = _jitted_forecast_bucket(sp, H, B)
    Ms = sp.state_dim
    return fn, [(f64(sp.n_params, B), f64(Ms, B), f64(Ms, Ms, B))]


@case("serving.online._jitted_update", label="donated", donated=2)
def _m_update_donated():
    from ..serving.online import _jitted_update

    sp = spec()
    Ms = sp.state_dim
    fn = _jitted_update(sp, "univariate", True)
    return fn, [(f64(sp.n_params), f64(Ms), f64(Ms, Ms), f64(N))]


@case("serving.online._jitted_update", label="sqrt-donated", donated=2)
def _m_update_sqrt():
    from ..serving.online import _jitted_update

    sp = spec()
    Ms = sp.state_dim
    fn = _jitted_update(sp, "sqrt", True)
    return fn, [(f64(sp.n_params), f64(Ms), f64(Ms, Ms), f64(N))]


@case("serving.online._jitted_update_k", label="donated", donated=2)
def _m_update_k():
    from ..serving.online import _jitted_update_k

    sp = spec()
    Ms = sp.state_dim
    kb = 4
    fn = _jitted_update_k(sp, "univariate", kb, True)
    return fn, [(f64(sp.n_params), f64(Ms), f64(Ms, Ms), f64(N, kb),
                 boolean(kb))]


@case("serving.streams._jitted_fan_refresh", donated=2, max_programs=1)
def _m_fan_refresh():
    from ..serving.streams import _jitted_fan_refresh, refresh_signature

    sp = spec()
    C = 8  # subscription lanes (batch-last, like the store slot axis)
    fn = _jitted_fan_refresh(sp, shocks2(), H, C)
    sig = refresh_signature(sp, len(shocks2()), H, C)
    order = ("params", "beta", "P", "active", "means", "covs", "codes",
             "refreshed")
    args = tuple(sds(*sig[k]) for k in order)
    # the same signature-derived avals TWICE with max_programs=1: the
    # YFM105 retrace pin — the hub's buffers and this manifest share ONE
    # shape recipe (refresh_signature), so a staging drift lowers as a
    # second program here instead of a silent live retrace
    return fn, [args, args]


@case("serving.streams._jitted_fan_refresh", label="shared", donated=2,
      max_programs=1)
def _m_fan_refresh_shared():
    # the service-mode variant: one live posterior, unbatched params/beta/P,
    # lane broadcast in-kernel — same donation table and retrace pin
    from ..serving.streams import _jitted_fan_refresh, refresh_signature

    sp = spec()
    C = 8
    fn = _jitted_fan_refresh(sp, shocks2(), H, C, shared=True)
    sig = refresh_signature(sp, len(shocks2()), H, C, shared=True)
    order = ("params", "beta", "P", "active", "means", "covs", "codes",
             "refreshed")
    args = tuple(sds(*sig[k]) for k in order)
    return fn, [args, args]


def _shard_update_args(warmup: bool):
    """The store's two staging paths for the SAME program: hot path
    (``_launch_chunk``) and warm-up (``warmup``) — bit-identical avals or
    the compile matrix silently doubles (the PR-8 staging-mismatch bug).
    The request arrays come from the REAL shared staging helper
    (``serving.store.stage_request_arrays``, the recipe both production
    paths call), with the hot variant filled the way ``_launch_chunk``
    fills it — so a dtype/shape drift in the actual staging code shows up
    here as a second lowering, not just in a hand-maintained copy."""
    from ..serving.store import stage_request_arrays

    sp = spec()
    Ms = sp.state_dim
    Y, slots, valid = stage_request_arrays(sp, BUCKET)
    if not warmup:
        # one live request, as _launch_chunk stages it (concrete values
        # never change the aval — the variants must still lower identically)
        Y[:, 0] = 0.04
        slots[0], valid[0] = 1, True
    return (f64(sp.n_params, CAP), f64(Ms, CAP), f64(Ms, Ms, CAP),
            i32(CAP), Y, slots, valid)


@case("serving.online._jitted_shard_update", label="donated", donated=4)
def _m_shard_update():
    from ..serving.online import _jitted_shard_update

    fn = _jitted_shard_update(spec(), "univariate", CAP, BUCKET, True)
    return fn, [_shard_update_args(warmup=False),
                _shard_update_args(warmup=True)]


@case("serving.online._jitted_slot_write", label="donated", donated=4)
def _m_slot_write():
    from ..serving.online import _jitted_slot_write

    sp = spec()
    Ms = sp.state_dim
    fn = _jitted_slot_write(sp, CAP, True)
    return fn, [(f64(sp.n_params, CAP), f64(Ms, CAP), f64(Ms, Ms, CAP),
                 i32(CAP), i32(), f64(sp.n_params), f64(Ms), f64(Ms, Ms),
                 i32())]


def _slot_write_many_args(warmup: bool):
    """The batched slot-write program's two staging paths (tier
    promotion/demotion waves vs ``TieredStateStore.warmup`` — DESIGN §21):
    both build their buffers with the REAL shared recipe
    (``serving.store.stage_slot_write_arrays``), the live variant filled the
    way ``_write_state_many`` fills it — aval-identical under
    ``max_programs=1`` or a first live promotion wave would pay a compile on
    the hot path (the PR-8 staging-mismatch bug class)."""
    from ..serving.store import stage_slot_write_arrays

    sp = spec()
    Ms = sp.state_dim
    slots, valid, p, b, c, v = stage_slot_write_arrays(sp, BUCKET)
    if not warmup:
        # one live promotion entry, as _write_state_many stages it
        slots[0], valid[0] = 1, True
        p[:, 0] = 0.1
        b[:, 0] = 0.05
        v[0] = 3
    return (f64(sp.n_params, CAP), f64(Ms, CAP), f64(Ms, Ms, CAP),
            i32(CAP), slots, valid, p, b, c, v)


@case("serving.online._jitted_slot_write_many", label="donated", donated=4)
def _m_slot_write_many():
    from ..serving.online import _jitted_slot_write_many

    fn = _jitted_slot_write_many(spec(), CAP, BUCKET, True)
    return fn, [_slot_write_many_args(warmup=False),
                _slot_write_many_args(warmup=True)]


@case("serving.online._jitted_refilter")
def _m_refilter():
    from ..serving.online import _jitted_refilter

    sp = spec()
    return _jitted_refilter(sp, T), [(f64(sp.n_params), f64(N, T))]


@case("serving.online._jitted_refilter", label="tvl-slr")
def _m_refilter_tvl():
    # the nonlinear-family dispatch: TVλ snapshots rebuild on the
    # iterated-SLR engine (ops/slr_scan, docs/DESIGN.md §19)
    from ..serving.online import _jitted_refilter

    sp = spec("kalman_tvl")
    return _jitted_refilter(sp, T), [(f64(sp.n_params), f64(N, T))]


@case("serving.online._jitted_scenarios")
def _m_scenarios():
    from ..serving.online import _jitted_scenarios

    sp = spec()
    Ms = sp.state_dim
    n = 4
    fn = _jitted_scenarios(sp, H, n)
    return fn, [(f64(sp.n_params), f64(Ms), f64(Ms, Ms), keys(n))]


@case("serving.snapshot._jitted_freeze_batch")
def _m_freeze_batch():
    from ..serving.snapshot import _jitted_freeze_batch

    sp = spec()
    B = 4
    fn = _jitted_freeze_batch(sp, T, "univariate", B)
    return fn, [(f64(B, sp.n_params), f64(N, T), i64(B))]


# ---------------------------------------------------------------------------
# robustness
# ---------------------------------------------------------------------------

@case("robustness.ladder._jitted_sqrt_rescue")
def _m_sqrt_rescue():
    from ..robustness.ladder import _jitted_sqrt_rescue

    P = npar()
    return _jitted_sqrt_rescue(spec(), T), [(f64(P), f64(N, T),
                                             i64(), i64())]


@case("robustness.ladder._jitted_assoc_rescue")
def _m_assoc_rescue():
    from ..robustness.ladder import _jitted_assoc_rescue

    P = npar()
    return _jitted_assoc_rescue(spec()), [(f64(P), f64(N, T),
                                           i64(), i64())]


@case("robustness.ladder._jitted_slr_rescue")
def _m_slr_rescue():
    # the assoc rung's nonlinear twin (TVλ — iterated-SLR engine with
    # PSD-projected moments, docs/DESIGN.md §19)
    from ..robustness.ladder import _jitted_slr_rescue

    sp = spec("kalman_tvl")
    return _jitted_slr_rescue(sp), [(f64(sp.n_params), f64(N, T),
                                     i64(), i64())]


@case("robustness.ladder._jitted_score_rescue")
def _m_score_rescue():
    # the assoc/slr rungs' score-driven twin (the O(log T) score-tree
    # engine, ops/score_scan, docs/DESIGN.md §19)
    from ..robustness.ladder import _jitted_score_rescue

    sp = spec("msed_lambda", duplicator=(0,))
    return _jitted_score_rescue(sp), [(f64(sp.n_params), f64(N, T),
                                       i64(), i64())]


@case("robustness.taxonomy._jitted_diagnose")
def _m_diagnose():
    from ..robustness.taxonomy import _jitted_diagnose

    P = npar()
    return _jitted_diagnose(spec(), T), [(f64(P), f64(N, T), i64(), i64())]


# ---------------------------------------------------------------------------
# parallel — mesh-sharded programs (2-device meshes; the audit env exposes 8
# virtual CPU devices, conftest-style)
# ---------------------------------------------------------------------------

@case("parallel.mesh._sharded_batch_loss", label="donated", donated=1)
def _m_sharded_batch_loss():
    from ..parallel.mesh import _sharded_batch_loss

    P = npar()
    fn = _sharded_batch_loss(spec(), T, mesh2(), "batch")
    return fn, [(f64(8, P), f64(N, T), i64(), i64())]


@case("parallel.mesh._sharded_multistart", label="donated", donated=1)
def _m_sharded_multistart():
    from ..parallel.mesh import _sharded_multistart

    P = npar()
    fn = _sharded_multistart(spec(), T, mesh2(), "batch", ITERS, GT, FA)
    return fn, [(f64(8, P), f64(N, T), i64(), i64())]


@case("parallel.mesh._sharded_pf")
def _m_sharded_pf():
    from ..parallel.mesh import _sharded_pf

    P = npar()
    fn = _sharded_pf(spec(), T, mesh2(), "batch", NP, 0.95, 0.2)
    return fn, [(f64(4, P), keys(4), f64(N, T))]


@case("parallel.time_parallel._jitted_time_sharded_loss")
def _m_time_sharded_loss():
    from ..parallel.time_parallel import _jitted_time_sharded_loss

    P = npar()
    fn = _jitted_time_sharded_loss(spec(), T, mesh2("time"), "time")
    return fn, [(f64(P), f64(N, T), i64(), i64())]


@case("parallel.time_parallel._jitted_time_sharded_loss", label="tvl-slr")
def _m_time_sharded_loss_tvl():
    # the nonlinear-family dispatch: iterated SLR with the refinement chunk
    # pinned to the shard length (docs/DESIGN.md §19)
    from ..parallel.time_parallel import _jitted_time_sharded_loss

    sp = spec("kalman_tvl")
    fn = _jitted_time_sharded_loss(sp, T, mesh2("time"), "time")
    return fn, [(f64(sp.n_params), f64(N, T), i64(), i64())]


@case("parallel.time_parallel._jitted_time_sharded_loss",
      label="msed-score-tree")
def _m_time_sharded_loss_msed():
    # the score-driven dispatch: the score-tree engine with the refinement
    # chunk pinned to the shard length (docs/DESIGN.md §19).  TWO
    # aval-identical stagings under max_programs=1 (the YFM105 retrace
    # census): a repeat call at the same avals must hit the one compiled
    # program, not trace a sibling (the PR-8 staging-mismatch bug class).
    from ..parallel.time_parallel import _jitted_time_sharded_loss

    sp = spec("msed_lambda", duplicator=(0,))
    fn = _jitted_time_sharded_loss(sp, T, mesh2("time"), "time")
    args = (f64(sp.n_params), f64(N, T), i64(), i64())
    return fn, [args, args]


@case("parallel.time_parallel._jitted_time_sharded_multistart")
def _m_time_sharded_multistart():
    from ..parallel.time_parallel import _jitted_time_sharded_multistart

    P = npar()
    fn = _jitted_time_sharded_multistart(spec(), T, mesh2("time"), "time",
                                         ITERS, GT, FA)
    return fn, [(f64(2, P), f64(N, T), i64(), i64())]
