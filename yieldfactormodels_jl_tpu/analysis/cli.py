"""graftlint CLI: ``python -m yieldfactormodels_jl_tpu.analysis``.

Exit codes: 0 = no unsuppressed/unbaselined findings, 1 = findings,
2 = usage/parse errors.  ``--format json`` emits the machine schema
(``version``/``counts``/``findings``/``suppressed``/``baselined``);
``--changed-only`` restricts the file set to the git worktree diff
(plus staged and untracked files) — the fast pre-commit mode.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import baseline as _baseline
from .engine import LintConfig, RULES, changed_files, run_lint


def _format_text(result, verbose: bool) -> str:
    lines = []
    for f in result.findings:
        lines.append(f"{f.file}:{f.line}: {f.rule} {f.message}")
    if verbose:
        for f in result.suppressed:
            reason = f.suppress_reason or "(no reason recorded)"
            lines.append(f"{f.file}:{f.line}: {f.rule} suppressed by pragma "
                         f"— {reason}")
        for f in result.baselined:
            lines.append(f"{f.file}:{f.line}: {f.rule} baselined")
    lines.append(
        f"graftlint: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined, "
        f"{result.files_scanned} files scanned")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m yieldfactormodels_jl_tpu.analysis",
        description="graftlint: rule-based AST static analysis for the "
                    "repo's jit/TPU invariants (docs/DESIGN.md §15)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only files changed vs git HEAD "
                             "(worktree + staged + untracked)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetected from the "
                             "installed package location)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: "
                             "<root>/.yfmlint-baseline.json)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather the current unsuppressed findings "
                             "into the baseline and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="also print suppressed/baselined findings")
    args = parser.parse_args(argv)

    config = LintConfig(root=args.root) if args.root else LintConfig()

    if args.list_rules:
        from . import rules as _rules  # noqa: F401  (registers RULES)
        for r in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{r.id}  {r.name}: {r.summary}")
        return 0

    rule_ids = None
    if args.rules:
        from . import rules as _rules  # noqa: F401
        rule_ids = [s.strip() for s in args.rules.split(",") if s.strip()]
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            print(f"unknown rule id(s): {unknown}", file=sys.stderr)
            return 2

    files = None
    if args.changed_only:
        changed = changed_files(config.root)
        if changed is None:
            print("--changed-only: git diff failed (no git / not a repo / "
                  "timeout) — refusing to lint an empty set", file=sys.stderr)
            return 2
        lintable = set(config.lint_files())
        files = [f for f in changed if f in lintable]

    baseline_path = args.baseline or config.abspath(config.baseline_path)
    try:
        baseline = _baseline.load_baseline(baseline_path)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"bad baseline: {e}", file=sys.stderr)
        return 2

    result = run_lint(config, files=files, rules=rule_ids, baseline=baseline)

    if args.write_baseline:
        n = _baseline.save_baseline(baseline_path, result.findings)
        print(f"graftlint: wrote {n} baseline entrie(s) to {baseline_path}")
        return 0

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=1))
    else:
        print(_format_text(result, args.verbose))
    if result.errors:
        return 2
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
