"""graftlint CLI: ``python -m yieldfactormodels_jl_tpu.analysis``.

Two tiers behind one entry point:

- default: the jax-free AST tier (rules YFM001–YFM011, ~1 s);
- ``--ir``: the IR tier (``ir.py``, docs/DESIGN.md §18) — imports jax,
  lowers every engine-cache builder at the manifest shapes and audits the
  compiled artifacts (rules YFM100–YFM105 + the runtime YFM011 census).

Exit codes: 0 = no unsuppressed/unbaselined findings, 1 = findings,
2 = usage/parse errors.  ``--format json`` emits the machine schema
(``version``/``counts``/``findings``/``suppressed``/``baselined``, plus
``tier``/``records`` under ``--ir`` and ``stale_baseline`` whenever the
committed baseline carries dead entries); ``--format sarif`` emits SARIF
2.1.0 for editor/CI annotation (suppressed and baselined findings carry
``suppressions`` so only actionable results annotate).  ``--changed-only``
restricts the AST tier's file set to the git worktree diff — worktree +
staged + untracked, so pre-commit runs see brand-new modules — and is
refused under ``--ir`` (programs have no file subset) and with
``--write-baseline`` (a partial run must never silently un-grandfather the
rest of the tree).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import baseline as _baseline
from .engine import LintConfig, RULES, changed_files, run_lint
from .ir import IR_RULES


def _format_text(result, verbose: bool, records=None) -> str:
    lines = []
    for f in result.findings:
        lines.append(f"{f.file}:{f.line}: {f.rule} {f.message}")
    if verbose:
        for f in result.suppressed:
            reason = f.suppress_reason or "(no reason recorded)"
            lines.append(f"{f.file}:{f.line}: {f.rule} suppressed by pragma "
                         f"— {reason}")
        for f in result.baselined:
            lines.append(f"{f.file}:{f.line}: {f.rule} baselined")
        for r in (records or []):
            if r.get("status") == "skip":
                lines.append(f"{r['file']}:{r['line']}: {r['builder']} "
                             f"skipped — {r['reason']}")
    skipped = sum(1 for r in (records or []) if r.get("status") == "skip")
    tail = (f", {len(records)} case(s) ({skipped} skipped)"
            if records is not None else
            f", {result.files_scanned} files scanned")
    lines.append(
        f"graftlint: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined" + tail)
    return "\n".join(lines)


def _rule_meta():
    """id → (name, summary) across both tiers (AST registry + IR table)."""
    from . import rules as _rules  # noqa: F401  (registers RULES)

    meta = {r.id: (r.name, r.summary) for r in RULES.values()}
    for rid, (name, summary) in IR_RULES.items():
        meta.setdefault(rid, (name, summary))
    return meta


def _format_sarif(result) -> str:
    """SARIF 2.1.0: one run, both tiers' rule metadata, suppressed/baselined
    results carrying ``suppressions`` (CI annotators skip those)."""
    meta = _rule_meta()
    used = sorted({f.rule for f in (result.findings + result.suppressed
                                    + result.baselined)})
    rules = [{
        "id": rid,
        "name": meta.get(rid, (rid, ""))[0] or rid,
        "shortDescription": {"text": meta.get(rid, ("", rid))[1] or rid},
    } for rid in used]

    def one(f, suppressions):
        d = {
            "ruleId": f.rule,
            "ruleIndex": used.index(f.rule),
            "level": "error",
            "message": {"text": f.message},
            "locations": [{"physicalLocation": {
                "artifactLocation": {"uri": f.file},
                "region": {"startLine": max(int(f.line), 1),
                           "startColumn": int(f.col) + 1},
            }}],
        }
        if suppressions is not None:
            d["suppressions"] = suppressions
        return d

    results = [one(f, None) for f in result.findings]
    results += [one(f, [{"kind": "inSource",
                         "justification": f.suppress_reason or ""}])
                for f in result.suppressed]
    results += [one(f, [{"kind": "external",
                         "justification":
                         "grandfathered in .yfmlint-baseline.json"}])
                for f in result.baselined]
    sarif = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                # informationUri must be a bare valid URI or schema
                # validators (GitHub code-scanning upload) reject the file
                "fullDescription": {
                    "text": "rule tables in docs/DESIGN.md §15 (AST tier) "
                            "and §18 (IR tier)"},
                "rules": rules,
            }},
            "results": results,
        }],
    }
    return json.dumps(sarif, indent=1)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m yieldfactormodels_jl_tpu.analysis",
        description="graftlint: rule-based static analysis for the repo's "
                    "jit/TPU invariants — AST tier (docs/DESIGN.md §15) "
                    "plus the --ir program-audit tier (§18)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text")
    parser.add_argument("--ir", action="store_true",
                        help="run the IR tier: lower every engine-cache "
                             "builder at the manifest shapes and audit the "
                             "compiled artifacts (imports jax; forces a "
                             "CPU backend with 8 virtual devices unless "
                             "JAX_PLATFORMS is already set)")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only files changed vs git HEAD "
                             "(worktree + staged + untracked)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetected from the "
                             "installed package location)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(default: all; AST tier only)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON path (default: "
                             "<root>/.yfmlint-baseline.json)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather the current unsuppressed findings "
                             "into the baseline (prunes + reports dropped "
                             "entries) and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table (both tiers) and exit")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="also print suppressed/baselined findings "
                             "(and, under --ir, skipped manifest cases)")
    args = parser.parse_args(argv)

    config = LintConfig(root=args.root) if args.root else LintConfig()

    if args.list_rules:
        for rid, (name, summary) in sorted(_rule_meta().items()):
            print(f"{rid}  {name}: {summary}")
        return 0

    if args.ir and args.root and os.path.realpath(args.root) \
            != os.path.realpath(LintConfig().root):
        print("--ir audits the IMPORTED package (builders register at "
              "import time) — it cannot audit a different checkout via "
              "--root; run it from that tree's environment instead",
              file=sys.stderr)
        return 2
    if args.ir and args.changed_only:
        print("--ir audits compiled programs — there is no changed-file "
              "subset to restrict to; drop --changed-only", file=sys.stderr)
        return 2
    if args.ir and args.rules:
        print("--rules selects AST rules; the IR tier runs its full check "
              "set — drop --rules", file=sys.stderr)
        return 2
    if args.write_baseline and (args.changed_only or args.rules):
        print("--write-baseline regenerates the baseline from a FULL run; "
              "with --changed-only/--rules it would silently drop every "
              "entry the partial run cannot see — run it unrestricted",
              file=sys.stderr)
        return 2

    rule_ids = None
    if args.rules:
        from . import rules as _rules  # noqa: F401
        rule_ids = [s.strip() for s in args.rules.split(",") if s.strip()]
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            print(f"unknown rule id(s): {unknown}", file=sys.stderr)
            return 2

    files = None
    if args.changed_only:
        changed = changed_files(config.root)
        if changed is None:
            print("--changed-only: git diff failed (no git / not a repo / "
                  "timeout) — refusing to lint an empty set", file=sys.stderr)
            return 2
        lintable = set(config.lint_files())
        files = [f for f in changed if f in lintable]

    baseline_path = args.baseline or config.abspath(config.baseline_path)
    try:
        baseline = _baseline.load_baseline(baseline_path)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"bad baseline: {e}", file=sys.stderr)
        return 2

    records = None
    if args.ir:
        from .ir import run_ir

        ir_result = run_ir(config, baseline=baseline)
        result, records = ir_result.lint, ir_result.records
        payload = ir_result.to_dict()
    else:
        result = run_lint(config, files=files, rules=rule_ids,
                          baseline=baseline)
        payload = result.to_dict()

    if args.write_baseline:
        if result.errors:
            # an unparseable module fires nothing — writing now would
            # silently un-grandfather everything it grandfathers
            for e in result.errors:
                print(f"graftlint: error: {e}", file=sys.stderr)
            print("graftlint: refusing --write-baseline while the run has "
                  "errors (entries in broken files would be dropped as "
                  "'fixed')", file=sys.stderr)
            return 2
        # keep: still-firing findings (actionable AND already-grandfathered)
        # plus every entry only the OTHER tier can observe — an AST run must
        # never prune IR debt (YFM10x) and vice versa; YFM011 is producible
        # by both tiers, so either run owns it
        producible = (set(IR_RULES) | {"YFM011"}) if args.ir else set(RULES)

        def _foreign(key):
            # malformed keys are NOT foreign — they match no finding in any
            # tier, and the plain-run stale warning promises a rewrite
            # prunes them
            parsed = _baseline.parse_key(key)
            return parsed is not None and parsed[0] not in producible

        foreign = {key for key in baseline if _foreign(key)}
        # staleness (file gone, line past EOF) is tier-agnostic: a stale
        # foreign key matches no finding in ANY tier, and the plain-run
        # warning promises the rewrite prunes it
        foreign -= set(_baseline.stale_entries(foreign, config.root))
        n = _baseline.save_baseline(
            baseline_path, result.findings + result.baselined,
            extra_keys=foreign)
        kept = ({f.key() for f in result.findings + result.baselined}
                | foreign)
        stale = _baseline.stale_entries(baseline - kept, config.root)
        dropped = sorted(baseline - kept)
        print(f"graftlint: wrote {n} baseline entrie(s) to {baseline_path}")
        for key in dropped:
            why = stale.get(key, "no longer fires (fixed)")
            print(f"graftlint: pruned {key} — {why}")
        if not dropped and baseline:
            print("graftlint: no entries pruned")
        return 0

    # a plain run must not silently carry dead grandfathered debt
    stale = _baseline.stale_entries(baseline, config.root)
    if stale:
        payload["stale_baseline"] = stale
        for key, why in sorted(stale.items()):
            print(f"graftlint: warning: stale baseline entry {key} — {why} "
                  f"(--write-baseline prunes it)", file=sys.stderr)

    if args.format == "json":
        print(json.dumps(payload, indent=1))
    elif args.format == "sarif":
        print(_format_sarif(result))
    else:
        print(_format_text(result, args.verbose, records))
    if result.errors:
        return 2
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
