"""graftlint tier 2: IR-level audit of the compiled engine-cache programs.

The AST tier (``engine.py``/``rules.py``) checks what the *source* promises;
this tier checks what the *artifact* delivers.  It enumerates every
``@register_engine_cache`` builder (``config.engine_cache_entries()`` — the
registrar introspection seam), constructs each cached jitted program at the
representative shapes ``analysis/manifest.py`` declares, LOWERS it (nothing
is compiled or executed), and audits the lowered StableHLO + jaxpr:

- **YFM101 donation honored.**  Source-level YFM002 can prove a donated
  value *reaches a return*; only the lowered module proves XLA actually
  aliased the buffer (``tf.aliasing_output`` on the argument).  A declared
  donation that lowers un-aliased is silently dropped — no reuse, no
  warning on some paths — which is exactly the failure mode the lattice /
  shard-update / multistart donation work guards against (DESIGN §14).
- **YFM102 dtype discipline.**  ``stablehlo.convert`` from f64 down to
  f32/f16/bf16 inside a float64 program means some intermediate silently
  dropped precision the oracle-parity tests assume.
- **YFM103 host round-trips.**  ``pure_callback``/``io_callback``/host
  custom-calls inside the graph serialize the device pipeline per call.
- **YFM104 lane rule.**  Heuristic over jaxpr avals: an UNBATCHED
  ``dot_general``/``scatter`` whose big free axis (≥ :data:`LANE_BIG`) sits
  off the trailing dimension while the trailing dimension is tiny wastes
  TPU lanes (CLAUDE.md lane convention).  Batched dots (vmap-generated —
  XLA owns their layout) are skipped.
- **YFM105 retrace census.**  All of a case's staging variants must lower
  to at most ``max_programs`` distinct artifacts — the PR-8 class of bug
  where warm-up staged inputs differently from the hot path and silently
  doubled the compile matrix.
- **YFM011 runtime coverage census.**  Builders that registered at import
  but have no manifest case (and stale manifest keys) — the runtime
  cross-check of the AST-side YFM011 rule.

Findings carry the builder's def site (file:line), so the ordinary pragma
(``# yfmlint: disable=YFM10x -- reason`` above the builder) and the
committed ``.yfmlint-baseline.json`` apply unchanged.  Manifest-level skips
(``skip_case`` — e.g. Pallas programs that only lower for TPU) surface as
suppressed findings with their reasons, never silently.

This module imports NO jax at import time; everything heavy happens inside
:func:`run_ir`, which the CLI reaches only under ``--ir``.  The default AST
tier stays jax-free and ~1 s (tests/test_lint.py pins it).
"""

from __future__ import annotations

import dataclasses
import importlib
import inspect
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .engine import Finding, LintConfig, LintResult, SourceModule

#: tier-2 rule table (id → (name, summary)); the CLI merges this with the
#: AST-side RULES for --list-rules and the SARIF rule metadata
IR_RULES: Dict[str, Tuple[str, str]] = {
    "YFM100": ("ir-audit-error",
               "a manifest case failed to build or lower — manifest rot or "
               "a broken builder"),
    "YFM101": ("ir-donation-honored",
               "every declared donated input must lower with an "
               "input_output alias — an un-aliased donation is silently "
               "dropped by XLA"),
    "YFM102": ("ir-dtype-discipline",
               "no f64→f32/f16/bf16 down-conversions inside float64 "
               "programs"),
    "YFM103": ("ir-host-roundtrip",
               "no pure_callback/io_callback/host custom-calls inside "
               "compiled programs"),
    "YFM104": ("ir-lane-rule",
               "big free axes of unbatched dot_general/scatter operands "
               "must ride the trailing (lane) dimension"),
    "YFM105": ("ir-retrace-census",
               "a case's staging variants must collapse to its declared "
               "program count — staging mismatches multiply compiles "
               "silently"),
}

#: an axis is "big" for the lane heuristic at/above this (one TPU lane tile
#: is 128; 512 keeps audit-sized batches of vmapped small-state filters out)
LANE_BIG = 512
#: ... and a trailing axis is "tiny" below this
LANE_TINY = 8

_ALIAS_ATTR = "tf.aliasing_output"
_CONVERT_RE = re.compile(
    r"stablehlo\.convert\s+%\S+\s*:\s*\(tensor<[^>]*xf64>\)\s*->\s*"
    r"tensor<[^>]*x(f32|f16|bf16)>")
_CUSTOM_CALL_RE = re.compile(r"stablehlo\.custom_call\s+@([\w.]+)")
_CALLBACK_MARKERS = ("callback", "host")


def _setup_audit_env() -> None:
    """Point the not-yet-initialized jax at a CPU backend with 8 virtual
    devices (the tests' conftest environment): the audit lowers mesh-sharded
    programs, and an un-forced import would dial the TPU tunnel (CLAUDE.md
    TPU access rules).  A no-op once jax is imported — an explicitly
    configured environment (JAX_PLATFORMS=tpu for an on-device audit) wins."""
    if "jax" in sys.modules:
        return
    # jax treats an EMPTY JAX_PLATFORMS as unset — setdefault would keep
    # the empty string and the import would dial the TPU tunnel anyway
    if not os.environ.get("JAX_PLATFORMS"):
        os.environ["JAX_PLATFORMS"] = "cpu"
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()


def _import_package_modules(config: LintConfig) -> List[str]:
    """Import every package module so ``engine_cache_entries()`` is complete
    (registration happens at import time).  Returns import failures as
    ``"module: error"`` strings; the analysis subpackage itself is skipped
    (it is jax-free by contract and registers nothing)."""
    from .engine import iter_py_files

    errors = []
    pkg_root = config.abspath(config.package)
    for path in iter_py_files(pkg_root):
        rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
        if rel.startswith("analysis/"):
            continue
        dotted = rel[:-3].replace("/", ".")
        if dotted.endswith("__init__"):
            dotted = dotted[: -len(".__init__")] or ""
        name = config.package + ("." + dotted if dotted else "")
        try:
            importlib.import_module(name)
        except Exception as e:  # noqa: BLE001 — report, keep auditing
            errors.append(f"{name}: {e!r}")
    return errors


# ---------------------------------------------------------------------------
# per-case checks
# ---------------------------------------------------------------------------

def _sub_jaxprs(v):
    out = []
    if hasattr(v, "jaxpr"):          # ClosedJaxpr
        out.append(v.jaxpr)
    elif hasattr(v, "eqns"):         # Jaxpr
        out.append(v)
    elif isinstance(v, (list, tuple)):
        for el in v:
            out.extend(_sub_jaxprs(el))
    return out


def _iter_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _lane_violations(jaxpr) -> List[str]:
    """Lane-rule heuristic (module docstring).  Returns human messages."""
    out = []
    for eqn in _iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim == "dot_general":
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            if lb or rb:
                continue  # vmap-generated batched dot: XLA owns the layout
            for aval, contract, side in ((eqn.invars[0].aval, lc, "lhs"),
                                         (eqn.invars[1].aval, rc, "rhs")):
                shape = getattr(aval, "shape", ())
                if len(shape) < 2:
                    continue
                free = [d for d in range(len(shape)) if d not in contract]
                bad = [d for d in free
                       if shape[d] >= LANE_BIG and d != len(shape) - 1]
                if bad and shape[-1] < LANE_TINY:
                    out.append(
                        f"dot_general {side} operand {tuple(shape)} carries "
                        f"a big free axis (dim {bad[0]}, size "
                        f"{shape[bad[0]]}) off the trailing lane dimension "
                        f"(trailing size {shape[-1]})")
        # scatter is deliberately NOT checked here: vmap's batching rule
        # hoists the batch axis to the FRONT of every interior scatter, so a
        # correctly batch-last program (the store's slot scatters, the
        # batcher buckets) and a violating one lower to identical interior
        # shapes — measured on serving.batcher._jitted_forecast_bucket.
    return out


def _audit_case(case, jitted, arg_sets) -> Tuple[List[Tuple[str, str]], dict]:
    """Lower every arg set of one case and run the artifact checks.
    Returns ``([(rule_id, message), ...], record)``."""
    problems: List[Tuple[str, str]] = []
    texts = []
    first_traced = None
    for args in arg_sets:
        # one trace serves both the lowered text and (for the first
        # variant) the YFM104 jaxpr scan — tracing dominates the tier's
        # wall, lowering the same trace twice would double it
        traced = jitted.trace(*args)
        if first_traced is None:
            first_traced = traced
        texts.append(traced.lower().as_text())

    # YFM101 — donation honored in the artifact
    aliases = min(t.count(_ALIAS_ATTR) for t in texts) if texts else 0
    if case.donated and aliases < case.donated:
        problems.append((
            "YFM101",
            f"case {case.label!r} declares {case.donated} donated "
            f"buffer(s) but the lowered artifact aliases only {aliases} — "
            f"XLA dropped the donation (no input_output alias); pass the "
            f"donated value through to a shape-matched output "
            f"(docs/DESIGN.md §14)"))

    # YFM102 — dtype discipline inside f64 programs
    for t in texts:
        if "xf64" not in t:
            continue
        m = _CONVERT_RE.search(t)
        if m:
            problems.append((
                "YFM102",
                f"case {case.label!r}: float64 program lowers a "
                f"down-conversion to {m.group(1)} "
                f"({m.group(0).split(':')[0].strip()}) — some intermediate "
                f"silently drops the precision the oracle parity assumes"))
            break

    # YFM103 — host round-trips
    for t in texts:
        hits = [tgt for tgt in _CUSTOM_CALL_RE.findall(t)
                if any(mk in tgt.lower() for mk in _CALLBACK_MARKERS)]
        if hits:
            problems.append((
                "YFM103",
                f"case {case.label!r}: compiled program contains host "
                f"callback custom-call(s) {sorted(set(hits))} — the device "
                f"pipeline serializes on the host once per call"))
            break

    # YFM104 — lane rule over the jaxpr
    lanes: List[str] = []
    try:
        lanes = _lane_violations(first_traced.jaxpr.jaxpr)
    except Exception:  # noqa: BLE001 — heuristic check, never fatal
        pass
    if lanes:
        problems.append((
            "YFM104",
            f"case {case.label!r}: {lanes[0]}" +
            (f" (+{len(lanes) - 1} more site(s))" if len(lanes) > 1 else "")
            + " — keep the big batch axis LAST (CLAUDE.md lane rule)"))

    # YFM105 — retrace census across the case's staging variants
    distinct = len(set(texts))
    if distinct > case.max_programs:
        problems.append((
            "YFM105",
            f"case {case.label!r}: {len(arg_sets)} staging variant(s) "
            f"lower to {distinct} distinct program(s), expected at most "
            f"{case.max_programs} — a staging mismatch multiplies the "
            f"compile matrix silently (the PR-8 warmup bug class)"))

    record = {"label": case.label, "variants": len(arg_sets),
              "aliases": aliases, "programs": distinct,
              "lane_sites": len(lanes)}
    return problems, record


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class IRResult:
    """Tier-2 result: the shared finding partition plus per-case records."""

    lint: LintResult
    #: one dict per audited (builder, case): status ok/skip/error + counters
    records: List[dict] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        d = self.lint.to_dict()
        d["tier"] = "ir"
        d["records"] = list(self.records)
        return d


def _builder_site(config: LintConfig, fn) -> Tuple[str, int]:
    """(repo-relative file, def line) of a registered builder — the anchor
    every IR finding reports, so pragmas/baseline address source lines.

    ``inspect.getsourcelines`` starts at the FIRST DECORATOR; the anchor
    must be the ``def`` line itself — it is where CLAUDE.md tells the
    maintainer to put the pragma, where ``suppression_for`` looks, and
    where the AST-side YFM011 rule anchors (``ast.FunctionDef.lineno``),
    so the two tiers' baseline keys agree."""
    try:
        raw = inspect.unwrap(fn)
        path = inspect.getsourcefile(raw)
        lines, line = inspect.getsourcelines(raw)
        for off, text in enumerate(lines):
            stripped = text.lstrip()
            if stripped.startswith(("def ", "async def ")):
                line += off
                break
        rel = os.path.relpath(path, config.root).replace(os.sep, "/")
        return rel, int(line)
    except (OSError, TypeError):
        return config.config_module, 1


def run_ir(config: Optional[LintConfig] = None,
           only: Optional[Sequence[str]] = None,
           baseline: Optional[set] = None) -> IRResult:
    """Audit the engine-cache builders' lowered artifacts.

    ``only`` restricts to a subset of builder keys (tests/partial audits;
    the completeness census is skipped then).  Findings flow through the
    same pragma + baseline partition as the AST tier."""
    config = config or LintConfig()
    baseline = baseline or set()
    _setup_audit_env()
    import jax

    jax.config.update("jax_enable_x64", True)

    result = LintResult()
    out = IRResult(result)
    result.errors.extend(_import_package_modules(config))

    from .. import config as pkg_config
    from . import manifest as mf

    entries = dict(pkg_config.engine_cache_entries())
    keys = sorted(set(mf.MANIFEST) | set(entries)) if only is None \
        else [k for k in sorted(set(mf.MANIFEST) | set(entries))
              if k in set(only)]

    raw: List[Finding] = []

    def add(rule, rel, line, msg):
        raw.append(Finding(rule, rel, line, 0, msg))

    # anchor stale-key findings at the case() registration line — the same
    # line the AST-side YFM011 rule uses, so the tiers' baseline keys agree
    from .rules import _manifest_keys

    manifest_rel = config.manifest_module
    manifest_lines = _manifest_keys(config) or {}
    for key in keys:
        cases = mf.MANIFEST.get(key)
        fn = entries.get(key)
        if fn is None:
            # manifest names a builder that never registered: stale manifest
            add("YFM011", manifest_rel, manifest_lines.get(key, 1),
                f"manifest case {key!r} names no registered engine-cache "
                f"builder — prune or fix the key (runtime census)")
            continue
        rel, line = _builder_site(config, fn)
        if cases is None:
            add("YFM011", rel, line,
                f"builder {key} registered at import but has no "
                f"manifest case — add one to analysis/manifest.py so "
                f"tier-2 coverage grows with the code (runtime census)")
            continue
        for case in cases:
            rec = {"builder": key, "file": rel, "line": line,
                   "label": case.label}
            if case.skip is not None:
                rec["status"] = "skip"
                rec["reason"] = case.skip
                out.records.append(rec)
                continue
            try:
                jitted, arg_sets = case.make()
                problems, counters = _audit_case(case, jitted, arg_sets)
            except Exception as e:  # noqa: BLE001 — audit must not die
                add("YFM100", rel, line,
                    f"case {case.label!r} failed to build/lower: {e!r}")
                rec["status"] = "error"
                rec["error"] = repr(e)
                out.records.append(rec)
                continue
            rec["status"] = "ok" if not problems else "findings"
            rec.update(counters)
            out.records.append(rec)
            for rule, msg in problems:
                add(rule, rel, line, f"{key}: {msg}")

    if only is None:
        # program ↔ manifest census (DESIGN §22): every registered program
        # must carry its auto-generated `program:<name>` case on every
        # audited builder, and every program-labeled case must name a
        # registered program — registration drift is a census finding in
        # both directions, not a silent coverage hole
        from ..program.registry import _AUDIT_BUILDERS, registered_programs

        prog_names = {p.name for p in registered_programs()}
        labeled: Dict[str, set] = {}
        for key, cases in mf.MANIFEST.items():
            for case in cases:
                if case.label.startswith("program:"):
                    labeled.setdefault(
                        case.label[len("program:"):], set()).add(key)
        for name in sorted(prog_names):
            for key in _AUDIT_BUILDERS:
                if key not in labeled.get(name, set()):
                    add("YFM011", manifest_rel, 1,
                        f"registered program {name!r} has no "
                        f"'program:{name}' case on builder {key} — "
                        f"register_program auto-generates these; "
                        f"re-register or repair the manifest "
                        f"(runtime census)")
        for name in sorted(set(labeled) - prog_names):
            add("YFM011", manifest_rel, 1,
                f"manifest case label 'program:{name}' on builders "
                f"{sorted(labeled[name])} names no registered program — "
                f"unregister_program drops its cases; prune the stale "
                f"label (runtime census)")

    # partition: pragmas (on the builder's source lines) > baseline > action
    mods: Dict[str, Optional[SourceModule]] = {}

    def module_for(rel: str) -> Optional[SourceModule]:
        if rel not in mods:
            path = config.abspath(rel)
            try:
                mods[rel] = SourceModule(path, rel)
            except (OSError, SyntaxError):
                mods[rel] = None
        return mods[rel]

    for f in sorted(raw, key=lambda f: (f.file, f.line, f.rule)):
        mod = module_for(f.file)
        reason = mod.suppression_for(f) if mod is not None else None
        if reason is not None:
            f.suppressed, f.suppress_reason = True, reason
            result.suppressed.append(f)
        elif f.key() in baseline:
            f.baselined = True
            result.baselined.append(f)
        else:
            result.findings.append(f)
    result.files_scanned = len([r for r in out.records
                                if r.get("status") != "skip"])
    return out
