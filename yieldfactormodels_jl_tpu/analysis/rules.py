"""graftlint rules YFM001–YFM009 (rule table in docs/DESIGN.md §15).

Each rule is a small function over a parsed :class:`~.engine.SourceModule`
(or the whole module list for project-scope rules) registered via
:func:`~.engine.rule`.  Rules only *report* — suppression (pragmas) and
grandfathering (baseline) are the engine's job, so a rule never needs its
own escape hatch.
"""

from __future__ import annotations

import ast
import os
import re
from functools import lru_cache
from typing import Iterable, List

from .engine import (Finding, JIT_ENTRY, LintConfig, SourceModule, call_name,
                     dotted_name, enclosing_functions, iter_py_files,
                     names_reaching_return, raised_name, rule)


def _finding(rule_id: str, mod: SourceModule, node, message: str) -> Finding:
    return Finding(rule_id, mod.rel, getattr(node, "lineno", 1),
                   getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
# YFM001 — sentinel discipline
# ---------------------------------------------------------------------------

@rule("YFM001", "sentinel-discipline",
      "no `raise` reachable inside kernel/scan bodies — failures are "
      "sentinels (−Inf loss, NaN moments) plus a taxonomy code")
def yfm001_sentinel_discipline(mod: SourceModule,
                               config: LintConfig) -> Iterable[Finding]:
    if not config.in_package(mod.rel):
        return
    kernel = config.is_kernel(mod.rel)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Raise):
            continue
        depth = mod.func_depth(node)
        name = raised_name(node)
        if kernel:
            # historical kernel-module semantics (tests/test_conventions.py):
            # any nested raise is a traced-body raise; top-level raises must
            # be trace-time validation classes
            if depth >= 2:
                yield _finding(
                    "YFM001", mod, node,
                    "raise inside a nested function (scan/kernel body) — "
                    "use the −Inf/NaN sentinel + taxonomy code instead")
            elif name not in config.raise_whitelist:
                yield _finding(
                    "YFM001", mod, node,
                    f"raises {name or '<bare>'} — only trace-time validation "
                    f"({sorted(config.raise_whitelist)}) is allowed in "
                    f"kernel modules")
            continue
        marker = mod.jit_marker(node)
        if marker is None:
            continue
        scope, kind = marker
        # a whitelisted validation raise sitting directly in a JIT-entry
        # function fires at trace time (shape/config checks) — allowed;
        # anything inside a traced body, nested closure, or of a
        # non-whitelisted class is a sentinel violation
        immediate = mod.func_depth(node) == mod.func_depth(scope) + 1 \
            if not isinstance(scope, ast.Lambda) else False
        if kind == JIT_ENTRY and immediate and name in config.raise_whitelist:
            continue
        yield _finding(
            "YFM001", mod, node,
            f"raise {name or '<bare>'} inside a jit context "
            f"({kind}) — failures inside traced code must be sentinels "
            f"(−Inf/NaN + taxonomy code), not exceptions")


# ---------------------------------------------------------------------------
# YFM002 — donation aliasing (docs/DESIGN.md §14)
# ---------------------------------------------------------------------------

def _donated_indices(expr, scope=None) -> List[int]:
    """Constant indices named by a ``donate_argnums=`` value, unioned across
    conditional branches (``(1, 2) if donate else ()``).  A ``tuple(name)``
    /bare ``name`` spec is resolved against ``scope`` (the enclosing
    function/module) by unioning the name's literal list assignments and
    ``name.append(<const>)`` calls — the scenario-lattice build-a-list
    idiom; an over-approximation is fine (extra indices only tighten the
    check)."""
    out: List[int] = []
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        out.append(expr.value)
    elif isinstance(expr, (ast.Tuple, ast.List)):
        for el in expr.elts:
            out.extend(_donated_indices(el, scope))
    elif isinstance(expr, ast.IfExp):
        out.extend(_donated_indices(expr.body, scope))
        out.extend(_donated_indices(expr.orelse, scope))
    elif isinstance(expr, ast.Call) and dotted_name(expr.func) in (
            "tuple", "list") and len(expr.args) == 1:
        out.extend(_donated_indices(expr.args[0], scope))
    elif isinstance(expr, ast.Name) and scope is not None:
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in node.targets) and \
                    isinstance(node.value, (ast.Tuple, ast.List)):
                out.extend(_donated_indices(node.value))
            elif isinstance(node, ast.Call) and \
                    dotted_name(node.func) == f"{expr.id}.append":
                for a in node.args:
                    out.extend(_donated_indices(a))
    return out


def _local_defs(mod: SourceModule):
    defs = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    return defs


def _check_donation(mod, site_node, fn, indices) -> Iterable[Finding]:
    args = fn.args
    params = [a.arg for a in args.posonlyargs + args.args]
    reach = names_reaching_return(fn)
    for idx in sorted(set(indices)):
        if idx >= len(params):
            yield _finding(
                "YFM002", mod, site_node,
                f"donate_argnums index {idx} is out of range for "
                f"{getattr(fn, 'name', '<lambda>')}({', '.join(params)})")
            continue
        pname = params[idx]
        if pname not in reach:
            yield _finding(
                "YFM002", mod, site_node,
                f"donated argument {idx} ({pname!r}) never flows into a "
                f"returned value of {getattr(fn, 'name', '<lambda>')} — "
                f"XLA will silently drop the donation (no aliasing, no "
                f"reuse); pass it through to a shape-matched output "
                f"(docs/DESIGN.md §14)")


def _donate_kw(call: ast.Call):
    return next((k for k in call.keywords
                 if k.arg in ("donate_argnums", "donate_argnames")), None)


@rule("YFM002", "donation-aliasing",
      "every donate_argnums input must flow into an output — XLA silently "
      "drops a donated buffer whose contents are dead")
def yfm002_donation_aliasing(mod: SourceModule,
                             config: LintConfig) -> Iterable[Finding]:
    if not config.in_package(mod.rel):
        return
    defs = None
    for node in ast.walk(mod.tree):
        # decorator form: @partial(jax.jit, donate_argnums=...) / @jax.jit(...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _donate_kw(dec) is not None:
                    indices = _donated_indices(_donate_kw(dec).value,
                                               scope=mod.tree)
                    yield from _check_donation(mod, dec, node, indices)
            continue
        if not isinstance(node, ast.Call):
            continue
        kw = _donate_kw(node)
        if kw is None or not node.args:
            continue
        # resolve a dynamic spec (a Name / tuple(name) built with literal
        # appends) against the innermost enclosing function, else the module
        chain = enclosing_functions(node, mod.parents)
        scope = chain[0] if chain else mod.tree
        indices = _donated_indices(kw.value, scope=scope)
        target = node.args[0]
        fn = None
        if isinstance(target, ast.Lambda):
            fn = target
        elif isinstance(target, ast.Name):
            if defs is None:
                defs = _local_defs(mod)
            fn = defs.get(target.id)
        if fn is None:
            continue  # non-local callee: not analyzable statically
        yield from _check_donation(mod, node, fn, indices)


# ---------------------------------------------------------------------------
# YFM003 — engine-cache idiom order
# ---------------------------------------------------------------------------

def _dec_name(dec) -> str:
    target = dec.func if isinstance(dec, ast.Call) else dec
    return dotted_name(target).split(".")[-1]


@rule("YFM003", "cache-idiom-order",
      "@register_engine_cache must sit directly above @lru_cache so the "
      "registrar holds the cache-clearable wrapper")
def yfm003_cache_idiom(mod: SourceModule,
                       config: LintConfig) -> Iterable[Finding]:
    if not config.in_package(mod.rel):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        names = [_dec_name(d) for d in node.decorator_list]
        if "register_engine_cache" not in names:
            continue
        reg = names.index("register_engine_cache")
        if "lru_cache" not in names:
            yield _finding(
                "YFM003", mod, node,
                f"{node.name}: @register_engine_cache without @lru_cache — "
                f"the registrar must receive a cache_clear-able wrapper")
        elif names.index("lru_cache") < reg:
            yield _finding(
                "YFM003", mod, node,
                f"{node.name}: decorator order is @lru_cache above "
                f"@register_engine_cache — swap them (cache under the "
                f"registrar) or engine switches leave stale traces alive")


# ---------------------------------------------------------------------------
# YFM004 — host impurity inside jit contexts
# ---------------------------------------------------------------------------

#: host-side calls that burn into the trace (stale value) or fire once per
#: trace instead of once per run — banned inside jit contexts
_HOST_CALLS = frozenset({
    "print", "input", "open",
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "time.sleep", "os.getenv", "os.urandom", "datetime.now",
    "datetime.datetime.now", "datetime.utcnow", "datetime.datetime.utcnow",
})
_HOST_PREFIXES = ("np.random.", "numpy.random.", "random.")
#: the documented trace-counter idiom (config.make_trace_counter): ONE host
#: call at the top of a to-be-jitted body, counting actual (re)traces
_ALLOWED = frozenset({"note_trace"})


@rule("YFM004", "host-impurity-in-jit",
      "no host-side effects (time/np.random/print/os.environ) inside jitted "
      "bodies — they burn into the trace instead of running per call")
def yfm004_host_impurity(mod: SourceModule,
                         config: LintConfig) -> Iterable[Finding]:
    if not config.in_package(mod.rel):
        return
    kernel = config.is_kernel(mod.rel)

    def in_context(node) -> bool:
        if mod.jit_marker(node) is not None:
            return True
        # kernel modules: every nested function is a traced body
        return kernel and mod.func_depth(node) >= 2

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if not name or name.split(".")[-1] in _ALLOWED:
                continue
            if name in _HOST_CALLS or name.startswith(_HOST_PREFIXES):
                if in_context(node):
                    yield _finding(
                        "YFM004", mod, node,
                        f"host call {name}() inside a jit context — its "
                        f"value/effect is frozen at trace time; hoist it to "
                        f"the driver layer")
        elif isinstance(node, ast.Attribute):
            if dotted_name(node) == "os.environ" and in_context(node):
                yield _finding(
                    "YFM004", mod, node,
                    "os.environ read inside a jit context — env knobs are "
                    "trace-time constants; read them in the builder, not "
                    "the traced body")


# ---------------------------------------------------------------------------
# YFM005 — atomic publish (tmp + os.replace)
# ---------------------------------------------------------------------------

_WRITE_MODE = re.compile(r"[wax]")


def _is_write_channel(node: ast.Call) -> bool:
    name = call_name(node)
    if name.split(".")[-1] == "savetxt":
        return True
    if name.split(".")[-1] in ("write_text", "write_bytes"):
        return True
    if name.split(".")[-1] == "open" or name == "open":
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for k in node.keywords:
            if k.arg == "mode":
                mode = k.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return bool(_WRITE_MODE.search(mode.value))
        return False
    return False


def _write_target(node: ast.Call):
    """The path expression a write channel writes to."""
    tail = call_name(node).split(".")[-1]
    if tail in ("write_text", "write_bytes"):
        return node.func.value  # the path object
    return node.args[0] if node.args else None


def _expr_tokens(expr):
    """(names, string constants) appearing anywhere in an expression."""
    names = {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}
    names |= {n.attr for n in ast.walk(expr) if isinstance(n, ast.Attribute)}
    strs = [n.value for n in ast.walk(expr)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]
    return names, strs


@rule("YFM005", "atomic-publish",
      "writes under orchestration/ and persistence/ publish via "
      "writer-unique tmp + os.replace — a torn file must be unobservable")
def yfm005_atomic_publish(mod: SourceModule,
                          config: LintConfig) -> Iterable[Finding]:
    rel = mod.rel.replace(os.sep, "/")
    if not any(rel.startswith(d + "/") for d in config.atomic_dirs):
        return
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _is_write_channel(node)):
            continue
        funcs = enclosing_functions(node, mod.parents)
        # the WRITTEN path must be the buffer a same-function os.replace/
        # os.link later publishes (name overlap with the publish's source
        # arg, or a visibly tmp-suffixed expression) — an unrelated atomic
        # publish elsewhere in the function must not vouch for this write
        publish_names: set = set()
        for fn in funcs[:1]:  # innermost enclosing function owns the publish
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) and sub.args and \
                        call_name(sub) in ("os.replace", "os.link"):
                    publish_names |= _expr_tokens(sub.args[0])[0]
        target = _write_target(node)
        target_ok = False
        if target is not None:
            names, strs = _expr_tokens(target)
            target_ok = bool(names & publish_names) or \
                any("tmp" in n.lower() for n in names) or \
                any(".tmp" in s for s in strs)
        if not funcs or not publish_names or not target_ok:
            yield _finding(
                "YFM005", mod, node,
                f"{call_name(node)}() writes a shard/DB/artifact path that "
                f"is not a tmp buffer published by a same-function "
                f"os.replace — build in a writer-unique tmp file and "
                f"publish atomically (tmp + os.replace)")


# ---------------------------------------------------------------------------
# YFM006 — env knobs documented in CLAUDE.md
# ---------------------------------------------------------------------------

_YFM_KNOB = re.compile(r"\bYFM_[A-Z0-9_]+\b")
_BENCH_KNOB = re.compile(r"\bBENCH_[A-Z0-9_]+\b")


def claude_md_text(config: LintConfig) -> str:
    path = config.abspath(config.claude_md)
    if not os.path.isfile(path):
        return ""
    # memoize on (path, mtime): one read per lint pass instead of one per
    # module, while fixture tests that rewrite the doc stay correct
    return _read_cached(path, os.stat(path).st_mtime_ns)


@lru_cache(maxsize=8)
def _read_cached(path: str, _mtime_ns: int) -> str:
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def knob_occurrences(mod: SourceModule, bench: bool):
    """(knob, lineno) pairs for every YFM_* — and, in bench-layer files,
    BENCH_* — name in the source (comments and strings included: a knob
    mentioned anywhere must be discoverable in CLAUDE.md)."""
    for i, line in enumerate(mod.source.splitlines(), start=1):
        for m in _YFM_KNOB.finditer(line):
            yield m.group(0), i
        if bench:
            for m in _BENCH_KNOB.finditer(line):
                yield m.group(0), i


@rule("YFM006", "env-knob-docs",
      "every YFM_*/BENCH_* knob referenced in source must be documented in "
      "CLAUDE.md — an undocumented knob is a silent behavior switch")
def yfm006_env_knob_docs(mod: SourceModule,
                         config: LintConfig) -> Iterable[Finding]:
    rel = mod.rel.replace(os.sep, "/")
    bench = config.matches(rel, config.bench_files)
    if not (bench or config.in_package(rel)):
        return
    # exact-token membership, not substring containment: a knob that is a
    # proper prefix of a documented one (e.g. the lock knob vs its _TTL
    # variant) must not pass on the longer name's substring
    doc = claude_md_text(config)
    documented = set(_YFM_KNOB.findall(doc)) | set(_BENCH_KNOB.findall(doc))
    seen = set()  # report each undocumented knob once per file
    for knob, line in knob_occurrences(mod, bench):
        if knob in documented or knob in seen:
            continue
        seen.add(knob)
        bullet = ("the Benchmarks bullet in CLAUDE.md's Commands"
                  if knob.startswith("BENCH_")
                  else "the env-knob bullets in CLAUDE.md's Conventions")
        yield Finding("YFM006", mod.rel, line, 0,
                      f"undocumented env knob {knob} — add it to {bullet}")


# ---------------------------------------------------------------------------
# YFM007 — every registered engine has oracle-backed parity coverage
# ---------------------------------------------------------------------------

#: engine registries in config.py whose every entry must be oracle-backed —
#: the Kalman loglik engines and the second-order (Newton HVP) engines share
#: one parity contract
_ENGINE_REGISTRIES = ("KALMAN_ENGINES", "NEWTON_ENGINES")


def kalman_engines_static(config: LintConfig):
    """(engines tuple, lineno) parsed from config.py's AST — the linter must
    not import the package (that would pull jax).  Collects every registry
    named in ``_ENGINE_REGISTRIES`` (a missing registry contributes
    nothing, so older trees still lint)."""
    path = config.abspath(config.config_module)
    if not os.path.isfile(path):
        return (), 1
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    engines: list = []
    lineno = 1
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id in _ENGINE_REGISTRIES
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                engines.extend(el.value for el in node.value.elts
                               if isinstance(el, ast.Constant))
                if lineno == 1:
                    lineno = node.lineno
    return tuple(engines), lineno


def oracle_backed_test_strings(config: LintConfig):
    """test-module name → set of string constants, for every test module
    that imports tests/oracle.py (the independent NumPy loops every numeric
    kernel must be pinned against)."""
    tdir = config.abspath(config.tests_dir)
    out = {}
    if not os.path.isdir(tdir):
        return out
    for path in iter_py_files(tdir):
        name = os.path.basename(path)
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        uses_oracle = False
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                    node.module.split(".")[-1] == "oracle"
                    or any(a.name == "oracle" for a in node.names)):
                uses_oracle = True
            if isinstance(node, ast.Import) and any(
                    a.name.split(".")[-1] == "oracle" for a in node.names):
                uses_oracle = True
        if uses_oracle:
            out[name] = {n.value for n in ast.walk(tree)
                         if isinstance(n, ast.Constant)
                         and isinstance(n.value, str)}
    return out


@rule("YFM007", "engine-oracle-parity",
      "every config.KALMAN_ENGINES entry must be named in an "
      "oracle-importing test module — no engine ships without parity",
      scope="project")
def yfm007_engine_parity(modules, config: LintConfig) -> Iterable[Finding]:
    engines, lineno = kalman_engines_static(config)
    if not engines:
        return
    strings = oracle_backed_test_strings(config)
    for engine in engines:
        if not any(engine in ss for ss in strings.values()):
            yield Finding(
                "YFM007", config.config_module, lineno, 0,
                f"engine {engine!r} has no oracle-backed parity coverage — "
                f"add a parity test against tests/oracle.py that names it "
                f"(see test_assoc_estimation.test_engine_oracle_parity_"
                f"with_nan_gap)")


# ---------------------------------------------------------------------------
# YFM008 — request-path hygiene (DESIGN §12)
# ---------------------------------------------------------------------------

_UNBOUNDED_QUEUES = ("queue.Queue", "Queue", "queue.LifoQueue",
                     "queue.PriorityQueue", "queue.SimpleQueue")

#: the per-request ROUTING functions (gateway pump → batch formation →
#: shard routing): work here happens BEFORE the flush, once per request, so
#: a host gather is an O(registry)-scaling tax the response boundary never
#: pays back.  Host transfer belongs in the collect/response functions only
#: (DESIGN §16 routing state machine).
_ROUTING_FUNCS = frozenset({"pump", "_pump_locked", "_dispatch_updates",
                            "_submit_read", "_route_waves", "_admit"})

#: calls that move device values to host (or force a device sync)
_HOST_TRANSFERS = ("jax.device_get", "device_get", "np.asarray", "np.array",
                   "numpy.asarray", "numpy.array", "jax.block_until_ready")


@rule("YFM008", "request-path-hygiene",
      "no unbounded queue.Queue(), no bare time.sleep, and no host "
      "gather/sync inside the per-request routing functions under serving/ "
      "— backpressure and O(batch) host traffic must not regress silently")
def yfm008_request_path(mod: SourceModule,
                        config: LintConfig) -> Iterable[Finding]:
    rel = mod.rel.replace(os.sep, "/")
    if not rel.startswith(config.serving_dir.rstrip("/") + "/"):
        return
    routing_spans = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in _ROUTING_FUNCS:
            routing_spans.append((node.name, node.lineno,
                                  node.end_lineno or node.lineno))
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in ("time.sleep", "sleep"):
            yield _finding(
                "YFM008", mod, node,
                f"bare {name}() on the request path — use an interruptible "
                f"Event/Condition wait")
        if name in _UNBOUNDED_QUEUES:
            bounded = bool(node.args) or any(
                kw.arg == "maxsize" for kw in node.keywords)
            if not bounded:
                yield _finding(
                    "YFM008", mod, node,
                    f"unbounded {name}() on the request path — give it a "
                    f"maxsize (backpressure)")
        if name and (name in _HOST_TRANSFERS
                     or name.split(".")[-1] in ("device_get",
                                                "block_until_ready")):
            lineno = getattr(node, "lineno", 0)
            for fname, lo, hi in routing_spans:
                if lo <= lineno <= hi:
                    yield _finding(
                        "YFM008", mod, node,
                        f"host transfer {name}() inside routing function "
                        f"{fname}() — the per-request routing path must "
                        f"stay device-side; gather only at the response "
                        f"boundary (collect/finish)")
                    break


# ---------------------------------------------------------------------------
# YFM009 — docstring citations must point at real reference files
# ---------------------------------------------------------------------------

_CITATION = re.compile(r"/root/reference/([A-Za-z0-9_./-]+)")


@rule("YFM009", "citation-exists",
      "docstring citations of /root/reference/<file> must name files that "
      "exist — a typo'd citation is unverifiable parity provenance")
def yfm009_citations(mod: SourceModule,
                     config: LintConfig) -> Iterable[Finding]:
    ref = config.reference_root
    if not os.path.isdir(ref):
        return  # reference tree absent on this box: nothing verifiable
    if not config.in_package(mod.rel):
        return
    seen = set()
    for i, line in enumerate(mod.source.splitlines(), start=1):
        for m in _CITATION.finditer(line):
            rel = m.group(1).rstrip("./")  # sentence period / brace prefix
            # strip a trailing :lines range that the char class can't include
            if (rel, i) in seen:
                continue
            seen.add((rel, i))
            path = os.path.join(ref, rel)
            if not (os.path.isfile(path) or os.path.isdir(path)):
                yield Finding(
                    "YFM009", mod.rel, i, 0,
                    f"citation /root/reference/{m.group(1)} does not exist "
                    f"under {ref} — fix the path (typo'd citations are "
                    f"silent provenance rot)")
