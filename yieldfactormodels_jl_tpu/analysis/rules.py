"""graftlint rules YFM001–YFM011 (rule table in docs/DESIGN.md §15/§18).

Each rule is a small function over a parsed :class:`~.engine.SourceModule`
(or the whole module list for project-scope rules) registered via
:func:`~.engine.rule`.  Rules only *report* — suppression (pragmas) and
grandfathering (baseline) are the engine's job, so a rule never needs its
own escape hatch.
"""

from __future__ import annotations

import ast
import os
import re
from functools import lru_cache
from typing import Iterable, List, Optional

from .engine import (Finding, JIT_ENTRY, LintConfig, SourceModule, call_name,
                     dotted_name, enclosing_functions, iter_py_files,
                     names_reaching_return, raised_name, rule)


def _finding(rule_id: str, mod: SourceModule, node, message: str) -> Finding:
    return Finding(rule_id, mod.rel, getattr(node, "lineno", 1),
                   getattr(node, "col_offset", 0), message)


# ---------------------------------------------------------------------------
# YFM001 — sentinel discipline
# ---------------------------------------------------------------------------

@rule("YFM001", "sentinel-discipline",
      "no `raise` reachable inside kernel/scan bodies — failures are "
      "sentinels (−Inf loss, NaN moments) plus a taxonomy code")
def yfm001_sentinel_discipline(mod: SourceModule,
                               config: LintConfig) -> Iterable[Finding]:
    if not config.in_package(mod.rel):
        return
    kernel = config.is_kernel(mod.rel)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Raise):
            continue
        depth = mod.func_depth(node)
        name = raised_name(node)
        if kernel:
            # historical kernel-module semantics (tests/test_conventions.py):
            # any nested raise is a traced-body raise; top-level raises must
            # be trace-time validation classes
            if depth >= 2:
                yield _finding(
                    "YFM001", mod, node,
                    "raise inside a nested function (scan/kernel body) — "
                    "use the −Inf/NaN sentinel + taxonomy code instead")
            elif name not in config.raise_whitelist:
                yield _finding(
                    "YFM001", mod, node,
                    f"raises {name or '<bare>'} — only trace-time validation "
                    f"({sorted(config.raise_whitelist)}) is allowed in "
                    f"kernel modules")
            continue
        marker = mod.jit_marker(node)
        if marker is None:
            continue
        scope, kind = marker
        # a whitelisted validation raise sitting directly in a JIT-entry
        # function fires at trace time (shape/config checks) — allowed;
        # anything inside a traced body, nested closure, or of a
        # non-whitelisted class is a sentinel violation
        immediate = mod.func_depth(node) == mod.func_depth(scope) + 1 \
            if not isinstance(scope, ast.Lambda) else False
        if kind == JIT_ENTRY and immediate and name in config.raise_whitelist:
            continue
        yield _finding(
            "YFM001", mod, node,
            f"raise {name or '<bare>'} inside a jit context "
            f"({kind}) — failures inside traced code must be sentinels "
            f"(−Inf/NaN + taxonomy code), not exceptions")


# ---------------------------------------------------------------------------
# YFM002 — donation aliasing (docs/DESIGN.md §14)
# ---------------------------------------------------------------------------

def _donated_indices(expr, scope=None) -> List[int]:
    """Constant indices named by a ``donate_argnums=`` value, unioned across
    conditional branches (``(1, 2) if donate else ()``).  A ``tuple(name)``
    /bare ``name`` spec is resolved against ``scope`` (the enclosing
    function/module) by unioning the name's literal list assignments and
    ``name.append(<const>)`` calls — the scenario-lattice build-a-list
    idiom; an over-approximation is fine (extra indices only tighten the
    check)."""
    out: List[int] = []
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        out.append(expr.value)
    elif isinstance(expr, (ast.Tuple, ast.List)):
        for el in expr.elts:
            out.extend(_donated_indices(el, scope))
    elif isinstance(expr, ast.IfExp):
        out.extend(_donated_indices(expr.body, scope))
        out.extend(_donated_indices(expr.orelse, scope))
    elif isinstance(expr, ast.Call) and dotted_name(expr.func) in (
            "tuple", "list") and len(expr.args) == 1:
        out.extend(_donated_indices(expr.args[0], scope))
    elif isinstance(expr, ast.Name) and scope is not None:
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in node.targets) and \
                    isinstance(node.value, (ast.Tuple, ast.List)):
                out.extend(_donated_indices(node.value))
            elif isinstance(node, ast.Call) and \
                    dotted_name(node.func) == f"{expr.id}.append":
                for a in node.args:
                    out.extend(_donated_indices(a))
    return out


def _local_defs(mod: SourceModule):
    defs = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    return defs


def _check_donation(mod, site_node, fn, indices) -> Iterable[Finding]:
    args = fn.args
    params = [a.arg for a in args.posonlyargs + args.args]
    reach = names_reaching_return(fn)
    for idx in sorted(set(indices)):
        if idx >= len(params):
            yield _finding(
                "YFM002", mod, site_node,
                f"donate_argnums index {idx} is out of range for "
                f"{getattr(fn, 'name', '<lambda>')}({', '.join(params)})")
            continue
        pname = params[idx]
        if pname not in reach:
            yield _finding(
                "YFM002", mod, site_node,
                f"donated argument {idx} ({pname!r}) never flows into a "
                f"returned value of {getattr(fn, 'name', '<lambda>')} — "
                f"XLA will silently drop the donation (no aliasing, no "
                f"reuse); pass it through to a shape-matched output "
                f"(docs/DESIGN.md §14)")


def _donate_kw(call: ast.Call):
    return next((k for k in call.keywords
                 if k.arg in ("donate_argnums", "donate_argnames")), None)


@rule("YFM002", "donation-aliasing",
      "every donate_argnums input must flow into an output — XLA silently "
      "drops a donated buffer whose contents are dead")
def yfm002_donation_aliasing(mod: SourceModule,
                             config: LintConfig) -> Iterable[Finding]:
    if not config.in_package(mod.rel):
        return
    defs = None
    for node in ast.walk(mod.tree):
        # decorator form: @partial(jax.jit, donate_argnums=...) / @jax.jit(...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and _donate_kw(dec) is not None:
                    indices = _donated_indices(_donate_kw(dec).value,
                                               scope=mod.tree)
                    yield from _check_donation(mod, dec, node, indices)
            continue
        if not isinstance(node, ast.Call):
            continue
        kw = _donate_kw(node)
        if kw is None or not node.args:
            continue
        # resolve a dynamic spec (a Name / tuple(name) built with literal
        # appends) against the innermost enclosing function, else the module
        chain = enclosing_functions(node, mod.parents)
        scope = chain[0] if chain else mod.tree
        indices = _donated_indices(kw.value, scope=scope)
        target = node.args[0]
        fn = None
        if isinstance(target, ast.Lambda):
            fn = target
        elif isinstance(target, ast.Name):
            if defs is None:
                defs = _local_defs(mod)
            fn = defs.get(target.id)
        if fn is None:
            continue  # non-local callee: not analyzable statically
        yield from _check_donation(mod, node, fn, indices)


# ---------------------------------------------------------------------------
# YFM003 — engine-cache idiom order
# ---------------------------------------------------------------------------

def _dec_name(dec) -> str:
    target = dec.func if isinstance(dec, ast.Call) else dec
    return dotted_name(target).split(".")[-1]


@rule("YFM003", "cache-idiom-order",
      "@register_engine_cache must sit directly above @lru_cache so the "
      "registrar holds the cache-clearable wrapper")
def yfm003_cache_idiom(mod: SourceModule,
                       config: LintConfig) -> Iterable[Finding]:
    if not config.in_package(mod.rel):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        names = [_dec_name(d) for d in node.decorator_list]
        if "register_engine_cache" not in names:
            continue
        reg = names.index("register_engine_cache")
        if "lru_cache" not in names:
            yield _finding(
                "YFM003", mod, node,
                f"{node.name}: @register_engine_cache without @lru_cache — "
                f"the registrar must receive a cache_clear-able wrapper")
        elif names.index("lru_cache") < reg:
            yield _finding(
                "YFM003", mod, node,
                f"{node.name}: decorator order is @lru_cache above "
                f"@register_engine_cache — swap them (cache under the "
                f"registrar) or engine switches leave stale traces alive")


# ---------------------------------------------------------------------------
# YFM004 — host impurity inside jit contexts
# ---------------------------------------------------------------------------

#: host-side calls that burn into the trace (stale value) or fire once per
#: trace instead of once per run — banned inside jit contexts
_HOST_CALLS = frozenset({
    "print", "input", "open",
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "time.sleep", "os.getenv", "os.urandom", "datetime.now",
    "datetime.datetime.now", "datetime.utcnow", "datetime.datetime.utcnow",
})
_HOST_PREFIXES = ("np.random.", "numpy.random.", "random.")
#: the documented trace-counter idiom (config.make_trace_counter): ONE host
#: call at the top of a to-be-jitted body, counting actual (re)traces
_ALLOWED = frozenset({"note_trace"})


@rule("YFM004", "host-impurity-in-jit",
      "no host-side effects (time/np.random/print/os.environ) inside jitted "
      "bodies — they burn into the trace instead of running per call")
def yfm004_host_impurity(mod: SourceModule,
                         config: LintConfig) -> Iterable[Finding]:
    if not config.in_package(mod.rel):
        return
    kernel = config.is_kernel(mod.rel)

    def in_context(node) -> bool:
        if mod.jit_marker(node) is not None:
            return True
        # kernel modules: every nested function is a traced body
        return kernel and mod.func_depth(node) >= 2

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if not name or name.split(".")[-1] in _ALLOWED:
                continue
            if name in _HOST_CALLS or name.startswith(_HOST_PREFIXES):
                if in_context(node):
                    yield _finding(
                        "YFM004", mod, node,
                        f"host call {name}() inside a jit context — its "
                        f"value/effect is frozen at trace time; hoist it to "
                        f"the driver layer")
        elif isinstance(node, ast.Attribute):
            if dotted_name(node) == "os.environ" and in_context(node):
                yield _finding(
                    "YFM004", mod, node,
                    "os.environ read inside a jit context — env knobs are "
                    "trace-time constants; read them in the builder, not "
                    "the traced body")


# ---------------------------------------------------------------------------
# YFM005 — atomic publish (tmp + os.replace)
# ---------------------------------------------------------------------------

_WRITE_MODE = re.compile(r"[wax]")


def _is_write_channel(node: ast.Call) -> bool:
    name = call_name(node)
    if name.split(".")[-1] == "savetxt":
        return True
    if name.split(".")[-1] in ("write_text", "write_bytes"):
        return True
    if name.split(".")[-1] == "open" or name == "open":
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for k in node.keywords:
            if k.arg == "mode":
                mode = k.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return bool(_WRITE_MODE.search(mode.value))
        return False
    return False


def _write_target(node: ast.Call):
    """The path expression a write channel writes to."""
    tail = call_name(node).split(".")[-1]
    if tail in ("write_text", "write_bytes"):
        return node.func.value  # the path object
    return node.args[0] if node.args else None


def _expr_tokens(expr):
    """(names, string constants) appearing anywhere in an expression."""
    names = {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}
    names |= {n.attr for n in ast.walk(expr) if isinstance(n, ast.Attribute)}
    strs = [n.value for n in ast.walk(expr)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]
    return names, strs


@rule("YFM005", "atomic-publish",
      "writes under orchestration/ and persistence/ publish via "
      "writer-unique tmp + os.replace — a torn file must be unobservable")
def yfm005_atomic_publish(mod: SourceModule,
                          config: LintConfig) -> Iterable[Finding]:
    rel = mod.rel.replace(os.sep, "/")
    if not any(rel.startswith(d + "/") for d in config.atomic_dirs):
        return
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and _is_write_channel(node)):
            continue
        funcs = enclosing_functions(node, mod.parents)
        # the WRITTEN path must be the buffer a same-function os.replace/
        # os.link later publishes (name overlap with the publish's source
        # arg, or a visibly tmp-suffixed expression) — an unrelated atomic
        # publish elsewhere in the function must not vouch for this write
        publish_names: set = set()
        for fn in funcs[:1]:  # innermost enclosing function owns the publish
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) and sub.args and \
                        call_name(sub) in ("os.replace", "os.link"):
                    publish_names |= _expr_tokens(sub.args[0])[0]
        target = _write_target(node)
        target_ok = False
        if target is not None:
            names, strs = _expr_tokens(target)
            target_ok = bool(names & publish_names) or \
                any("tmp" in n.lower() for n in names) or \
                any(".tmp" in s for s in strs)
        if not funcs or not publish_names or not target_ok:
            yield _finding(
                "YFM005", mod, node,
                f"{call_name(node)}() writes a shard/DB/artifact path that "
                f"is not a tmp buffer published by a same-function "
                f"os.replace — build in a writer-unique tmp file and "
                f"publish atomically (tmp + os.replace)")


# ---------------------------------------------------------------------------
# YFM006 — env knobs documented in CLAUDE.md
# ---------------------------------------------------------------------------

_YFM_KNOB = re.compile(r"\bYFM_[A-Z0-9_]+\b")
_BENCH_KNOB = re.compile(r"\bBENCH_[A-Z0-9_]+\b")


def claude_md_text(config: LintConfig) -> str:
    path = config.abspath(config.claude_md)
    if not os.path.isfile(path):
        return ""
    # memoize on (path, mtime): one read per lint pass instead of one per
    # module, while fixture tests that rewrite the doc stay correct
    return _read_cached(path, os.stat(path).st_mtime_ns)


@lru_cache(maxsize=8)
def _read_cached(path: str, _mtime_ns: int) -> str:
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def knob_occurrences(mod: SourceModule, bench: bool):
    """(knob, lineno) pairs for every YFM_* — and, in bench-layer files,
    BENCH_* — name in the source (comments and strings included: a knob
    mentioned anywhere must be discoverable in CLAUDE.md)."""
    for i, line in enumerate(mod.source.splitlines(), start=1):
        for m in _YFM_KNOB.finditer(line):
            yield m.group(0), i
        if bench:
            for m in _BENCH_KNOB.finditer(line):
                yield m.group(0), i


@rule("YFM006", "env-knob-docs",
      "every YFM_*/BENCH_* knob referenced in source must be documented in "
      "CLAUDE.md — an undocumented knob is a silent behavior switch")
def yfm006_env_knob_docs(mod: SourceModule,
                         config: LintConfig) -> Iterable[Finding]:
    rel = mod.rel.replace(os.sep, "/")
    bench = config.matches(rel, config.bench_files)
    if not (bench or config.in_package(rel)):
        return
    # exact-token membership, not substring containment: a knob that is a
    # proper prefix of a documented one (e.g. the lock knob vs its _TTL
    # variant) must not pass on the longer name's substring
    doc = claude_md_text(config)
    documented = set(_YFM_KNOB.findall(doc)) | set(_BENCH_KNOB.findall(doc))
    seen = set()  # report each undocumented knob once per file
    for knob, line in knob_occurrences(mod, bench):
        if knob in documented or knob in seen:
            continue
        seen.add(knob)
        bullet = ("the Benchmarks bullet in CLAUDE.md's Commands"
                  if knob.startswith("BENCH_")
                  else "the env-knob bullets in CLAUDE.md's Conventions")
        yield Finding("YFM006", mod.rel, line, 0,
                      f"undocumented env knob {knob} — add it to {bullet}")


# ---------------------------------------------------------------------------
# YFM007 — every registered engine has oracle-backed parity coverage
# ---------------------------------------------------------------------------

#: engine registries in config.py whose every entry must be oracle-backed —
#: the Kalman loglik engines, the SLR linearization rules, the score-driven
#: engines, the second-order (Newton HVP) engines and the
#: amortized-estimation surrogate architectures share one parity contract
_ENGINE_REGISTRIES = ("KALMAN_ENGINES", "SLR_ENGINES", "MSED_ENGINES",
                      "NEWTON_ENGINES", "AMORTIZER_ENGINES")


def kalman_engines_static(config: LintConfig):
    """(engines tuple, lineno) parsed from config.py's AST — the linter must
    not import the package (that would pull jax).  Collects every registry
    named in ``_ENGINE_REGISTRIES`` (a missing registry contributes
    nothing, so older trees still lint)."""
    path = config.abspath(config.config_module)
    if not os.path.isfile(path):
        return (), 1
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    engines: list = []
    lineno = 1
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id in _ENGINE_REGISTRIES
                for t in node.targets):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                engines.extend(el.value for el in node.value.elts
                               if isinstance(el, ast.Constant))
                if lineno == 1:
                    lineno = node.lineno
    return tuple(engines), lineno


def oracle_backed_test_strings(config: LintConfig):
    """test-module name → set of string constants, for every test module
    that imports tests/oracle.py (the independent NumPy loops every numeric
    kernel must be pinned against)."""
    tdir = config.abspath(config.tests_dir)
    out = {}
    if not os.path.isdir(tdir):
        return out
    for path in iter_py_files(tdir):
        name = os.path.basename(path)
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        uses_oracle = False
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                    node.module.split(".")[-1] == "oracle"
                    or any(a.name == "oracle" for a in node.names)):
                uses_oracle = True
            if isinstance(node, ast.Import) and any(
                    a.name.split(".")[-1] == "oracle" for a in node.names):
                uses_oracle = True
        if uses_oracle:
            out[name] = {n.value for n in ast.walk(tree)
                         if isinstance(n, ast.Constant)
                         and isinstance(n.value, str)}
    return out


def declared_program_names(config: LintConfig):
    """``name → (rel, lineno)`` of every ``ModelProgram(name="...")``
    literal declaration in the package — the program layer's analogue of
    the engine registries: a shipped declarative model carries the same
    oracle-parity contract as a hand-ported family.  Scanned from disk
    like :func:`kalman_engines_static` (the coverage contract is
    project-global, independent of the linted subset); declarations in
    tests/fixtures don't count — only the package ships programs."""
    out: dict = {}
    pkg = config.abspath(config.package)
    if not os.path.isdir(pkg):
        return out
    for path in iter_py_files(pkg):
        rel = os.path.relpath(path, config.root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and dotted_name(
                    node.func).split(".")[-1] == "ModelProgram"):
                continue
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    out.setdefault(kw.value.value, (rel, node.lineno))
    return out


@rule("YFM007", "engine-oracle-parity",
      "every config.KALMAN_ENGINES entry and every declared ModelProgram "
      "name must be named in an oracle-importing test module — no engine "
      "or shipped program without parity", scope="project")
def yfm007_engine_parity(modules, config: LintConfig) -> Iterable[Finding]:
    engines, lineno = kalman_engines_static(config)
    programs = declared_program_names(config)
    if not engines and not programs:
        return
    strings = oracle_backed_test_strings(config)
    for engine in engines:
        if not any(engine in ss for ss in strings.values()):
            yield Finding(
                "YFM007", config.config_module, lineno, 0,
                f"engine {engine!r} has no oracle-backed parity coverage — "
                f"add a parity test against tests/oracle.py that names it "
                f"(see test_assoc_estimation.test_engine_oracle_parity_"
                f"with_nan_gap)")
    for name, (rel, prog_lineno) in sorted(programs.items()):
        if not any(name in ss for ss in strings.values()):
            yield Finding(
                "YFM007", rel, prog_lineno, 0,
                f"program {name!r} has no oracle-backed parity coverage — "
                f"a shipped ModelProgram needs a parity test against "
                f"tests/oracle.py that names it (see tests/test_program.py)")


# ---------------------------------------------------------------------------
# YFM008 — request-path hygiene (DESIGN §12)
# ---------------------------------------------------------------------------

_UNBOUNDED_QUEUES = ("queue.Queue", "Queue", "queue.LifoQueue",
                     "queue.PriorityQueue", "queue.SimpleQueue")

#: the per-request ROUTING functions (gateway pump → batch formation →
#: shard routing): work here happens BEFORE the flush, once per request, so
#: a host gather is an O(registry)-scaling tax the response boundary never
#: pays back.  Host transfer belongs in the collect/response functions only
#: (DESIGN §16 routing state machine).
_ROUTING_FUNCS = frozenset({"pump", "_pump_locked", "_dispatch_updates",
                            "_submit_read", "_route_waves", "_admit",
                            # tier promotion/eviction routing (DESIGN §21):
                            # deciding WHAT moves between tiers is per-request
                            # planning work; the actual freeze/thaw transfer
                            # belongs in the batched flush boundaries only
                            "_prepare_batch", "_promote_plan", "_demote_plan",
                            "prepare_reads", "_account",
                            # stream-hub refresh/dispatch (DESIGN §23): dirty
                            # marking, wave staging and the donated refresh
                            # launch run on the update pump path; the pending
                            # → good promotion and the fan slices gather only
                            # at the answer boundary (streams.fan)
                            "_refresh_wave", "_stage_wave", "notify_updated",
                            "_mark_dirty",
                            # shard-loss rebuild planning (DESIGN §24): which
                            # keys lived on the lost shard and what each
                            # replays is per-key dict routing; the fresh
                            # arrays, slot writes and journal replay happen
                            # in the rebuild flush (_rebuild_shard)
                            "_rebuild_plan"})

#: calls that move device values to host (or force a device sync)
_HOST_TRANSFERS = ("jax.device_get", "device_get", "np.asarray", "np.array",
                   "numpy.asarray", "numpy.array", "jax.block_until_ready")


@rule("YFM008", "request-path-hygiene",
      "no unbounded queue.Queue(), no bare time.sleep, and no host "
      "gather/sync inside the per-request routing functions under serving/ "
      "— backpressure and O(batch) host traffic must not regress silently")
def yfm008_request_path(mod: SourceModule,
                        config: LintConfig) -> Iterable[Finding]:
    rel = mod.rel.replace(os.sep, "/")
    if not rel.startswith(config.serving_dir.rstrip("/") + "/"):
        return
    routing_spans = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in _ROUTING_FUNCS:
            routing_spans.append((node.name, node.lineno,
                                  node.end_lineno or node.lineno))
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in ("time.sleep", "sleep"):
            yield _finding(
                "YFM008", mod, node,
                f"bare {name}() on the request path — use an interruptible "
                f"Event/Condition wait")
        if name in _UNBOUNDED_QUEUES:
            bounded = bool(node.args) or any(
                kw.arg == "maxsize" for kw in node.keywords)
            if not bounded:
                yield _finding(
                    "YFM008", mod, node,
                    f"unbounded {name}() on the request path — give it a "
                    f"maxsize (backpressure)")
        if name and (name in _HOST_TRANSFERS
                     or name.split(".")[-1] in ("device_get",
                                                "block_until_ready")):
            lineno = getattr(node, "lineno", 0)
            for fname, lo, hi in routing_spans:
                if lo <= lineno <= hi:
                    yield _finding(
                        "YFM008", mod, node,
                        f"host transfer {name}() inside routing function "
                        f"{fname}() — the per-request routing path must "
                        f"stay device-side; gather only at the response "
                        f"boundary (collect/finish)")
                    break


# ---------------------------------------------------------------------------
# YFM009 — docstring citations must point at real reference files
# ---------------------------------------------------------------------------

_CITATION = re.compile(r"/root/reference/([A-Za-z0-9_./-]+)")


# ---------------------------------------------------------------------------
# YFM010 — lock discipline in the threaded host layer (DESIGN §18)
# ---------------------------------------------------------------------------

_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
})

#: method calls that mutate their receiver in place (dict/list/set/deque
#: surface) — the writes a plain assignment scan would miss
_INPLACE_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard", "clear",
})

#: construction-time methods: single-threaded by contract, writes there are
#: neither locked nor unlocked evidence
_CTOR_METHODS = frozenset({"__init__", "__new__", "__post_init__"})


def _self_attr1(expr) -> Optional[str]:
    """Depth-1 ``self`` attribute a write targets: ``self.a``, ``self.a[k]``,
    ``self.a[k][j]`` → ``'a'``; ``self.a.b`` (a write into a sub-object,
    ambiguous ownership) and non-self bases → ``None``."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


def _under_self_lock(node, parents, locks: frozenset) -> bool:
    """Whether ``node`` sits inside ``with self.<lock>:`` for ANY of the
    class's lock attributes.  Any-lock on purpose: guarding one attribute
    with two different locks is a (rare) design choice the gateway makes
    deliberately (``_cv`` wraps ``_lock``); the bug class YFM010 hunts is
    *no lock at all* on one path while another path locks."""
    p = parents.get(node)
    while p is not None:
        if isinstance(p, (ast.With, ast.AsyncWith)):
            for item in p.items:
                name = dotted_name(item.context_expr)
                if name.startswith("self.") and name[5:] in locks:
                    return True
        p = parents.get(p)
    return False


def _iter_self_writes(method):
    """(node, attr) pairs for every depth-1 ``self`` attribute mutation in
    ``method``: assignments (plain/aug/ann, incl. subscript targets),
    ``del self.a[...]``, and in-place mutator calls."""
    for node in ast.walk(method):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                attr = _self_attr1(t)
                if attr:
                    yield node, attr
        elif isinstance(node, ast.AugAssign) \
                or (isinstance(node, ast.AnnAssign)
                    and node.value is not None):
            # a bare `self._x: SomeType` annotation (no value) declares,
            # it does not mutate
            attr = _self_attr1(node.target)
            if attr:
                yield node, attr
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr1(t)
                if attr:
                    yield node, attr
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _INPLACE_MUTATORS:
            attr = _self_attr1(node.func.value)
            if attr:
                yield node, attr


@rule("YFM010", "lock-discipline",
      "in serving/ and orchestration/ classes that create a threading lock, "
      "an instance attribute mutated under `with self._lock` somewhere must "
      "not also be mutated with no lock held elsewhere — the silent-race "
      "bug class the PR-3 thread-local report and PR-8 registry RLock "
      "patched by hand")
def yfm010_lock_discipline(mod: SourceModule,
                           config: LintConfig) -> Iterable[Finding]:
    rel = mod.rel.replace(os.sep, "/")
    if not any(rel.startswith(d.rstrip("/") + "/") for d in config.lock_dirs):
        return
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        def _lock_attrs(node):
            # plain AND annotated assignments create locks — missing
            # AnnAssign would silently disable the rule for a class that
            # writes `self._lock: threading.Lock = threading.Lock()`
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign):
                targets, value = [node.target], node.value
            else:
                return
            if isinstance(value, ast.Call) \
                    and call_name(value) in _LOCK_CTORS:
                for t in targets:
                    attr = _self_attr1(t)
                    if attr:
                        yield attr

        locks = frozenset(attr for node in ast.walk(cls)
                          for attr in _lock_attrs(node))
        if not locks:
            continue
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        # a PRIVATE method every same-class call site invokes while holding
        # a lock runs locked by construction (`_rebuild_slot` under the
        # store's `_collect` lock, the `pump`→`_pump_locked`→dispatch
        # convention) — closed to a fixed point so locked-ness propagates
        # down call chains
        method_names = {m.name for m in methods}

        def owner(node):
            for fn in enclosing_functions(node, mod.parents):
                if getattr(fn, "name", None) in method_names:
                    return fn.name
            return None

        sites = {m.name: [c for c in ast.walk(cls)
                          if isinstance(c, ast.Call)
                          and dotted_name(c.func) == f"self.{m.name}"]
                 for m in methods
                 if m.name.startswith("_") and m.name not in _CTOR_METHODS}

        # calls FROM construction-time code are single-threaded by the same
        # contract that exempts ctor bodies — neither locked nor unlocked
        # evidence; a private method reachable ONLY from ctors inherits the
        # exemption wholesale (the `__init__ → self._reset()` chain).  Both
        # closures run as GREATEST fixed points (start optimistic, strike
        # any method with a disqualifying call site) so recursive and
        # mutually-recursive chains converge — a least fixed point could
        # never admit `pump() { with lock: self._retry() }` with a
        # self-recursive `_retry`, flagging correct code
        ctor_only: set = {name for name, calls in sites.items() if calls}
        changed = True
        while changed:
            changed = False
            for name in sorted(ctor_only):
                if not all(owner(c) in _CTOR_METHODS or owner(c) in ctor_only
                           for c in sites[name]):
                    ctor_only.discard(name)
                    changed = True

        runtime_calls = {name: [c for c in calls
                                if owner(c) not in _CTOR_METHODS
                                and owner(c) not in ctor_only]
                         for name, calls in sites.items()}
        locked_methods: set = {name for name, rc in runtime_calls.items()
                               if rc and name not in ctor_only}
        changed = True
        while changed:
            changed = False
            for name in sorted(locked_methods):
                if not all(_under_self_lock(c, mod.parents, locks)
                           or owner(c) in locked_methods
                           for c in runtime_calls[name]):
                    locked_methods.discard(name)
                    changed = True
        locked: dict = {}
        unlocked: dict = {}
        for m in methods:
            if m.name in _CTOR_METHODS or m.name in ctor_only:
                continue
            for node, attr in _iter_self_writes(m):
                if attr in locks:
                    continue
                if _under_self_lock(node, mod.parents, locks) \
                        or m.name in locked_methods:
                    locked.setdefault(attr, []).append(node)
                else:
                    unlocked.setdefault(attr, []).append(node)
        for attr in sorted(set(locked) & set(unlocked)):
            seen_lines = set()
            for node in unlocked[attr]:
                if node.lineno in seen_lines:
                    continue
                seen_lines.add(node.lineno)
                yield _finding(
                    "YFM010", mod, node,
                    f"{cls.name}.{attr} is mutated under `with self.<lock>` "
                    f"elsewhere (locks: {sorted(locks)}) but written here "
                    f"with no lock held — a silent race; take the lock, or "
                    f"pragma with the invariant that makes this safe")


# ---------------------------------------------------------------------------
# YFM011 — IR-audit manifest coverage (DESIGN §18)
# ---------------------------------------------------------------------------

def _manifest_keys(config: LintConfig):
    """``key → lineno`` of every ``case("...")``/``skip_case("...")``
    registration in the manifest module, or ``None`` when the manifest does
    not exist (pre-tier-2 trees and fixture repos lint clean)."""
    path = config.abspath(config.manifest_module)
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as fh:
        try:
            tree = ast.parse(fh.read(), filename=path)
        except SyntaxError:
            return None
    keys: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted_name(
                node.func).split(".")[-1] in ("case", "skip_case") \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            keys.setdefault(node.args[0].value, node.lineno)
    return keys


def _registered_builders(config: LintConfig):
    """``key → (rel, lineno)`` for every ``@register_engine_cache`` builder
    in the package, discovered from disk (like YFM007's registry read: the
    coverage contract is project-global, independent of the linted subset)."""
    out: dict = {}
    pkg = config.abspath(config.package)
    prefix = config.package + "/analysis/"
    for path in iter_py_files(pkg):
        rel = os.path.relpath(path, config.root).replace(os.sep, "/")
        if rel.startswith(prefix):
            continue
        with open(path, encoding="utf-8") as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError:
                continue
        dotted = rel[len(config.package) + 1:-3].replace("/", ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[: -len(".__init__")]
        # `from ..config import register_engine_cache as _rec` must not
        # hide a builder from the coverage census (the runtime census in
        # ir.py would still see it — the tiers must observe the same set)
        names = {"register_engine_cache"}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "register_engine_cache" and a.asname:
                        names.add(a.asname)
        # module-level defs only: the runtime census keys builders by
        # __qualname__, which equals the bare name ONLY at top level — a
        # nested builder would make the two tiers demand contradictory
        # manifest keys (the runtime census still covers it by qualname)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(_dec_name(d) in names
                            for d in node.decorator_list):
                out[f"{dotted}.{node.name}"] = (rel, node.lineno)
    return out


@rule("YFM011", "ir-manifest-coverage",
      "every @register_engine_cache builder must have a case/skip_case "
      "entry in analysis/manifest.py (and every manifest key must name a "
      "real builder) — tier-2 IR coverage grows with the code instead of "
      "rotting", scope="project")
def yfm011_manifest_coverage(modules, config: LintConfig) -> Iterable[Finding]:
    keys = _manifest_keys(config)
    if keys is None:
        return
    builders = _registered_builders(config)
    for key, (rel, lineno) in sorted(builders.items()):
        if key not in keys:
            yield Finding(
                "YFM011", rel, lineno, 0,
                f"builder {key} has no IR-audit manifest entry — add a "
                f"case()/skip_case() to analysis/manifest.py so `--ir` "
                f"covers it (docs/DESIGN.md §18)")
    for key, lineno in sorted(keys.items()):
        if key not in builders:
            yield Finding(
                "YFM011", config.manifest_module, lineno, 0,
                f"manifest entry {key!r} names no registered engine-cache "
                f"builder — prune the stale key or fix the name")


@rule("YFM009", "citation-exists",
      "docstring citations of /root/reference/<file> must name files that "
      "exist — a typo'd citation is unverifiable parity provenance")
def yfm009_citations(mod: SourceModule,
                     config: LintConfig) -> Iterable[Finding]:
    ref = config.reference_root
    if not os.path.isdir(ref):
        return  # reference tree absent on this box: nothing verifiable
    if not config.in_package(mod.rel):
        return
    seen = set()
    for i, line in enumerate(mod.source.splitlines(), start=1):
        for m in _CITATION.finditer(line):
            rel = m.group(1).rstrip("./")  # sentence period / brace prefix
            # strip a trailing :lines range that the char class can't include
            if (rel, i) in seen:
                continue
            seen.add((rel, i))
            path = os.path.join(ref, rel)
            if not (os.path.isfile(path) or os.path.isdir(path)):
                yield Finding(
                    "YFM009", mod.rel, i, 0,
                    f"citation /root/reference/{m.group(1)} does not exist "
                    f"under {ref} — fix the path (typo'd citations are "
                    f"silent provenance rot)")
