"""graftlint: rule-based AST static analysis for the repo's jit/TPU
invariants (docs/DESIGN.md §15).

One parse per file, shared scope/decorator/call-name resolution, named rules
YFM001–YFM009, inline ``# yfmlint: disable=YFM00x -- reason`` pragmas, and a
committed baseline for deliberately-kept findings.  Import-light on purpose:
nothing in this package imports jax (enforced by
tests/test_lint.py::test_engine_imports_without_jax), so the CLI runs in
about a second on a CPU-only box without touching backend init.

CLI: ``python -m yieldfactormodels_jl_tpu.analysis --format json|text
[--changed-only]``.
"""

from .baseline import load_baseline, save_baseline
from .engine import (Finding, JIT_ENTRY, JIT_WRAPPERS, LintConfig,
                     LintResult, RULES, SourceModule, TRACE_BODY,
                     TRACE_BODY_WRAPPERS, call_name, changed_files,
                     detect_jit_contexts, dotted_name, enclosing_functions,
                     func_depth, iter_py_files, names_reaching_return,
                     parent_map, raised_name, rule, run_lint)
from . import rules as rules  # registers YFM001–YFM009 on import

__all__ = [
    "Finding", "JIT_ENTRY", "JIT_WRAPPERS", "LintConfig", "LintResult",
    "RULES", "SourceModule", "TRACE_BODY", "TRACE_BODY_WRAPPERS",
    "call_name", "changed_files", "detect_jit_contexts", "dotted_name",
    "enclosing_functions", "func_depth", "iter_py_files", "load_baseline",
    "names_reaching_return", "parent_map", "raised_name", "rule", "rules",
    "run_lint", "save_baseline",
]
