"""graftlint: rule-based static analysis for the repo's jit/TPU invariants.

Tier 1 (default; docs/DESIGN.md §15): one AST parse per file, shared
scope/decorator/call-name resolution, named rules YFM001–YFM011, inline
``# yfmlint: disable=YFM00x -- reason`` pragmas, and a committed baseline
for deliberately-kept findings.  Import-light on purpose: importing this
package pulls NO jax (enforced by
tests/test_lint.py::test_engine_imports_without_jax), so the CLI runs in
about a second on a CPU-only box without touching backend init.

Tier 2 (``--ir``; docs/DESIGN.md §18): the IR program audit — ``ir.py``
lowers every ``@register_engine_cache`` builder at the shapes
``manifest.py`` declares and checks the compiled artifacts (donation
honored, dtype discipline, host round-trips, lane rule, retrace census).
Only :func:`ir.run_ir` itself imports jax, and only when invoked.

CLI: ``python -m yieldfactormodels_jl_tpu.analysis --format json|text|sarif
[--changed-only | --ir]``.
"""

from .baseline import load_baseline, save_baseline, stale_entries
from .engine import (Finding, JIT_ENTRY, JIT_WRAPPERS, LintConfig,
                     LintResult, RULES, SourceModule, TRACE_BODY,
                     TRACE_BODY_WRAPPERS, call_name, changed_files,
                     detect_jit_contexts, dotted_name, enclosing_functions,
                     func_depth, iter_py_files, names_reaching_return,
                     parent_map, raised_name, rule, run_lint)
from .ir import IR_RULES, IRResult, run_ir  # jax-free until run_ir is called
from . import rules as rules  # registers YFM001–YFM011 on import

__all__ = [
    "Finding", "IR_RULES", "IRResult", "JIT_ENTRY", "JIT_WRAPPERS",
    "LintConfig", "LintResult", "RULES", "SourceModule", "TRACE_BODY",
    "TRACE_BODY_WRAPPERS", "call_name", "changed_files",
    "detect_jit_contexts", "dotted_name", "enclosing_functions",
    "func_depth", "iter_py_files", "load_baseline",
    "names_reaching_return", "parent_map", "raised_name", "rule", "rules",
    "run_ir", "run_lint", "save_baseline", "stale_entries",
]
