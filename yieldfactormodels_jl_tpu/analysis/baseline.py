"""Committed lint baseline: grandfathered findings we deliberately keep.

The baseline is a JSON file of ``RULE::file::line`` keys.  A finding whose
key appears here is reported under ``baselined`` (visible, never actionable)
so the zero-unsuppressed-findings CI gate stays green while the debt stays
on the books.  ``--write-baseline`` regenerates it from the current
unsuppressed findings — pruning entries that no longer fire and REPORTING
what it dropped (a silently shrinking baseline hides both progress and
typos) — and a plain run warns on stale entries (file gone, line past EOF)
instead of carrying them forever.  An empty baseline is the healthy steady
state.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, Optional, Set, Tuple

VERSION = 1


def parse_key(key: str) -> Optional[Tuple[str, str, int]]:
    """``"RULE::file::line"`` → ``(rule, file, line)``; ``None`` for a
    malformed entry (itself a kind of staleness)."""
    parts = key.split("::")
    if len(parts) != 3:
        return None
    try:
        return parts[0], parts[1], int(parts[2])
    except ValueError:
        return None


def stale_entries(entries: Iterable[str], root: str) -> Dict[str, str]:
    """``key → reason`` for baseline entries that can no longer match any
    finding: malformed keys, files that no longer exist, line numbers past
    the current end of file.  (An entry whose site exists but no longer
    fires is only detectable by a lint run — ``--write-baseline`` prunes
    those and reports them as fixed.)"""
    stale: Dict[str, str] = {}
    line_counts: Dict[str, Optional[int]] = {}
    for key in entries:
        parsed = parse_key(key)
        if parsed is None:
            stale[key] = "malformed entry (want RULE::file::line)"
            continue
        _, rel, line = parsed
        if rel not in line_counts:
            path = os.path.join(root, rel)
            if not os.path.isfile(path):
                line_counts[rel] = None
            else:
                with open(path, encoding="utf-8", errors="replace") as fh:
                    line_counts[rel] = sum(1 for _ in fh)
        n = line_counts[rel]
        if n is None:
            stale[key] = f"{rel} no longer exists"
        elif line > n:
            stale[key] = f"line {line} is past the end of {rel} ({n} lines)"
    return stale


def load_baseline(path: str) -> Set[str]:
    """Set of grandfathered finding keys (empty when the file is absent —
    a missing baseline means nothing is grandfathered, not an error)."""
    if not os.path.isfile(path):
        return set()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("version") != VERSION:
        raise ValueError(
            f"unrecognized baseline format at {path} (want "
            f'{{"version": {VERSION}, "entries": [...]}})')
    return set(data.get("entries", []))


def save_baseline(path: str, findings: Iterable,
                  extra_keys: Iterable[str] = ()) -> int:
    """Atomically write the baseline from findings (tmp + os.replace — the
    same publish discipline the linter enforces on everyone else).
    ``extra_keys`` are preserved verbatim: entries the calling run cannot
    re-observe (the other tier's rules) must never be pruned by it."""
    entries = sorted({f.key() for f in findings} | set(extra_keys))
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"version": VERSION, "entries": entries}, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)
    return len(entries)
