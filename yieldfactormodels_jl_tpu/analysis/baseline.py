"""Committed lint baseline: grandfathered findings we deliberately keep.

The baseline is a JSON file of ``RULE::file::line`` keys.  A finding whose
key appears here is reported under ``baselined`` (visible, never actionable)
so the zero-unsuppressed-findings CI gate stays green while the debt stays
on the books.  ``--write-baseline`` regenerates it from the current
unsuppressed findings; an empty baseline is the healthy steady state.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Set

VERSION = 1


def load_baseline(path: str) -> Set[str]:
    """Set of grandfathered finding keys (empty when the file is absent —
    a missing baseline means nothing is grandfathered, not an error)."""
    if not os.path.isfile(path):
        return set()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("version") != VERSION:
        raise ValueError(
            f"unrecognized baseline format at {path} (want "
            f'{{"version": {VERSION}, "entries": [...]}})')
    return set(data.get("entries", []))


def save_baseline(path: str, findings: Iterable) -> int:
    """Atomically write the baseline from findings (tmp + os.replace — the
    same publish discipline the linter enforces on everyone else)."""
    entries = sorted({f.key() for f in findings})
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"version": VERSION, "entries": entries}, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)
    return len(entries)
