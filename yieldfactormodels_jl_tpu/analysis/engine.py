"""graftlint engine: one ``ast.parse`` per source file, many rules.

The repo's correctness conventions (CLAUDE.md, docs/DESIGN.md §§4/14) used to
be enforced by ad-hoc AST guards scattered across ``tests/test_conventions.py``
and ``tests/test_env_knobs.py``, each with its own file walk, call-name
resolution and non-vacuity boilerplate.  This module is the one shared
implementation: a :class:`SourceModule` wraps a parsed file with cached
parent/pragma/jit-context maps, :func:`run_lint` feeds every module through
every registered rule (``rules.py``) exactly once, and findings flow through
pragma suppression (``# yfmlint: disable=YFM00x -- reason``) and the committed
baseline before anything is reported.

Deliberately jax-free: the linter must be runnable in about a second on a
CPU-only box without touching backend init (see the package ``__init__``'s
lazy import table, which exists so ``python -m yieldfactormodels_jl_tpu
.analysis`` never imports jax).
"""

from __future__ import annotations

import ast
import io
import os
import re
import subprocess
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# shared AST resolution helpers (the layer tests/test_conventions.py used to
# hand-roll; tests now import these)
# ---------------------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def dotted_name(expr) -> str:
    """Dotted name of a Name/Attribute chain: ``'os.environ.get'``; ``''``
    for anything whose base is not a plain Name (subscripts, calls...)."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    """Dotted name of a Call's callee: 'time.sleep', 'queue.Queue', 'Queue'."""
    return dotted_name(node.func)


def raised_name(node: ast.Raise) -> Optional[str]:
    """Class name a ``raise`` statement raises (last attribute segment), or
    ``None`` for a bare ``raise`` / exotic expression."""
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Name):
        return exc.id
    if isinstance(exc, ast.Attribute):
        return exc.attr
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    return parents


def func_depth(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> int:
    """Number of enclosing FunctionDef/AsyncFunctionDef/Lambda scopes."""
    depth = 0
    p = parents.get(node)
    while p is not None:
        if isinstance(p, _FUNC_NODES):
            depth += 1
        p = parents.get(p)
    return depth


def enclosing_functions(node: ast.AST,
                        parents: Dict[ast.AST, ast.AST]) -> List[ast.AST]:
    """Innermost-first chain of enclosing function scopes."""
    chain = []
    p = parents.get(node)
    while p is not None:
        if isinstance(p, _FUNC_NODES):
            chain.append(p)
        p = parents.get(p)
    return chain


def iter_py_files(root: str, *, exclude_dirs: Sequence[str] = ("__pycache__",)
                  ) -> Iterable[str]:
    """Sorted ``.py`` paths under ``root`` (deterministic walk order)."""
    for dirpath, dirnames, names in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in exclude_dirs)
        for name in sorted(names):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


# ---------------------------------------------------------------------------
# jit-context detection
# ---------------------------------------------------------------------------

#: wrappers whose first functional argument is compiled as one program —
#: a function handed to these is a jit context (decorator or call form)
JIT_WRAPPERS = frozenset({
    "jax.jit", "jit", "pjit", "jax.pmap", "pmap",
    "jax.experimental.pjit.pjit",
})

#: wrappers whose body argument runs *traced* (inside someone's trace):
#: scan/loop/branch bodies and vmapped closures — ``raise``/host calls there
#: either fire spuriously at trace time or silently never fire at run time
TRACE_BODY_WRAPPERS = frozenset({
    "lax.scan", "jax.lax.scan", "lax.while_loop", "jax.lax.while_loop",
    "lax.cond", "jax.lax.cond", "lax.fori_loop", "jax.lax.fori_loop",
    "lax.switch", "jax.lax.switch", "lax.map", "jax.lax.map",
    "jax.vmap", "vmap", "jax.checkpoint", "jax.remat", "shard_map",
    "jax.grad", "jax.value_and_grad",
})

#: marker kinds: how a function entered the jit set (whitelisted trace-time
#: validation raises are allowed at the top of a JIT-entry function, never
#: inside a traced body function)
JIT_ENTRY = "jit_entry"
TRACE_BODY = "trace_body"
ENCLOSED = "enclosed"


def _decorator_marks(fn) -> bool:
    for dec in fn.decorator_list:
        target = dec
        if isinstance(dec, ast.Call):
            name = dotted_name(dec.func)
            if name in JIT_WRAPPERS:
                return True
            if name.split(".")[-1] == "partial" and dec.args:
                target = dec.args[0]
        if dotted_name(target) in JIT_WRAPPERS:
            return True
    return False


def detect_jit_contexts(tree: ast.AST,
                        parents: Dict[ast.AST, ast.AST]
                        ) -> Dict[ast.AST, str]:
    """Map of function nodes → marker kind (:data:`JIT_ENTRY` /
    :data:`TRACE_BODY` / :data:`ENCLOSED`).

    Detection is syntactic and local to one module: ``@jax.jit``-family
    decorators (incl. ``@partial(jax.jit, ...)``), functions/lambdas passed by
    name or inline to ``jax.jit(...)``/``pjit``/``pmap`` (jit entries) and to
    ``lax.scan``/``while_loop``/``cond``/``vmap``/... (traced bodies), plus
    every function *nested inside* a marked one (closures run traced too).
    """
    marked: Dict[ast.AST, str] = {}
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
            if _decorator_marks(node):
                marked[node] = JIT_ENTRY

    def mark(expr, kind):
        if isinstance(expr, ast.Lambda):
            marked.setdefault(expr, kind)
        elif isinstance(expr, ast.Name):
            for d in defs_by_name.get(expr.id, ()):
                marked.setdefault(d, kind)
        elif isinstance(expr, (ast.Tuple, ast.List)):
            for el in expr.elts:       # lax.switch branch lists
                mark(el, kind)

    def is_function_valued(expr) -> bool:
        if isinstance(expr, ast.Lambda):
            return True
        if isinstance(expr, ast.Name):
            return expr.id in defs_by_name
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(is_function_valued(el) for el in expr.elts)
        return False

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name in JIT_WRAPPERS and node.args:
            mark(node.args[0], JIT_ENTRY)
        elif name in TRACE_BODY_WRAPPERS:
            # the traced callable is not always args[0]: cond's branches are
            # args[1:3], fori_loop's body is args[2], while_loop traces BOTH
            # cond_fun and body_fun, switch takes a branch list — so mark
            # every function-valued argument (incl. keywords) conservatively
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if is_function_valued(arg):
                    mark(arg, TRACE_BODY)

    # closure rule: everything defined inside a marked function runs traced
    for node in ast.walk(tree):
        if isinstance(node, _FUNC_NODES) and node not in marked:
            if any(p in marked for p in enclosing_functions(node, parents)):
                marked[node] = ENCLOSED
    return marked


# ---------------------------------------------------------------------------
# donation data-flow: which names can reach a function's outputs
# ---------------------------------------------------------------------------

def names_reaching_return(fn) -> set:
    """Over-approximate set of local names whose value can flow into the
    function's return value (backward reachability through assignments).

    Seeds with every Name under a ``return`` (for a Lambda: the body), then
    closes over assignment edges: if an assigned target (including a
    subscript/attribute base like ``out["losses"]``) is reachable, every name
    on the right-hand side becomes reachable.  Used by the donation-aliasing
    rule: a donated parameter whose name never reaches an output is the
    silent-drop shape XLA discards (docs/DESIGN.md §14).
    """
    def expr_names(e) -> set:
        return {n.id for n in ast.walk(e) if isinstance(n, ast.Name)}

    if isinstance(fn, ast.Lambda):
        return expr_names(fn.body)

    reach: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            reach |= expr_names(node.value)

    def target_names(t) -> set:
        if isinstance(t, (ast.Tuple, ast.List)):
            out = set()
            for el in t.elts:
                out |= target_names(el)
            return out
        if isinstance(t, ast.Starred):
            return target_names(t.value)
        if isinstance(t, (ast.Subscript, ast.Attribute)):
            base = dotted_name(t.value if not isinstance(t.value, ast.Subscript)
                               else t.value.value)
            return {base.split(".")[0]} if base else set()
        if isinstance(t, ast.Name):
            return {t.id}
        return set()

    edges: List[Tuple[set, set]] = []  # (targets, rhs names)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            tnames = set()
            for t in node.targets:
                tnames |= target_names(t)
            edges.append((tnames, expr_names(node.value)))
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                edges.append((target_names(node.target),
                              expr_names(node.value)))
        elif isinstance(node, ast.NamedExpr):
            edges.append((target_names(node.target), expr_names(node.value)))
        elif isinstance(node, ast.For):
            edges.append((target_names(node.target), expr_names(node.iter)))

    changed = True
    while changed:
        changed = False
        for targets, rhs in edges:
            if targets & reach and not rhs <= reach:
                reach |= rhs
                changed = True
    return reach


# ---------------------------------------------------------------------------
# findings, pragmas, modules
# ---------------------------------------------------------------------------

PRAGMA_RE = re.compile(
    r"yfmlint:\s*disable=([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
    r"(?:\s*--\s*(.+?))?\s*$")


@dataclass
class Finding:
    rule: str
    file: str            # repo-relative path
    line: int
    col: int
    message: str
    suppressed: bool = False
    suppress_reason: Optional[str] = None  # pragma reason ('' if none given)
    baselined: bool = False

    def key(self) -> str:
        return f"{self.rule}::{self.file}::{self.line}"

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "file": self.file, "line": self.line,
             "col": self.col, "message": self.message}
        if self.suppressed:
            d["suppressed"] = True
            d["suppress_reason"] = self.suppress_reason
        if self.baselined:
            d["baselined"] = True
        return d


class SourceModule:
    """One parsed source file with lazily-built, shared resolution maps —
    every rule sees the same single ``ast.parse``."""

    def __init__(self, path: str, rel: str, source: Optional[str] = None):
        self.path = path
        self.rel = rel
        if source is None:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._pragmas: Optional[Dict[int, Tuple[frozenset, str]]] = None
        self._jit: Optional[Dict[ast.AST, str]] = None

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = parent_map(self.tree)
        return self._parents

    @property
    def jit_contexts(self) -> Dict[ast.AST, str]:
        if self._jit is None:
            self._jit = detect_jit_contexts(self.tree, self.parents)
        return self._jit

    def func_depth(self, node) -> int:
        return func_depth(node, self.parents)

    def jit_marker(self, node) -> Optional[Tuple[ast.AST, str]]:
        """(outermost-marked-scope, marker-kind) when ``node`` sits inside a
        detected jit context, else ``None``."""
        chain = enclosing_functions(node, self.parents)
        for fn in reversed(chain):        # outermost first
            kind = self.jit_contexts.get(fn)
            if kind is not None:
                return fn, kind
        return None

    @property
    def pragmas(self) -> Dict[int, Tuple[frozenset, str]]:
        """line → (rule ids disabled on that line, recorded reason).

        A pragma comment applies to its own line; a pragma on a standalone
        comment line also covers the line directly below it (the usual
        "comment above the offending statement" placement).
        """
        if self._pragmas is None:
            pragmas: Dict[int, Tuple[frozenset, str]] = {}
            try:
                toks = list(tokenize.generate_tokens(
                    io.StringIO(self.source).readline))
            except tokenize.TokenError:
                toks = []
            lines = self.source.splitlines()
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if not m:
                    continue
                ids = frozenset(s.strip() for s in m.group(1).split(","))
                reason = (m.group(2) or "").strip()
                line = tok.start[0]
                pragmas[line] = (ids | pragmas.get(line, (frozenset(), ""))[0],
                                 reason)
                text = lines[line - 1] if line <= len(lines) else ""
                if text.strip().startswith("#"):  # standalone comment line
                    nxt = line + 1
                    pragmas[nxt] = (
                        ids | pragmas.get(nxt, (frozenset(), ""))[0], reason)
            self._pragmas = pragmas
        return self._pragmas

    def suppression_for(self, finding: Finding):
        entry = self.pragmas.get(finding.line)
        if entry and finding.rule in entry[0]:
            return entry[1]
        return None


# ---------------------------------------------------------------------------
# config + rule registry
# ---------------------------------------------------------------------------

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


@dataclass
class LintConfig:
    """File sets and repo anchors the rules resolve against (all paths
    repo-root-relative; tests point ``root`` at fixture trees)."""

    root: str = field(default_factory=_repo_root)
    package: str = "yieldfactormodels_jl_tpu"
    #: kernel modules under the historical sentinel guard: every *nested*
    #: function there is treated as a traced body (scan/kernel closures)
    kernel_globs: Tuple[str, ...] = (
        "yieldfactormodels_jl_tpu/ops/*.py",
        "yieldfactormodels_jl_tpu/serving/online.py",
        "yieldfactormodels_jl_tpu/estimation/scenario.py",
    )
    serving_dir: str = "yieldfactormodels_jl_tpu/serving"
    atomic_dirs: Tuple[str, ...] = (
        "yieldfactormodels_jl_tpu/orchestration",
        "yieldfactormodels_jl_tpu/persistence",
    )
    #: directories whose classes run genuinely multi-threaded (gateway
    #: worker, store slot tables, supervisor) — the YFM010 lock-discipline
    #: scope
    lock_dirs: Tuple[str, ...] = (
        "yieldfactormodels_jl_tpu/serving",
        "yieldfactormodels_jl_tpu/orchestration",
    )
    #: the IR-audit shape manifest YFM011 requires coverage in
    manifest_module: str = "yieldfactormodels_jl_tpu/analysis/manifest.py"
    bench_files: Tuple[str, ...] = ("bench.py", "benchmarks/*.py")
    tests_dir: str = "tests"
    claude_md: str = "CLAUDE.md"
    config_module: str = "yieldfactormodels_jl_tpu/config.py"
    reference_root: str = "/root/reference"
    raise_whitelist: frozenset = frozenset(
        {"ValueError", "TypeError", "NotImplementedError", "AttributeError"})
    baseline_path: str = ".yfmlint-baseline.json"

    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel)

    def matches(self, rel: str, patterns: Sequence[str]) -> bool:
        import fnmatch
        rel = rel.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rel, p) for p in patterns)

    def is_kernel(self, rel: str) -> bool:
        return self.matches(rel, self.kernel_globs)

    def in_package(self, rel: str) -> bool:
        return rel.replace(os.sep, "/").startswith(self.package + "/")

    def lint_files(self) -> List[str]:
        """The default linted set: the package + the bench layer (bench-only
        code obeys the same conventions, notably knob documentation)."""
        rels: List[str] = []
        pkg = self.abspath(self.package)
        for path in iter_py_files(pkg):
            rels.append(os.path.relpath(path, self.root))
        for pattern in self.bench_files:
            import glob as _glob
            for path in sorted(_glob.glob(self.abspath(pattern))):
                if path.endswith(".py"):
                    rels.append(os.path.relpath(path, self.root))
        return rels


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str
    scope: str                      # 'module' | 'project'
    fn: Callable


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, name: str, summary: str, scope: str = "module"):
    """Register a rule.  ``scope='module'`` rules run once per
    :class:`SourceModule` as ``fn(module, config) -> iterable[Finding]``;
    ``scope='project'`` rules run once per lint pass as
    ``fn(modules, config) -> iterable[Finding]``."""
    def wrap(fn):
        RULES[rule_id] = Rule(rule_id, name, summary, scope, fn)
        return fn
    return wrap


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------

@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)    # actionable
    suppressed: List[Finding] = field(default_factory=list)  # pragma'd
    baselined: List[Finding] = field(default_factory=list)   # grandfathered
    files_scanned: int = 0
    errors: List[str] = field(default_factory=list)          # unparseable

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "files_scanned": self.files_scanned,
            "counts": {"findings": len(self.findings),
                       "suppressed": len(self.suppressed),
                       "baselined": len(self.baselined)},
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "baselined": [f.to_dict() for f in self.baselined],
            "errors": list(self.errors),
        }


def changed_files(root: str) -> Optional[List[str]]:
    """Repo-relative paths touched vs HEAD (worktree + staged + untracked) —
    the ``--changed-only`` file set.  Returns ``None`` when git itself fails
    (missing binary, timeout, not a repo): "couldn't diff" must stay
    distinguishable from "nothing changed", or a broken pre-commit hook
    green-lights every diff."""
    rels: set = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            out = subprocess.run(args, cwd=root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0:
            return None
        rels |= {ln.strip() for ln in out.stdout.splitlines() if ln.strip()}
    return sorted(rels)


def run_lint(config: Optional[LintConfig] = None,
             files: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[str]] = None,
             baseline: Optional[set] = None) -> LintResult:
    """Parse each file once, run the selected rules, partition findings into
    actionable / pragma-suppressed / baselined."""
    from . import rules as _rules  # noqa: F401  (registers RULES on import)

    config = config or LintConfig()
    rels = list(files) if files is not None else config.lint_files()
    selected = [RULES[r] for r in rules] if rules is not None \
        else list(RULES.values())
    baseline = baseline or set()

    result = LintResult()
    modules: List[SourceModule] = []
    for rel in rels:
        path = config.abspath(rel)
        if not os.path.isfile(path):
            continue
        try:
            modules.append(SourceModule(path, rel.replace(os.sep, "/")))
        except SyntaxError as e:
            result.errors.append(f"{rel}: {e}")
    result.files_scanned = len(modules)

    raw: List[Tuple[Finding, Optional[SourceModule]]] = []
    for r in selected:
        if r.scope == "module":
            for mod in modules:
                for f in r.fn(mod, config):
                    raw.append((f, mod))
        else:
            for f in r.fn(modules, config):
                mod = next((m for m in modules if m.rel == f.file), None)
                raw.append((f, mod))

    for f, mod in sorted(raw, key=lambda p: (p[0].file, p[0].line, p[0].rule)):
        reason = mod.suppression_for(f) if mod is not None else None
        if reason is not None:
            f.suppressed, f.suppress_reason = True, reason
            result.suppressed.append(f)
        elif f.key() in baseline:
            f.baselined = True
            result.baselined.append(f)
        else:
            result.findings.append(f)
    return result
