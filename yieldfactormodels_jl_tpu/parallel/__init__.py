from . import mesh, multihost

__all__ = ["mesh", "multihost"]
