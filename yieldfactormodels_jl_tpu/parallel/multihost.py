"""Multi-host coordination: SPMD within a slice, crash-only work queue across.

Two complementary layers (SURVEY.md §5.8):

1. **Within a TPU slice (ICI):** `jax.distributed.initialize` + the mesh
   sharding in `mesh.py` — one SPMD program, XLA collectives over ICI.
2. **Across independent jobs (DCN / preemptible fleets):** the reference's
   idempotent design — atomic mkdir locks + shard files that double as the
   checkpoint (forecasting.jl:53-79,128-136; databaseoperations.jl:247-293) —
   is kept verbatim in `persistence/locks.py` and the forecast driver.  A
   killed worker loses only its in-flight task; rerunning the same command
   resumes exactly.  This layer needs no message passing, matching the
   reference (no NCCL/MPI — SURVEY.md §2.10).

This module adds the glue: process-group init, host-local task slicing, and a
stale-lock TTL sweep addressing the reference's known weakness that a
SIGKILLed worker's lock dir starves its task forever (SURVEY.md §5.3) — the
forecast drivers invoke it when ``stale_lock_ttl`` is set.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import jax


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """`jax.distributed.initialize` wrapper; no-op for single-process runs."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def host_task_slice(tasks: Sequence[int], process_id: Optional[int] = None,
                    num_processes: Optional[int] = None) -> List[int]:
    """Deterministic round-robin split of a task list across hosts.

    Unlike the reference's shuffled racing (forecasting.jl:86-88), hosts get
    disjoint slices up front; the lock/shard protocol still makes overlap safe
    if lists disagree.
    """
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if num_processes is None else num_processes
    return [t for i, t in enumerate(tasks) if i % n == pid]


def sweep_stale_locks(lockroot: str, ttl_seconds: float = 3600.0) -> List[str]:
    """Remove lock dirs older than ``ttl_seconds`` (crash recovery).

    The reference never expires locks, so a SIGKILLed worker permanently
    starves its task (SURVEY.md §5.3).  The per-dir primitive (atomicity,
    worst-case analysis) is ``persistence.locks.break_stale_lock``; this is
    the whole-tree sweep the forecast drivers run at entry when
    ``stale_lock_ttl`` is set.
    """
    from ..persistence.locks import break_stale_lock

    removed = []
    if not os.path.isdir(lockroot):
        return removed
    for window in os.listdir(lockroot):
        wdir = os.path.join(lockroot, window)
        if not os.path.isdir(wdir):
            continue
        for name in os.listdir(wdir):
            if not name.endswith(".lock"):
                continue
            path = os.path.join(wdir, name)
            if break_stale_lock(path, ttl_seconds):
                removed.append(path)
    return removed
