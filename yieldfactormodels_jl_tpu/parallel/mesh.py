"""Device-mesh batching: the TPU replacement for the reference's process farm.

The reference parallelizes only at the *experiment* level — independent OS
processes contending on filesystem locks (SURVEY.md §2.14, forecasting.jl:
86-136).  Here every independent unit of work (parameter draw, multi-start
column, rolling-window origin, bootstrap resample) is a batch axis:

- within one chip, `vmap` fuses the batch into large dense ops for the MXU;
- across chips, inputs carry a `NamedSharding` over a `Mesh` and XLA
  partitions the same jitted program, inserting ICI collectives only for the
  final argmax/reduction (which is bytes, not bandwidth).

The work is embarrassingly parallel, so the right "distributed backend" is
SPMD sharding of the batch axis, not point-to-point messaging.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..estimation import optimize as opt
from ..models import api
from ..models.specs import ModelSpec
from ..config import make_trace_counter, register_engine_cache

# trace counters (config.make_trace_counter): incremented INSIDE traced
# bodies so they count actual (re)compilations — the donation regression
# tests pin "bit-identical results AND no recompile" across repeated calls
trace_counts, note_trace, reset_trace_counts = make_trace_counter()


def make_mesh(n_devices: Optional[int] = None, axis_name: str = "batch") -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    return Mesh(np.asarray(devs[:n_devices]), (axis_name,))


def batch_last_sharding(mesh: Mesh, ndim: int,
                        axis_name: str = "batch") -> NamedSharding:
    """``NamedSharding`` splitting the TRAILING axis over the mesh — the
    lane-rule twin of the ``P(axis, None)`` leading-axis shardings above, for
    state that keeps its batch axis LAST (per-element serving state,
    ops/particle.py layout).  ``ndim`` is the array rank: every leading axis
    is replicated, the last rides the mesh."""
    return NamedSharding(mesh, P(*([None] * (ndim - 1) + [axis_name])))


def shard_devices(mesh: Mesh):
    """The mesh's devices in shard order (flat mesh-major order) — the
    placement contract between a mesh and per-shard resident state
    (serving/store.py): shard s of a batch-last sharded global array lives
    on ``shard_devices(mesh)[s]``."""
    return list(mesh.devices.flat)


def pad_to_multiple(arr, multiple: int, axis: int = 0):
    """Pad a batch axis up to a device-count multiple (returns arr, true_n)."""
    n = arr.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return arr, n
    pad_widths = [(0, 0)] * arr.ndim
    pad_widths[axis] = (0, rem)
    return np.pad(np.asarray(arr), pad_widths, mode="edge"), n


@register_engine_cache
@lru_cache(maxsize=64)
def _sharded_batch_loss(spec: ModelSpec, T: int, mesh: Mesh, axis_name: str):
    """The draws/resamples hot loop, params batch DONATED: the launch
    consumes the (B, P) buffer, whose values ride back out as a pass-through
    second output — a donated buffer whose contents are dead gets silently
    dropped by XLA (no aliasing, no reuse), so the alias target must be a
    real output (docs/DESIGN.md §14 donation invariant).  The public wrapper
    returns only the losses; sweep drivers that re-feed the same draw batch
    should re-feed the returned alias instead of keeping their own handle."""
    batch_sharding = NamedSharding(mesh, P(axis_name, None))
    repl = NamedSharding(mesh, P())

    def fn(params, data, start, end):
        note_trace("batch_loss")
        lls = jax.vmap(
            lambda p: api.get_loss(spec, p, data, start, end))(params)
        return lls, params

    return jax.jit(fn, in_shardings=(batch_sharding, repl, repl, repl),
                   out_shardings=(NamedSharding(mesh, P(axis_name)),
                                  batch_sharding),
                   donate_argnums=(0,))


def batch_loss_sharded(spec: ModelSpec, params_batch, data, mesh: Optional[Mesh] = None,
                       start=0, end=None, axis_name: str = "batch"):
    """Loglik of a (B, P) parameter batch, sharded over the mesh.

    This is the BASELINE.json hot path: thousands of likelihood evaluations
    (draws/resamples) as one SPMD program over the chips.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    data = jnp.asarray(data, dtype=spec.dtype)
    if end is None:
        end = data.shape[1]
    n_dev = mesh.devices.size
    # np.asarray first: the donated device buffer below is always FRESH
    # (jnp.asarray of host memory), never a caller-held jax array
    padded, n = pad_to_multiple(np.asarray(params_batch), n_dev, axis=0)
    fn = _sharded_batch_loss(spec, data.shape[1], mesh, axis_name)
    out, _ = fn(jnp.asarray(padded, dtype=spec.dtype), data,
                jnp.asarray(start), jnp.asarray(end))
    return out[:n]


@register_engine_cache
@lru_cache(maxsize=64)
def _sharded_multistart(spec: ModelSpec, T: int, mesh: Mesh, axis_name: str,
                        max_iters: int, g_tol: float, f_abstol: float):
    """Start buffer DONATED: the (S, P) raw starts are consumed by the launch
    and their memory is reused for the identically-shaped, identically-
    sharded converged-``xs`` output — the natural aliasing pair (the cascade
    overwrites starts with solutions), so the donation is always usable and
    warning-free."""
    batch_sharding = NamedSharding(mesh, P(axis_name, None))
    repl = NamedSharding(mesh, P())

    def single(x0, data, start, end):
        fun = lambda p: opt._finite_objective(spec, data, p, start, end)
        return opt._run_lbfgs(fun, x0, max_iters, g_tol, f_abstol)

    def fn(x0s, data, start, end):
        note_trace("multistart")
        return jax.vmap(single, in_axes=(0, None, None, None))(
            x0s, data, start, end)

    return jax.jit(
        fn,
        in_shardings=(batch_sharding, repl, repl, repl),
        out_shardings=(batch_sharding,
                       NamedSharding(mesh, P(axis_name)),
                       NamedSharding(mesh, P(axis_name)),
                       NamedSharding(mesh, P(axis_name))),
        donate_argnums=(0,),
    )


def multistart_sharded(spec: ModelSpec, raw_starts, data, mesh: Optional[Mesh] = None,
                       start=0, end=None, max_iters: int = 1000,
                       g_tol: float = 1e-6, f_abstol: float = 1e-6,
                       axis_name: str = "batch"):
    """Multi-start LBFGS with the start axis sharded across chips.

    Returns (raw_params (S, P), lls (S,)).  64 starts on a v4-8 run 8-per-chip
    with zero communication until the final best-of reduction.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    data = jnp.asarray(data, dtype=spec.dtype)
    if end is None:
        end = data.shape[1]
    n_dev = mesh.devices.size
    padded, n = pad_to_multiple(np.asarray(raw_starts), n_dev, axis=0)
    fn = _sharded_multistart(spec, data.shape[1], mesh, axis_name,
                             max_iters, g_tol, f_abstol)
    xs, fs, its, convs = fn(jnp.asarray(padded, dtype=spec.dtype), data,
                            jnp.asarray(start), jnp.asarray(end))
    return xs[:n], -fs[:n]


@register_engine_cache
@lru_cache(maxsize=32)
def _sharded_pf(spec: ModelSpec, T: int, mesh: Mesh, axis_name: str,
                n_particles: int, sv_phi: float, sv_sigma: float):
    from ..ops.particle import particle_filter_loglik

    batch = NamedSharding(mesh, P(axis_name, None))
    repl = NamedSharding(mesh, P())
    fn = jax.vmap(
        lambda p, k, data: particle_filter_loglik(
            spec, p, data, k, n_particles=n_particles,
            sv_phi=sv_phi, sv_sigma=sv_sigma),
        in_axes=(0, 0, None))
    return jax.jit(fn, in_shardings=(batch, batch, repl),
                   out_shardings=NamedSharding(mesh, P(axis_name)))


def particle_filter_sharded(spec: ModelSpec, draws, data, keys=None,
                            mesh: Optional[Mesh] = None, n_particles: int = 1000,
                            sv_phi: float = 0.95, sv_sigma: float = 0.2,
                            axis_name: str = "batch"):
    """SV particle-filter logliks for a (D, P) draw batch, draw axis sharded.

    BASELINE.md config 3 at multi-chip scale: each chip runs its slice of the
    1,000 draws (each a full n_particles filter) with zero cross-chip traffic.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    data = jnp.asarray(data, dtype=spec.dtype)
    n_dev = mesh.devices.size
    draws = np.asarray(draws)
    if keys is None:
        keys = jax.random.split(jax.random.PRNGKey(0), draws.shape[0])
    keys = np.asarray(keys)
    if keys.shape[0] != draws.shape[0]:
        raise ValueError(
            f"particle_filter_sharded: {draws.shape[0]} draws but "
            f"{keys.shape[0]} keys — each draw needs its own PRNG key "
            f"(independent padding would silently pair draws with repeated "
            f"keys)")
    padded, n = pad_to_multiple(draws, n_dev, axis=0)
    keys_p, _ = pad_to_multiple(keys, n_dev, axis=0)
    fn = _sharded_pf(spec, data.shape[1], mesh, axis_name,
                     n_particles, sv_phi, sv_sigma)
    out = fn(jnp.asarray(padded, dtype=spec.dtype),
             jnp.asarray(keys_p, dtype=jnp.uint32), data)
    return out[:n]


def bootstrap_grid_sharded(spec: ModelSpec, params, data, lambda_grid,
                           n_resamples: int = 2000, block_len: int = 12,
                           key=None, mesh: Optional[Mesh] = None,
                           axis_name: str = "batch"):
    """Block-bootstrap λ-grid (BASELINE.md config 5) with the resample axis
    sharded across chips.

    The resample indices are placed with a NamedSharding and the cached grid
    engine (fused MXU kernel for fully-observed static-λ panels) is invoked
    on them — XLA's computation-follows-data partitioning runs each chip's
    resample slice locally; padded rows are trimmed BEFORE the CI/selection
    stats so they cannot bias the percentiles.  Same return contract as
    ``estimation.bootstrap.bootstrap_lambda_grid``.
    """
    from ..estimation.bootstrap import (grid_losses, grid_stats,
                                        lambda_to_gamma, moving_block_indices)

    if key is None:
        key = jax.random.PRNGKey(0)
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    lam = jnp.asarray(lambda_grid, dtype=spec.dtype)
    gammas = lambda_to_gamma(lam)
    idx = np.asarray(moving_block_indices(key, T, block_len, n_resamples))
    n_dev = mesh.devices.size
    padded, n = pad_to_multiple(idx, n_dev, axis=0)
    idx_sharded = jax.device_put(
        jnp.asarray(padded), NamedSharding(mesh, P(axis_name, None)))
    losses = grid_losses(spec, gammas, idx_sharded, params, data)[:n]
    return (losses,) + grid_stats(losses, lam.shape[0])


def scenario_lattice_sharded(
    data,
    *,
    mesh: Optional[Mesh] = None,
    axis_name: str = "batch",
    static_spec: Optional[ModelSpec] = None,
    static_params=None,
    lambda_grid=None,
    n_resamples: int = 0,
    block_len: int = 12,
    grid_engine: str = "auto",
    kalman_spec: Optional[ModelSpec] = None,
    kalman_params=None,
    sv_draws=None,
    n_particles: int = 200,
    sv_phi: float = 0.95,
    sv_sigma: float = 0.2,
    shocks=(),
    horizon: int = 12,
    n_paths: int = 0,
    key=None,
    donate: bool = True,
) -> dict:
    """The scenario lattice (estimation/scenario.py) with its big axes riding
    the device mesh: the RESAMPLE axis and the SV-DRAW axis are padded to a
    device-count multiple and placed with ``NamedSharding(P(axis_name,
    None))`` — computation-follows-data partitions the one lattice program so
    each chip evaluates its slice of the (R × G) loss plane and its share of
    the D particle filters, while the shock fan (a single filtered state)
    stays replicated.  Padded rows are trimmed BEFORE the CI/selection stats
    (``with_stats=False`` in-program, stats host-side here) so they cannot
    bias the percentiles — the ``bootstrap_grid_sharded`` discipline.

    Donation: the sharded index/draw/accumulator buffers are created fresh
    here and donated by ``evaluate_lattice`` (its aliasing invariants hold
    under sharding because the alias outputs carry the same sharding as the
    inputs); callers never see a consumed buffer.  Same per-face returns as
    :func:`~..estimation.scenario.evaluate_lattice`.
    """
    from ..estimation.bootstrap import (grid_stats, moving_block_indices,
                                        resolve_grid_engine)
    from ..estimation.scenario import evaluate_lattice, face_keys

    if key is None:
        key = jax.random.PRNGKey(0)
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    n_dev = mesh.devices.size
    shard = NamedSharding(mesh, P(axis_name, None))
    spec0 = kalman_spec if kalman_spec is not None else static_spec
    if spec0 is None:
        raise ValueError("scenario_lattice_sharded needs static_spec and/or "
                         "kalman_spec")
    data = jnp.asarray(data, dtype=spec0.dtype)
    T = int(data.shape[1])

    R = D = 0
    idx_sharded = draws_sharded = None
    recycle = None
    if lambda_grid is not None:
        R = int(n_resamples)
        # the same index stream as the unsharded lattice / bootstrap driver
        # (face_keys: the resample stream is the master key itself), padded
        # by repeating the first rows — trimmed before anything statistical
        idx = np.asarray(moving_block_indices(face_keys(key)[0], T,
                                              block_len, R))
        padded, _ = pad_to_multiple(idx, n_dev, axis=0)
        idx_sharded = jax.device_put(jnp.asarray(padded, jnp.int32), shard)
        G = int(np.shape(lambda_grid)[0])
        if donate and resolve_grid_engine(static_spec, data,
                                          grid_engine) == "fused":
            # accumulator sharded like the losses output it aliases
            recycle = {"losses": jax.device_put(
                jnp.zeros((int(padded.shape[0]), G), dtype=spec0.dtype),
                shard)}
    if sv_draws is not None:
        draws = np.asarray(sv_draws)
        if draws.ndim == 1:
            draws = draws[None, :]
        D = int(draws.shape[0])
        padded_d, _ = pad_to_multiple(draws, n_dev, axis=0)
        draws_sharded = jax.device_put(
            jnp.asarray(padded_d, dtype=spec0.dtype), shard)

    out = evaluate_lattice(
        data, static_spec=static_spec, static_params=static_params,
        lambda_grid=lambda_grid, resample_idx=idx_sharded,
        block_len=block_len, grid_engine=grid_engine,
        kalman_spec=kalman_spec, kalman_params=kalman_params,
        sv_draws=draws_sharded, n_particles=n_particles, sv_phi=sv_phi,
        sv_sigma=sv_sigma, shocks=tuple(shocks), horizon=horizon,
        n_paths=n_paths, key=key, donate=donate, recycle=recycle,
        with_stats=False)

    if R:
        out["losses"] = out["losses"][:R]
        out["resample_idx"] = out["resample_idx"][:R]
        out["ci_low"], out["ci_high"], out["selection_freq"] = grid_stats(
            out["losses"], int(np.shape(lambda_grid)[0]))
    if D:
        out["pf_logliks"] = out["pf_logliks"][:D]
        out["sv_draws"] = out["sv_draws"][:D]
    return out
