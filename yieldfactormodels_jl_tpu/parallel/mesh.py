"""Device-mesh batching: the TPU replacement for the reference's process farm.

The reference parallelizes only at the *experiment* level — independent OS
processes contending on filesystem locks (SURVEY.md §2.14, forecasting.jl:
86-136).  Here every independent unit of work (parameter draw, multi-start
column, rolling-window origin, bootstrap resample) is a batch axis:

- within one chip, `vmap` fuses the batch into large dense ops for the MXU;
- across chips, inputs carry a `NamedSharding` over a `Mesh` and XLA
  partitions the same jitted program, inserting ICI collectives only for the
  final argmax/reduction (which is bytes, not bandwidth).

The work is embarrassingly parallel, so the right "distributed backend" is
SPMD sharding of the batch axis, not point-to-point messaging.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..estimation import optimize as opt
from ..models import api
from ..models.specs import ModelSpec
from ..config import register_engine_cache


def make_mesh(n_devices: Optional[int] = None, axis_name: str = "batch") -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    return Mesh(np.asarray(devs[:n_devices]), (axis_name,))


def pad_to_multiple(arr, multiple: int, axis: int = 0):
    """Pad a batch axis up to a device-count multiple (returns arr, true_n)."""
    n = arr.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return arr, n
    pad_widths = [(0, 0)] * arr.ndim
    pad_widths[axis] = (0, rem)
    return np.pad(np.asarray(arr), pad_widths, mode="edge"), n


@register_engine_cache
@lru_cache(maxsize=64)
def _sharded_batch_loss(spec: ModelSpec, T: int, mesh: Mesh, axis_name: str):
    batch_sharding = NamedSharding(mesh, P(axis_name, None))
    repl = NamedSharding(mesh, P())

    fn = jax.vmap(lambda p, data, start, end: api.get_loss(spec, p, data, start, end),
                  in_axes=(0, None, None, None))
    return jax.jit(fn, in_shardings=(batch_sharding, repl, repl, repl),
                   out_shardings=NamedSharding(mesh, P(axis_name)))


def batch_loss_sharded(spec: ModelSpec, params_batch, data, mesh: Optional[Mesh] = None,
                       start=0, end=None, axis_name: str = "batch"):
    """Loglik of a (B, P) parameter batch, sharded over the mesh.

    This is the BASELINE.json hot path: thousands of likelihood evaluations
    (draws/resamples) as one SPMD program over the chips.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    data = jnp.asarray(data, dtype=spec.dtype)
    if end is None:
        end = data.shape[1]
    n_dev = mesh.devices.size
    padded, n = pad_to_multiple(np.asarray(params_batch), n_dev, axis=0)
    fn = _sharded_batch_loss(spec, data.shape[1], mesh, axis_name)
    out = fn(jnp.asarray(padded, dtype=spec.dtype), data,
             jnp.asarray(start), jnp.asarray(end))
    return out[:n]


@register_engine_cache
@lru_cache(maxsize=64)
def _sharded_multistart(spec: ModelSpec, T: int, mesh: Mesh, axis_name: str,
                        max_iters: int, g_tol: float, f_abstol: float):
    batch_sharding = NamedSharding(mesh, P(axis_name, None))
    repl = NamedSharding(mesh, P())

    def single(x0, data, start, end):
        fun = lambda p: opt._finite_objective(spec, data, p, start, end)
        return opt._run_lbfgs(fun, x0, max_iters, g_tol, f_abstol)

    fn = jax.vmap(single, in_axes=(0, None, None, None))
    return jax.jit(
        fn,
        in_shardings=(batch_sharding, repl, repl, repl),
        out_shardings=(NamedSharding(mesh, P(axis_name, None)),
                       NamedSharding(mesh, P(axis_name)),
                       NamedSharding(mesh, P(axis_name)),
                       NamedSharding(mesh, P(axis_name))),
    )


def multistart_sharded(spec: ModelSpec, raw_starts, data, mesh: Optional[Mesh] = None,
                       start=0, end=None, max_iters: int = 1000,
                       g_tol: float = 1e-6, f_abstol: float = 1e-6,
                       axis_name: str = "batch"):
    """Multi-start LBFGS with the start axis sharded across chips.

    Returns (raw_params (S, P), lls (S,)).  64 starts on a v4-8 run 8-per-chip
    with zero communication until the final best-of reduction.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    data = jnp.asarray(data, dtype=spec.dtype)
    if end is None:
        end = data.shape[1]
    n_dev = mesh.devices.size
    padded, n = pad_to_multiple(np.asarray(raw_starts), n_dev, axis=0)
    fn = _sharded_multistart(spec, data.shape[1], mesh, axis_name,
                             max_iters, g_tol, f_abstol)
    xs, fs, its, convs = fn(jnp.asarray(padded, dtype=spec.dtype), data,
                            jnp.asarray(start), jnp.asarray(end))
    return xs[:n], -fs[:n]
