"""Sequence (time-axis) parallelism for the Kalman filter.

The reference's filters are strictly sequential ``for t`` loops
(/root/reference/src/models/filter.jl:225, kalman/filter.jl:190) and its only
parallelism is process farming — there is no sequence parallelism of any kind
(SURVEY.md §5.7).  Here the filter recursion is an *associative* operation
(ops/assoc_scan.py), which makes the time axis shardable: each device owns a
contiguous block of timesteps, runs the blockwise combine locally, and XLA
stitches the blocks with ICI collectives inside ``lax.associative_scan`` — the
state-space analogue of blockwise/ring sequence parallelism for attention.

This is the long-context story of this framework: a T-step panel is sharded
``P("time")`` over the mesh, the O(log T) combine tree crosses devices only at
block boundaries (Ms² payloads, tiny), and the loglik reduction is a psum.
For the T≈300 monthly panels of the reference domain this is latency
insurance; for simulated long histories (T ~ 10⁵–10⁶, e.g. daily/intraday
curves or long bootstrap paths) it is the difference between fitting in one
device's step-sequential latency and log-depth across the mesh.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.specs import ModelSpec
from .mesh import make_mesh


@lru_cache(maxsize=32)
def _jitted_time_sharded_loss(spec: ModelSpec, T: int, mesh: Mesh, axis: str):
    from ..ops import assoc_scan

    data_sh = NamedSharding(mesh, P(None, axis))   # (N, T) sharded over time
    repl = NamedSharding(mesh, P())

    fn = jax.jit(
        lambda params, data, start, end: assoc_scan.get_loss(
            spec, params, data, start, end),
        in_shardings=(repl, data_sh, repl, repl),
        out_shardings=repl,
    )
    return fn


def get_loss_time_sharded(spec: ModelSpec, params, data, start=0, end=None,
                          mesh: Mesh | None = None, axis_name: str = "time"):
    """Kalman loglik with the TIME axis sharded over the device mesh.

    Equivalent to ``assoc_scan.get_loss`` (itself equal to the sequential
    kernels — tested) but with ``data`` laid out ``P(None, "time")``: the
    parallel-prefix combine runs block-local on each device and crosses the
    mesh O(log n_devices) times.  Constant-measurement Kalman families only
    (the associative form needs a constant Z).
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    T = data.shape[1]
    if end is None:
        end = T
    fn = _jitted_time_sharded_loss(spec, T, mesh, axis_name)
    data = jax.device_put(jnp.asarray(data, dtype=spec.dtype),
                          NamedSharding(mesh, P(None, axis_name)))
    return fn(jnp.asarray(params, dtype=spec.dtype), data,
              jnp.asarray(start), jnp.asarray(end))
