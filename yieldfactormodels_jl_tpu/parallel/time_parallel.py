"""Sequence (time-axis) parallelism for the Kalman filter.

The reference's filters are strictly sequential ``for t`` loops
(/root/reference/src/models/filter.jl:225, kalman/filter.jl:190) and its only
parallelism is process farming — there is no sequence parallelism of any kind
(SURVEY.md §5.7).  Here the filter recursion is an *associative* operation
(ops/assoc_scan.py), which makes the time axis shardable: each device owns a
contiguous block of timesteps, runs the blockwise combine locally, and XLA
stitches the blocks with ICI collectives inside ``lax.associative_scan`` — the
state-space analogue of blockwise/ring sequence parallelism for attention.

This is the long-context story of this framework: a T-step panel is sharded
``P("time")`` over the mesh, the O(log T) combine tree crosses devices only at
block boundaries (Ms² payloads, tiny), and the loglik reduction is a psum.
For the T≈300 monthly panels of the reference domain this is latency
insurance; for simulated long histories (T ~ 10⁵–10⁶, e.g. daily/intraday
curves or long bootstrap paths) it is the difference between fitting in one
device's step-sequential latency and log-depth across the mesh.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import register_engine_cache
from ..models.specs import ModelSpec
from .mesh import make_mesh


def _tree_loss_fn(spec: ModelSpec, T: int, n_dev: int):
    """The family's O(log T) parallel-in-time loss over a TIME-SHARDED
    panel: ``assoc_scan.get_loss`` for the constant-Z families,
    ``slr_scan.get_loss`` (the iterated-SLR engine, docs/DESIGN.md §19)
    for the state-dependent-measurement ones, ``score_scan.get_loss`` for
    the capable score-driven specs.  One dispatch through
    ``config.tree_engine_for`` so this module, the ``api.get_loss``
    T-switch and the ladder's rescue rungs can never disagree on
    applicability.  All run the ``"interleaved"`` combine schedule
    (block-local under SPMD); the chunked-refinement engines (slr,
    score_tree) additionally pin their refinement chunk to the SHARD length
    T/n_dev, so the (C, L) chunk reshape is exactly the sharding layout and
    every device refines its own block — a misaligned chunk makes the
    partitioner rematerialize the scan's slices across shards, which was
    observed to MISCOMPILE (wrong loss, no error) on the 8-virtual-device
    mesh; the aligned form is verified bit-identical to the unsharded
    engine at the same chunk."""
    from .. import config

    eng = config.tree_engine_for(spec)
    if eng == "assoc":
        from ..ops import assoc_scan

        def loss(params, data, start, end):
            return assoc_scan.get_loss(spec, params, data, start, end,
                                       prefix="interleaved")
        return loss
    if eng == "slr":
        from ..ops import slr_scan

        chunk = max(1, T // max(n_dev, 1))

        def loss(params, data, start, end):
            return slr_scan.get_loss(spec, params, data, start, end,
                                     prefix="interleaved", chunk=chunk)
        return loss
    if eng == "score_tree":
        from ..ops import score_scan

        # same shard-aligned-chunk pin as the SLR engine: the refinement's
        # (C, L) reshape must BE the sharding layout (a misaligned chunk
        # rematerializes the scan's slices across shards — observed to
        # MISCOMPILE for the SLR engine; the aligned form is pinned
        # bit-identical to the unsharded engine in tests/test_score_scan.py)
        chunk = max(1, T // max(n_dev, 1))

        def loss(params, data, start, end):
            return score_scan.get_loss(spec, params, data, start, end,
                                       prefix="interleaved", chunk=chunk)
        return loss
    raise ValueError(
        f"time-sharded likelihood needs a family with a parallel-in-time "
        f"engine; config.engines_for({spec.family!r}) "
        f"lists none of ('assoc', 'slr', 'score_tree')")


def _pad_time(data, n_dev: int):
    """Pad the TIME axis with NaN columns up to a device-count multiple —
    ``NamedSharding`` placement needs the sharded dimension divisible by the
    mesh, and real daily histories have arbitrary length.  Exact by
    construction: with ``end`` kept at the ORIGINAL T the padded columns sit
    outside the window, so the assoc elements there are pure prediction
    steps past every contributing prefix — the loss is bit-identical."""
    T = data.shape[1]
    rem = (-T) % n_dev
    if rem:
        pad = jnp.full(data.shape[:1] + (rem,), jnp.nan, dtype=data.dtype)
        data = jnp.concatenate([data, pad], axis=1)
    return data


@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_time_sharded_loss(spec: ModelSpec, T: int, mesh: Mesh, axis: str):
    # interleaved combine tree: block-local under SPMD (the blocked
    # prefix's chunk reshape would cross shard boundaries — see
    # assoc_scan.filter_means_covs); SLR refinement chunk = shard length
    loss = _tree_loss_fn(spec, T, int(mesh.devices.size))

    data_sh = NamedSharding(mesh, P(None, axis))   # (N, T) sharded over time
    repl = NamedSharding(mesh, P())

    fn = jax.jit(
        loss,
        in_shardings=(repl, data_sh, repl, repl),
        out_shardings=repl,
    )
    return fn


def get_loss_time_sharded(spec: ModelSpec, params, data, start=0, end=None,
                          mesh: Mesh | None = None, axis_name: str = "time"):
    """Kalman loglik with the TIME axis sharded over the device mesh.

    Equivalent to ``assoc_scan.get_loss`` (itself equal to the sequential
    kernels — tested) but with ``data`` laid out ``P(None, "time")``: the
    parallel-prefix combine runs block-local on each device and crosses the
    mesh O(log n_devices) times.  Kalman families with a parallel-in-time
    engine (``config.engines_for``): the constant-Z families ride the assoc
    tree, the state-dependent-measurement ones (TVλ) the iterated-SLR
    engine.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    T = data.shape[1]
    if end is None:
        end = T
    data = _pad_time(jnp.asarray(data, dtype=spec.dtype),
                     int(mesh.devices.size))
    fn = _jitted_time_sharded_loss(spec, data.shape[1], mesh, axis_name)
    data = jax.device_put(data, NamedSharding(mesh, P(None, axis_name)))
    return fn(jnp.asarray(params, dtype=spec.dtype), data,
              jnp.asarray(start), jnp.asarray(end))


# ---------------------------------------------------------------------------
# time-sharded estimation: the long-panel MLE hot path (docs/DESIGN.md §13)
# ---------------------------------------------------------------------------

@register_engine_cache
@lru_cache(maxsize=32)
def _jitted_time_sharded_multistart(spec: ModelSpec, T: int, mesh: Mesh,
                                    axis: str, max_iters: int, g_tol: float,
                                    f_abstol: float):
    """Multi-start L-BFGS whose every objective/gradient eval is the
    associative-scan loglik over TIME-SHARDED data: starts replicated, the
    panel laid out ``P(None, time)``, so a T=20k daily history optimizes at
    O(log T) span per eval instead of 20k sequential steps per device.
    (Lazy optimizer import: estimation ← parallel would otherwise cycle.)"""
    from ..estimation import optimize as opt
    from ..models.params import transform_params

    # interleaved tree + shard-aligned SLR chunking (see the loss builder)
    loss = _tree_loss_fn(spec, T, int(mesh.devices.size))
    data_sh = NamedSharding(mesh, P(None, axis))
    repl = NamedSharding(mesh, P())

    def single(x0, data, start, end):
        def fun(p):
            v = -loss(transform_params(spec, p), data, start, end)
            return jnp.where(jnp.isfinite(v), v, 1e12)

        return opt._run_lbfgs(fun, x0, max_iters, g_tol, f_abstol)

    fn = jax.vmap(single, in_axes=(0, None, None, None))
    return jax.jit(fn, in_shardings=(repl, data_sh, repl, repl),
                   out_shardings=(repl, repl, repl, repl))


def multistart_time_sharded(spec: ModelSpec, data, raw_starts, start=0,
                            end=None, mesh: Mesh | None = None,
                            max_iters: int = 1000, g_tol: float = 1e-6,
                            f_abstol: float = 1e-6, axis_name: str = "time"):
    """Multi-start MLE on the family's tree engine with TIME sharded.

    The dual of :func:`~.mesh.multistart_sharded` (which shards the START
    axis): here every device owns a contiguous block of timesteps and the
    whole start batch rides each device — the right split when T is the big
    axis (daily/intraday panels) and S is a handful.  Kalman families with
    a parallel-in-time engine (``config.engines_for`` — assoc for
    constant-Z, iterated SLR for TVλ).
    Arbitrary T: the panel is NaN-padded to a device-count multiple with
    ``end`` kept at the true length (exact — see :func:`_pad_time`).

    Returns ``(raw_params (S, P), lls (S,), iters (S,), converged (S,))`` —
    the ``estimate``-compatible artifact
    (``estimation.optimize.estimate(objective="time_sharded")`` wraps this
    with the standard best-of/reporting tail).
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    data = jnp.asarray(data, dtype=spec.dtype)
    T = data.shape[1]
    if end is None:
        end = T
    data = _pad_time(data, int(mesh.devices.size))
    fn = _jitted_time_sharded_multistart(spec, data.shape[1], mesh, axis_name,
                                         max_iters, g_tol, f_abstol)
    data = jax.device_put(data, NamedSharding(mesh, P(None, axis_name)))
    xs, fs, its, convs = fn(jnp.asarray(np.asarray(raw_starts),
                                        dtype=spec.dtype), data,
                            jnp.asarray(start), jnp.asarray(end))
    return xs, -fs, its, convs
