"""yieldfactormodels_jl_tpu — a TPU-native (JAX/XLA/pjit/Pallas) yield-factor-model framework.

A ground-up re-design of the capabilities of Sicco123/YieldFactorModels.jl
(reference layer map in SURVEY.md §1) for TPU hardware:

- immutable model *specs* + flat parameter vectors (pytrees) instead of mutable
  structs with in-place ``set_params!`` (reference: src/models/*/
  paramteroperations.jl),
- every filter recursion is a single ``lax.scan`` kernel under ``jit``
  (reference: per-timestep Julia loops in src/models/filter.jl:225,
  src/models/kalman/filter.jl:190),
- NaN observations become masked, branchless predict-only steps so multi-step
  forecasting falls out of the same kernel (reference trick:
  src/forecasting.jl:141),
- multi-start estimation, initialization grids, rolling windows and bootstrap
  resamples are ``vmap``/``shard_map`` batch axes on a device mesh instead of a
  process farm (reference: src/forecasting.jl:86-136).

The reference contains zero native (C++/CUDA) components (SURVEY.md §2); the
native layer of this framework is XLA itself plus optional Pallas kernels.

Every public name resolves lazily (PEP 562): importing the bare package —
or a jax-free subpackage like ``analysis`` via ``python -m
yieldfactormodels_jl_tpu.analysis`` — must not pull jax (this container
auto-registers the axon TPU plugin in every python process, so an eager jax
import would put backend init one device-op away from dialing the TPU
tunnel; the linter also wants its one-second startup).  The first access of
any model/estimation name imports its home module, which imports jax.
"""

#: public name -> home module (relative); resolved on first attribute access
_LAZY = {name: ".config" for name in (
    "default_dtype", "set_default_dtype", "kalman_engine",
    "set_kalman_engine", "KALMAN_ENGINES", "SLR_ENGINES", "engines_for",
    "tree_engine_for")}
_LAZY["ModelSpec"] = ".models.specs"
_LAZY.update({name: ".models.registry" for name in
              ("create_model", "MODEL_CODES")})
_LAZY.update({name: ".models.api" for name in (
    "get_params", "n_params", "get_param_groups", "get_static_model_type",
    "init_state", "get_loss", "get_loss_array", "predict",
    "forecast_density", "simulate", "smooth", "update_factor_loadings",
    "random_initial_params")})
_LAZY.update({name: ".models.params" for name in (
    "transform_params", "untransform_params", "expand_params",
    "get_unique_params", "get_new_initial_params",
    "initialize_with_static_params")})
_LAZY["load_data"] = ".utils.data_management"
_LAZY.update({name: ".estimation.optimize" for name in (
    "compute_loss", "estimate", "estimate_steps", "try_initializations")})
_LAZY.update({name: ".estimation.amortize" for name in (
    "Amortizer", "AmortizerConfig", "train_amortizer", "register_amortizer",
    "get_amortizer", "amortized_refit")})
_LAZY["run_rolling_forecasts"] = ".forecasting"
_LAZY["run"] = ".run"
_LAZY["save_results"] = ".persistence.io"
_LAZY.update({name: ".serving" for name in (
    "YieldCurveService", "ServingSnapshot", "SnapshotRegistry",
    "freeze_snapshot", "load_snapshot")})
_LAZY.update({name: ".program" for name in (
    "ModelProgram", "ParamBlock", "ProgramSpec", "compile_program",
    "register_program", "unregister_program", "registered_programs")})
# "model_api" (the module itself, not an attribute of it) is special-cased
# in __getattr__ below and deliberately absent from this table

#: subpackages reachable as plain attributes (``yfm.serving``) without an
#: explicit submodule import at the call site
_SUBMODULES = frozenset({
    "analysis", "config", "estimation", "forecasting", "models", "ops",
    "orchestration", "parallel", "persistence", "program", "robustness",
    "run", "serving", "utils",
})

__all__ = sorted(set(_LAZY) | {"model_api"})

__version__ = "0.1.0"


def __getattr__(name):
    # importlib, not `from . import`: the latter re-enters this __getattr__
    # through _handle_fromlist's hasattr and recurses
    import importlib

    if name == "model_api":
        return importlib.import_module(".models.api", __name__)
    home = _LAZY.get(name)
    if home is not None:
        mod = importlib.import_module(home, __name__)
        value = getattr(mod, name)
        globals()[name] = value  # cache: next access skips __getattr__
        return value
    if name in _SUBMODULES:
        return importlib.import_module("." + name, __name__)
    raise AttributeError(name)


def __dir__():
    return sorted(set(globals()) | set(__all__) | set(_SUBMODULES))
