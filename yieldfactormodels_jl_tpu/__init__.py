"""yieldfactormodels_jl_tpu — a TPU-native (JAX/XLA/pjit/Pallas) yield-factor-model framework.

A ground-up re-design of the capabilities of Sicco123/YieldFactorModels.jl
(reference layer map in SURVEY.md §1) for TPU hardware:

- immutable model *specs* + flat parameter vectors (pytrees) instead of mutable
  structs with in-place ``set_params!`` (reference: src/models/*/
  paramteroperations.jl),
- every filter recursion is a single ``lax.scan`` kernel under ``jit``
  (reference: per-timestep Julia loops in src/models/filter.jl:225,
  src/models/kalman/filter.jl:190),
- NaN observations become masked, branchless predict-only steps so multi-step
  forecasting falls out of the same kernel (reference trick:
  src/forecasting.jl:141),
- multi-start estimation, initialization grids, rolling windows and bootstrap
  resamples are ``vmap``/``shard_map`` batch axes on a device mesh instead of a
  process farm (reference: src/forecasting.jl:86-136).

The reference contains zero native (C++/CUDA) components (SURVEY.md §2); the
native layer of this framework is XLA itself plus optional Pallas kernels.
"""

from .config import (default_dtype, set_default_dtype,
                     kalman_engine, set_kalman_engine, KALMAN_ENGINES)
from .models.specs import ModelSpec
from .models.registry import create_model, MODEL_CODES
from .models import api as model_api
from .models.api import (
    get_params,
    n_params,
    get_param_groups,
    get_static_model_type,
    init_state,
    get_loss,
    get_loss_array,
    predict,
    forecast_density,
    simulate,
    smooth,
    update_factor_loadings,
    random_initial_params,
)
from .models.params import (
    transform_params,
    untransform_params,
    expand_params,
    get_unique_params,
    get_new_initial_params,
    initialize_with_static_params,
)
from .utils.data_management import load_data

__all__ = [
    "ModelSpec",
    "create_model",
    "MODEL_CODES",
    "model_api",
    "get_params",
    "n_params",
    "get_param_groups",
    "get_static_model_type",
    "init_state",
    "get_loss",
    "get_loss_array",
    "predict",
    "forecast_density",
    "simulate",
    "smooth",
    "update_factor_loadings",
    "random_initial_params",
    "transform_params",
    "untransform_params",
    "expand_params",
    "get_unique_params",
    "get_new_initial_params",
    "initialize_with_static_params",
    "load_data",
    "default_dtype",
    "set_default_dtype",
    "kalman_engine",
    "set_kalman_engine",
    "KALMAN_ENGINES",
]

__version__ = "0.1.0"

# Estimation / forecasting / persistence layers are imported lazily so the
# core model zoo stays importable in minimal environments.
def __getattr__(name):
    if name in ("compute_loss", "estimate", "estimate_steps", "try_initializations"):
        from .estimation import optimize as _opt

        return getattr(_opt, name)
    if name == "run_rolling_forecasts":
        from .forecasting import run_rolling_forecasts

        return run_rolling_forecasts
    if name == "run":
        from .run import run

        return run
    if name == "save_results":
        from .persistence.io import save_results

        return save_results
    if name in ("YieldCurveService", "ServingSnapshot", "SnapshotRegistry",
                "freeze_snapshot", "load_snapshot", "serving"):
        # importlib, not `from . import`: the latter re-enters this
        # __getattr__ through _handle_fromlist's hasattr and recurses
        import importlib

        mod = importlib.import_module(".serving", __name__)
        return mod if name == "serving" else getattr(mod, name)
    raise AttributeError(name)
