"""Merge a device-side measurement log into benchmarks/results.json.

The recovery loop (BASELINE.md "TPU availability" note) runs
``run_all.py --side device`` for the configs when the relay recovers
and appends the JSON lines to its log.  This script folds those lines into
``results.json`` as COHERENT pairs against the round's clean CPU walls, so
the whole device sequence needs no manual bookkeeping:

    python benchmarks/merge_device.py /tmp/r4/probe_loop.log

CPU walls of record (measured this round / carried where the kernel is
unchanged — see BASELINE.md round-4 section):
  dns3-mle 4.252 (r2, code unchanged), afns5-mle64 648.665 (r2),
  afns5-sv-pf 307.3 (r2 lane-major re-measure), rolling-240 442.936 (r2),
  bootstrap-2000 0.957 (r2 MXU-fused; r4 re-measure 1.014 agrees),
  ssd-nns-m3 199.614 (r4 HEAD — the closed-form group-2 code the device
  runs; the r3 177.803 paired the OLD iterative code),
  bootstrap-xl 15.917 (r4).
"""

from __future__ import annotations

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

CPU_WALLS = {
    "dns3-mle": 4.252,
    "afns5-mle64": 648.665,
    "afns5-sv-pf": 307.3,
    "rolling-240": 442.936,
    "bootstrap-2000": 0.957,
    "ssd-nns-m3": 199.614,
    "bootstrap-xl": 15.917,
}


def main(log_path: str) -> None:
    device = {}
    with open(log_path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("side") == "device":
                device[rec["config"]] = rec  # last occurrence wins

    out_path = os.path.join(HERE, "results.json")
    previous = {}
    if os.path.isfile(out_path):
        previous = {r["config"]: r for r in json.load(open(out_path))}

    merged = []
    # never drop unknown configs, from either side (previous ledger entries
    # AND fresh device records for configs this script doesn't know yet)
    extra = list(dict.fromkeys(
        n for n in list(previous) + list(device) if n not in CPU_WALLS))
    for name in list(CPU_WALLS) + extra:
        cpu_wall = CPU_WALLS.get(name)
        if cpu_wall is None:
            rec = previous.get(name, {"config": name})
            if name in device:  # fresh device wall with no vetted CPU wall:
                rec["device_wall_s"] = device[name]["wall_s"]
                rec["work"] = device[name]["work"]
                # a carried cpu_wall_s_est is from some prior round — pairing
                # it with this round's device wall would be exactly the
                # cross-round incoherence the known-config path refuses, so
                # drop the ratio until a vetted CPU wall exists
                rec.pop("speedup_vs_1core", None)
            merged.append(rec)
            print(json.dumps(rec))
            continue
        rec = previous.get(name, {"config": name})
        if name in device:
            # coherent pair: fresh device wall against this round's CPU wall
            rec["cpu_scale"] = 1
            rec["cpu_wall_s_scaled"] = cpu_wall
            rec["cpu_wall_s_est"] = cpu_wall
            rec["device_wall_s"] = device[name]["wall_s"]
            rec["work"] = device[name]["work"]
            if rec["device_wall_s"] > 0:  # rounded-to-0 sub-ms walls
                rec["speedup_vs_1core"] = round(
                    cpu_wall / rec["device_wall_s"], 2)
            else:
                rec.pop("speedup_vs_1core", None)
        # no device record -> leave the previous (coherent r2) pair verbatim
        # rather than mixing a new CPU wall with a stale device wall
        if rec != {"config": name}:
            merged.append(rec)
            print(json.dumps(rec))
    with open(out_path, "w") as f:
        json.dump(merged, f, indent=2)
    sys.stderr.write(f"# wrote {out_path} ({len(device)} device records "
                     f"from {log_path})\n")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/r4/probe_loop.log")
