"""The seven BASELINE.md benchmark configs, measured device-vs-CPU.

Workloads (full scale, from BASELINE.json + VERDICT r2 #3):
  1. dns3-mle        3-factor DNS, single-start MLE (LBFGS)
  2. afns5-mle64     5-factor AFNS, multi-start MLE, 64 starts
  3. afns5-sv-pf     AFNS + stochastic-volatility errors, 1,000 particle-filter
                     draws (1,000 particles each)
  4. rolling-240     240 expanding windows × 2 starts re-estimation + 12-step
                     forecasts
  5. bootstrap-2000  2,000 moving-block resamples × 64-point λ grid
  6. ssd-nns-m3      1SSD-NNS (the reference driver's flagship) block-coordinate
                     estimation: 256-candidate A/B init grid + best start
                     (reference try_initializations semantics) × 10 group iters
  7. bootstrap-xl    8,000 resamples × 256-point λ grid (16× config 5) —
                     VERDICT r3 item 8: config 5's 0.241 s device wall measures
                     launch latency, not throughput; this row scales the same
                     workload to a multi-second wall on both sides.  The
                     BASELINE.json-parity row stays bootstrap-2000.

Protocol: every config runs the SAME jitted code path on the device and on a
single CPU core (``taskset -c 0``, JAX CPU backend) — a generous stand-in for
the reference's 1-thread Julia loop (its per-step CPU oracle is measured by
the repo-root ``bench.py``).  CPU baselines are MEASURED at full scale
(cpu_scale=1 — no extrapolation); device numbers are full scale, steady state
(2nd run, compile cached).  Results: one JSON line per config, merged into
``benchmarks/results.json`` by the orchestrator:

    python benchmarks/run_all.py              # orchestrate device + cpu
    python benchmarks/run_all.py --side device --configs all
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

# (config, cpu_scale) — cpu_scale=1 everywhere: CPU baselines are MEASURED at
# full scale on the pinned core (VERDICT round 1, item 5 — no extrapolation).
# The scale machinery remains for quick ad-hoc runs via --cpu-scale.
CONFIGS = [
    ("dns3-mle", 1),
    ("afns5-mle64", 1),
    ("afns5-sv-pf", 1),
    ("rolling-240", 1),
    ("bootstrap-2000", 1),
    ("ssd-nns-m3", 1),
    ("bootstrap-xl", 1),
]


def _run_config(name: str, scale: int):
    """Returns (wall_seconds, work_descr).  ``scale`` divides the batch axis."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    for p in (HERE, ROOT):
        if p not in sys.path:
            sys.path.insert(0, p)
    import common

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.estimation import optimize
    from yieldfactormodels_jl_tpu.estimation.bootstrap import bootstrap_lambda_grid
    from yieldfactormodels_jl_tpu.models import api
    from yieldfactormodels_jl_tpu.models.params import untransform_params
    from yieldfactormodels_jl_tpu.ops.particle import particle_filter_loglik

    def steady(fn):
        """Run twice (compile + steady state), hard-synced; time the 2nd."""
        np.asarray(jax.block_until_ready(fn()))
        t0 = time.perf_counter()
        out = np.asarray(jax.block_until_ready(fn()))
        return time.perf_counter() - t0, out

    if name == "dns3-mle":
        spec, _ = create_model("1C", tuple(common.MATURITIES), float_type="float32")
        data = common.dns_panel()
        p0 = common.dns_params(spec)

        def job():
            _, ll, best, _ = optimize.estimate(spec, data, p0[:, None],
                                               max_iters=200)
            return np.asarray([ll])

        wall, out = steady(job)
        return wall, f"1 start x 200 LBFGS iters, ll={out[0]:.1f}"

    if name == "afns5-mle64":
        spec, _ = create_model("AFNS5", tuple(common.MATURITIES), float_type="float32")
        data = common.afns5_panel()
        S = max(1, 64 // scale)
        starts = common.jitter_starts(common.afns5_params(spec), S).T  # (P, S)
        # cascade resolved EXPLICITLY through the one shared env helper
        # (estimation.optimize.resolve_estimation_env): the ledger honors
        # YFM_NEWTON/YFM_AMORT exactly the way bench.py's estimation benches
        # do, and the work description names which cascade actually ran
        kw = common.estimation_env_kwargs()

        def job():
            _, ll, best, _ = optimize.estimate(spec, data, starts,
                                               max_iters=100, **kw)
            return np.asarray([ll])

        wall, out = steady(job)
        cascade = "lbfgs" if not kw["second_order"] \
            else f"newton:{kw['second_order']}"
        # label from what actually RAN, not from the knob: warm_start=True
        # resolves through the process-wide registry, and run_all never
        # trains/registers a surrogate — the report's phase tags are the
        # ground truth of which cascade produced the measured wall
        if any(p.startswith("amortized")
               for p in optimize.last_multistart_report()["phase"]):
            cascade = "amort+" + cascade
        elif kw["warm_start"]:
            cascade += " (YFM_AMORT armed, no surrogate registered)"
        return wall, (f"{S} starts x 100 LBFGS iters, cascade={cascade}, "
                      f"ll={out[0]:.1f}")

    if name == "afns5-sv-pf":
        spec, _ = create_model("AFNS5", tuple(common.MATURITIES), float_type="float32")
        data = jnp.asarray(common.afns5_panel(), dtype=spec.dtype)
        D = max(1, 1000 // scale)
        # chunk the draw axis: 1000 draws x 1000 particles at once exhausts
        # HBM; 250-draw chunks are the stable envelope for the round-1 layout
        # (the lane-major kernel's smaller intermediates may admit more —
        # override with BENCH_PF_CHUNK to probe)
        CH = min(D, max(1, int(os.environ.get("BENCH_PF_CHUNK", "250"))))
        D = (D // CH) * CH
        draws = common.stationary_draws(spec, common.afns5_params(spec), D,
                                        scale=0.02)
        draws = jnp.asarray(draws, dtype=spec.dtype).reshape(D // CH, CH, -1)
        keys = jax.random.split(jax.random.PRNGKey(0), D).reshape(D // CH, CH, -1)
        # chunks dispatched as a python loop of jitted calls (lax.map over the
        # chunk axis faults the TPU runtime here)
        inner = jax.jit(jax.vmap(
            lambda p, k: particle_filter_loglik(spec, p, data, k,
                                                n_particles=1000)))

        def fn(ds, ks):
            return jnp.concatenate([inner(ds[i], ks[i])
                                    for i in range(ds.shape[0])])

        # warm/compile on one chunk, then time a single full pass (a second
        # full pass would double a ~15 min device run for no extra signal)
        np.asarray(jax.block_until_ready(inner(draws[0], keys[0])))
        t0 = time.perf_counter()
        out = np.asarray(jax.block_until_ready(fn(draws, keys)))
        wall = time.perf_counter() - t0
        n_fin = int(np.isfinite(out).sum())
        descr = f"{D} draws x 1000 particles (xla), finite {n_fin}/{D}"

        # On the chip, also race the fused Pallas PF kernel (ops/pallas_pf:
        # one grid program per draw, 1024 lane-tiled particles, on-chip
        # resampling; hw_verify.py holds its correctness gate) and keep the
        # faster engine — same winner-selection protocol as bench.py.  Noise
        # generation is inside the timed region, mirroring the XLA path's
        # in-scan key splitting.
        if jax.devices()[0].platform == "tpu":
            try:
                from yieldfactormodels_jl_tpu.ops.pallas_pf import pf_loglik_batch

                Tm1 = data.shape[1] - 1

                @jax.jit
                def pallas_chunk(d, key):
                    kz, ku = jax.random.split(key)
                    nzc = jax.random.normal(kz, (CH, Tm1, 1024), dtype=spec.dtype)
                    usc = jax.random.uniform(ku, (CH, Tm1), dtype=spec.dtype)
                    # n_particles=1000: the EXACT config-3 workload — lanes
                    # 1000..1023 are dead padding, counted against the kernel
                    return pf_loglik_batch(spec, d, data, nzc, usc,
                                           n_particles=1000, interpret=False)

                ckeys = jax.random.split(jax.random.PRNGKey(7), D // CH)

                def pallas_fn():
                    return jnp.concatenate([pallas_chunk(draws[i], ckeys[i])
                                            for i in range(D // CH)])

                np.asarray(jax.block_until_ready(pallas_chunk(draws[0],
                                                              ckeys[0])))
                t0 = time.perf_counter()
                out_p = np.asarray(jax.block_until_ready(pallas_fn()))
                wall_p = time.perf_counter() - t0
                fin_p = int(np.isfinite(out_p).sum())
                descr += (f"; pallas 1000 particles (1024-lane padded): "
                          f"{wall_p:.3f}s, finite {fin_p}/{D}, "
                          f"mean {np.mean(out_p[np.isfinite(out_p)]):.1f} vs "
                          f"xla {np.mean(out[np.isfinite(out)]):.1f}")
                if wall_p < wall and fin_p >= n_fin:
                    wall = wall_p
                    descr += "; winner=pallas"
                else:
                    descr += "; winner=xla"
            except Exception as e:  # Mosaic failure must not kill the config
                descr += f"; pallas engine failed ({type(e).__name__}: {e})"
        return wall, descr

    if name == "rolling-240":
        spec, _ = create_model("1C", tuple(common.MATURITIES), float_type="float32")
        data = common.dns_panel()
        T = data.shape[1]
        W = max(1, 240 // scale)
        S = 2
        ends = np.linspace(T - 240, T, 240, endpoint=False, dtype=np.int64) + 1
        ends = ends[-W:]
        raw0 = np.asarray(untransform_params(
            spec, jnp.asarray(common.dns_params(spec), dtype=spec.dtype)))
        starts2 = common.jitter_starts(raw0, S, scale=0.02)
        horizon = 12
        nan_pad = np.full((data.shape[0], horizon), np.nan, dtype=np.float32)
        data_ext = jnp.asarray(np.concatenate([data.astype(np.float32), nan_pad], axis=1))

        predict_w = jax.jit(jax.vmap(
            lambda p, end: api.predict(
                spec,
                p,
                jnp.where(jnp.arange(data_ext.shape[1])[None, :] < end,
                          data_ext, jnp.nan))))

        def job():
            params_ws, lls = optimize.estimate_windows(
                spec, data, jnp.asarray(starts2, dtype=spec.dtype),
                jnp.zeros((W,), dtype=jnp.int32), jnp.asarray(ends),
                max_iters=50)
            # estimate_windows returns log-likelihoods — higher is better
            best = jnp.argmax(jnp.where(jnp.isfinite(lls), lls, -jnp.inf), axis=1)
            best_p = jax.vmap(lambda ps, j: ps[j])(params_ws, best)
            from yieldfactormodels_jl_tpu.models.params import transform_params
            cons = jax.vmap(lambda p: transform_params(spec, p))(best_p)
            preds = predict_w(cons, jnp.asarray(ends))["preds"]
            return np.asarray(preds)

        wall, out = steady(job)
        return wall, f"{W} windows x {S} starts x 50 iters + {horizon}-step forecasts"

    if name == "ssd-nns-m3":
        # the reference driver's OWN model and scale: test.jl:22-27 runs the
        # score-driven neural "1SSD-NNS" with M=3 multi-starts through the
        # block-coordinate estimation (SURVEY §2.6 marks this filter — one
        # second-order-AD lax.scan per loss eval — as THE hot loop).  Groups
        # come from the reference's grouping table: a 22-dim Nelder–Mead
        # block (A/B/ω) and a 12-dim LBFGS block (δ/Φ).
        spec, _ = create_model("1SSD-NNS", tuple(common.MATURITIES),
                               float_type="float32")
        data = common.dns_panel()
        groups = list(api.get_param_groups(spec, None))
        iters = max(1, 10 // scale)
        # M=3 like the reference driver — but for MSED models the reference's
        # try_initializations REPLACES the start matrix with the single best
        # A/B-grid candidate (optimization.jl:153 + :73-114), so the real
        # workload is the 256-candidate grid + ONE surviving start; we
        # reproduce that faithfully and label it honestly.
        starts = common.jitter_starts(common.ssd_nns_params(spec), 3,
                                      scale=0.02).T  # (P, 3)

        def job():
            _, ll, best, conv = optimize.estimate_steps(
                spec, data, starts, groups, max_group_iters=iters)
            return np.asarray([ll])

        wall, out = steady(job)
        # engine note: on TPU the grid + NM candidate values and the L-BFGS
        # Armijo probes run the fused Pallas value kernel (ops/pallas_ssd,
        # gated by hw_verify's ssd-value check); gradients keep the scan.
        eng = ("pallas-value" if optimize._ssd_kernel_enabled(spec)
               else "scan")
        return wall, (f"256-cand A/B grid + best start x {iters} group iters "
                      f"(22-dim NM + 12-dim LBFGS blocks, engine={eng}), "
                      f"ll={out[0]:.5f}")

    if name in ("bootstrap-2000", "bootstrap-xl"):
        spec, _ = create_model("NS", tuple(common.MATURITIES), float_type="float32")
        data = common.dns_panel()
        # -xl: same workload × 16 so the wall measures throughput, not
        # dispatch latency (VERDICT r3 item 8; device wall target ≥ 2 s)
        base_R, G = (8000, 256) if name == "bootstrap-xl" else (2000, 64)
        R = max(1, base_R // scale)
        grid = np.linspace(0.1, 1.2, G)
        p = np.zeros(spec.n_params, dtype=np.float32)
        p[1:4] = [0.08, -0.06, 0.03]
        p[4:13] = np.diag([0.9, 0.9, 0.9]).reshape(-1)

        def job():
            losses, lo, hi, freq = bootstrap_lambda_grid(
                spec, p, data, grid, n_resamples=R, block_len=12)
            return np.asarray(losses)

        wall, out = steady(job)
        return wall, f"{R} resamples x {G} lambdas = {R * G} filter passes"

    raise ValueError(name)


def _side_main(side: str, configs):
    for name, cpu_scale in CONFIGS:
        if configs != "all" and name not in configs:
            continue
        scale = 1 if side == "device" else cpu_scale
        wall, descr = _run_config(name, scale)
        print(json.dumps({"config": name, "side": side, "wall_s": round(wall, 3),
                          "scale": scale, "work": descr}), flush=True)


def _orchestrate(configs):
    """Device subprocess (axon TPU) + pinned single-core CPU subprocess."""
    me = os.path.abspath(__file__)
    results = {}

    def collect(cmd, env, timeout, tag):
        # NEVER subprocess.run(timeout=...) here: its TimeoutExpired path
        # SIGKILLs the child, and a device child killed while holding the
        # relay claim wedges the TPU for hours (CLAUDE.md "TPU access rules";
        # this exact mechanism ended round 2's and round 3's windows —
        # VERDICT r3 item 7).  SIGTERM is catchable, lets the claim release;
        # the wait afterwards is unbounded by design.  File-backed output so
        # an abandoned child can keep logging without blocking on a full
        # unread pipe (same recipe as bench.py's orchestrator).
        import tempfile
        out_f = tempfile.NamedTemporaryFile("w+", suffix=f".{tag.replace(':', '_')}.out",
                                            delete=False)
        err_f = tempfile.NamedTemporaryFile("w+", suffix=f".{tag.replace(':', '_')}.err",
                                            delete=False)
        abandoned = False
        try:
            proc = subprocess.Popen(cmd, env=env, cwd=ROOT,
                                    stdout=out_f, stderr=err_f, text=True)
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                sys.stderr.write(f"# {tag} past {timeout}s; SIGTERM + patient "
                                 "wait (no SIGKILL: relay claim safety)\n")
                proc.terminate()
                try:
                    proc.wait(timeout=600)
                except subprocess.TimeoutExpired:
                    # TERM ignored (stuck inside a C call, e.g. wedged
                    # backend init): abandon the child WITHOUT killing it —
                    # an orphan that eventually exits is recoverable, a
                    # SIGKILL'd claim holder wedges the relay (same recipe
                    # as bench.py's orchestrator); keep its files on disk
                    sys.stderr.write(f"# {tag} ignored SIGTERM; abandoning "
                                     "unkilled and moving on\n")
                    abandoned = True
            out_f.flush()
            err_f.flush()
            with open(out_f.name) as fh:
                stdout = fh.read()
            with open(err_f.name) as fh:
                stderr = fh.read()
            if proc.returncode != 0:
                sys.stderr.write(f"# {tag} rc={proc.returncode}:\n"
                                 f"{stderr[-1500:]}\n")
            for line in stdout.splitlines():
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                results.setdefault(rec["config"], {})[rec["side"]] = rec
        finally:
            out_f.close()
            err_f.close()
            if not abandoned:  # an abandoned child may still be writing
                for path in (out_f.name, err_f.name):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass

    cpu_env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    cpu_env.update({"JAX_PLATFORMS": "cpu", "OMP_NUM_THREADS": "1",
                    "OPENBLAS_NUM_THREADS": "1"})
    # one subprocess per (config, side): a failure (OOM etc.) can't take the
    # remaining configs down with it
    names = [n for n, _ in CONFIGS] if configs == "all" else configs.split(",")
    for name in names:
        collect([sys.executable, me, "--side", "device", "--configs", name],
                dict(os.environ), 3000, f"device:{name}")
        collect(["taskset", "-c", "0", sys.executable, me,
                 "--side", "cpu", "--configs", name], cpu_env, 6000, f"cpu:{name}")

    out_path = os.path.join(HERE, "results.json")
    # merge over any previously recorded entries so a timed-out/failed config
    # doesn't erase its last successful measurement
    previous = {}
    if os.path.isfile(out_path):
        try:
            previous = {r["config"]: r for r in json.load(open(out_path))}
        except (json.JSONDecodeError, KeyError, TypeError):
            previous = {}
    merged = []
    for name, _scale in CONFIGS:
        if name not in results:
            if name in previous:
                merged.append(previous[name])
                print(json.dumps(previous[name]))
            continue
        rec = {"config": name}
        dev = results[name].get("device")
        cpu = results[name].get("cpu")
        if dev:
            rec["device_wall_s"] = dev["wall_s"]
            rec["work"] = dev["work"]
        if cpu:
            rec["cpu_scale"] = cpu["scale"]
            rec["cpu_wall_s_scaled"] = cpu["wall_s"]
            rec["cpu_wall_s_est"] = round(cpu["wall_s"] * cpu["scale"], 3)
        if dev and cpu and dev["wall_s"] > 0:
            rec["speedup_vs_1core"] = round(
                cpu["wall_s"] * cpu["scale"] / dev["wall_s"], 2)
        merged.append(rec)
        print(json.dumps(rec))
    with open(out_path, "w") as f:
        json.dump(merged, f, indent=2)
    sys.stderr.write(f"# wrote {out_path}\n")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--side", choices=["device", "cpu"], default=None)
    ap.add_argument("--configs", default="all",
                    help="'all' or comma-separated config names")
    a = ap.parse_args()
    cfgs = a.configs if a.configs == "all" else a.configs.split(",")
    if a.side:
        _side_main(a.side, cfgs)
    else:
        _orchestrate(a.configs)
