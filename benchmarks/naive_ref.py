"""Reference-equivalent naive CPU walls — the second ratio column.

BASELINE.md's config table compares the SAME optimal algorithm on device vs
a pinned CPU core.  That is the honest algorithm-for-algorithm ratio, but it
is not what a user of the reference experiences: the reference runs 1-thread
per-step loops (/root/reference/src/models/kalman/filter.jl:125-209 and
friends).  This script MEASURES that cost at FULL scale for the configs
where a naive run is feasible, using the same style of stand-in as
``bench.py``'s oracle line: NumPy per-step loops (tests/oracle.py) as the
proxy for a compiled per-step Julia loop — vectorized only *within* a step,
python loop over time/draws/resamples, one thread.

Measured here (full scale, no extrapolation):
  1. dns3-mle        scipy L-BFGS-B (2-point FD gradients, the naive stand-in
                     for ForwardDiff replays) over the NumPy per-step filter
  3. afns5-sv-pf     the same Rao-Blackwellized sqrt PF ported to NumPy
                     per-step loops, 1,000 draws x 1,000 particles
  5. bootstrap-2000  per-step re-OLS static filter, 2,000 x 64 passes

NOT measured — a full-scale naive run is infeasible (hours to days), and the
table in BASELINE.md reports an explicit LOWER BOUND computed from a unit
cost that IS measured here times the exact pass count (labeled as a bound,
never presented as a measurement):
  2. afns5-mle64     >= 64 starts x 100 iters x 2 passes x (measured
                     seconds/pass of the AFNS5 naive filter)
  4. rolling-240     >= 240 windows x 2 starts x 50 iters x 2 passes x
                     (measured seconds/pass at mean window length)
  6. ssd-nns-m3      >= (256 A/B-grid candidates + 10 group iters x 25
                     passes for the ONE surviving start — the reference's
                     MSED try_initializations collapses M starts to the
                     best grid candidate, optimization.jl:153) x (measured
                     seconds/pass of the naive score-driven filter)

Usage: taskset -c <core> python benchmarks/naive_ref.py [config ...]
Emits one JSON line per config: {"config", "naive_wall_s" | "unit_s", ...}.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
for p in (HERE, ROOT, os.path.join(ROOT, "tests")):
    if p not in sys.path:
        sys.path.insert(0, p)

import common  # noqa: E402  (benchmarks/common.py)
import oracle  # noqa: E402  (tests/oracle.py — independent NumPy loops)

_LOG_2PI = math.log(2.0 * math.pi)


def _dns3_matrices(spec, p):
    """Constrained DNS3 vector → (Z, Phi, delta, Omega, obs_var) in NumPy."""
    lo, hi = spec.layout["gamma"]
    Z = oracle.dns_loadings(float(p[lo]), np.asarray(spec.maturities))
    obs_var = float(p[spec.layout["obs_var"][0]])
    Ms = spec.state_dim
    C = np.zeros((Ms, Ms))
    rows, cols = spec.chol_indices
    a, _ = spec.layout["chol"]
    for k, (r, c) in enumerate(zip(rows, cols)):
        C[r, c] = p[a + k]
    lo, hi = spec.layout["delta"]
    delta = np.asarray(p[lo:hi], dtype=np.float64)
    lo, hi = spec.layout["phi"]
    Phi = np.asarray(p[lo:hi], dtype=np.float64).reshape(Ms, Ms)
    return Z, Phi, delta, C @ C.T, obs_var


def _np_transform(codes, raw):
    """NumPy copy of utils/transformations.apply_transforms (0 identity,
    1 exp, 2 2σ(x)−1) — the raw→constrained bijections the reference
    optimizes through."""
    out = raw.copy()
    out = np.where(codes == 1, np.exp(raw), out)
    out = np.where(codes == 2, 2.0 / (1.0 + np.exp(-raw)) - 1.0, out)
    return out


def _np_untransform(codes, p):
    out = p.copy()
    out = np.where(codes == 1, np.log(np.maximum(p, 1e-300)), out)
    with np.errstate(divide="ignore"):
        out = np.where(codes == 2, np.log((1.0 + p) / np.maximum(1.0 - p, 1e-300)),
                       out)
    return out


def naive_dns3_mle():
    """Config 1: 200-iteration L-BFGS over the per-step NumPy filter with
    2-point finite-difference gradients (the naive stand-in for the
    reference's ForwardDiff filter replays), in RAW (bijected) space like
    the reference's optimizer."""
    from scipy.optimize import minimize
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("1C", tuple(common.MATURITIES), float_type="float32")
    data = np.asarray(common.dns_panel(), dtype=np.float64)
    p0 = np.asarray(common.dns_params(spec), dtype=np.float64)
    codes = np.asarray(spec.transform_codes)
    raw0 = _np_untransform(codes, p0)
    nfev = [0]

    def nll(raw):
        nfev[0] += 1
        Z, Phi, delta, Om, ov = _dns3_matrices(spec, _np_transform(codes, raw))
        try:
            ll = oracle.kalman_filter_loglik(Z, Phi, delta, Om, ov, data)
        except np.linalg.LinAlgError:
            # probe stepped into singular-F territory; the reference
            # penalizes invalid points the same way (-Inf -> penalty)
            return 1e12
        return -ll if np.isfinite(ll) else 1e12

    t0 = time.perf_counter()
    res = minimize(nll, raw0, method="L-BFGS-B",
                   options=dict(maxiter=200, maxfun=10 ** 7))
    wall = time.perf_counter() - t0
    return wall, (f"{int(res.nit)} LBFGS iters, {nfev[0]} filter passes "
                  f"(2-point FD grads), ll={-res.fun:.1f}")


def _afns5_tensors(spec, draws):
    """Per-draw (Z, d, Phi, delta, chol_Om, beta0, S0) via the package's
    unpack (tiny vs the 360-step loops being timed), as NumPy arrays."""
    import jax.numpy as jnp
    from yieldfactormodels_jl_tpu.models import kalman as K
    from yieldfactormodels_jl_tpu.models.params import unpack_kalman
    from yieldfactormodels_jl_tpu.ops.particle import _measurement

    out = []
    for p in draws:
        kp = unpack_kalman(spec, jnp.asarray(p, dtype=jnp.float64))
        Z, d = _measurement(spec, kp, jnp.float64)
        st = K.init_state(spec, kp)
        P0 = 0.5 * (st.P + st.P.T) + 1e-9 * jnp.eye(spec.state_dim)
        Om = (0.5 * (kp.Omega_state + kp.Omega_state.T)
              + 1e-12 * jnp.eye(spec.state_dim))
        out.append(tuple(np.asarray(x, dtype=np.float64) for x in (
            Z, d, kp.Phi, kp.delta, jnp.linalg.cholesky(Om),
            st.beta, jnp.linalg.cholesky(P0), kp.obs_var)))
    return out


def _naive_pf_one_draw(rng, Z, d, Phi, delta, cholOm, beta0, S0, obs_var,
                       data, Pn, sv_phi=0.95, sv_sigma=0.2, ess_frac=0.5):
    """One draw of the Rao-Blackwellized sqrt PF as per-step NumPy loops —
    the same algorithm as ops/particle.py (Potter scalar updates, systematic
    resampling), vectorized only across the particle axis within a step."""
    Ms, N = beta0.shape[0], Z.shape[0]
    T = data.shape[1]
    beta = np.repeat(beta0[:, None], Pn, axis=1)           # (Ms, Pn)
    S = np.repeat(S0[:, :, None], Pn, axis=2)              # (Ms, Ms, Pn)
    h = np.zeros(Pn)
    logw = np.full(Pn, -math.log(Pn))
    total = 0.0
    for t in range(T - 1):
        y = data[:, t]
        h = sv_phi * h + sv_sigma * rng.standard_normal(Pn)
        obs = bool(np.all(np.isfinite(y)))
        r = obs_var * np.exp(h)
        sqrt_r = np.sqrt(r)
        b_u, S_u = beta.copy(), S.copy()
        ll = np.zeros(Pn)
        for i in range(N):
            z = Z[i]
            phi = np.einsum("mkp,m->kp", S_u, z)           # Sᵀz (Ms, Pn)
            f = np.einsum("kp,kp->p", phi, phi) + r
            v = y[i] - d[i] - z @ b_u
            Sphi = np.einsum("mkp,kp->mp", S_u, phi)       # P z
            b_u = b_u + Sphi * (v / f)
            alpha = 1.0 / (f + sqrt_r * np.sqrt(f))
            S_u = S_u - alpha[None, None, :] * (Sphi[:, None, :] * phi[None, :, :])
            ll -= 0.5 * (np.log(f) + v * v / f + _LOG_2PI)
        if obs:
            beta, S = b_u, S_u
        beta = delta[:, None] + Phi @ beta
        A = np.einsum("ij,jkp->ikp", Phi, S)
        # P = A Aᵀ + Ω, refactored per particle (LAPACK per-step batch loop)
        P = np.einsum("ikp,jkp->ijp", A, A) + (cholOm @ cholOm.T)[:, :, None]
        S = np.linalg.cholesky(P.transpose(2, 0, 1)).transpose(1, 2, 0)
        contributes = obs and t > 0
        if contributes:
            logw = logw + ll
            m = logw.max()
            step_ll = m + math.log(np.exp(logw - m).sum())
            total += step_ll
            logw -= step_ll
            w = np.exp(logw)
            ess = 1.0 / np.sum(w * w)
            if ess < ess_frac * Pn:
                pos = (np.arange(Pn) + rng.uniform()) / Pn
                idx = np.searchsorted(np.cumsum(w), pos)
                beta, S, h = beta[:, idx], S[:, :, idx], h[idx]
                logw = np.full(Pn, -math.log(Pn))
    return total


def naive_afns5_sv_pf(n_draws=1000, n_particles=1000):
    """Config 3: the full 1,000-draw x 1,000-particle PF sweep, per-step
    NumPy loops, same draws/panel as run_all's config 3."""
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("AFNS5", tuple(common.MATURITIES), float_type="float32")
    data = np.asarray(common.afns5_panel(), dtype=np.float64)
    draws = common.stationary_draws(spec, common.afns5_params(spec), n_draws,
                                    scale=0.02)
    tensors = _afns5_tensors(spec, draws)
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    lls = [_naive_pf_one_draw(rng, *tt[:7], float(tt[7]), data, n_particles)
           for tt in tensors]
    wall = time.perf_counter() - t0
    fin = int(np.isfinite(np.asarray(lls)).sum())
    return wall, f"{n_draws} draws x {n_particles} particles, finite {fin}/{n_draws}"


def naive_bootstrap(n_resamples=2000, n_lambdas=64, block_len=12):
    """Config 5: per-(resample, λ) static-filter passes with per-step re-OLS
    (models/filter.jl:93-110 semantics via tests/oracle.static_filter)."""
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("NS", tuple(common.MATURITIES), float_type="float32")
    data = np.asarray(common.dns_panel(), dtype=np.float64)
    N, T = data.shape
    grid = np.linspace(0.1, 1.2, n_lambdas)
    delta = np.array([0.08, -0.06, 0.03])
    Phi = np.diag([0.9, 0.9, 0.9])
    rng = np.random.default_rng(0)
    n_blocks = -(-T // block_len)
    t0 = time.perf_counter()
    losses = np.zeros((n_resamples, n_lambdas))
    Zs = [oracle.dns_loadings(math.log(lam - 1e-2), np.asarray(common.MATURITIES))
          for lam in grid]
    for r in range(n_resamples):
        starts = rng.integers(0, T - block_len + 1, n_blocks)
        idx = (starts[:, None] + np.arange(block_len)[None, :]).reshape(-1)[:T]
        resampled = data[:, idx]
        for g in range(n_lambdas):
            preds = oracle.static_filter(Zs[g], delta, Phi, resampled)
            v = resampled[:, 1:] - preds[:, :-1]
            losses[r, g] = -np.sum(v * v) / N / T
    wall = time.perf_counter() - t0
    return wall, f"{n_resamples} resamples x {n_lambdas} lambdas, per-step re-OLS"


def unit_afns5_pass():
    """Measured seconds per naive AFNS5 filter pass (the unit behind the
    config-2/4 lower bounds; same oracle loop bench.py uses)."""
    from yieldfactormodels_jl_tpu import create_model
    import jax.numpy as jnp
    from yieldfactormodels_jl_tpu.models import kalman as K
    from yieldfactormodels_jl_tpu.models.params import unpack_kalman
    from yieldfactormodels_jl_tpu.ops.particle import _measurement

    spec, _ = create_model("AFNS5", tuple(common.MATURITIES), float_type="float32")
    data = np.asarray(common.afns5_panel(), dtype=np.float64)
    p = common.afns5_params(spec)
    kp = unpack_kalman(spec, jnp.asarray(p, dtype=jnp.float64))
    Z, d = _measurement(spec, kp, jnp.float64)
    Z, dv = np.asarray(Z, dtype=np.float64), np.asarray(d, dtype=np.float64)
    Om = np.asarray(kp.Omega_state, dtype=np.float64)
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        oracle.kalman_filter_loglik(Z, np.asarray(kp.Phi), np.asarray(kp.delta),
                                    Om, float(kp.obs_var), data - dv[:, None])
    return (time.perf_counter() - t0) / reps, f"mean of {reps} full-panel passes"


def unit_longt_pass(T=20000):
    """Long-panel unit (the BENCH_LONGT dual-ratio wall): one naive per-step
    NumPy AFNS5 filter pass over a T=20,000 daily/intraday-scale history —
    what a user of the reference pays per likelihood evaluation on a long
    panel (1-thread per-step loop, kalman/filter.jl:125-209 semantics via
    tests/oracle.py).  Pairs with bench.py's ``BENCH_LONGT=1`` seq/assoc
    line for the BASELINE.md "longt-20k" dual-ratio row."""
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("AFNS5", tuple(common.MATURITIES),
                           float_type="float32")
    p = common.afns5_params(spec)
    (tt,) = _afns5_tensors(spec, [p])
    Z, d, Phi, delta, cholOm, beta0, S0, obs_var = tt
    # long stationary AFNS panel from the same DGP family as the T=360
    # configs (bench.py make_panel), generated inline at full length
    rng = np.random.default_rng(7)
    Ms = Phi.shape[0]
    x = np.linalg.solve(np.eye(Ms) - Phi, delta)
    Om = cholOm @ cholOm.T
    data = np.zeros((Z.shape[0], T))
    for t in range(T):
        x = delta + Phi @ x + rng.multivariate_normal(np.zeros(Ms), Om)
        data[:, t] = Z @ x + d + np.sqrt(obs_var) * rng.standard_normal(
            Z.shape[0])
    reps = 2
    t0 = time.perf_counter()
    for _ in range(reps):
        ll = oracle.kalman_filter_loglik(Z, Phi, delta, Om, float(obs_var),
                                         data - d[:, None])
    wall = (time.perf_counter() - t0) / reps
    return wall, (f"mean of {reps} naive per-step passes at T={T}, "
                  f"ll={ll:.1f}")


def unit_slr_pass(T=20000, sweeps=2, chunk=128):
    """Nonlinear long-panel unit (the BENCH_LONGT TVλ dual-ratio wall): one
    naive 1-thread NumPy ITERATED-SLR evaluation — the sequential affine
    pass plus ``sweeps`` chunked exact-EKF refinement sweeps
    (tests/oracle.iterated_slr_filter, the independent loop the engine is
    pinned against) — at the T=20,000 daily/intraday scale.  What a user of
    the reference pays to run the same algorithm as per-step loops: ~(1 +
    sweeps) sequential T-step walks with per-step relinearization and an
    N×N inverse each.  Pairs with bench.py's ``BENCH_LONGT=1``
    seq-vs-SLR TVλ line for the BASELINE.md dual-ratio row."""
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("TVλ", tuple(common.MATURITIES),
                           float_type="float32")
    p = oracle.stable_tvl_params(spec)
    Ms = spec.state_dim
    C = np.zeros((Ms, Ms))
    rows, cols = spec.chol_indices
    a, _ = spec.layout["chol"]
    for k, (r, c) in enumerate(zip(rows, cols)):
        C[r, c] = p[a + k]
    lo, hi = spec.layout["delta"]
    delta = np.asarray(p[lo:hi], dtype=np.float64)
    lo, hi = spec.layout["phi"]
    Phi = np.asarray(p[lo:hi], dtype=np.float64).reshape(Ms, Ms)
    ov = float(p[spec.layout["obs_var"][0]])
    mats = np.asarray(common.MATURITIES, dtype=np.float64)
    rng = np.random.default_rng(7)
    data = oracle.simulate_dns_panel(rng, mats, T=T, lam=0.5)
    t0 = time.perf_counter()
    *_, ll = oracle.iterated_slr_filter(Phi, delta, C @ C.T, ov, mats, data,
                                        sweeps=sweeps, chunk=chunk)
    wall = time.perf_counter() - t0
    return wall, (f"one naive iterated-SLR pass at T={T} "
                  f"(K={sweeps} sweeps, chunk={chunk}), ll={ll:.1f}")


def unit_msed_pass(T=20000, sweeps=2, chunk=256):
    """Score-driven long-panel unit (the BENCH_LONGT MSED dual-ratio wall):
    one naive 1-thread NumPy SCORE-TREE evaluation — the FD-linearized
    affine γ/β prefix passes plus ``sweeps`` chunked exact-recursion
    refinement sweeps (tests/oracle.linearized_score_filter, the
    independent loop the engine is pinned against) — at the T=20,000
    daily/intraday scale.  What a user of the reference pays to run the
    same algorithm as per-step loops: ~(2 + sweeps) sequential T-step
    walks, each an OLS solve + analytic score per step.  Pairs with
    bench.py's ``BENCH_LONGT=1`` seq-vs-tree MSED line for the BASELINE.md
    dual-ratio row."""
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("SD-NS", tuple(common.MATURITIES),
                           float_type="float32")
    p = oracle.stable_msed_params(spec)
    struct = {"A": np.array([p[0]]), "B": np.array([p[1]]),
              "omega": np.array([p[2]]), "delta": p[3:6],
              "Phi": p[6:15].reshape(3, 3).T}
    mats = np.asarray(common.MATURITIES, dtype=np.float64)
    rng = np.random.default_rng(7)
    data = oracle.simulate_dns_panel(rng, mats, T=T, lam=0.5)
    t0 = time.perf_counter()
    preds, _, _ = oracle.linearized_score_filter(struct, mats, data,
                                                 sweeps=sweeps, chunk=chunk)
    wall = time.perf_counter() - t0
    loss = oracle.msed_loss_from_preds(preds, data)
    return wall, (f"one naive score-tree pass at T={T} "
                  f"(K={sweeps} sweeps, chunk={chunk}), loss={loss:.6f}")


def naive_scenario_fan(R=256, G=16, D=8, Pn=128, S=6, h=12, n_paths=32,
                       block_len=12):
    """Scenario-lattice wall (the ``BENCH_SCEN`` dual-ratio denominator): a
    reference-equivalent 1-thread loop over the SAME cells the fused lattice
    evaluates at its bench defaults — R×G static re-OLS bootstrap passes,
    D SV particle-filter draws of ``Pn`` particles, and an S-shock stress
    fan (h-step density recursion + ``n_paths`` sampled paths per shock),
    all per-step NumPy loops over one AFNS5-shaped panel."""
    from yieldfactormodels_jl_tpu import create_model

    nspec, _ = create_model("NS", tuple(common.MATURITIES),
                            float_type="float32")
    aspec, _ = create_model("AFNS5", tuple(common.MATURITIES),
                            float_type="float32")
    data = np.asarray(common.afns5_panel(), dtype=np.float64)
    N, T = data.shape
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()

    # --- bootstrap face: R×G per-step re-OLS static passes ---------------
    grid = np.linspace(0.15, 1.0, G)
    delta3 = np.array([0.08, -0.06, 0.03])
    Phi3 = np.diag([0.9, 0.9, 0.9])
    Zs = [oracle.dns_loadings(math.log(lam - 1e-2),
                              np.asarray(common.MATURITIES)) for lam in grid]
    n_blocks = -(-T // block_len)
    for r in range(R):
        starts = rng.integers(0, T - block_len + 1, n_blocks)
        idx = (starts[:, None] + np.arange(block_len)[None, :]).reshape(-1)[:T]
        resampled = data[:, idx]
        for g in range(G):
            preds = oracle.static_filter(Zs[g], delta3, Phi3, resampled)
            v = resampled[:, 1:] - preds[:, :-1]
            _ = -np.sum(v * v) / N / T

    # --- SV-draw face: D particle filters of Pn particles ----------------
    draws = common.stationary_draws(aspec, common.afns5_params(aspec), D,
                                    scale=0.02)
    tensors = _afns5_tensors(aspec, draws)
    for tt in tensors:
        _naive_pf_one_draw(rng, *tt[:7], float(tt[7]), data, Pn)

    # --- shock fan: filter to the origin once, then S densities + paths --
    (tt,) = _afns5_tensors(aspec, [common.afns5_params(aspec)])
    Z, d, Phi, delta, cholOm, beta, S0, obs_var = tt
    Ms = Phi.shape[0]
    Om = cholOm @ cholOm.T
    P = S0 @ S0.T
    for t in range(T):  # per-step filtered moments (joint form)
        y = data[:, t]
        F = Z @ P @ Z.T + obs_var * np.eye(N)
        K = P @ Z.T @ np.linalg.inv(F)
        beta = beta + K @ (y - d - Z @ beta)
        P = (np.eye(Ms) - K @ Z) @ P
        if t < T - 1:
            beta = delta + Phi @ beta
            P = Phi @ P @ Phi.T + Om
    # the standard_fan pattern (baseline, parallel +/-, twist +/-, vol x2),
    # cycled for any S
    fan_cells = [(0, 0.0, 1.0), (0, .5, 1.0), (0, -.5, 1.0),
                 (1, .5, 1.0), (1, -.5, 1.0), (0, 0.0, 2.0)]
    shifts = np.zeros((S, Ms))
    vols = np.ones(S)
    for s in range(S):
        f, v, sc = fan_cells[s % len(fan_cells)]
        shifts[s, f] = v
        vols[s] = sc
    for s in range(S):
        b, Pm = beta + shifts[s], P * vols[s] ** 2
        for _k in range(h):  # analytic density recursion
            b = delta + Phi @ b
            Pm = Phi @ Pm @ Phi.T + Om
            _ = Z @ Pm @ Z.T + obs_var * np.eye(N)
        for _p in range(n_paths):  # sampled paths, per-step loops
            bp = beta + shifts[s] + np.linalg.cholesky(
                Pm + 1e-9 * np.eye(Ms)) @ rng.standard_normal(Ms)
            for _k in range(h):
                bp = delta + Phi @ bp + cholOm @ rng.standard_normal(Ms)
                _ = Z @ bp + d + math.sqrt(obs_var) * rng.standard_normal(N)

    wall = time.perf_counter() - t0
    return wall, (f"{R}x{G} re-OLS passes + {D} PF draws x {Pn} particles + "
                  f"{S}-shock fan (h={h}, {n_paths} paths)")


def unit_ssd_nns_pass():
    """Measured seconds per naive score-driven-neural filter pass (config-6
    lower-bound unit): tests/oracle.msed_neural_filter — per-step loop with
    the finite-difference inner score, the NumPy stand-in for the
    reference's per-step AD score."""
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("1SSD-NNS", tuple(common.MATURITIES),
                           float_type="float32")
    data = np.asarray(common.dns_panel(), dtype=np.float64)
    p = common.ssd_nns_params(spec)
    expand = lambda u: np.concatenate([np.full(9, u[0]), np.full(9, u[1])])
    lo, hi = spec.layout["A"]; A = expand(p[lo:hi])
    lo, hi = spec.layout["B"]; B = expand(p[lo:hi])
    lo, hi = spec.layout["omega"]; omega = np.asarray(p[lo:hi])
    lo, hi = spec.layout["delta"]; delta = np.asarray(p[lo:hi])
    lo, hi = spec.layout["phi"]; Phi = np.asarray(p[lo:hi]).reshape(3, 3).T
    struct = {"A": A, "B": B, "omega": omega, "delta": delta, "Phi": Phi}
    t0 = time.perf_counter()
    oracle.msed_neural_filter(struct, np.asarray(common.MATURITIES), data,
                              transform_bool=True, scale_grad=True,
                              forget_factor=spec.forget_factor)
    return time.perf_counter() - t0, "1 full-panel pass (FD inner score)"


def unit_fan(subs=24, S=6, h=8):
    """Measured seconds for ONE update cycle of the pre-streaming serving
    answer (the ``load-fan-bench`` naive denominator): a single online
    filter update (element-masked per-step NumPy loop) followed by ``subs``
    FULL stress-fan recomputes — per subscriber, per shock, the h-step
    density recursion in straight float64 loops
    (tests/oracle.fan_refresh).  This is the per-update cost the
    ScenarioStreamHub's one-launch delta refresh replaces."""
    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.models.params import unpack_kalman

    spec, _ = create_model("1C", tuple(common.MATURITIES),
                           float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    rng = np.random.default_rng(3)
    data = oracle.simulate_dns_panel(rng, np.asarray(common.MATURITIES),
                                     T=96)
    kp = unpack_kalman(spec, p)
    Z = oracle.dns_loadings(float(p[spec.layout["gamma"][0]]),
                            np.asarray(common.MATURITIES))
    Phi, delta = np.asarray(kp.Phi), np.asarray(kp.delta)
    Om, ov = np.asarray(kp.Omega_state), float(kp.obs_var)
    d = np.zeros(spec.N)
    # the standard 6-shock fan's displacements (estimation/scenario.py),
    # truncated to the first S rows when the caller shrinks the fan
    full_shifts = np.zeros((6, spec.state_dim))
    full_shifts[1, 0], full_shifts[2, 0] = 0.5, -0.5
    full_shifts[3, 1], full_shifts[4, 1] = -0.5, 0.5
    full_vols = np.ones(6)
    full_vols[5] = 1.5
    shifts = np.zeros((S, spec.state_dim))
    shifts[: min(S, 6)] = full_shifts[: min(S, 6)]
    vols = np.ones(S)
    vols[: min(S, 6)] = full_vols[: min(S, 6)]
    betas, Ps, _ = oracle.online_filter(Z, d, Phi, delta, Om, ov,
                                        data[:, :64])
    t0 = time.perf_counter()
    betas2, Ps2, _ = oracle.online_filter(Z, d, Phi, delta, Om, ov,
                                          data[:, 64:65])
    for _ in range(subs):
        oracle.fan_refresh(Z, d, Phi, delta, Om, ov, betas[-1], Ps[-1],
                           shifts, vols, h)
    wall = time.perf_counter() - t0
    return wall, (f"1 online update + {subs} full {S}-shock h={h} fan "
                  f"recomputes (per-step NumPy loops, 1C f64)")


def unit_newton_iteration():
    """Measured seconds for ONE naive second-order iteration at the DNS3
    config: the reference-equivalent way to get a Newton step is a
    finite-difference Hessian of the filter loglik — (P+1)² per-step NumPy
    filter replays (FD-of-FD-gradient, the ForwardDiff-Hessian stand-in) —
    plus the P+1-pass gradient it rides on.  This is the BENCH_NEWTON
    cascade's naive denominator: ops/newton.py's dense Fisher solve prices
    the same curvature at ~P linearized passes for the WHOLE start batch
    in one program (docs/DESIGN.md §17)."""
    from yieldfactormodels_jl_tpu import create_model

    spec, _ = create_model("1C", tuple(common.MATURITIES),
                           float_type="float32")
    data = np.asarray(common.dns_panel(), dtype=np.float64)
    p0 = np.asarray(common.dns_params(spec), dtype=np.float64)
    codes = np.asarray(spec.transform_codes)
    raw0 = _np_untransform(codes, p0)
    P = raw0.shape[0]
    npass = [0]

    def nll(raw):
        npass[0] += 1
        Z, Phi, delta, Om, ov = _dns3_matrices(spec, _np_transform(codes, raw))
        try:
            ll = oracle.kalman_filter_loglik(Z, Phi, delta, Om, ov, data)
        except np.linalg.LinAlgError:
            return 1e12
        return -ll if np.isfinite(ll) else 1e12

    t0 = time.perf_counter()
    eps = 1e-5 * np.maximum(1.0, np.abs(raw0))
    g = np.zeros(P)
    for i in range(P):  # forward-difference gradient: P+1 passes
        e = np.zeros(P); e[i] = eps[i]
        g[i] = (nll(raw0 + e) - nll(raw0)) / eps[i]
    H = np.zeros((P, P))
    for i in range(P):  # FD of the FD gradient: (P+1)·P more passes
        e = np.zeros(P); e[i] = eps[i]
        for j in range(P):
            ej = np.zeros(P); ej[j] = eps[j]
            H[i, j] = ((nll(raw0 + e + ej) - nll(raw0 + e))
                       / eps[j] - g[j]) / eps[i]
    np.linalg.solve(0.5 * (H + H.T) + 1e-8 * np.eye(P), -g)
    wall = time.perf_counter() - t0
    return wall, (f"{npass[0]} filter passes for one FD-Hessian Newton "
                  f"iteration (P={P})")


def unit_amort():
    """Measured seconds for ONE naive amortized refit at the config-2 shape
    (the BENCH_AMORT dual-ratio denominator): the surrogate forward pass as
    straight per-step NumPy loops (tests/oracle.amortizer_forward — the
    independent implementation the jitted "deepset" kernel is pinned
    against) plus ONE naive per-step filter pass to evaluate the predicted
    point.  This is what the amortized request-path refit costs without the
    compiled batch-last forward program and the fused polish — the honest
    1-thread floor for the SAME algorithm; the cold multi-start it replaces
    is priced by ``unit-afns5-pass`` × its pass count."""
    import jax
    import jax.numpy as jnp

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.estimation.amortize import (
        AmortizerConfig, init_params, raw_from_net, set_normalization)
    from yieldfactormodels_jl_tpu.models.params import (transform_params,
                                                        unpack_kalman)
    from yieldfactormodels_jl_tpu.ops.particle import _measurement

    spec, _ = create_model("AFNS5", tuple(common.MATURITIES),
                           float_type="float64")
    data = np.asarray(common.afns5_panel(), dtype=np.float64)
    cfg = AmortizerConfig()
    params = init_params(cfg, spec, jax.random.PRNGKey(0))
    params = set_normalization(params, data[:, :, None])
    params = {k: np.asarray(v) for k, v in params.items()}
    t0 = time.perf_counter()
    net = oracle.amortizer_forward(params, data)          # NumPy loops
    raw = raw_from_net(spec, net[None])[0]
    cons = np.asarray(transform_params(spec, jnp.asarray(raw)))
    kp = unpack_kalman(spec, jnp.asarray(cons))
    Z, d = _measurement(spec, kp, jnp.float64)
    try:
        ll = oracle.kalman_filter_loglik(
            np.asarray(Z, dtype=np.float64), np.asarray(kp.Phi),
            np.asarray(kp.delta), np.asarray(kp.Omega_state),
            float(kp.obs_var),
            data - np.asarray(d, dtype=np.float64)[:, None])
    except np.linalg.LinAlgError:
        ll = float("-inf")                                # untrained net: ok
    wall = time.perf_counter() - t0
    return wall, (f"1 naive forward pass + 1 naive filter eval "
                  f"(T={data.shape[1]}, ll={ll:.1f})")


RUNNERS = {
    "dns3-mle": naive_dns3_mle,
    "afns5-sv-pf": naive_afns5_sv_pf,
    "bootstrap-2000": naive_bootstrap,
    "unit-afns5-pass": unit_afns5_pass,
    "unit-longt-pass": unit_longt_pass,
    "unit-slr-pass": unit_slr_pass,
    "unit-msed-pass": unit_msed_pass,
    "unit-ssd-pass": unit_ssd_nns_pass,
    "scenario-fan": naive_scenario_fan,
    "unit-fan": unit_fan,
    "unit-newton-iteration": unit_newton_iteration,
    "unit-amort": unit_amort,
}


def main(argv):
    names = argv or list(RUNNERS)
    for name in names:
        wall, descr = RUNNERS[name]()
        print(json.dumps({"config": name, "naive_wall_s": round(wall, 3),
                          "work": descr}), flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
