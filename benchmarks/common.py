"""Shared panel/parameter builders for the BASELINE.md benchmark configs.

Synthetic Liu–Wu-shaped monthly panels (N=20 maturities, T=360 months) from
stationary DNS/AFNS DGPs — the same shapes the repo-root ``bench.py`` uses,
factored out for the five-config suite in run_all.py.
"""

from __future__ import annotations

import math
import time

import numpy as np

N_MATURITIES = 20
T_MONTHS = 360

MATURITIES_M = np.array([3, 6, 9, 12, 15, 18, 21, 24, 30, 36, 48, 60, 72, 84,
                         96, 108, 120, 180, 240, 360], dtype=np.float64)
MATURITIES = MATURITIES_M / 12.0


def grad_agreement(g_a, g_b, cos_min=0.999, norm_tol=0.05):
    """Direction + magnitude agreement of two gradient batches (rows = lanes).

    Elementwise f32 comparison is cancellation noise at the ~1e7 gradient
    norms these models produce; what an L-BFGS line search actually consumes
    is the direction (cosine) and the step scale (norm ratio).  Shared by
    ``bench.py`` and ``hw_verify.py`` so the two harnesses can never disagree
    about what "agrees" means.  Returns ``(ok, detail)``; an EMPTY batch (no
    finite lanes — exactly the regression a harness exists to catch) is a
    clean ``(False, ...)``, not a zero-size reduction crash.
    """
    g_a, g_b = np.asarray(g_a), np.asarray(g_b)
    if g_a.size == 0 or g_a.shape[0] == 0:
        return False, "no finite lanes"
    na = np.linalg.norm(g_a, axis=1)
    nb = np.linalg.norm(g_b, axis=1)
    cos = np.sum(g_a * g_b, axis=1) / np.maximum(na * nb, 1e-12)
    ratio = np.abs(na / np.maximum(nb, 1e-12) - 1)
    ok = bool(cos.min() > cos_min) and bool(np.all(ratio < norm_tol))
    return ok, f"cos_min {cos.min():.6f}, norm_ratio_max {ratio.max():.3f}"


def steady_wall(fn, arg, reps=5):
    """Warm (compile) then time ``reps`` back-to-back calls, hard-synced.

    The shared warm-then-time discipline for the benchmark scripts (bench.py
    and run_all.py carry older local variants with their own flow-specific
    semantics; new scripts should use this one)."""
    import jax
    import numpy as _np

    _np.asarray(jax.block_until_ready(fn(arg)))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(arg)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def dns_panel(seed=0, lam=0.5, T=T_MONTHS):
    """3-factor DNS DGP panel (N, T)."""
    rng = np.random.default_rng(seed)
    tau = lam * MATURITIES
    Z = np.ones((N_MATURITIES, 3))
    Z[:, 1] = (1 - np.exp(-tau)) / tau
    Z[:, 2] = Z[:, 1] - np.exp(-tau)
    Phi = np.diag([0.98, 0.94, 0.9])
    delta = np.array([0.08, -0.06, 0.03])
    x = np.linalg.solve(np.eye(3) - Phi, delta)
    data = np.zeros((N_MATURITIES, T))
    for t in range(T):
        x = delta + Phi @ x + 0.05 * rng.standard_normal(3)
        data[:, t] = Z @ x + 0.02 * rng.standard_normal(N_MATURITIES)
    return data + 4.0


def afns5_panel(seed=0, T=T_MONTHS):
    """5-factor AFNS (AFGNS) DGP panel (N, T)."""
    rng = np.random.default_rng(seed)
    lam1, lam2 = 0.5, 0.15
    Z = np.ones((N_MATURITIES, 5))
    for col, lam in ((1, lam1), (3, lam2)):
        tau = lam * MATURITIES
        Z[:, col] = (1 - np.exp(-tau)) / tau
        Z[:, col + 1] = Z[:, col] - np.exp(-tau)
    Phi = np.diag([0.98, 0.94, 0.9, 0.92, 0.88])
    delta = np.array([0.08, -0.06, 0.03, -0.02, 0.01])
    x = np.linalg.solve(np.eye(5) - Phi, delta)
    data = np.zeros((N_MATURITIES, T))
    for t in range(T):
        x = delta + Phi @ x + 0.05 * rng.standard_normal(5)
        data[:, t] = Z @ x + 0.02 * rng.standard_normal(N_MATURITIES)
    return data + 4.0


def dns_params(spec):
    """Plausible constrained DNS ('1C') parameter vector."""
    p = np.zeros(spec.n_params)
    lo, hi = spec.layout["gamma"]
    p[lo:hi] = math.log(0.5 - 1e-2)
    lo, hi = spec.layout["obs_var"]
    p[lo:hi] = 4e-4
    k = spec.layout["chol"][0]
    for j in range(spec.state_dim):
        for i in range(j + 1):
            p[k] = 0.05 + 0.01 * i if i == j else 0.002
            k += 1
    lo, hi = spec.layout["delta"]
    p[lo:hi] = [0.08, -0.06, 0.03][: hi - lo] + [0.0] * max(0, hi - lo - 3)
    lo, hi = spec.layout["phi"]
    p[lo:hi] = np.diag([0.98, 0.94, 0.9][: spec.state_dim]).reshape(-1)
    return p


def afns5_params(spec):
    """Plausible constrained AFNS5 parameter vector."""
    p = np.zeros(spec.n_params)
    p[0:2] = [math.log(0.5), math.log(0.15)]
    lo, hi = spec.layout["obs_var"]
    p[lo:hi] = 4e-4
    k = spec.layout["chol"][0]
    for j in range(5):
        for i in range(j + 1):
            p[k] = 0.05 + 0.01 * i if i == j else 0.002
            k += 1
    lo, hi = spec.layout["delta"]
    p[lo:hi] = [4.0, -1.0, 0.5, -0.3, 0.2]
    lo, hi = spec.layout["phi"]
    p[lo:hi] = np.diag([0.98, 0.94, 0.9, 0.92, 0.88]).reshape(-1)
    return p


def ssd_nns_params(spec):
    """Plausible constrained 1SSD-NNS (score-driven neural) vector — the
    reference driver's flagship model (test.jl:22-27).  Layout: EWMA step
    sizes A, persistence B, 18 neural-loading weights ω, state intercept δ,
    transition Φ (models/specs.py msed_neural)."""
    rng = np.random.default_rng(3)
    p = np.zeros(spec.n_params)
    lo, hi = spec.layout["A"]
    p[lo:hi] = 1e-4
    lo, hi = spec.layout["B"]
    p[lo:hi] = 0.98
    lo, hi = spec.layout["omega"]
    p[lo:hi] = rng.standard_normal(hi - lo) / 10
    lo, hi = spec.layout["delta"]
    p[lo:hi] = [0.3, -0.1, 0.05]
    lo, hi = spec.layout["phi"]
    p[lo:hi] = np.diag([0.95, 0.9, 0.85]).T.reshape(-1)
    return p


def estimation_env_kwargs():
    """The estimation-cascade env knobs (``YFM_NEWTON`` / ``YFM_AMORT``)
    resolved into EXPLICIT ``estimate()`` kwargs — ONE resolution, owned by
    ``estimation.optimize.resolve_estimation_env``, shared by run_all.py's
    config 2 and bench.py's opt-in estimation benches so the perf ledger can
    never measure a different cascade than the headline (ISSUE 15)."""
    from yieldfactormodels_jl_tpu.estimation.optimize import (
        resolve_estimation_env)

    return resolve_estimation_env()


def jitter_starts(p, n_starts, seed=1, scale=0.05):
    """(S, P) stack of jittered copies of ``p`` (multi-start initialization)."""
    rng = np.random.default_rng(seed)
    s = np.tile(p, (n_starts, 1))
    s += scale * rng.standard_normal(s.shape) * np.maximum(np.abs(p), 0.01)[None, :]
    return s


def stationary_draws(spec, p, n_draws, seed=1, scale=0.02):
    """Jittered parameter draws with Φ projected back inside the unit circle.

    A plain jitter makes ~16% of AFNS5 draws non-stationary (spectral radius
    of Φ ≥ 1), for which −Inf is the *correct* likelihood sentinel — a draw
    sampler for evaluation sweeps must not produce invalid parameters in the
    first place.  Rows whose Φ has ρ(Φ) ≥ 1 are rescaled by 0.995/ρ."""
    draws = jitter_starts(p, n_draws, seed=seed, scale=scale)
    lo, hi = spec.layout["phi"]
    Ms = spec.state_dim
    for i in range(n_draws):
        Phi = draws[i, lo:hi].reshape(Ms, Ms)
        rho = float(np.max(np.abs(np.linalg.eigvals(Phi))))
        if rho >= 1.0:
            draws[i, lo:hi] = (Phi * (0.995 / rho)).reshape(-1)
    return draws
