"""Device evidence for the BASELINE.md roofline claim (VERDICT r3 #9).

BASELINE.md argues the fused Pallas Kalman value kernel is LATENCY-BOUND on
its serial dependency chain (T × N-chain of rank-1 updates), achieving ~1-2%
of VPU peak — credible but argued, not traced.  This script produces the
evidence two ways:

1. **Batch sweep** — steady-state wall vs batch size for the fused kernel,
   in WHOLE grid programs: the kernel pads any batch up to TILE = 8×128 =
   1024 draws per grid program (ops/pallas_kf.py), so the sweep runs B =
   1024·nb only — sub-TILE batches all execute one identical padded program
   and would poison the scaling read.  A latency-bound kernel's wall grows
   ~linearly with the number of serialized grid programs (TPU v5e has ONE
   TensorCore) and evals/s stays FLAT; launch-overhead slack shows evals/s
   RISING with nb.  The sweep separates those regimes with numbers.
2. **jax.profiler trace** — one traced run per variant into
   ``<workdir>/trace`` (Perfetto/TensorBoard-readable artifact; the driver
   archives it), with the kernel region annotated.

Prints one JSON line per (variant, batch) and a summary verdict line.
Device-only: exits 0 with a skip note off-TPU (the sweep measures Mosaic
executables, not interpret mode).
"""

from __future__ import annotations

import json
import os
import sys
from functools import partial

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
for p in (HERE, ROOT):
    if p not in sys.path:
        sys.path.insert(0, p)

import common  # noqa: E402

WORKDIR = os.environ.get("RECOVER_WORKDIR", "/tmp/r4")


def main() -> int:
    import jax
    import jax.numpy as jnp

    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.ops import pallas_kf
    from yieldfactormodels_jl_tpu.utils.profiling import annotate, device_trace

    if jax.devices()[0].platform != "tpu":
        print(json.dumps({"skipped": "not on TPU (sweep measures Mosaic "
                                     "executables, not interpret mode)"}))
        return 0

    spec, _ = create_model("AFNS5", tuple(common.MATURITIES),
                           float_type="float32")
    data = jnp.asarray(common.afns5_panel(), dtype=jnp.float32)

    kernel = jax.jit(partial(pallas_kf.batched_loglik, spec, data=data))
    walls = {}
    for B in (1024, 2048, 4096, 8192):  # whole TILE-sized grid programs only
        batch = jnp.asarray(common.stationary_draws(
            spec, common.afns5_params(spec), B, scale=0.02), jnp.float32)
        w = common.steady_wall(kernel, batch)
        walls[B] = w
        print(json.dumps({"variant": "pallas-value", "batch": B,
                          "grid_programs": B // 1024,
                          "wall_s": round(w, 6),
                          "evals_per_s": round(B / w, 1)}), flush=True)

    # one traced run for the artifact (largest batch: clearest timeline)
    logdir = os.path.join(WORKDIR, "trace")
    batch = jnp.asarray(common.stationary_draws(
        spec, common.afns5_params(spec), 1024, scale=0.02), jnp.float32)
    np.asarray(jax.block_until_ready(kernel(batch)))
    with device_trace(logdir):
        with annotate("pallas_kf.batched_loglik[B=1024]"):
            jax.block_until_ready(kernel(batch))

    # verdict: compare wall scaling against the two structural hypotheses
    # (8× the grid programs ⇒ wall ≈8× and rate ≈1× if serialized/
    # latency-bound; rate rising well above 1 means per-launch slack)
    r_wall = walls[8192] / walls[1024]
    r_rate = (8192 / walls[8192]) / (1024 / walls[1024])
    verdict = ("latency-bound: wall scales ~linearly with serialized grid "
               "programs, evals/s flat" if r_rate < 2.0 else
               "launch-overhead slack: evals/s still rising with batch — "
               "larger batches or multi-draw sublane packing would help")
    print(json.dumps({"variant": "pallas-value",
                      "wall_8192_over_1024": round(r_wall, 2),
                      "rate_8192_over_1024": round(r_rate, 2),
                      "verdict": verdict, "trace_dir": logdir}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
