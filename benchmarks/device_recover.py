"""Standing TPU-recovery loop (BASELINE.md round-3 "TPU availability" note).

The single tunneled chip has been dark since the round-2 claim incident
(`UNAVAILABLE: TPU backend setup/compile error` on every backend init).  This
script is the persisted version of the recovery path BASELINE.md describes:

  probe →(fail)→ sleep → probe → ... →(success)→ device sequence → merge

One probe = one subprocess that initializes the axon backend and runs a tiny
computation.  Probes are PATIENT: the relay rules (CLAUDE.md) forbid killing a
client mid-claim — a SIGKILL'd claimant is exactly what wedged the relay — so
a probe is given a long soft deadline, then SIGTERM (catchable; Python-side
init failures surface as exceptions, so TERM lands in interpreter code), then
an unbounded wait.  Strictly one client at a time: the loop is sequential and
nothing else in the session may open a TPU client while it runs.

On the first successful probe it runs, in order (same order as VERDICT r2 #1):
  1. run_all.py --side device --configs all   (seven configs, JSON lines)
  2. hw_verify.py                             (on-chip kernel verification)
  3. bench.py                                 (headline JSON line)
  4. merge_device.py <log>                    (fold device walls into
                                               results.json as coherent pairs)
then writes <workdir>/SUCCESS and exits.  A deadline (default 10 h) stops the
loop so the driver's end-of-round bench.py never contends with a probe; touch
<workdir>/stop for an early exit.

Usage (detached):
  nohup python benchmarks/device_recover.py >/tmp/r3/recover.out 2>&1 &
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
WORKDIR = os.environ.get("RECOVER_WORKDIR", "/tmp/r4")
LOG = os.path.join(WORKDIR, "probe_loop.log")
PROBE_SOFT_S = float(os.environ.get("RECOVER_PROBE_SOFT_S", "2700"))
SLEEP_S = float(os.environ.get("RECOVER_SLEEP_S", "120"))
DEADLINE_S = float(os.environ.get("RECOVER_DEADLINE_S", str(10 * 3600)))
STEP_SOFT_S = float(os.environ.get("RECOVER_STEP_SOFT_S", "5400"))

PROBE_SRC = (
    "import jax, json;"
    "d = jax.devices();"
    "import jax.numpy as jnp;"
    "x = float(jnp.arange(8.0).sum());"
    "print(json.dumps({'platform': d[0].platform, 'n': len(d), 'x': x}))"
)


def _log(msg: str) -> None:
    line = f"# [{time.strftime('%Y-%m-%d %H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def _patient_run(cmd, soft_s, tag, extra_env=None):
    """Run cmd; after soft_s send SIGTERM (never SIGKILL), then wait.

    Returns (returncode, stdout_text).  stdout/stderr stream to the log file
    so device JSON lines land where merge_device.py expects them.
    """
    env = dict(os.environ)
    # persistent compile cache: remote compiles through the relay dominate
    # every device step's wall time; cache executables across processes so
    # re-runs (second windows, bench after hw_verify) skip them where the
    # PJRT plugin supports serialization (harmless no-op where it doesn't).
    # Device steps only — XLA:CPU AOT executables are host-specific and a
    # stale CPU cache risks SIGILL (see hw_verify.py).
    if (extra_env or {}).get("JAX_PLATFORMS") != "cpu":
        env.setdefault("JAX_COMPILATION_CACHE_DIR",
                       os.path.join(WORKDIR, "jax_cache"))
    else:
        # an inherited cache dir must not reach CPU steps either (popping,
        # not just skipping the setdefault): host-specific XLA:CPU AOT
        # artifacts from another container risk SIGILL
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
    if extra_env:
        env.update(extra_env)
    with open(LOG, "a") as logf:
        logf.write(f"# --- {tag}: {' '.join(cmd)}\n")
        logf.flush()
        out_path = os.path.join(WORKDIR, f"{tag}.out")
        with open(out_path, "w") as outf:
            proc = subprocess.Popen(cmd, cwd=ROOT, env=env,
                                    stdout=outf, stderr=logf)
            try:
                proc.wait(timeout=soft_s)
            except subprocess.TimeoutExpired:
                _log(f"{tag}: past soft deadline {soft_s:.0f}s -> SIGTERM "
                     "(no SIGKILL per relay rules), waiting")
                try:
                    proc.send_signal(signal.SIGTERM)
                except ProcessLookupError:
                    pass
                proc.wait()  # unbounded: let the claim resolve
    out = open(out_path).read()
    with open(LOG, "a") as logf:
        logf.write(out if out.endswith("\n") or not out else out + "\n")
    return proc.returncode, out


def probe_once(i: int) -> bool:
    rc, out = _patient_run([sys.executable, "-c", PROBE_SRC],
                           PROBE_SOFT_S, f"probe_{i:03d}")
    ok = False
    for line in out.splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("platform") == "tpu":
            ok = True
    _log(f"probe {i}: rc={rc} tpu={'YES' if ok else 'no'} "
         f"({out.strip()[:120]!r})")
    return ok


def device_sequence() -> None:
    _log("TPU is back: running the device sequence")
    catalog = {
        "run_all_device":
            [sys.executable, os.path.join(HERE, "run_all.py"),
             "--side", "device", "--configs", "all"],
        "pf_race":  # config 3 only: XLA lane-major vs fused Pallas PF
            [sys.executable, os.path.join(HERE, "run_all.py"),
             "--side", "device", "--configs", "afns5-sv-pf"],
        "ssd_race":  # config 6 only: closed-form group-2 + SSD value kernel
            [sys.executable, os.path.join(HERE, "run_all.py"),
             "--side", "device", "--configs", "ssd-nns-m3"],
        "hw_grad":  # the adjoint gates alone, small shapes — the round-3
                    # optimum-regression anomaly's decisive evidence, first
            [sys.executable, os.path.join(HERE, "hw_verify.py"),
             "--only", "grad"],
        "hw_verify": [sys.executable, os.path.join(HERE, "hw_verify.py")],
        "bench": [sys.executable, os.path.join(ROOT, "bench.py")],
        "trace":  # roofline evidence: batch sweep + jax.profiler artifact
            [sys.executable, os.path.join(HERE, "trace_kernel.py")],
    }
    wanted = [w.strip() for w in os.environ.get(
        "RECOVER_STEPS",
        "hw_grad,ssd_race,pf_race,bench,trace,hw_verify,run_all_device"
        ).split(",") if w.strip()]
    unknown = [w for w in wanted if w not in catalog]
    if unknown:  # a typo must not silently degrade to a no-op "success"
        raise SystemExit(f"unknown RECOVER_STEPS {unknown}; "
                         f"valid: {sorted(catalog)}")
    steps = [(w, catalog[w]) for w in wanted]
    for tag, cmd in steps:
        rc, _ = _patient_run(cmd, STEP_SOFT_S, tag)
        _log(f"{tag}: rc={rc}")
    rc, _ = _patient_run([sys.executable, os.path.join(HERE, "merge_device.py"),
                          LOG], 600, "merge",
                         extra_env={"JAX_PLATFORMS": "cpu"})
    _log(f"merge: rc={rc}")
    with open(os.path.join(WORKDIR, "SUCCESS"), "w") as f:
        f.write(time.strftime("%Y-%m-%d %H:%M:%S\n"))


def main() -> None:
    os.makedirs(WORKDIR, exist_ok=True)
    t0 = time.time()
    _log(f"recovery loop start (deadline {DEADLINE_S/3600:.1f} h, "
         f"probe soft {PROBE_SOFT_S:.0f} s, sleep {SLEEP_S:.0f} s)")
    i = 0
    while True:
        if os.path.exists(os.path.join(WORKDIR, "stop")):
            _log("stop file found; exiting")
            return
        if time.time() - t0 > DEADLINE_S:
            _log("deadline reached without a working TPU window; exiting "
                 "to leave the relay free for the driver's bench run")
            return
        i += 1
        if probe_once(i):
            device_sequence()
            return
        time.sleep(SLEEP_S)


if __name__ == "__main__":
    main()
