"""Repeatable on-hardware verification of the Pallas kernels (all families).

VERDICT round 1, weak #7: Mosaic-compiled agreement used to rest on bench.py's
single AFNS5 config.  This harness checks EVERY family the fused kernels
support, on the real chip, against the XLA univariate scan path:

  - adjoint kernel (`pallas_kf_grad.batched_loglik_diff`): value + gradient
    (direction/norm agreement — elementwise f32 comparison is cancellation
    noise at these gradient norms, see bench.py) for all three Kalman
    families incl. the TVλ EKF's per-step jax.vjp adjoint, shared and
    per-lane windows,
  - value kernel (`pallas_kf.batched_loglik`): 1C (DNS), AFNS3, AFNS5,
    TVλ (EKF with in-kernel Jacobian), with NaN forecast tails, an interior
    missing column, an estimation window, and per-lane windows,
  - the fused particle filter, score-driven value kernel, and the
    MXU-fused bootstrap grid.

Window-budget engineering (VERDICT round 3, weak #4: the adjoint compiles
exceeded window 1's 90-min step budget and the decisive grad verdict was
never recorded):

  * the GRAD gates run FIRST — they are the open-anomaly evidence
    (BASELINE.md round-3 "Anomaly under investigation"), so a window cut
    short still lands the verdict that matters;
  * grad gates use small shapes (B=64, T=48 on hardware) — the adjoint
    algebra is shape-independent, and both the Mosaic adjoint compile and
    the reverse-mode-through-scan reference compile shrink with T;
  * a persistent compilation cache (JAX_COMPILATION_CACHE_DIR, default
    benchmarks/.jax_cache) lets a second window skip every compile the
    first one paid for (harmless no-op where the PJRT plugin can't
    serialize executables);
  * every check prints its own wall seconds, so the window log shows
    exactly where a budget went;
  * ``--only grad`` (or any comma-set of gate names) runs a subset, so the
    recovery loop can land the grad verdict as its own short step.

Exit code 0 iff every selected check passes; one summary line per check.

    python benchmarks/hw_verify.py                 # all gates, on the TPU
    python benchmarks/hw_verify.py --only grad     # just the adjoint gates
    JAX_PLATFORMS=cpu python benchmarks/hw_verify.py   # interpret-mode smoke
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
for p in (HERE, ROOT):
    if p not in sys.path:
        sys.path.insert(0, p)

# Persistent compile cache: enabled in main() AFTER backend init, keyed on
# the ACTUAL platform (window-budget fix above).  TPU only: XLA:CPU
# serializes host-specific AOT executables, and a cache written on a
# different container's CPU loads with machine-feature mismatch warnings
# ("could lead to ... SIGILL") — a silent CPU fallback (relay down, no
# JAX_PLATFORMS=cpu) must never gamble the gate verdict on that.  The env
# var can't be trusted for the decision; only jax.devices() can.

# The container's sitecustomize hook re-pins JAX_PLATFORMS=axon after env
# parsing, so a plain `JAX_PLATFORMS=cpu python hw_verify.py` would still dial
# the TPU tunnel and wedge (the exact failure tests/conftest.py and bench.py
# each work around).  Honor an explicit cpu request by neutralizing the axon
# factory BEFORE any jax computation — same recipe as tests/conftest.py.
if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    from _cpu_guard import force_cpu_platform  # repo root (on sys.path above)

    force_cpu_platform()

GATES = ("grad", "value", "pf-collapse", "pallas-pf", "ssd", "bootstrap")


def main(only=None) -> int:
    import jax
    import jax.numpy as jnp
    import common

    from yieldfactormodels_jl_tpu import create_model, get_loss
    from yieldfactormodels_jl_tpu.ops import pallas_kf, pallas_kf_grad, univariate_kf

    selected = tuple(only) if only else GATES

    platform = jax.devices()[0].platform
    interpret = platform != "tpu"
    # compile cache per the header comment: actual-platform-keyed
    if platform == "tpu":
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                         os.path.join(HERE, ".jax_cache")))
    else:
        jax.config.update("jax_compilation_cache_dir", None)
    mats = tuple(common.MATURITIES)
    rng = np.random.default_rng(0)
    # interpret mode executes the kernel per-step in python — keep the CPU
    # smoke tiny; the real check is the Mosaic-compiled path on the chip
    B, T = (8, 16) if interpret else (256, 120)
    # grad gates get their own, deliberately small, hardware shapes: the
    # adjoint contract is shape-independent and the compiles are the window
    # budget's dominant cost (round-3 window 1 never landed them at 256/120)
    GB, GT = (8, 16) if interpret else (64, 48)
    failures = 0
    t_last = time.perf_counter()

    def check(name, ok, detail=""):
        nonlocal failures, t_last
        now = time.perf_counter()
        print(f"{'PASS' if ok else 'FAIL'}  {name}  {detail}  "
              f"[{now - t_last:.1f}s]", flush=True)
        t_last = now
        if not ok:
            failures += 1

    def params_for(spec, nb):
        prng = np.random.default_rng(0)
        p = np.zeros((nb, spec.n_params), dtype=np.float64)
        if "gamma" in spec.layout:
            lo, hi = spec.layout["gamma"]
            p[:, lo:hi] = np.log(0.4) + 0.15 * prng.standard_normal((nb, hi - lo))
        lo, hi = spec.layout["obs_var"]
        p[:, lo:hi] = 0.01
        Ms = spec.state_dim
        k = spec.layout["chol"][0]
        for j in range(Ms):
            for i in range(j + 1):
                p[:, k] = 0.1 if i == j else 0.01
                k += 1
        lo, hi = spec.layout["delta"]
        p[:, lo:hi] = 0.2 * prng.standard_normal((nb, Ms))
        lo, hi = spec.layout["phi"]
        p[:, lo:hi] = (0.9 * np.eye(Ms)).reshape(-1)
        return p

    def panel_for(Tn):
        d = (0.5 * rng.standard_normal((len(mats), Tn)) + 4.0).astype(np.float32)
        d[:, -6:] = np.nan      # forecast tail
        d[3, Tn // 2] = np.nan  # interior missing column
        return d

    data = panel_for(T)
    start, end = 2, T - 2
    gdata = data if (GB, GT) == (B, T) else panel_for(GT)
    gstart, gend = 2, GT - 2

    # f32 agreement tolerance between the Mosaic kernel and the XLA scan.
    # Calibration history, kept honest and explicit: round 1's chip passed at
    # rtol 5e-4; the first post-outage window (round 3, 2026-07-31) measured
    # maxrel 0.98–1.3e-3 on the same checks (finite 256/256, sentinels exact)
    # — two correct-but-different f32 accumulation orders over ~2,400
    # log-domain accumulations drift at this scale, and the relay's compiler
    # version changed across the outage.  2e-3 stays 10× tighter than
    # bench.py's cross-kernel gate (2e-2); the elementwise correctness gate
    # remains the f64 interpret parity in tests/.
    V_RTOL, V_ATOL = 2e-3, 5e-2

    # ---- adjoint kernel FIRST: value + gradient direction/norm ----
    # hardware covers all three Kalman families incl. the TVλ EKF's
    # per-step jax.vjp adjoint (round 3) and the per-lane-window path
    if "grad" in selected:
        glos = rng.integers(0, min(10, GT // 4), size=GB)
        ghis = rng.integers(max(GT - 20, 3 * GT // 4), GT, size=GB)
        grad_cases = ((("1C", None),) if interpret else
                      (("1C", None), ("AFNS5", None), ("TVλ", None),
                       ("1C", "per-lane")))
        for code, win in grad_cases:
            spec, _ = create_model(code, mats, float_type="float32")
            p = jnp.asarray(params_for(spec, GB), jnp.float32)
            kw = (dict(starts=jnp.asarray(glos), ends=jnp.asarray(ghis))
                  if win else dict(start=gstart, end=gend))

            def tot_kernel(pb):
                return jnp.sum(pallas_kf_grad.batched_loglik_diff(
                    spec, pb, gdata, interpret=interpret, **kw))

            def single_ref(q, lo, hi):
                return univariate_kf.get_loss(spec, q, gdata, lo, hi)

            if win:
                def tot_ref(pb):
                    return jnp.sum(jax.vmap(single_ref)(
                        pb, jnp.asarray(glos), jnp.asarray(ghis)))
                ref_v = np.asarray(jax.jit(jax.vmap(single_ref))(
                    p, jnp.asarray(glos), jnp.asarray(ghis)))
            else:
                def tot_ref(pb):
                    return jnp.sum(jax.vmap(
                        lambda q: single_ref(q, gstart, gend))(pb))
                ref_v = np.asarray(jax.jit(jax.vmap(
                    lambda q: single_ref(q, gstart, gend)))(p))

            got_v = np.asarray(pallas_kf_grad.batched_loglik_diff(
                spec, p, gdata, interpret=interpret, **kw))
            g_got = np.asarray(jax.grad(tot_kernel)(p))
            g_ref = np.asarray(jax.grad(tot_ref)(p))
            both = np.isfinite(ref_v) & np.isfinite(got_v)
            vals_ok = bool(both.any()) and np.allclose(
                got_v[both], ref_v[both], rtol=V_RTOL, atol=V_ATOL)
            grads_ok, detail = common.grad_agreement(g_got[both], g_ref[both])
            tag = f"grad[{code}{', per-lane' if win else ''}]"
            check(tag, vals_ok and grads_ok, detail)

    # ---- value kernel, every family (interpret smoke: just one) ----
    if "value" in selected:
        value_codes = ("1C",) if interpret else ("1C", "AFNS3", "AFNS5", "TVλ")
        for code in value_codes:
            spec, _ = create_model(code, mats, float_type="float32")
            p = params_for(spec, B)
            ref = np.asarray(jax.jit(jax.vmap(
                lambda q: univariate_kf.get_loss(spec, q, data, start, end)))(
                jnp.asarray(p, jnp.float32)))
            got = np.asarray(pallas_kf.batched_loglik(spec, p, data, start, end,
                                                      interpret=interpret))
            both = np.isfinite(ref) & np.isfinite(got)
            same_sentinels = bool(np.array_equal(np.isfinite(ref),
                                                 np.isfinite(got)))
            agree = bool(both.any()) and np.allclose(got[both], ref[both],
                                                     rtol=V_RTOL, atol=V_ATOL)
            check(f"value[{code}]", agree and same_sentinels,
                  f"finite {int(both.sum())}/{B}, "
                  f"maxrel {np.max(np.abs(got[both]-ref[both])/np.abs(ref[both])):.2e}"
                  if both.any() else "no finite lanes")

        # ---- value kernel, per-lane windows ----
        spec, _ = create_model("1C", mats, float_type="float32")
        p = params_for(spec, B)
        los = rng.integers(0, min(10, T // 4), size=B)
        his = rng.integers(max(T - 20, 3 * T // 4), T, size=B)
        ref = np.asarray(jax.jit(jax.vmap(
            lambda q, lo, hi: univariate_kf.get_loss(spec, q, data, lo, hi)))(
            jnp.asarray(p, jnp.float32), jnp.asarray(los), jnp.asarray(his)))
        got = np.asarray(pallas_kf.batched_loglik(spec, p, data, starts=los,
                                                  ends=his, interpret=interpret))
        both = np.isfinite(ref) & np.isfinite(got)
        same_sentinels = bool(np.array_equal(np.isfinite(ref), np.isfinite(got)))
        check("value[1C, per-lane windows]",
              bool(both.any()) and same_sentinels
              and np.allclose(got[both], ref[both], rtol=V_RTOL, atol=V_ATOL),
              f"finite {int(both.sum())}/{B}, sentinels_match {same_sentinels}")

    # ---- SV particle filter: σ_h → 0 collapse to the exact Kalman loglik ----
    # (Mosaic isn't involved, but the lane-major layout + resample gathers are
    # exactly the parts whose XLA:TPU lowering differs from CPU)
    fin = jnp.asarray(np.nan_to_num(data, nan=4.0))
    if "pf-collapse" in selected:
        from yieldfactormodels_jl_tpu.ops.particle import particle_filter_loglik

        spec, _ = create_model("1C", mats, float_type="float32")
        pf_B = 2 if interpret else 16
        pf_P = 8 if interpret else 256
        p = jnp.asarray(params_for(spec, B)[:pf_B], jnp.float32)
        kf = np.asarray(jax.jit(jax.vmap(
            lambda q: univariate_kf.get_loss(spec, q, fin)))(p))
        pf = np.asarray(jax.jit(jax.vmap(
            lambda q, k: particle_filter_loglik(
                spec, q, fin, k, n_particles=pf_P, sv_phi=0.0, sv_sigma=0.0)))(
            p, jax.random.split(jax.random.PRNGKey(0), pf_B)))
        both = np.isfinite(kf) & np.isfinite(pf)
        same_sentinels = bool(np.array_equal(np.isfinite(kf), np.isfinite(pf)))
        check("pf[1C, sv->0 collapse]",
              bool(both.any()) and same_sentinels
              and np.allclose(pf[both], kf[both], rtol=2e-3),
              f"finite {int(both.sum())}/{pf_B}, sentinels_match {same_sentinels}, "
              f"maxrel {np.max(np.abs(pf[both]-kf[both])/np.abs(kf[both])):.2e}"
              if both.any() else "no finite lanes")

    # ---- fused Pallas PF kernel vs the XLA engine, common noise ----
    # same noise arrays ⇒ same trajectories; at σ_h = 0 resampling never
    # fires so the comparison is deterministic per draw even in f32.  With
    # σ_h > 0, f32 rounding can flip a resampling boundary and de-synchronize
    # a draw's trajectory, so that check is sentinel+distribution level.
    if "pallas-pf" in selected:
        from yieldfactormodels_jl_tpu.ops.particle import particle_filter_loglik
        from yieldfactormodels_jl_tpu.ops.pallas_pf import pf_loglik_batch

        spec, _ = create_model("AFNS5", mats, float_type="float32")
        pp_B, pp_P = (2, 128) if interpret else (16, 1024)
        pp = jnp.asarray(common.stationary_draws(
            spec, common.afns5_params(spec), pp_B, scale=0.01), jnp.float32)
        nz = jnp.asarray(rng.standard_normal((pp_B, fin.shape[1] - 1, pp_P)),
                         jnp.float32)
        us = jnp.asarray(rng.uniform(size=(pp_B, fin.shape[1] - 1)), jnp.float32)
        cn_ref = np.asarray(jax.jit(jax.vmap(
            lambda q, z, u: particle_filter_loglik(
                spec, q, fin, n_particles=pp_P, noise=(z, u),
                sv_sigma=0.0)))(pp, nz, us))
        cn_got = np.asarray(pf_loglik_batch(spec, pp, fin, nz, us, sv_sigma=0.0,
                                            interpret=interpret))
        both = np.isfinite(cn_ref) & np.isfinite(cn_got)
        check("pallas-pf[AFNS5, sv=0 common-noise]",
              bool(np.array_equal(np.isfinite(cn_ref), np.isfinite(cn_got)))
              and bool(both.any())
              and np.allclose(cn_got[both], cn_ref[both], rtol=V_RTOL, atol=V_ATOL),
              f"finite {int(both.sum())}/{pp_B}, "
              f"maxrel {np.max(np.abs(cn_got[both]-cn_ref[both])/np.abs(cn_ref[both])):.2e}"
              if both.any() else "no finite lanes")
        if interpret:
            # f64 common-noise parity IS elementwise-tight off-hardware (no
            # boundary flips at f64 resolution); a 2-draw "distribution" gate
            # would be statistically degenerate, so check exactly instead.
            # x64 must be on or the casts below silently stay f32 and the
            # rtol=1e-9 gate fails on good code (explicit dtypes elsewhere in
            # this harness are unaffected by the flag)
            jax.config.update("jax_enable_x64", True)
            pp64 = pp.astype(jnp.float64)
            nz64, us64 = nz.astype(jnp.float64), us.astype(jnp.float64)
            f64 = jnp.asarray(fin, jnp.float64)
            sv_ref = np.asarray(jax.vmap(
                lambda q, z, u: particle_filter_loglik(
                    spec, q, f64, n_particles=pp_P, noise=(z, u)))(pp64, nz64, us64))
            sv_got = np.asarray(pf_loglik_batch(spec, pp64, f64, nz64, us64,
                                                interpret=True))
            bsv = np.isfinite(sv_ref) & np.isfinite(sv_got)
            check("pallas-pf[AFNS5, sv=0.2 f64 exact]",
                  bool(np.array_equal(np.isfinite(sv_ref), np.isfinite(sv_got)))
                  and bool(bsv.any())
                  and np.allclose(sv_got[bsv], sv_ref[bsv], rtol=1e-9),
                  f"finite {int(bsv.sum())}/{pp_B}")
        else:
            sv_ref = np.asarray(jax.jit(jax.vmap(
                lambda q, z, u: particle_filter_loglik(
                    spec, q, fin, n_particles=pp_P, noise=(z, u))))(pp, nz, us))
            sv_got = np.asarray(pf_loglik_batch(spec, pp, fin, nz, us,
                                                interpret=False))
            bsv = np.isfinite(sv_ref) & np.isfinite(sv_got)
            # distribution-level: batch means within 3 cross-draw standard
            # errors plus an f32-accumulation allowance (boundary flips
            # de-synchronize individual trajectories; 16 draws give the gate
            # real power)
            if bsv.any():
                sd = float(np.std(sv_ref[bsv]))
                tol = (3.0 * sd / np.sqrt(bsv.sum())
                       + 5e-4 * abs(float(np.mean(sv_ref[bsv]))))
                mean_gap = abs(float(np.mean(sv_got[bsv]) - np.mean(sv_ref[bsv])))
            else:
                tol, mean_gap = 0.0, np.inf
            check("pallas-pf[AFNS5, sv=0.2 distribution]",
                  bool(np.array_equal(np.isfinite(sv_ref), np.isfinite(sv_got)))
                  and mean_gap < tol,
                  f"finite {int(bsv.sum())}/{pp_B}, "
                  f"means {np.mean(sv_got[bsv]):.2f}/{np.mean(sv_ref[bsv]):.2f}, "
                  f"gap {mean_gap:.3f} < tol {tol:.3f}"
                  if bsv.any() else "no finite lanes")

    # ---- fused score-driven VALUE kernel vs the scan engine ----
    # the recursion amplifies rounding through T steps (see
    # tests/test_pallas_ssd.py docstring), so the f32 on-chip gate is looser
    # than the Kalman value gate; the tight correctness gate is the f64
    # interpret parity in tests/ (engine + NumPy oracle)
    if "ssd" in selected:
        from yieldfactormodels_jl_tpu.ops.pallas_ssd import batched_loss as ssd_loss

        sspec, _ = create_model("1SSD-NNS", mats, float_type="float32")
        sB = 4 if interpret else 64
        sp = np.asarray(common.ssd_nns_params(sspec))
        srng = np.random.default_rng(11)
        sbatch = jnp.asarray(np.tile(sp, (sB, 1))
                             + 1e-3 * srng.standard_normal((sB, sspec.n_params)),
                             jnp.float32)
        sdata = jnp.asarray(np.nan_to_num(data, nan=4.0), jnp.float32)
        s_ref = np.asarray(jax.jit(jax.vmap(
            lambda q: get_loss(sspec, q, sdata)))(sbatch))
        s_got = np.asarray(ssd_loss(sspec, sbatch, sdata, interpret=interpret))
        sboth = np.isfinite(s_ref) & np.isfinite(s_got)
        check("ssd-value[1SSD-NNS]",
              bool(np.array_equal(np.isfinite(s_ref), np.isfinite(s_got)))
              and bool(sboth.any())
              and np.allclose(s_got[sboth], s_ref[sboth], rtol=2e-2, atol=1e-4),
              f"finite {int(sboth.sum())}/{sB}, "
              f"maxrel {np.max(np.abs(s_got[sboth]-s_ref[sboth])/np.abs(s_ref[sboth])):.2e}"
              if sboth.any() else "no finite lanes")

    # ---- bootstrap λ-grid: MXU-fused engine vs general scan engine ----
    if "bootstrap" in selected:
        from yieldfactormodels_jl_tpu.estimation.bootstrap import (
            _jitted_grid_loss, _jitted_grid_loss_fused, lambda_to_gamma,
            moving_block_indices)

        from tests.oracle import stable_ns_params

        nspec, _ = create_model("NS", mats, float_type="float32")
        np_ = stable_ns_params(nspec)
        R = 4 if interpret else 128
        gam = lambda_to_gamma(jnp.asarray([0.3, 0.6, 0.9], jnp.float32))
        idx = moving_block_indices(jax.random.PRNGKey(2), fin.shape[1], 8, R)
        args = (gam, idx, jnp.asarray(np_), fin)
        want = np.asarray(_jitted_grid_loss(nspec, fin.shape[1])(*args))
        got = np.asarray(_jitted_grid_loss_fused(nspec, fin.shape[1])(*args))
        check("bootstrap[NS, fused vs scan]",
              np.isfinite(got).all() and np.allclose(got, want, rtol=2e-3,
                                                     atol=1e-5),
              f"maxabs {np.max(np.abs(got-want)):.2e}")

    print(f"# platform={platform} interpret={interpret} gates={','.join(selected)} "
          f"{'ALL PASS' if failures == 0 else f'{failures} FAILURES'}")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    help=f"comma-separated subset of {','.join(GATES)}")
    a = ap.parse_args()
    gates = None
    if a.only:
        gates = tuple(g.strip() for g in a.only.split(",") if g.strip())
        bad = [g for g in gates if g not in GATES]
        if bad:  # a typo must not silently degrade to a no-op "all pass"
            sys.exit(f"unknown gates {bad}; valid: {GATES}")
    sys.exit(main(gates))
