"""The ONE copy of the cpu-mode axon guard (imported before any JAX compute).

This container's sitecustomize registers the axon TPU PJRT plugin in every
python process (trigger: ``PALLAS_AXON_POOL_IPS``) and pins
``JAX_PLATFORMS=axon`` — so a process that wants CPU must, before its first
JAX computation, (a) point ``jax_platforms`` at cpu and (b) deregister the
axon backend factory, or lazy backend init dials the TPU tunnel (which can
wedge the single shared relay for hours — CLAUDE.md).

The deregistration uses ``jax._src.xla_bridge._backend_factories``, a private
API with no stability guarantee.  It must therefore fail LOUDLY if a JAX
upgrade removes it: silently proceeding would dial the relay from a cpu-mode
run.  All three cpu-mode entry points (tests/conftest.py,
benchmarks/hw_verify.py, __graft_entry__.py) call this one function, so a
breakage is fixed in exactly one place.

This module deliberately lives at the REPO ROOT, outside the package: the
guard must run before the first JAX computation, so importing it must not
execute the package's import graph (where any future module-level jnp
constant would trigger backend init ahead of the guard).  Its only import is
``jax`` itself, inside the function.
"""

from __future__ import annotations


def force_cpu_platform() -> None:
    """Pin this process to the CPU backend; raise loudly if the guard breaks.

    Safe to call when the axon plugin was never registered (no-op pop).
    Must run before the first JAX computation — backend init is lazy and
    one-shot.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception as e:  # pragma: no cover - depends on the JAX version
        raise RuntimeError(
            "cpu-mode axon guard failed: jax._src.xla_bridge."
            "_backend_factories is gone in this JAX version.  Fix "
            "_cpu_guard.py at the repo root (the single shared copy) or "
            "this cpu run will dial the TPU relay."
        ) from e
