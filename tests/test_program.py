"""Program layer (docs/DESIGN.md §22) acceptance tests.

The two shipped proving cases, oracle-backed (CLAUDE.md rule — never
JAX-vs-JAX alone):

- ``prog-dns``: the hand-ported 1C family re-declared through the program
  layer, pinned BIT-IDENTICAL (loss + grad + filter outputs) on every
  engine ``config.engines_for`` grants — the correctness anchor that says
  the program path IS the family path, not a parallel implementation.
- ``svensson4``: a 4-factor Svensson model the zoo lacks, with its own
  λ₂-gap transform block — engine-parity vs an independent NumPy oracle
  (tests/oracle.py ``svensson_loadings``), estimated, T-switch
  tree-dispatched, served and scenario-fanned end to end.

Plus the registration state machine (collisions, replace, unregister,
auto-generated manifest cases), the declaration validation errors, the
state-dependent measurement lowering, and the registry's unknown-code
error naming program codes (models/registry.valid_codes).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import config
from yieldfactormodels_jl_tpu.models import api
from yieldfactormodels_jl_tpu.models.loadings import dns_loadings
from yieldfactormodels_jl_tpu.models.registry import valid_codes
from yieldfactormodels_jl_tpu.program import (ModelProgram, ParamBlock,
                                              compile_program,
                                              register_program,
                                              unregister_program)
from yieldfactormodels_jl_tpu.program.compile import ProgramSpec
from yieldfactormodels_jl_tpu.utils import transformations as tr

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)

#: literal twin of config.KALMAN_ENGINES, ON PURPOSE (the YFM007 coverage
#: census greps oracle-backed test ASTs for these names); the sync test in
#: test_assoc_estimation.py pins the registry side
ALL_ENGINES = ("univariate", "sqrt", "joint", "assoc", "slr")


def _linear_sd_measurement(beta, mats):
    """A state-dependent declaration of a LINEAR measurement (fixed-λ DNS):
    Z constant, y_pred = Zβ — so the EKF linearization is exact and the
    oracle pins the state-dependent lowering path too."""
    Z = dns_loadings(jnp.log(0.5), mats)
    return Z, Z @ beta


#: module-level (stable identity: programs key trace-time caches by hash)
SD_LINEAR_PROGRAM = ModelProgram(
    name="test-sd-linear", kind="kalman", factors=3,
    measurement=_linear_sd_measurement,
)


def _dns_pair(rng, T=60):
    spec1c, _ = yfm.create_model("1C", MATS, float_type="float64")
    specp, code = yfm.create_model("prog-dns", MATS, float_type="float64")
    assert code == "prog-dns" and isinstance(specp, ProgramSpec)
    p = oracle.stable_1c_params(spec1c, np.float64)
    data = 0.4 * rng.standard_normal((len(MATS), T)) + 4.0
    data[:, 25:28] = np.nan  # interior gap: mask parity rides along
    return spec1c, specp, jnp.asarray(p), jnp.asarray(data)


def _svensson_case(rng, T=80):
    spec, code = yfm.create_model("svensson4", MATS, float_type="float64")
    assert code == "svensson4" and spec.state_dim == 4
    p = oracle.stable_svensson_params(spec)
    data = 0.4 * rng.standard_normal((len(MATS), T)) + 4.0
    return spec, p, data


def _oracle_state_pieces(spec, p):
    """(Phi, delta, Omega_state, obs_var) from the flat vector, layout-driven
    (works for any Kalman-kind program spec)."""
    Ms = spec.state_dim
    C = np.zeros((Ms, Ms))
    rows, cols = spec.chol_indices
    a, _ = spec.layout["chol"]
    for k, (r, c) in enumerate(zip(rows, cols)):
        C[r, c] = p[a + k]
    lo, hi = spec.layout["delta"]
    delta = np.asarray(p[lo:hi], dtype=np.float64)
    lo, hi = spec.layout["phi"]
    Phi = np.asarray(p[lo:hi], dtype=np.float64).reshape(Ms, Ms)
    return Phi, delta, C @ C.T, float(p[spec.layout["obs_var"][0]])


# ---------------------------------------------------------------------------
# prog-dns — the bit-identity anchor
# ---------------------------------------------------------------------------

def test_prog_dns_compiles_to_the_family_layout(rng):
    spec1c, specp, _, _ = _dns_pair(rng)
    assert specp.layout == spec1c.layout
    assert specp.transform_codes == spec1c.transform_codes
    assert specp.n_params == spec1c.n_params == 20
    assert config.engines_for(specp) == config.engines_for(spec1c) \
        == config.KALMAN_ENGINES
    assert config.tree_engine_for(specp) == "assoc"


@pytest.mark.parametrize("engine",
                         ["univariate", "sqrt", "joint", "assoc", "slr"])
def test_prog_dns_bit_identical_loss_and_grad(engine, rng):
    """The tentpole pin: the compiled program flows through the SAME kernels
    as the hand-ported family — loss and gradient EXACTLY equal (==, not
    allclose) on every granted engine."""
    spec1c, specp, p, data = _dns_pair(rng)
    l1 = api.get_loss(spec1c, p, data, engine=engine)
    l2 = api.get_loss(specp, p, data, engine=engine)
    assert float(l1) == float(l2), engine
    g1 = jax.grad(lambda q: api.get_loss(spec1c, q, data, engine=engine))(p)
    g2 = jax.grad(lambda q: api.get_loss(specp, q, data, engine=engine))(p)
    assert bool(jnp.all(g1 == g2)), engine


def test_prog_dns_oracle_parity_and_filter_outputs(rng):
    """Not only family-vs-program: the program is also pinned against the
    independent NumPy loop directly, and the predict artifact set (filtered
    factors + predictions) is bit-identical to the family's."""
    spec1c, specp, p, data = _dns_pair(rng)
    pn = np.asarray(p)
    Z = oracle.dns_loadings(float(pn[spec1c.layout["gamma"][0]]),
                            np.asarray(MATS))
    Phi, delta, Om, ov = _oracle_state_pieces(spec1c, pn)
    want = oracle.kalman_filter_loglik(Z, Phi, delta, Om, ov,
                                       np.asarray(data))
    got = float(api.get_loss(specp, p, data))
    np.testing.assert_allclose(got, want, rtol=1e-8)
    out1 = api.predict(spec1c, p, data)
    out2 = api.predict(specp, p, data)
    for k in out1:
        assert bool(jnp.all(out1[k] == out2[k])), k


# ---------------------------------------------------------------------------
# svensson4 — the new-model proving case
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("engine",
                         ["univariate", "sqrt", "joint", "assoc", "slr"])
def test_svensson_engine_oracle_parity(engine, rng):
    spec, p, data = _svensson_case(rng)
    data[:, 30:33] = np.nan
    lo, hi = spec.layout["gamma"]  # the concatenated (λ₁ driver, gap) head
    Z = oracle.svensson_loadings(np.asarray(p[lo:hi]), np.asarray(MATS))
    Phi, delta, Om, ov = _oracle_state_pieces(spec, p)
    want = oracle.kalman_filter_loglik(Z, Phi, delta, Om, ov, data)
    got = float(api.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                             engine=engine))
    np.testing.assert_allclose(got, want, rtol=1e-8, err_msg=engine)


def test_svensson_transform_table_enforces_gap(rng):
    """The block transform table is real: the λ₂-gap slot carries R_TO_POS,
    so ANY unconstrained value maps to a strictly positive gap (λ₂ > λ₁ by
    construction), and untransform∘transform is the identity."""
    spec, p, _ = _svensson_case(rng)
    gap_slot = spec.layout["lambda2_gap"][0]
    assert spec.transform_codes[gap_slot] == tr.R_TO_POS
    raw = yfm.untransform_params(spec, jnp.asarray(p))
    back = yfm.transform_params(spec, raw)
    np.testing.assert_allclose(np.asarray(back), p, rtol=1e-12)
    neg = raw.at[gap_slot].set(-7.0)  # deeply negative unconstrained slot
    assert float(yfm.transform_params(spec, neg)[gap_slot]) > 0.0


@pytest.mark.slow
def test_svensson_estimate_end_to_end(rng):
    """Multi-start MLE on simulated svensson4 data recovers a loglik at
    least as good as the truth's (the estimator's own acceptance bar)."""
    spec, p_true, _ = _svensson_case(rng)
    sim = api.simulate(spec, jnp.asarray(p_true), 120, jax.random.PRNGKey(0))
    data = np.asarray(sim["data"])
    ll_true = float(api.get_loss(spec, jnp.asarray(p_true),
                                 jnp.asarray(data)))
    starts = np.stack([p_true,
                       p_true + 0.05 * rng.standard_normal(spec.n_params)])
    _, ll, best, conv = yfm.estimate(spec, data, starts.T,
                                     max_iters=60, g_tol=1e-5)
    assert np.isfinite(float(ll)) and float(ll) >= ll_true - 1e-3
    assert np.asarray(best).shape == (spec.n_params,)


def test_svensson_t_switch_tree_dispatch(rng):
    """YFM_LOGLIK_T_SWITCH upgrades the svensson4 production default onto
    its O(log T) tree ('assoc': the program is constant-Z) — same policy
    seam as the zoo families, same numbers as the sequential default."""
    spec, p, data = _svensson_case(rng, T=96)
    assert config.tree_engine_for(spec) == "assoc"
    pj, dj = jnp.asarray(p), jnp.asarray(data)
    seq = float(api.get_loss(spec, pj, dj, engine="univariate"))
    config.set_loglik_t_switch(50)
    try:
        auto = float(api.get_loss(spec, pj, dj))
    finally:
        config.set_loglik_t_switch(0)
    np.testing.assert_allclose(auto, seq, rtol=1e-9)


@pytest.mark.slow
def test_svensson_serving_scenario_end_to_end(rng):
    """freeze → update → refilter → forecast → scenarios → stress_fan, all
    on the compiled program spec — serving and the scenario lattice consume
    it unchanged."""
    spec, p, data = _svensson_case(rng, T=80)
    snap = yfm.freeze_snapshot(spec, jnp.asarray(p), jnp.asarray(data),
                               end=70)
    svc = yfm.YieldCurveService(snap)
    for t in range(70, 74):
        ll = svc.update(t, data[:, t])
        assert np.isfinite(ll)
    ll_re = svc.refilter(data[:, :74])
    assert np.isfinite(ll_re)
    fc = svc.forecast(h=6)
    assert fc["means"].shape == (6, len(MATS))
    assert np.all(np.isfinite(fc["means"]))
    sc = svc.scenarios(n=8, h=6)
    assert sc["paths"].shape == (len(MATS), 6, 8)
    assert np.all(np.isfinite(sc["paths"]))
    fan = svc.stress_fan(h=6)
    assert np.all(np.isfinite(np.asarray(fan["means"])))


# ---------------------------------------------------------------------------
# state-dependent measurement lowering
# ---------------------------------------------------------------------------

def test_state_dependent_program_engines_and_oracle_parity(rng):
    """A measurement= declaration drops 'assoc' (no constant Z) but keeps
    the sequential engines and the SLR tree; declaring a LINEAR measurement
    makes the EKF linearization exact, so the NumPy oracle pins the whole
    state-dependent path."""
    spec = compile_program(SD_LINEAR_PROGRAM, MATS, float_type="float64")
    assert spec.has_constant_measurement is False
    assert config.engines_for(spec) == tuple(
        e for e in config.KALMAN_ENGINES if e != "assoc")
    assert config.tree_engine_for(spec) == "slr"
    assert "gamma" not in spec.layout  # no head blocks declared
    p = oracle.generic_stable_params(spec, rng)
    data = 0.1 * rng.standard_normal((len(MATS), 50)) + 0.3
    Z = oracle.dns_loadings(np.log(0.5), np.asarray(MATS))
    Phi, delta, Om, ov = _oracle_state_pieces(spec, p)
    want = oracle.kalman_filter_loglik(Z, Phi, delta, Om, ov, data)
    for engine in ("univariate", "slr"):
        got = float(api.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                 engine=engine))
        np.testing.assert_allclose(got, want, rtol=1e-8, err_msg=engine)
    with pytest.raises(ValueError, match="not applicable"):
        api.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                     engine="assoc")


def test_state_dependent_program_rejects_ukf_rule(rng):
    """The sigma-point linearization rule is TVλ-specific; a state-dependent
    program gets the generic EKF rule and a loud error on 'ukf'."""
    from yieldfactormodels_jl_tpu.ops import slr_scan

    spec = compile_program(SD_LINEAR_PROGRAM, MATS, float_type="float64")
    p = oracle.generic_stable_params(spec, rng)
    data = 0.1 * rng.standard_normal((len(MATS), 40)) + 0.3
    with pytest.raises(ValueError, match="TVλ-specific"):
        slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                          linearization="ukf")


def test_state_dependent_program_loadings_error():
    spec = compile_program(SD_LINEAR_PROGRAM, MATS, float_type="float64")
    with pytest.raises(ValueError, match="state-dependent"):
        api.update_factor_loadings(spec, jnp.zeros(1))


# ---------------------------------------------------------------------------
# registration state machine + registry integration (satellite: the
# unknown-code error names program codes)
# ---------------------------------------------------------------------------

def test_unknown_code_error_names_program_codes():
    codes = valid_codes()
    assert "prog-dns" in codes and "svensson4" in codes and "1C" in codes
    with pytest.raises(ValueError) as ei:
        yfm.create_model("no-such-model", MATS)
    msg = str(ei.value)
    assert "no-such-model" in msg
    assert "svensson4" in msg and "prog-dns" in msg and "1C" in msg


def test_register_program_state_machine():
    from yieldfactormodels_jl_tpu.analysis import manifest as mf
    from yieldfactormodels_jl_tpu.program.registry import (_AUDIT_BUILDERS,
                                                           lookup)

    prog = ModelProgram(
        name="test-reg-prog", kind="kalman", factors=3,
        blocks=(ParamBlock("gamma", 1, (tr.IDENTITY,)),),
        loadings=dns_loadings)
    register_program(prog)
    try:
        register_program(prog)  # same object: idempotent no-op
        assert lookup("test-reg-prog") is prog
        # the auto-generated tier-2 cases landed on every audited builder
        for key in _AUDIT_BUILDERS:
            labels = [c.label for c in mf.MANIFEST.get(key, [])]
            assert "program:test-reg-prog" in labels, key
        spec, code = yfm.create_model("test-reg-prog", MATS,
                                      float_type="float64")
        assert code == "test-reg-prog" and spec.program is prog
        clone = ModelProgram(
            name="test-reg-prog", kind="kalman", factors=3,
            blocks=(ParamBlock("gamma", 1, (tr.IDENTITY,)),),
            loadings=dns_loadings)
        with pytest.raises(ValueError, match="already registered"):
            register_program(clone)
        register_program(clone, replace=True)
        assert lookup("test-reg-prog") is clone
    finally:
        unregister_program("test-reg-prog")
    assert lookup("test-reg-prog") is None
    for key in _AUDIT_BUILDERS:  # cases dropped with the program
        labels = [c.label for c in mf.MANIFEST.get(key, [])]
        assert "program:test-reg-prog" not in labels, key
    with pytest.raises(ValueError, match="valid codes"):
        yfm.create_model("test-reg-prog", MATS)


def test_register_program_rejects_zoo_collision():
    prog = ModelProgram(
        name="1C", kind="kalman", factors=3,
        blocks=(ParamBlock("gamma", 1, (tr.IDENTITY,)),),
        loadings=dns_loadings)
    with pytest.raises(ValueError, match="collides with a built-in"):
        register_program(prog)


# ---------------------------------------------------------------------------
# declaration validation
# ---------------------------------------------------------------------------

def test_param_block_validation_errors():
    with pytest.raises(ValueError, match="identifier"):
        ParamBlock("not a name", 1, (tr.IDENTITY,))
    with pytest.raises(ValueError, match="reserved"):
        ParamBlock("obs_var", 1, (tr.IDENTITY,))
    with pytest.raises(ValueError, match="one code per slot"):
        ParamBlock("head", 2, (tr.IDENTITY,))
    with pytest.raises(ValueError, match="unknown transform code"):
        ParamBlock("head", 1, (999,))


def test_model_program_validation_errors():
    with pytest.raises(ValueError, match="EXACTLY ONE measurement"):
        ModelProgram(name="p", kind="kalman", factors=3)
    with pytest.raises(ValueError, match="EXACTLY ONE measurement"):
        ModelProgram(name="p", kind="kalman", factors=3,
                     loadings=dns_loadings,
                     measurement=_linear_sd_measurement)
    with pytest.raises(ValueError, match="head parameter blocks"):
        ModelProgram(name="p", kind="kalman", factors=3,
                     measurement=_linear_sd_measurement,
                     blocks=(ParamBlock("g", 1, (tr.IDENTITY,)),))
    with pytest.raises(ValueError, match="unknown program kind"):
        ModelProgram(name="p", kind="arma", factors=3,
                     loadings=dns_loadings)
    with pytest.raises(ValueError, match="state must carry"):
        ModelProgram(name="p", kind="kalman", factors=3, state_dim=2,
                     measurement=_linear_sd_measurement)
    with pytest.raises(ValueError, match="loadings= only"):
        ModelProgram(name="p", kind="msed", factors=3,
                     measurement=_linear_sd_measurement)
    with pytest.raises(ValueError, match="program name"):
        ModelProgram(name="bad name!", kind="kalman", factors=3,
                     loadings=dns_loadings)


def test_msed_program_capability_flags():
    plain = ModelProgram(name="m1", kind="msed", factors=3,
                         loadings=dns_loadings)
    scaled = ModelProgram(name="m2", kind="msed", factors=3,
                          loadings=dns_loadings, scale_grad=True)
    assert plain.supports_score_tree and not scaled.supports_score_tree
    sp, ss = (compile_program(q, MATS, float_type="float64")
              for q in (plain, scaled))
    assert sp.is_msed and config.engines_for(sp) == config.MSED_ENGINES
    assert config.engines_for(ss) == tuple(
        e for e in config.MSED_ENGINES if e != "score_tree")
