"""Tiered state store (serving/tiers.py, docs/DESIGN.md §21).

Acceptance coverage for the hot/warm/cold tentpole:

- demote→promote round trips are BIT-exact on both engines (the warm tier
  freezes the engine representation, not moments), including states that
  have absorbed partially-quoted and whole-column-NaN curves;
- a working-set-2×-hot dry run on the 8-virtual-device mesh: every request
  answered, promotions/demotions flow, the ledger accounts every request
  exactly once;
- the tier chaos seams: ``evict_corrupt`` (poisoned freeze caught by the
  promotion-side health watch, rebuilt from the cold registry — or parked
  stale when no fallback exists) and ``promote_stall`` (wave dropped,
  requests degrade, next wave recovers);
- the batched promotion path compiles ONE ``slot_write_many`` program per
  update bucket across a 1→2→4→8 mesh sweep at fixed shard capacity — zero
  retraces in steady state, zero donation warnings;
- a 2-thread hammer on the tier manager's lock discipline (mutating churn
  vs operator reads — no exceptions, consistent ledgers);
- the fleet seam: one gateway over MANY stores routed by ``model_string``.
"""

import dataclasses
import threading
import warnings

import numpy as np
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import serving
from yieldfactormodels_jl_tpu.orchestration import chaos
from yieldfactormodels_jl_tpu.parallel import mesh as pmesh
from yieldfactormodels_jl_tpu.serving import online as so

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)
T_PANEL = 48
T_ORIGIN = 40

LATTICE = dict(horizons=(4,), batch_sizes=(1, 4), scenario_counts=(4,),
               update_batch_sizes=(1, 4))


@pytest.fixture(scope="module")
def dns_setup():
    rng = np.random.default_rng(11)
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T_PANEL)
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    return spec, p, data, snap


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.reset()
    yield
    chaos.reset()


def _snap_for(snap, task_id):
    return dataclasses.replace(
        snap, meta=dataclasses.replace(snap.meta, task_id=task_id))


def _tiered(spec, snap, n_keys, mesh_size=2, shard_capacity=2,
            warm_capacity=8, registry=True, **kw):
    store = serving.TieredStateStore(
        spec, mesh=pmesh.make_mesh(mesh_size), shard_capacity=shard_capacity,
        warm_capacity=warm_capacity,
        registry=serving.SnapshotRegistry() if registry else None,
        lattice=serving.BucketLattice(**LATTICE), **kw)
    keys = store.register_many(_snap_for(snap, i) for i in range(n_keys))
    return store, keys


def _slot_bits(store, key):
    """The exact device bits of one resident slot (engine representation)."""
    import jax
    s, sl = store._slot[key]
    sh = store._shards[s]
    p, b, c, v = jax.device_get((sh["params"][:, sl], sh["beta"][:, sl],
                                 sh["cov"][:, :, sl], sh["ver"][sl]))
    return (np.asarray(p).tobytes(), np.asarray(b).tobytes(),
            np.asarray(c).tobytes(), np.asarray(v).tobytes())


# ---------------------------------------------------------------------------
# boot across tiers, occupancy, containment
# ---------------------------------------------------------------------------

def test_register_many_boots_across_tiers(dns_setup):
    """Bulk boot fills hot first, freezes the tail warm, and spills past the
    warm bound to the cold registry — all-or-nothing, everything findable."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 8, warm_capacity=3)
    t = store.tiers()
    assert t["hot"] == 4 and t["warm"] == 3 and t["cold"] == 1
    assert t["ledger"]["spills"] == 1 and t["ledger"]["dropped"] == 0
    assert all(k in store for k in keys)
    for k in keys:  # every tier serves snapshots without promotion
        assert store.snapshot_of(k).meta.task_id == k[1]
    assert store.tiers()["hot"] == 4  # snapshot_of promoted nothing


def test_warm_capacity_env_knob(dns_setup, monkeypatch):
    spec, p, data, snap = dns_setup
    monkeypatch.setenv("YFM_STORE_WARM_CAP", "7")
    store = serving.TieredStateStore(
        spec, mesh=pmesh.make_mesh(2), shard_capacity=2,
        lattice=serving.BucketLattice(**LATTICE))
    assert store.warm.capacity == 7
    monkeypatch.delenv("YFM_STORE_WARM_CAP")
    store = serving.TieredStateStore(
        spec, mesh=pmesh.make_mesh(2), shard_capacity=2,
        lattice=serving.BucketLattice(**LATTICE))
    assert store.warm.capacity == 4 * store.capacity


# ---------------------------------------------------------------------------
# bit parity: demote → promote restores the EXACT engine bits
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("engine", ["univariate", "sqrt"])
def test_demote_promote_bit_parity(dns_setup, engine):
    """Freeze/thaw is bit-for-bit on both engines, including states that
    have absorbed a partially-quoted curve and a whole-column-NaN curve
    (the sqrt factor is never re-factored on the warm leg)."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 4, engine=engine)
    curves = [data[:, T_ORIGIN].copy(), data[:, T_ORIGIN + 1].copy(),
              np.full(spec.N, np.nan)]
    curves[1][2] = np.nan
    for t, y in enumerate(curves):
        res = store.update_batch([(k, y) for k in keys], dates=[t] * 4)
        assert all("error" not in r and not r.get("degraded") for r in res)
    before = {k: _slot_bits(store, k) for k in keys}
    store.demote(keys[:2])
    assert all(k in store.warm and k not in store._slot for k in keys[:2])
    promoted, unpromoted = store.ensure_resident(keys[:2])
    assert sorted(promoted) == sorted(keys[:2]) and not unpromoted
    for k in keys:
        assert _slot_bits(store, k) == before[k], k
    lg = store.tiers()["ledger"]
    assert lg["demotions"] == 2 and lg["promotions"] == 2


def test_promoted_update_matches_never_demoted_twin(dns_setup):
    """An update right after promotion is bit-identical to the same update
    on a twin store that never demoted — the round trip is invisible to the
    filter."""
    spec, p, data, snap = dns_setup
    a, keys_a = _tiered(spec, snap, 4)
    b, keys_b = _tiered(spec, snap, 4)
    y0, y1 = data[:, T_ORIGIN], data[:, T_ORIGIN + 1]
    for st, ks in ((a, keys_a), (b, keys_b)):
        assert all(np.isfinite(r["ll"])
                   for r in st.update_batch([(k, y0) for k in ks]))
    a.demote([keys_a[0]])
    ra = a.update_batch([(keys_a[0], y1)])[0]
    rb = b.update_batch([(keys_b[0], y1)])[0]
    assert not ra.get("degraded")
    np.testing.assert_array_equal(ra["ll"], rb["ll"])
    np.testing.assert_array_equal(
        np.asarray(a.snapshot_of(keys_a[0]).beta),
        np.asarray(b.snapshot_of(keys_b[0]).beta))


# ---------------------------------------------------------------------------
# LRU policy
# ---------------------------------------------------------------------------

def test_lru_demotes_coldest_under_pressure(dns_setup):
    """Under promotion pressure the least-recently-touched resident key is
    the victim; the freshly-touched keys stay hot."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 5, warm_capacity=4)
    hot = [k for k in keys if k in store._slot]
    warm = [k for k in keys if k in store.warm]
    assert len(hot) == 4 and len(warm) == 1
    y = data[:, T_ORIGIN]
    store.update_batch([(k, y) for k in hot[1:]])  # hot[0] stays untouched
    store.update_batch([(warm[0], y)])             # miss → promotion wave
    assert warm[0] in store._slot
    assert hot[0] in store.warm and hot[0] not in store._slot
    assert all(k in store._slot for k in hot[1:])


# ---------------------------------------------------------------------------
# working set 2× hot on the 8-virtual-device mesh (the bench scenario)
# ---------------------------------------------------------------------------

def test_working_set_2x_dry_run_8_devices(dns_setup):
    """The BENCH_LOAD working-set column's scenario in miniature: 32 states
    over 16 hot slots on the full mesh, zipf-skewed update traffic — every
    request answered (no structural errors), the tier ledger accounts every
    request exactly once, and promotions/demotions actually flow."""
    from yieldfactormodels_jl_tpu.robustness import loadgen
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 32, mesh_size=8, shard_capacity=2,
                          warm_capacity=16)
    assert store.tiers()["hot"] == 16
    store.warmup()
    rng = np.random.default_rng(7)
    w = loadgen.zipf_weights(len(keys), s=1.2)
    n_requests, answered = 0, 0
    for t in range(12):
        picks = rng.choice(len(keys), size=8, replace=False, p=w)
        items = [(keys[i], data[:, T_ORIGIN + t % 8]) for i in picks]
        n_requests += len(items)
        for r in store.update_batch(items):
            assert "error" not in r, r
            answered += 1
            assert r.get("degraded") or np.isfinite(r["ll"])
    lg = store.ledger
    assert answered == n_requests
    assert lg.accounted == n_requests
    assert lg.promotions > 0 and lg.demotions > 0
    assert lg.hits + lg.misses_warm + lg.misses_cold == n_requests
    t = store.tiers()
    assert t["hot"] == 16 and t["hot_free"] == 0
    assert t["promote_waves"] > 0 and t["promote_p99_ms"] >= t["promote_p50_ms"]


# ---------------------------------------------------------------------------
# chaos seams: evict_corrupt / promote_stall
# ---------------------------------------------------------------------------

def test_evict_corrupt_rebuilds_from_cold_registry(dns_setup):
    """A poisoned freeze (chaos ``evict_corrupt``) is caught by the
    promotion-side health watch and rebuilt from the cold registry — the
    answer is healthy, the rebuild is ledgered."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 4)
    k = keys[0]
    store.registry.put(store.snapshot_of(k))
    chaos.configure("evict_corrupt:@1")
    store.demote([k])
    chaos.reset()
    assert np.isnan(store.warm.peek(k).beta).all()
    r = store.update_batch([(k, data[:, T_ORIGIN])])[0]
    assert not r.get("degraded") and np.isfinite(r["ll"])
    assert store.ledger.corrupt_rebuilds == 1


def test_evict_corrupt_without_fallback_parks_stale(dns_setup):
    """No cold fallback: the poisoned record is parked back warm,
    stale-flagged, and its requests degrade — visible, never silently
    dropped."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 4, registry=False)
    k = keys[0]
    chaos.configure("evict_corrupt:@1")
    store.demote([k])
    chaos.reset()
    r = store.update_batch([(k, data[:, T_ORIGIN])])[0]
    assert r.get("degraded") and r.get("stale")
    assert k in store.warm and store.warm.peek(k).stale
    assert store.ledger.corrupt_rebuilds == 0


def test_promote_stall_degrades_then_recovers(dns_setup):
    """A dropped promotion wave (chaos ``promote_stall``) answers its
    requests degraded-stale from the warm record; the next wave lands."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 4)
    k = keys[0]
    store.demote([k])
    chaos.configure("promote_stall:@1")
    r = store.update_batch([(k, data[:, T_ORIGIN])])[0]
    assert r.get("degraded") and r.get("stale")
    assert store.ledger.promote_stalls == 1 and k in store.warm
    r = store.update_batch([(k, data[:, T_ORIGIN])])[0]
    assert not r.get("degraded") and np.isfinite(r["ll"])
    chaos.reset()


# ---------------------------------------------------------------------------
# one program per bucket across the mesh sweep; steady state retrace-free
# ---------------------------------------------------------------------------

def test_promotion_one_program_per_bucket_across_mesh_sweep(dns_setup):
    """Fixed shard capacity → the batched slot-write program keys never
    mention mesh size: the whole 1→2→4→8 sweep (boot + demote + promote
    waves on every size) compiles each update bucket ONCE, and the donated
    launches never warn about unusable donated buffers."""
    spec, p, data, snap = dns_setup
    cap = 3  # unique to this test: the lru cache must start cold
    so.reset_trace_counts()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for m in (1, 2, 4, 8):
            store = serving.TieredStateStore(
                spec, mesh=pmesh.make_mesh(m), shard_capacity=cap,
                warm_capacity=4 * m,
                registry=serving.SnapshotRegistry(),
                lattice=serving.BucketLattice(**LATTICE))
            keys = store.register_many(
                _snap_for(snap, i) for i in range(3 * m + 2))
            store.demote([k for k in keys if k in store._slot][:2])
            promoted, _ = store.ensure_resident(keys[:2])
            assert promoted
    n_buckets = len(LATTICE["update_batch_sizes"])
    assert so.trace_counts["slot_write_many"] <= n_buckets
    donation = [str(i.message) for i in w
                if "donat" in str(i.message).lower()]
    assert donation == []


def test_steady_state_waves_are_trace_free(dns_setup):
    """After warmup, promotion/demotion waves and resident updates add ZERO
    retraces — the acceptance bar for the hot path."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 8, warm_capacity=8)
    store.warmup()
    so.reset_trace_counts()
    y = data[:, T_ORIGIN]
    for t in range(4):
        miss = [k for k in keys if k not in store._slot][:2]
        res = store.update_batch([(k, y) for k in miss + keys[:2]])
        assert all("error" not in r for r in res)
    assert so.trace_counts["slot_write_many"] == 0
    assert so.trace_counts["store_update"] == 0


# ---------------------------------------------------------------------------
# lock discipline: 2-thread hammer (mutating churn vs operator reads)
# ---------------------------------------------------------------------------

def test_two_thread_hammer_lock_discipline(dns_setup):
    """Thread A churns updates over a working set 2× hot (constant
    promotion/demotion waves); thread B hammers the operator surface
    (health / tiers / containment / last-good snapshots).  No exceptions on
    either side, and the ledger stays exactly-once consistent."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 8, warm_capacity=8)
    store.warmup()
    errors = []
    stop = threading.Event()
    n_rounds = 25

    def churn():
        try:
            rng = np.random.default_rng(0)
            for t in range(n_rounds):
                picks = rng.choice(len(keys), size=3, replace=False)
                res = store.update_batch(
                    [(keys[i], data[:, T_ORIGIN + t % 8]) for i in picks])
                for r in res:
                    if "error" in r:
                        raise AssertionError(f"structural error: {r}")
        except Exception as e:  # surfaced to the main thread
            errors.append(e)
        finally:
            stop.set()

    def observe():
        try:
            while not stop.is_set():
                h = store.health()
                assert "tiers" in h
                t = store.tiers()
                assert t["hot"] <= t["hot_capacity"]
                for k in keys[:3]:
                    k in store
                    store.last_good_snapshot_of(k)
        except Exception as e:
            errors.append(e)

    a = threading.Thread(target=churn)
    b = threading.Thread(target=observe)
    a.start(); b.start()
    a.join(timeout=120); b.join(timeout=120)
    assert not a.is_alive() and not b.is_alive()
    assert errors == []
    assert store.ledger.accounted == n_rounds * 3


# ---------------------------------------------------------------------------
# gateway integration: reads promote through the pump
# ---------------------------------------------------------------------------

def test_gateway_pump_promotes_read_keys(dns_setup):
    """A keyed read of a demoted state is admitted, promoted in the next
    pump wave (``prepare_reads``), and answered non-degraded."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 4)
    store.warmup()
    gw = serving.ShardedGateway(store, queue_max=64, queue_age_ms=0.0)
    k = keys[0]
    store.demote([k])
    t1 = gw.submit_forecast(4, key=k)
    t2 = gw.submit_update(99, data[:, T_ORIGIN], key=k)
    gw.pump()
    r1, r2 = gw.result(t1), gw.result(t2)
    assert not r1.get("degraded") and not r2.get("degraded")
    assert np.isfinite(r2["ll"])
    assert k in store._slot
    assert store.ledger.misses_warm >= 1


# ---------------------------------------------------------------------------
# fleet seam: one gateway, many stores
# ---------------------------------------------------------------------------

def test_fleet_routes_by_model_string(dns_setup):
    """Two tiered stores (distinct specs) under ONE gateway: requests route
    by their key's model_string; unroutable keys get structured errors, and
    the fleet's health/latency surfaces aggregate the members."""
    spec, p, data, snap = dns_setup
    store, keys = _tiered(spec, snap, 4)
    spec2, _ = yfm.create_model("AFNS3", MATS, float_type="float64")
    p2 = oracle.generic_stable_params(spec2, np.random.default_rng(0))
    snap2 = serving.freeze_snapshot(spec2, p2, data, end=T_ORIGIN)
    store2 = serving.TieredStateStore(
        spec2, mesh=pmesh.make_mesh(2), shard_capacity=2,
        lattice=serving.BucketLattice(**LATTICE))
    k2 = store2.register(snap2)
    fleet = serving.StoreFleet([store, store2])
    assert len(fleet) == 5
    assert fleet.spec_for(keys[0]) is spec and fleet.spec_for(k2) is spec2

    gw = serving.ShardedGateway(fleet, queue_max=64, queue_age_ms=0.0)
    ta = gw.submit_update(1, data[:, T_ORIGIN], key=keys[0])
    tb = gw.submit_update(1, data[:, T_ORIGIN], key=k2)
    tc = gw.submit_forecast(4, key=k2)
    gw.pump()
    for t in (ta, tb, tc):
        assert "error" not in gw.result(t)

    bogus = ("no-such-model", 0)
    r = fleet.update_batch([(bogus, data[:, T_ORIGIN])])[0]
    assert isinstance(r.get("error"), serving.ServingError)
    h = fleet.health()
    assert h["status"] in ("ok", "stale")
    assert sorted(h["stores"]) == ["1C", "AFNS3"] == h["models"]


def test_fleet_rejects_duplicate_model_strings(dns_setup):
    spec, p, data, snap = dns_setup
    store, _ = _tiered(spec, snap, 2)
    other, _ = _tiered(spec, snap, 2)
    with pytest.raises(serving.ServingError):
        serving.StoreFleet([store, other])
    with pytest.raises(serving.ServingError):
        serving.StoreFleet([])
