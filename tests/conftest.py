"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding is exercised
on a fake mesh (SURVEY.md §4), with float64 enabled for tight NumPy-oracle
comparisons.

This container routes JAX to a single real TPU through the `axon` PJRT plugin:
a sitecustomize hook registers the plugin in every python process (when
``PALLAS_AXON_POOL_IPS`` is set) and pins ``JAX_PLATFORMS=axon``.  Initializing
that backend dials the TPU tunnel, which serializes/hangs pytest.  Backend init
is lazy, so before any JAX computation we (a) point ``jax_platforms`` at cpu,
(b) deregister the axon factory, and (c) request 8 virtual CPU devices.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from _cpu_guard import force_cpu_platform  # repo-root module: no package imports

force_cpu_platform()  # sitecustomize already captured env; shared loud guard
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest

assert jax.devices()[0].platform == "cpu"
assert len(jax.devices()) == 8, "expected the 8-device virtual CPU mesh"


@pytest.fixture
def rng():
    return np.random.default_rng(0)


MATURITIES = (3.0, 6.0, 9.0, 12.0, 15.0, 18.0, 21.0, 24.0, 30.0, 36.0,
              48.0, 60.0, 72.0, 84.0, 96.0, 108.0, 120.0, 180.0, 240.0, 360.0)


@pytest.fixture
def maturities():
    # Liu–Wu style monthly-maturity grid, in months/12 = years
    return np.asarray(MATURITIES) / 12.0


@pytest.fixture
def yields_panel(rng, maturities):
    """Synthetic DNS-generated panel (N, T) in float64."""
    from tests.oracle import simulate_dns_panel

    return simulate_dns_panel(rng, maturities, T=80)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables between test modules.

    A single pytest process accumulates ~200 jitted programs (several of them
    very large: interpret-mode Pallas kernels, 2nd-order-AD scans, whole-
    optimizer while_loops); past that point the XLA:CPU backend_compile has
    been observed to SEGFAULT on a compile that succeeds in a fresh process
    (reproduced twice at test_run's flagship estimation, 2026-07-31 — solo
    and any-subset runs pass).  Clearing caches per module bounds the live
    compiler state; the cost is re-compiling shared fixtures a few times."""
    yield
    import jax

    jax.clear_caches()
