"""Every registry code runs end-to-end: get_loss AND predict.

The registry-level tests (test_registry.py) pin counts/aliases/groups; this
is the completeness guard at the layer above — a user switching from the
reference must find every one of the 34 model codes (plus the AFNS
extensions) actually *runnable*: spec construction, a generically stable
parameter point built from the spec's own layout, one loss evaluation
(finite or the −Inf sentinel — never NaN), and a NaN-tail predict returning
the reference's artifact set with the right shapes.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from yieldfactormodels_jl_tpu import create_model, get_loss, predict
from yieldfactormodels_jl_tpu.models.registry import MODEL_CODES

MATS = tuple(np.array([3, 12, 36, 120, 360]) / 12.0)
PLACEHOLDERS = {"pC", "vanillaNN"}  # reference placeholders (test_registry)

_CODES = sorted({c for c in MODEL_CODES if c not in PLACEHOLDERS})


@pytest.mark.parametrize("code", _CODES)
def test_code_runs_end_to_end(code, rng):
    from tests.oracle import generic_stable_params

    spec, canon = create_model(code, MATS, float_type="float64")
    p = jnp.asarray(generic_stable_params(spec, rng))
    data = 0.4 * rng.standard_normal((len(MATS), 25)) + 4.0

    loss = float(get_loss(spec, p, jnp.asarray(data)))
    assert not np.isnan(loss), f"{code} ({canon}): loss is NaN"

    nan_tail = np.concatenate(
        [data, np.full((len(MATS), 3), np.nan)], axis=1)
    out = predict(spec, p, jnp.asarray(nan_tail))
    # preds[:, k] is the one-step-ahead prediction of column k+1; predict
    # appends one internal NaN step, so the output spans all T_ext columns
    T_ext = nan_tail.shape[1]
    assert np.asarray(out["preds"]).shape == (len(MATS), T_ext), code
    for key in ("factors", "states", "factor_loadings_1", "factor_loadings_2"):
        assert key in out, f"{code}: missing artifact {key!r}"
    # ALL THREE appended forecast-only steps must be filled, not NaN
    tail = np.asarray(out["preds"])[:, -3:]
    assert np.isfinite(tail).all(), f"{code}: NaN forecast tail"
