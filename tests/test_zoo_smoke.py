"""Every registry code runs end-to-end: get_loss AND predict.

The registry-level tests (test_registry.py) pin counts/aliases/groups; this
is the completeness guard at the layer above — a user switching from the
reference must find every one of the 34 model codes (plus the AFNS
extensions) actually *runnable*: spec construction, a generically stable
parameter point built from the spec's own layout, one loss evaluation
(finite or the −Inf sentinel — never NaN), and a NaN-tail predict returning
the reference's artifact set with the right shapes.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from yieldfactormodels_jl_tpu import create_model, get_loss, predict
from yieldfactormodels_jl_tpu.models.registry import MODEL_CODES

MATS = tuple(np.array([3, 12, 36, 120, 360]) / 12.0)
PLACEHOLDERS = {"pC", "vanillaNN"}  # reference placeholders (test_registry)

_CODES = sorted({c for c in MODEL_CODES if c not in PLACEHOLDERS})


def _generic_stable_params(spec, rng):
    """A finite-loss parameter point for ANY family, driven by spec.layout."""
    p = np.zeros(spec.n_params)
    lo, hi = spec.layout.get("gamma", (0, 0))
    n = hi - lo
    if n == 1:
        p[lo] = np.log(0.5 - 1e-2)
    elif n == 2:  # AFNS5 double decay
        p[lo:hi] = [np.log(0.5), np.log(0.15)]
    elif n > 2:   # neural loading weights
        p[lo:hi] = rng.standard_normal(n) / 10
    lo, hi = spec.layout.get("obs_var", (0, 0))
    p[lo:hi] = 4e-4
    if "chol" in spec.layout:
        a, _ = spec.layout["chol"]
        rows, cols = spec.chol_indices
        for k, (r, c) in enumerate(zip(rows, cols)):
            p[a + k] = 0.05 if r == c else 0.0
    lo, hi = spec.layout.get("A", (0, 0))
    p[lo:hi] = 1e-4
    lo, hi = spec.layout.get("B", (0, 0))
    p[lo:hi] = 0.97
    lo, hi = spec.layout.get("omega", (0, 0))
    p[lo:hi] = rng.standard_normal(hi - lo) / 10
    lo, hi = spec.layout.get("delta", (0, 0))
    vals = [0.3, -0.1, 0.05] + [-0.07] * max(0, hi - lo - 3)
    p[lo:hi] = vals[: hi - lo]
    lo, hi = spec.layout.get("phi", (0, 0))
    m = int(round((hi - lo) ** 0.5))
    p[lo:hi] = (0.9 * np.eye(m)).reshape(-1)
    return p


@pytest.mark.parametrize("code", _CODES)
def test_code_runs_end_to_end(code, rng):
    spec, canon = create_model(code, MATS, float_type="float64")
    p = jnp.asarray(_generic_stable_params(spec, rng))
    data = 0.4 * rng.standard_normal((len(MATS), 25)) + 4.0

    loss = float(get_loss(spec, p, jnp.asarray(data)))
    assert not np.isnan(loss), f"{code} ({canon}): loss is NaN"

    nan_tail = np.concatenate(
        [data, np.full((len(MATS), 3), np.nan)], axis=1)
    out = predict(spec, p, jnp.asarray(nan_tail))
    T_ext = nan_tail.shape[1]  # predict appends one internal NaN step and
    assert np.asarray(out["preds"]).shape == (len(MATS), T_ext), code
    for key in ("factors", "states", "factor_loadings_1", "factor_loadings_2"):
        assert key in out, f"{code}: missing artifact {key!r}"
    # the forecast tail must be filled (predict-only steps), not NaN
    tail = np.asarray(out["preds"])[:, -2:]
    assert np.isfinite(tail).all(), f"{code}: NaN forecast tail"
