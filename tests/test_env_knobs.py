"""Guard: every ``YFM_*`` engine env knob referenced anywhere in source —
and every ``BENCH_*`` knob ``bench.py`` reads — is documented in CLAUDE.md
(an undocumented knob is a silent behavior switch the next session can't
discover) — grep-based, fails loudly on the first undocumented name."""

import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KNOB = re.compile(r"\bYFM_[A-Z0-9_]+\b")
BENCH_KNOB = re.compile(r"\bBENCH_[A-Z0-9_]+\b")


def _source_files():
    for dirpath, _, names in os.walk(
            os.path.join(ROOT, "yieldfactormodels_jl_tpu")):
        for name in names:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)
    yield os.path.join(ROOT, "bench.py")
    bench_dir = os.path.join(ROOT, "benchmarks")
    for name in os.listdir(bench_dir):
        if name.endswith(".py"):
            yield os.path.join(bench_dir, name)


def test_every_yfm_knob_is_documented_in_claude_md():
    knobs = set()
    for path in _source_files():
        with open(path) as fh:
            knobs |= set(KNOB.findall(fh.read()))
    # vacuity guard: the knobs this repo is known to ship; if the grep rots
    # and finds nothing, fail instead of green-lighting
    assert {"YFM_SSD_PALLAS", "YFM_FUSED_CHECK", "YFM_MSED_CLOSED",
            "YFM_PF_PALLAS"} <= knobs, f"grep drifted: found only {knobs}"
    with open(os.path.join(ROOT, "CLAUDE.md")) as fh:
        doc = fh.read()
    undocumented = sorted(k for k in knobs if k not in doc)
    assert not undocumented, (
        f"undocumented YFM_* env knobs: {undocumented} — add them to the "
        f"'Engine env knobs' bullet in CLAUDE.md's Conventions")


def test_every_bench_knob_read_by_bench_py_is_documented_in_claude_md():
    """The same guard the YFM_* knobs carry, extended to bench.py's BENCH_*
    switches: every knob the headline bench reads must be discoverable in
    CLAUDE.md — an opt-in bench section nobody can find is a bench section
    nobody runs."""
    with open(os.path.join(ROOT, "bench.py")) as fh:
        knobs = set(BENCH_KNOB.findall(fh.read()))
    # vacuity guard: the opt-in sections this repo is known to ship
    assert {"BENCH_SERVING", "BENCH_ORCH", "BENCH_LOAD", "BENCH_LONGT",
            "BENCH_ROBUST", "BENCH_SCEN"} <= knobs, \
        f"grep drifted: found only {sorted(knobs)}"
    with open(os.path.join(ROOT, "CLAUDE.md")) as fh:
        doc = fh.read()
    undocumented = sorted(k for k in knobs if k not in doc)
    assert not undocumented, (
        f"undocumented BENCH_* env knobs: {undocumented} — add them to the "
        f"Benchmarks bullet in CLAUDE.md's Commands")
