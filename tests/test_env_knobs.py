"""Guard: every ``YFM_*`` engine env knob referenced anywhere in source is
documented in CLAUDE.md's Conventions (an undocumented knob is a silent
behavior switch the next session can't discover) — grep-based, fails loudly
on the first undocumented name."""

import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KNOB = re.compile(r"\bYFM_[A-Z0-9_]+\b")


def _source_files():
    for dirpath, _, names in os.walk(
            os.path.join(ROOT, "yieldfactormodels_jl_tpu")):
        for name in names:
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)
    yield os.path.join(ROOT, "bench.py")
    bench_dir = os.path.join(ROOT, "benchmarks")
    for name in os.listdir(bench_dir):
        if name.endswith(".py"):
            yield os.path.join(bench_dir, name)


def test_every_yfm_knob_is_documented_in_claude_md():
    knobs = set()
    for path in _source_files():
        with open(path) as fh:
            knobs |= set(KNOB.findall(fh.read()))
    # vacuity guard: the knobs this repo is known to ship; if the grep rots
    # and finds nothing, fail instead of green-lighting
    assert {"YFM_SSD_PALLAS", "YFM_FUSED_CHECK", "YFM_MSED_CLOSED",
            "YFM_PF_PALLAS"} <= knobs, f"grep drifted: found only {knobs}"
    with open(os.path.join(ROOT, "CLAUDE.md")) as fh:
        doc = fh.read()
    undocumented = sorted(k for k in knobs if k not in doc)
    assert not undocumented, (
        f"undocumented YFM_* env knobs: {undocumented} — add them to the "
        f"'Engine env knobs' bullet in CLAUDE.md's Conventions")
