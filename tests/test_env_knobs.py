"""Guard: every ``YFM_*`` engine env knob referenced anywhere in source —
and every ``BENCH_*`` knob the bench layer reads — is documented in
CLAUDE.md (an undocumented knob is a silent behavior switch the next
session can't discover).

Thin wrapper over graftlint rule YFM006 (docs/DESIGN.md §15): the knob
regexes, file walk and CLAUDE.md lookup live once in
``yieldfactormodels_jl_tpu.analysis.rules``; this module keeps the
historical test names, per-namespace split and vacuity anchors.
"""

import os

from yieldfactormodels_jl_tpu.analysis import LintConfig, SourceModule, run_lint
from yieldfactormodels_jl_tpu.analysis.rules import knob_occurrences

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = LintConfig(root=ROOT)


def _all_knobs(bench_only: bool):
    """Knob names the linted file set references (YFM_* everywhere;
    BENCH_* in the bench layer)."""
    knobs = set()
    for rel in CFG.lint_files():
        bench = CFG.matches(rel, CFG.bench_files)
        if bench_only and not bench:
            continue
        mod = SourceModule(CFG.abspath(rel), rel)
        for knob, _line in knob_occurrences(mod, bench):
            if bench_only == knob.startswith("BENCH_"):
                knobs.add(knob)
    return knobs


def _yfm006_findings():
    # pragma suppressions honored — same policy as the CLI (DESIGN §15)
    return run_lint(CFG, rules=["YFM006"]).findings


def test_every_yfm_knob_is_documented_in_claude_md():
    # vacuity guard: the knobs this repo is known to ship; if the walk rots
    # and finds nothing, fail instead of green-lighting
    knobs = _all_knobs(bench_only=False)
    assert {"YFM_SSD_PALLAS", "YFM_FUSED_CHECK", "YFM_MSED_CLOSED",
            "YFM_PF_PALLAS"} <= knobs, f"knob walk drifted: found only {knobs}"
    undocumented = sorted(f"{f.file}:{f.line} {f.message}"
                          for f in _yfm006_findings()
                          if "YFM_" in f.message)
    assert not undocumented, (
        "undocumented YFM_* env knobs — add them to the 'Engine env knobs' "
        "bullet in CLAUDE.md's Conventions:\n" + "\n".join(undocumented))


def test_every_bench_knob_read_by_bench_py_is_documented_in_claude_md():
    """The same guard the YFM_* knobs carry, extended to the whole bench
    layer's BENCH_* switches (bench.py AND benchmarks/*.py since graftlint):
    every knob the bench layer reads must be discoverable in CLAUDE.md — an
    opt-in bench section nobody can find is a bench section nobody runs."""
    knobs = _all_knobs(bench_only=True)
    # vacuity guard: the opt-in sections this repo is known to ship
    assert {"BENCH_SERVING", "BENCH_ORCH", "BENCH_LOAD", "BENCH_LONGT",
            "BENCH_ROBUST", "BENCH_SCEN"} <= knobs, \
        f"knob walk drifted: found only {sorted(knobs)}"
    undocumented = sorted(f"{f.file}:{f.line} {f.message}"
                          for f in _yfm006_findings()
                          if "BENCH_" in f.message)
    assert not undocumented, (
        "undocumented BENCH_* env knobs — add them to the Benchmarks bullet "
        "in CLAUDE.md's Commands:\n" + "\n".join(undocumented))
