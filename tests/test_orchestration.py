"""Orchestration subsystem: leased queue, checkpoint/resume, chaos recovery.

The flagship test simulates a worker dying mid-window (chaos-injected, no
SIGKILL), then asserts a second supervisor steals the lease, resumes the
estimation cascade from the checkpoint, and produces a merged forecast DB
whose every row — loss floats and result blobs byte-for-byte — equals a
fault-free single-worker run, with the resumed worker demonstrably skipping
the group iterations the dead worker already completed (recorded call
counts in ``orchestration.checkpoint.ITERS_EXECUTED``).
"""

import os
import sqlite3
import time

import numpy as np
import pytest

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.orchestration import chaos
from yieldfactormodels_jl_tpu.orchestration import checkpoint as ckpt_mod
from yieldfactormodels_jl_tpu.orchestration.checkpoint import WindowCheckpoint
from yieldfactormodels_jl_tpu.orchestration.queue import (LeaseLost, TaskQueue)
from yieldfactormodels_jl_tpu.orchestration.retry import (RetryPolicy,
                                                          SentinelFailure,
                                                          backoff_delay)
from yieldfactormodels_jl_tpu.orchestration import supervisor as sup
from yieldfactormodels_jl_tpu.persistence import database as db
from yieldfactormodels_jl_tpu.persistence.locks import break_stale_lock

MATS = tuple(np.array([3.0, 12.0, 24.0, 60.0, 120.0, 360.0]) / 12.0)


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.reset()
    yield
    chaos.reset()


def _spec(tmp_path, code="RW"):
    spec, _ = create_model(code, MATS, float_type="float64",
                           results_location=str(tmp_path) + os.sep)
    return spec


def _panel(T=40):
    rng = np.random.default_rng(5)
    return np.cumsum(rng.standard_normal((len(MATS), T)) * 0.1, axis=1) + 5.0


def _ns_init(spec):
    p = np.zeros(spec.n_params)
    p[0] = np.log(0.5)
    p[1:4] = [0.3, -0.1, 0.05]
    p[4:13] = np.diag([0.9, 0.85, 0.8]).T.reshape(-1)
    return p[:, None]


def _merged_rows(path):
    conn = sqlite3.connect(path)
    try:
        return conn.execute(
            "SELECT model,thread,window,task_id,loss,params,preds,fl1,fl2,"
            "factors,states FROM forecasts ORDER BY task_id").fetchall()
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# queue
# ---------------------------------------------------------------------------

def test_queue_lease_lifecycle_and_steal(tmp_path):
    q = TaskQueue(str(tmp_path / "q.sqlite3"))
    assert q.enqueue(["a", "b"]) == 2
    assert q.enqueue(["a", "b"]) == 0  # idempotent

    # unexpired leases are exclusive (generous TTL: this box is 1-core and
    # a loaded scheduler must not fake an expiry)
    l1 = q.claim("w1", ttl=120.0)
    l2 = q.claim("w2", ttl=120.0)
    assert {l1.key, l2.key} == {"a", "b"}
    assert q.claim("w3", ttl=120.0) is None

    q.complete(l2)
    assert q.counts()["done"] == 1

    # TTL expiry -> atomic steal; the dead owner's late writes are rejected
    q2 = TaskQueue(str(tmp_path / "q2.sqlite3"))
    q2.enqueue(["t"])
    dead = q2.claim("dead", ttl=0.1)
    time.sleep(0.15)
    stolen = q2.claim("alive", ttl=120.0)
    assert stolen is not None and stolen.key == "t" and stolen.attempts == 2
    assert q2.heartbeat(dead) is False
    with pytest.raises(LeaseLost):
        q2.complete(dead)
    q2.complete(stolen)
    assert q2.counts()["done"] == 1


def test_queue_retry_backoff_and_quarantine(tmp_path):
    q = TaskQueue(str(tmp_path / "q.sqlite3"))
    q.enqueue(["poison"])
    lease = q.claim("w1", ttl=120.0)
    q.fail(lease, "boom", retry_in=30.0)
    assert q.claim("w1", ttl=120.0) is None  # backoff holds it
    snap = q.snapshot()[0]
    assert snap["status"] == "pending" and snap["last_error"] == "boom"

    # zero backoff -> claimable again; quarantine is terminal w/ cause
    q2 = TaskQueue(str(tmp_path / "q2.sqlite3"))
    q2.enqueue(["poison"])
    l1 = q2.claim("w1", ttl=120.0)
    q2.fail(l1, "first", retry_in=0.0)
    l2 = q2.claim("w1", ttl=120.0)
    assert l2.attempts == 2
    q2.fail(l2, "ZeroDivisionError: the cause", quarantine=True)
    assert q2.claim("w1", ttl=120.0) is None
    assert q2.all_terminal()
    row = q2.snapshot()[0]
    assert row["status"] == "quarantined" and "the cause" in row["last_error"]

    # release gives the claim back without burning an attempt (merge barrier)
    q3 = TaskQueue(str(tmp_path / "q3.sqlite3"))
    q3.enqueue(["merge"])
    lr = q3.claim("w1", ttl=120.0)
    q3.release(lr, retry_in=0.0)
    assert q3.claim("w1", ttl=120.0).attempts == 1


def test_queue_degraded_mkdir_fallback(tmp_path):
    # journal path under a FILE -> unreachable -> mkdir-lock protocol
    blocker = tmp_path / "blocker"
    blocker.write_text("not a dir")
    q = TaskQueue(str(blocker / "q.sqlite3"),
                  fallback_lockroot=str(tmp_path / "locks"))
    assert q.degraded
    q.enqueue(["a", "b"])
    l1 = q.claim("w1", ttl=120.0)
    assert l1 is not None and l1.token == "mkdir"
    assert os.path.isdir(os.path.join(str(tmp_path / "locks"), "a.lock"))
    assert q.heartbeat(l1) is True  # utime on the lock dir
    # a second degraded queue (another process) cannot double-claim
    q2 = TaskQueue(str(blocker / "q.sqlite3"),
                   fallback_lockroot=str(tmp_path / "locks"))
    q2.enqueue(["a", "b"])
    assert q2.claim("w2", ttl=120.0).key == "b"
    q.complete(l1)
    assert not os.path.isdir(os.path.join(str(tmp_path / "locks"), "a.lock"))
    assert q.counts()["done"] == 1


# ---------------------------------------------------------------------------
# chaos / retry / checkpoint / locks units
# ---------------------------------------------------------------------------

def test_chaos_count_and_probability_triggers():
    chaos.configure("estimate:@2")
    chaos.maybe_fail("estimate")
    with pytest.raises(chaos.ChaosInjected):
        chaos.maybe_fail("estimate")
    chaos.maybe_fail("estimate")  # only the N-th hit fires
    chaos.maybe_fail("other_seam")  # unarmed seams never fire
    assert chaos.hits("estimate") == 3

    # probability triggers replay under a fixed seed
    def run(seed):
        chaos.configure("merge:0.5", seed=seed)
        fired = []
        for _ in range(32):
            try:
                chaos.maybe_fail("merge")
                fired.append(0)
            except chaos.ChaosInjected:
                fired.append(1)
        return fired

    assert run(7) == run(7)
    assert any(run(7))
    # armed seam names are validated against the KNOWN_SEAMS registry: a
    # typo'd seam must fail loudly at configure time, naming the valid set
    with pytest.raises(ValueError, match="unknown seam 'estimat'"):
        chaos.configure("estimat:@2")
    with pytest.raises(ValueError, match="one of: "):
        chaos.configure("merge:@1,typo_seam:0.5")
    chaos.reset()


def test_backoff_delay_grows_and_is_bounded():
    pol = RetryPolicy(max_attempts=5, base_delay=1.0, factor=2.0,
                      max_delay=5.0, jitter=0.0)
    assert [backoff_delay(pol, k) for k in (1, 2, 3, 4, 5)] == \
        [1.0, 2.0, 4.0, 5.0, 5.0]


def test_checkpoint_roundtrip_signature_and_clear(tmp_path):
    ck = WindowCheckpoint(str(tmp_path), "expanding", 31)
    sig = dict(model="NS", T=36, groups="1,2")
    assert ck.load(sig) is None
    state = dict(X=np.arange(6, dtype=np.float64).reshape(2, 3),
                 prev_ll=np.array([-1.5, -2.5]), next_it=2)
    ck.save(sig, state)
    got = ck.load(sig)
    np.testing.assert_array_equal(got["X"], state["X"])
    assert int(got["next_it"]) == 2 and ck.resumed_iters == 2
    # any signature drift (different data length) discards the checkpoint
    assert ck.load(dict(sig, T=40)) is None
    # corrupt file is refit-from-scratch, not a crash
    with open(ck.path, "wb") as fh:
        fh.write(b"garbage")
    assert ck.load(sig) is None
    ck.clear()
    assert not os.path.isfile(ck.path)


def test_break_stale_lock(tmp_path):
    lock = str(tmp_path / "task_7.lock")
    os.makedirs(lock)
    assert not break_stale_lock(lock, ttl_seconds=3600.0)  # fresh: kept
    old = time.time() - 7200
    os.utime(lock, (old, old))
    assert break_stale_lock(lock, ttl_seconds=3600.0)
    assert not os.path.isdir(lock)
    assert not break_stale_lock(lock, ttl_seconds=3600.0)  # gone: no-op


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def test_worker_completes_rw_run_and_status_reports(tmp_path):
    """Fast tier-1 smoke: one worker drains an RW rolling run through the
    queue (claim → shard → complete → merge barrier → export)."""
    spec = _spec(tmp_path)
    data = _panel(T=36)
    init = np.zeros((spec.n_params, 1))
    stats = sup.run_worker(
        spec, data, "1", 30, 1, 4, init, window_type="expanding",
        worker_id="solo", lease_ttl=120.0, poll_interval=0.05,
        reestimate=False)
    assert not stats.died
    assert stats.merged == ["expanding"]
    assert stats.completed == 7 + 1  # 7 origins + merge barrier
    merged = os.path.join(str(tmp_path), "db",
                          "forecasts_expanding_merged.sqlite3")
    rows = _merged_rows(merged)
    assert [r[3] for r in rows] == list(range(30, 37))
    # exported the legacy CSVs too
    assert os.path.isfile(os.path.join(
        str(tmp_path), "RW__thread_id__1__expanding_window_forecasts.csv"))
    st = sup.status(sup.default_queue_path(spec))
    assert st["counts"]["done"] == 8 and st["progress"] == 1.0
    assert "progress 100.0%" in sup.format_status(sup.default_queue_path(spec))
    # a rerun against the terminal queue is a no-op
    stats2 = sup.run_worker(
        spec, data, "1", 30, 1, 4, init, window_type="expanding",
        worker_id="again", lease_ttl=120.0, poll_interval=0.05,
        reestimate=False)
    assert stats2.completed == 0


def test_chaos_shard_write_death_then_restart_completes(tmp_path):
    """Worker dies (chaos) before a shard write; a restarted worker steals
    the expired lease and finishes the run — the mkdir-era bug (forever-
    leaked lock) becomes a bounded TTL wait."""
    spec = _spec(tmp_path)
    data = _panel(T=40)
    init = np.zeros((spec.n_params, 1))
    chaos.configure("shard_write:@4")
    w1 = sup.run_worker(
        spec, data, "1", 31, 1, 3, init, window_type="expanding",
        worker_id="w1", lease_ttl=0.4, poll_interval=0.05, reestimate=False)
    assert w1.died and w1.completed == 3
    chaos.reset()  # the restarted worker is healthy
    w2 = sup.run_worker(
        spec, data, "1", 31, 1, 3, init, window_type="expanding",
        worker_id="w2", lease_ttl=0.4, poll_interval=0.05, reestimate=False)
    assert not w2.died and w2.stolen >= 1
    merged = os.path.join(str(tmp_path), "db",
                          "forecasts_expanding_merged.sqlite3")
    rows = _merged_rows(merged)
    assert [r[3] for r in rows] == list(range(31, 41))


def test_chaos_merge_death_then_restart_remerges(tmp_path):
    spec = _spec(tmp_path)
    data = _panel(T=36)
    init = np.zeros((spec.n_params, 1))
    chaos.configure("merge:@1")
    w1 = sup.run_worker(
        spec, data, "1", 32, 1, 3, init, window_type="expanding",
        worker_id="w1", lease_ttl=0.4, poll_interval=0.05, reestimate=False)
    assert w1.died and w1.merged == []
    chaos.reset()
    w2 = sup.run_worker(
        spec, data, "1", 32, 1, 3, init, window_type="expanding",
        worker_id="w2", lease_ttl=0.4, poll_interval=0.05, reestimate=False)
    assert w2.merged == ["expanding"]
    merged = os.path.join(str(tmp_path), "db",
                          "forecasts_expanding_merged.sqlite3")
    assert [r[3] for r in _merged_rows(merged)] == list(range(32, 37))


def test_sentinel_loss_raises_retriable_failure(tmp_path, monkeypatch):
    """−Inf at the driver boundary becomes a retriable task failure under
    sentinel_policy='retry' (the queue path), while the legacy path keeps
    the reference behavior of saving the NULL loss."""
    from yieldfactormodels_jl_tpu import forecasting as fc

    spec = _spec(tmp_path, code="NS")
    data = _panel(T=36)
    monkeypatch.setattr(
        fc, "_estimate_for_window",
        lambda *a, **k: (float("-inf"), np.zeros(spec.n_params)))
    with pytest.raises(SentinelFailure, match="non-finite loss sentinel"):
        fc.run_single_window_task(
            spec, data, "1", 33, "expanding", 33, 1, 3,
            np.zeros((spec.n_params, 1)), param_groups=["1"] * spec.n_params,
            sentinel_policy="retry")
    # legacy policy: shard written with NULL loss
    p = fc.run_single_window_task(
        spec, data, "1", 33, "expanding", 33, 1, 3,
        np.zeros((spec.n_params, 1)), param_groups=["1"] * spec.n_params,
        sentinel_policy="save")
    assert os.path.isfile(p)


def test_poison_task_quarantined_with_cause(tmp_path):
    """A structurally failing estimation burns its attempts and lands in
    quarantine with the recorded cause; the merge barrier then quarantines
    too (cannot merge) instead of hanging the worker loop."""
    spec = _spec(tmp_path, code="NS")
    data = np.full((len(MATS), 34), 1e308)  # objective non-finite everywhere
    init = _ns_init(spec)
    stats = sup.run_worker(
        spec, data, "1", 33, 1, 3, init, window_type="expanding",
        worker_id="w1", lease_ttl=120.0, poll_interval=0.05,
        retry=RetryPolicy(max_attempts=2, base_delay=0.01, max_delay=0.02),
        param_groups=list(spec.default_param_groups()), max_group_iters=1)
    assert stats.failed > 0 and not stats.died
    st = sup.status(sup.default_queue_path(spec))
    assert st["counts"]["quarantined"] == 2 + 1  # 2 windows + merge barrier
    window_errs = [r for r in st["quarantined"]
                   if not r["task"].startswith("merge:")]
    assert all(r["attempts"] == 2 for r in window_errs)
    assert any("non-finite" in (r["error"] or "") for r in window_errs)
    assert any("cannot merge" in (r["error"] or "")
               for r in st["quarantined"] if r["task"].startswith("merge:"))
    rendered = sup.format_status(sup.default_queue_path(spec))
    assert "QUARANTINED" in rendered


# ---------------------------------------------------------------------------
# the acceptance scenario: mid-estimation death, steal, checkpoint resume
# ---------------------------------------------------------------------------

def test_mid_window_death_lease_steal_checkpoint_resume(tmp_path):
    """YFM_CHAOS-style injected death MID-ESTIMATION (after one of two
    block-coordinate iterations of the second window): the restarted
    supervisor must steal the lease, resume the cascade from the
    checkpoint, and produce a merged DB identical row-for-row (losses,
    params and forecast blobs byte-exact) to a fault-free single-worker
    run, with no duplicate shards and with the resumed worker's recorded
    group-iteration counts proving the completed multi-starts were
    skipped, not refit."""
    data = _panel(T=36)
    in_end, h = 34, 3  # windows 34, 35, 36
    n_windows, iters_per_window = 3, 2

    # ---- fault-free reference run (its own results dir) ----
    spec_ref = _spec(tmp_path / "ref", code="NS")
    groups = list(spec_ref.default_param_groups())
    kw = dict(window_type="expanding", poll_interval=0.05,
              param_groups=groups, max_group_iters=iters_per_window,
              group_tol=0.0, reestimate=True)  # tol=0: fixed iteration count
    ckpt_mod.ITERS_EXECUTED.clear()
    ref = sup.run_worker(spec_ref, data, "1", in_end, 1, h, _ns_init(spec_ref),
                         worker_id="ref", lease_ttl=120.0, **kw)
    assert not ref.died and ref.merged == ["expanding"]
    ref_iters = dict(ckpt_mod.ITERS_EXECUTED)
    assert sum(ref_iters.values()) == n_windows * iters_per_window

    # ---- chaos run: worker 1 dies after iteration 1 of its 2nd window ----
    spec = _spec(tmp_path / "chaos", code="NS")
    hit = iters_per_window + 1  # the 3rd 'estimate' seam hit = mid-window 2
    chaos.configure(f"estimate:@{hit}")
    ckpt_mod.ITERS_EXECUTED.clear()
    w1 = sup.run_worker(spec, data, "1", in_end, 1, h, _ns_init(spec),
                        worker_id="w1", lease_ttl=1.0, **kw)
    assert w1.died and w1.completed == 1  # first window done, second in-flight
    w1_iters = sum(ckpt_mod.ITERS_EXECUTED.values())
    assert w1_iters == hit
    # the in-flight window left a live checkpoint behind
    ckroot = os.path.join(spec.results_location, "db", "checkpoints")
    left = [f for f in os.listdir(os.path.join(ckroot, "expanding"))]
    assert left == ["task_35.ckpt.npz"]

    # ---- restarted supervisor: steal + resume + finish + merge ----
    chaos.reset()
    ckpt_mod.ITERS_EXECUTED.clear()
    w2 = sup.run_worker(spec, data, "1", in_end, 1, h, _ns_init(spec),
                        worker_id="w2", lease_ttl=1.0, **kw)
    assert not w2.died and w2.stolen >= 1 and w2.merged == ["expanding"]
    w2_iters = sum(ckpt_mod.ITERS_EXECUTED.values())
    # resumed, not refit: w1+w2 together ran exactly the fault-free count,
    # so w2 skipped every iteration w1 had already checkpointed
    assert w1_iters + w2_iters == sum(ref_iters.values())
    assert w2_iters < sum(ref_iters.values())
    # checkpoints are cleared once their shard is durable
    assert os.listdir(os.path.join(ckroot, "expanding")) == []

    # ---- artifact equality: merged DB row-for-row vs the fault-free run ----
    rows_ref = _merged_rows(os.path.join(
        spec_ref.results_location, "db", "forecasts_expanding_merged.sqlite3"))
    rows = _merged_rows(os.path.join(
        spec.results_location, "db", "forecasts_expanding_merged.sqlite3"))
    assert len(rows) == n_windows  # no duplicate shards
    assert rows == rows_ref  # losses, params and blobs byte-identical
    # shards were folded and deleted
    leftovers = [f for f in os.listdir(os.path.join(spec.results_location, "db"))
                 if f.startswith("forecasts_expanding") and "merged" not in f]
    assert leftovers == []
