"""Stage timer / trace wrappers (SURVEY.md §5.1 subsystem)."""

import time

from yieldfactormodels_jl_tpu.utils.profiling import StageTimer, annotate, device_trace


def test_stage_timer_accumulates():
    t = StageTimer()
    for _ in range(3):
        with t.stage("est"):
            time.sleep(0.01)
    assert t.counts["est"] == 3
    assert t.totals["est"] >= 0.03
    assert abs(t.mean("est") - t.totals["est"] / 3) < 1e-12
    assert "est:" in t.report()
    assert t.mean("never") == 0.0


def test_summary_percentiles_nearest_rank():
    """summary()/to_json() — deterministic via record(): nearest-rank p50/p99
    so serving latency percentiles can land in the BENCH ledger."""
    t = StageTimer()
    for ms in range(1, 101):          # 0.001 .. 0.100 s
        t.record("update", ms / 1000.0)
    t.record("forecast", 0.5)
    s = t.summary()
    assert s["update"]["count"] == 100
    assert abs(s["update"]["p50"] - 0.050) < 1e-12   # ⌈0.5·100⌉ = 50th
    assert abs(s["update"]["p99"] - 0.099) < 1e-12   # ⌈0.99·100⌉ = 99th
    assert abs(s["update"]["max"] - 0.100) < 1e-12
    assert abs(s["update"]["mean"] - 0.0505) < 1e-12
    # single sample: every percentile is that sample
    assert s["forecast"]["p50"] == s["forecast"]["p99"] == 0.5

    import json

    j = json.loads(t.to_json(config="headline"))
    assert j["config"] == "headline"
    assert j["stages"]["update"]["count"] == 100

    # stage() feeds the same sample store as record()
    with t.stage("est"):
        time.sleep(0.001)
    assert t.summary()["est"]["count"] == 1
    assert t.summary()["est"]["p50"] > 0.0


def test_sample_window_is_bounded_but_totals_exact():
    """Percentiles ride a bounded sliding window (long-lived serving
    process); count/total/mean stay exact over the full history."""
    t = StageTimer(max_samples=4)
    for ms in range(1, 11):
        t.record("u", ms / 1000.0)
    s = t.summary()
    assert s["u"]["count"] == 10
    assert abs(s["u"]["total"] - 0.055) < 1e-12
    assert len(t.samples["u"]) == 4          # only the last 4 retained
    assert abs(s["u"]["p50"] - 0.008) < 1e-12  # window = 7,8,9,10 ms


def test_summary_empty_timer():
    t = StageTimer()
    assert t.summary() == {}
    import json

    assert json.loads(t.to_json()) == {"stages": {}}


def test_device_trace_noop_and_annotation():
    with device_trace(None):  # no logdir -> must be a pure no-op
        x = 1
    with annotate("region"):
        x += 1
    assert x == 2


def test_device_trace_writes_profile(tmp_path):
    import jax.numpy as jnp

    logdir = str(tmp_path / "prof")
    with device_trace(logdir):
        jnp.ones((4, 4)).sum().block_until_ready()
    import os

    assert os.path.isdir(logdir) and os.listdir(logdir)
