"""Stage timer / trace wrappers (SURVEY.md §5.1 subsystem)."""

import time

from yieldfactormodels_jl_tpu.utils.profiling import StageTimer, annotate, device_trace


def test_stage_timer_accumulates():
    t = StageTimer()
    for _ in range(3):
        with t.stage("est"):
            time.sleep(0.01)
    assert t.counts["est"] == 3
    assert t.totals["est"] >= 0.03
    assert abs(t.mean("est") - t.totals["est"] / 3) < 1e-12
    assert "est:" in t.report()
    assert t.mean("never") == 0.0


def test_device_trace_noop_and_annotation():
    with device_trace(None):  # no logdir -> must be a pure no-op
        x = 1
    with annotate("region"):
        x += 1
    assert x == 2


def test_device_trace_writes_profile(tmp_path):
    import jax.numpy as jnp

    logdir = str(tmp_path / "prof")
    with device_trace(logdir):
        jnp.ones((4, 4)).sum().block_until_ready()
    import os

    assert os.path.isdir(logdir) and os.listdir(logdir)
