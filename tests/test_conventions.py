"""Static-analysis guards for repo-wide mechanical conventions.

1. Sentinel convention (CLAUDE.md, DESIGN §4): no ``raise`` inside
   jit/scan/Pallas kernel bodies under ``ops/`` and ``serving/online.py`` —
   failures there must be sentinels (−Inf loss, NaN moments) plus a taxonomy
   code (robustness/taxonomy.py), never exceptions.

   Mechanical rule (AST, not regex, so strings/comments can't fool it):

   - a ``raise`` inside a NESTED function (a closure — scan bodies, jitted
     ``one``/``many`` builders, Pallas kernel bodies) is a violation: those
     run traced, where ``raise`` either fires spuriously at trace time or
     silently never fires at run time;
   - a ``raise`` at the top level of a module-level function is allowed only
     for the trace-time validation classes (ValueError / TypeError /
     NotImplementedError / AttributeError) — shape/config checks that fire
     before tracing starts, the documented driver-layer exception.

2. Request-path backpressure convention (DESIGN §12): the serving
   request-path modules (everything under ``serving/``) may hold work only
   in BOUNDED buffers and may never block on a bare ``time.sleep`` — an
   unbounded ``queue.Queue()`` or an uninterruptible sleep is exactly how
   backpressure regresses silently.  Chaos injection
   (orchestration/chaos.py, where injected latency legitimately sleeps) and
   test code live outside the scanned set by construction.
"""

import ast
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "yieldfactormodels_jl_tpu")

#: trace-time validation exception classes (allowed in top-level functions)
WHITELIST = {"ValueError", "TypeError", "NotImplementedError",
             "AttributeError"}


def _kernel_files():
    opsdir = os.path.join(PKG, "ops")
    for name in sorted(os.listdir(opsdir)):
        if name.endswith(".py"):
            yield os.path.join(opsdir, name)
    yield os.path.join(PKG, "serving", "online.py")
    # the fused scenario-lattice module (DESIGN §14): its programs must stay
    # sentinel-coded (−Inf cells / NaN fan) like every other kernel
    yield os.path.join(PKG, "estimation", "scenario.py")


def _func_depth(node, parents):
    """Number of enclosing FunctionDef/AsyncFunctionDef/Lambda scopes."""
    depth = 0
    p = parents.get(node)
    while p is not None:
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            depth += 1
        p = parents.get(p)
    return depth


def _raised_name(node):
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Name):
        return exc.id
    if isinstance(exc, ast.Attribute):
        return exc.attr
    return None  # bare `raise` / exotic expression


def test_no_raise_inside_kernel_bodies():
    violations = []
    for path in _kernel_files():
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        rel = os.path.relpath(path, ROOT)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Raise):
                continue
            depth = _func_depth(node, parents)
            name = _raised_name(node)
            if depth >= 2:
                violations.append(
                    f"{rel}:{node.lineno} raise inside a nested function "
                    f"(scan/kernel body) — use the −Inf/NaN sentinel + "
                    f"taxonomy code instead")
            elif name not in WHITELIST:
                violations.append(
                    f"{rel}:{node.lineno} raises {name or '<bare>'} — only "
                    f"trace-time validation ({sorted(WHITELIST)}) is allowed "
                    f"in kernel modules")
    assert not violations, "sentinel-convention violations:\n" + \
        "\n".join(violations)


def test_guard_is_not_vacuous():
    """The file walk must actually see the kernel modules it claims to guard
    (a rotted path would green-light everything)."""
    names = {os.path.basename(p) for p in _kernel_files()}
    assert {"univariate_kf.py", "sqrt_kf.py", "particle.py", "smoother.py",
            "online.py", "scenario.py"} <= names


# ---------------------------------------------------------------------------
# request-path guard: bounded queues, no bare sleeps (DESIGN §12)
# ---------------------------------------------------------------------------

def _request_path_files():
    servdir = os.path.join(PKG, "serving")
    for name in sorted(os.listdir(servdir)):
        if name.endswith(".py"):
            yield os.path.join(servdir, name)


def _call_name(node):
    """Dotted name of a Call's callee: 'time.sleep', 'queue.Queue', 'Queue'."""
    fn = node.func
    parts = []
    while isinstance(fn, ast.Attribute):
        parts.append(fn.attr)
        fn = fn.value
    if isinstance(fn, ast.Name):
        parts.append(fn.id)
    return ".".join(reversed(parts))


def test_request_path_bounded_queues_and_no_bare_sleep():
    """No unbounded ``queue.Queue()`` and no bare ``time.sleep`` anywhere in
    the serving request path: depth bounds must be explicit (the gateway's
    deque + admission control) and waits must be interruptible
    (``Event.wait``/``Condition.wait``).  Chaos/test code is whitelisted by
    living outside ``serving/``."""
    violations = []
    for path in _request_path_files():
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        rel = os.path.relpath(path, ROOT)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name in ("time.sleep", "sleep"):
                violations.append(
                    f"{rel}:{node.lineno} bare {name}() on the request path "
                    f"— use an interruptible Event/Condition wait")
            if name in ("queue.Queue", "Queue", "queue.LifoQueue",
                        "queue.PriorityQueue", "queue.SimpleQueue"):
                # stdlib Queue() with no maxsize is unbounded by default;
                # (the gateway's raw deque is fine: its bound is the
                # admission check, pinned by tests/test_gateway.py)
                bounded = bool(node.args) or any(
                    kw.arg == "maxsize" for kw in node.keywords)
                if not bounded:
                    violations.append(
                        f"{rel}:{node.lineno} unbounded {name}() on the "
                        f"request path — give it a maxsize (backpressure)")
    assert not violations, "request-path convention violations:\n" + \
        "\n".join(violations)


def test_request_path_guard_is_not_vacuous():
    names = {os.path.basename(p) for p in _request_path_files()}
    assert {"gateway.py", "batcher.py", "service.py", "online.py"} <= names


# ---------------------------------------------------------------------------
# engine-coverage guard: every Kalman loglik engine is oracle-backed
# ---------------------------------------------------------------------------

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _oracle_backed_test_files():
    """(name, AST) of every test module that imports ``tests/oracle.py`` —
    the independent NumPy float64 loops every numeric kernel must be pinned
    against (CLAUDE.md: never against another JAX path alone)."""
    for name in sorted(os.listdir(TESTS_DIR)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        path = os.path.join(TESTS_DIR, name)
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        uses_oracle = False
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.split(".")[-1] == "oracle":
                uses_oracle = True
            if isinstance(node, ast.ImportFrom) and node.module \
                    and any(a.name == "oracle" for a in node.names):
                uses_oracle = True
            if isinstance(node, ast.Import) \
                    and any(a.name.split(".")[-1] == "oracle"
                            for a in node.names):
                uses_oracle = True
        if uses_oracle:
            yield name, tree


def test_every_kalman_engine_has_oracle_parity_coverage():
    """Mechanical guard (AST, matching the sentinel guards above): every
    engine name in ``config.KALMAN_ENGINES`` must appear as a string
    constant inside at least one oracle-importing test module — a new
    engine cannot ship selectable without an oracle-backed parity test
    naming it.  (tests/test_assoc_estimation.py carries the canonical
    all-engines row and pins its literal list to the registry, so the
    string-level proxy here is anchored to a real parity test.)"""
    from yieldfactormodels_jl_tpu.config import KALMAN_ENGINES

    files = dict(_oracle_backed_test_files())
    strings = {
        name: {n.value for n in ast.walk(tree)
               if isinstance(n, ast.Constant) and isinstance(n.value, str)}
        for name, tree in files.items()
    }
    missing = [e for e in KALMAN_ENGINES
               if not any(e in ss for ss in strings.values())]
    assert not missing, (
        f"engines with no oracle-backed parity coverage: {missing} — add a "
        f"parity test against tests/oracle.py that names the engine "
        f"(see test_assoc_estimation.test_engine_oracle_parity_with_nan_gap)")
    # non-vacuity: the walk must see the canonical coverage module and the
    # registry must still be the four-engine set (or larger)
    assert "test_assoc_estimation.py" in files, \
        "engine-coverage guard rotted: canonical parity module not scanned"
    assert len(KALMAN_ENGINES) >= 4
