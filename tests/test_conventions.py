"""Static-analysis guard for the sentinel convention (CLAUDE.md, DESIGN §4):
no ``raise`` inside jit/scan/Pallas kernel bodies under ``ops/`` and
``serving/online.py`` — failures there must be sentinels (−Inf loss, NaN
moments) plus a taxonomy code (robustness/taxonomy.py), never exceptions.

Mechanical rule (AST, not regex, so strings/comments can't fool it):

- a ``raise`` inside a NESTED function (a closure — scan bodies, jitted
  ``one``/``many`` builders, Pallas kernel bodies) is a violation: those run
  traced, where ``raise`` either fires spuriously at trace time or silently
  never fires at run time;
- a ``raise`` at the top level of a module-level function is allowed only
  for the trace-time validation classes (ValueError / TypeError /
  NotImplementedError / AttributeError) — shape/config checks that fire
  before tracing starts, the documented driver-layer exception.
"""

import ast
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "yieldfactormodels_jl_tpu")

#: trace-time validation exception classes (allowed in top-level functions)
WHITELIST = {"ValueError", "TypeError", "NotImplementedError",
             "AttributeError"}


def _kernel_files():
    opsdir = os.path.join(PKG, "ops")
    for name in sorted(os.listdir(opsdir)):
        if name.endswith(".py"):
            yield os.path.join(opsdir, name)
    yield os.path.join(PKG, "serving", "online.py")


def _func_depth(node, parents):
    """Number of enclosing FunctionDef/AsyncFunctionDef/Lambda scopes."""
    depth = 0
    p = parents.get(node)
    while p is not None:
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            depth += 1
        p = parents.get(p)
    return depth


def _raised_name(node):
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Name):
        return exc.id
    if isinstance(exc, ast.Attribute):
        return exc.attr
    return None  # bare `raise` / exotic expression


def test_no_raise_inside_kernel_bodies():
    violations = []
    for path in _kernel_files():
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        rel = os.path.relpath(path, ROOT)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Raise):
                continue
            depth = _func_depth(node, parents)
            name = _raised_name(node)
            if depth >= 2:
                violations.append(
                    f"{rel}:{node.lineno} raise inside a nested function "
                    f"(scan/kernel body) — use the −Inf/NaN sentinel + "
                    f"taxonomy code instead")
            elif name not in WHITELIST:
                violations.append(
                    f"{rel}:{node.lineno} raises {name or '<bare>'} — only "
                    f"trace-time validation ({sorted(WHITELIST)}) is allowed "
                    f"in kernel modules")
    assert not violations, "sentinel-convention violations:\n" + \
        "\n".join(violations)


def test_guard_is_not_vacuous():
    """The file walk must actually see the kernel modules it claims to guard
    (a rotted path would green-light everything)."""
    names = {os.path.basename(p) for p in _kernel_files()}
    assert {"univariate_kf.py", "sqrt_kf.py", "particle.py", "smoother.py",
            "online.py"} <= names
