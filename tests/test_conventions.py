"""Repo-wide mechanical-convention guards — thin wrappers over graftlint.

These five tests predate the `analysis/` lint engine; they keep their names
and their exact behavioral contracts (same file sets, same whitelists, same
failure messages' content) but delegate the AST walking, call-name
resolution and jit-context detection to the one shared implementation in
``yieldfactormodels_jl_tpu.analysis`` (docs/DESIGN.md §15).  The engine's
own positive/negative fixtures live in tests/test_lint_rules.py; the
repo-wide zero-findings gate in tests/test_lint.py.

1. Sentinel convention (CLAUDE.md, DESIGN §4) → rule YFM001: no ``raise``
   inside jit/scan/Pallas kernel bodies under ``ops/``,
   ``serving/online.py`` and ``estimation/scenario.py`` — failures there
   must be sentinels (−Inf loss, NaN moments) plus a taxonomy code; only
   trace-time validation classes may raise at the top of kernel-module
   functions.
2. Request-path backpressure convention (DESIGN §12) → rule YFM008: the
   serving request path holds work only in BOUNDED buffers and never blocks
   on a bare ``time.sleep``.  Chaos injection (orchestration/chaos.py) and
   test code live outside the scanned set by construction.
3. Engine-coverage convention (CLAUDE.md parity rule) → rule YFM007: every
   ``config.KALMAN_ENGINES`` entry is named in an oracle-importing test
   module — no engine ships selectable without oracle-backed parity.
"""

import os

from yieldfactormodels_jl_tpu.analysis import LintConfig, run_lint
from yieldfactormodels_jl_tpu.analysis.rules import (
    kalman_engines_static, oracle_backed_test_strings)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = LintConfig(root=ROOT)


def _kernel_files():
    return [rel for rel in CFG.lint_files() if CFG.is_kernel(rel)]


def _request_path_files():
    serv = CFG.serving_dir.rstrip("/") + "/"
    return [rel for rel in CFG.lint_files() if rel.startswith(serv)]


def _render(findings):
    return "\n".join(f"{f.file}:{f.line} {f.message}" for f in findings)


def test_no_raise_inside_kernel_bodies():
    """No raise reachable inside kernel/scan bodies; top-level raises in
    kernel modules restricted to trace-time validation classes (YFM001).
    Pragma-suppressed findings are honored — ONE suppression policy
    everywhere (DESIGN §15), so this guard and the CLI can never
    disagree; today the kernel set carries zero pragmas."""
    res = run_lint(CFG, files=_kernel_files(), rules=["YFM001"])
    assert not res.findings, \
        "sentinel-convention violations:\n" + _render(res.findings)


def test_guard_is_not_vacuous():
    """The file walk must actually see the kernel modules it claims to guard
    (a rotted path would green-light everything)."""
    names = {os.path.basename(p) for p in _kernel_files()}
    assert {"univariate_kf.py", "sqrt_kf.py", "particle.py", "smoother.py",
            "online.py", "scenario.py"} <= names


def test_request_path_bounded_queues_and_no_bare_sleep():
    """No unbounded ``queue.Queue()``, no bare ``time.sleep``, and no host
    gather (``jax.device_get``/``np.asarray``/``block_until_ready``) inside
    the per-request ROUTING functions (gateway ``pump()``/``_pump_locked``
    → ``_dispatch_updates``/``_submit_read`` → store ``_route_waves``)
    anywhere in the serving request path: depth bounds must be explicit,
    waits interruptible, and device values cross to host only at the
    response boundary (the collect/finish functions) — YFM008."""
    res = run_lint(CFG, files=_request_path_files(), rules=["YFM008"])
    assert not res.findings, \
        "request-path convention violations:\n" + _render(res.findings)


def test_request_path_guard_is_not_vacuous():
    names = {os.path.basename(p) for p in _request_path_files()}
    assert {"gateway.py", "batcher.py", "service.py", "online.py",
            "store.py"} <= names


def test_every_kalman_engine_has_oracle_parity_coverage():
    """Every engine name in ``config.KALMAN_ENGINES`` must appear as a
    string constant inside at least one oracle-importing test module — a new
    engine cannot ship selectable without an oracle-backed parity test
    naming it (YFM007; tests/test_assoc_estimation.py carries the canonical
    all-engines row and pins its literal list to the registry, so the
    string-level proxy here is anchored to a real parity test)."""
    res = run_lint(CFG, files=[], rules=["YFM007"])
    assert not res.findings, _render(res.findings)

    # non-vacuity: the statically-parsed registries match the live ones
    # (KALMAN_ENGINES plus the SLR linearization rules plus the
    # second-order NEWTON_ENGINES — one parity contract), the scan saw the
    # canonical coverage modules, and the Kalman registry is still the
    # five-engine set (or larger)
    engines, _ = kalman_engines_static(CFG)
    from yieldfactormodels_jl_tpu.config import (AMORTIZER_ENGINES,
                                                 KALMAN_ENGINES,
                                                 MSED_ENGINES,
                                                 NEWTON_ENGINES, SLR_ENGINES)
    assert tuple(engines) == tuple(KALMAN_ENGINES) + tuple(SLR_ENGINES) \
        + tuple(MSED_ENGINES) + tuple(NEWTON_ENGINES) \
        + tuple(AMORTIZER_ENGINES)
    assert len(KALMAN_ENGINES) >= 5
    assert len(SLR_ENGINES) >= 2       # "ekf" + the sigma-point "ukf" rule
    assert len(MSED_ENGINES) >= 2      # "scan" + the "score_tree" engine
    assert len(NEWTON_ENGINES) >= 2
    assert len(AMORTIZER_ENGINES) >= 1
    strings = oracle_backed_test_strings(CFG)
    assert "test_assoc_estimation.py" in strings, \
        "engine-coverage guard rotted: canonical parity module not scanned"
    assert "test_newton.py" in strings, \
        "engine-coverage guard rotted: second-order parity module not scanned"
    assert "test_slr_scan.py" in strings, \
        "engine-coverage guard rotted: SLR parity module not scanned"
    assert "test_score_scan.py" in strings, \
        "engine-coverage guard rotted: score-tree parity module not scanned"
    assert "test_amortize.py" in strings, \
        "engine-coverage guard rotted: amortizer parity module not scanned"
