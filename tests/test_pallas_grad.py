"""Differentiable Pallas Kalman kernel (hand-derived adjoint) vs jax.grad.

``ops/pallas_kf_grad.batched_loglik_diff`` implements the reverse pass of the
univariate Kalman recursion by hand (binomial checkpointing in VMEM).  These
tests run the kernel in interpret mode at float64 and require agreement with
``jax.grad`` of ``ops/univariate_kf.get_loss`` — the same algebra differentiated
by JAX — to near machine precision: value AND gradient, across model families,
estimation windows, NaN forecast tails, interior missing columns, and invalid
(non-finite-loglik) draws in the batch.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.ops import pallas_kf_grad, univariate_kf

# interpret-mode pallas executes the per-step python loop per timestep; keep
# shapes small so the suite stays fast (hardware agreement: bench.py)
MATS = tuple(np.array([3, 12, 36, 84, 180, 360]) / 12.0)


def _params(spec, B, rng):
    p = np.zeros((B, spec.n_params), dtype=np.float64)
    if "gamma" in spec.layout:
        lo, hi = spec.layout["gamma"]
        p[:, lo:hi] = np.log(0.4) + 0.2 * rng.standard_normal((B, hi - lo))
    lo, hi = spec.layout["obs_var"]
    p[:, lo:hi] = 0.01
    Ms = spec.state_dim
    k = spec.layout["chol"][0]
    for j in range(Ms):
        for i in range(j + 1):
            p[:, k] = (0.1 if i == j else 0.01) * (1 + 0.1 * rng.standard_normal())
            k += 1
    lo, hi = spec.layout["delta"]
    p[:, lo:hi] = 0.2 * rng.standard_normal((B, Ms))
    lo, hi = spec.layout["phi"]
    ph = 0.9 * np.eye(Ms)
    p[:, lo:hi] = ph.reshape(-1) + 0.01 * rng.standard_normal((B, Ms * Ms))
    return p


def _panel(rng, T, nan_tail=0, nan_interior=False):
    data = 0.5 * rng.standard_normal((len(MATS), T)) + 4.0
    if nan_tail:
        data[:, -nan_tail:] = np.nan
    if nan_interior:
        data[2, T // 3] = np.nan  # partial NaN -> whole column missing
    return data


def _ref_value_and_grad(spec, p, data, start, end):
    def total(pb):
        return jnp.sum(jax.vmap(
            lambda q: univariate_kf.get_loss(spec, q, data, start, end))(pb))

    vals = jax.vmap(lambda q: univariate_kf.get_loss(spec, q, data, start, end))(p)
    return vals, jax.grad(total)(p)


def _kernel_value_and_grad(spec, p, data, start, end):
    def total(pb):
        return jnp.sum(pallas_kf_grad.batched_loglik_diff(
            spec, pb, data, start, end, interpret=True, dtype=jnp.float64))

    vals = pallas_kf_grad.batched_loglik_diff(
        spec, p, data, start, end, interpret=True, dtype=jnp.float64)
    return vals, jax.grad(total)(p)


@pytest.mark.parametrize("code", ["1C", "AFNS3", "AFNS5", "TVλ"])
def test_value_and_grad_match_jax(code, rng):
    spec, _ = create_model(code, MATS, float_type="float64")
    B, T = 3, 18
    p = jnp.asarray(_params(spec, B, rng))
    data = _panel(rng, T, nan_tail=3, nan_interior=True)
    start, end = 2, T - 1

    ref_v, ref_g = _ref_value_and_grad(spec, p, data, start, end)
    got_v, got_g = _kernel_value_and_grad(spec, p, data, start, end)

    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                               rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(ref_g),
                               rtol=1e-6, atol=1e-7)


def test_full_window_default(rng):
    spec, _ = create_model("1C", MATS, float_type="float64")
    B, T = 2, 14
    p = jnp.asarray(_params(spec, B, rng))
    data = _panel(rng, T)
    ref_v, ref_g = _ref_value_and_grad(spec, p, data, 0, T)
    got_v, got_g = _kernel_value_and_grad(spec, p, data, 0, None)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                               rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(ref_g),
                               rtol=1e-6, atol=1e-7)


def test_checkpoint_segmentation_covers_odd_T(rng):
    """T not a multiple of the ~sqrt(T) segment length exercises the tail
    masking of the backward segment sweep."""
    spec, _ = create_model("1C", MATS, float_type="float64")
    for T in (7, 13):
        p = jnp.asarray(_params(spec, 2, rng))
        data = _panel(rng, T)
        ref_v, ref_g = _ref_value_and_grad(spec, p, data, 0, T)
        got_v, got_g = _kernel_value_and_grad(spec, p, data, 0, T)
        np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                                   rtol=1e-9, atol=1e-8)
        np.testing.assert_allclose(np.asarray(got_g), np.asarray(ref_g),
                                   rtol=1e-6, atol=1e-7)


def test_invalid_draw_is_gated_not_contaminating(rng):
    """A NaN-parameter draw gives ll=-inf; its lanes must not poison the
    finite draws' values or gradients (the backward gates its cotangent)."""
    spec, _ = create_model("1C", MATS, float_type="float64")
    B, T = 3, 12
    p = _params(spec, B, rng)
    data = _panel(rng, T)

    p_bad = p.copy()
    p_bad[1, :] = np.nan
    got_v, got_g = _kernel_value_and_grad(spec, jnp.asarray(p_bad), data, 0, T)
    ref_v, ref_g = _kernel_value_and_grad(
        spec, jnp.asarray(p[[0, 2]]), data, 0, T)

    got_v = np.asarray(got_v)
    assert got_v[1] == -np.inf
    np.testing.assert_allclose(got_v[[0, 2]], np.asarray(ref_v), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(got_g)[[0, 2]], np.asarray(ref_g),
                               rtol=1e-9, atol=1e-12)


def test_grad_through_transform_composition(rng):
    """Gradient wrt *unconstrained* params: the kernel's custom VJP composes
    with ordinary JAX AD of the bijector layer (the MLE objective shape)."""
    from yieldfactormodels_jl_tpu.models.params import transform_params

    spec, _ = create_model("AFNS3", MATS, float_type="float64")
    B, T = 3, 12
    p = _params(spec, B, rng)
    from yieldfactormodels_jl_tpu.models.params import untransform_params
    raw = jnp.asarray(np.stack(
        [np.asarray(untransform_params(spec, jnp.asarray(c))) for c in p]))
    data = _panel(rng, T)

    def obj_kernel(rb):
        cb = jax.vmap(lambda r: transform_params(spec, r))(rb)
        return jnp.sum(pallas_kf_grad.batched_loglik_diff(
            spec, cb, data, interpret=True, dtype=jnp.float64))

    def obj_ref(rb):
        cb = jax.vmap(lambda r: transform_params(spec, r))(rb)
        return jnp.sum(jax.vmap(
            lambda q: univariate_kf.get_loss(spec, q, data))(cb))

    np.testing.assert_allclose(np.asarray(jax.grad(obj_kernel)(raw)),
                               np.asarray(jax.grad(obj_ref)(raw)),
                               rtol=1e-6, atol=1e-7)


def test_unsupported_family_raises(rng):
    spec, _ = create_model("NS", MATS, float_type="float64")  # static family
    with pytest.raises(ValueError):
        pallas_kf_grad.batched_loglik_diff(
            spec, np.zeros((2, spec.n_params)), np.zeros((len(MATS), 10)),
            interpret=True)


def test_tvl_exact_jacobian_variant(rng):
    """The adjoint must follow the forward's dZ₂/dλ formula selection: with
    ``exact_jacobian=True`` the EKF linearization (and hence the loglik and
    its gradient) changes, and the jax.vjp-based adjoint tracks it because it
    differentiates the same build (pallas_kf.tvl_rows)."""
    import dataclasses
    spec, _ = create_model("TVλ", MATS, float_type="float64")
    spec_x = dataclasses.replace(spec, exact_jacobian=True)
    B, T = 2, 14
    p = jnp.asarray(_params(spec, B, rng))
    data = _panel(rng, T)
    ref_vq, ref_gq = _ref_value_and_grad(spec, p, data, 0, T)
    got_vq, got_gq = _kernel_value_and_grad(spec, p, data, 0, T)
    ref_vx, ref_gx = _ref_value_and_grad(spec_x, p, data, 0, T)
    got_vx, got_gx = _kernel_value_and_grad(spec_x, p, data, 0, T)
    for got, ref in ((got_vq, ref_vq), (got_gq, ref_gq),
                     (got_vx, ref_vx), (got_gx, ref_gx)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6, atol=1e-7)
    # and the two formulas genuinely differ (the quirk is not a no-op here)
    assert not np.allclose(np.asarray(got_vq), np.asarray(got_vx))


@pytest.mark.parametrize("code", ["1C", "TVλ"])
def test_per_lane_windows_match_per_row_reference(code, rng):
    """Each draw carries its own [start, end): values AND gradients must match
    running the univariate loss per row with that row's window — the fused
    rolling-window MLE path (one program for all origins)."""
    spec, _ = create_model(code, MATS, float_type="float64")
    B, T = 3, 16
    p = jnp.asarray(_params(spec, B, rng))
    data = _panel(rng, T)
    starts = jnp.asarray([0, 2, 5])
    ends = jnp.asarray([16, 12, 14])

    def ref_total(pb):
        return jnp.sum(jnp.stack([
            univariate_kf.get_loss(spec, pb[i], data, int(starts[i]), int(ends[i]))
            for i in range(B)]))

    def got_total(pb):
        return jnp.sum(pallas_kf_grad.batched_loglik_diff(
            spec, pb, data, interpret=True, dtype=jnp.float64,
            starts=starts, ends=ends))

    ref_v = jnp.stack([univariate_kf.get_loss(spec, p[i], data, int(starts[i]),
                                              int(ends[i])) for i in range(B)])
    got_v = pallas_kf_grad.batched_loglik_diff(
        spec, p, data, interpret=True, dtype=jnp.float64,
        starts=starts, ends=ends)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v),
                               rtol=1e-9, atol=1e-8)
    np.testing.assert_allclose(np.asarray(jax.grad(got_total)(p)),
                               np.asarray(jax.grad(ref_total)(p)),
                               rtol=1e-6, atol=1e-7)
