"""Pallas fused batched Kalman loglik vs the XLA univariate kernel.

Runs in interpret mode on CPU (the kernel compiles to Mosaic on real TPU;
bench.py cross-checks there).  Agreement target: same f32 arithmetic, only
accumulation-order differences.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.ops import pallas_kf, univariate_kf

MATS = tuple(np.array([3, 6, 9, 12, 24, 36, 48, 60, 84, 120, 180, 240, 360]) / 12.0)


def _params(spec, B, rng):
    p = np.zeros((B, spec.n_params), dtype=np.float32)
    if "gamma" in spec.layout:  # TVλ has no γ slot (λ is the 4th state)
        lo, hi = spec.layout["gamma"]
        p[:, lo:hi] = np.log(0.4) + 0.2 * rng.standard_normal((B, hi - lo))
    lo, hi = spec.layout["obs_var"]
    p[:, lo:hi] = 0.01
    Ms = spec.state_dim
    k = spec.layout["chol"][0]
    for j in range(Ms):
        for i in range(j + 1):
            p[:, k] = 0.1 if i == j else 0.01
            k += 1
    lo, hi = spec.layout["delta"]
    p[:, lo:hi] = 0.2 * rng.standard_normal((B, Ms))
    lo, hi = spec.layout["phi"]
    ph = 0.9 * np.eye(Ms)
    p[:, lo:hi] = ph.reshape(-1)
    return p


@pytest.mark.parametrize("code", ["1C", "AFNS3", "AFNS5", "TVλ"])
def test_matches_univariate(code, rng):
    spec, _ = create_model(code, MATS, float_type="float32")
    B, T = 6, 36
    p = _params(spec, B, rng)
    data = (0.5 * rng.standard_normal((len(MATS), T)) + 4).astype(np.float32)
    data[:, -3:] = np.nan          # forecast tail -> predict-only
    data[2, 10] = np.nan           # interior partial NaN -> column missing
    start, end = 2, T - 1
    ref = jax.vmap(lambda q: univariate_kf.get_loss(spec, q, data, start, end))(
        jnp.asarray(p))
    got = pallas_kf.batched_loglik(spec, p, data, start, end, interpret=True)
    assert got.shape == (B,)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=5e-4, atol=1e-2)


def test_full_window_default(rng):
    spec, _ = create_model("1C", MATS, float_type="float32")
    p = _params(spec, 3, rng)
    data = (0.5 * rng.standard_normal((len(MATS), 30)) + 4).astype(np.float32)
    ref = jax.vmap(lambda q: univariate_kf.get_loss(spec, q, data))(jnp.asarray(p))
    got = pallas_kf.batched_loglik(spec, p, data, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=5e-4, atol=1e-2)


def test_invalid_params_give_neg_inf(rng):
    spec, _ = create_model("1C", MATS, float_type="float32")
    p = _params(spec, 2, rng)
    p[1, :] = np.nan
    data = (0.5 * rng.standard_normal((len(MATS), 20)) + 4).astype(np.float32)
    got = np.asarray(pallas_kf.batched_loglik(spec, p, data, interpret=True))
    assert np.isfinite(got[0])
    assert got[1] == -np.inf


def test_unsupported_family_raises(rng):
    spec, _ = create_model("SD-NS", MATS, float_type="float32")
    with pytest.raises(ValueError):
        pallas_kf.batched_loglik(spec, np.zeros((2, spec.n_params)),
                                 np.zeros((len(MATS), 10)), interpret=True)


def test_per_lane_windows_match_univariate(rng):
    """Per-draw [start, end) windows (the fused rolling-window batch path)."""
    spec, _ = create_model("1C", MATS, float_type="float32")
    B, T = 4, 30
    p = _params(spec, B, rng)
    data = (0.5 * rng.standard_normal((len(MATS), T)) + 4).astype(np.float32)
    starts = np.array([0, 3, 5, 0])
    ends = np.array([30, 25, 28, 18])
    ref = jnp.stack([univariate_kf.get_loss(spec, jnp.asarray(p[i]), data,
                                            int(starts[i]), int(ends[i]))
                     for i in range(B)])
    got = pallas_kf.batched_loglik(spec, p, data, interpret=True,
                                   starts=starts, ends=ends)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=5e-4, atol=1e-2)


def test_tile_rows_variants_agree(rng):
    """tile_rows=16/32 (wider VPU tiles for dependency-chain pipelining) must
    be numerically identical to the default 8-row layout."""
    spec, _ = create_model("1C", MATS, float_type="float32")
    B, T = 5, 20
    p = _params(spec, B, rng)
    data = (0.5 * rng.standard_normal((len(MATS), T)) + 4).astype(np.float32)
    base = np.asarray(pallas_kf.batched_loglik(spec, p, data, interpret=True))
    for rows in (16, 32):
        got = np.asarray(pallas_kf.batched_loglik(spec, p, data,
                                                  interpret=True,
                                                  tile_rows=rows))
        np.testing.assert_allclose(got, base, rtol=1e-6)
    import pytest
    with pytest.raises(ValueError):
        pallas_kf.batched_loglik(spec, p, data, interpret=True, tile_rows=12)
