"""Fused scenario lattice (estimation/scenario.py, docs/DESIGN.md §14):
parity against the separate drivers it fuses, donation invariants
(bit-identical results, consumed buffers, no recompiles, no
buffer-not-donated warnings), degenerate/NaN-gapped configurations, the
8-virtual-device mesh entry, and the serving stress fan + donated online
update regressions."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests import oracle
from yieldfactormodels_jl_tpu import create_model, serving
from yieldfactormodels_jl_tpu.estimation import scenario as sc

MATS = (0.25, 0.5, 1.0, 2.0, 5.0, 10.0)
T = 40


@pytest.fixture
def panel(rng):
    return oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T)


@pytest.fixture
def ns_setup():
    spec, _ = create_model("NS", MATS, float_type="float64")
    return spec, oracle.stable_ns_params(spec, dtype=np.float64)


@pytest.fixture
def k_setup():
    spec, _ = create_model("1C", MATS, float_type="float64")
    return spec, oracle.stable_1c_params(spec, dtype=np.float64)


GRID = np.linspace(0.2, 0.9, 4)


def _donation_warnings(w):
    return [str(i.message) for i in w
            if "donated" in str(i.message).lower()]


# ---------------------------------------------------------------------------
# parity vs the separate drivers (ISSUE acceptance: same losses as
# bootstrap_lambda_grid, same PF logliks as estimate_sv's objective)
# ---------------------------------------------------------------------------

def test_lattice_matches_bootstrap_driver(panel, ns_setup):
    """The bootstrap face seeded with ``key`` reproduces
    ``bootstrap_lambda_grid(key=key)`` cell-for-cell: same index stream,
    same fused-engine dispatch, same CI/selection stats."""
    from yieldfactormodels_jl_tpu.estimation.bootstrap import (
        bootstrap_lambda_grid, moving_block_indices)

    spec, p = ns_setup
    key = jax.random.PRNGKey(11)
    out = sc.evaluate_lattice(panel, static_spec=spec, static_params=p,
                              lambda_grid=GRID, n_resamples=6, key=key)
    losses, lo, hi, freq = bootstrap_lambda_grid(spec, p, panel, GRID,
                                                 n_resamples=6, key=key)
    np.testing.assert_allclose(np.asarray(out["losses"]),
                               np.asarray(losses), rtol=1e-9)
    np.testing.assert_array_equal(
        np.asarray(out["resample_idx"]),
        np.asarray(moving_block_indices(key, T, 12, 6)))
    np.testing.assert_allclose(np.asarray(out["ci_low"]), np.asarray(lo),
                               rtol=1e-9)
    np.testing.assert_allclose(np.asarray(out["ci_high"]), np.asarray(hi),
                               rtol=1e-9)
    np.testing.assert_allclose(np.asarray(out["selection_freq"]),
                               np.asarray(freq), rtol=1e-12)


def test_lattice_pf_parity_with_sv_objective(panel, k_setup):
    """The SV-draw face returns exactly the common-random-numbers PF logliks
    ``estimate_sv``'s objective evaluates at those parameter points, in its
    streamed-noise CRN flavor: per-draw ``particle_filter_loglik`` on the
    SAME shared noise pair (``draw_noise`` at the documented face key) —
    float64, one noise realization across draws."""
    from yieldfactormodels_jl_tpu.estimation.sv import pf_draw_logliks
    from yieldfactormodels_jl_tpu.ops.particle import (draw_noise,
                                                      particle_filter_loglik)

    spec, p = k_setup
    rng = np.random.default_rng(7)
    draws = np.tile(p, (3, 1))
    draws[1:, spec.layout["delta"][0]] += 0.1 * rng.standard_normal(2)
    key = jax.random.PRNGKey(5)
    out = sc.evaluate_lattice(panel, kalman_spec=spec, kalman_params=p,
                              sv_draws=draws, n_particles=40, key=key)
    pf_key = sc.face_keys(key)[1]
    want_seam = np.asarray(pf_draw_logliks(spec, draws, panel, key=pf_key,
                                           n_particles=40))
    noise = draw_noise(T, 40, pf_key, jnp.float64)
    want_direct = np.asarray([
        particle_filter_loglik(spec, jnp.asarray(d), jnp.asarray(panel),
                               noise=noise, n_particles=40)
        for d in draws])
    got = np.asarray(out["pf_logliks"])
    np.testing.assert_allclose(got, want_seam, rtol=1e-12)
    np.testing.assert_allclose(got, want_direct, rtol=1e-9)
    assert np.isfinite(got).all()


def test_lattice_fan_matches_forecast_density(panel, k_setup):
    """The shock face's baseline cell equals ``api.forecast_density`` (same
    filter, same density recursion); shifted cells move the mean paths the
    way the shock says; the vol regime widens every predictive variance."""
    from yieldfactormodels_jl_tpu.ops.forecast import forecast_density

    spec, p = k_setup
    shocks = sc.standard_fan(spec, shift=0.5)
    out = sc.evaluate_lattice(panel, kalman_spec=spec, kalman_params=p,
                              shocks=shocks, horizon=5)
    fd = forecast_density(spec, p, panel, 5)
    fan = out["fan"]
    np.testing.assert_allclose(np.asarray(fan["means"])[0],
                               np.asarray(fd["means"]), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(fan["covs"])[0],
                               np.asarray(fd["covs"]), rtol=1e-10)
    means = np.asarray(fan["means"])
    assert (means[1] > means[0]).all() and (means[2] < means[0]).all()
    base_var = np.diagonal(np.asarray(fan["covs"])[0], axis1=-2, axis2=-1)
    vol_var = np.diagonal(np.asarray(fan["covs"])[5], axis1=-2, axis2=-1)
    assert (vol_var > base_var).all()
    # the filtered origin state is the forecast origin
    assert np.isfinite(np.asarray(out["state_beta"])).all()


def test_lattice_paths_calibrated_against_density(panel, k_setup):
    """Sampled baseline paths agree with the analytic density face in
    distribution (mean within MC error) — ties simulate(start_state=) to
    density_from_state through one program."""
    spec, p = k_setup
    out = sc.evaluate_lattice(panel, kalman_spec=spec, kalman_params=p,
                              shocks=(sc.ShockSpec("baseline"),),
                              horizon=4, n_paths=256,
                              key=jax.random.PRNGKey(2))
    paths = np.asarray(out["fan"]["paths"])[0]        # (N, h, n)
    means = np.asarray(out["fan"]["means"])[0]        # (h, N)
    sds = np.sqrt(np.diagonal(np.asarray(out["fan"]["covs"])[0],
                              axis1=-2, axis2=-1))    # (h, N)
    mc_err = 4.0 * sds / np.sqrt(paths.shape[-1])
    assert (np.abs(paths.mean(axis=-1).T - means) < mc_err + 1e-8).all()


# ---------------------------------------------------------------------------
# donation invariants
# ---------------------------------------------------------------------------

def test_lattice_donation_bit_identical_consumed_no_recompile(panel, ns_setup,
                                                              k_setup):
    """The §14 donation contract: donated and undonated programs agree
    bit-for-bit; explicitly passed device buffers (index sets, draw batch)
    and the recycled accumulator are CONSUMED; repeated recycled launches
    never retrace; and no 'donated buffers were not usable' warning fires
    anywhere on the lattice path."""
    nspec, pn = ns_setup
    kspec, pk = k_setup
    from yieldfactormodels_jl_tpu.estimation.bootstrap import \
        moving_block_indices

    key = jax.random.PRNGKey(4)
    draws_host = np.tile(pk, (3, 1))
    idx_host = np.asarray(moving_block_indices(key, T, 12, 5))
    kw = dict(static_spec=nspec, static_params=pn, lambda_grid=GRID,
              kalman_spec=kspec, kalman_params=pk, n_particles=30, key=key)

    plain = sc.evaluate_lattice(panel, resample_idx=idx_host,
                                sv_draws=draws_host, donate=False, **kw)
    sc.reset_trace_counts()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        idx_dev = jnp.asarray(idx_host)
        draws_dev = jnp.asarray(draws_host, dtype=kspec.dtype)
        out = sc.evaluate_lattice(panel, resample_idx=idx_dev,
                                  sv_draws=draws_dev, **kw)
        jax.block_until_ready(out)
        # donated device inputs are consumed; their values rode out as the
        # pass-through outputs
        assert idx_dev.is_deleted() and draws_dev.is_deleted()
        np.testing.assert_array_equal(np.asarray(out["resample_idx"]),
                                      idx_host)
        np.testing.assert_array_equal(np.asarray(out["sv_draws"]),
                                      draws_host)
        # bit-identical to the undonated program
        np.testing.assert_array_equal(np.asarray(out["losses"]),
                                      np.asarray(plain["losses"]))
        np.testing.assert_array_equal(np.asarray(out["pf_logliks"]),
                                      np.asarray(plain["pf_logliks"]))
        # recycled launches: buffers consumed, results identical, no retrace
        for _ in range(2):
            prev = out
            out = sc.evaluate_lattice(panel,
                                      resample_idx=prev["resample_idx"],
                                      sv_draws=prev["sv_draws"],
                                      recycle=prev, **kw)
            jax.block_until_ready(out)
            assert prev["losses"].is_deleted()
            assert prev["resample_idx"].is_deleted()
            np.testing.assert_array_equal(np.asarray(out["losses"]),
                                          np.asarray(plain["losses"]))
        assert not _donation_warnings(w)
    assert sc.trace_counts["lattice"] == 1, dict(sc.trace_counts)


def test_lattice_recycle_rejects_stale_buffers(panel, ns_setup):
    """A recycle dict whose buffers are consumed or shape-mismatched falls
    back to fresh buffers instead of crashing — a recycle is an optimization,
    never a correctness hazard."""
    spec, p = ns_setup
    key = jax.random.PRNGKey(9)
    out = sc.evaluate_lattice(panel, static_spec=spec, static_params=p,
                              lambda_grid=GRID, n_resamples=4, key=key)
    out2 = sc.evaluate_lattice(panel, static_spec=spec, static_params=p,
                               lambda_grid=GRID, n_resamples=4, key=key,
                               recycle=out)
    # out's accumulator was consumed by out2 — recycling OUT again must not
    # blow up on the dead buffer (falls back to a fresh accumulator)
    out3 = sc.evaluate_lattice(panel, static_spec=spec, static_params=p,
                               lambda_grid=GRID, n_resamples=4, key=key,
                               recycle=out)
    np.testing.assert_array_equal(np.asarray(out3["losses"]),
                                  np.asarray(out2["losses"]))
    # shape-mismatched recycle (different R) → fresh buffers
    out4 = sc.evaluate_lattice(panel, static_spec=spec, static_params=p,
                               lambda_grid=GRID, n_resamples=6, key=key,
                               recycle=out3)
    assert np.asarray(out4["losses"]).shape == (6, len(GRID))


def test_lattice_recycle_with_sentinel_cells_stays_exact(panel, ns_setup):
    """Recycling a loss plane that carries −Inf sentinel cells must not
    poison the next launch: the recycled accumulator zeroes through a
    finiteness mask (a plain ``acc * 0`` would turn −Inf into NaN scan
    carries and flush those cells to −Inf forever)."""
    spec, p = ns_setup
    bad = np.asarray(p, dtype=np.float64).copy()
    a, _ = spec.layout["delta"]
    bad[a] = 1e200  # overflowing level mean → every cell −Inf (sentinel)
    key = jax.random.PRNGKey(31)
    kw = dict(static_spec=spec, lambda_grid=GRID, n_resamples=4, key=key)
    poisoned = sc.evaluate_lattice(panel, static_params=bad, **kw)
    assert np.isneginf(np.asarray(poisoned["losses"])).all()
    fresh = sc.evaluate_lattice(panel, static_params=p, donate=False, **kw)
    recycled = sc.evaluate_lattice(panel, static_params=p, recycle=poisoned,
                                   **kw)
    np.testing.assert_array_equal(np.asarray(recycled["losses"]),
                                  np.asarray(fresh["losses"]))
    assert np.isfinite(np.asarray(recycled["losses"])).all()


# ---------------------------------------------------------------------------
# degenerate / gapped configurations
# ---------------------------------------------------------------------------

def test_degenerate_1x1x1_lattice(panel, ns_setup, k_setup):
    """R = G = D = S = 1, one path: the same program shape as the full sweep,
    every face present and finite."""
    nspec, pn = ns_setup
    kspec, pk = k_setup
    out = sc.evaluate_lattice(
        panel, static_spec=nspec, static_params=pn,
        lambda_grid=GRID[:1], n_resamples=1,
        kalman_spec=kspec, kalman_params=pk, sv_draws=pk[None, :],
        n_particles=20, shocks=(sc.ShockSpec("baseline"),), horizon=1,
        n_paths=1, key=jax.random.PRNGKey(0))
    assert np.asarray(out["losses"]).shape == (1, 1)
    assert np.asarray(out["pf_logliks"]).shape == (1,)
    assert np.asarray(out["fan"]["paths"]).shape == (1, len(MATS), 1, 1)
    assert np.isfinite(np.asarray(out["losses"])).all()
    assert np.isfinite(np.asarray(out["pf_logliks"])).all()
    assert np.isfinite(np.asarray(out["fan"]["paths"])).all()


def test_lattice_nan_gap_panel_takes_scan_engine(panel, ns_setup):
    """A NaN-gapped panel (whole missing columns — the offline convention)
    auto-dispatches the bootstrap face to the general scan engine and
    matches it exactly; the fused engine cannot be forced onto gaps."""
    from yieldfactormodels_jl_tpu.estimation.bootstrap import (
        _jitted_grid_loss, lambda_to_gamma, moving_block_indices)

    spec, p = ns_setup
    gapped = np.asarray(panel).copy()
    gapped[:, 7] = np.nan
    key = jax.random.PRNGKey(13)
    out = sc.evaluate_lattice(gapped, static_spec=spec, static_params=p,
                              lambda_grid=GRID, n_resamples=5, key=key)
    idx = moving_block_indices(key, T, 12, 5)
    want = _jitted_grid_loss(spec, T)(
        lambda_to_gamma(jnp.asarray(GRID)), idx, jnp.asarray(p),
        jnp.asarray(gapped))
    np.testing.assert_allclose(np.asarray(out["losses"]), np.asarray(want),
                               rtol=1e-12)
    with pytest.raises(ValueError, match="fully-observed"):
        sc.evaluate_lattice(gapped, static_spec=spec, static_params=p,
                            lambda_grid=GRID, n_resamples=5,
                            grid_engine="fused")


def test_lattice_validation_is_loud(panel, ns_setup, k_setup):
    nspec, pn = ns_setup
    kspec, pk = k_setup
    with pytest.raises(ValueError, match="empty lattice"):
        sc.evaluate_lattice(panel)
    with pytest.raises(ValueError, match="bootstrap face"):
        sc.evaluate_lattice(panel, lambda_grid=GRID)
    with pytest.raises(ValueError, match="n_resamples"):
        sc.evaluate_lattice(panel, static_spec=nspec, static_params=pn,
                            lambda_grid=GRID)
    with pytest.raises(ValueError, match="kalman_spec"):
        sc.evaluate_lattice(panel, sv_draws=pk[None, :])
    with pytest.raises(ValueError, match="Kalman family"):
        sc.evaluate_lattice(panel, kalman_spec=nspec, kalman_params=pn,
                            shocks=(sc.ShockSpec("baseline"),))
    with pytest.raises(ValueError, match="horizon"):
        sc.evaluate_lattice(panel, kalman_spec=kspec, kalman_params=pk,
                            shocks=(sc.ShockSpec("baseline"),), horizon=0)
    with pytest.raises(ValueError, match="factors"):
        sc.evaluate_lattice(panel, kalman_spec=kspec, kalman_params=pk,
                            shocks=(sc.ShockSpec("bad", (1.0,) * 9),))


def test_lattice_failed_filter_poisons_fan_not_losses(panel, ns_setup,
                                                      k_setup):
    """Sentinel discipline: invalid Kalman params NaN-poison the fan face
    while the bootstrap face's cells stay finite — faces fail independently,
    nothing raises inside the program."""
    nspec, pn = ns_setup
    kspec, pk = k_setup
    bad = np.asarray(pk, dtype=np.float64).copy()
    bad[kspec.layout["obs_var"][0]] = -1.0  # invalid variance → -Inf filter
    out = sc.evaluate_lattice(panel, static_spec=nspec, static_params=pn,
                              lambda_grid=GRID, n_resamples=4,
                              kalman_spec=kspec, kalman_params=bad,
                              shocks=(sc.ShockSpec("baseline"),), horizon=3)
    assert np.isfinite(np.asarray(out["losses"])).all()
    assert np.isnan(np.asarray(out["fan"]["means"])).all()
    assert np.isnan(np.asarray(out["state_beta"])).all()


# ---------------------------------------------------------------------------
# mesh-sharded entry (8 virtual devices, conftest)
# ---------------------------------------------------------------------------

def test_sharded_lattice_dry_run_matches_serial(panel, ns_setup, k_setup):
    """R = 13 and D = 5 (neither a multiple of 8) ride the mesh padded and
    trimmed; every face matches the serial lattice, stats are computed on
    trimmed losses only, and the donation path stays warning-free under
    sharding."""
    from yieldfactormodels_jl_tpu.parallel import mesh as pmesh

    nspec, pn = ns_setup
    kspec, pk = k_setup
    key = jax.random.PRNGKey(21)
    draws = np.tile(pk, (5, 1))
    serial = sc.evaluate_lattice(panel, static_spec=nspec, static_params=pn,
                                 lambda_grid=GRID, n_resamples=13,
                                 kalman_spec=kspec, kalman_params=pk,
                                 sv_draws=draws, n_particles=30,
                                 shocks=(sc.ShockSpec("baseline"),),
                                 horizon=3, key=key, donate=False)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        sharded = pmesh.scenario_lattice_sharded(
            panel, static_spec=nspec, static_params=pn, lambda_grid=GRID,
            n_resamples=13, kalman_spec=kspec, kalman_params=pk,
            sv_draws=draws, n_particles=30,
            shocks=(sc.ShockSpec("baseline"),), horizon=3, key=key)
        jax.block_until_ready(sharded)
        assert not _donation_warnings(w)
    np.testing.assert_allclose(np.asarray(sharded["losses"]),
                               np.asarray(serial["losses"]), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(sharded["ci_low"]),
                               np.asarray(serial["ci_low"]), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(sharded["pf_logliks"]),
                               np.asarray(serial["pf_logliks"]), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(sharded["fan"]["means"]),
                               np.asarray(serial["fan"]["means"]),
                               rtol=1e-10)
    assert np.asarray(sharded["losses"]).shape == (13, len(GRID))
    assert np.asarray(sharded["pf_logliks"]).shape == (5,)


def test_sharded_lattice_nan_gap_dry_run(panel, ns_setup):
    """NaN-gapped panel on the 8-device mesh: the scan engine runs sharded
    and matches the serial scan engine."""
    from yieldfactormodels_jl_tpu.parallel import mesh as pmesh

    spec, p = ns_setup
    gapped = np.asarray(panel).copy()
    gapped[:, 11] = np.nan
    key = jax.random.PRNGKey(23)
    serial = sc.evaluate_lattice(gapped, static_spec=spec, static_params=p,
                                 lambda_grid=GRID, n_resamples=5, key=key,
                                 donate=False)
    sharded = pmesh.scenario_lattice_sharded(
        gapped, static_spec=spec, static_params=p, lambda_grid=GRID,
        n_resamples=5, key=key)
    np.testing.assert_allclose(np.asarray(sharded["losses"]),
                               np.asarray(serial["losses"]), rtol=1e-12)


# ---------------------------------------------------------------------------
# mesh donation on the existing hot entries
# ---------------------------------------------------------------------------

def test_sharded_batch_loss_donation_bit_identical_no_recompile(panel):
    """parallel/mesh._sharded_batch_loss donates the params batch: repeated
    sweeps give bit-identical losses with ONE trace, and the public wrapper
    never exposes a consumed buffer (host batches in, fresh results out)."""
    from yieldfactormodels_jl_tpu.parallel import mesh as pmesh

    spec, _ = create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, dtype=np.float64)
    batch = np.tile(p, (16, 1))
    batch[:, spec.layout["delta"][0]] += np.linspace(0, 0.05, 16)
    pmesh.reset_trace_counts()
    first = np.asarray(pmesh.batch_loss_sharded(spec, batch, panel))
    second = np.asarray(pmesh.batch_loss_sharded(spec, batch, panel))
    np.testing.assert_array_equal(first, second)
    assert np.isfinite(first).all()
    assert pmesh.trace_counts["batch_loss"] == 1, dict(pmesh.trace_counts)
    # the donated program consumes the padded device batch it was handed
    # (placed with the program's sharding — a mismatched layout would be
    # resharded into a fresh buffer and THAT copy donated instead)
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = pmesh.make_mesh()
    fn = pmesh._sharded_batch_loss(spec, T, mesh, "batch")
    dev_batch = jax.device_put(jnp.asarray(batch, dtype=spec.dtype),
                               NamedSharding(mesh, P("batch", None)))
    lls, alias = fn(dev_batch, jnp.asarray(panel, dtype=spec.dtype),
                    jnp.asarray(0), jnp.asarray(T))
    jax.block_until_ready((lls, alias))
    assert dev_batch.is_deleted()
    np.testing.assert_array_equal(np.asarray(alias), batch)


def test_sharded_multistart_donation_bit_identical_no_recompile(panel):
    """parallel/mesh._sharded_multistart donates the start buffer (the
    converged xs reuse its memory): same results across repeated calls, one
    trace, improved losses."""
    from yieldfactormodels_jl_tpu.parallel import mesh as pmesh

    spec, _ = create_model("1C", MATS, float_type="float64")
    from yieldfactormodels_jl_tpu.models.params import untransform_params

    p = oracle.stable_1c_params(spec, dtype=np.float64)
    raw = np.asarray(untransform_params(spec, jnp.asarray(p)))
    starts = np.tile(raw, (8, 1))
    starts += 0.01 * np.random.default_rng(3).standard_normal(starts.shape)
    pmesh.reset_trace_counts()
    xs1, lls1 = pmesh.multistart_sharded(spec, starts, panel, max_iters=5)
    xs2, lls2 = pmesh.multistart_sharded(spec, starts, panel, max_iters=5)
    np.testing.assert_array_equal(np.asarray(xs1), np.asarray(xs2))
    np.testing.assert_array_equal(np.asarray(lls1), np.asarray(lls2))
    assert pmesh.trace_counts["multistart"] == 1, dict(pmesh.trace_counts)


# ---------------------------------------------------------------------------
# serving: donated O(1) updates + the one-launch stress fan
# ---------------------------------------------------------------------------

@pytest.fixture
def service_pair(panel, k_setup):
    spec, p = k_setup
    snap = serving.freeze_snapshot(spec, p, panel, end=30)
    return (serving.YieldCurveService(snap, donate=True),
            serving.YieldCurveService(snap, donate=False), panel)


def test_donated_online_update_bit_identical_no_recompile(service_pair):
    """ISSUE satellite: donation on serving/online.py's update state —
    donated and undonated services stay bit-identical through updates,
    catch-up batches, forecasts and failures, with one trace per program."""
    svc_d, svc_p, panel = service_pair
    from yieldfactormodels_jl_tpu.serving import online

    online.reset_trace_counts()
    for i in range(5):
        ll_d = svc_d.update(i, panel[:, 30 + i])
        ll_p = svc_p.update(i, panel[:, 30 + i])
        assert ll_d == ll_p  # bit-identical loglik
        np.testing.assert_array_equal(np.asarray(svc_d.snapshot.beta),
                                      np.asarray(svc_p.snapshot.beta))
        np.testing.assert_array_equal(np.asarray(svc_d.snapshot.P),
                                      np.asarray(svc_p.snapshot.P))
    # one trace per (donate, engine) program, stable across the 5 updates
    assert online.trace_counts["update"] == 2, dict(online.trace_counts)
    # catch-up path
    lls_d = svc_d.update_many("cat", panel[:, 35:38])
    lls_p = svc_p.update_many("cat", panel[:, 35:38])
    np.testing.assert_array_equal(lls_d, lls_p)
    # a rejected update keeps the state without a rebuild, donated or not
    # (negative obs_var → f < 0 → NaN sentinel in-kernel, like the
    # test_serving.py rollback regression)
    import dataclasses as _dc

    spec = svc_d.snapshot.spec
    bad = np.asarray(svc_d.snapshot.params, dtype=np.float64).copy()
    bad[spec.layout["obs_var"][0]] = -10.0
    for svc in (svc_d, svc_p):
        beta0 = np.asarray(svc.snapshot.beta).copy()
        svc.snapshot = _dc.replace(svc.snapshot, params=jnp.asarray(bad))
        with pytest.raises(serving.ServingError):
            svc.update("bad", panel[:, 38])
        assert svc.rebuilds == 0  # a rejection is NOT a rebuild
        np.testing.assert_array_equal(np.asarray(svc.snapshot.beta), beta0)
    np.testing.assert_array_equal(np.asarray(svc_d.snapshot.beta),
                                  np.asarray(svc_p.snapshot.beta))
    # both services keep serving after the rejection (params put back — the
    # donated flavor restored them with the banked snapshot already, the
    # plain flavor keeps whatever the operator poked in)
    good = np.asarray(svc_d._boot_snapshot.params)
    for svc in (svc_d, svc_p):
        svc.snapshot = _dc.replace(svc.snapshot, params=jnp.asarray(good))
        assert np.isfinite(svc.update("next", panel[:, 38]))


def test_service_stress_fan_is_one_program(service_pair):
    """`scenarios(shocks=...)` routes the whole fan through ONE fused fan
    program: per-shock densities + paths in a single launch, no retrace on
    repeat, baseline density identical to the forecast verb's."""
    svc, _, panel = service_pair
    sc.reset_trace_counts()
    out = svc.scenarios(8, 6, seed=3, shocks="standard")
    assert out["names"][0] == "baseline" and len(out["names"]) == 6
    assert out["paths"].shape == (6, len(MATS), 6, 8)
    assert out["means"].shape == (6, 6, len(MATS))
    assert np.isfinite(out["paths"]).all()
    out2 = svc.scenarios(8, 6, seed=3, shocks="standard")
    np.testing.assert_array_equal(out["paths"], out2["paths"])
    assert sc.trace_counts["fan"] == 1, dict(sc.trace_counts)
    # baseline density face == the forecast verb's density (same moments)
    fc = svc.forecast(6)
    np.testing.assert_allclose(out["means"][0], np.asarray(fc["means"]),
                               rtol=1e-10)
    # the documented density-only request shape: scenarios(shocks="standard")
    dens = svc.scenarios(shocks="standard")
    assert "paths" not in dens and dens["means"].shape[0] == 6
    with pytest.raises(serving.ServingError, match="unknown shock fan"):
        svc.stress_fan("bogus")
    with pytest.raises(serving.ServingError, match="sampled"):
        svc.scenarios()  # plain path needs an explicit draw count
