"""Mesh-resident serving state (serving/store.py, docs/DESIGN.md §16).

Acceptance coverage for the sharded-state tentpole:

- 8-virtual-device sharded-update parity against ``tests/oracle.
  online_filter`` (the f64 NumPy loop), including partially-quoted and
  whole-column-NaN curves, with the shard path pinned to the UNSHARDED
  ``serving/online`` update too (bit-level loglik, padding-invariant slot
  state);
- one compiled program per update bucket across a 1→2→4→8 mesh sweep at
  fixed shard capacity — zero retraces, zero donation warnings;
- the chaos-armed ``nonpsd_cov`` slot rebuild: corruption written into the
  resident slot is caught by the batched health watch and the slot is
  rewritten from the banked last-good WITHOUT gathering the shard;
- slot lifecycle (capacity, eviction, unknown keys), duplicate-key waves,
  the batch-last ``NamedSharding`` global view, and the sharded gateway's
  end-to-end routing incl. deadline-degraded last-good answers.
"""

import dataclasses
import warnings

import jax
import numpy as np
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import serving
from yieldfactormodels_jl_tpu.models.params import unpack_kalman
from yieldfactormodels_jl_tpu.orchestration import chaos
from yieldfactormodels_jl_tpu.parallel import mesh as pmesh
from yieldfactormodels_jl_tpu.robustness import health as rh
from yieldfactormodels_jl_tpu.robustness import loadgen
from yieldfactormodels_jl_tpu.robustness import taxonomy as tax
from yieldfactormodels_jl_tpu.serving import online as so

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)
T_PANEL = 48
T_ORIGIN = 40

LATTICE = dict(horizons=(4, 8), batch_sizes=(1, 4), scenario_counts=(4,),
               update_batch_sizes=(1, 4))


@pytest.fixture(scope="module")
def dns_setup():
    rng = np.random.default_rng(11)
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T_PANEL)
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    return spec, p, data, snap


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.reset()
    yield
    chaos.reset()


def _snap_for(snap, task_id):
    return dataclasses.replace(
        snap, meta=dataclasses.replace(snap.meta, task_id=task_id))


def _store(spec, snap, n_keys, mesh_size=8, shard_capacity=4, **kw):
    store = serving.ShardedStateStore(
        spec, mesh=pmesh.make_mesh(mesh_size), shard_capacity=shard_capacity,
        lattice=serving.BucketLattice(**LATTICE), **kw)
    keys = store.register_many(_snap_for(snap, i) for i in range(n_keys))
    return store, keys


def _oracle_final_state(spec, p, data, curves):
    """f64 NumPy element-masked filter over conditioning sample + curves."""
    kp = unpack_kalman(spec, np.asarray(p))
    Z = np.asarray(oracle.dns_loadings(float(np.asarray(kp.gamma)[0]),
                                       np.asarray(MATS)))
    panel = np.concatenate(
        [data[:, :T_ORIGIN], np.stack(curves, axis=1)], axis=1) \
        if curves else data[:, :T_ORIGIN]
    betas, Ps, _ = oracle.online_filter(
        Z, np.zeros(spec.N), np.asarray(kp.Phi), np.asarray(kp.delta),
        np.asarray(kp.Omega_state), float(kp.obs_var), panel)
    return betas[-1], Ps[-1]


# ---------------------------------------------------------------------------
# parity: sharded updates == oracle == unsharded serving path
# ---------------------------------------------------------------------------

def test_sharded_update_oracle_parity_8_devices(dns_setup):
    """Keys spread over all 8 shards ride shard-routed micro-batches through
    three rounds of live curves — one partially quoted, one whole-column-NaN
    (a pure transition step) — and every key's final state matches the f64
    NumPy oracle; logliks are bit-identical to the unsharded
    ``YieldCurveService`` update path."""
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 16)  # 16 keys on 8 shards
    assert store.n_shards == 8
    assert len({store.shard_of(k) for k in keys}) == 8

    curves = [data[:, T_ORIGIN].copy(), data[:, T_ORIGIN + 1].copy(),
              data[:, T_ORIGIN + 2].copy()]
    curves[1][2] = np.nan          # partially-quoted tenor
    curves.append(np.full(spec.N, np.nan))  # whole curve missing: predict only

    svc = serving.YieldCurveService(snap)
    svc_lls = [svc.update(t, y) for t, y in enumerate(curves)]

    for t, y in enumerate(curves):
        res = store.update_batch([(k, y) for k in keys], dates=[t] * 16)
        for r in res:
            assert r.get("error") is None and not r.get("degraded")
            # float64 roundoff only: the lanes batch the update's matvec
            # into a matmul, so states (and hence lls) agree to the last
            # few bits, not bit-for-bit — the bit-level pin lives in
            # test_sharded_update_padding_invariant_bit_level
            np.testing.assert_allclose(r["ll"], svc_lls[t], rtol=1e-12)
            assert r["version"] == t + 1

    b_ref, P_ref = _oracle_final_state(spec, p, data, curves)
    for k in keys:
        got = store.snapshot_of(k)
        np.testing.assert_allclose(np.asarray(got.beta), b_ref, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got.P), P_ref, atol=1e-6)
        assert got.meta.version == len(curves)
    # the unsharded path agrees to float64 roundoff (the lanes batch a
    # matvec into a matmul — everything else is the same filter_step)
    np.testing.assert_allclose(np.asarray(store.snapshot_of(keys[0]).beta),
                               np.asarray(svc.snapshot.beta), rtol=1e-12,
                               atol=1e-12)


def test_sharded_update_padding_invariant_bit_level(dns_setup):
    """Trimmed-row bit-exactness: a key updated alone (bucket-1 launch) and
    the same key riding a padded bucket-4 launch with three other keys end
    in BIT-IDENTICAL slot state — padding rows and lane neighbours cannot
    perturb a request's arithmetic."""
    spec, p, data, snap = dns_setup
    store_a, keys_a = _store(spec, snap, 4, mesh_size=1, shard_capacity=4)
    store_b, keys_b = _store(spec, snap, 4, mesh_size=1, shard_capacity=4)
    y = data[:, T_ORIGIN]
    ra = store_a.update_batch([(keys_a[0], y)])           # bucket 1
    rb = store_b.update_batch([(k, y) for k in keys_b])   # bucket 4
    np.testing.assert_array_equal(ra[0]["ll"], rb[0]["ll"])
    sa, sb = store_a.snapshot_of(keys_a[0]), store_b.snapshot_of(keys_b[0])
    np.testing.assert_array_equal(np.asarray(sa.beta), np.asarray(sb.beta))
    np.testing.assert_array_equal(np.asarray(sa.P), np.asarray(sb.P))


def test_sqrt_engine_store_matches_univariate(dns_setup):
    spec, p, data, snap = dns_setup
    store_u, keys_u = _store(spec, snap, 4, mesh_size=2, shard_capacity=2)
    store_s, keys_s = _store(spec, snap, 4, mesh_size=2, shard_capacity=2,
                             engine="sqrt")
    for t in range(3):
        y = data[:, T_ORIGIN + t]
        ru = store_u.update_batch([(k, y) for k in keys_u])
        rs = store_s.update_batch([(k, y) for k in keys_s])
        np.testing.assert_allclose(ru[0]["ll"], rs[0]["ll"], rtol=1e-9)
    np.testing.assert_allclose(
        np.asarray(store_s.snapshot_of(keys_s[1]).P),
        np.asarray(store_u.snapshot_of(keys_u[1]).P), atol=1e-8)


def test_duplicate_key_waves_match_sequential_updates(dns_setup):
    """Two updates for the SAME key in one batch commute through successive
    waves — equal to two sequential single-update batches."""
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 2, mesh_size=2, shard_capacity=2)
    y0, y1 = data[:, T_ORIGIN], data[:, T_ORIGIN + 1]
    res = store.update_batch([(keys[0], y0), (keys[0], y1)])
    assert [r["version"] for r in res] == [1, 2]

    store2, keys2 = _store(spec, snap, 2, mesh_size=2, shard_capacity=2)
    r0 = store2.update_batch([(keys2[0], y0)])
    r1 = store2.update_batch([(keys2[0], y1)])
    np.testing.assert_array_equal(res[0]["ll"], r0[0]["ll"])
    np.testing.assert_array_equal(res[1]["ll"], r1[0]["ll"])
    np.testing.assert_array_equal(
        np.asarray(store.snapshot_of(keys[0]).beta),
        np.asarray(store2.snapshot_of(keys2[0]).beta))


# ---------------------------------------------------------------------------
# one program per bucket across mesh sizes; donation stays warning-free
# ---------------------------------------------------------------------------

def test_no_recompile_across_mesh_sweep_1_2_4_8(dns_setup):
    """Fixed shard capacity → the (engine, capacity, bucket) program keys
    never mention mesh size: the whole 1→2→4→8 sweep compiles each update
    bucket ONCE, and the donated launches never warn about unusable donated
    buffers."""
    spec, p, data, snap = dns_setup
    cap = 6  # unique to this test: the lru cache must start cold
    so.reset_trace_counts()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for m in (1, 2, 4, 8):
            store = serving.ShardedStateStore(
                spec, mesh=pmesh.make_mesh(m), shard_capacity=cap,
                lattice=serving.BucketLattice(**LATTICE))
            keys = store.register_many(
                _snap_for(snap, i) for i in range(2 * m))
            r = store.update_batch([(k, data[:, T_ORIGIN]) for k in keys])
            assert all("error" not in x for x in r)
            r = store.update_batch([(keys[0], data[:, T_ORIGIN + 1])])
            assert np.isfinite(r[0]["ll"])
    assert so.trace_counts["store_update"] <= \
        serving.BucketLattice(**LATTICE).n_update_programs
    donation = [str(i.message) for i in w
                if "donat" in str(i.message).lower()]
    assert donation == []


def test_warmup_then_updates_are_trace_free(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 8)
    store.warmup()
    so.reset_trace_counts()
    for t in range(3):
        res = store.update_batch(
            [(k, data[:, T_ORIGIN + t]) for k in keys[t:t + 5]])
        assert all(np.isfinite(r["ll"]) for r in res)
    assert so.trace_counts["store_update"] == 0


# ---------------------------------------------------------------------------
# health watch, chaos rebuild, slot lifecycle
# ---------------------------------------------------------------------------

def test_chaos_nonpsd_cov_slot_rebuild(dns_setup):
    """A ``nonpsd_cov`` fault injected INTO the accepted resident slot is
    caught by the batched watch; the slot is rewritten from the banked
    last-good (pre-update) state and later updates continue from there —
    the oracle path that SKIPS the corrupted curve."""
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 8)
    k = keys[3]
    y0, y1, y2 = (data[:, T_ORIGIN + i] for i in range(3))
    assert np.isfinite(store.update_batch([(k, y0)])[0]["ll"])

    chaos.configure("nonpsd_cov:@1", seed=0)
    res = store.update_batch([(k, y1)])[0]
    assert res["degraded"] and res["stale"]
    assert "NONPSD_COV" in res["code"]
    assert store.rebuilds == 1
    assert store.health()["status"] == "stale"
    chaos.reset()

    # the rebuilt slot equals the banked pre-corruption state...
    got = store.snapshot_of(k)
    b_ref, P_ref = _oracle_final_state(spec, p, data, [y0])
    np.testing.assert_allclose(np.asarray(got.beta), b_ref, atol=1e-6)
    # ...and the next healthy update proceeds from it (y1 skipped)
    res2 = store.update_batch([(k, y2)])[0]
    assert np.isfinite(res2["ll"]) and not res2.get("degraded")
    assert store.health()["status"] == "ok"
    b_ref2, _ = _oracle_final_state(spec, p, data, [y0, y2])
    np.testing.assert_allclose(np.asarray(store.snapshot_of(k).beta),
                               b_ref2, atol=1e-6)
    # isolation: the other 7 keys never noticed
    for other in keys:
        if other != k:
            assert store.snapshot_of(other).meta.version == 0


def test_failed_update_keeps_state_in_program(dns_setup):
    """A slot whose innovation chain fails (NaN-poisoned covariance — e.g.
    an operator registering a broken snapshot) degrades ITS requests only:
    the kernel's accept mask keeps the resident state without any host
    restore, batch neighbours complete, and a kernel REJECT never counts as
    a rebuild.  (A non-finite curve is NOT a failure — its elements are
    masked as unquoted, the pure-transition case in the parity test.)"""
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 1, mesh_size=1, shard_capacity=2)
    bad = dataclasses.replace(
        _snap_for(snap, 55), P=np.full((spec.state_dim,) * 2, np.nan))
    kbad = store.register(bad)
    y_good = data[:, T_ORIGIN]
    res = store.update_batch([(kbad, y_good), (keys[0], y_good)])
    assert res[0]["degraded"] and np.isnan(res[0]["ll"])
    assert np.isfinite(res[1]["ll"])
    # the healthy neighbour's state is exactly the single-update state
    b_ref, _ = _oracle_final_state(spec, p, data, [y_good])
    np.testing.assert_allclose(np.asarray(store.snapshot_of(keys[0]).beta),
                               b_ref, atol=1e-6)
    assert store.rebuilds == 0  # reject ≠ rebuild: state was never touched


def test_slot_lifecycle_and_structural_errors(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 4, mesh_size=2, shard_capacity=2)
    # full: a fifth registration is a loud structural error
    with pytest.raises(serving.ServingError):
        store.register(_snap_for(snap, 99))
    # unknown key: per-request error result, batch unaffected
    res = store.update_batch([(("nope", 0), data[:, T_ORIGIN]),
                              (keys[0], data[:, T_ORIGIN])])
    assert "error" in res[0] and np.isfinite(res[1]["ll"])
    # wrong curve length: ditto
    res = store.update_batch([(keys[1], np.zeros(3))])
    assert "error" in res[0]
    # evict frees the slot for a new tenant and kills reads
    store.evict(keys[2])
    assert keys[2] not in store
    with pytest.raises(serving.ServingError):
        store.snapshot_of(keys[2])
    newkey = store.register(_snap_for(snap, 77))
    assert store.shard_of(newkey) in (0, 1)
    assert len(store) == 4


def test_global_view_is_batch_last_namedsharding(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 8, mesh_size=4, shard_capacity=2)
    gv = store.global_view()
    Ms = spec.state_dim
    assert gv["beta"].shape == (Ms, 8)
    assert gv["cov"].shape == (Ms, Ms, 8)
    spec_parts = gv["beta"].sharding.spec
    assert tuple(spec_parts) == (None, "batch")
    # values round-trip: every key's slot matches its snapshot view
    beta_g = np.asarray(gv["beta"])
    for k in keys:
        s, sl = store._slot[k]
        np.testing.assert_array_equal(
            beta_g[:, s * store.shard_capacity + sl],
            np.asarray(store.snapshot_of(k).beta))


def test_state_health_batch_matches_scalar_watch():
    rng = np.random.default_rng(0)
    Ms, B = 3, 6
    betas = rng.standard_normal((Ms, B))
    covs = np.stack([np.eye(Ms)] * B, axis=-1) * 0.5
    covs[:, :, 2] -= 2.0 * np.eye(Ms)[:, :, None][:, :, 0]  # non-PSD
    betas[0, 4] = np.nan                                     # NaN state
    codes = rh.state_health_batch(betas, covs, "univariate")
    for j in range(B):
        ref = rh.state_health(betas[:, j], covs[:, :, j], "univariate")
        assert int(codes[j]) == ref["code"]
    assert int(codes[2]) == tax.NONPSD_COV
    assert int(codes[4]) == tax.NAN_STATE


# ---------------------------------------------------------------------------
# the sharded gateway: routing, reads, degraded answers, ledger
# ---------------------------------------------------------------------------

def test_sharded_gateway_end_to_end(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 8)
    gw = serving.ShardedGateway(store, queue_max=64, queue_age_ms=0.0)
    t_u = gw.submit_update(0, data[:, T_ORIGIN], key=keys[0])
    t_f = gw.submit_forecast(4, quantiles=(0.1, 0.9), key=keys[0])
    t_s = gw.submit_scenarios(4, 4, seed=3, key=keys[1])
    assert gw.pump() == 3
    r_u, r_f, r_s = gw.poll(t_u), gw.poll(t_f), gw.poll(t_s)
    assert np.isfinite(r_u["ll"]) and not r_u["stale"]
    assert r_f["means"].shape == (4, spec.N) and 0.1 in r_f["quantiles"]
    assert r_s["paths"].shape == (spec.N, 4, 4)
    c = store.counters.to_dict()
    assert c["admitted"] == 3 and c["completed"] == 3 and c["errors"] == 0
    assert store.health()["requests"] == c
    # the forecast equals a single-service forecast from the same state
    svc = serving.YieldCurveService(snap,
                                    lattice=serving.BucketLattice(**LATTICE))
    svc.update(0, data[:, T_ORIGIN])
    np.testing.assert_allclose(r_f["means"], svc.forecast(4)["means"],
                               rtol=1e-10)
    # a key missing is an admission-layer structural error
    with pytest.raises(serving.ServingError):
        gw.submit_update(0, data[:, T_ORIGIN])


def test_sharded_gateway_deadline_answers_from_keys_last_good(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 4, mesh_size=2, shard_capacity=2)
    store.update_batch([(keys[1], data[:, T_ORIGIN])])
    fake = [0.0]
    gw = serving.ShardedGateway(store, queue_max=16, queue_age_ms=0.0,
                                clock=lambda: fake[0])
    t = gw.submit_forecast(4, key=keys[1], deadline_ms=5.0)
    fake[0] = 1.0  # the deadline expired before the pump
    gw.pump()
    out = gw.poll(t)
    assert out["degraded"] and out["stale"] and out["key"] == keys[1]
    bank_b, bank_c = store._bank[keys[1]]
    np.testing.assert_array_equal(out["beta"], bank_b)
    np.testing.assert_array_equal(out["P"], bank_c)
    assert store.counters.deadline == 1 and store.counters.degraded == 1


def test_degraded_answer_for_missing_key_is_error_not_crash(dns_setup):
    """A deadline-expired request whose key was evicted between admission
    and the pump must NOT raise out of pump() (that would strand the
    batch's tickets and kill the worker thread) — its ticket banks the
    structured error instead."""
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 2, mesh_size=1, shard_capacity=2)
    fake = [0.0]
    gw = serving.ShardedGateway(store, queue_max=16, queue_age_ms=0.0,
                                clock=lambda: fake[0])
    t_doomed = gw.submit_forecast(4, key=keys[0], deadline_ms=5.0)
    t_ok = gw.submit_update(0, data[:, T_ORIGIN], key=keys[1])
    store.evict(keys[0])
    fake[0] = 1.0  # the deadline expired before the pump
    assert gw.pump() == 2  # never raises: worker-isolation contract
    with pytest.raises(serving.ServingError):
        gw.poll(t_doomed)
    assert np.isfinite(gw.poll(t_ok)["ll"])
    assert store.counters.errors == 1


def test_register_many_partial_failure_leaves_store_unchanged(dns_setup):
    """Bulk boot is all-or-nothing: a non-PSD snapshot mid-list must leave
    NO half-registered tables behind (a partial boot would alias later
    tenants onto zero-state slots)."""
    spec, p, data, snap = dns_setup
    store = serving.ShardedStateStore(
        spec, mesh=pmesh.make_mesh(2), shard_capacity=2,
        lattice=serving.BucketLattice(**LATTICE), engine="sqrt")
    bad = dataclasses.replace(
        _snap_for(snap, 1), P=-np.eye(spec.state_dim))  # non-PSD under sqrt
    with pytest.raises(serving.ServingError):
        store.register_many([_snap_for(snap, 0), bad])
    assert len(store) == 0 and store.keys() == []
    # duplicate keys are rejected up front too
    with pytest.raises(serving.ServingError):
        store.register_many([_snap_for(snap, 0), _snap_for(snap, 0)])
    assert len(store) == 0
    # and a clean list still boots
    keys = store.register_many([_snap_for(snap, i) for i in range(3)])
    assert len(store) == 3 and len(keys) == 3
    assert np.isfinite(store.update_batch(
        [(keys[2], data[:, T_ORIGIN])])[0]["ll"])


def test_register_many_batched_matches_sequential(dns_setup):
    """Bulk boot through the batched slot-write waves leaves every state
    bit-identical to one-at-a-time ``register()`` — the batching is a
    dispatch-count optimization, never a numeric one — and updates on both
    stores stay bit-equal afterwards."""
    spec, p, data, snap = dns_setup
    a, keys_a = _store(spec, snap, 6)
    b = serving.ShardedStateStore(
        spec, mesh=pmesh.make_mesh(8), shard_capacity=4,
        lattice=serving.BucketLattice(**LATTICE))
    keys_b = [b.register(_snap_for(snap, i)) for i in range(6)]
    assert keys_a == keys_b
    for k in keys_a:
        sa, sb = a.snapshot_of(k), b.snapshot_of(k)
        np.testing.assert_array_equal(np.asarray(sa.beta), np.asarray(sb.beta))
        np.testing.assert_array_equal(np.asarray(sa.P), np.asarray(sb.P))
        np.testing.assert_array_equal(np.asarray(sa.params),
                                      np.asarray(sb.params))
    y = data[:, T_ORIGIN]
    ra = a.update_batch([(k, y) for k in keys_a])
    rb = b.update_batch([(k, y) for k in keys_b])
    np.testing.assert_array_equal([r["ll"] for r in ra],
                                  [r["ll"] for r in rb])


def test_mesh_scaling_ledger_record(dns_setup):
    """The loadgen mesh dimension: a tiny 1→2 sweep produces the scaling
    ledger record (real numbers land in BASELINE.md via BENCH_LOAD; here we
    pin the record's shape and that both meshes actually serve)."""
    spec, p, data, snap = dns_setup

    def factory(m):
        store, keys = _store(spec, snap, 4 * m, mesh_size=m,
                             shard_capacity=4)
        store.warmup()
        gw = serving.ShardedGateway(store, queue_max=256, queue_age_ms=0.0)
        return gw, keys

    out = loadgen.mesh_scaling(factory, data[:, :T_ORIGIN],
                               mesh_sizes=(1, 2), n=24, burst=8)
    assert out["mesh_sizes"] == [1, 2]
    assert len(out["capacity_qps"]) == 2
    assert all(c > 0 for c in out["capacity_qps"])
    assert np.isfinite(out["scaling"])
