"""graftlint tier 2 (the IR program audit, docs/DESIGN.md §18).

Two layers of coverage:

- unit fixtures drive each artifact check (``ir._audit_case``) on tiny
  synthetic programs — the dropped-donation regression the acceptance
  criteria name, dtype down-casts, host callbacks, the lane heuristic, the
  retrace census — both the fire and the quiet direction;
- the CI gate runs the real ``--ir`` CLI in a CPU subprocess
  (``JAX_PLATFORMS=cpu``, 8 virtual devices — the CLAUDE.md TPU access
  rules) and requires ZERO unsuppressed findings across every registered
  engine-cache builder, with the flagship donated entries' aliases verified
  in the lowered artifacts.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp

from yieldfactormodels_jl_tpu.analysis import ir as ir_mod
from yieldfactormodels_jl_tpu.analysis.manifest import MANIFEST, Case

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _case(donated=0, max_programs=1, label="t"):
    return Case("tests.synthetic", label, None, donated, max_programs)


def _rules(problems):
    return [rule for rule, _ in problems]


def _audit(case, jitted, arg_sets):
    problems, record = ir_mod._audit_case(case, jitted, arg_sets)
    return problems, record


F64 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.float64)  # noqa: E731


# ---------------------------------------------------------------------------
# YFM101 — donation honored vs silently dropped
# ---------------------------------------------------------------------------

def test_dropped_donation_fires():
    """THE regression fixture: a donated argument whose value never reaches
    a shape-matched output lowers with no input_output alias — source-level
    YFM002 would pass a subtler variant of this, only the artifact check
    catches the drop."""
    fn = jax.jit(lambda a, b: b * 2.0, donate_argnums=(0,))
    problems, record = _audit(_case(donated=1), fn, [(F64(4), F64(4))])
    assert _rules(problems) == ["YFM101"]
    assert record["aliases"] == 0
    assert "dropped the donation" in problems[0][1]


def test_honored_donation_quiet():
    fn = jax.jit(lambda a, b: (a + b, a * 2.0), donate_argnums=(0,))
    problems, record = _audit(_case(donated=1), fn, [(F64(4), F64(4))])
    assert not problems
    assert record["aliases"] == 1


def test_shape_mismatched_donation_fires():
    # the value flows to an output, but reshaped — no output aval matches
    # the donated buffer, so XLA cannot alias it (this is the shape YFM002's
    # reachability analysis wrongly passes: the value reaches a return)
    import warnings

    fn = jax.jit(lambda a, b: a.reshape(2, 2) + b.reshape(2, 2),
                 donate_argnums=(0,))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # jax's own donation warning
        problems, _ = _audit(_case(donated=1), fn, [(F64(4), F64(4))])
    assert "YFM101" in _rules(problems)


# ---------------------------------------------------------------------------
# YFM102 — dtype discipline
# ---------------------------------------------------------------------------

def test_f32_downcast_inside_f64_program_fires():
    fn = jax.jit(lambda a: a.astype(jnp.float32).astype(jnp.float64).sum())
    problems, _ = _audit(_case(), fn, [(F64(4),)])
    assert "YFM102" in _rules(problems)


def test_pure_f64_program_quiet():
    fn = jax.jit(lambda a: jnp.linalg.cholesky(a @ a.T
                                               + jnp.eye(3)).sum())
    problems, _ = _audit(_case(), fn, [(F64(3, 3),)])
    assert not problems


# ---------------------------------------------------------------------------
# YFM103 — host round-trips
# ---------------------------------------------------------------------------

def test_host_callback_fires():
    import numpy as np

    def with_cb(x):
        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct((4,), jnp.float64),
            x)

    problems, _ = _audit(_case(), jax.jit(with_cb), [(F64(4),)])
    assert "YFM103" in _rules(problems)


# ---------------------------------------------------------------------------
# YFM104 — lane rule (unbatched dot_general heuristic)
# ---------------------------------------------------------------------------

def test_lane_rule_fires_on_big_leading_free_axis():
    # (1024, 4) @ (4, 4): the big axis rides dim 0, the trailing lane dim
    # is 4 — the transposed formulation the lane convention forbids
    fn = jax.jit(lambda A, B: A @ B)
    problems, _ = _audit(_case(), fn, [(F64(1024, 4), F64(4, 4))])
    assert "YFM104" in _rules(problems)


def test_lane_rule_quiet_on_batch_last_formulation():
    fn = jax.jit(lambda A, B: A @ B)   # (4, 4) @ (4, 1024): batch last
    problems, _ = _audit(_case(), fn, [(F64(4, 4), F64(4, 1024))])
    assert not problems


def test_lane_rule_skips_vmap_batched_dots():
    # vmap hoists the batch axis into dot_general BATCH dims (and, for
    # scatter, to the operand front) — XLA owns that layout, no finding
    fn = jax.jit(jax.vmap(lambda a, b: a @ b, in_axes=(-1, -1),
                          out_axes=-1))
    problems, _ = _audit(_case(), fn, [(F64(4, 4, 1024), F64(4, 4, 1024))])
    assert not problems


# ---------------------------------------------------------------------------
# YFM105 — retrace census
# ---------------------------------------------------------------------------

def test_retrace_census_fires_on_staging_mismatch():
    fn = jax.jit(lambda a: a * 2)
    problems, record = _audit(
        _case(max_programs=1), fn,
        [(F64(4),), (jax.ShapeDtypeStruct((4,), jnp.float32),)])
    assert "YFM105" in _rules(problems)
    assert record["programs"] == 2


def test_retrace_census_quiet_on_identical_staging():
    fn = jax.jit(lambda a: a * 2)
    problems, record = _audit(_case(max_programs=1), fn,
                              [(F64(4),), (F64(4),)])
    assert not problems
    assert record["programs"] == 1


# ---------------------------------------------------------------------------
# finding anchors: the builder's def line, where the documented pragma goes
# ---------------------------------------------------------------------------

def test_builder_site_anchors_at_def_line_and_pragma_applies(tmp_path):
    """``inspect.getsourcelines`` starts at the first DECORATOR; the finding
    must anchor at the ``def`` line — the line CLAUDE.md tells the
    maintainer to pragma, the line ``suppression_for`` reads, and the line
    the AST-side YFM011 rule uses (so the tiers' baseline keys agree)."""
    import importlib.util
    import textwrap

    from yieldfactormodels_jl_tpu.analysis.engine import (Finding, LintConfig,
                                                          SourceModule)

    mod = tmp_path / "m.py"
    mod.write_text(textwrap.dedent("""\
        def deco(fn):
            return fn

        @deco
        @deco
        # yfmlint: disable=YFM104 -- fixture: deliberate layout
        def builder():
            return 1
    """))
    spec = importlib.util.spec_from_file_location("m_anchor_fixture", mod)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    cfg = LintConfig(root=str(tmp_path))
    rel, line = ir_mod._builder_site(cfg, m.builder)
    assert rel == "m.py"
    assert line == 7  # the def, not the decorator block's first line

    src = SourceModule(str(mod), rel)
    reason = src.suppression_for(Finding("YFM104", rel, line, 0, "x"))
    assert reason == "fixture: deliberate layout"


# ---------------------------------------------------------------------------
# run_ir: flagship donations + runtime census
# ---------------------------------------------------------------------------

FLAGSHIPS = {
    "estimation.scenario._jitted_lattice": 3,   # idx, sv_draws, acc
    "serving.online._jitted_shard_update": 4,   # params, β, cov, ver
    "parallel.mesh._sharded_multistart": 1,     # x0 → xs
}


def test_flagship_donated_entries_alias_in_lowered_artifact():
    """Acceptance: the lattice, the shard update and the sharded multistart
    must lower with every declared donation ALIASED (not just reachable)."""
    res = ir_mod.run_ir(only=sorted(FLAGSHIPS))
    assert not res.lint.findings, [f.message for f in res.lint.findings]
    assert not res.lint.errors, res.lint.errors
    by_builder = {}
    for r in res.records:
        by_builder.setdefault(r["builder"], []).append(r)
    for builder, want in FLAGSHIPS.items():
        recs = by_builder[builder]
        assert recs, f"{builder} not audited"
        for r in recs:
            assert r["status"] == "ok", r
            assert r["aliases"] >= want, r


def test_runtime_census_fires_on_unmanifested_builder(monkeypatch):
    key = "estimation.optimize._jitted_loss"
    pruned = {k: v for k, v in MANIFEST.items() if k != key}
    monkeypatch.setattr(ir_mod, "_import_package_modules",
                        lambda config: [])
    import yieldfactormodels_jl_tpu.estimation.optimize  # registers builders

    monkeypatch.setattr("yieldfactormodels_jl_tpu.analysis.manifest.MANIFEST",
                        pruned)
    res = ir_mod.run_ir(only=[key])
    assert [f.rule for f in res.lint.findings] == ["YFM011"]
    assert key in res.lint.findings[0].message


def test_runtime_census_fires_on_stale_manifest_key(monkeypatch):
    key = "estimation.optimize._no_such_builder"
    padded = dict(MANIFEST)
    padded[key] = [Case(key, "skip", None, skip="stale")]
    monkeypatch.setattr(ir_mod, "_import_package_modules",
                        lambda config: [])
    monkeypatch.setattr("yieldfactormodels_jl_tpu.analysis.manifest.MANIFEST",
                        padded)
    res = ir_mod.run_ir(only=[key])
    assert [f.rule for f in res.lint.findings] == ["YFM011"]
    assert "manifest" in res.lint.findings[0].message


def test_program_census_fires_on_missing_program_cases(monkeypatch):
    """Full-audit census (only=None): a registered program whose
    auto-generated `program:<name>` cases are missing from the manifest is
    a YFM011 finding per audited builder — coverage drift, both shipped
    programs reported."""
    import yieldfactormodels_jl_tpu.program  # noqa: F401 — registers library

    # register FIRST, then blank the manifest: the auto-generated cases land
    # in the real MANIFEST, and the census sees programs with no cases
    monkeypatch.setattr(ir_mod, "_import_package_modules",
                        lambda config: [])
    monkeypatch.setattr("yieldfactormodels_jl_tpu.config.engine_cache_entries",
                        lambda: [])
    monkeypatch.setattr("yieldfactormodels_jl_tpu.analysis.manifest.MANIFEST",
                        {})
    res = ir_mod.run_ir()
    assert res.lint.findings and all(
        f.rule == "YFM011" for f in res.lint.findings)
    msgs = " ".join(f.message for f in res.lint.findings)
    assert "prog-dns" in msgs and "svensson4" in msgs


def test_program_census_fires_on_stale_program_label(monkeypatch):
    """The reverse direction: a `program:<name>` manifest label naming no
    registered program is a census finding, not silent dead coverage."""
    key = "estimation.optimize._jitted_loss"
    import yieldfactormodels_jl_tpu.estimation.optimize  # noqa: F401
    import yieldfactormodels_jl_tpu.program  # noqa: F401 — library must be
    # imported BEFORE _PROGRAMS is blanked, or the census's own import
    # re-registers the shipped programs into the patched registry
    from yieldfactormodels_jl_tpu import config as pkg_config

    entries = dict(pkg_config.engine_cache_entries())
    monkeypatch.setattr(ir_mod, "_import_package_modules",
                        lambda config: [])
    monkeypatch.setattr("yieldfactormodels_jl_tpu.config.engine_cache_entries",
                        lambda: [(key, entries[key])])
    monkeypatch.setattr(
        "yieldfactormodels_jl_tpu.analysis.manifest.MANIFEST",
        {key: [Case(key, "program:ghost", None, skip="census fixture")]})
    monkeypatch.setattr("yieldfactormodels_jl_tpu.program.registry._PROGRAMS",
                        {})
    res = ir_mod.run_ir()
    assert [f.rule for f in res.lint.findings] == ["YFM011"]
    assert "program:ghost" in res.lint.findings[0].message


# ---------------------------------------------------------------------------
# the CI gate: full --ir run, zero unsuppressed findings
# ---------------------------------------------------------------------------

def test_ir_cli_full_audit_zero_findings():
    """Every ``@register_engine_cache`` builder audits clean at the manifest
    shapes (skips carry reasons; the AST-side YFM011 + the runtime census
    guarantee nothing is silently uncovered)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "yieldfactormodels_jl_tpu.analysis", "--ir",
         "--format", "json"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["tier"] == "ir"
    assert data["counts"]["findings"] == 0
    assert not data["errors"]
    # every non-skip record lowered clean, and coverage is the whole registry
    records = data["records"]
    assert len(records) >= 40
    skipped = [r for r in records if r["status"] == "skip"]
    assert all(r["reason"] for r in skipped)
    assert all(r["status"] in ("ok", "skip") for r in records), [
        r for r in records if r["status"] not in ("ok", "skip")]
