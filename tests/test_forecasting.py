"""Forecast driver + persistence integration tests (tmp dirs, small models)."""

import os
import sqlite3

import numpy as np

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.forecasting import (
    run_forecast_no_window_database,
    run_forecast_window_batched,
    run_forecast_window_database,
    run_rolling_forecasts,
)
from yieldfactormodels_jl_tpu.persistence import database as db
from yieldfactormodels_jl_tpu.persistence.locks import acquire_task_lock, release_task_lock

MATS = tuple(np.array([3.0, 12.0, 24.0, 60.0, 120.0, 360.0]) / 12.0)


def _spec(tmp_path, code="RW"):
    spec, _ = create_model(code, MATS, float_type="float64",
                           results_location=str(tmp_path) + os.sep)
    return spec


def _panel(T=40):
    rng = np.random.default_rng(5)
    return np.cumsum(rng.standard_normal((len(MATS), T)) * 0.1, axis=1) + 5.0


def test_locks_are_atomic(tmp_path):
    root = str(tmp_path / "locks")
    l1 = acquire_task_lock(root, "expanding", 7)
    assert l1 is not None
    assert acquire_task_lock(root, "expanding", 7) is None
    release_task_lock(l1)
    assert acquire_task_lock(root, "expanding", 7) is not None


def test_shard_save_merge_export_roundtrip(tmp_path):
    spec = _spec(tmp_path)
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    H = 3
    results = {
        "preds": np.arange(len(MATS) * 10, dtype=float).reshape(len(MATS), 10),
        "factors": np.ones((3, 10)),
        "states": np.zeros((1, 10)),
        "factor_loadings_1": np.ones((len(MATS), 10)),
        "factor_loadings_2": np.ones((len(MATS), 10)),
    }
    params = np.array([1.0, 2.0, 3.0])
    for task in (30, 31, 32):
        p = db.save_oos_forecast_sharded(base, spec.model_string, "1", "expanding",
                                         task, results, -0.5, params, forecast_horizon=H)
        assert os.path.isfile(p)
    out = db.merge_forecast_shards(base, task_ids=[30, 31, 32], delete_shards=True)
    assert out.endswith("_merged.sqlite3")
    conn = sqlite3.connect(out)
    n = conn.execute("SELECT COUNT(*) FROM forecasts").fetchone()[0]
    conn.close()
    assert n == 3
    # round-trip params through the blob format
    got = db.read_task_params(out, 31)
    np.testing.assert_allclose(got, params)
    csvs = db.export_all_csv(spec, "1", [30, 31, 32], window_type="expanding")
    fc = np.loadtxt(csvs["forecasts"], delimiter=",")
    assert fc.shape == (3 * H, 2 + len(MATS))
    # legacy layout: col0=origin, col1=target=origin+h
    assert fc[0, 1] == fc[0, 0] + 1
    fp = np.loadtxt(csvs["fitted_params"], delimiter=",")
    assert fp.shape == (3, 1 + 3)


def test_rolling_window_database_rw_model(tmp_path):
    """End-to-end rolling backtest with the RW model (no estimation cost)."""
    spec = _spec(tmp_path)
    data = _panel(T=36)
    init = np.zeros((spec.n_params, 1))
    run_forecast_window_database(
        spec, data, "1", 30, 1, 4, "expanding", init,
        param_groups=[], reestimate=False, printing=False)
    merged = os.path.join(str(tmp_path), "db", "forecasts_expanding_merged.sqlite3")
    assert os.path.isfile(merged)
    csv = os.path.join(str(tmp_path),
                       "RW__thread_id__1__expanding_window_forecasts.csv")
    arr = np.loadtxt(csv, delimiter=",")
    # 7 origins (30..36) × horizon 4
    assert arr.shape == (7 * 4, 2 + len(MATS))
    # RW forecast = last observed column, rounded to 3 decimals
    first = arr[arr[:, 0] == 30][0]
    np.testing.assert_allclose(first[2:], np.round(data[:, 29], 3))
    # resume is a no-op (idempotent shards/merged short-circuit)
    run_forecast_window_database(
        spec, data, "1", 30, 1, 4, "expanding", init,
        param_groups=[], reestimate=False, printing=False)


def test_rolling_window_batched_static_model(tmp_path):
    """Batched (windows × starts) path writes the same artifact contract."""
    spec = _spec(tmp_path, code="NS")
    data = _panel(T=36)
    p = np.zeros(spec.n_params)
    p[0] = np.log(0.5)
    p[1:4] = [0.3, -0.1, 0.05]
    p[4:13] = np.diag([0.9, 0.85, 0.8]).T.reshape(-1)
    run_forecast_window_batched(
        spec, data, "1", 32, 1, 3, "expanding", p[:, None],
        reestimate=True, printing=False)
    merged = os.path.join(str(tmp_path), "db", "forecasts_expanding_merged.sqlite3")
    assert os.path.isfile(merged)
    conn = sqlite3.connect(merged)
    rows = conn.execute("SELECT task_id, loss FROM forecasts ORDER BY task_id").fetchall()
    conn.close()
    assert [r[0] for r in rows] == [32, 33, 34, 35, 36]
    assert all(np.isfinite(r[1]) for r in rows)


def test_no_window_database(tmp_path):
    spec = _spec(tmp_path)
    data = _panel(T=30)
    init = np.zeros((spec.n_params, 1))
    run_forecast_no_window_database(
        spec, data, "1", 25, 1, 3, "no_windowing", init,
        param_groups=["1"] * spec.n_params, max_group_iters=1, reestimate=False)
    csv = os.path.join(str(tmp_path),
                       "RW__thread_id__1__expanding_window_forecasts.csv")
    arr = np.loadtxt(csv, delimiter=",")
    assert arr.shape == (6 * 3, 2 + 3 + 1 + len(MATS))


def test_moving_window_span(tmp_path):
    spec = _spec(tmp_path)
    data = _panel(T=34)
    init = np.zeros((spec.n_params, 1))
    run_rolling_forecasts(spec, data, "1", 30, 1, 3, init,
                          window_type="moving", param_groups=[],
                          reestimate=False)
    merged = os.path.join(str(tmp_path), "db", "forecasts_moving_merged.sqlite3")
    assert os.path.isfile(merged)


def test_merged_db_path_resolves_sibling_model(tmp_path):
    """Warm-start reads must target .../thread_id__X/<static_model>/db/."""
    from yieldfactormodels_jl_tpu.persistence.database import _merged_db_path

    rl = os.path.join(str(tmp_path), "results", "thread_id__1", "SD-NS") + os.sep
    got = _merged_db_path(rl, "NS", "expanding")
    want = os.path.join(str(tmp_path), "results", "thread_id__1", "NS", "db",
                        "forecasts_expanding_merged.sqlite3")
    assert got == want


def test_read_static_params_from_db_roundtrip(tmp_path):
    """MSED warm start pulls the static model's fitted tail from its merged DB."""
    spec, _ = create_model("SD-NS", MATS, float_type="float64",
                           results_location=os.path.join(
                               str(tmp_path), "thread_id__1", "SD-NS") + os.sep)
    ns_db_dir = os.path.join(str(tmp_path), "thread_id__1", "NS", "db")
    base = os.path.join(ns_db_dir, "forecasts_expanding.sqlite3")
    static_params = np.arange(13, dtype=float)
    results = {k: np.ones((2, 4)) for k in
               ("preds", "factors", "states", "factor_loadings_1", "factor_loadings_2")}
    db.save_oos_forecast_sharded(base, "NS", "1", "expanding", 30, results,
                                 -1.0, static_params, forecast_horizon=2)
    db.merge_forecast_shards(base, task_ids=[30])
    all_params = np.zeros((15, 1))
    out = db.read_static_params_from_db(spec, 30, all_params, window_type="expanding")
    # tail [ω, δ, Φ] overwritten with the static fit (paramteroperations.jl:124-128)
    np.testing.assert_allclose(out[2:, 0], static_params)
    np.testing.assert_allclose(out[:2, 0], 0.0)


def test_crash_recovery_stale_lock(tmp_path):
    """A SIGKILL'd worker leaves a stale lock dir that would permanently skip
    its task (the reference's known weakness, SURVEY.md §5.3); the TTL sweep +
    rerun must complete the backtest anyway."""
    import time as _time

    spec = _spec(tmp_path)
    data = _panel(T=36)
    init = np.zeros((spec.n_params, 1))
    # simulate a worker killed mid-task 31: lock dir exists, no shard written
    lockroot = os.path.join(spec.results_location, "db", "locks")
    stale = os.path.join(lockroot, "expanding", "task_31.lock")
    os.makedirs(stale)
    old = _time.time() - 7200
    os.utime(stale, (old, old))

    # without a sweep the task is skipped -> no merged db
    run_forecast_window_database(
        spec, data, "1", 30, 1, 4, "expanding", init,
        param_groups=[], reestimate=False, printing=False)
    merged = os.path.join(str(tmp_path), "db", "forecasts_expanding_merged.sqlite3")
    assert not os.path.isfile(merged)
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    assert db.forecast_path(base, 31).endswith("_31.sqlite3")
    assert not os.path.isfile(db.forecast_path(base, 31))
    assert os.path.isfile(db.forecast_path(base, 30))  # other tasks DID run

    # rerun with the TTL sweep (crash recovery): completes and merges
    run_forecast_window_database(
        spec, data, "1", 30, 1, 4, "expanding", init,
        param_groups=[], reestimate=False, printing=False,
        stale_lock_ttl=3600.0)
    assert os.path.isfile(merged)
    conn = sqlite3.connect(merged)
    tasks = [r[0] for r in conn.execute(
        "SELECT task_id FROM forecasts ORDER BY task_id").fetchall()]
    conn.close()
    assert tasks == list(range(30, 37))


def test_merge_skips_corrupt_shard_with_summary(tmp_path):
    """A truncated/corrupt shard DB is skipped with a recorded reason in the
    merge summary instead of aborting the whole merge; the corrupt file is
    kept on disk for repair, healthy shards still fold and delete."""
    spec = _spec(tmp_path)
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    results = {k: np.ones((2, 6)) for k in
               ("preds", "factors", "states", "factor_loadings_1",
                "factor_loadings_2")}
    for task in (30, 31, 32):
        db.save_oos_forecast_sharded(base, spec.model_string, "1", "expanding",
                                     task, results, -0.5, np.arange(3.0),
                                     forecast_horizon=2)
    # truncate task 31's shard mid-file (a worker killed mid-write)
    with open(db.forecast_path(base, 31), "r+b") as fh:
        fh.truncate(100)
    out = db.merge_forecast_shards(base, task_ids=[30, 31, 32],
                                   delete_shards=True)
    assert os.path.isfile(out)
    assert sorted(out.merged) == [30, 32]
    assert [t for t, _ in out.skipped] == [31]
    assert "corrupt" in out.skipped[0][1]
    conn = sqlite3.connect(out)
    tasks = [r[0] for r in conn.execute(
        "SELECT task_id FROM forecasts ORDER BY task_id").fetchall()]
    conn.close()
    assert tasks == [30, 32]
    assert os.path.isfile(db.forecast_path(base, 31))  # kept for repair
    assert not os.path.isfile(db.forecast_path(base, 32))  # healthy: deleted


def test_merge_survives_corrupt_first_shard(tmp_path):
    """The fold target itself may be the corrupt one — the merge must pick
    the next healthy shard instead of renaming garbage to _merged."""
    spec = _spec(tmp_path)
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    results = {k: np.ones((2, 6)) for k in
               ("preds", "factors", "states", "factor_loadings_1",
                "factor_loadings_2")}
    for task in (30, 31):
        db.save_oos_forecast_sharded(base, spec.model_string, "1", "expanding",
                                     task, results, -0.5, np.arange(3.0),
                                     forecast_horizon=2)
    with open(db.forecast_path(base, 30), "wb") as fh:
        fh.write(b"\x00" * 64)
    out = db.merge_forecast_shards(base, task_ids=[30, 31])
    assert out.merged == [31] and [t for t, _ in out.skipped] == [30]
    conn = sqlite3.connect(out)
    assert conn.execute("SELECT COUNT(*) FROM forecasts").fetchone()[0] == 1
    conn.close()


def test_merge_publish_is_at_most_once(tmp_path):
    """A slow duplicate merger (its lease was stolen while it was still
    alive) must NOT overwrite an already-published merged DB with a partial
    one — the publish is an at-most-once link, first merger wins."""
    spec = _spec(tmp_path)
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    results = {k: np.ones((2, 6)) for k in
               ("preds", "factors", "states", "factor_loadings_1",
                "factor_loadings_2")}
    for task in (30, 31, 32):
        db.save_oos_forecast_sharded(base, spec.model_string, "1", "expanding",
                                     task, results, -0.5, np.arange(3.0),
                                     forecast_horizon=2)
    first = db.merge_forecast_shards(base, task_ids=[30, 31, 32],
                                     delete_shards=True)
    assert sorted(first.merged) == [30, 31, 32]
    # the loser re-runs after the winner published + deleted the shards:
    # it must not clobber the complete merged DB with its empty view
    second = db.merge_forecast_shards(base, task_ids=[30, 31, 32],
                                      delete_shards=True)
    assert str(second) == str(first)
    assert second.merged == []  # discarded, not published
    conn = sqlite3.connect(first)
    tasks = [r[0] for r in conn.execute(
        "SELECT task_id FROM forecasts ORDER BY task_id").fetchall()]
    conn.close()
    assert tasks == [30, 31, 32]  # winner's rows intact


def test_merge_concurrent_duplicate_mergers(tmp_path):
    """Two mergers racing over the same shard set (the lease-steal double
    execution): exactly one publishes, the merged DB holds every row, no
    shard row is lost regardless of interleaving."""
    import threading

    spec = _spec(tmp_path)
    base = os.path.join(str(tmp_path), "db", "forecasts_expanding.sqlite3")
    results = {k: np.ones((2, 6)) for k in
               ("preds", "factors", "states", "factor_loadings_1",
                "factor_loadings_2")}
    tasks = list(range(30, 38))
    for task in tasks:
        db.save_oos_forecast_sharded(base, spec.model_string, "1", "expanding",
                                     task, results, -0.5, np.arange(3.0),
                                     forecast_horizon=2)
    outs, errs = [], []

    def go():
        try:
            outs.append(db.merge_forecast_shards(base, task_ids=tasks,
                                                 delete_shards=True))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=go) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    published = [o for o in outs if o.merged]
    assert len(published) == 1  # at-most-once publish
    conn = sqlite3.connect(outs[0])
    got = [r[0] for r in conn.execute(
        "SELECT task_id FROM forecasts ORDER BY task_id").fetchall()]
    conn.close()
    assert got == tasks  # complete, no lost rows


def test_held_lock_broken_via_env_ttl(tmp_path, monkeypatch):
    """YFM_LOCK_TTL arms break_stale_lock inside the task loop: a dead
    worker's 2h-old lock no longer starves its task even WITHOUT the
    explicit stale_lock_ttl entry sweep."""
    import time as _time

    spec = _spec(tmp_path)
    data = _panel(T=36)
    init = np.zeros((spec.n_params, 1))
    lockroot = os.path.join(spec.results_location, "db", "locks")
    stale = os.path.join(lockroot, "expanding", "task_31.lock")
    os.makedirs(stale)
    old = _time.time() - 7200
    os.utime(stale, (old, old))
    monkeypatch.setenv("YFM_LOCK_TTL", "3600")
    run_forecast_window_database(
        spec, data, "1", 30, 1, 4, "expanding", init,
        param_groups=[], reestimate=False, printing=False)
    merged = os.path.join(str(tmp_path), "db",
                          "forecasts_expanding_merged.sqlite3")
    assert os.path.isfile(merged)
    conn = sqlite3.connect(merged)
    tasks = [r[0] for r in conn.execute(
        "SELECT task_id FROM forecasts ORDER BY task_id").fetchall()]
    conn.close()
    assert tasks == list(range(30, 37))


def test_held_lock_broken_via_env_ttl_batched(tmp_path, monkeypatch):
    """The batched driver honors YFM_LOCK_TTL for its per-task locks too —
    a dead worker's stale lock must not starve the origin (and with it the
    all-shards merge gate) on the device-batched path."""
    import time as _time

    spec = _spec(tmp_path)
    data = _panel(T=36)
    init = np.zeros((spec.n_params, 1))
    lockroot = os.path.join(spec.results_location, "db", "locks")
    stale = os.path.join(lockroot, "expanding", "task_31.lock")
    os.makedirs(stale)
    old = _time.time() - 7200
    os.utime(stale, (old, old))
    monkeypatch.setenv("YFM_LOCK_TTL", "3600")
    run_forecast_window_batched(
        spec, data, "1", 30, 1, 4, "expanding", init,
        param_groups=[], reestimate=False, printing=False)
    merged = os.path.join(str(tmp_path), "db",
                          "forecasts_expanding_merged.sqlite3")
    assert os.path.isfile(merged)
    conn = sqlite3.connect(merged)
    tasks = [r[0] for r in conn.execute(
        "SELECT task_id FROM forecasts ORDER BY task_id").fetchall()]
    conn.close()
    assert tasks == list(range(30, 37))


def test_batched_window_predicts_equal_truncated_per_task(maturities, yields_panel):
    """The fused one-program per-origin predict (masked uniform panel) must
    equal the per-task truncated predict column-for-column over the saved
    forecast span, for BOTH window types and a score-driven family (whose
    masked-prefix == truncation property rests on γ₀/β₀ being transition
    fixed points)."""
    import jax.numpy as jnp
    from yieldfactormodels_jl_tpu import create_model
    from yieldfactormodels_jl_tpu.forecasting import (
        _batched_window_predicts, _window_forecast_data)
    from yieldfactormodels_jl_tpu.models import api

    h = 5
    for code in ("NS", "SD-NS", "1C"):
        spec, _ = create_model(code, tuple(maturities), float_type="float64")
        p = np.zeros(spec.n_params)
        if code == "SD-NS":
            p[0], p[1], p[2] = 1e-3, 0.97, np.log(0.5)
            p[3:6] = [0.3, -0.1, 0.05]
            p[6:15] = np.diag([0.95, 0.9, 0.85]).T.reshape(-1)
        elif code == "NS":
            p[0] = np.log(0.5)
            p[1:4] = [0.3, -0.1, 0.05]
            p[4:13] = np.diag([0.95, 0.9, 0.85]).reshape(-1)
        else:  # 1C kalman
            p[0] = np.log(0.5)
            p[1] = 1e-3
            k = 2
            for j in range(3):
                for i in range(j + 1):
                    p[k] = 0.1 if i == j else 0.01
                    k += 1
            p[6:9] = [0.3, -0.1, 0.05]
            p[9:18] = np.diag([0.95, 0.9, 0.85]).reshape(-1)
        data = yields_panel[:, :40]
        in_end, in_start = 30, 1
        tasks = [30, 33, 40]
        for wt in ("expanding", "moving"):
            batched = _batched_window_predicts(
                spec, data, tasks, wt, in_end, in_start, h,
                np.tile(p, (len(tasks), 1)))
            for i, tid in enumerate(tasks):
                fdata = _window_forecast_data(spec, data, tid, wt, in_end,
                                              in_start, h)
                want = api.predict(spec, jnp.asarray(p), jnp.asarray(fdata))
                for key in ("preds", "factors", "states"):
                    np.testing.assert_allclose(
                        np.asarray(batched[i][key])[:, -h:],
                        np.asarray(want[key])[:, -h:],
                        rtol=1e-9, atol=1e-12,
                        err_msg=f"{code}/{wt}/task {tid}/{key}")
