"""forecast_density(): analytic multi-step predictive densities.

Oracle parity (CLAUDE.md rule): the filtered moments come from
oracle.rts_smoother's INDEPENDENT NumPy forward pass, and the h-step
prediction recursion is re-run in NumPy; means AND covariances must match.
Plus structural checks: predictive variance is non-decreasing in the
horizon, the means match api.predict's NaN-padding point forecasts, and
statistical calibration on a simulated panel.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import yieldfactormodels_jl_tpu as yfm
from yieldfactormodels_jl_tpu.models.params import unpack_kalman

from tests import oracle
from tests.oracle import stable_1c_params

MATS = tuple(np.array([3, 12, 36, 84, 180, 360]) / 12.0)
H = 12


def _case(rng, T=60):
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = stable_1c_params(spec, dtype=np.float64)
    data = np.asarray(
        yfm.simulate(spec, jnp.asarray(p), T=T, key=jax.random.PRNGKey(4))
        ["data"])
    return spec, p, data


@pytest.mark.parametrize("engine", ["joint", "univariate"])
def test_density_matches_numpy_oracle(engine, rng):
    spec, p, data = _case(rng)
    out = yfm.forecast_density(spec, jnp.asarray(p), data, H, engine=engine)
    kp = unpack_kalman(spec, jnp.asarray(p))
    Z = oracle.dns_loadings(p[spec.layout["gamma"][0]], np.asarray(MATS))
    Phi = np.asarray(kp.Phi)
    delta = np.asarray(kp.delta)
    Om = np.asarray(kp.Omega_state)
    ov = float(kp.obs_var)
    # independent NumPy forward pass -> final FILTERED moments
    _, _, bf, Pf = oracle.rts_smoother(Z, Phi, delta, Om, ov, data)
    b, P = bf[-1], Pf[-1]
    for k in range(H):
        b = delta + Phi @ b
        P = Phi @ P @ Phi.T + Om
        np.testing.assert_allclose(np.asarray(out["means"])[k], Z @ b,
                                   rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(np.asarray(out["covs"])[k],
                                   Z @ P @ Z.T + ov * np.eye(len(MATS)),
                                   rtol=1e-8, atol=1e-12)


def test_variance_grows_with_horizon_and_means_match_predict(rng):
    spec, p, data = _case(rng)
    out = yfm.forecast_density(spec, jnp.asarray(p), data, H)
    var = np.diagonal(np.asarray(out["covs"]), axis1=1, axis2=2)
    assert np.all(np.diff(var, axis=0) >= -1e-12), "variance must not shrink"
    # the density means ARE the point forecasts the NaN-padding path makes:
    # preds[:, k] is the one-step-ahead prediction of column k+1, so the H
    # forecast-only columns sit at preds[:, T-1 : T+H-1]
    T = data.shape[1]
    nan_pad = np.concatenate(
        [data, np.full((len(MATS), H), np.nan)], axis=1)
    preds = np.asarray(yfm.predict(spec, jnp.asarray(p), nan_pad)["preds"])
    np.testing.assert_allclose(np.asarray(out["means"]).T,
                               preds[:, T - 1:T + H - 1],
                               rtol=1e-8, atol=1e-10)


def test_calibration_on_simulated_future(rng):
    """~95% of realized h=1..3 yields fall inside the 95% predictive
    interval when the model is true (loose bound: binomial noise)."""
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = jnp.asarray(stable_1c_params(spec, dtype=np.float64))
    hits = total = 0
    for seed in range(6):
        sim = yfm.simulate(spec, p, T=80, key=jax.random.PRNGKey(seed))
        data = np.asarray(sim["data"])
        out = yfm.forecast_density(spec, p, data[:, :70], 3)
        for k in range(3):
            m = np.asarray(out["means"])[k]
            s = np.sqrt(np.diagonal(np.asarray(out["covs"])[k]))
            y = data[:, 70 + k]
            hits += int(np.sum(np.abs(y - m) <= 1.96 * s))
            total += len(MATS)
    assert 0.85 <= hits / total <= 1.0, hits / total


def test_end_is_the_forecast_origin(rng):
    """end=E must condition on columns :E only — identical to calling on
    the truncated panel, so 'step k' is genuinely (k+1) steps past E."""
    spec, p, data = _case(rng)
    a = yfm.forecast_density(spec, jnp.asarray(p), data, 4, end=40)
    b = yfm.forecast_density(spec, jnp.asarray(p), data[:, :40], 4)
    np.testing.assert_allclose(np.asarray(a["means"]), np.asarray(b["means"]),
                               rtol=1e-12)
    np.testing.assert_allclose(np.asarray(a["covs"]), np.asarray(b["covs"]),
                               rtol=1e-12)


def test_failed_filter_poisons_density(rng):
    spec, p, data = _case(rng)
    bad = p.copy()
    lo, hi = spec.layout["phi"]
    bad[lo:hi] = (1.5 * np.eye(3)).reshape(-1)  # non-stationary
    out = yfm.forecast_density(spec, jnp.asarray(bad), data, 4)
    assert np.isnan(np.asarray(out["means"])).all()


def test_rejects_prediction_error_families_and_bad_engine(rng):
    spec, p, data = _case(rng)
    nspec, _ = yfm.create_model("NS", MATS, float_type="float64")
    with pytest.raises(ValueError, match="Kalman"):
        yfm.forecast_density(nspec, np.zeros(nspec.n_params), data, 4)
    with pytest.raises(ValueError, match="filtering-moments"):
        yfm.forecast_density(spec, jnp.asarray(p), data, 4, engine="sqrt")


def test_density_fan_poisons_per_shock_with_codes(rng):
    """density_fan is the sentinel boundary for the fan axis (DESIGN §11):
    a non-finite displaced start NaN-poisons ONLY its own fan row and
    stamps a per-shock taxonomy code; finite rows still match the
    independent NumPy oracle."""
    from yieldfactormodels_jl_tpu.ops.forecast import density_fan
    from yieldfactormodels_jl_tpu.robustness import taxonomy as tax

    spec, p, data = _case(rng)
    kp = unpack_kalman(spec, jnp.asarray(p))
    Z = oracle.dns_loadings(p[spec.layout["gamma"][0]], np.asarray(MATS))
    _, _, bf, Pf = oracle.rts_smoother(
        Z, np.asarray(kp.Phi), np.asarray(kp.delta),
        np.asarray(kp.Omega_state), float(kp.obs_var), data)
    beta, P = bf[-1], Pf[-1]
    Ms = spec.state_dim
    shifts = jnp.stack([jnp.zeros(Ms), jnp.full((Ms,), jnp.nan)])
    out = density_fan(spec, kp, jnp.asarray(beta), jnp.asarray(P),
                      shifts, jnp.ones(2), 4)
    codes = np.asarray(out["codes"])
    assert codes.dtype == np.int32
    assert codes[0] == tax.OK and codes[1] == tax.NAN_STATE
    assert np.isnan(np.asarray(out["means"])[1]).all()
    assert np.isnan(np.asarray(out["covs"])[1]).all()
    # the finite row is untouched: the NumPy fan recursion, bit for bit
    o_means, o_covs = oracle.fan_refresh(
        Z, np.zeros(spec.N), np.asarray(kp.Phi), np.asarray(kp.delta),
        np.asarray(kp.Omega_state), float(kp.obs_var), beta, P,
        np.zeros((1, Ms)), np.ones(1), 4)
    np.testing.assert_allclose(np.asarray(out["means"])[0], o_means[0],
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(out["covs"])[0], o_covs[0],
                               rtol=1e-9, atol=1e-12)
    # a NaN covariance start reports NONPSD_COV, not NAN_STATE
    badP = jnp.asarray(P).at[0, 0].set(jnp.nan)
    out2 = density_fan(spec, kp, jnp.asarray(beta), badP,
                       jnp.zeros((1, Ms)), jnp.ones(1), 4)
    assert int(np.asarray(out2["codes"])[0]) == tax.NONPSD_COV
