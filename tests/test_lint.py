"""graftlint CI gate + suppression machinery (ISSUE 10 tentpole).

The single fast check every PR runs: zero unsuppressed findings over the
package, the engine importable without jax, the CLI JSON schema stable,
``--changed-only`` honest against a real git diff, pragmas and the baseline
round-tripping, and (when ruff is installed) the generic pyflakes-level
pass clean too.  Per-rule positive/negative fixtures live in
tests/test_lint_rules.py.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from yieldfactormodels_jl_tpu.analysis import (LintConfig, RULES,
                                               load_baseline, run_lint,
                                               save_baseline)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def test_repo_is_lint_clean():
    """Zero unsuppressed, unbaselined findings over the package + bench
    layer — the acceptance bar every future PR inherits."""
    cfg = LintConfig(root=ROOT)
    baseline = load_baseline(cfg.abspath(cfg.baseline_path))
    result = run_lint(cfg, baseline=baseline)
    assert not result.errors, result.errors
    msgs = [f"{f.file}:{f.line}: {f.rule} {f.message}"
            for f in result.findings]
    assert not msgs, "graftlint findings:\n" + "\n".join(msgs)


def test_lint_pass_is_not_vacuous():
    """All nine rules registered and the walk actually covers the package,
    the bench layer, and the kernel modules (a rotted glob would green-light
    everything)."""
    assert {f"YFM{i:03d}" for i in range(1, 10)} <= set(RULES)
    cfg = LintConfig(root=ROOT)
    rels = set(cfg.lint_files())
    assert {"yieldfactormodels_jl_tpu/ops/univariate_kf.py",
            "yieldfactormodels_jl_tpu/serving/gateway.py",
            "yieldfactormodels_jl_tpu/estimation/scenario.py",
            "bench.py", "benchmarks/run_all.py"} <= rels
    kernels = {os.path.basename(r) for r in rels if cfg.is_kernel(r)}
    assert {"univariate_kf.py", "sqrt_kf.py", "particle.py", "smoother.py",
            "online.py", "scenario.py"} <= kernels


def test_engine_imports_without_jax():
    """The linter must start in ~a second on a CPU-only box: importing the
    analysis package (as ``python -m`` does via the lazy package __init__)
    must not pull jax — which on this container would put backend init one
    device-op away from dialing the TPU tunnel."""
    code = ("import sys; import yieldfactormodels_jl_tpu.analysis; "
            "assert 'jax' not in sys.modules, 'analysis import pulled jax'")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT, env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# CLI: JSON schema + exit codes
# ---------------------------------------------------------------------------

def _cli(*args, cwd=ROOT, timeout=180):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.run(
        [sys.executable, "-m", "yieldfactormodels_jl_tpu.analysis", *args],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=timeout)


def test_cli_json_schema():
    proc = _cli("--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["version"] == 1
    assert set(data) >= {"version", "files_scanned", "counts", "findings",
                         "suppressed", "baselined", "errors"}
    assert data["counts"]["findings"] == len(data["findings"]) == 0
    assert data["files_scanned"] >= 50
    for bucket in ("findings", "suppressed", "baselined"):
        for f in data[bucket]:
            assert set(f) >= {"rule", "file", "line", "col", "message"}


def test_cli_list_rules():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rid in ("YFM001", "YFM005", "YFM009"):
        assert rid in proc.stdout


# ---------------------------------------------------------------------------
# fixture repo scaffolding
# ---------------------------------------------------------------------------

_CLEAN = "def ok():\n    return 1\n"
_BAD_SERVING = textwrap.dedent("""\
    import queue

    def pump():
        return queue.Queue()
""")


def _scaffold(tmp_path, serving_body=_CLEAN):
    pkg = tmp_path / "yieldfactormodels_jl_tpu"
    (pkg / "serving").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "serving" / "__init__.py").write_text("")
    (pkg / "serving" / "gw.py").write_text(serving_body)
    (tmp_path / "CLAUDE.md").write_text("no knobs documented\n")
    return tmp_path


def test_changed_only_on_synthetic_git_diff(tmp_path):
    """--changed-only lints exactly the files git reports as touched: a
    committed violation is invisible, the same violation in the worktree
    diff is caught."""
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    git_env = dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*args):
        proc = subprocess.run(["git", *args], cwd=root, env=git_env,
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")

    # nothing changed: --changed-only sees an empty file set → exit 0 even
    # though the committed tree contains a violation
    proc = _cli("--changed-only", "--root", str(root), "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["counts"]["findings"] == 0

    # touch the violating file: now it is in the diff and the finding fires
    gw = root / "yieldfactormodels_jl_tpu" / "serving" / "gw.py"
    gw.write_text(_BAD_SERVING + "\n# touched\n")
    proc = _cli("--changed-only", "--root", str(root), "--format", "json")
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert [f["rule"] for f in data["findings"]] == ["YFM008"]
    assert data["findings"][0]["file"].endswith("serving/gw.py")

    # a full (non-changed-only) run still sees it regardless of git state
    proc = _cli("--root", str(root))
    assert proc.returncode == 1


# ---------------------------------------------------------------------------
# suppression machinery: pragmas + baseline
# ---------------------------------------------------------------------------

def test_pragma_suppresses_with_recorded_reason(tmp_path):
    body = textwrap.dedent("""\
        import queue

        def pump():
            # yfmlint: disable=YFM008 -- bounded by the admission check
            return queue.Queue()
    """)
    root = _scaffold(tmp_path, serving_body=body)
    res = run_lint(LintConfig(root=str(root)))
    assert not res.findings
    assert len(res.suppressed) == 1
    s = res.suppressed[0]
    assert s.rule == "YFM008"
    assert s.suppress_reason == "bounded by the admission check"


def test_pragma_without_reason_still_suppresses_and_records_empty(tmp_path):
    body = textwrap.dedent("""\
        import queue

        def pump():
            return queue.Queue()  # yfmlint: disable=YFM008
    """)
    root = _scaffold(tmp_path, serving_body=body)
    res = run_lint(LintConfig(root=str(root)))
    assert not res.findings
    assert len(res.suppressed) == 1
    assert res.suppressed[0].suppress_reason == ""


def test_pragma_for_other_rule_does_not_suppress(tmp_path):
    body = textwrap.dedent("""\
        import queue

        def pump():
            return queue.Queue()  # yfmlint: disable=YFM001 -- wrong id
    """)
    root = _scaffold(tmp_path, serving_body=body)
    res = run_lint(LintConfig(root=str(root)))
    assert [f.rule for f in res.findings] == ["YFM008"]
    assert not res.suppressed


def test_baseline_roundtrip(tmp_path):
    """Findings grandfathered via save_baseline stop being actionable but
    stay visible; an edited line (moved finding) escapes the baseline."""
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    cfg = LintConfig(root=str(root))
    res = run_lint(cfg)
    assert [f.rule for f in res.findings] == ["YFM008"]

    bl_path = cfg.abspath(cfg.baseline_path)
    n = save_baseline(bl_path, res.findings)
    assert n == 1
    baseline = load_baseline(bl_path)
    res2 = run_lint(cfg, baseline=baseline)
    assert not res2.findings
    assert [f.rule for f in res2.baselined] == ["YFM008"]

    # shift the violation one line down: the stale baseline no longer
    # matches and the finding is actionable again
    gw = root / "yieldfactormodels_jl_tpu" / "serving" / "gw.py"
    gw.write_text("# moved\n" + _BAD_SERVING)
    res3 = run_lint(cfg, baseline=baseline)
    assert [f.rule for f in res3.findings] == ["YFM008"]


def test_write_baseline_cli(tmp_path):
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert load_baseline(str(root / ".yfmlint-baseline.json"))
    proc = _cli("--root", str(root))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_repo_baseline_is_wellformed_and_empty():
    """The committed baseline parses and is empty — the healthy steady
    state; deliberate debt must be added consciously, not accumulate."""
    entries = load_baseline(os.path.join(ROOT, ".yfmlint-baseline.json"))
    assert entries == set()


# ---------------------------------------------------------------------------
# generic lint: ruff (pyflakes-level), gated on availability
# ---------------------------------------------------------------------------

def test_ruff_pyflakes_clean():
    """Plain-Python errors are caught the same way as domain rules.  Gated:
    this container does not ship ruff (and nothing may be pip-installed),
    so the check runs wherever ruff exists and skips loudly here."""
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff not installed in this container (see CLAUDE.md: "
                    "no new deps); [tool.ruff] config in pyproject.toml is "
                    "exercised wherever ruff is available")
    proc = subprocess.run(
        [ruff, "check", "yieldfactormodels_jl_tpu", "bench.py", "benchmarks",
         "tests"],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
