"""graftlint CI gate + suppression machinery (ISSUE 10 tentpole).

The single fast check every PR runs: zero unsuppressed findings over the
package, the engine importable without jax, the CLI JSON schema stable,
``--changed-only`` honest against a real git diff, pragmas and the baseline
round-tripping, and (when ruff is installed) the generic pyflakes-level
pass clean too.  Per-rule positive/negative fixtures live in
tests/test_lint_rules.py.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from yieldfactormodels_jl_tpu.analysis import (LintConfig, RULES,
                                               load_baseline, run_lint,
                                               save_baseline)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def test_repo_is_lint_clean():
    """Zero unsuppressed, unbaselined findings over the package + bench
    layer — the acceptance bar every future PR inherits."""
    cfg = LintConfig(root=ROOT)
    baseline = load_baseline(cfg.abspath(cfg.baseline_path))
    result = run_lint(cfg, baseline=baseline)
    assert not result.errors, result.errors
    msgs = [f"{f.file}:{f.line}: {f.rule} {f.message}"
            for f in result.findings]
    assert not msgs, "graftlint findings:\n" + "\n".join(msgs)


def test_lint_pass_is_not_vacuous():
    """All eleven AST rules registered and the walk actually covers the
    package, the bench layer, and the kernel modules (a rotted glob would
    green-light everything)."""
    assert {f"YFM{i:03d}" for i in range(1, 12)} <= set(RULES)
    cfg = LintConfig(root=ROOT)
    rels = set(cfg.lint_files())
    assert {"yieldfactormodels_jl_tpu/ops/univariate_kf.py",
            "yieldfactormodels_jl_tpu/serving/gateway.py",
            "yieldfactormodels_jl_tpu/estimation/scenario.py",
            "bench.py", "benchmarks/run_all.py"} <= rels
    kernels = {os.path.basename(r) for r in rels if cfg.is_kernel(r)}
    assert {"univariate_kf.py", "sqrt_kf.py", "particle.py", "smoother.py",
            "online.py", "scenario.py"} <= kernels


def test_engine_imports_without_jax():
    """The linter must start in ~a second on a CPU-only box: importing the
    analysis package (as ``python -m`` does via the lazy package __init__)
    must not pull jax — which on this container would put backend init one
    device-op away from dialing the TPU tunnel."""
    code = ("import sys; import yieldfactormodels_jl_tpu.analysis; "
            "assert 'jax' not in sys.modules, 'analysis import pulled jax'")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT, env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# CLI: JSON schema + exit codes
# ---------------------------------------------------------------------------

def _cli(*args, cwd=ROOT, timeout=180):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.run(
        [sys.executable, "-m", "yieldfactormodels_jl_tpu.analysis", *args],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=timeout)


def test_cli_json_schema():
    proc = _cli("--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["version"] == 1
    assert set(data) >= {"version", "files_scanned", "counts", "findings",
                         "suppressed", "baselined", "errors"}
    assert data["counts"]["findings"] == len(data["findings"]) == 0
    assert data["files_scanned"] >= 50
    for bucket in ("findings", "suppressed", "baselined"):
        for f in data[bucket]:
            assert set(f) >= {"rule", "file", "line", "col", "message"}


def test_cli_list_rules():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rid in ("YFM001", "YFM005", "YFM009"):
        assert rid in proc.stdout


# ---------------------------------------------------------------------------
# fixture repo scaffolding
# ---------------------------------------------------------------------------

_CLEAN = "def ok():\n    return 1\n"
_BAD_SERVING = textwrap.dedent("""\
    import queue

    def pump():
        return queue.Queue()
""")


def _scaffold(tmp_path, serving_body=_CLEAN):
    pkg = tmp_path / "yieldfactormodels_jl_tpu"
    (pkg / "serving").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "serving" / "__init__.py").write_text("")
    (pkg / "serving" / "gw.py").write_text(serving_body)
    (tmp_path / "CLAUDE.md").write_text("no knobs documented\n")
    return tmp_path


def test_changed_only_on_synthetic_git_diff(tmp_path):
    """--changed-only lints exactly the files git reports as touched: a
    committed violation is invisible, the same violation in the worktree
    diff is caught."""
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    git_env = dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*args):
        proc = subprocess.run(["git", *args], cwd=root, env=git_env,
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")

    # nothing changed: --changed-only sees an empty file set → exit 0 even
    # though the committed tree contains a violation
    proc = _cli("--changed-only", "--root", str(root), "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout)["counts"]["findings"] == 0

    # touch the violating file: now it is in the diff and the finding fires
    gw = root / "yieldfactormodels_jl_tpu" / "serving" / "gw.py"
    gw.write_text(_BAD_SERVING + "\n# touched\n")
    proc = _cli("--changed-only", "--root", str(root), "--format", "json")
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert [f["rule"] for f in data["findings"]] == ["YFM008"]
    assert data["findings"][0]["file"].endswith("serving/gw.py")

    # a full (non-changed-only) run still sees it regardless of git state
    proc = _cli("--root", str(root))
    assert proc.returncode == 1


# ---------------------------------------------------------------------------
# suppression machinery: pragmas + baseline
# ---------------------------------------------------------------------------

def test_pragma_suppresses_with_recorded_reason(tmp_path):
    body = textwrap.dedent("""\
        import queue

        def pump():
            # yfmlint: disable=YFM008 -- bounded by the admission check
            return queue.Queue()
    """)
    root = _scaffold(tmp_path, serving_body=body)
    res = run_lint(LintConfig(root=str(root)))
    assert not res.findings
    assert len(res.suppressed) == 1
    s = res.suppressed[0]
    assert s.rule == "YFM008"
    assert s.suppress_reason == "bounded by the admission check"


def test_pragma_without_reason_still_suppresses_and_records_empty(tmp_path):
    body = textwrap.dedent("""\
        import queue

        def pump():
            return queue.Queue()  # yfmlint: disable=YFM008
    """)
    root = _scaffold(tmp_path, serving_body=body)
    res = run_lint(LintConfig(root=str(root)))
    assert not res.findings
    assert len(res.suppressed) == 1
    assert res.suppressed[0].suppress_reason == ""


def test_pragma_for_other_rule_does_not_suppress(tmp_path):
    body = textwrap.dedent("""\
        import queue

        def pump():
            return queue.Queue()  # yfmlint: disable=YFM001 -- wrong id
    """)
    root = _scaffold(tmp_path, serving_body=body)
    res = run_lint(LintConfig(root=str(root)))
    assert [f.rule for f in res.findings] == ["YFM008"]
    assert not res.suppressed


def test_baseline_roundtrip(tmp_path):
    """Findings grandfathered via save_baseline stop being actionable but
    stay visible; an edited line (moved finding) escapes the baseline."""
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    cfg = LintConfig(root=str(root))
    res = run_lint(cfg)
    assert [f.rule for f in res.findings] == ["YFM008"]

    bl_path = cfg.abspath(cfg.baseline_path)
    n = save_baseline(bl_path, res.findings)
    assert n == 1
    baseline = load_baseline(bl_path)
    res2 = run_lint(cfg, baseline=baseline)
    assert not res2.findings
    assert [f.rule for f in res2.baselined] == ["YFM008"]

    # shift the violation one line down: the stale baseline no longer
    # matches and the finding is actionable again
    gw = root / "yieldfactormodels_jl_tpu" / "serving" / "gw.py"
    gw.write_text("# moved\n" + _BAD_SERVING)
    res3 = run_lint(cfg, baseline=baseline)
    assert [f.rule for f in res3.findings] == ["YFM008"]


def test_write_baseline_cli(tmp_path):
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert load_baseline(str(root / ".yfmlint-baseline.json"))
    proc = _cli("--root", str(root))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_repo_baseline_is_wellformed_and_empty():
    """The committed baseline parses and is empty — the healthy steady
    state; deliberate debt must be added consciously, not accumulate."""
    entries = load_baseline(os.path.join(ROOT, ".yfmlint-baseline.json"))
    assert entries == set()


# ---------------------------------------------------------------------------
# generic lint: ruff (pyflakes-level), gated on availability
# ---------------------------------------------------------------------------

def test_ruff_pyflakes_clean():
    """Plain-Python errors are caught the same way as domain rules.  Gated:
    this container does not ship ruff (and nothing may be pip-installed),
    so the check runs wherever ruff exists and skips loudly here."""
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff not installed in this container (see CLAUDE.md: "
                    "no new deps); [tool.ruff] config in pyproject.toml is "
                    "exercised wherever ruff is available")
    proc = subprocess.run(
        [ruff, "check", "yieldfactormodels_jl_tpu", "bench.py", "benchmarks",
         "tests"],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# --format sarif (both tiers share the emitter; exercised on the AST tier)
# ---------------------------------------------------------------------------

def test_cli_sarif_schema(tmp_path):
    body = _BAD_SERVING + textwrap.dedent("""\

        def pump2():
            # yfmlint: disable=YFM008 -- fixture: deliberately suppressed
            return queue.Queue()
    """)
    root = _scaffold(tmp_path, serving_body=body)
    proc = _cli("--root", str(root), "--format", "sarif")
    assert proc.returncode == 1  # findings still drive the exit code
    sarif = json.loads(proc.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    # schema validators reject a malformed informationUri (spaces/parens)
    # wholesale — either omit it or keep it a bare valid URI
    assert " " not in run["tool"]["driver"].get("informationUri", "")
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "YFM008" in rule_ids
    actionable = [r for r in run["results"] if "suppressions" not in r]
    suppressed = [r for r in run["results"] if "suppressions" in r]
    assert len(actionable) == 1 and actionable[0]["ruleId"] == "YFM008"
    loc = actionable[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("serving/gw.py")
    assert loc["region"]["startLine"] >= 1
    assert len(suppressed) == 1
    assert suppressed[0]["suppressions"][0]["kind"] == "inSource"
    assert "deliberately" in suppressed[0]["suppressions"][0]["justification"]


def test_cli_list_rules_includes_ir_tier():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rid in ("YFM010", "YFM011", "YFM101", "YFM105"):
        assert rid in proc.stdout


# ---------------------------------------------------------------------------
# --changed-only: staged and untracked files (pre-commit on new modules)
# ---------------------------------------------------------------------------

def test_changed_only_sees_staged_and_untracked_files(tmp_path):
    """A brand-new module must be linted by a pre-commit run whether it is
    merely on disk (untracked) or already ``git add``-ed (staged) — the
    committed-diff-only failure mode misses both."""
    root = _scaffold(tmp_path)  # clean tree
    git_env = dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                   GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*args):
        proc = subprocess.run(["git", *args], cwd=root, env=git_env,
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")

    # untracked new module: in the --changed-only set before any git add
    new = root / "yieldfactormodels_jl_tpu" / "serving" / "new_mod.py"
    new.write_text(_BAD_SERVING)
    proc = _cli("--changed-only", "--root", str(root), "--format", "json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert [f["rule"] for f in data["findings"]] == ["YFM008"]
    assert data["findings"][0]["file"].endswith("new_mod.py")

    # staged (git add, not committed): still in the set — and the worktree
    # copy is what gets linted
    git("add", "yieldfactormodels_jl_tpu/serving/new_mod.py")
    proc = _cli("--changed-only", "--root", str(root), "--format", "json")
    assert proc.returncode == 1
    assert json.loads(proc.stdout)["findings"][0]["file"].endswith(
        "new_mod.py")

    # committed: drops out of the changed set again
    git("commit", "-qm", "add module")
    proc = _cli("--changed-only", "--root", str(root), "--format", "json")
    assert proc.returncode == 0
    assert json.loads(proc.stdout)["counts"]["findings"] == 0


# ---------------------------------------------------------------------------
# baseline hygiene: prune reporting + stale-entry warnings
# ---------------------------------------------------------------------------

def test_write_baseline_refused_under_partial_runs(tmp_path):
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    for extra in (("--changed-only",), ("--rules", "YFM008")):
        proc = _cli("--root", str(root), "--write-baseline", *extra)
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "partial" in proc.stderr or "FULL" in proc.stderr


def test_ir_refused_with_foreign_root(tmp_path):
    """The IR tier audits the IMPORTED package — builders register at
    import time, so a different checkout's --root would silently audit the
    wrong tree (anchors, pragmas and baseline keys all diverging)."""
    proc = _cli("--ir", "--root", str(tmp_path))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "IMPORTED package" in proc.stderr


def test_write_baseline_prunes_fixed_entries_and_reports(tmp_path):
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    bl = str(root / ".yfmlint-baseline.json")
    assert len(load_baseline(bl)) == 1

    # fix the violation: the next --write-baseline must PRUNE the entry and
    # say why, not silently shrink
    (root / "yieldfactormodels_jl_tpu" / "serving" / "gw.py").write_text(
        _CLEAN + "\n\n# fixed\n")
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0
    assert "pruned" in proc.stdout
    assert "no longer fires (fixed)" in proc.stdout
    assert load_baseline(bl) == set()


def test_write_baseline_is_idempotent_and_keeps_foreign_tier(tmp_path):
    """Still-firing grandfathered entries survive a rewrite (they land in
    ``baselined``, not ``findings`` — dropping them would empty the baseline
    on the second consecutive write), and entries only the OTHER tier can
    observe (IR YFM10x keys during an AST run) are preserved verbatim."""
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    bl = str(root / ".yfmlint-baseline.json")
    entries = load_baseline(bl)
    assert len(entries) == 1

    # seed an IR-tier key: the AST rewrite cannot re-observe it and must
    # carry it, pruning nothing
    ir_key = "YFM101::yieldfactormodels_jl_tpu/serving/gw.py::1"
    save_baseline(bl, [], extra_keys=entries | {ir_key})
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint: pruned" not in proc.stdout
    assert load_baseline(bl) == entries | {ir_key}

    # third write, unchanged tree: still a fixed point
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0
    assert load_baseline(bl) == entries | {ir_key}

    # a malformed key is NOT foreign — it matches no finding in any tier,
    # and the plain-run stale warning promises the rewrite prunes it
    bad_key = "YFM008:wrong:separator"
    save_baseline(bl, [], extra_keys=entries | {ir_key, bad_key})
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "malformed" in proc.stdout
    assert load_baseline(bl) == entries | {ir_key}

    # staleness is tier-agnostic: a foreign (IR) key whose file is gone
    # matches no finding in ANY tier — the rewrite prunes it as promised
    stale_ir = "YFM101::yieldfactormodels_jl_tpu/serving/deleted.py::5"
    save_baseline(bl, [], extra_keys=entries | {ir_key, stale_ir})
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no longer exists" in proc.stdout
    assert load_baseline(bl) == entries | {ir_key}


def test_write_baseline_refused_while_run_has_errors(tmp_path):
    """A module that fails to parse fires nothing — rewriting the baseline
    then would drop its grandfathered entries as 'fixed'."""
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    bl = str(root / ".yfmlint-baseline.json")
    before = load_baseline(bl)
    (root / "yieldfactormodels_jl_tpu" / "serving" / "gw.py").write_text(
        "def broken(:\n")
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "refusing --write-baseline" in proc.stderr
    assert load_baseline(bl) == before  # untouched


def test_stale_baseline_entries_warn_on_plain_runs(tmp_path):
    root = _scaffold(tmp_path, serving_body=_BAD_SERVING)
    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # delete the violating module: the baseline entry now points nowhere —
    # a plain run must SAY so (and stay green: nothing fires), and a
    # rewrite must prune it with the file-gone reason
    (root / "yieldfactormodels_jl_tpu" / "serving" / "gw.py").unlink()
    proc = _cli("--root", str(root), "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["counts"]["findings"] == 0
    assert len(data["stale_baseline"]) == 1
    assert "no longer exists" in next(iter(data["stale_baseline"].values()))
    assert "stale baseline entry" in proc.stderr

    proc = _cli("--root", str(root), "--write-baseline")
    assert proc.returncode == 0
    assert "pruned" in proc.stdout and "no longer exists" in proc.stdout
    assert load_baseline(str(root / ".yfmlint-baseline.json")) == set()
