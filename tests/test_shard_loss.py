"""Shard-loss fault domains (serving/store.py + journal.py, DESIGN §24).

Acceptance coverage for the failure-domain tentpole:

- the ``shard_lost`` chaos seam mid-batch: the killed shard's requests
  answer degraded from the banked last-good, the rebuild wave runs at the
  batch boundary, and every subsequent round is fully accepted with the
  resident state BIT-IDENTICAL to a fault-free twin fed the same accepted
  stream;
- journal replay: a rebuild whose best surviving source lags the accepted
  stream re-drives the journal suffix through the donated update program —
  bit-parity again, with the replay ledgered;
- the ``journal_gap`` seam: a dropped append is DETECTED, the key
  stale-flags at rebuild (never replays silently wrong) and STAYS stale
  through later accepts until a refit re-bases it, while its shard
  siblings heal;
- blast radius: the fleet routes around a rebuilding member, the
  subscription hub full-recomputes affected fans, ``health()`` carries the
  recovery ledger and the armed chaos seams' hit/fired counters;
- redistribution: a lost shard's keys re-home onto surviving capacity,
  overflow parking to the tiered store's warm tier stale-aware;
- the closed-loop recovery harness (``robustness.loadgen.
  run_recovery_load``): kills under sustained gateway load finish with
  ZERO lost accepted updates.
"""

import dataclasses

import numpy as np
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import serving
from yieldfactormodels_jl_tpu.orchestration import chaos
from yieldfactormodels_jl_tpu.robustness import loadgen
from yieldfactormodels_jl_tpu.serving.snapshot import SnapshotRegistry

MATS = tuple(np.array([3, 6, 12, 24, 60, 120]) / 12.0)
T_PANEL = 48
T_ORIGIN = 40

LATTICE = serving.BucketLattice(horizons=(4,), batch_sizes=(1, 4),
                                scenario_counts=(4,),
                                update_batch_sizes=(1, 4))


@pytest.fixture(scope="module")
def dns_setup():
    rng = np.random.default_rng(11)
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T_PANEL)
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    return spec, p, data, snap


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.reset()
    yield
    chaos.reset()


def _snap_for(snap, task_id):
    return dataclasses.replace(
        snap, meta=dataclasses.replace(snap.meta, task_id=task_id))


def _store(spec, snap, n_keys, **kw):
    kw.setdefault("n_shards", 2)
    kw.setdefault("shard_capacity", 4)
    store = serving.ShardedStateStore(spec, engine="univariate",
                                     lattice=LATTICE, **kw)
    keys = store.register_many(_snap_for(snap, i) for i in range(n_keys))
    return store, keys


def _assert_bit_identical(s1, s2, key):
    assert s1.meta.version == s2.meta.version, key
    assert np.array_equal(np.asarray(s1.beta), np.asarray(s2.beta)), key
    assert np.array_equal(np.asarray(s1.P), np.asarray(s2.P)), key


# ---------------------------------------------------------------------------
# the headline invariant: chaos kill -> rebuild -> bit-parity vs twin
# ---------------------------------------------------------------------------

def test_chaos_shard_lost_rebuilds_bit_identical_to_twin(dns_setup):
    """A ``shard_lost`` seam fired mid-batch drops one shard's resident
    arrays.  The killed batch's lost-shard requests answer degraded from
    the bank (never an exception), the rebuild wave runs at the batch
    boundary, and after two more fully-accepted rounds every key is
    bit-identical to a fault-free twin fed the same ACCEPTED stream."""
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 6)
    twin, _ = _store(spec, snap, 6)
    curves = [data[:, T_ORIGIN + t] for t in range(6)]

    for t in range(3):
        items = [(k, curves[t]) for k in keys]
        r1 = store.update_batch(items, dates=[f"d{t}"] * len(keys))
        twin.update_batch(items, dates=[f"d{t}"] * len(keys))
        assert all(x.get("error") is None and not x.get("degraded")
                   for x in r1)

    chaos.configure("shard_lost:@1")
    items = [(k, curves[3]) for k in keys]
    r1 = store.update_batch(items, dates=["d3"] * len(keys))
    obs = chaos.observe()["shard_lost"]
    assert obs["fired"] == 1 and obs["hits"] >= 1
    chaos.reset()     # process-global counters: disarm before the twin runs

    deg = [(x, k) for x, (k, _) in zip(r1, items) if x.get("degraded")]
    acc = [k for x, (k, _) in zip(r1, items)
           if x.get("error") is None and not x.get("degraded")]
    assert deg, "the killed shard's requests must answer degraded"
    assert acc, "the surviving shard's requests must accept"
    for x, k in deg:
        # degraded-from-bank: last-good answer, stale-flagged, no error
        assert x.get("error") is None and x.get("stale")
    # the twin is fed ONLY what the store accepted (the parity contract)
    twin.update_batch([(k, curves[3]) for k in acc],
                      dates=["d3"] * len(acc))

    rec = store.health()["recovery"]
    assert rec["lost_shards"] == 1 and rec["rebuilt_shards"] == 1
    assert rec["gapped_keys"] == 0 and not store.rebuilding

    for t in (4, 5):
        items = [(k, curves[t]) for k in keys]
        for st in (store, twin):
            r = st.update_batch(items, dates=[f"d{t}"] * len(keys))
            assert all(x.get("error") is None and not x.get("degraded")
                       for x in r)
    for k in keys:
        _assert_bit_identical(store.snapshot_of(k), twin.snapshot_of(k), k)


def test_rebuild_replays_journal_suffix_bit_identical(dns_setup):
    """The replay path proper: roll every bank entry back to its round-0
    state (a lagging rebuild source), kill shard 0 explicitly, and the
    rebuild must re-drive the journaled accepts v2..v4 through the donated
    update program — post-replay state bit-identical to the fault-free
    twin, replays ledgered."""
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 6)
    twin, _ = _store(spec, snap, 6)
    curves = [data[:, T_ORIGIN + t] for t in range(4)]

    bank0 = {}
    for t in range(4):
        items = [(k, curves[t]) for k in keys]
        r1 = store.update_batch(items)
        twin.update_batch(items)
        assert all(x.get("error") is None and not x.get("degraded")
                   for x in r1)
        if t == 0:
            bank0 = {k: (store._bank[k][0].copy(), store._bank[k][1].copy(),
                         store._bank_ver[k]) for k in keys}

    with store._lock:
        for k in keys:
            b, c, v = bank0[k]
            store._bank[k] = (b, c)
            store._bank_ver[k] = v
    store.mark_shard_lost(0, "replay test")
    assert store.rebuilding
    rebuilt = store.recover_lost_shards()
    assert rebuilt == [0] and not store.rebuilding

    rec = store.health()["recovery"]
    n_lost_keys = sum(1 for k in keys if store.shard_of(k) == 0)
    assert n_lost_keys >= 1
    # every lost key replayed its v2..v4 suffix (3 records each)
    assert rec["replayed_updates"] == 3 * n_lost_keys
    assert rec["gapped_keys"] == 0 and rec["mttr_p50_s"] is not None
    for k in keys:
        _assert_bit_identical(store.snapshot_of(k), twin.snapshot_of(k), k)


def test_journal_gap_stale_flags_instead_of_wrong_replay(dns_setup):
    """A ``journal_gap``-dropped append makes exactly the affected key
    unreplayable: at rebuild it parks on its (rolled-back) bank record,
    stale-flagged and ledgered, and STAYS stale through later accepted
    updates — only a refit heals it — while its siblings replay clean."""
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 4)
    curves = [data[:, T_ORIGIN + t] for t in range(4)]

    store.update_batch([(k, curves[0]) for k in keys])
    bank1 = {k: (store._bank[k][0].copy(), store._bank[k][1].copy(),
                 store._bank_ver[k]) for k in keys}
    chaos.configure("journal_gap:@2")      # drop one append in round 2
    store.update_batch([(k, curves[1]) for k in keys])
    assert chaos.fired("journal_gap") == 1
    chaos.reset()
    store.update_batch([(k, curves[2]) for k in keys])

    gapped = [k for k in keys if store.journal.is_gapped(k)]
    assert len(gapped) == 1

    with store._lock:
        for k in keys:
            b, c, v = bank1[k]
            store._bank[k] = (b, c)
            store._bank_ver[k] = v
    store.mark_shard_lost(0)
    store.mark_shard_lost(1)
    store.recover_lost_shards()

    h = store.health()
    assert h["recovery"]["gapped_keys"] == 1
    # no replay ran for the gapped key: its bank stays at the rolled-back
    # source version (the meta keeps the accepted-stream version — the
    # stale flag is the loud signal for the divergence)
    assert store._bank_ver[gapped[0]] == bank1[gapped[0]][2]
    assert gapped[0] in store._stale
    for k in keys:
        if k not in gapped:
            assert k not in store._stale
            assert store.snapshot_of(k).meta.version == 3

    # the gap-stale flag survives later ACCEPTED updates: the state
    # diverged from the never-lost run, and only a refit re-bases it
    r = store.update_batch([(k, curves[3]) for k in keys])
    flags = {k: x.get("stale") for x, (k, _) in
             zip(r, [(k, None) for k in keys])}
    assert flags[gapped[0]] is True
    assert all(not flags[k] for k in keys if k not in gapped)
    assert gapped[0] in store._stale

    # refit heals: a fresh authoritative state re-bases the journal
    store.publish_refit(gapped[0], p, history=data[:, :T_ORIGIN])
    assert gapped[0] not in store._stale
    assert not store.journal.is_gapped(gapped[0])
    r = store.update_batch([(gapped[0], curves[3])])
    assert not r[0].get("stale") and r[0].get("error") is None


# ---------------------------------------------------------------------------
# blast radius: fleet routing, hub recompute, health/chaos observability
# ---------------------------------------------------------------------------

def test_fleet_routes_around_rebuilding_member(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 4)
    fleet = serving.StoreFleet([store])
    store.update_batch([(k, data[:, T_ORIGIN]) for k in keys])
    store.mark_shard_lost(0)
    assert fleet.rebuilding
    assert fleet.health()["status"] == "rebuilding"
    lost_key = next(k for k in keys if store.shard_of(k) == 0)
    # a lost-shard read serves the banked last-good instead of raising
    sv = fleet.snapshot_of(lost_key)
    assert sv.meta.version >= 1
    rebuilt = fleet.recover_lost_shards()
    assert rebuilt == {spec.model_string: [0]}
    assert not fleet.rebuilding and fleet.health()["status"] == "ok"


def test_hub_full_recomputes_after_rebuild(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 4)
    hub = serving.ScenarioStreamHub(store)
    hub.subscribe(keys[0])
    hub.subscribe(keys[1])
    store.update_batch([(k, data[:, T_ORIGIN]) for k in keys])
    before = hub.counters.full_recomputes
    store.mark_shard_lost(0, "hub blast radius")
    store.recover_lost_shards()
    # the rebuild listener broke the affected delta chains: full recompute
    assert hub.counters.full_recomputes > before
    out = hub.fan(keys[0])
    assert not out.get("degraded", False)


def test_health_carries_recovery_ledger_and_chaos_counters(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 2)
    h = store.health()
    assert {"lost_shards", "rebuilt_shards", "rehomed_keys", "parked_keys",
            "replayed_updates", "gapped_keys",
            "listener_errors"} <= set(h["recovery"])
    svc = serving.YieldCurveService(snap)
    chaos.configure("nan_curve:@100")
    rep = svc.health()
    assert rep["chaos"]["nan_curve"]["trigger"] == "@100"
    assert rep["chaos"]["nan_curve"]["hits"] == 0
    assert rep["chaos"]["nan_curve"]["fired"] == 0


def test_mark_shard_lost_validates_range(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 2)
    with pytest.raises(serving.ServingError):
        store.mark_shard_lost(99)
    with pytest.raises(serving.ServingError):
        store.mark_shard_lost(-1)
    # idempotent on an already-lost shard
    store.mark_shard_lost(0)
    store.mark_shard_lost(0)
    assert store.health()["recovery"]["lost_shards"] == 1


# ---------------------------------------------------------------------------
# redistribution: re-home on surviving capacity, warm-park the overflow
# ---------------------------------------------------------------------------

def test_tiered_redistribute_parks_overflow_warm(dns_setup):
    """A full 2x2 hot mesh loses shard 0 with ``redistribute=True``: no
    reset shard to re-home onto and no surviving hot capacity, so the lost
    keys PARK to the warm tier at their source version — every key keeps
    answering, and the next update round heals via normal promotion."""
    spec, p, data, snap = dns_setup

    def mk(reg):
        return serving.TieredStateStore(
            spec, n_shards=2, shard_capacity=2, engine="univariate",
            lattice=LATTICE, registry=reg, warm_capacity=8)

    ts, twin = mk(SnapshotRegistry()), mk(SnapshotRegistry())
    keys = ts.register_many([_snap_for(snap, i) for i in range(6)])
    twin.register_many([_snap_for(snap, i) for i in range(6)])
    curves = [data[:, T_ORIGIN + t] for t in range(4)]
    for t in range(3):
        items = [(k, curves[t]) for k in keys]
        ts.update_batch(items)
        twin.update_batch(items)

    ts.mark_shard_lost(0, "redistribute test")
    rebuilt = ts.recover_lost_shards(redistribute=True)
    assert rebuilt == [0]
    rec = ts.health()["recovery"]
    assert rec["parked_keys"] >= 1
    assert rec["parked_keys"] + rec["rehomed_keys"] >= 2
    assert rec["gapped_keys"] == 0

    # parked clean (suffix empty at park version): not stale, still serving
    for k in keys:
        _assert_bit_identical(ts.snapshot_of(k), twin.snapshot_of(k), k)
    # the next round: parked keys degrade from their tier record until a
    # promotion wave lands (the over-capacity working set keeps churning —
    # same-wave demotion errors are the tiered store's pre-existing
    # steady-state behavior, fault-free control included, NOT a rebuild
    # regression), and crucially NO key is lost: every one still reads
    pre = {k: ts.snapshot_of(k).meta.version for k in keys}
    ts.update_batch([(k, curves[3]) for k in keys])
    for k in keys:
        assert ts.snapshot_of(k).meta.version >= pre[k]


# ---------------------------------------------------------------------------
# the closed-loop harness: kills under load, zero lost accepted updates
# ---------------------------------------------------------------------------

def test_run_recovery_load_zero_lost_accepted(dns_setup):
    spec, p, data, snap = dns_setup
    store, keys = _store(spec, snap, 6)
    twin, _ = _store(spec, snap, 6)
    gw = serving.ShardedGateway(store, queue_max=1024, queue_age_ms=0.0)
    curves = data[:, T_ORIGIN:T_ORIGIN + 6]
    rep = loadgen.run_recovery_load(
        gw, store, twin, curves, keys, rounds=8,
        kill_at=[(2, 0)], chaos_kill_rounds=[5])
    assert rep.kills == 2 and rep.rebuilds >= 2
    assert rep.updates_offered == 8 * len(keys)
    assert rep.errors == 0 and rep.shed == 0
    assert rep.updates_degraded >= 1          # the killed rounds degrade
    assert rep.lost_accepted == 0             # THE acceptance number
    assert rep.parity_checked == len(keys)
    assert rep.mttr_p50_s is not None and rep.mttr_p99_s >= rep.mttr_p50_s
    d = rep.to_dict()
    assert d["lost_accepted"] == 0 and 0.0 < d["degraded_rate"] < 1.0
