"""Iterated-SLR engine (ops/slr_scan.py, docs/DESIGN.md §19) acceptance.

Oracle-backed parity of the ``"slr"`` engine and its ``"ekf"`` linearization
rule against the independent NumPy loops (tests/oracle.iterated_slr_filter —
sequential affine pass A + chunked exact-EKF refinement, a DIFFERENT
algebraic route than the engine's Woodbury elements + combine tree), the
``"ukf"`` sigma-point rule against its own oracle pair
(oracle.iterated_sigma_slr_filter / oracle.sigma_point_filter — textbook
full-Ψ regression vs the engine's triangular shortcut), the
fixed-point contract against the sequential EKF (oracle.ekf_tvl_loglik /
oracle.kalman_filter_loglik), NaN-panel semantics, K-sweep convergence
monotonicity, grad parity, trace counters, the introspection seam
(config.engines_for / tree_engine_for) with the api dispatch built on it,
the ladder's slr rescue rung, the time-sharded objective for TVλ, the
serving ``refilter()`` on a TVλ snapshot, and the tree-composed Newton
tangents pinned against oracle.fd_hessian.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import config
from yieldfactormodels_jl_tpu.models import api
from yieldfactormodels_jl_tpu.models.params import untransform_params
from yieldfactormodels_jl_tpu.ops import slr_scan, univariate_kf
from yieldfactormodels_jl_tpu.robustness import ladder, taxonomy as tax

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)


def _tvl_case(rng, T=160, seed_panel=True):
    spec, _ = yfm.create_model("TVλ", MATS, float_type="float64")
    p = oracle.stable_tvl_params(spec)
    if seed_panel:
        data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T, lam=0.5)
    else:
        data = 0.4 * rng.standard_normal((len(MATS), T)) + 4.0
    return spec, p, np.asarray(data, dtype=np.float64)


def _tvl_pieces(spec, p):
    Ms = spec.state_dim
    C = np.zeros((Ms, Ms))
    rows, cols = spec.chol_indices
    a, _ = spec.layout["chol"]
    for k, (r, c) in enumerate(zip(rows, cols)):
        C[r, c] = p[a + k]
    lo, hi = spec.layout["delta"]
    delta = np.asarray(p[lo:hi], dtype=np.float64)
    lo, hi = spec.layout["phi"]
    Phi = np.asarray(p[lo:hi], dtype=np.float64).reshape(Ms, Ms)
    return Phi, delta, C @ C.T, float(p[spec.layout["obs_var"][0]])


# ---------------------------------------------------------------------------
# the introspection seam (config.engines_for) and registries
# ---------------------------------------------------------------------------

def test_engine_registries_and_applicability():
    """"slr" is a first-class KALMAN_ENGINES entry, "ekf" its registered
    linearization rule, and engines_for/tree_engine_for agree with the
    family structure (the seam every dispatch site consults)."""
    assert "slr" in config.KALMAN_ENGINES
    assert config.SLR_ENGINES == ("ekf", "ukf")
    dns, _ = yfm.create_model("1C", MATS, float_type="float64")
    tvl, _ = yfm.create_model("TVλ", MATS, float_type="float64")
    ns, _ = yfm.create_model("NS", MATS, float_type="float64")
    assert config.engines_for(dns) == config.KALMAN_ENGINES
    assert config.engines_for(tvl) == tuple(
        e for e in config.KALMAN_ENGINES if e != "assoc")
    assert config.engines_for(ns) == ()
    assert config.tree_engine_for(dns) == "assoc"
    assert config.tree_engine_for(tvl) == "slr"
    assert config.tree_engine_for(ns) is None


def test_api_dispatch_validation_consults_engines_for(rng):
    """Explicit engine= outside engines_for(spec) raises naming the valid
    set; a process-wide default that does not apply falls back to the
    sequential default (never an error on a call that chose nothing)."""
    spec, p, data = _tvl_case(rng, T=60)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    with pytest.raises(ValueError, match="engines_for"):
        api.get_loss(spec, pj, dj, engine="assoc")
    u = float(api.get_loss(spec, pj, dj, engine="univariate"))
    try:
        yfm.set_kalman_engine("assoc")   # valid globally, not for TVλ
        v = float(api.get_loss(spec, pj, dj))
    finally:
        yfm.set_kalman_engine("univariate")
    np.testing.assert_allclose(v, u, rtol=1e-12)


def test_t_switch_upgrades_tvl_to_slr(rng, monkeypatch):
    spec, p, data = _tvl_case(rng, T=100)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    calls = []
    real = slr_scan.get_loss
    monkeypatch.setattr(slr_scan, "get_loss",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    try:
        config.set_loglik_t_switch(64)
        api.get_loss(spec, pj, dj)                 # T=100 >= 64 → slr
        assert len(calls) == 1
        api.get_loss(spec, pj, dj[:, :50])         # short → sequential
        assert len(calls) == 1
        api.get_loss(spec, pj, dj, engine="univariate")  # explicit wins
        assert len(calls) == 1
    finally:
        config.set_loglik_t_switch(0)


# ---------------------------------------------------------------------------
# oracle parity — the iterated semantics AND the EKF fixed point
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sweeps", [1, 2, 3])
def test_slr_oracle_parity_iterated_semantics(sweeps, rng):
    """Engine vs tests/oracle.iterated_slr_filter at MATCHING (sweeps,
    chunk) — pins the iterated two-scale semantics themselves (tree-composed
    pass A + chunked exact refinement), not just the fixed point, at an
    adversarially small chunk where intermediate sweeps still differ from
    the EKF."""
    spec, p, data = _tvl_case(rng, T=200)
    data[:, 90:95] = np.nan
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    *_, want = oracle.iterated_slr_filter(Phi, delta, Om, ov,
                                          np.asarray(MATS), data,
                                          sweeps=sweeps, chunk=32)
    got = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                  sweeps=sweeps, chunk=32,
                                  linearization="ekf"))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_slr_oracle_parity_filtered_moments(rng):
    """The filtered trajectories (the serving re-filter surface) against the
    oracle's, element-wise."""
    spec, p, data = _tvl_case(rng, T=150)
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    betas, Ps, _, _ = oracle.iterated_slr_filter(Phi, delta, Om, ov,
                                                 np.asarray(MATS), data,
                                                 sweeps=2, chunk=32)
    m, P = slr_scan.filter_means_covs(spec, jnp.asarray(p),
                                      jnp.asarray(data), sweeps=2, chunk=32)
    np.testing.assert_allclose(np.asarray(m), betas, atol=1e-9)
    np.testing.assert_allclose(np.asarray(P), Ps, atol=1e-9)


def test_slr_matches_sequential_ekf_fixed_point(rng):
    """The engine at its DEFAULTS against the sequential EKF oracle
    (oracle.ekf_tvl_loglik): exact to float rounding for T <= chunk (one
    chunk covers the panel), and at parity tolerance on a multi-chunk panel
    — with one extra sweep tightening it by orders of magnitude (the ρ^L
    contraction)."""
    spec, p, data = _tvl_case(rng, T=120)
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    want = oracle.ekf_tvl_loglik(Phi, delta, Om, ov, np.asarray(MATS), data)
    got = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    np.testing.assert_allclose(got, want, rtol=1e-10)

    spec, p, data = _tvl_case(rng, T=1100)
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    want = oracle.ekf_tvl_loglik(Phi, delta, Om, ov, np.asarray(MATS), data)
    got2 = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    np.testing.assert_allclose(got2, want, rtol=1e-6)
    got3 = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                   sweeps=3))
    assert abs(got3 - want) < abs(got2 - want) or got2 == want
    np.testing.assert_allclose(got3, want, rtol=1e-9)


def test_slr_constant_z_collapses_to_exact_filter(rng):
    """Constant-measurement families collapse to one sweep whose refinement
    IS the exact filter: parity against the NumPy KF oracle and the
    sequential engine at float rounding, any K."""
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=300)
    Z = oracle.dns_loadings(float(p[spec.layout["gamma"][0]]),
                            np.asarray(MATS))
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    want = oracle.kalman_filter_loglik(Z, Phi, delta, Om, ov, data)
    got = float(api.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                             engine="slr"))
    np.testing.assert_allclose(got, want, rtol=1e-9)
    seq = float(univariate_kf.get_loss(spec, jnp.asarray(p),
                                       jnp.asarray(data)))
    k5 = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                 sweeps=5))
    np.testing.assert_allclose(got, seq, rtol=1e-12)
    np.testing.assert_allclose(k5, got, rtol=1e-12)


def test_slr_sweep_convergence_monotone(rng):
    """The K-sweep gap to the sequential EKF shrinks monotonically at an
    adversarially small chunk (each sweep contracts boundary errors by the
    chunk's forgetting factor)."""
    spec, p, data = _tvl_case(rng, T=160)
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    want = oracle.ekf_tvl_loglik(Phi, delta, Om, ov, np.asarray(MATS), data)
    gaps = [abs(float(slr_scan.get_loss(spec, jnp.asarray(p),
                                        jnp.asarray(data), sweeps=k,
                                        chunk=16)) - want)
            for k in (1, 2, 3, 4)]
    assert all(g1 > g2 for g1, g2 in zip(gaps, gaps[1:])), gaps
    # the contraction factor is panel-dependent (ρ^16 here); monotone
    # decrease plus an order of magnitude over three extra sweeps is the
    # stable property
    assert gaps[-1] < 0.1 * gaps[0]


def test_slr_nan_panels(rng):
    """Whole/partial-NaN panels: a partially-quoted column is a pure
    prediction step (identical to dropping the whole column — the offline
    convention every engine shares); an all-NaN panel carries the
    MISSING_ALL_OBS code."""
    spec, p, data = _tvl_case(rng, T=120)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    partial = data.copy()
    partial[0, 60] = np.nan             # one element missing
    whole = data.copy()
    whole[:, 60] = np.nan               # whole column missing
    a = float(slr_scan.get_loss(spec, pj, jnp.asarray(partial), chunk=32))
    b = float(slr_scan.get_loss(spec, pj, jnp.asarray(whole), chunk=32))
    np.testing.assert_allclose(a, b, rtol=1e-12)
    # sequential parity at the single-chunk configuration (exact; the
    # multi-chunk K-gap tolerances live in the fixed-point tests above)
    seq = float(univariate_kf.get_loss(spec, pj, jnp.asarray(whole)))
    one = float(slr_scan.get_loss(spec, pj, jnp.asarray(whole)))
    np.testing.assert_allclose(one, seq, rtol=1e-10)
    all_nan = jnp.full((len(MATS), 50), jnp.nan, dtype=jnp.float64)
    ll, code = slr_scan.get_loss_coded(spec, pj, all_nan)
    assert float(ll) == 0.0
    assert "MISSING_ALL_OBS" in tax.decode(int(code))


def test_slr_taxonomy_codes(rng):
    """Non-finite slr losses carry decoded causes like every other engine
    (robustness/taxonomy.py channel)."""
    spec, p, data = _tvl_case(rng, T=80)
    dj = jnp.asarray(data)
    ll, code = slr_scan.get_loss_coded(spec, jnp.asarray(p), dj)
    assert np.isfinite(float(ll)) and int(code) == tax.OK
    bad = p.copy()
    bad[spec.layout["obs_var"][0]] = -10.0
    ll, code = slr_scan.get_loss_coded(spec, jnp.asarray(bad), dj)
    assert float(ll) == -np.inf and tax.decode(code)
    nanp = p.copy()
    nanp[0] = np.nan
    _, code = slr_scan.get_loss_coded(spec, jnp.asarray(nanp), dj)
    assert "TRANSFORM_OVERFLOW" in tax.decode(code)
    _, code = slr_scan.get_loss_coded(spec, jnp.asarray(p), dj, 5, 6)
    assert "MISSING_ALL_OBS" in tax.decode(code)


def test_slr_psd_floor_noop_at_stable_point(rng):
    """psd_floor (the stabilized recovery surface) is a no-op at a healthy
    point — projection only clips what was already indefinite."""
    spec, p, data = _tvl_case(rng, T=90)
    a = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    s = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                psd_floor=ladder.SQRT_RESCUE_FLOOR))
    np.testing.assert_allclose(s, a, rtol=1e-9)


def test_slr_validation_errors(rng):
    spec, p, data = _tvl_case(rng, T=40)
    with pytest.raises(ValueError, match="linearization"):
        slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                          linearization="sigma-point")
    with pytest.raises(ValueError, match="sweeps"):
        slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data), sweeps=0)
    with pytest.raises(ValueError, match="prefix"):
        slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                          prefix="zigzag")
    ns, _ = yfm.create_model("NS", MATS, float_type="float64")
    with pytest.raises(ValueError, match="Kalman family"):
        slr_scan.get_loss(ns, jnp.zeros(ns.n_params), jnp.asarray(data))


# ---------------------------------------------------------------------------
# the "ukf" linearization rule — sigma-point SLR (registry-selected)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sweeps", [1, 2, 3])
def test_ukf_oracle_parity_iterated_semantics(sweeps, rng):
    """Engine under ``linearization="ukf"`` vs tests/oracle.
    iterated_sigma_slr_filter at MATCHING (sweeps, chunk) — the oracle
    regresses the full Ψ = Σ wᵢ(χᵢ−m)(h(χᵢ)−μ)ᵀ statistic against P where
    the engine collapses it to a triangular solve against L, so agreement
    pins the sigma-point statistics and the combine tree, not a
    transliteration."""
    spec, p, data = _tvl_case(rng, T=200)
    data[:, 90:95] = np.nan
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    *_, want = oracle.iterated_sigma_slr_filter(Phi, delta, Om, ov,
                                                np.asarray(MATS), data,
                                                sweeps=sweeps, chunk=32)
    got = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                  sweeps=sweeps, chunk=32,
                                  linearization="ukf"))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_ukf_oracle_parity_filtered_moments(rng):
    """The sigma-point rule's filtered trajectories against the oracle's."""
    spec, p, data = _tvl_case(rng, T=150)
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    betas, Ps, _, _ = oracle.iterated_sigma_slr_filter(Phi, delta, Om, ov,
                                                       np.asarray(MATS), data,
                                                       sweeps=2, chunk=32)
    m, P = slr_scan.filter_means_covs(spec, jnp.asarray(p),
                                      jnp.asarray(data), sweeps=2, chunk=32,
                                      linearization="ukf")
    np.testing.assert_allclose(np.asarray(m), betas, atol=1e-9)
    np.testing.assert_allclose(np.asarray(P), Ps, atol=1e-9)


def test_ukf_matches_sequential_sigma_point_fixed_point(rng):
    """The "ukf" rule at its defaults against the sequential
    statistically-linearized filter oracle (oracle.sigma_point_filter) — the
    acceptance contract: K=2 within 1e-6 relative on a multi-chunk panel,
    K=3 tightening it (the ρ^L contraction), and the single-chunk sweep
    exact to float rounding."""
    spec, p, data = _tvl_case(rng, T=500)
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    want = oracle.sigma_point_filter(Phi, delta, Om, ov, np.asarray(MATS),
                                     data)[-1]
    one = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                  sweeps=1, chunk=500, linearization="ukf"))
    np.testing.assert_allclose(one, want, rtol=1e-10)

    spec, p, data = _tvl_case(rng, T=1100)
    Phi, delta, Om, ov = _tvl_pieces(spec, p)
    want = oracle.sigma_point_filter(Phi, delta, Om, ov, np.asarray(MATS),
                                     data)[-1]
    got2 = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                   linearization="ukf"))
    np.testing.assert_allclose(got2, want, rtol=1e-6)
    got3 = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                   sweeps=3, linearization="ukf"))
    assert abs(got3 - want) < abs(got2 - want) or got2 == want
    np.testing.assert_allclose(got3, want, rtol=1e-9)


def test_ukf_grad_parity_vs_sequential_sigma_point(rng):
    """The default-K "ukf" gradient against the single-chunk sequential
    sigma-point recursion's (the rule's own exact reference — same
    linearization, no chunk boundaries)."""
    spec, p, data = _tvl_case(rng, T=500)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    g_seq = np.asarray(jax.grad(lambda q: slr_scan.get_loss(
        spec, q, dj, sweeps=1, chunk=500, linearization="ukf"))(pj))
    g2 = np.asarray(jax.grad(lambda q: slr_scan.get_loss(
        spec, q, dj, linearization="ukf"))(pj))
    assert np.isfinite(g2).all()
    assert np.linalg.norm(g2 - g_seq) / np.linalg.norm(g_seq) < 5e-6


def test_ukf_rules_disagree_then_both_converge(rng):
    """Non-vacuity for the registry: the two linearization rules produce
    genuinely different losses at K=1 on a curved panel (different
    surrogates), yet land on nearby fixed points as K grows (both are
    statistical linearizations of the same filter)."""
    spec, p, data = _tvl_case(rng, T=300)
    pj, dj = jnp.asarray(p), jnp.asarray(data)
    e1 = float(slr_scan.get_loss(spec, pj, dj, sweeps=1, chunk=32,
                                 linearization="ekf"))
    u1 = float(slr_scan.get_loss(spec, pj, dj, sweeps=1, chunk=32,
                                 linearization="ukf"))
    assert e1 != u1
    e4 = float(slr_scan.get_loss(spec, pj, dj, sweeps=4, chunk=32,
                                 linearization="ekf"))
    u4 = float(slr_scan.get_loss(spec, pj, dj, sweeps=4, chunk=32,
                                 linearization="ukf"))
    # distinct fixed points (EKF vs statistically-linearized filter — a few
    # percent apart on a curved panel), but the same filter to leading order
    assert np.isfinite(e4) and np.isfinite(u4)
    np.testing.assert_allclose(u4, e4, rtol=5e-2)


# ---------------------------------------------------------------------------
# grad parity + trace counters
# ---------------------------------------------------------------------------

def test_slr_grad_parity_vs_sequential_ekf(rng):
    """Differentiable end-to-end: the K=2 gradient (with the tree's entry
    states stop-gradient-ed — the ρ^L-damped adjoint cut) against the
    sequential EKF's, and K=3 tightening it by orders of magnitude."""
    spec, p, data = _tvl_case(rng, T=500)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    g_seq = np.asarray(jax.grad(
        lambda q: univariate_kf.get_loss(spec, q, dj))(pj))
    g2 = np.asarray(jax.grad(lambda q: slr_scan.get_loss(spec, q, dj))(pj))
    g3 = np.asarray(jax.grad(
        lambda q: slr_scan.get_loss(spec, q, dj, sweeps=3))(pj))
    assert np.isfinite(g2).all()
    n = np.linalg.norm(g_seq)
    assert np.linalg.norm(g2 - g_seq) / n < 5e-6
    assert np.linalg.norm(g3 - g_seq) / n < 1e-9


def test_slr_no_recompile_trace_counter(rng):
    """Same-shape repeat calls reuse ONE traced program; a different static
    configuration (sweeps) traces its own."""
    spec, p, data = _tvl_case(rng, T=96)
    dj, pj = jnp.asarray(data), jnp.asarray(p)
    fn = jax.jit(lambda q, d: slr_scan.get_loss(spec, q, d))
    slr_scan.reset_trace_counts()
    fn(pj, dj).block_until_ready()
    fn(pj * 1.001, dj).block_until_ready()
    fn(pj * 0.999, dj).block_until_ready()
    assert slr_scan.trace_counts["slr_filter"] == 1
    fn3 = jax.jit(lambda q, d: slr_scan.get_loss(spec, q, d, sweeps=3))
    fn3(pj, dj).block_until_ready()
    assert slr_scan.trace_counts["slr_filter"] == 2


# ---------------------------------------------------------------------------
# ladder: slr as the nonlinear long-panel rescue rung
# ---------------------------------------------------------------------------

def _dead_tvl_start(spec, p):
    bad = np.asarray(p, dtype=np.float64).copy()
    a, b = spec.layout["phi"]
    Ms = spec.state_dim
    Phi = 0.9 * np.eye(Ms)
    Phi[0, 1] = Phi[1, 0] = Phi[0, 2] = Phi[2, 0] = 0.8
    Phi[1, 2] = Phi[2, 1] = 0.8
    bad[a:b] = Phi.reshape(-1)
    return bad


@pytest.mark.slow
def test_ladder_slr_rung_rescues_long_tvl_panel(rng):
    """A dead TVλ start on a long panel (T >= ASSOC_RESCUE_MIN_T) is
    recovered by the slr rung — the nonlinear twin of the assoc rung — and
    the trace says so."""
    spec, p, data = _tvl_case(rng, T=ladder.ASSOC_RESCUE_MIN_T + 40)
    raw_bad = np.asarray(untransform_params(
        spec, jnp.asarray(_dead_tvl_start(spec, p))))
    tr = ladder.escalate(spec, data, raw_bad)
    assert [r.rung for r in tr.rungs] == ["scan", "slr"]
    assert tr.recovered and tr.rung == "slr" and tr.engine == "slr"
    assert np.isfinite(tr.ll)


def test_ladder_slr_rung_skipped_on_short_panels(rng):
    spec, p, data = _tvl_case(rng, T=60)
    raw_bad = np.asarray(untransform_params(
        spec, jnp.asarray(_dead_tvl_start(spec, p))))
    tr = ladder.escalate(spec, data, raw_bad)
    assert "slr" not in [r.rung for r in tr.rungs]
    assert tr.recovered and tr.rung == "sqrt"


# ---------------------------------------------------------------------------
# estimation: time-sharded objective for the nonlinear family
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_estimate_time_sharded_objective_tvl(rng):
    """estimate(objective="time_sharded") now covers TVλ: the iterated-SLR
    loss over the sharded time axis (refinement chunk = shard length — the
    aligned layout verified bit-identical to the unsharded engine) drives
    the same multi-start artifact as the vmap objective."""
    from yieldfactormodels_jl_tpu.estimation import optimize

    jax.clear_caches()   # this module is program-heavy; see conftest note
    spec, p, data = _tvl_case(rng, T=250)   # 250 % 8 != 0: ragged T works
    starts = np.stack([p, p * 0.995], axis=1)
    base = optimize.estimate(spec, data, starts, max_iters=15,
                             objective="vmap")
    ts = optimize.estimate(spec, data, starts, max_iters=15,
                           objective="time_sharded")
    assert np.isfinite(ts[1])
    # the time-sharded objective is the K=2 chunk-(T/8) surrogate, so the
    # two 15-iteration trajectories walk slightly different surfaces —
    # same basin, loose ll agreement (the bit-level sharded-vs-unsharded
    # parity is pinned separately below)
    np.testing.assert_allclose(ts[1], base[1], rtol=2e-2)


def test_time_sharded_loss_tvl_matches_unsharded_engine(rng):
    """The sharded program equals the UNSHARDED slr engine at the same
    (chunk, sweeps) bit-tight — sharding must not change the math (the
    misaligned-chunk layout MISCOMPILED under SPMD; this pins the aligned
    one)."""
    from yieldfactormodels_jl_tpu.parallel.mesh import make_mesh
    from yieldfactormodels_jl_tpu.parallel.time_parallel import (
        _pad_time, get_loss_time_sharded)

    spec, p, data = _tvl_case(rng, T=250)
    mesh = make_mesh(axis_name="time")
    n_dev = int(mesh.devices.size)
    par = float(get_loss_time_sharded(spec, p, data, mesh=mesh))
    padded = np.asarray(_pad_time(jnp.asarray(data), n_dev))
    chunk = padded.shape[1] // n_dev
    want = float(slr_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(padded),
                                   0, data.shape[1], prefix="interleaved",
                                   chunk=chunk))
    np.testing.assert_allclose(par, want, rtol=1e-12)


# ---------------------------------------------------------------------------
# serving: refilter() for TVλ snapshots
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_refilter_tvl_agrees_with_accumulated_updates(rng):
    """A TVλ service fed fully-quoted curves, then one SLR refilter: the
    rebuilt state matches the accumulated recursive EKF state at engine
    tolerance (the SLR fixed point IS the sequential EKF), version bumped,
    cadence reset."""
    from yieldfactormodels_jl_tpu.serving import (YieldCurveService,
                                                  freeze_snapshot)

    jax.clear_caches()   # this module is program-heavy; see conftest note
    spec, p, _ = _tvl_case(rng, T=8)
    T_cond, n_upd = 64, 240
    panel = oracle.simulate_dns_panel(rng, np.asarray(MATS),
                                      T=T_cond + n_upd, lam=0.5)
    svc = YieldCurveService(freeze_snapshot(spec, p, panel[:, :T_cond]))
    for t in range(T_cond, T_cond + n_upd):
        svc.update(t, panel[:, t])
    beta_acc = np.asarray(svc.snapshot.beta).copy()
    P_acc = np.asarray(svc.snapshot.P).copy()
    ll = svc.refilter(panel, date="rebuild")
    assert np.isfinite(ll)
    assert svc.version == n_upd + 1 and not svc.stale
    np.testing.assert_allclose(np.asarray(svc.snapshot.beta), beta_acc,
                               atol=1e-7)
    np.testing.assert_allclose(np.asarray(svc.snapshot.P), P_acc, atol=1e-7)
    assert svc._updates_since_refresh == 0


# ---------------------------------------------------------------------------
# Newton tangents on the tree (ops/newton.py × YFM_LOGLIK_T_SWITCH)
# ---------------------------------------------------------------------------

def test_newton_innovations_tree_matches_sequential(rng):
    """The assoc-assembled innovations provider equals the sequential one
    (values AND the Fisher quantities built from it) — the tree is an
    engine change, not a math change."""
    from yieldfactormodels_jl_tpu.ops import newton

    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=160)
    data[:, 70:74] = np.nan
    dj = jnp.asarray(data)
    raw = jnp.asarray(untransform_params(spec, jnp.asarray(p)))
    u = jnp.ones_like(raw) / np.sqrt(raw.shape[0])
    H_seq = np.asarray(newton.fisher_matrix(spec, raw, dj, 0, 160))
    h_seq = np.asarray(newton.fisher_hvp(spec, raw, u, dj, 0, 160))
    try:
        config.set_loglik_t_switch(1)       # every panel rides the tree
        H_tree = np.asarray(newton.fisher_matrix(spec, raw, dj, 0, 160))
        h_tree = np.asarray(newton.fisher_hvp(spec, raw, u, dj, 0, 160))
    finally:
        config.set_loglik_t_switch(0)
    np.testing.assert_allclose(H_tree, H_seq, rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(h_tree, h_seq, rtol=1e-9, atol=1e-9)


@pytest.mark.slow
def test_newton_tree_hvp_pinned_against_fd_oracle(rng):
    """The tree-composed exact HVP (api.get_loss dispatches the nll to the
    assoc engine under the T-switch) against the central-difference NumPy
    Hessian oracle — the same pin test_newton.py applies to the sequential
    recursion.  Both probes are jitted ONCE (one program each, hundreds of
    fast calls) — this module compiles many engine variants and XLA:CPU
    segfaults past ~200 accumulated programs (see conftest)."""
    from yieldfactormodels_jl_tpu.ops import newton

    jax.clear_caches()
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=120)
    dj = jnp.asarray(data)
    raw = np.asarray(untransform_params(spec, jnp.asarray(p)),
                     dtype=np.float64)
    nll_jit = jax.jit(lambda x: newton._clamped_nll(spec, x, dj, 0, 120))

    def nll_np(x):
        return float(nll_jit(jnp.asarray(x)))

    H_fd = oracle.fd_hessian(nll_np, raw, eps=1e-4)
    try:
        config.set_loglik_t_switch(1)
        hvp_jit = jax.jit(lambda u: newton.exact_hvp(
            spec, jnp.asarray(raw), u, dj, 0, 120))
        cols = [np.asarray(hvp_jit(jnp.asarray(e)))
                for e in np.eye(raw.shape[0])]
    finally:
        config.set_loglik_t_switch(0)
    H_tree = np.stack(cols, axis=1)
    scale = np.abs(H_fd).max()
    np.testing.assert_allclose(H_tree, H_fd, atol=5e-3 * scale)
