"""Parity tests for the fused Pallas particle-filter kernel (ops/pallas_pf).

The kernel runs in interpret mode on CPU under float64 (this suite), fed the
SAME noise arrays as ``particle_filter_loglik(..., noise=...)`` — the
common-noise contract makes both engines follow identical particle
trajectories, so agreement is elementwise-tight, not statistical.  Hardware
compilation and the f32 statistical criterion live in benchmarks/hw_verify.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.ops import sqrt_kf
from yieldfactormodels_jl_tpu.ops.pallas_pf import pf_loglik_batch
from yieldfactormodels_jl_tpu.ops.particle import particle_filter_loglik

from tests.test_afns import _afns5_params

P = 128  # one lane-tile of particles keeps interpret mode fast


def _setup(maturities, yields_panel, D=3, T=40, seed=0):
    spec, _ = create_model("AFNS5", tuple(maturities), float_type="float64")
    data = jnp.asarray(yields_panel[:, :T])
    p, *_ = _afns5_params(spec)
    rng = np.random.default_rng(seed)
    batch = np.tile(np.asarray(p), (D, 1))
    # jitter only the well-conditioned coordinates (decay drivers, δ): the
    # point is distinct trajectories per draw, not pathological inputs
    batch[:, 0:2] += 0.05 * rng.standard_normal((D, 2))
    batch[:, 18:23] += 0.05 * rng.standard_normal((D, 5))
    batch = jnp.asarray(batch)
    normals = jnp.asarray(rng.standard_normal((D, T - 1, P)))
    uniforms = jnp.asarray(rng.uniform(size=(D, T - 1)))
    return spec, data, batch, normals, uniforms


def _xla(spec, data, batch, normals, uniforms, **kw):
    return jax.vmap(
        lambda q, nz, u: particle_filter_loglik(
            spec, q, data, n_particles=P, noise=(nz, u), **kw)
    )(batch, normals, uniforms)


def test_pallas_pf_matches_xla_common_noise(maturities, yields_panel):
    spec, data, batch, nz, u = _setup(maturities, yields_panel)
    want = np.asarray(_xla(spec, data, batch, nz, u))
    got = np.asarray(pf_loglik_batch(spec, batch, data, nz, u))
    assert np.all(np.isfinite(want))
    np.testing.assert_allclose(got, want, rtol=1e-9)


@pytest.mark.parametrize("th", [0.0, 1.5])
def test_pallas_pf_resample_extremes(maturities, yields_panel, th):
    """th=0 never resamples; th=1.5 resamples every contributing step."""
    spec, data, batch, nz, u = _setup(maturities, yields_panel, D=2)
    want = np.asarray(_xla(spec, data, batch, nz, u, ess_threshold=th))
    got = np.asarray(pf_loglik_batch(spec, batch, data, nz, u,
                                     ess_threshold=th))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_pallas_pf_nan_column_predict_only(maturities, yields_panel):
    spec, data, batch, nz, u = _setup(maturities, yields_panel, D=2)
    data = data.at[:, 7].set(jnp.nan)
    want = np.asarray(_xla(spec, data, batch, nz, u))
    got = np.asarray(pf_loglik_batch(spec, batch, data, nz, u))
    assert np.all(np.isfinite(want))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_pallas_pf_collapse_exact_kalman(maturities, yields_panel):
    """σ_h = 0 ⇒ every particle runs the exact filter ⇒ PF loglik == KF."""
    spec, data, batch, nz, u = _setup(maturities, yields_panel, D=2)
    want = np.asarray(jax.vmap(
        lambda q: sqrt_kf.get_loss(spec, q, data))(batch))
    got = np.asarray(pf_loglik_batch(spec, batch, data, nz, u, sv_sigma=0.0))
    assert np.all(np.isfinite(want))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_pallas_pf_invalid_draw_sentinels(maturities, yields_panel):
    """Non-stationary Φ and σ² < 0 both hit −Inf, matching the XLA engine."""
    spec, data, batch, nz, u = _setup(maturities, yields_panel, D=3)
    bad = np.array(batch)
    bad[0, 23] = 1.5      # Φ₁₁ > 1: P0 solve explodes → factorization sentinel
    bad[1, 2] = -4e-4     # σ² < 0: innovation variance goes negative
    bad = jnp.asarray(bad)
    want = np.asarray(_xla(spec, data, bad, nz, u))
    got = np.asarray(pf_loglik_batch(spec, bad, data, nz, u))
    assert want[0] == -np.inf and want[1] == -np.inf
    assert got[0] == -np.inf and got[1] == -np.inf
    assert np.isfinite(want[2])
    np.testing.assert_allclose(got[2], want[2], rtol=1e-9)


def test_pallas_pf_dns_family(maturities, yields_panel):
    """The Ms=3 constant-λ family runs through the same kernel."""
    from tests.test_extensions import _dns_params

    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    data = jnp.asarray(yields_panel[:, :40])
    rng = np.random.default_rng(3)
    batch = jnp.asarray(np.tile(_dns_params(), (2, 1)))
    nz = jnp.asarray(rng.standard_normal((2, 39, P)))
    u = jnp.asarray(rng.uniform(size=(2, 39)))
    want = np.asarray(_xla(spec, data, batch, nz, u))
    got = np.asarray(pf_loglik_batch(spec, batch, data, nz, u))
    assert np.all(np.isfinite(want))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_pallas_pf_shape_validation(maturities, yields_panel):
    spec, data, batch, nz, u = _setup(maturities, yields_panel, D=2)
    with pytest.raises(ValueError, match="multiple of 128"):
        pf_loglik_batch(spec, batch, data, nz[:, :, :100], u)
    with pytest.raises(ValueError, match="noise shapes"):
        pf_loglik_batch(spec, batch, data, nz[:, :-1], u)
    sd, _ = create_model("TVλ", tuple(maturities), float_type="float64")
    with pytest.raises(ValueError, match="constant-measurement"):
        pf_loglik_batch(sd, batch, data, nz, u)


def test_pallas_pf_oracle_parity(maturities, yields_panel):
    """House rule (CLAUDE.md): every numeric kernel gets parity coverage
    against tests/oracle.py's independent NumPy loops — never against
    another JAX path alone.  The oracle runs the plain-covariance JOINT
    per-particle update (inv/slogdet), a different algebraic route than both
    engines' sequential Potter form, on the same common noise."""
    from tests.test_kalman import _dns_params as _dns_pieces
    from tests import oracle

    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, Phi, delta, Omega, obs_var = _dns_pieces()
    data = np.asarray(yields_panel[:, :40])
    rng = np.random.default_rng(7)
    nz = rng.standard_normal((39, P))
    u = rng.uniform(size=(39,))
    Z = oracle.dns_loadings(p[0], maturities)
    want = oracle.rbpf_loglik(Z, Phi, delta, Omega, obs_var, data, nz, u,
                              sv_phi=0.95, sv_sigma=0.2)
    xla = float(particle_filter_loglik(
        spec, jnp.asarray(p), jnp.asarray(data), n_particles=P,
        noise=(jnp.asarray(nz), jnp.asarray(u))))
    pal = float(pf_loglik_batch(
        spec, jnp.asarray(p)[None, :], jnp.asarray(data),
        jnp.asarray(nz)[None], jnp.asarray(u)[None])[0])
    np.testing.assert_allclose(xla, want, rtol=1e-8)
    np.testing.assert_allclose(pal, want, rtol=1e-8)


def test_pallas_pf_zero_offset_resampling(maturities, yields_panel):
    """Regression: a resampling offset of exactly u = 0 must clone particle 0
    into slot 0 (searchsorted-left semantics), not zero the slot's state —
    the selection matrix's row-0 lower bound is −∞, not 0."""
    from tests.test_kalman import _dns_params as _dns_pieces
    from tests import oracle

    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, Phi, delta, Omega, obs_var = _dns_pieces()
    data = np.asarray(yields_panel[:, :30])
    rng = np.random.default_rng(8)
    nz = rng.standard_normal((29, P))
    u = np.zeros(29)  # every resampling offset exactly 0
    Z = oracle.dns_loadings(p[0], maturities)
    want = oracle.rbpf_loglik(Z, Phi, delta, Omega, obs_var, data, nz, u,
                              sv_phi=0.95, sv_sigma=0.2, ess_frac=1.5)
    xla = float(particle_filter_loglik(
        spec, jnp.asarray(p), jnp.asarray(data), n_particles=P,
        noise=(jnp.asarray(nz), jnp.asarray(u)), ess_threshold=1.5))
    pal = float(pf_loglik_batch(
        spec, jnp.asarray(p)[None, :], jnp.asarray(data),
        jnp.asarray(nz)[None], jnp.asarray(u)[None], ess_threshold=1.5)[0])
    np.testing.assert_allclose(xla, want, rtol=1e-8)
    np.testing.assert_allclose(pal, want, rtol=1e-8)


def test_pallas_pf_dead_lane_padding(maturities, yields_panel):
    """n_particles < lane width: dead lanes must not change the estimate —
    a 96-live-particle kernel run on 128 lanes equals the 96-particle XLA
    engine fed the same (zero-padded) noise."""
    spec, data, batch, nz, u = _setup(maturities, yields_panel, D=2)
    n_live = 96
    want = np.asarray(jax.vmap(
        lambda q, z, uu: particle_filter_loglik(
            spec, q, data, n_particles=n_live, noise=(z, uu))
    )(batch, nz[:, :, :n_live], u))
    got = np.asarray(pf_loglik_batch(spec, batch, data, nz, u,
                                     n_particles=n_live))
    assert np.all(np.isfinite(want))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_estimate_sv_kernel_engine(maturities, yields_panel, monkeypatch):
    """estimate_sv with the fused-kernel CRN engine (YFM_PF_PALLAS=force →
    interpret): deterministic, finite, and recovers a sane optimum; the
    estimate-sv-params variant returns in-range (φ_h, σ_h).  The noise
    realization differs from the key-splitting scan path by design, so the
    contract is quality, not equality."""
    from yieldfactormodels_jl_tpu.estimation.sv import estimate_sv
    from yieldfactormodels_jl_tpu.models.params import untransform_params
    from tests.test_extensions import _dns_params

    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    data = jnp.asarray(yields_panel[:, :40])
    raw = np.asarray(untransform_params(spec, jnp.asarray(_dns_params())))
    starts = np.stack([raw, raw + 1e-3], axis=0)
    monkeypatch.setenv("YFM_PF_PALLAS", "force")
    kw = dict(n_particles=P, max_iters=15, sv_phi=0.9, sv_sigma=0.15)
    best, ll, lls, iters = estimate_sv(spec, data, starts,
                                       key=jax.random.PRNGKey(3), **kw)
    best2, ll2, *_ = estimate_sv(spec, data, starts,
                                 key=jax.random.PRNGKey(3), **kw)
    assert np.isfinite(ll) and ll == ll2
    np.testing.assert_allclose(best, best2, rtol=0, atol=0)
    bestf, llf, _, _, (phi_hat, sig_hat) = estimate_sv(
        spec, data, starts, key=jax.random.PRNGKey(3),
        estimate_sv_params=True, **kw)
    assert np.isfinite(llf)
    assert -1.0 < phi_hat < 1.0 and sig_hat > 0.0
