"""Tests for the beyond-reference capabilities: SV particle filter,
block bootstrap over a λ grid, associative-scan (parallel-in-time) Kalman."""

import jax
import jax.numpy as jnp
import numpy as np

from yieldfactormodels_jl_tpu import create_model, get_loss
from yieldfactormodels_jl_tpu.estimation.bootstrap import (
    bootstrap_lambda_grid, moving_block_indices
)
from yieldfactormodels_jl_tpu.ops import assoc_scan
from yieldfactormodels_jl_tpu.ops.particle import particle_filter_loglik

from tests import oracle


def _dns_params():
    p = np.zeros(20)
    p[0] = np.log(0.5)
    p[1] = 4e-4
    p[2], p[4], p[7] = 0.10, 0.08, 0.12
    p[3], p[5], p[6] = 0.01, -0.02, 0.005
    p[8:11] = [0.3, -0.1, 0.05]
    p[11:20] = np.array([[0.95, 0.02, 0.0], [0.01, 0.9, 0.03], [0.0, 0.02, 0.85]]).reshape(-1)
    return p


def test_particle_filter_collapses_to_kalman(maturities, yields_panel):
    """With σ_h → 0 and φ_h = 0, every particle is exact ⇒ PF loglik == KF."""
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p = jnp.asarray(_dns_params())
    data = jnp.asarray(yields_panel[:, :40])
    want = float(get_loss(spec, p, data))
    got = float(particle_filter_loglik(spec, p, data, jax.random.PRNGKey(0),
                                       n_particles=8, sv_phi=0.0, sv_sigma=0.0))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_particle_filter_sv_estimates_are_stable(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p = jnp.asarray(_dns_params())
    data = jnp.asarray(yields_panel[:, :40])
    lls = [float(particle_filter_loglik(spec, p, data, jax.random.PRNGKey(s),
                                        n_particles=200, sv_phi=0.9, sv_sigma=0.2))
           for s in range(3)]
    assert all(np.isfinite(lls))
    assert np.std(lls) < 0.05 * abs(np.mean(lls))  # RB keeps MC noise small


def test_particle_filter_f32_afns5_under_x64(maturities, yields_panel):
    """Regression: with jax_enable_x64 on (this suite) and an f32 AFNS5 spec,
    the yield-adjustment quadrature must not leak f64 into the f32 scan carry
    (particle._measurement casts like kalman.measurement_setup)."""
    from tests.test_afns import _afns5_params

    spec, _ = create_model("AFNS5", tuple(maturities), float_type="float32")
    p, *_ = _afns5_params(spec)
    ll = float(particle_filter_loglik(
        spec, jnp.asarray(np.asarray(p), jnp.float32),
        jnp.asarray(np.asarray(yields_panel)[:, :20], jnp.float32),
        jax.random.PRNGKey(0), n_particles=8, sv_phi=0.5, sv_sigma=0.1))
    assert not np.isnan(ll)


def test_estimate_sv_improves_pf_loglik(maturities, yields_panel):
    """Simulated MLE (common-random-numbers Nelder–Mead over the PF loglik)
    must improve on its starts and report the best start's loglik."""
    from yieldfactormodels_jl_tpu.estimation.sv import estimate_sv
    from yieldfactormodels_jl_tpu.models.params import (transform_params,
                                                       untransform_params)

    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    raw = np.asarray(untransform_params(spec, jnp.asarray(_dns_params())))
    rng = np.random.default_rng(2)
    starts = raw[None, :] + 0.05 * rng.standard_normal((2, raw.shape[0]))
    data = jnp.asarray(yields_panel[:, :30])
    key = jax.random.PRNGKey(4)
    kw = dict(n_particles=32, sv_phi=0.9, sv_sigma=0.2)
    best, best_ll, lls, iters = estimate_sv(spec, data, starts, key=key,
                                            max_iters=40, **kw)
    assert np.isfinite(best_ll) and best_ll == np.nanmax(lls)
    # the optimized loglik beats both raw starts under the SAME key
    start_lls = [float(particle_filter_loglik(
        spec, transform_params(spec, jnp.asarray(s)), data, key, **kw))
        for s in starts]
    assert best_ll >= max(start_lls) - 1e-9


def test_estimate_sv_recovers_hyperparameters():
    """DGP recovery for the SV hyperparameters: data simulated with known
    (φ_h, σ_h) = (0.9, 0.6) (oracle.simulate_sv_panel, matched to the PF's
    model), estimation started at (0.5, 0.2) with estimate_sv_params=True
    must move both into a sampling-error neighborhood of the truth and beat
    the fixed-hyperparameter loglik at the start values.  (The CRN profile
    of this sample is flat within ~1.5 ll units over φ_h ∈ [0.78, 0.95], so
    the bounds are genuine sampling error, not slack.)"""
    from yieldfactormodels_jl_tpu.estimation.sv import estimate_sv
    from yieldfactormodels_jl_tpu.models.params import untransform_params

    mats = tuple(np.array([3, 12, 36, 84, 180, 360]) / 12.0)  # N=6: CPU speed
    rng = np.random.default_rng(7)
    data = oracle.simulate_sv_panel(rng, np.asarray(mats), T=150,
                                    sv_phi=0.9, sv_sigma=0.6)
    spec, _ = create_model("1C", mats, float_type="float64")
    raw = np.asarray(untransform_params(
        spec, jnp.asarray(oracle.stable_1c_params(spec, np.float64))))
    key = jax.random.PRNGKey(11)
    best, best_ll, lls, iters, (phi_hat, sig_hat) = estimate_sv(
        spec, jnp.asarray(data), raw, key=key, n_particles=200,
        sv_phi=0.5, sv_sigma=0.2, max_iters=350, estimate_sv_params=True)
    assert np.isfinite(best_ll)
    assert 0.65 <= phi_hat <= 0.99, phi_hat   # truth 0.9, start 0.5
    assert 0.35 <= sig_hat <= 0.90, sig_hat   # truth 0.6, start 0.2
    # joint estimation must beat holding (φ_h, σ_h) fixed at the start values
    _, fixed_ll, _, _ = estimate_sv(
        spec, jnp.asarray(data), raw, key=key, n_particles=200,
        sv_phi=0.5, sv_sigma=0.2, max_iters=350)
    assert best_ll > fixed_ll


def test_moving_block_indices_shape_and_range():
    idx = np.asarray(moving_block_indices(jax.random.PRNGKey(0), 50, 12, 7))
    assert idx.shape == (7, 50)
    assert idx.min() >= 0 and idx.max() < 50
    # blocks are contiguous runs of length 12
    d = np.diff(idx[0][:12])
    np.testing.assert_array_equal(d, 1)


def test_bootstrap_lambda_grid(maturities, yields_panel):
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    p = np.zeros(13)
    p[0] = np.log(0.5)
    p[1:4] = [0.3, -0.1, 0.05]
    p[4:13] = np.diag([0.9, 0.85, 0.8]).T.reshape(-1)
    grid = np.array([0.2, 0.5, 1.0])
    losses, lo, hi, freq = bootstrap_lambda_grid(
        spec, p, yields_panel, grid, n_resamples=32, block_len=8)
    assert losses.shape == (32, 3)
    assert np.all(np.asarray(lo) <= np.asarray(hi))
    np.testing.assert_allclose(float(jnp.sum(freq)), 1.0, rtol=1e-6)


def test_bootstrap_fused_matches_scan_engine(maturities, yields_panel):
    """The MXU-fused grid loss must agree with the general scan engine on a
    fully-observed panel — same ridge-select OLS, window, normalization."""
    from yieldfactormodels_jl_tpu.estimation.bootstrap import (
        _jitted_grid_loss, _jitted_grid_loss_fused)
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    p = jnp.asarray(oracle.stable_ns_params(spec, dtype=np.float64))
    data = jnp.asarray(yields_panel)
    T = data.shape[1]
    grid = jnp.asarray([0.2, 0.5, 1.0])
    gammas = jnp.log(grid - 1e-2)
    idx = moving_block_indices(jax.random.PRNGKey(3), T, 8, 16)
    want = np.asarray(_jitted_grid_loss(spec, T)(gammas, idx, p, data))
    got = np.asarray(_jitted_grid_loss_fused(spec, T)(gammas, idx, p, data))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_bootstrap_nan_panel_takes_general_engine(maturities, yields_panel):
    """A panel with missing columns must dispatch to the general scan engine
    (the fused kernel's no-carry identity only holds when every column is
    observed) and still produce the scan engine's carry-through losses."""
    from yieldfactormodels_jl_tpu.estimation.bootstrap import (
        _jitted_grid_loss, grid_losses, lambda_to_gamma, moving_block_indices)
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    p = jnp.asarray(oracle.stable_ns_params(spec, dtype=np.float64))
    data = np.asarray(yields_panel).copy()
    data[:, 7] = np.nan  # a fully-missing column → unobserved carry step
    data = jnp.asarray(data)
    T = data.shape[1]
    gammas = lambda_to_gamma(jnp.asarray([0.3, 0.8]))
    idx = moving_block_indices(jax.random.PRNGKey(5), T, 8, 6)
    got = np.asarray(grid_losses(spec, gammas, idx, p, data))
    want = np.asarray(_jitted_grid_loss(spec, T)(gammas, idx, p, data))
    np.testing.assert_array_equal(got, want)


def test_bootstrap_engine_override(maturities, yields_panel):
    """The explicit ``engine`` kwarg pins a path: fused/scan agree on finite
    f64 panels, forced-fused validates its preconditions, bad names raise."""
    import pytest
    from yieldfactormodels_jl_tpu.estimation.bootstrap import (
        grid_losses, lambda_to_gamma)
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    p = jnp.asarray(oracle.stable_ns_params(spec, dtype=np.float64))
    data = jnp.asarray(yields_panel)
    T = data.shape[1]
    gammas = lambda_to_gamma(jnp.asarray([0.3, 0.8]))
    idx = moving_block_indices(jax.random.PRNGKey(7), T, 8, 6)
    fused = np.asarray(grid_losses(spec, gammas, idx, p, data, engine="fused"))
    scan = np.asarray(grid_losses(spec, gammas, idx, p, data, engine="scan"))
    auto = np.asarray(grid_losses(spec, gammas, idx, p, data))
    np.testing.assert_allclose(fused, scan, rtol=1e-9)
    np.testing.assert_array_equal(auto, fused)  # auto dispatches to fused here
    with pytest.raises(ValueError, match="engine must be"):
        grid_losses(spec, gammas, idx, p, data, engine="bogus")
    # forced fused enforces the auto-dispatch preconditions instead of
    # silently producing -Inf cells
    nan_data = np.asarray(yields_panel).copy()
    nan_data[:, 5] = np.nan
    with pytest.raises(ValueError, match="fully-observed"):
        grid_losses(spec, gammas, idx, p, jnp.asarray(nan_data), engine="fused")
    kspec, _ = create_model("1C", tuple(maturities), float_type="float64")
    kp = jnp.asarray(oracle.stable_1c_params(kspec))
    with pytest.raises(ValueError, match="static_lambda"):
        grid_losses(kspec, gammas, idx, kp, data, engine="fused")


def test_bootstrap_traceable_under_jit(maturities, yields_panel):
    """bootstrap_lambda_grid must stay jit-wrappable: with tracer data the
    concrete-finiteness gate is skipped and the general engine runs."""
    spec, _ = create_model("NS", tuple(maturities), float_type="float64")
    p = np.zeros(13)
    p[0] = np.log(0.5)
    p[4:13] = np.diag([0.9, 0.85, 0.8]).T.reshape(-1)
    grid = np.array([0.3, 0.8])
    f = jax.jit(lambda d: bootstrap_lambda_grid(
        spec, p, d, grid, n_resamples=8, block_len=6)[0])
    out = np.asarray(f(jnp.asarray(yields_panel)))
    assert out.shape == (8, 2) and np.isfinite(out).all()
    # and the traced result matches the eager (fused-path) one
    eager = np.asarray(bootstrap_lambda_grid(
        spec, p, yields_panel, grid, n_resamples=8, block_len=6)[0])
    np.testing.assert_allclose(out, eager, rtol=1e-9)


def test_assoc_scan_matches_sequential_kalman(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p = jnp.asarray(_dns_params())
    data = jnp.asarray(yields_panel)
    want = float(get_loss(spec, p, data))
    got = float(assoc_scan.get_loss(spec, p, data))
    np.testing.assert_allclose(got, want, rtol=1e-8)


def test_assoc_scan_masked_window(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p = jnp.asarray(_dns_params())
    data = jnp.asarray(yields_panel)
    want = float(get_loss(spec, p, data, start=10, end=60))
    got = float(assoc_scan.get_loss(spec, p, data, start=10, end=60))
    np.testing.assert_allclose(got, want, rtol=1e-8)


def test_assoc_scan_afns5(maturities, yields_panel):
    spec, _ = create_model("AFNS5", tuple(maturities), float_type="float64")
    from tests.test_afns import _afns5_params

    p, *_ = _afns5_params(spec)
    want = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    got = float(assoc_scan.get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(got, want, rtol=1e-8)
