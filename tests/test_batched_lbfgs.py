"""Natively-batched multi-start L-BFGS (estimation/batched_lbfgs.py).

This is the optimizer that drives the fused-Pallas-objective MLE path: one
L-BFGS loop over the whole (S, P) start matrix, every eval a single batched
call.  Correctness bar: per-start results match an independent per-start
optimizer (the vmapped optax LBFGS already golden-tested in
tests/test_estimation.py) on the same objectives.
"""

import numpy as np
import jax.numpy as jnp

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.estimation import optimize as opt
from yieldfactormodels_jl_tpu.estimation.batched_lbfgs import batched_lbfgs

MATS = tuple(np.array([3, 12, 36, 84, 180, 360]) / 12.0)


def test_batched_quadratics_hit_known_minima():
    """S independent anisotropic quadratics with distinct known minimizers."""
    rng = np.random.default_rng(1)
    S, P = 5, 7
    centers = jnp.asarray(rng.standard_normal((S, P)))
    scales = jnp.asarray(1.0 + rng.uniform(size=(S, P)) * 9.0)

    def vag(X):
        r = (X - centers) * scales
        f = 0.5 * jnp.sum(r * r, axis=-1)
        g = r * scales
        return f, g

    x0 = jnp.zeros((S, P))
    res = batched_lbfgs(vag, x0, max_iters=200, g_tol=1e-10, f_abstol=0.0)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(centers),
                               rtol=0, atol=1e-6)
    assert bool(jnp.all(res.converged))
    assert bool(jnp.all(res.iters > 0)) and bool(jnp.all(res.iters < 200))


def test_frozen_rows_do_not_move():
    """A start that converges immediately must keep its x while others run."""
    centers = jnp.asarray([[0.0, 0.0], [3.0, -2.0]])

    def vag(X):
        r = X - centers
        return 0.5 * jnp.sum(r * r, axis=-1), r

    x0 = jnp.asarray([[0.0, 0.0], [10.0, 10.0]])  # row 0 starts at its optimum
    res = batched_lbfgs(vag, x0, max_iters=100, g_tol=1e-8, f_abstol=0.0)
    np.testing.assert_allclose(np.asarray(res.x[0]), [0.0, 0.0], atol=1e-12)
    assert int(res.iters[0]) == 0
    np.testing.assert_allclose(np.asarray(res.x[1]), [3.0, -2.0], atol=1e-6)


def test_mle_parity_with_vmapped_lbfgs(yields_panel):
    """Same DNS multi-start MLE through (a) the vmapped optax LBFGS and
    (b) batched_lbfgs over the batched objective: best LL must agree."""
    spec, _ = create_model("1C", MATS, float_type="float64")
    rng = np.random.default_rng(3)
    data = yields_panel[: len(MATS), :60]

    from yieldfactormodels_jl_tpu.models.params import untransform_params

    base = np.asarray([0.5] * spec.n_params)
    starts = np.stack([base * (1 + 0.1 * rng.standard_normal(spec.n_params))
                       for _ in range(3)], axis=1)  # (P, S) constrained
    raw = np.stack([np.asarray(untransform_params(spec, jnp.asarray(c)))
                    for c in starts.T], axis=0)
    raw = np.nan_to_num(raw)

    _, ll_ref, _, conv_ref = opt.estimate(spec, data, starts, max_iters=150,
                                          objective="vmap")

    vag = opt.vmapped_value_and_grad(spec, jnp.asarray(data, spec.dtype),
                                     0, data.shape[1])
    res = batched_lbfgs(vag, jnp.asarray(raw, spec.dtype), max_iters=150,
                        g_tol=1e-6, f_abstol=1e-6)
    ll_batched = float(-jnp.min(res.f))
    # same optima modulo linesearch-detail differences
    assert abs(ll_batched - ll_ref) / max(abs(ll_ref), 1.0) < 5e-3
    assert isinstance(conv_ref, opt.Convergence)
    assert conv_ref.iterations > 0


def test_estimate_reports_real_convergence(yields_panel):
    spec, _ = create_model("1C", tuple(np.array([3, 12, 36, 84, 180, 360]) / 12.0),
                           float_type="float64")
    data = yields_panel[:6, :50]
    starts = np.full((spec.n_params, 1), 0.5)
    _, _, _, conv = opt.estimate(spec, data, starts, max_iters=300,
                                 objective="vmap")
    assert isinstance(conv, opt.Convergence)
    assert conv.converged in (True, False)
    assert 0 <= conv.iterations <= 300
    # hard iteration cap ⇒ cannot report convergence
    _, _, _, conv1 = opt.estimate(spec, data, starts, max_iters=2,
                                  g_tol=1e-14, f_abstol=0.0, objective="vmap")
    assert conv1.iterations <= 2


def test_fused_estimate_composition_interpret(yields_panel):
    """Wiring smoke test for the fused MLE paths (estimate / estimate_windows
    with objective='fused') in interpret mode: tiny shapes, few iterations —
    asserts the composition runs, improves the objective, and returns sane
    shapes.  (Kernel-level numerics: tests/test_pallas_grad.py; hardware
    performance: bench.py.)"""
    mats = tuple(np.array([3, 36, 120, 360]) / 12.0)
    spec, _ = create_model("1C", mats, float_type="float32")
    data = np.asarray(yields_panel[:4, :10], dtype=np.float32)

    p = np.zeros(spec.n_params)
    lo, hi = spec.layout["gamma"]; p[lo:hi] = 0.5
    lo, hi = spec.layout["obs_var"]; p[lo:hi] = 0.01
    Ms = spec.state_dim
    k = spec.layout["chol"][0]
    for j in range(Ms):
        for i in range(j + 1):
            p[k] = 0.1 if i == j else 0.01
            k += 1
    lo, hi = spec.layout["phi"]; p[lo:hi] = (0.9 * np.eye(Ms)).reshape(-1)
    starts = np.stack([p, p * 1.02], axis=1)  # (P, S=2) constrained, stationary

    init, ll, best, conv = opt.estimate(spec, data, starts, max_iters=2,
                                        objective="fused")
    assert np.isfinite(ll)
    assert best.shape == (spec.n_params,)
    assert isinstance(conv, opt.Convergence)

    # fused rolling windows: (W=2 windows) x (S=2 starts) in one program
    from yieldfactormodels_jl_tpu.models.params import untransform_params
    raw = np.stack([np.asarray(untransform_params(spec, jnp.asarray(c)))
                    for c in starts.T], axis=0)
    xs, lls = opt.estimate_windows(
        spec, data, np.nan_to_num(raw), np.array([0, 2]), np.array([10, 9]),
        max_iters=2, objective="fused")
    assert xs.shape == (2, 2, spec.n_params)
    assert lls.shape == (2, 2)
    assert np.all(np.isfinite(np.asarray(lls)))

    # cross-check the fused window losses against the univariate loss at the
    # returned parameters (same window masks, same algebra)
    from yieldfactormodels_jl_tpu.ops import univariate_kf
    from yieldfactormodels_jl_tpu.models.params import transform_params
    p00 = transform_params(spec, jnp.asarray(np.asarray(xs)[1, 0]))
    ref = float(univariate_kf.get_loss(spec, p00, jnp.asarray(data), 2, 9))
    np.testing.assert_allclose(float(lls[1, 0]), ref, rtol=2e-3)


def test_fused_estimate_tvl_interpret(yields_panel):
    """The TVλ EKF runs the fused MLE path too (its per-step jax.vjp adjoint
    kernel): estimate(objective='fused') must run, improve the objective,
    and agree with the vmapped scan objective at the returned point."""
    from tests.oracle import stable_tvl_params

    mats = tuple(np.array([3, 36, 120, 360]) / 12.0)
    spec, _ = create_model("TVλ", mats, float_type="float32")
    data = np.asarray(yields_panel[:4, :10], dtype=np.float32)

    p = stable_tvl_params(spec, dtype=np.float64)
    starts = np.stack([p, p * 1.02], axis=1)  # (P, S=2)

    init, ll, best, conv = opt.estimate(spec, data, starts, max_iters=2,
                                        objective="fused")
    assert np.isfinite(ll)
    assert best.shape == (spec.n_params,)

    from yieldfactormodels_jl_tpu.ops import univariate_kf
    ref = float(univariate_kf.get_loss(spec, jnp.asarray(best),
                                       jnp.asarray(data)))
    np.testing.assert_allclose(float(ll), ref, rtol=2e-3)

    # fused rolling windows for the EKF: per-lane [start, end) inside the
    # TVλ adjoint kernel (W=2 windows x S=2 starts, one program per eval)
    from yieldfactormodels_jl_tpu.models.params import untransform_params
    raw = np.stack([np.asarray(untransform_params(spec, jnp.asarray(c)))
                    for c in starts.T], axis=0)
    xs, lls = opt.estimate_windows(
        spec, data, np.nan_to_num(raw), np.array([0, 2]), np.array([10, 9]),
        max_iters=2, objective="fused")
    assert xs.shape == (2, 2, spec.n_params)
    assert lls.shape == (2, 2)
    assert np.all(np.isfinite(np.asarray(lls)))
    from yieldfactormodels_jl_tpu.models.params import transform_params
    p10 = transform_params(spec, jnp.asarray(np.asarray(xs)[1, 0]))
    ref_w = float(univariate_kf.get_loss(spec, p10, jnp.asarray(data), 2, 9))
    np.testing.assert_allclose(float(lls[1, 0]), ref_w, rtol=2e-3)
