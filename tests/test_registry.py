"""Model-dictionary parity: all 34 codes, aliases, param counts, groups."""

import numpy as np
import pytest

from yieldfactormodels_jl_tpu import create_model, get_param_groups, get_static_model_type

MATS = tuple(np.arange(1, 13) / 2.0)


def test_alias_equivalence():
    pairs = [("1C", "0"), ("TVλ", "1"), ("NS", "2"), ("NNS", "3"),
             ("SD-NS", "4"), ("RWSD-NS", "5"), ("SSD-NS", "6"), ("SRWSD-NS", "7"),
             ("1SD-NNS", "8"), ("3SRWSD-NNS", "19"), ("NNS-Anchored", "20"),
             ("1SD-NNS-Anchored", "21"), ("3SRWSD-NNS-Anchored", "32"), ("RW", "-1")]
    for name, alias in pairs:
        s1, c1 = create_model(name, MATS)
        s2, c2 = create_model(alias, MATS)
        assert c1 == c2 == name
        assert s1.family == s2.family
        assert s1.n_params == s2.n_params
        assert s1.random_walk == s2.random_walk
        assert s1.scale_grad == s2.scale_grad
        assert s1.transform_bool == s2.transform_bool


def test_param_counts_match_survey():
    # SURVEY.md §2.13 parameter-count reference
    expect = {
        "1C": 20, "TVλ": 31, "NS": 13, "NNS": 30, "RW": 13,
        "SD-NS": 15, "RWSD-NS": 14,
        "1SD-NNS": 34,   # u=2: A2+B2+ω18+δ3+Φ9
        "1RWSD-NNS": 32,
        "2SD-NNS": 42,   # u=6
        "3SD-NNS": 66,   # u=18
        "3RWSD-NNS": 48,
    }
    for code, n in expect.items():
        spec, _ = create_model(code, MATS)
        assert spec.n_params == n, (code, spec.n_params, n)


def test_placeholders_and_errors():
    spec, canon = create_model("pC", MATS)
    assert spec is None and canon == "pC"
    spec, canon = create_model("a", MATS)
    assert spec is None and canon == "vanillaNN"
    with pytest.raises(ValueError):
        create_model("bogus", MATS)


def test_param_groups_defaults():
    spec, _ = create_model("1C", MATS)
    assert get_param_groups(spec) == ("1",) * 20
    spec, _ = create_model("SD-NS", MATS)
    g = get_param_groups(spec)
    assert g[-12:] == ("2",) * 12 and g[:-12] == ("1",) * 3
    # matching-length override accepted, wrong length rejected
    assert get_param_groups(spec, ["3"] * 15) == ("3",) * 15
    assert get_param_groups(spec, ["3"] * 4) == g


def test_static_model_type_cascade():
    assert get_static_model_type(create_model("1C", MATS)[0]) == "DNS"
    assert get_static_model_type(create_model("TVλ", MATS)[0]) == "1C"
    assert get_static_model_type(create_model("SD-NS", MATS)[0]) == "NS"
    assert get_static_model_type(create_model("1SD-NNS", MATS)[0]) == "NNS"
    assert get_static_model_type(create_model("1SD-NNS-Anchored", MATS)[0]) == "NNS-Anchored"
    assert get_static_model_type(create_model("RW", MATS)[0]) == ""


def test_duplicator_shapes():
    for code, u in [("1SD-NNS", 2), ("2SD-NNS", 6), ("3SD-NNS", 18)]:
        spec, _ = create_model(code, MATS)
        assert spec.n_unique == u
        assert len(spec.duplicator) == 18
