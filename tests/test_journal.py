"""Per-shard accepted-update journal (serving/journal.py, DESIGN §24).

The journal is the replay source a lost shard is rebuilt from, so its
safety story is entirely host-side and jax-free: bounded rings whose
eviction is a DETECTED gap (never a silent short replay), per-key version
watermarks that catch dropped appends, contiguous-suffix extraction, the
atomic tmp+``os.replace`` spill (YFM005), and lock-consistent snapshots
under a concurrent append hammer (YFM010).
"""

import pickle
import threading

import numpy as np
import pytest

from yieldfactormodels_jl_tpu.serving.journal import (JournalRecord,
                                                      UpdateJournal)

K0 = ("1C", 0)
K1 = ("1C", 1)


def _curve(v, n=6):
    return np.full(n, float(v))


def _fill(j, shard, key, versions, base=None):
    if base is not None:
        j.note_base(key, base)
    for v in versions:
        j.append(shard, key, f"d{v}", _curve(v), v)


# ---------------------------------------------------------------------------
# construction + validation
# ---------------------------------------------------------------------------

def test_constructor_validation():
    with pytest.raises(ValueError):
        UpdateJournal(0)
    with pytest.raises(ValueError):
        UpdateJournal(2, capacity=0)
    j = UpdateJournal(3, capacity=7)
    assert j.n_shards == 3 and j.capacity == 7


def test_env_capacity_constructor_wins(monkeypatch):
    monkeypatch.setenv("YFM_JOURNAL_CAP", "5")
    assert UpdateJournal(1).capacity == 5
    assert UpdateJournal(1, capacity=9).capacity == 9
    monkeypatch.setenv("YFM_JOURNAL_CAP", "0")
    with pytest.raises(ValueError):
        UpdateJournal(1)
    monkeypatch.delenv("YFM_JOURNAL_CAP")
    assert UpdateJournal(1).capacity == 1024


# ---------------------------------------------------------------------------
# watermarks + suffix contiguity
# ---------------------------------------------------------------------------

def test_clean_suffix_and_watermarks():
    j = UpdateJournal(2, capacity=16)
    _fill(j, 0, K0, [1, 2, 3, 4], base=0)
    _fill(j, 1, K1, [11, 12], base=10)
    assert j.watermark(K0) == 4 and j.watermark(K1) == 12
    assert j.shard_seq(0) == 4 and j.shard_seq(1) == 2
    recs, ok = j.suffix(K0, 1, 4)
    assert ok and [r.version for r in recs] == [2, 3, 4]
    # the records carry private float64 copies of the curves
    assert all(r.curve.dtype == np.float64 for r in recs)
    assert np.array_equal(recs[0].curve, _curve(2))
    # empty needed range with an intact watermark is trivially ok
    recs, ok = j.suffix(K0, 4, 4)
    assert ok and recs == []
    # a key the journal never saw: empty range ok, non-empty is a gap
    recs, ok = j.suffix(("2C", 9), 3, 3)
    assert ok and recs == []
    recs, ok = j.suffix(("2C", 9), 3, 5)
    assert not ok


def test_append_curve_copy_is_private():
    j = UpdateJournal(1, capacity=4)
    y = _curve(1.0)
    j.note_base(K0, 0)
    j.append(0, K0, "d", y, 1)
    y[:] = 99.0      # caller mutates after the accept
    recs, ok = j.suffix(K0, 0, 1)
    assert ok and np.array_equal(recs[0].curve, _curve(1.0))


# ---------------------------------------------------------------------------
# gap detection: dropped appends, trailing drops, ring eviction
# ---------------------------------------------------------------------------

def test_dropped_append_gaps_the_key():
    j = UpdateJournal(1, capacity=16)
    j.note_base(K0, 0)
    j.append(0, K0, "d1", _curve(1), 1)
    # version 2's append was dropped (the journal_gap seam); 3 arrives
    j.append(0, K0, "d3", _curve(3), 3)
    assert j.is_gapped(K0)
    recs, ok = j.suffix(K0, 0, 3)
    assert not ok and recs == []
    # a re-base (refit/promotion installs a fresh record) heals the key
    j.note_base(K0, 3)
    assert not j.is_gapped(K0)
    j.append(0, K0, "d4", _curve(4), 4)
    recs, ok = j.suffix(K0, 3, 4)
    assert ok and [r.version for r in recs] == [4]


def test_trailing_drop_detected_by_watermark():
    """A dropped LAST append leaves no version jump to catch — the suffix
    check ``watermark < upto_version`` is what refuses the short replay."""
    j = UpdateJournal(1, capacity=16)
    _fill(j, 0, K0, [1, 2], base=0)
    assert not j.is_gapped(K0)          # no jump observed...
    recs, ok = j.suffix(K0, 0, 3)       # ...but the accepted stream is at 3
    assert not ok and recs == []


def test_dropped_first_append_caught_via_base():
    j = UpdateJournal(1, capacity=16)
    j.note_base(K0, 0)
    j.append(0, K0, "d2", _curve(2), 2)   # v1's append was dropped
    assert j.is_gapped(K0)


def test_ring_eviction_is_a_gap_not_a_short_replay():
    j = UpdateJournal(1, capacity=3)
    _fill(j, 0, K0, [1, 2, 3, 4, 5], base=0)   # ring holds only 3,4,5
    assert not j.is_gapped(K0)                 # eviction is not a key gap
    recs, ok = j.suffix(K0, 0, 5)              # needs 1..5: 1,2 aged out
    assert not ok and recs == []
    recs, ok = j.suffix(K0, 2, 5)              # 3..5 still resident
    assert ok and [r.version for r in recs] == [3, 4, 5]
    assert j.shard_seq(0) == 5                 # seq survives eviction


def test_forget_drops_watermark_and_gap_state():
    j = UpdateJournal(1, capacity=8)
    _fill(j, 0, K0, [1, 3], base=0)            # gapped
    assert j.is_gapped(K0)
    j.forget(K0)
    assert j.watermark(K0) is None and not j.is_gapped(K0)
    # non-empty suffix for a forgotten key is a gap (no watermark to trust)
    _, ok = j.suffix(K0, 0, 3)
    assert not ok


# ---------------------------------------------------------------------------
# spill / load (YFM005 atomic publish) round trip
# ---------------------------------------------------------------------------

def test_spill_load_round_trip(tmp_path):
    j = UpdateJournal(2, capacity=8)
    _fill(j, 0, K0, [1, 2, 3], base=0)
    _fill(j, 1, K1, [11, 13], base=10)         # gapped on shard 1
    path = str(tmp_path / "journal.pkl")
    j.spill(path)
    assert not list(tmp_path.glob("*.tmp.*"))  # tmp sibling replaced away
    j2 = UpdateJournal.load(path)
    assert j2.capacity == 8 and j2.n_shards == 2
    assert j2.watermark(K0) == 3 and j2.shard_seq(0) == 3
    assert j2.is_gapped(K1) and not j2.is_gapped(K0)
    recs, ok = j2.suffix(K0, 0, 3)
    assert ok and [r.version for r in recs] == [1, 2, 3]
    assert all(isinstance(r, JournalRecord) for r in recs)
    # spill again over the existing file: os.replace, not append
    j2.append(0, K0, "d4", _curve(4), 4)
    j2.spill(path)
    with open(path, "rb") as fh:
        assert pickle.load(fh)["last_ver"][K0] == 4


# ---------------------------------------------------------------------------
# threading: append hammer vs consistent snapshots (YFM010)
# ---------------------------------------------------------------------------

def test_two_thread_append_vs_snapshot_hammer():
    """Two writer threads append disjoint per-key streams while the main
    thread snapshots concurrently: every snapshot must be internally
    consistent (per-key max ring version == watermark, no gaps — the
    streams themselves are contiguous) and the final state exact."""
    j = UpdateJournal(2, capacity=4096)
    n = 300
    keys = [("1C", 0), ("1C", 1)]
    for k in keys:
        j.note_base(k, 0)

    def writer(shard, key):
        for v in range(1, n + 1):
            j.append(shard, key, v, _curve(v), v)

    threads = [threading.Thread(target=writer, args=(s, k))
               for s, k in enumerate(keys)]
    for t in threads:
        t.start()
    snaps = []
    while any(t.is_alive() for t in threads):
        snaps.append(j.snapshot())
    for t in threads:
        t.join()
    snaps.append(j.snapshot())

    for snap in snaps:
        assert not snap["gapped"]
        for s, key in enumerate(keys):
            ring_vers = [r.version for r in snap["rings"][s]
                         if r.key == key]
            assert ring_vers == sorted(ring_vers)
            if ring_vers:
                # the ring's high edge and the watermark agree in every
                # consistent cut (the lock's whole job)
                assert snap["last_ver"][key] == ring_vers[-1]
                assert snap["seq"][s] == len(ring_vers)
    final = snaps[-1]
    for s, key in enumerate(keys):
        assert final["last_ver"][key] == n
        assert final["seq"][s] == n
    for s, key in enumerate(keys):
        recs, ok = j.suffix(key, 0, n)
        assert ok and len(recs) == n
