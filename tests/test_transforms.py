"""Bijection parity + round-trip property tests (SURVEY.md §2.7)."""

import jax
import jax.numpy as jnp
import numpy as np

from yieldfactormodels_jl_tpu.utils import transformations as tr


def test_scalar_bijections_match_reference_formulas():
    x = np.linspace(-3, 3, 31)
    np.testing.assert_allclose(tr.from_R_to_pos(x), np.exp(x), rtol=1e-12)
    np.testing.assert_allclose(
        tr.from_R_to_11(x), 2 * np.exp(x) / (1 + np.exp(x)) - 1, rtol=1e-12
    )
    np.testing.assert_allclose(tr.from_R_to_01(x), 1 / (1 + np.exp(-x)), rtol=1e-12)


def test_roundtrips():
    x = np.linspace(-4, 4, 41)
    np.testing.assert_allclose(tr.from_pos_to_R(tr.from_R_to_pos(x)), x, atol=1e-10)
    np.testing.assert_allclose(tr.from_11_to_R(tr.from_R_to_11(x)), x, atol=1e-9)
    np.testing.assert_allclose(tr.from_01_to_R(tr.from_R_to_01(x)), x, atol=1e-9)


def test_coded_vector_apply():
    params = jnp.asarray([0.5, -1.0, 2.0, 0.3])
    codes = jnp.asarray([tr.IDENTITY, tr.R_TO_POS, tr.R_TO_11, tr.R_TO_01])
    out = tr.apply_transforms(params, codes)
    np.testing.assert_allclose(
        out,
        [0.5, np.exp(-1.0), np.tanh(1.0), 1 / (1 + np.exp(-0.3))],
        rtol=1e-7,
    )
    back = tr.apply_untransforms(out, codes)
    np.testing.assert_allclose(back, params, atol=1e-7)


def test_transform_gradients_finite_under_extremes():
    """The double-where idiom must not leak NaN grads from inactive branches."""
    params = jnp.asarray([500.0, -500.0, 3.0])  # identity slots would overflow exp
    codes = jnp.asarray([tr.IDENTITY, tr.IDENTITY, tr.R_TO_POS])

    def s(p):
        return jnp.sum(tr.apply_transforms(p, codes))

    g = jax.grad(s)(params)
    assert np.all(np.isfinite(np.asarray(g)))
