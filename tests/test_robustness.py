"""Numerics sentry (robustness/, docs/DESIGN.md §11): failure taxonomy,
engine escalation ladder, self-healing serving state.

Acceptance coverage (ISSUE 5):

- coded kernels return the SAME loss as the plain kernels bit-for-bit, plus
  a decodable cause for every failure class the sentinels can hit;
- with ``YFM_ESCALATE=1`` a seeded non-PSD start that fails the joint/scan
  filter is recovered by the square-root rung and its ladder trace (codes +
  rung) lands in the multi-start report; ``YFM_ESCALATE=0`` reproduces the
  drop-the-start behavior exactly; both runs are deterministic;
- with the ``nan_curve:@3`` chaos seam armed, ``YieldCurveService`` degrades
  (stale flag + rebuild, no exception) and the next healthy update returns
  it to ``ok`` — bit-for-bit deterministic under fixed seeds;
- the long-horizon drift regression: 5k online updates stay PSD and agree
  with one batch filter pass / the float64 NumPy oracle.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import serving
from yieldfactormodels_jl_tpu.estimation import optimize as opt
from yieldfactormodels_jl_tpu.models import kalman as kalman_joint
from yieldfactormodels_jl_tpu.models.params import unpack_kalman
from yieldfactormodels_jl_tpu.ops import sqrt_kf, univariate_kf
from yieldfactormodels_jl_tpu.orchestration import chaos
from yieldfactormodels_jl_tpu.orchestration.retry import SentinelFailure
from yieldfactormodels_jl_tpu.robustness import health as rh
from yieldfactormodels_jl_tpu.robustness import ladder, taxonomy as tax

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)


@pytest.fixture(scope="module")
def dns_setup():
    rng = np.random.default_rng(7)
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=46)
    return spec, p, data


def _nonpsd_start(spec, p):
    """Heavy off-diagonal Φ (spectral radius > 1): the kron-solve P₀ is
    indefinite, so the univariate/joint filters die (f ≤ 0 / failed
    innovation Cholesky) and the plain sqrt engine dies at chol(P₀)."""
    bad = np.asarray(p, dtype=np.float64).copy()
    a, b = spec.layout["phi"]
    Phi = 0.9 * np.eye(3)
    Phi[0, 1] = Phi[1, 0] = Phi[0, 2] = Phi[2, 0] = Phi[1, 2] = Phi[2, 1] = 0.8
    bad[a:b] = Phi.reshape(-1)
    return bad


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------

def test_decode_describe_roundtrip():
    assert tax.decode(0) == ()
    assert tax.describe(0) == "OK"
    both = tax.NONPSD_INNOVATION | tax.CHOL_BREAKDOWN
    assert tax.decode(both) == ("NONPSD_INNOVATION", "CHOL_BREAKDOWN")
    assert tax.describe(both) == "NONPSD_INNOVATION|CHOL_BREAKDOWN"
    # bits are distinct powers of two (OR-combinable)
    flags = [f for f, _ in tax.NAMES]
    assert len(set(flags)) == len(flags)
    assert all(f & (f - 1) == 0 for f in flags)


def test_combine_is_bitwise_or():
    codes = jnp.asarray([0, tax.NONPSD_INNOVATION, tax.STATE_EXPLODED, 0],
                        dtype=jnp.int32)
    assert int(tax.combine(codes)) == \
        tax.NONPSD_INNOVATION | tax.STATE_EXPLODED
    assert int(tax.combine(jnp.zeros(5, dtype=jnp.int32))) == 0


def test_coded_losses_match_plain_bitforbit(dns_setup):
    """The taxonomy channel must not perturb the loss: get_loss_coded ==
    get_loss exactly, healthy code 0, on all three coded Kalman engines."""
    spec, p, data = dns_setup
    pj, dj = jnp.asarray(p), jnp.asarray(data)
    for plain, coded in ((univariate_kf.get_loss, univariate_kf.get_loss_coded),
                         (sqrt_kf.get_loss, sqrt_kf.get_loss_coded),
                         (kalman_joint.get_loss, kalman_joint.get_loss_coded)):
        ll, code = coded(spec, pj, dj)
        assert float(ll) == float(plain(spec, pj, dj))
        assert int(code) == tax.OK


def test_taxonomy_flags_each_failure_class(dns_setup):
    spec, p, data = dns_setup
    dj = jnp.asarray(data)
    # non-PD innovation variance (σ² < 0 in constrained space)
    bad = np.asarray(p).copy()
    bad[spec.layout["obs_var"][0]] = -10.0
    ll, code = univariate_kf.get_loss_coded(spec, jnp.asarray(bad), dj)
    assert float(ll) == -np.inf
    assert "NONPSD_INNOVATION" in tax.decode(code)
    # joint engine: same point is a failed innovation Cholesky
    ll, code = kalman_joint.get_loss_coded(spec, jnp.asarray(bad), dj)
    assert "CHOL_BREAKDOWN" in tax.decode(code)
    # sqrt engine: an indefinite P0 is a failed initial factorization
    ll, code = sqrt_kf.get_loss_coded(spec, jnp.asarray(_nonpsd_start(spec, p)),
                                      dj)
    assert float(ll) == -np.inf and "CHOL_BREAKDOWN" in tax.decode(code)
    # non-finite params → TRANSFORM_OVERFLOW
    nanp = np.asarray(p).copy()
    nanp[0] = np.nan
    ll, code = univariate_kf.get_loss_coded(spec, jnp.asarray(nanp), dj)
    assert "TRANSFORM_OVERFLOW" in tax.decode(code)
    # empty window → MISSING_ALL_OBS (loss convention unchanged: 0.0)
    ll, code = univariate_kf.get_loss_coded(spec, jnp.asarray(p), dj, 5, 6)
    assert "MISSING_ALL_OBS" in tax.decode(code)


def test_smoother_carries_code(dns_setup):
    spec, p, data = dns_setup
    from yieldfactormodels_jl_tpu.ops.smoother import smooth

    out = smooth(spec, jnp.asarray(p), jnp.asarray(data))
    assert int(out["code"]) == tax.OK
    bad = np.asarray(p).copy()
    bad[spec.layout["obs_var"][0]] = -10.0
    out = smooth(spec, jnp.asarray(bad), jnp.asarray(data))
    assert np.isnan(np.asarray(out["beta_smooth"])).all()
    assert "NAN_STATE" in tax.decode(out["code"])
    assert "NONPSD_INNOVATION" in tax.decode(out["code"])


def test_diagnose_driver_entry(dns_setup):
    spec, p, data = dns_setup
    ll, code = tax.diagnose(spec, p, data)
    assert np.isfinite(ll) and code == 0
    ll, code = tax.diagnose(spec, _nonpsd_start(spec, p), data)
    assert ll == -np.inf and code != 0


# ---------------------------------------------------------------------------
# escalation ladder (acceptance: sqrt-rung recovery, exact off-behavior)
# ---------------------------------------------------------------------------

def test_ladder_recovers_nonpsd_start_via_sqrt_rung(dns_setup, monkeypatch):
    spec, p, data = dns_setup
    bad = _nonpsd_start(spec, p)
    starts = np.stack([p, bad], axis=1)  # (P, S): one good, one dead

    monkeypatch.setenv("YFM_ESCALATE", "0")
    r_off = opt.estimate(spec, data, starts, max_iters=5)
    rep_off = opt.last_multistart_report()
    assert rep_off["ladder"] == []  # drop-the-start: no escalation ran

    monkeypatch.setenv("YFM_ESCALATE", "1")
    r_on = opt.estimate(spec, data, starts, max_iters=5)
    rep_on = opt.last_multistart_report()

    # the good start still wins, and its result is IDENTICAL to the off run
    assert r_on[1] == r_off[1]
    np.testing.assert_array_equal(r_on[2], r_off[2])
    assert bool(r_on[3].converged) == bool(r_off[3].converged)

    # ... but the dead start was recovered by the sqrt rung, with its trace
    # (initial diagnosis code + rungs climbed) in the multi-start report
    (trace,) = rep_on["ladder"]
    assert trace["start"] == 1 and trace["recovered"]
    assert trace["rung"] == "sqrt" and trace["engine"] == "sqrt"
    assert "NONPSD_INNOVATION" in trace["cause"]
    assert [r["rung"] for r in trace["rungs"]] == ["scan", "sqrt"]
    assert np.isfinite(trace["ll"])
    assert np.isfinite(rep_on["lls"][1])

    # determinism: the escalated run replays bit-for-bit
    r_on2 = opt.estimate(spec, data, starts, max_iters=5)
    assert r_on2[1] == r_on[1]
    np.testing.assert_array_equal(r_on2[2], r_on[2])
    assert opt.last_multistart_report() == rep_on


def test_ladder_rescues_all_dead_batch(dns_setup, monkeypatch):
    """When EVERY start is dead the ladder's value is the answer (flagged
    not-converged: a rescued evaluation, not an optimizer optimum)."""
    spec, p, data = dns_setup
    bad = _nonpsd_start(spec, p)
    monkeypatch.setenv("YFM_ESCALATE", "1")
    _, ll, best, conv = opt.estimate(spec, data, bad[:, None], max_iters=5)
    assert np.isfinite(ll) and not conv.converged
    monkeypatch.setenv("YFM_ESCALATE", "0")
    _, ll0, _, _ = opt.estimate(spec, data, bad[:, None], max_iters=5)
    assert not np.isfinite(ll0) or ll0 <= -opt._PENALTY_THRESH  # dropped


def test_ladder_shrink_rung_reference_parity(dns_setup):
    """A start that no engine can evaluate but whose ×0.95-shrunk point can
    be recovers through the shrink rung with a modified raw vector — the
    reference's rescue (optimization.jl:173-184), now recorded."""
    spec, p, data = dns_setup
    # NaN params: scan/sqrt/jitter all dead (TRANSFORM_OVERFLOW);
    # shrink of NaN stays NaN → unrecovered trace, exercised end-to-end
    raw_nan = np.full(spec.n_params, np.nan)
    tr = ladder.escalate(spec, data, raw_nan)
    assert not tr.recovered and tr.rung is None and tr.ll == -np.inf
    assert "TRANSFORM_OVERFLOW" in tax.describe(tr.code)


def test_ladder_trace_asdict_shape(dns_setup):
    spec, p, data = dns_setup
    tr = ladder.escalate(spec, data,
                         np.asarray(opt.untransform_params(
                             spec, jnp.asarray(p)), dtype=np.float64))
    d = tr.as_dict()
    assert d["recovered"] and d["rung"] == "scan" and d["cause"] == "OK"
    assert d["rungs"][0]["rung"] == "scan"


# ---------------------------------------------------------------------------
# SentinelFailure context (satellite: actionable quarantine rows)
# ---------------------------------------------------------------------------

def test_sentinel_failure_carries_seam_and_code():
    e = SentinelFailure("boom", seam="estimate",
                        code=tax.NONPSD_INNOVATION | tax.CHOL_BREAKDOWN)
    assert e.seam == "estimate"
    assert e.code == (tax.NONPSD_INNOVATION | tax.CHOL_BREAKDOWN)
    assert "seam=estimate" in str(e)
    assert "NONPSD_INNOVATION|CHOL_BREAKDOWN" in str(e)
    legacy = SentinelFailure("plain")
    assert legacy.seam is None and legacy.code == 0 and str(legacy) == "plain"


def test_window_task_sentinel_carries_cause(tmp_path, monkeypatch):
    """run_single_window_task's retry-policy sentinel now names the seam and
    the decoded cause — what the queue's quarantine row will persist."""
    from yieldfactormodels_jl_tpu import forecasting as fc

    spec, _ = yfm.create_model(
        "NS", tuple(np.array([3.0, 12.0, 24.0, 60.0, 120.0, 360.0]) / 12.0),
        float_type="float64", results_location=str(tmp_path) + "/")
    rng = np.random.default_rng(3)
    data = oracle.simulate_dns_panel(
        rng, np.array([3.0, 12.0, 24.0, 60.0, 120.0, 360.0]) / 12.0, T=36)
    monkeypatch.setattr(
        fc, "_estimate_for_window",
        lambda *a, **k: (float("-inf"), np.full(spec.n_params, np.nan)))
    with pytest.raises(SentinelFailure, match="non-finite loss sentinel") as ei:
        fc.run_single_window_task(
            spec, data, "1", 33, "expanding", 33, 1, 3,
            np.zeros((spec.n_params, 1)), param_groups=["1"] * spec.n_params,
            sentinel_policy="retry")
    assert ei.value.seam == "estimate"
    assert ei.value.code != 0
    assert "cause=" in str(ei.value)


# ---------------------------------------------------------------------------
# self-healing serving (acceptance: chaos degrade → rebuild → recover)
# ---------------------------------------------------------------------------

T_ORIGIN = 34


def _service(spec, p, data, **kw):
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    return serving.YieldCurveService(snap, **kw)


def _run_updates(svc, data, n=5):
    return [svc.update(T_ORIGIN + k, data[:, T_ORIGIN + k]) for k in range(n)]


def test_chaos_nan_curve_degrades_and_recovers(dns_setup):
    """YFM_CHAOS=nan_curve:@3 (programmatic arm): the 3rd update's state
    poison is caught by the health watch — stale + rebuild, NO exception —
    and the next healthy update returns the service to ok.  Deterministic:
    two runs agree bit-for-bit."""
    spec, p, data = dns_setup

    def run():
        svc = _service(spec, p, data, self_heal=True)
        chaos.configure("nan_curve:@3")
        try:
            lls = _run_updates(svc, data, 5)
        finally:
            chaos.reset()
        return svc, lls

    svc, lls = run()
    assert np.isnan(lls[2]) and all(np.isfinite(lls[k]) for k in (0, 1, 3, 4))
    h = svc.health()
    assert h["status"] == "ok" and h["rebuilds"] == 1
    assert svc.version == 4  # the poisoned update was rolled back

    svc2, lls2 = run()  # bit-for-bit determinism under the fixed trigger
    np.testing.assert_array_equal(np.asarray(svc.snapshot.beta),
                                  np.asarray(svc2.snapshot.beta))
    np.testing.assert_array_equal(np.asarray(svc.snapshot.P),
                                  np.asarray(svc2.snapshot.P))
    assert [x for x in lls if np.isfinite(x)] == \
        [x for x in lls2 if np.isfinite(x)]


def test_chaos_env_route_arms_numeric_seam(dns_setup, monkeypatch):
    """The acceptance knob spelling: YFM_CHAOS=nan_curve:@1 in the
    environment (re-read after reset) arms the numeric seam."""
    spec, p, data = dns_setup
    monkeypatch.setenv("YFM_CHAOS", "nan_curve:@1")
    chaos.reset()  # force the env re-read on the next hit
    try:
        svc = _service(spec, p, data, self_heal=True)
        ll = svc.update(T_ORIGIN, data[:, T_ORIGIN])
        assert np.isnan(ll) and svc.health()["status"] == "stale"
    finally:
        chaos.reset()


def test_chaos_nan_curve_stale_while_degraded(dns_setup):
    spec, p, data = dns_setup
    svc = _service(spec, p, data, self_heal=True)
    chaos.configure("nan_curve:@2")
    try:
        svc.update(T_ORIGIN, data[:, T_ORIGIN])
        assert svc.health()["status"] == "ok"
        svc.update(T_ORIGIN + 1, data[:, T_ORIGIN + 1])  # poisoned
    finally:
        chaos.reset()
    h = svc.health()
    assert h["status"] == "stale" and h["rebuilds"] == 1
    assert "NAN_STATE" in h["last_code_names"]
    # forecasts still answer from the last-good state while stale
    fc = svc.forecast(4)
    assert np.all(np.isfinite(fc["means"]))


def test_chaos_nonpsd_cov_caught_by_min_eig_watch(dns_setup):
    spec, p, data = dns_setup
    svc = _service(spec, p, data, self_heal=True)
    chaos.configure("nonpsd_cov:@2")
    try:
        lls = _run_updates(svc, data, 4)
    finally:
        chaos.reset()
    assert np.isnan(lls[1]) and np.isfinite(lls[2])
    h = svc.health()
    assert h["status"] == "ok" and h["rebuilds"] == 1
    assert h["cov_min_eig"] > 0


def test_chaos_nonpsd_cov_sqrt_engine_forces_restore(dns_setup):
    """With the sqrt engine a corrupted FACTOR is invisible to the min-eig
    watch (S Sᵀ is PSD for any finite S) — the fired seam must force the
    restore anyway, and the post-rebuild state must equal the pre-corruption
    state exactly."""
    spec, p, data = dns_setup
    svc = _service(spec, p, data, self_heal=True, engine="sqrt")
    ll0 = svc.update(T_ORIGIN, data[:, T_ORIGIN])
    good_cov = np.asarray(svc._state.cov).copy()
    chaos.configure("nonpsd_cov:@1")
    try:
        ll1 = svc.update(T_ORIGIN + 1, data[:, T_ORIGIN + 1])
    finally:
        chaos.reset()
    assert np.isfinite(ll0) and np.isnan(ll1)
    h = svc.health()
    assert h["status"] == "stale" and h["rebuilds"] == 1
    assert "NONPSD_COV" in h["last_code_names"]
    np.testing.assert_array_equal(np.asarray(svc._state.cov), good_cov)
    # healthy update → back to ok, continuing from the restored state
    assert np.isfinite(svc.update(T_ORIGIN + 2, data[:, T_ORIGIN + 2]))
    assert svc.health()["status"] == "ok"


def test_unhealed_service_still_raises_and_rolls_back(dns_setup):
    """Default (self_heal=False) keeps the historical contract: structured
    ServingError, last good snapshot retained — now with the decoded cause
    in the error context."""
    spec, p, data = dns_setup
    bad = np.asarray(p, dtype=np.float64).copy()
    bad[spec.layout["obs_var"][0]] = -10.0
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    svc = serving.YieldCurveService(dataclasses.replace(
        snap, params=jnp.asarray(bad)))
    v0 = svc.version
    with pytest.raises(serving.ServingError) as ei:
        svc.update(0, data[:, T_ORIGIN])
    assert svc.version == v0
    assert "NONPSD_INNOVATION" in ei.value.context["code"]


def test_request_path_rolls_back_poisoned_state(dns_setup):
    """Satellite: the request-path finiteness guard must not leave a
    poisoned in-memory OnlineState behind — the state is restored to the
    last good snapshot BEFORE the structured error surfaces (and under
    self_heal the request is retried from the healed state)."""
    spec, p, data = dns_setup
    svc = _service(spec, p, data)  # self_heal=False: raise, but heal first
    svc.update(T_ORIGIN, data[:, T_ORIGIN])
    good_beta = np.asarray(svc._state.beta).copy()
    # poison the in-memory state behind the service's back (the class of bug
    # the old _check_finite left unrecoverable)
    svc._state = serving.OnlineState(
        jnp.full_like(svc._state.beta, jnp.nan),
        jnp.full_like(svc._state.cov, jnp.nan))
    svc.snapshot = dataclasses.replace(
        svc.snapshot, beta=svc._state.beta, P=svc._state.cov)
    with pytest.raises(serving.ServingError):
        svc.forecast(4)
    np.testing.assert_array_equal(np.asarray(svc._state.beta), good_beta)
    assert svc.rebuilds == 1 and svc.stale

    # self_heal=True: same poisoning, but the caller gets a (stale) answer
    svc2 = _service(spec, p, data, self_heal=True)
    svc2.update(T_ORIGIN, data[:, T_ORIGIN])
    svc2._state = serving.OnlineState(
        jnp.full_like(svc2._state.beta, jnp.nan),
        jnp.full_like(svc2._state.cov, jnp.nan))
    svc2.snapshot = dataclasses.replace(
        svc2.snapshot, beta=svc2._state.beta, P=svc2._state.cov)
    out = svc2.forecast(4)
    assert np.all(np.isfinite(out["means"]))
    assert svc2.stale and svc2.rebuilds == 1


def test_registry_is_rebuild_source_of_last_resort(dns_setup):
    """When even the last-good state is poisoned, the rebuild falls back to
    the frozen registry/boot snapshot."""
    spec, p, data = dns_setup
    reg = serving.SnapshotRegistry()
    snap = serving.freeze_snapshot(
        spec, p, data, end=T_ORIGIN,
        meta=serving.SnapshotMeta(model_string=spec.model_string, task_id=7))
    reg.put(snap)
    svc = serving.YieldCurveService(snap, registry=reg, self_heal=True)
    nan_state = serving.OnlineState(
        jnp.full_like(svc._state.beta, jnp.nan),
        jnp.full_like(svc._state.cov, jnp.nan))
    svc._state = nan_state
    svc._last_good = (svc.snapshot, nan_state)  # last-good poisoned too
    ll = svc.update(T_ORIGIN, data[:, T_ORIGIN])
    # the update itself ran against a NaN carry → rejected and rebuilt
    assert np.isnan(ll) and svc.rebuilds == 1 and svc.stale
    # next update runs from the registry-restored state and is healthy
    assert np.isfinite(svc.update(T_ORIGIN + 1, data[:, T_ORIGIN + 1]))
    assert svc.health()["status"] == "ok"


def test_serve_refresh_keeps_oracle_parity(dns_setup, monkeypatch):
    """YFM_SERVE_REFRESH scrubs must not move the state beyond rounding:
    with a refresh every 3 updates the final state still matches the plain
    run at 1e-9 (f64) and the refresh counter cycles."""
    spec, p, data = dns_setup
    monkeypatch.setenv("YFM_SERVE_REFRESH", "3")
    svc_r = _service(spec, p, data)  # reads the env knob
    monkeypatch.delenv("YFM_SERVE_REFRESH")
    svc_p = _service(spec, p, data)
    for k in range(10):
        svc_r.update(T_ORIGIN + k, data[:, (T_ORIGIN + k) % data.shape[1]])
        svc_p.update(T_ORIGIN + k, data[:, (T_ORIGIN + k) % data.shape[1]])
    assert svc_r.health()["refresh_every"] == 3
    assert svc_r.health()["updates_since_refresh"] == 1  # 10 % 3
    np.testing.assert_allclose(np.asarray(svc_r.snapshot.beta),
                               np.asarray(svc_p.snapshot.beta), atol=1e-12)
    np.testing.assert_allclose(np.asarray(svc_r.snapshot.P),
                               np.asarray(svc_p.snapshot.P), atol=1e-9)


def test_update_many_advances_refresh_cadence(dns_setup):
    """Catch-up batches count toward YFM_SERVE_REFRESH too — k accepted
    steps credit the cadence, and the scrubbed state stays at oracle parity
    with the plain run."""
    spec, p, data = dns_setup
    svc_r = _service(spec, p, data, refresh_every=4)
    svc_p = _service(spec, p, data)
    Y = data[:, T_ORIGIN:T_ORIGIN + 6]
    svc_r.update_many(T_ORIGIN, Y)
    svc_p.update_many(T_ORIGIN, Y)
    assert svc_r.health()["updates_since_refresh"] == 0  # 6 ≥ 4 → scrubbed
    np.testing.assert_allclose(np.asarray(svc_r.snapshot.P),
                               np.asarray(svc_p.snapshot.P), atol=1e-9)


def test_health_report_vocabulary(dns_setup):
    spec, p, data = dns_setup
    svc = _service(spec, p, data, engine="sqrt")
    svc.update(T_ORIGIN, data[:, T_ORIGIN])
    h = svc.health()
    assert h["status"] == "ok" and h["engine"] == "sqrt"
    assert h["cov_min_eig"] > 0 and np.isfinite(h["cov_cond"])
    assert h["rebuilds"] == 0 and h["last_code"] == 0


# ---------------------------------------------------------------------------
# long-horizon drift regression (satellite: the health monitor's yardstick)
# ---------------------------------------------------------------------------

def test_long_horizon_online_drift_5k_updates(dns_setup):
    """5,000 recursive online updates (f64, chunked through the bucketed
    catch-up program) vs ONE batch filter pass and the independent NumPy
    oracle: the covariance must stay PSD the whole way and the final state
    must agree — the regression the per-update health watch is measured
    against."""
    spec, p, _ = dns_setup
    T_LONG = 5000 + T_ORIGIN
    rng = np.random.default_rng(11)
    panel = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T_LONG)

    snap = serving.freeze_snapshot(spec, p, panel[:, :T_ORIGIN])
    params = jnp.asarray(p, dtype=jnp.float64)
    st = serving.OnlineState(snap.beta, snap.P)
    min_eigs = []
    for lo in range(T_ORIGIN, T_LONG, 125):
        hi = min(lo + 125, T_LONG)
        st, _, oks = serving.update_k(spec, params, st,
                                      jnp.asarray(panel[:, lo:hi]))
        assert bool(np.asarray(oks).all())
        w = np.linalg.eigvalsh(np.asarray(st.cov, dtype=np.float64))
        min_eigs.append(float(w[0]))
    assert min(min_eigs) > 0  # PSD at every checkpoint, not just the end

    # one batch filter pass over the whole panel (library, univariate scan)
    from yieldfactormodels_jl_tpu.ops.smoother import forward_moments

    _, outs = forward_moments(spec, params, jnp.asarray(panel), 0, T_LONG,
                              "univariate")
    np.testing.assert_allclose(np.asarray(st.beta),
                               np.asarray(outs["beta_upd"][-1]), atol=1e-8)
    np.testing.assert_allclose(np.asarray(st.cov),
                               np.asarray(outs["P_upd"][-1]), atol=1e-8)

    # independent float64 NumPy oracle (tests/oracle.py), never another JAX
    # path alone (CLAUDE.md parity rule)
    kp = unpack_kalman(spec, params)
    Z = np.asarray(oracle.dns_loadings(float(np.asarray(kp.gamma)[0]),
                                       np.asarray(MATS)))
    betas, Ps, _ = oracle.online_filter(
        Z, np.zeros(spec.N), np.asarray(kp.Phi), np.asarray(kp.delta),
        np.asarray(kp.Omega_state), float(kp.obs_var), panel)
    np.testing.assert_allclose(np.asarray(st.beta), betas[-1], atol=1e-7)
    np.testing.assert_allclose(np.asarray(st.cov), Ps[-1], atol=1e-7)


# ---------------------------------------------------------------------------
# health module units
# ---------------------------------------------------------------------------

def test_state_health_flags():
    P = np.diag([1.0, 2.0, 3.0])
    h = rh.state_health(np.zeros(3), P)
    assert h["code"] == tax.OK and h["min_eig"] == pytest.approx(1.0)
    h = rh.state_health(np.zeros(3), P - 2.5 * np.eye(3))
    assert h["code"] == tax.NONPSD_COV
    h = rh.state_health(np.full(3, np.nan), P)
    assert h["code"] == tax.NAN_STATE
    # sqrt engine: the factor's product is watched, not the factor itself
    S = np.linalg.cholesky(P)
    h = rh.state_health(np.zeros(3), S, engine="sqrt")
    assert h["code"] == tax.OK


def test_refresh_state_projects_to_psd():
    P = np.diag([1.0, -0.5, 2.0])  # indefinite
    P2 = rh.refresh_state(np.zeros(3), P)
    assert np.linalg.eigvalsh(P2)[0] >= 0
    S = rh.refresh_state(np.zeros(3), np.linalg.cholesky(np.diag([1., 2., 3.])),
                         engine="sqrt")
    np.testing.assert_allclose(S @ S.T, np.diag([1.0, 2.0, 3.0]), atol=1e-12)
