"""Parity tests for the fused Pallas score-driven loss kernel (ops/pallas_ssd).

Interpret mode under float64 against BOTH the XLA scan engine and the NumPy
oracle (house rule).  Fixtures are the stable points of
tests/test_score_driven.py; tolerances follow that suite's rtol=1e-6 — the
score-driven recursion amplifies last-ulp differences through T steps (its
inner gradients can reach 1e12 at wilder points), so elementwise bit-parity
is not the contract even between two exact implementations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from yieldfactormodels_jl_tpu import create_model, get_loss
from yieldfactormodels_jl_tpu.ops.pallas_ssd import batched_loss

from tests import oracle
from tests.test_score_driven import (_lambda_params, _neural_params,
                                     _struct)

CASES = [
    ("1SSD-NNS", False, True, True),        # the reference driver's model
    ("1SD-NNS", False, False, True),
    ("1SD-NNS-Anchored", False, False, False),
    ("1RWSD-NNS", True, False, True),
]


def _batch(p, n=3, scale=1e-3, seed=0):
    rng = np.random.default_rng(seed)
    b = np.tile(np.asarray(p), (n, 1))
    b[1:] += scale * rng.standard_normal((n - 1, b.shape[1]))
    return jnp.asarray(b)


@pytest.mark.parametrize("code,rw,sg,tb", CASES)
def test_pallas_ssd_matches_engine_and_oracle(maturities, yields_panel,
                                              code, rw, sg, tb):
    spec, _ = create_model(code, tuple(maturities), float_type="float64")
    rng = np.random.default_rng(7)
    p, struct = _neural_params(spec, rng, rw)
    data = yields_panel[:, :50]
    want_preds = oracle.msed_neural_filter(
        struct, maturities, data, tb, scale_grad=sg,
        forget_factor=spec.forget_factor)
    want_oracle = oracle.msed_loss_from_preds(want_preds, data)
    batch = _batch(p)
    want = np.asarray(jax.vmap(
        lambda q: get_loss(spec, q, jnp.asarray(data)))(batch))
    got = np.asarray(batched_loss(spec, batch, jnp.asarray(data)))
    np.testing.assert_allclose(got[0], want_oracle, rtol=1e-6)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_pallas_ssd_lambda_family(maturities, yields_panel):
    """SD-NS / SSD-NS / RW variants: scalar-γ DNS loadings, analytic dλ —
    checked against the engine AND the independent NumPy oracle (house rule:
    never against another JAX path alone)."""
    for code, rw, sg in (("SD-NS", False, False), ("SSD-NS", False, True),
                         ("RWSD-NS", True, False)):
        spec, _ = create_model(code, tuple(maturities), float_type="float64")
        p, _ = _lambda_params(spec, rw)
        batch = _batch(p)
        data = jnp.asarray(yields_panel[:, :50])
        want_preds = oracle.msed_lambda_filter(
            _struct(p, rw), maturities, np.asarray(data), scale_grad=sg,
            forget_factor=spec.forget_factor)
        want_oracle = oracle.msed_loss_from_preds(want_preds, np.asarray(data))
        want = np.asarray(jax.vmap(lambda q: get_loss(spec, q, data))(batch))
        got = np.asarray(batched_loss(spec, batch, data))
        np.testing.assert_allclose(got[0], want_oracle, rtol=1e-6, err_msg=code)
        np.testing.assert_allclose(got, want, rtol=1e-6, err_msg=code)


def test_pallas_ssd_window(maturities, yields_panel):
    spec, _ = create_model("1SSD-NNS", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(7)
    p, _ = _neural_params(spec, rng, False)
    batch = _batch(p, n=2)
    data = jnp.asarray(yields_panel[:, :60])
    want = np.asarray(jax.vmap(
        lambda q: get_loss(spec, q, data, 5, 48))(batch))
    got = np.asarray(batched_loss(spec, batch, data, 5, 48))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_pallas_ssd_nan_column_transition_only(maturities, yields_panel):
    """A fully-NaN column is a transition-only step (filter.jl:53-60)."""
    spec, _ = create_model("1SSD-NNS", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(7)
    p, _ = _neural_params(spec, rng, False)
    batch = _batch(p, n=2)
    data = np.array(yields_panel[:, :50])
    data[:, 20] = np.nan
    data = jnp.asarray(data)
    want = np.asarray(jax.vmap(lambda q: get_loss(spec, q, data))(batch))
    got = np.asarray(batched_loss(spec, batch, data))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_pallas_ssd_partial_nan_poisons(maturities, yields_panel):
    """Partially-NaN observed column ⇒ −Inf, matching the engine's poison."""
    spec, _ = create_model("1SSD-NNS", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(7)
    p, _ = _neural_params(spec, rng, False)
    batch = _batch(p, n=2)
    data = np.array(yields_panel[:, :50])
    data[3, 20] = np.nan
    data = jnp.asarray(data)
    want = np.asarray(jax.vmap(lambda q: get_loss(spec, q, data))(batch))
    got = np.asarray(batched_loss(spec, batch, data))
    assert np.all(want == -np.inf)
    assert np.all(got == -np.inf)


def test_pallas_ssd_family_validation(maturities):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    with pytest.raises(ValueError, match="MSED"):
        batched_loss(spec, jnp.zeros((1, spec.n_params)),
                     jnp.zeros((len(maturities), 10)))


def test_estimate_steps_ssd_engine_quality(maturities, yields_panel,
                                           monkeypatch):
    """Block-coordinate estimation with the kernel-backed value engine
    (YFM_SSD_PALLAS=force → interpret on CPU) is a valid optimizer swap:
    deterministic, finite, and at least as good as the scan engine up to the
    tolerance-parity doctrine (SURVEY §7) — the L-BFGS implementations differ
    (batched Armijo vs optax backtracking), so trajectory equality is NOT the
    contract, optimum quality is."""
    from yieldfactormodels_jl_tpu.estimation import optimize
    from yieldfactormodels_jl_tpu.models import api

    spec, _ = create_model("1SSD-NNS", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(7)
    p, _ = _neural_params(spec, rng, False)
    data = jnp.asarray(yields_panel[:, :40])
    groups = list(api.get_param_groups(spec, None))
    budgets = {"1": ("neldermead", dict(max_iters=25)),
               "2": ("lbfgs", dict(max_iters=8, g_tol=1e-6, f_abstol=1e-6))}

    def run():
        return optimize.estimate_steps(spec, data, np.asarray(p)[:, None],
                                       groups, max_group_iters=1,
                                       optimizers=budgets)

    monkeypatch.setenv("YFM_SSD_PALLAS", "0")
    _, ll_scan, _, _ = run()
    monkeypatch.setenv("YFM_SSD_PALLAS", "force")
    _, ll_pal, best_pal, _ = run()
    _, ll_pal2, best_pal2, _ = run()
    assert np.isfinite(ll_scan) and np.isfinite(ll_pal)
    assert ll_pal == ll_pal2                       # deterministic
    np.testing.assert_allclose(best_pal, best_pal2, rtol=0, atol=0)
    # not catastrophically worse than the scan engine (loss is −MSE ≤ 0;
    # this run it is strictly BETTER: −0.023 vs −0.066)
    assert ll_pal >= ll_scan - 0.1 * abs(ll_scan)


def test_nelder_mead_batched_trajectory_parity():
    """The lockstep-batched NM follows the sequential optimizer's trajectory
    per start (the batched docstring's '(tested)' claim lives here).  The
    vmapped objective compiles with different reduction orderings than the
    scalar one (last-ulp value differences), so the contract is tight
    agreement of the optimum, not bitwise state equality."""
    from yieldfactormodels_jl_tpu.estimation.neldermead import (
        nelder_mead, nelder_mead_batched)

    def rosen(x):
        return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                       + (1 - x[:-1]) ** 2)

    X0 = jnp.asarray(np.random.default_rng(0).standard_normal((3, 5)))
    batch_fun = jax.jit(jax.vmap(jax.vmap(rosen)))
    Xb, fb, itb = nelder_mead_batched(batch_fun, X0, max_iters=300)
    for s in range(3):
        xs, fs, its = nelder_mead(rosen, X0[s], max_iters=300)
        np.testing.assert_allclose(np.asarray(Xb[s]), np.asarray(xs),
                                   rtol=1e-6, atol=1e-9)
        np.testing.assert_allclose(float(fb[s]), float(fs),
                                   rtol=1e-6, atol=1e-12)
        assert abs(int(itb[s]) - int(its)) <= 10
