"""Top-level run() end-to-end tests (reference call-stack §3.1 parity)."""

import os

import numpy as np

from yieldfactormodels_jl_tpu.run import run

MATS_MONTHS = np.array([3.0, 12.0, 24.0, 60.0, 120.0, 360.0])


def _write_data(scratch, thread_id="1", T=40, simulation=False):
    sub = "data_simulation" if simulation else "data"
    folder = os.path.join(scratch, "YieldFactorModels.jl", sub)
    os.makedirs(folder, exist_ok=True)
    rng = np.random.default_rng(11)
    data = np.cumsum(rng.standard_normal((len(MATS_MONTHS), T)) * 0.1, axis=1) + 5.0
    np.savetxt(os.path.join(folder, f"thread_id__{thread_id}__data.csv"),
               data, delimiter=",")
    np.savetxt(os.path.join(folder, f"thread_id__{thread_id}__maturities.csv"),
               MATS_MONTHS / 12.0, delimiter=",")
    return data


def test_run_simulation_mode_rw(tmp_path, monkeypatch):
    """simulation=True forces no-window forecasting, no optimization, no saving
    (YieldFactorModels.jl:241-246)."""
    monkeypatch.chdir(tmp_path)
    scratch = str(tmp_path) + os.sep
    _write_data(scratch, simulation=True)
    out = run("1", 30, 3, True, "RW", "float64",
              simulation=True, scratch_dir=scratch)
    assert out is not None
    csv = os.path.join(scratch, "YieldFactorModels.jl", "results_simulation",
                       "thread_id__1", "RW",
                       "RW__thread_id__1__expanding_window_forecasts.csv")
    assert os.path.isfile(csv)
    arr = np.loadtxt(csv, delimiter=",")
    assert arr.shape[1] == 2 + 3 + 1 + len(MATS_MONTHS)


def test_run_no_optimization_saves_artifacts(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    scratch = str(tmp_path) + os.sep
    _write_data(scratch)
    out = run("1", 30, 3, False, "NS", "float64",
              run_optimization=False, scratch_dir=scratch)
    assert out is not None
    res = os.path.join(scratch, "YieldFactorModels.jl", "results", "thread_id__1", "NS")
    for suffix in ("factors_filtered_insample", "fit_filtered_insample",
                   "factor_loadings_1_filtered_insample", "loss", "out_params"):
        assert os.path.isfile(
            os.path.join(res, f"NS__thread_id__1__{suffix}.csv")), suffix
    # random initial parameters were written for reuse (fallback path)
    assert os.path.isfile(os.path.join(
        str(tmp_path), "YieldFactorModels.jl", "initializations", "NS",
        "init_params_NS.csv"))


def test_run_placeholder_returns_none(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    scratch = str(tmp_path) + os.sep
    _write_data(scratch)
    assert run("1", 30, 3, False, "pC", "float64", scratch_dir=scratch) is None


def test_run_rolling_batched_windows_end_to_end(tmp_path, monkeypatch):
    """run(batched_windows=True): the QUICKSTART-advertised device-batched
    rolling path, wired through the full driver (estimate → predict → shards
    → merged DB → legacy CSV)."""
    monkeypatch.chdir(tmp_path)
    scratch = str(tmp_path) + os.sep
    _write_data(scratch, T=36)
    run("1", 32, 3, True, "NS", "float64",
        window_type="expanding", run_optimization=False,
        batched_windows=True, scratch_dir=scratch)
    res = os.path.join(scratch, "YieldFactorModels.jl", "results", "thread_id__1", "NS")
    merged = os.path.join(res, "db", "forecasts_expanding_merged.sqlite3")
    assert os.path.isfile(merged)
    csv = os.path.join(res, "NS__thread_id__1__expanding_window_forecasts.csv")
    arr = np.loadtxt(csv, delimiter=",")
    assert arr.shape == (5 * 3, 2 + len(MATS_MONTHS))
    assert np.isfinite(arr).all()


def test_run_flagship_with_estimation(tmp_path, monkeypatch):
    """The reference's OWN driver flow (test.jl:22-27): run() on 1SSD-NNS
    with optimization enabled — A/B-grid initialization + block-coordinate
    estimate_steps (1 group iteration keeps the CPU cost test-sized) —
    through filtering and artifact export."""
    monkeypatch.chdir(tmp_path)
    scratch = str(tmp_path) + os.sep
    _write_data(scratch, T=40)
    out = run("1", 34, 3, False, "1SSD-NNS", "float64",
              run_optimization=True, max_group_iters=1,
              scratch_dir=scratch)
    assert out is not None
    res = os.path.join(scratch, "YieldFactorModels.jl", "results",
                       "thread_id__1", "1SSD-NNS")
    loss_csv = os.path.join(res, "1SSD-NNS__thread_id__1__loss.csv")
    assert os.path.isfile(loss_csv)
    loss = float(np.loadtxt(loss_csv, delimiter=","))
    assert np.isfinite(loss), loss
    params_csv = os.path.join(res, "1SSD-NNS__thread_id__1__out_params.csv")
    assert os.path.isfile(params_csv)
    assert np.isfinite(np.loadtxt(params_csv, delimiter=",")).all()


def test_run_orchestrated_rolling_rw(tmp_path, monkeypatch):
    """run(orchestrated=True): the same rolling windows as the lock-loop
    driver, executed as leased queue tasks by 2 in-process workers
    (orchestration/supervisor.py) — merged DB + legacy CSV still land."""
    monkeypatch.chdir(tmp_path)
    scratch = str(tmp_path) + os.sep
    _write_data(scratch, T=36)
    run("1", 32, 3, True, "RW", "float64",
        window_type="expanding", run_optimization=False,
        reestimate=False, orchestrated=True, n_workers=2,
        scratch_dir=scratch)
    res = os.path.join(scratch, "YieldFactorModels.jl", "results", "thread_id__1", "RW")
    merged = os.path.join(res, "db", "forecasts_expanding_merged.sqlite3")
    assert os.path.isfile(merged)
    queue = os.path.join(res, "db", "queue.sqlite3")
    assert os.path.isfile(queue)  # the run was journaled, not mkdir-locked
    csv = os.path.join(res, "RW__thread_id__1__expanding_window_forecasts.csv")
    arr = np.loadtxt(csv, delimiter=",")
    assert arr.shape == (5 * 3, 2 + len(MATS_MONTHS))


def test_run_rolling_rw_end_to_end(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    scratch = str(tmp_path) + os.sep
    data = _write_data(scratch, T=36)
    run("1", 32, 3, True, "RW", "float64",
        window_type="expanding", run_optimization=False,
        reestimate=False, scratch_dir=scratch)
    res = os.path.join(scratch, "YieldFactorModels.jl", "results", "thread_id__1", "RW")
    merged = os.path.join(res, "db", "forecasts_expanding_merged.sqlite3")
    assert os.path.isfile(merged)
    csv = os.path.join(res, "RW__thread_id__1__expanding_window_forecasts.csv")
    arr = np.loadtxt(csv, delimiter=",")
    assert arr.shape == (5 * 3, 2 + len(MATS_MONTHS))
