"""Kalman/EKF scan-kernel golden tests vs the NumPy oracle."""

import jax.numpy as jnp
import numpy as np

from tests import oracle
from yieldfactormodels_jl_tpu import create_model, get_loss, get_loss_array, predict
from yieldfactormodels_jl_tpu.models import kalman as K
from yieldfactormodels_jl_tpu.models.params import unpack_kalman


def _dns_params(M=3):
    """Constrained flat vector [γ, σ², chol(6), δ, Φ_rowmajor] + its pieces."""
    p = np.zeros(20)
    p[0] = np.log(0.5)
    p[1] = 4e-4
    p[2], p[4], p[7] = 0.10, 0.08, 0.12   # chol diag
    p[3], p[5], p[6] = 0.01, -0.02, 0.005  # chol off-diag
    p[8:11] = [0.3, -0.1, 0.05]
    Phi = np.array([[0.95, 0.02, 0.0], [0.01, 0.9, 0.03], [0.0, 0.02, 0.85]])
    p[11:20] = Phi.reshape(-1)
    C = np.array([[0.10, 0.01, -0.02], [0, 0.08, 0.005], [0, 0, 0.12]])
    return p, Phi, p[8:11].copy(), C.T @ C, 4e-4


def test_unpack_kalman_layout(maturities):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, Phi, delta, Omega, obs_var = _dns_params()
    kp = unpack_kalman(spec, jnp.asarray(p))
    np.testing.assert_allclose(kp.Phi, Phi, rtol=1e-12)
    np.testing.assert_allclose(kp.delta, delta, rtol=1e-12)
    np.testing.assert_allclose(kp.Omega_state, Omega, rtol=1e-12)
    assert float(kp.obs_var) == obs_var


def test_kalman_loglik_matches_oracle(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, Phi, delta, Omega, obs_var = _dns_params()
    Z = oracle.dns_loadings(p[0], maturities)
    want = oracle.kalman_filter_loglik(Z, Phi, delta, Omega, obs_var, yields_panel)
    got = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(got, want, rtol=1e-8)


def test_kalman_masked_prefix_equals_truncation(maturities, yields_panel):
    """Leading-NaN masking == truncation (the rolling-window vmap lever)."""
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, *_ = _dns_params()
    T = yields_panel.shape[1]
    full = jnp.asarray(yields_panel)
    lo, hi = 10, 60
    masked = float(K.get_loss(spec, jnp.asarray(p), full, start=lo, end=hi))
    trunc = float(K.get_loss(spec, jnp.asarray(p), full[:, lo:hi]))
    np.testing.assert_allclose(masked, trunc, rtol=1e-9)


def test_kalman_nonstationary_gives_neg_inf(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, *_ = _dns_params()
    p[11] = 1.5  # explosive Phi[0,0] ⇒ invalid unconditional covariance
    got = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    assert got == -np.inf


def test_ekf_tvl_matches_oracle(maturities, yields_panel):
    spec, _ = create_model("TVλ", tuple(maturities), float_type="float64")
    assert spec.n_params == 31  # SURVEY.md §2.13
    Ms = 4
    p = np.zeros(31)
    p[0] = 4e-4
    # chol: diag entries at column-wise positions
    chol_diag_pos = [1, 3, 6, 10]
    C = np.zeros((Ms, Ms))
    k = 1
    for j in range(Ms):
        for i in range(j + 1):
            val = 0.09 + 0.01 * i if i == j else 0.004 * (i + j)
            C[i, j] = val
            p[k] = val
            k += 1
    delta = np.array([0.3, -0.1, 0.05, np.log(0.5) * 0.05])
    p[11:15] = delta
    Phi = np.diag([0.95, 0.9, 0.85, 0.95])
    Phi[0, 1] = 0.01
    p[15:31] = Phi.reshape(-1)
    want = oracle.ekf_tvl_loglik(Phi, delta, C.T @ C, 4e-4, maturities, yields_panel)
    got = float(get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    np.testing.assert_allclose(got, want, rtol=1e-7)


def test_kalman_predict_shapes_and_alignment(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, *_ = _dns_params()
    h = 6
    ext = np.concatenate([yields_panel, np.full((len(maturities), h - 1), np.nan)], axis=1)
    res = predict(spec, jnp.asarray(p), jnp.asarray(ext))
    N, T = ext.shape
    assert res["preds"].shape == (N, T)
    assert res["factors"].shape == (3, T)
    assert res["states"].shape == (1, T)
    assert np.all(np.isfinite(np.asarray(res["preds"])))
    # trailing forecast columns are pure transitions of the last filtered state
    tail = np.asarray(res["preds"][:, -(h - 1):])
    assert np.all(np.isfinite(tail))


def test_kalman_loss_array_K_replay(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, *_ = _dns_params()
    a1 = np.asarray(get_loss_array(spec, jnp.asarray(p), jnp.asarray(yields_panel), K=1))
    a2 = np.asarray(get_loss_array(spec, jnp.asarray(p), jnp.asarray(yields_panel), K=2))
    assert a1.shape == (yields_panel.shape[1] - 1,)
    # pass 2 continues from the end state, so K=2 is NOT just a rescaled K=1
    assert not np.allclose(a2, a1 / 2.0)
    assert not np.allclose(a2, a1)
