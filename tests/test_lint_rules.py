"""Per-rule positive/negative fixtures: every graftlint rule must fire on a
bad snippet AND stay quiet on the idiomatic one — the non-vacuity contract
the pre-graftlint AST guards hand-rolled one test at a time.

Each fixture builds a tiny repo tree under tmp_path, so rules that key on
file location (kernel modules, serving/, orchestration/) see realistic
paths, and rules that key on repo anchors (CLAUDE.md, config.py, tests/,
the reference tree) get controlled ones.
"""

import textwrap

from yieldfactormodels_jl_tpu.analysis import (LintConfig,
                                               detect_jit_contexts,
                                               names_reaching_return,
                                               parent_map, run_lint)

PKG = "yieldfactormodels_jl_tpu"


def lint(tmp_path, rel, source, rules, claude_md="", **cfg_kwargs):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    (tmp_path / "CLAUDE.md").write_text(claude_md)
    cfg = LintConfig(root=str(tmp_path), **cfg_kwargs)
    res = run_lint(cfg, files=[rel], rules=rules)
    assert not res.errors, res.errors
    return res


def fired(res, rule_id):
    return [f for f in res.findings if f.rule == rule_id]


# ---------------------------------------------------------------------------
# YFM001 — sentinel discipline
# ---------------------------------------------------------------------------

def test_yfm001_fires_on_raise_in_kernel_scan_body(tmp_path):
    res = lint(tmp_path, f"{PKG}/ops/kern.py", """\
        def get_loss(spec, params):
            def step(carry, y):
                raise RuntimeError("boom")
            return step
    """, ["YFM001"])
    assert fired(res, "YFM001")


def test_yfm001_quiet_on_tracetime_validation_and_sentinels(tmp_path):
    res = lint(tmp_path, f"{PKG}/ops/kern.py", """\
        import jax.numpy as jnp

        def get_loss(spec, params):
            if spec is None:
                raise ValueError("bad spec")
            def step(carry, y):
                return carry, jnp.where(y > 0, y, -jnp.inf)
            return step
    """, ["YFM001"])
    assert not res.findings


def test_yfm001_fires_on_nonwhitelisted_toplevel_raise_in_kernel(tmp_path):
    res = lint(tmp_path, f"{PKG}/ops/kern.py", """\
        def get_loss(spec):
            raise RuntimeError("driver-style error in a kernel module")
    """, ["YFM001"])
    assert fired(res, "YFM001")


def test_yfm001_detects_jit_contexts_outside_kernel_modules(tmp_path):
    # jit-decorated function whose scan body raises: fires even though the
    # module is not in the historical kernel set
    res = lint(tmp_path, f"{PKG}/models/extra.py", """\
        import jax
        from jax import lax

        @jax.jit
        def loss(x):
            def body(c, y):
                if y is None:
                    raise RuntimeError("traced")
                return c, y
            return lax.scan(body, x, x)
    """, ["YFM001"])
    assert fired(res, "YFM001")


def test_yfm001_quiet_on_driver_layer_raise(tmp_path):
    # plain driver code raising structured errors is the documented policy
    res = lint(tmp_path, f"{PKG}/models/extra.py", """\
        def estimate(spec, data):
            def check(d):
                if d is None:
                    raise RuntimeError("driver closure, never traced")
            check(data)
    """, ["YFM001"])
    assert not res.findings


# ---------------------------------------------------------------------------
# YFM002 — donation aliasing
# ---------------------------------------------------------------------------

def test_yfm002_fires_on_silently_dropped_donation(tmp_path):
    res = lint(tmp_path, f"{PKG}/estimation/extra.py", """\
        import jax

        def build():
            def fn(params, acc):
                return params * 2.0
            return jax.jit(fn, donate_argnums=(1,))
    """, ["YFM002"])
    assert fired(res, "YFM002")
    assert "acc" in res.findings[0].message


def test_yfm002_quiet_on_passthrough_and_flow_through_calls(tmp_path):
    # direct pass-through, flow through an assignment chain, and the
    # conditional donate_argnums idiom are all idiomatic (DESIGN §14)
    res = lint(tmp_path, f"{PKG}/estimation/extra.py", """\
        import jax

        def build(donate):
            def fn(params, beta, cov):
                st = step(make_state(beta, cov))
                out = transform(st)
                return out, params
            return jax.jit(fn, donate_argnums=(0, 1, 2) if donate else ())
    """, ["YFM002"])
    assert not res.findings


def test_yfm002_fires_on_out_of_range_index(tmp_path):
    res = lint(tmp_path, f"{PKG}/estimation/extra.py", """\
        import jax

        def build():
            def fn(a):
                return a
            return jax.jit(fn, donate_argnums=(3,))
    """, ["YFM002"])
    assert fired(res, "YFM002")


def test_yfm002_resolves_dynamic_append_built_donate_argnums(tmp_path):
    # the scenario-lattice idiom: donate_argnums built as a list of
    # conditional appends, passed as tuple(...) — must still be analyzed
    res = lint(tmp_path, f"{PKG}/estimation/extra.py", """\
        import jax

        def build(with_acc):
            def run(key, idx, acc):
                return core(idx)
            donate_argnums = []
            donate_argnums.append(1)
            if with_acc:
                donate_argnums.append(2)
            return jax.jit(run, donate_argnums=tuple(donate_argnums))
    """, ["YFM002"])
    hits = fired(res, "YFM002")
    assert len(hits) == 1 and "'acc'" in hits[0].message  # idx flows, acc dead


def test_yfm002_checks_partial_decorator_form(tmp_path):
    res = lint(tmp_path, f"{PKG}/estimation/extra.py", """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(1,))
        def run(params, acc):
            return params * 2.0
    """, ["YFM002"])
    assert fired(res, "YFM002")
    res = lint(tmp_path, f"{PKG}/estimation/extra.py", """\
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(1,))
        def run(params, acc):
            return params * 2.0, acc
    """, ["YFM002"])
    assert not res.findings


def test_names_reaching_return_closure():
    # the engine's backward-reachability helper: subscript-target writes
    # into a returned dict count as flow (the scenario-lattice shape)
    import ast
    fn = ast.parse(textwrap.dedent("""\
        def run(idx, acc):
            out = {}
            losses = core(acc)
            out["losses"] = losses
            out["resample_idx"] = idx
            return out
    """)).body[0]
    reach = names_reaching_return(fn)
    assert {"idx", "acc", "out", "losses"} <= reach


# ---------------------------------------------------------------------------
# YFM003 — cache idiom order
# ---------------------------------------------------------------------------

def test_yfm003_fires_on_swapped_decorators(tmp_path):
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        from functools import lru_cache
        from ..config import register_engine_cache

        @lru_cache(maxsize=64)
        @register_engine_cache
        def _jitted_thing(spec):
            return spec
    """, ["YFM003"])
    assert fired(res, "YFM003")


def test_yfm003_fires_on_registrar_without_lru_cache(tmp_path):
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        from ..config import register_engine_cache

        @register_engine_cache
        def _jitted_thing(spec):
            return spec
    """, ["YFM003"])
    assert fired(res, "YFM003")


def test_yfm003_quiet_on_canonical_order(tmp_path):
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        from functools import lru_cache
        from ..config import register_engine_cache

        @register_engine_cache
        @lru_cache(maxsize=64)
        def _jitted_thing(spec):
            return spec
    """, ["YFM003"])
    assert not res.findings


# ---------------------------------------------------------------------------
# YFM004 — host impurity in jit
# ---------------------------------------------------------------------------

def test_yfm004_fires_on_host_calls_in_jitted_body(tmp_path):
    res = lint(tmp_path, f"{PKG}/models/extra.py", """\
        import os
        import time
        import numpy as np
        import jax

        @jax.jit
        def loss(x):
            t = time.time()
            noise = np.random.normal()
            knob = os.environ.get("YFM_CHAOS")
            return x + t + noise
    """, ["YFM004"])
    assert len(fired(res, "YFM004")) == 3


def test_yfm004_quiet_on_driver_and_note_trace(tmp_path):
    # host calls at the driver layer are fine; note_trace is the documented
    # trace-counter idiom (one host call per (re)trace, by design)
    res = lint(tmp_path, f"{PKG}/models/extra.py", """\
        import time
        import jax

        def build(spec):
            t0 = time.time()

            def fn(x):
                note_trace("fn")
                return x * 2.0

            print(f"built in {time.time() - t0:.3f}s")
            return jax.jit(fn)
    """, ["YFM004"])
    assert not res.findings


# ---------------------------------------------------------------------------
# YFM005 — atomic publish
# ---------------------------------------------------------------------------

def test_yfm005_fires_on_plain_write_in_orchestration(tmp_path):
    res = lint(tmp_path, f"{PKG}/orchestration/extra.py", """\
        def publish(path, payload):
            with open(path, "w") as fh:
                fh.write(payload)
    """, ["YFM005"])
    assert fired(res, "YFM005")


def test_yfm005_quiet_on_tmp_plus_replace_and_reads(tmp_path):
    res = lint(tmp_path, f"{PKG}/persistence/extra.py", """\
        import os

        def publish(path, payload):
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                fh.write(payload)
            os.replace(tmp, path)

        def load(path):
            with open(path) as fh:
                return fh.read()
    """, ["YFM005"])
    assert not res.findings


def test_yfm005_unrelated_replace_does_not_vouch(tmp_path):
    # an atomic publish elsewhere in the function must not green-light a
    # direct torn-file-prone write to a DIFFERENT path
    res = lint(tmp_path, f"{PKG}/persistence/extra.py", """\
        import os
        import numpy as np

        def export(p, q, rows, other):
            np.savetxt(p, rows)
            tmp = f"{q}.tmp-{os.getpid()}"
            np.savetxt(tmp, other)
            os.replace(tmp, q)
    """, ["YFM005"])
    hits = fired(res, "YFM005")
    assert len(hits) == 1 and hits[0].line == 5


def test_yfm005_quiet_outside_atomic_dirs(tmp_path):
    # result CSVs under utils/ etc. are not shard/DB publishes
    res = lint(tmp_path, f"{PKG}/utils/extra.py", """\
        def dump(path, payload):
            with open(path, "w") as fh:
                fh.write(payload)
    """, ["YFM005"])
    assert not res.findings


# ---------------------------------------------------------------------------
# YFM006 — env-knob documentation
# ---------------------------------------------------------------------------

def test_yfm006_fires_on_undocumented_knob(tmp_path):
    res = lint(tmp_path, f"{PKG}/models/extra.py", """\
        import os
        FLAG = os.environ.get("YFM_SHINY_NEW_TOGGLE", "0")
    """, ["YFM006"], claude_md="Knobs: `YFM_CHAOS` only.\n")
    assert fired(res, "YFM006")
    assert "YFM_SHINY_NEW_TOGGLE" in res.findings[0].message


def test_yfm006_quiet_on_documented_knob(tmp_path):
    res = lint(tmp_path, f"{PKG}/models/extra.py", """\
        import os
        FLAG = os.environ.get("YFM_CHAOS", "")
    """, ["YFM006"], claude_md="`YFM_CHAOS` arms fault injection.\n")
    assert not res.findings


def test_yfm006_prefix_of_documented_knob_still_fires(tmp_path):
    # exact-token membership: a knob that is a proper PREFIX of a documented
    # one must not pass on the longer name's substring
    res = lint(tmp_path, f"{PKG}/models/extra.py", """\
        import os
        FLAG = os.environ.get("YFM_LOCK", "")
    """, ["YFM006"], claude_md="`YFM_LOCK_TTL` is documented; bare it isn't.\n")
    assert fired(res, "YFM006")


def test_yfm006_bench_knobs_checked_in_bench_layer_only(tmp_path):
    # BENCH_* is a bench-layer namespace: an undocumented BENCH_ name in a
    # benchmarks file fires, the same name in package source does not
    bad = """\
        import os
        N = int(os.environ.get("BENCH_MYSTERY_REPS", "3"))
    """
    res = lint(tmp_path, "benchmarks/extra.py", bad, ["YFM006"],
               claude_md="nothing documented\n")
    assert fired(res, "YFM006")
    res = lint(tmp_path, f"{PKG}/models/extra.py", bad, ["YFM006"],
               claude_md="nothing documented\n")
    assert not res.findings


# ---------------------------------------------------------------------------
# YFM007 — engine-registry parity coverage
# ---------------------------------------------------------------------------

def _engine_tree(tmp_path, tests_body):
    cfgpath = tmp_path / PKG / "config.py"
    cfgpath.parent.mkdir(parents=True, exist_ok=True)
    cfgpath.write_text('KALMAN_ENGINES = ("univariate", "sqrt")\n')
    tdir = tmp_path / "tests"
    tdir.mkdir(exist_ok=True)
    (tdir / "test_parity.py").write_text(textwrap.dedent(tests_body))
    (tmp_path / "CLAUDE.md").write_text("")
    return LintConfig(root=str(tmp_path))


def test_yfm007_fires_on_uncovered_engine(tmp_path):
    cfg = _engine_tree(tmp_path, """\
        from .oracle import kalman_filter_loglik
        ENGINES = ("univariate",)  # 'sqrt' has no oracle-backed mention
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert [f.rule for f in res.findings] == ["YFM007"]
    assert "'sqrt'" in res.findings[0].message


def test_yfm007_quiet_when_all_engines_oracle_covered(tmp_path):
    cfg = _engine_tree(tmp_path, """\
        from .oracle import kalman_filter_loglik
        ENGINES = ("univariate", "sqrt")
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert not res.findings


def test_yfm007_engine_named_without_oracle_import_does_not_count(tmp_path):
    # naming the engine in a non-oracle test is exactly the JAX-vs-JAX
    # parity the convention bans — it must NOT satisfy the rule
    cfg = _engine_tree(tmp_path, """\
        ENGINES = ("univariate", "sqrt")  # no oracle import here
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert len(res.findings) == 2


def _newton_engine_tree(tmp_path, tests_body):
    cfgpath = tmp_path / PKG / "config.py"
    cfgpath.parent.mkdir(parents=True, exist_ok=True)
    cfgpath.write_text('KALMAN_ENGINES = ("univariate",)\n'
                       'NEWTON_ENGINES = ("fisher", "exact")\n')
    tdir = tmp_path / "tests"
    tdir.mkdir(exist_ok=True)
    (tdir / "test_parity.py").write_text(textwrap.dedent(tests_body))
    (tmp_path / "CLAUDE.md").write_text("")
    return LintConfig(root=str(tmp_path))


def test_yfm007_fires_on_uncovered_newton_engine(tmp_path):
    # the second-order registry rides the same parity contract as
    # KALMAN_ENGINES: a NEWTON_ENGINES entry with no oracle-backed mention
    # must fire
    cfg = _newton_engine_tree(tmp_path, """\
        from .oracle import fd_hessian
        ENGINES = ("univariate", "fisher")  # 'exact' uncovered
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert [f.rule for f in res.findings] == ["YFM007"]
    assert "'exact'" in res.findings[0].message


def test_yfm007_quiet_when_newton_engines_oracle_covered(tmp_path):
    cfg = _newton_engine_tree(tmp_path, """\
        from .oracle import fd_hessian
        ENGINES = ("univariate", "fisher", "exact")
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert not res.findings


def _slr_engine_tree(tmp_path, tests_body):
    cfgpath = tmp_path / PKG / "config.py"
    cfgpath.parent.mkdir(parents=True, exist_ok=True)
    cfgpath.write_text('KALMAN_ENGINES = ("univariate",)\n'
                       'SLR_ENGINES = ("ekf", "sigma")\n')
    tdir = tmp_path / "tests"
    tdir.mkdir(exist_ok=True)
    (tdir / "test_parity.py").write_text(textwrap.dedent(tests_body))
    (tmp_path / "CLAUDE.md").write_text("")
    return LintConfig(root=str(tmp_path))


def test_yfm007_fires_on_uncovered_slr_linearization(tmp_path):
    # the SLR linearization-rule registry rides the same parity contract:
    # an SLR_ENGINES entry with no oracle-backed mention must fire
    cfg = _slr_engine_tree(tmp_path, """\
        from .oracle import iterated_slr_filter
        ENGINES = ("univariate", "ekf")  # 'sigma' uncovered
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert [f.rule for f in res.findings] == ["YFM007"]
    assert "'sigma'" in res.findings[0].message


def test_yfm007_quiet_when_slr_linearizations_oracle_covered(tmp_path):
    cfg = _slr_engine_tree(tmp_path, """\
        from .oracle import iterated_slr_filter
        ENGINES = ("univariate", "ekf", "sigma")
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert not res.findings


def _program_tree(tmp_path, tests_body):
    cfgpath = tmp_path / PKG / "config.py"
    cfgpath.parent.mkdir(parents=True, exist_ok=True)
    cfgpath.write_text('KALMAN_ENGINES = ("univariate",)\n')
    lib = tmp_path / PKG / "program" / "library.py"
    lib.parent.mkdir(parents=True, exist_ok=True)
    lib.write_text(textwrap.dedent("""\
        MY_PROGRAM = ModelProgram(
            name="myprog",
            kind="kalman",
            factors=3,
        )
    """))
    tdir = tmp_path / "tests"
    tdir.mkdir(exist_ok=True)
    (tdir / "test_parity.py").write_text(textwrap.dedent(tests_body))
    (tmp_path / "CLAUDE.md").write_text("")
    return LintConfig(root=str(tmp_path))


def test_yfm007_fires_on_uncovered_program_name(tmp_path):
    # a shipped ModelProgram declaration rides the engine-parity contract:
    # its name absent from every oracle-backed test module must fire, and
    # the finding anchors at the declaration site, not config.py
    cfg = _program_tree(tmp_path, """\
        from .oracle import kalman_filter_loglik
        ENGINES = ("univariate",)  # 'myprog' has no oracle-backed mention
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert [f.rule for f in res.findings] == ["YFM007"]
    assert "'myprog'" in res.findings[0].message
    assert res.findings[0].file == f"{PKG}/program/library.py"


def test_yfm007_quiet_when_program_name_oracle_covered(tmp_path):
    cfg = _program_tree(tmp_path, """\
        from .oracle import kalman_filter_loglik
        NAMES = ("univariate", "myprog")
    """)
    res = run_lint(cfg, files=[], rules=["YFM007"])
    assert not res.findings


# ---------------------------------------------------------------------------
# YFM008 — request-path hygiene
# ---------------------------------------------------------------------------

def test_yfm008_fires_on_unbounded_queue_and_bare_sleep(tmp_path):
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import queue
        import time

        def pump():
            q = queue.Queue()
            time.sleep(0.1)
            return q
    """, ["YFM008"])
    assert len(fired(res, "YFM008")) == 2


def test_yfm008_quiet_on_bounded_queue_and_event_wait(tmp_path):
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import queue
        import threading

        def pump(stop: threading.Event):
            q = queue.Queue(maxsize=256)
            stop.wait(timeout=0.1)
            return q
    """, ["YFM008"])
    assert not res.findings


def test_yfm008_fires_on_host_gather_in_routing_function(tmp_path):
    """The DESIGN §16 routing-path rule: a host transfer inside the
    per-request routing functions (pump → batch formation → shard routing)
    is an O(registry) tax — it must live at the response boundary."""
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import jax
        import numpy as np

        def _pump_locked(self, batch):
            beta = np.asarray(self.state.beta)   # host gather while routing
            return jax.device_get(batch)
    """, ["YFM008"])
    assert len(fired(res, "YFM008")) == 2


def test_yfm008_quiet_on_host_transfer_at_response_boundary(tmp_path):
    # same calls, but in a collect/finish function: the response boundary
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import jax
        import numpy as np

        def _collect(self, outs):
            return [np.asarray(o) for o in jax.device_get(outs)]
    """, ["YFM008"])
    assert not res.findings


def test_yfm008_fires_on_host_gather_in_tier_planning(tmp_path):
    """The DESIGN §21 tier-routing rule: promotion/eviction PLANNING
    functions (which keys move between tiers) are per-request work and must
    stay pure host routing — the actual freeze/thaw transfer belongs in the
    batched flush boundaries only."""
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import numpy as np

        def _promote_plan(self, keys):
            return np.asarray(self.warm.beta)    # transfer while planning

        def _demote_plan(self, n):
            return np.array(self.clock)

        def prepare_reads(self, keys):
            return np.asarray(keys)

        def _account(self, keys):
            return np.asarray(self.ledger)
    """, ["YFM008"])
    assert len(fired(res, "YFM008")) == 4


def test_yfm008_quiet_on_pure_tier_planning_with_batched_flush(tmp_path):
    # the same module split the sanctioned way: pure planning, transfers
    # confined to the wave-flush boundary
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import jax
        import numpy as np

        def _promote_plan(self, keys):
            want = [k for k in keys if k not in self.slots]
            return {"want": want, "victims": want[:1]}

        def _prepare_batch(self, run_updates, run_batched):
            self.store.prepare_reads([r.key for r in run_batched])

        def _promote_flush_locked(self, plan):
            return np.asarray(jax.device_get(plan))
    """, ["YFM008"])
    assert not res.findings


def test_yfm008_fires_on_host_gather_in_fan_refresh_routing(tmp_path):
    """The DESIGN §23 subscription-routing rule: the hub's dirty-marking and
    wave functions run on the accepted-update hot path — a host gather there
    stalls every subscriber on one fan."""
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import numpy as np

        def _refresh_wave(self, block):
            return np.asarray(block.means)       # gather mid-wave

        def _stage_wave(self, block, lanes):
            return np.asarray(block.refreshed)

        def notify_updated(self, keys):
            return np.array(keys)

        def _mark_dirty(self, keys):
            return np.asarray(self.versions)
    """, ["YFM008"])
    assert len(fired(res, "YFM008")) == 4


def test_yfm008_quiet_on_device_side_fan_refresh(tmp_path):
    # the sanctioned split: device-side staging in the wave, host
    # materialization only at the answer boundary (fan())
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        def _refresh_wave(self, block, fn):
            block.means, block.covs = fn(block.means, block.covs)

        def _stage_wave(self, block, lanes):
            mask = np.zeros((block.capacity,), dtype=bool)
            mask[lanes] = True
            return jnp.asarray(mask)

        def notify_updated(self, keys):
            for key in keys:
                self.dirty[key] = True

        def fan(self, key):
            return np.asarray(self.means[..., 0])
    """, ["YFM008"])
    assert not res.findings


def test_yfm008_fires_on_host_gather_in_rebuild_planning(tmp_path):
    """The DESIGN §24 rebuild-routing rule: deciding which keys lived on a
    lost shard (and what each replays) is per-key dict routing — the array
    work belongs in the rebuild flush, not the plan."""
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import jax
        import numpy as np

        def _rebuild_plan(self, s):
            return jax.device_get(np.asarray(self.bank[s]))
    """, ["YFM008"])
    assert len(fired(res, "YFM008")) == 2


def test_yfm008_quiet_on_rebuild_flush_transfers(tmp_path):
    # the sanctioned split: the plan is pure routing; fresh arrays, slot
    # writes and journal replay transfer only inside the rebuild flush
    res = lint(tmp_path, f"{PKG}/serving/extra.py", """\
        import jax
        import numpy as np

        def _rebuild_plan(self, s):
            return sorted(k for k, loc in self.slots.items() if loc[0] == s)

        def _rebuild_shard(self, s, plan):
            return np.asarray(jax.device_get(self.shards[s]))
    """, ["YFM008"])
    assert not res.findings


def test_yfm008_scoped_to_serving(tmp_path):
    # the orchestrator's poll loop may sleep (chaos/test code likewise by
    # living outside serving/)
    res = lint(tmp_path, f"{PKG}/orchestration/extra.py", """\
        import time

        def poll():
            time.sleep(0.1)
    """, ["YFM008"])
    assert not res.findings


# ---------------------------------------------------------------------------
# YFM009 — reference-citation existence
# ---------------------------------------------------------------------------

def _ref_tree(tmp_path):
    ref = tmp_path / "reference"
    (ref / "src" / "models").mkdir(parents=True)
    (ref / "src" / "models" / "filter.jl").write_text("# julia\n")
    return str(ref)


def test_yfm009_fires_on_typod_citation(tmp_path):
    ref = _ref_tree(tmp_path)
    res = lint(tmp_path, f"{PKG}/models/extra.py", '''\
        """Parity with /root/reference/src/models/fliter.jl:10-20 (typo)."""
    ''', ["YFM009"], reference_root=ref)
    assert fired(res, "YFM009")
    assert "fliter.jl" in res.findings[0].message


def test_yfm009_quiet_on_real_citation_with_lines_and_dirs(tmp_path):
    ref = _ref_tree(tmp_path)
    res = lint(tmp_path, f"{PKG}/models/extra.py", '''\
        """Parity with /root/reference/src/models/filter.jl:52-91 and the
        layout of /root/reference/src/models/."""
    ''', ["YFM009"], reference_root=ref)
    assert not res.findings


def test_yfm009_silent_when_reference_tree_absent(tmp_path):
    # on boxes without /root/reference nothing is verifiable — the rule
    # must gate itself off rather than flag every citation
    res = lint(tmp_path, f"{PKG}/models/extra.py", '''\
        """Parity with /root/reference/src/models/anything.jl:1."""
    ''', ["YFM009"], reference_root=str(tmp_path / "no-such-tree"))
    assert not res.findings


# ---------------------------------------------------------------------------
# engine unit coverage: jit-context detection table
# ---------------------------------------------------------------------------

def test_detect_jit_contexts_decorator_call_and_closure_forms():
    import ast
    src = textwrap.dedent("""\
        import jax
        from functools import partial
        from jax import lax

        @jax.jit
        def a(x):
            def inner(y):
                return y
            return inner(x)

        @partial(jax.jit, static_argnums=0)
        def b(x):
            return x

        def c(x):
            return x

        def build():
            def body(carry, y):
                return carry, y
            jitted_c = jax.jit(c)
            return lax.scan(body, 0, None)

        def true_br(x):
            return x

        def false_br(x):
            return -x

        def loop_body(i, x):
            return x + i

        def br0(x):
            return x

        def dispatch(pred, idx, x):
            y = lax.cond(pred, true_br, false_br, x)
            z = lax.fori_loop(0, 10, loop_body, x)
            return lax.switch(idx, [br0, lambda v: v * 2], x) + y + z

        def plain(x):
            return x
    """)
    tree = ast.parse(src)
    marked = detect_jit_contexts(tree, parent_map(tree))
    names = {getattr(n, "name", "<lambda>"): kind
             for n, kind in marked.items()}
    assert names.get("a") == "jit_entry"
    assert names.get("b") == "jit_entry"
    assert names.get("c") == "jit_entry"       # passed to jax.jit by name
    assert names.get("body") == "trace_body"   # lax.scan body
    assert names.get("inner") == "enclosed"    # closure inside a jit entry
    # non-args[0] callables are traced too: cond branches, fori_loop's body
    # (args[2]), switch's branch LIST — the silent-miss class a review found
    assert names.get("true_br") == "trace_body"
    assert names.get("false_br") == "trace_body"
    assert names.get("loop_body") == "trace_body"
    assert names.get("br0") == "trace_body"
    assert "plain" not in names
    assert "build" not in names
    assert "dispatch" not in names


def test_yfm001_fires_inside_cond_branch_and_fori_body(tmp_path):
    res = lint(tmp_path, f"{PKG}/models/extra.py", """\
        from jax import lax

        def true_br(x):
            raise RuntimeError("traced branch")

        def loop_body(i, x):
            raise RuntimeError("traced loop body")

        def driver(pred, x):
            y = lax.cond(pred, true_br, lambda v: v, x)
            return lax.fori_loop(0, 3, loop_body, y)
    """, ["YFM001"])
    assert len(fired(res, "YFM001")) == 2


# ---------------------------------------------------------------------------
# YFM010 — lock discipline (serving/ + orchestration/ threaded classes)
# ---------------------------------------------------------------------------

def test_yfm010_fires_on_write_outside_lock(tmp_path):
    res = lint(tmp_path, f"{PKG}/serving/st.py", """\
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._slot = {}

            def register(self, k, v):
                with self._lock:
                    self._slot[k] = v

            def evict(self, k):
                self._slot.pop(k)
    """, ["YFM010"])
    hits = fired(res, "YFM010")
    assert len(hits) == 1
    assert "_slot" in hits[0].message
    assert hits[0].line == 13  # the unlocked pop, not the locked write


def test_yfm010_fires_on_inplace_mutator_outside_lock(tmp_path):
    # deque-style mutation: append under the lock, popleft bare
    res = lint(tmp_path, f"{PKG}/serving/gw.py", """\
        import threading
        from collections import deque

        class Gateway:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = deque()

            def admit(self, req):
                with self._lock:
                    self._queue.append(req)

            def drain(self):
                return self._queue.popleft()
    """, ["YFM010"])
    assert fired(res, "YFM010")


def test_yfm010_quiet_on_init_only_and_locked_writes(tmp_path):
    res = lint(tmp_path, f"{PKG}/serving/st.py", """\
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._slot = {}
                self._free = [1, 2, 3]   # construction: single-threaded

            def register(self, k, v):
                with self._lock:
                    self._slot[k] = v
                    self._free.pop()
    """, ["YFM010"])
    assert not res.findings


def test_yfm010_quiet_on_lock_held_through_private_call_chain(tmp_path):
    # the pump -> _pump_locked -> _dispatch convention: every call site of
    # the private method holds a lock, so its writes are locked writes —
    # closed to a fixed point down the chain
    res = lint(tmp_path, f"{PKG}/serving/gw.py", """\
        import threading

        class Gateway:
            def __init__(self):
                self._pump_lock = threading.Lock()
                self._cost = 0.0

            def pump(self):
                with self._pump_lock:
                    return self._pump_locked()

            def _pump_locked(self):
                self._cost = 0.5 * self._cost
                return self._dispatch()

            def _dispatch(self):
                self._cost = self._cost + 1.0
                return 1
    """, ["YFM010"])
    assert not res.findings


def test_yfm010_fires_when_one_call_site_is_unlocked(tmp_path):
    # same chain, but a second caller reaches the private method with no
    # lock held: the fixed point must NOT mark it locked
    res = lint(tmp_path, f"{PKG}/serving/gw.py", """\
        import threading

        class Gateway:
            def __init__(self):
                self._lock = threading.Lock()
                self._cost = 0.0

            def pump(self):
                with self._lock:
                    self._cost = 0.0
                    self._bump()

            def hot_path(self):
                self._bump()

            def _bump(self):
                self._cost = self._cost + 1.0
    """, ["YFM010"])
    hits = fired(res, "YFM010")
    assert len(hits) == 1
    assert hits[0].line == 17  # _bump's write: one bare call site unlocks it


def test_yfm010_quiet_on_ctor_only_helper_chain(tmp_path):
    # __init__ -> self._reset(): calls FROM construction-time code are
    # single-threaded by the same contract that exempts ctor bodies, so a
    # private helper reachable only from ctors inherits the exemption —
    # its writes are neither locked nor unlocked evidence
    res = lint(tmp_path, f"{PKG}/serving/st.py", """\
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._reset()

            def _reset(self):
                self._state = {}

            def update(self, k, v):
                with self._lock:
                    self._state[k] = v
    """, ["YFM010"])
    assert not res.findings


def test_yfm010_fires_when_ctor_helper_is_also_called_at_runtime(tmp_path):
    # same helper, but a runtime method reaches it with no lock held: the
    # ctor call is still exempt, the runtime call is what convicts it
    res = lint(tmp_path, f"{PKG}/serving/st.py", """\
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._reset()

            def _reset(self):
                self._state = {}

            def clear(self):
                self._reset()

            def update(self, k, v):
                with self._lock:
                    self._state[k] = v
    """, ["YFM010"])
    hits = fired(res, "YFM010")
    assert len(hits) == 1
    assert hits[0].line == 9  # _reset's write, convicted by clear()


def test_yfm010_fires_with_annotated_lock_creation(tmp_path):
    # `self._lock: threading.Lock = threading.Lock()` must register the
    # lock — an AnnAssign-shaped ctor would otherwise disable the rule for
    # the whole class
    res = lint(tmp_path, f"{PKG}/serving/st.py", """\
        import threading

        class Store:
            def __init__(self):
                self._lock: threading.Lock = threading.Lock()
                self._slot = {}

            def register(self, k, v):
                with self._lock:
                    self._slot[k] = v

            def evict(self, k):
                self._slot.pop(k)
    """, ["YFM010"])
    hits = fired(res, "YFM010")
    assert len(hits) == 1 and "_slot" in hits[0].message


def test_yfm010_quiet_on_recursive_locked_chain(tmp_path):
    # self- and mutually-recursive private methods whose every EXTERNAL
    # entry point holds the lock: the greatest-fixed-point closure must
    # converge to locked (a least fixed point never could — the recursive
    # call site's owner is the method itself)
    res = lint(tmp_path, f"{PKG}/serving/gw.py", """\
        import threading

        class Gateway:
            def __init__(self):
                self._lock = threading.Lock()
                self._cost = 0.0

            def pump(self):
                with self._lock:
                    self._retry(3)

            def _retry(self, n):
                self._cost = self._cost + 1.0
                if n:
                    self._retry(n - 1)
                else:
                    self._backoff(n)

            def _backoff(self, n):
                self._cost = 0.5 * self._cost
                self._retry(n)
    """, ["YFM010"])
    assert not res.findings


def test_yfm010_quiet_on_bare_annotation(tmp_path):
    # `self._pending: Dict[str, int]` (no value) declares for the type
    # checker — it mutates nothing and must not count as an unlocked write
    res = lint(tmp_path, f"{PKG}/serving/st.py", """\
        import threading
        from typing import Dict

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def setup(self):
                self._pending: Dict[str, int]

            def update(self, k, v):
                with self._lock:
                    self._pending = {k: v}
    """, ["YFM010"])
    assert not res.findings


def test_yfm010_quiet_on_subobject_writes_and_other_dirs(tmp_path):
    # writes into a sub-object (self.counters.shed) have ambiguous
    # ownership — out of scope by design; and the rule only patrols the
    # genuinely threaded serving/ + orchestration/ layers
    res = lint(tmp_path, f"{PKG}/serving/svc.py", """\
        import threading

        class Service:
            def __init__(self):
                self._lock = threading.Lock()

            def locked(self):
                with self._lock:
                    self.counters.completed += 1

            def bare(self):
                self.counters.shed += 1
    """, ["YFM010"])
    assert not res.findings
    res = lint(tmp_path, f"{PKG}/models/mod.py", """\
        import threading

        class NotPatrolled:
            def __init__(self):
                self._lock = threading.Lock()
                self._x = 0

            def locked(self):
                with self._lock:
                    self._x = 1

            def bare(self):
                self._x = 2
    """, ["YFM010"])
    assert not res.findings


# ---------------------------------------------------------------------------
# YFM011 — IR-audit manifest coverage
# ---------------------------------------------------------------------------

_MANIFEST_STUB = """\
def case(builder, label="default", donated=0, max_programs=1):
    def wrap(fn):
        return fn
    return wrap


def skip_case(builder, reason):
    pass
"""


def _builder_module():
    return """\
        from functools import lru_cache
        from ..config import register_engine_cache

        @register_engine_cache
        @lru_cache(maxsize=8)
        def _jitted_thing(spec, T):
            return None
    """


def test_yfm011_fires_on_unmanifested_builder(tmp_path):
    (tmp_path / PKG / "analysis").mkdir(parents=True)
    (tmp_path / PKG / "analysis" / "manifest.py").write_text(_MANIFEST_STUB)
    res = lint(tmp_path, f"{PKG}/estimation/opt.py", _builder_module(),
               ["YFM011"])
    hits = fired(res, "YFM011")
    assert len(hits) == 1
    assert "estimation.opt._jitted_thing" in hits[0].message
    assert hits[0].file.endswith("estimation/opt.py")


def test_yfm011_quiet_when_covered_and_fires_on_stale_key(tmp_path):
    (tmp_path / PKG / "analysis").mkdir(parents=True)
    (tmp_path / PKG / "analysis" / "manifest.py").write_text(
        _MANIFEST_STUB + """

@case("estimation.opt._jitted_thing", donated=1)
def _m_thing():
    return None, []

skip_case("estimation.gone._jitted_stale", "builder was deleted")
""")
    res = lint(tmp_path, f"{PKG}/estimation/opt.py", _builder_module(),
               ["YFM011"])
    hits = fired(res, "YFM011")
    assert len(hits) == 1           # the covered builder is quiet...
    assert "_jitted_stale" in hits[0].message   # ...the stale key is not
    assert hits[0].file.endswith("analysis/manifest.py")


def test_yfm011_sees_aliased_decorator_import(tmp_path):
    # `from ..config import register_engine_cache as _rec` must not hide a
    # builder from the census — the runtime census in ir.py would still
    # see it, and the tiers must observe the same builder set
    (tmp_path / PKG / "analysis").mkdir(parents=True)
    (tmp_path / PKG / "analysis" / "manifest.py").write_text(_MANIFEST_STUB)
    res = lint(tmp_path, f"{PKG}/estimation/opt.py", """\
        from functools import lru_cache
        from ..config import register_engine_cache as _rec

        @_rec
        @lru_cache(maxsize=8)
        def _jitted_thing(spec, T):
            return None
    """, ["YFM011"])
    hits = fired(res, "YFM011")
    assert len(hits) == 1
    assert "estimation.opt._jitted_thing" in hits[0].message


def test_yfm011_ignores_nested_builders(tmp_path):
    # the runtime census keys builders by __qualname__ (mod.factory.
    # <locals>.builder), which the AST tier cannot reproduce — a nested
    # builder must not make the tiers demand contradictory manifest keys
    # (tier 2's runtime census still covers it)
    (tmp_path / PKG / "analysis").mkdir(parents=True)
    (tmp_path / PKG / "analysis" / "manifest.py").write_text(_MANIFEST_STUB)
    res = lint(tmp_path, f"{PKG}/estimation/opt.py", """\
        from functools import lru_cache
        from ..config import register_engine_cache

        def factory():
            @register_engine_cache
            @lru_cache(maxsize=8)
            def _jitted_inner(spec, T):
                return None
            return _jitted_inner
    """, ["YFM011"])
    assert not res.findings


def test_yfm011_gated_off_without_manifest(tmp_path):
    # pre-tier-2 trees (and most fixture repos here) have no manifest:
    # the rule must stay quiet, not flag every builder
    res = lint(tmp_path, f"{PKG}/estimation/opt.py", _builder_module(),
               ["YFM011"])
    assert not res.findings
