"""Time-axis (sequence) parallel Kalman loglik on the 8-device virtual mesh."""

import numpy as np
import jax.numpy as jnp

from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.ops import univariate_kf
from yieldfactormodels_jl_tpu.parallel.mesh import make_mesh
from yieldfactormodels_jl_tpu.parallel.time_parallel import get_loss_time_sharded

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)


def _params(spec, rng):
    p = np.zeros(spec.n_params)
    p[0] = np.log(0.45)
    p[1] = 4e-4
    k = 2
    for j in range(3):
        for i in range(j + 1):
            p[k] = 0.05 if i == j else 0.004
            k += 1
    p[8:11] = [0.1, -0.05, 0.02]
    p[11:20] = (0.92 * np.eye(3)).reshape(-1)
    return p


def test_time_sharded_matches_sequential(rng):
    spec, _ = create_model("1C", MATS, float_type="float64")
    p = _params(spec, rng)
    T = 240  # divisible by the 8 virtual devices
    data = 0.4 * rng.standard_normal((len(MATS), T)) + 4.0
    mesh = make_mesh(axis_name="time")
    assert mesh.devices.size == 8
    seq = float(univariate_kf.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    par = float(get_loss_time_sharded(spec, p, data, mesh=mesh))
    assert np.isfinite(seq)
    np.testing.assert_allclose(par, seq, rtol=1e-9)


def test_time_sharded_windows_and_nans(rng):
    spec, _ = create_model("1C", MATS, float_type="float64")
    p = _params(spec, rng)
    T = 160
    data = 0.4 * rng.standard_normal((len(MATS), T)) + 4.0
    data[:, -8:] = np.nan
    mesh = make_mesh(axis_name="time")
    seq = float(univariate_kf.get_loss(spec, jnp.asarray(p), jnp.asarray(data),
                                       4, T - 2))
    par = float(get_loss_time_sharded(spec, p, data, start=4, end=T - 2,
                                      mesh=mesh))
    np.testing.assert_allclose(par, seq, rtol=1e-9)


def test_time_sharded_long_history(rng):
    """The long-context case: T = 20,000 sharded 8 ways stays exact."""
    spec, _ = create_model("1C", MATS, float_type="float64")
    p = _params(spec, rng)
    T = 20_000
    data = 0.4 * rng.standard_normal((len(MATS), T)) + 4.0
    mesh = make_mesh(axis_name="time")
    seq = float(univariate_kf.get_loss(spec, jnp.asarray(p), jnp.asarray(data)))
    par = float(get_loss_time_sharded(spec, p, data, mesh=mesh))
    np.testing.assert_allclose(par, seq, rtol=1e-8)
