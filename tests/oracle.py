"""Independent NumPy oracle of the reference semantics (SURVEY.md §4 test
strategy): straight per-step float64 loops, written directly from the formulas
in /root/reference/src — used as golden values for the lax.scan kernels.

The score-driven oracle's inner gradient uses a *hand-derived analytic*
gradient for the λ model and finite differences cross-checked against it, so
the oracle shares no AD machinery with the library for that model.
"""

from __future__ import annotations

import numpy as np

LAMBDA_FLOOR = 1e-2
LOG_2PI = np.log(2.0 * np.pi)


# ---------------------------------------------------------------------------
# loadings
# ---------------------------------------------------------------------------

def dns_loadings(gamma_scalar, maturities):
    lam = LAMBDA_FLOOR + np.exp(gamma_scalar)
    tau = lam * maturities
    z = np.exp(-tau)
    Z = np.ones((len(maturities), 3))
    Z[:, 1] = (1 - z) / tau
    Z[:, 2] = Z[:, 1] - z
    return Z


def svensson_loadings(gamma2, maturities):
    """4-factor Svensson loadings [1, slope(λ₁), curv(λ₁), curv(λ₂)] from the
    constrained head (γ₁, g): λ₁ = floor + exp(γ₁) (the DNS driver
    convention above), λ₂ = λ₁ + g with g > 0 — the independent twin of the
    program library's jnp implementation (program/library.py)."""
    lam1 = LAMBDA_FLOOR + np.exp(gamma2[0])
    lam2 = lam1 + gamma2[1]
    Z = np.ones((len(maturities), 4))
    tau1 = lam1 * maturities
    z1 = np.exp(-tau1)
    Z[:, 1] = (1 - z1) / tau1
    Z[:, 2] = Z[:, 1] - z1
    tau2 = lam2 * maturities
    z2 = np.exp(-tau2)
    Z[:, 3] = (1 - z2) / tau2 - z2
    return Z


def mlp_curve(p9, maturities):
    w1, b1, w2 = p9[0:3], p9[3:6], p9[6:9]
    out = np.zeros(len(maturities))
    for n, tau in enumerate(maturities):
        h = np.tanh(w1 * tau + b1)
        out[n] = float(w2 @ h)
    return out


def transform_net_1(raw, transformed):
    n = len(raw)
    dest = raw.copy()
    if transformed:
        raw_first, raw_last = dest[0], dest[n - 2]
        inv_first = 1.0 / (raw_first - raw_last + 1e-7)
        for i in range(1, n - 2):
            t = (dest[i] - raw_last) * inv_first
            dest[i] = t * t
    else:
        for i in range(1, n - 2):
            dest[i] = dest[i] * dest[i]
    dest[0] = 1.0
    dest[n - 2] = 0.0
    dest[n - 1] = 0.0
    return dest


def transform_net_2(raw, maturities, transformed, scale=0.9610):
    n = len(raw)
    dest = raw.copy()
    if transformed:
        x1, xN = maturities[0], maturities[n - 1]
        raw1, rawN = dest[0], dest[n - 1]
        slope = (rawN - raw1) / (xN - x1)
        intercept = raw1 - slope * x1
        sum_sq = 0.0
        for i in range(1, n - 1):
            r = dest[i] - (slope * maturities[i] - intercept)
            r2 = r * r
            dest[i] = r2
            sum_sq += r2 * r2
        dest[0] = 0.0
        dest[n - 1] = 0.0
        denom = np.sqrt(sum_sq) / scale + 1e-7
        dest /= denom
    else:
        dest[0] = 0.0
        dest[n - 1] = 0.0
        sum_sq = 0.0
        for i in range(1, n - 1):
            dest[i] = dest[i] * dest[i]
            sum_sq += dest[i] * dest[i]
        denom_inv = scale / np.sqrt(sum_sq) + 1e-7
        for i in range(1, n - 1):
            dest[i] *= denom_inv
    return dest


def neural_loadings(gamma18, maturities, transform_bool):
    Z = np.ones((len(maturities), 3))
    Z[:, 1] = transform_net_1(mlp_curve(gamma18[0:9], maturities), transform_bool)
    Z[:, 2] = transform_net_2(mlp_curve(gamma18[9:18], maturities), maturities, transform_bool)
    return Z


# ---------------------------------------------------------------------------
# Kalman oracle (kalman/filter.jl:125-209, predicted-state form, explicit inv)
# ---------------------------------------------------------------------------

def kalman_init(Phi, delta, Omega_state):
    Ms = Phi.shape[0]
    beta = np.linalg.solve(np.eye(Ms) - Phi, delta)
    P = np.linalg.solve(np.eye(Ms * Ms) - np.kron(Phi, Phi), Omega_state.reshape(-1)).reshape(Ms, Ms)
    return beta, P


def kalman_filter_loglik(Z, Phi, delta, Omega_state, obs_var, data):
    N, T = data.shape
    Ms = Phi.shape[0]
    Omega_obs = obs_var * np.eye(N)
    beta, P = kalman_init(Phi, delta, Omega_state)
    loglik = 0.0
    preds = []
    for t in range(T - 1):
        y = data[:, t]
        y_pred = Z @ beta
        preds.append(y_pred)
        if np.any(np.isnan(y)):
            beta = delta + Phi @ beta
            P = Phi @ P @ Phi.T + Omega_state
            continue
        v = y - y_pred
        F = Z @ P @ Z.T + Omega_obs
        F_inv = np.linalg.inv(F)
        K = P @ Z.T @ F_inv
        beta = delta + Phi @ (beta + K @ v)
        P = Phi @ ((np.eye(Ms) - K @ Z) @ P) @ Phi.T + Omega_state
        if t > 0:  # reference skips t == 1 (1-based)
            sign, logdet = np.linalg.slogdet(F)
            loglik -= 0.5 * (logdet + v @ F_inv @ v + N * LOG_2PI)
    return loglik


def ekf_tvl_loglik(Phi, delta, Omega_state, obs_var, maturities, data,
                   exact_jacobian=False):
    """EKF for TVλ (kalman/filter.jl:12-80), loglik accumulation (:182-209)."""
    N, T = data.shape
    Ms = Phi.shape[0]  # 4
    Omega_obs = obs_var * np.eye(N)
    beta, P = kalman_init(Phi, delta, Omega_state)
    loglik = 0.0
    for t in range(T - 1):
        y = data[:, t]
        lam = LAMBDA_FLOOR + np.exp(beta[3])
        tau = lam * maturities
        z = np.exp(-tau)
        z2 = (1 - z) / tau
        z3 = z2 - z
        y_pred = beta[0] + z2 * beta[1] + z3 * beta[2]
        if np.any(np.isnan(y)):
            beta = delta + Phi @ beta
            P = Phi @ P @ Phi.T + Omega_state
            continue
        v = y - y_pred
        dlam = lam - LAMBDA_FLOOR
        if exact_jacobian:
            dz2 = z / lam - (1 - z) / (lam * lam * maturities)
        else:
            dz2 = z / lam - z / (lam * lam * maturities)
        extra = maturities * z
        jac = ((beta[1] + beta[2]) * dz2 + beta[2] * extra) * dlam
        Zd = np.column_stack([np.ones(N), z2, z3, jac])
        F = Zd @ P @ Zd.T + Omega_obs
        F_inv = np.linalg.inv(F)
        K = P @ Zd.T @ F_inv
        beta = delta + Phi @ (beta + K @ v)
        P = Phi @ ((np.eye(Ms) - K @ Zd) @ P) @ Phi.T + Omega_state
        if t > 0:
            sign, logdet = np.linalg.slogdet(F)
            loglik -= 0.5 * (logdet + v @ F_inv @ v + N * LOG_2PI)
    return loglik


# ---------------------------------------------------------------------------
# score-driven oracle (models/filter.jl:52-91, λ model with analytic score)
# ---------------------------------------------------------------------------

def _ols(Z, y):
    G = Z.T @ Z
    try:
        L = np.linalg.cholesky(G)
    except np.linalg.LinAlgError:
        L = np.linalg.cholesky(G + 1e-3 * np.eye(G.shape[0]))
    x = np.linalg.solve(L, Z.T @ y)
    return np.linalg.solve(L.T, x)


def _dns_score(gamma, beta, y, maturities):
    """Analytic ∇_γ −‖y − Z(γ)β‖² for the λ model (β detached)."""
    lam = LAMBDA_FLOOR + np.exp(gamma[0])
    tau = lam * maturities
    z = np.exp(-tau)
    z2 = (1 - z) / tau
    z3 = z2 - z
    resid = y - (beta[0] + z2 * beta[1] + z3 * beta[2])
    # dZ2/dλ and dZ3/dλ (true derivatives; the inner score is exact AD)
    dz2 = z / lam - (1 - z) / (lam * lam * maturities)
    dz3 = dz2 + maturities * z
    dlam_dg = np.exp(gamma[0])
    dresid_dg = -(beta[1] * dz2 + beta[2] * dz3) * dlam_dg
    return np.array([-2.0 * np.dot(resid, dresid_dg)])


def msed_lambda_filter(params_struct, maturities, data, scale_grad=False,
                       forget_factor=0.98, dtype_eps=np.finfo(np.float64).eps,
                       record_traj=False):
    """params_struct: dict with A (L,), B (L,) or None, omega, delta, Phi.

    ``record_traj=True`` additionally returns the per-step (Z_next, β_obs)
    trajectory — the post-transition loadings and the post-re-OLS β the
    closed-form (δ, Φ) parity check needs (fully-observed data only)."""
    A = params_struct["A"]
    B = params_struct["B"]
    omega = params_struct["omega"]
    delta = params_struct["delta"]
    Phi = params_struct["Phi"]
    mu = (np.eye(3) - Phi) @ delta
    nu = np.zeros_like(omega) if B is None else (1 - B) * omega

    gamma = omega.copy()
    beta = delta.copy()
    ewma = np.zeros_like(gamma)
    count = 0

    N, T = data.shape
    preds = np.zeros((N, T))
    Z_traj = np.zeros((T, N, 3))
    b_traj = np.zeros((T, 3))
    for t in range(T):
        y = data[:, t]
        if np.isnan(y[0]):
            if B is not None:
                gamma = nu + B * gamma
            beta = mu + Phi @ beta
            Z = dns_loadings(gamma[0], maturities)
            preds[:, t] = Z @ beta
            continue
        Z = dns_loadings(gamma[0], maturities)
        beta = _ols(Z, y)
        g = _dns_score(gamma, beta, y, maturities)
        if scale_grad:
            ewma = forget_factor * ewma + (1 - forget_factor) * g * g
            count += 1
            denom = 1 - forget_factor ** count
            g = g / (np.sqrt(ewma / denom) + dtype_eps)
        gamma = gamma + g * A
        Z = dns_loadings(gamma[0], maturities)
        beta = _ols(Z, y)
        if B is not None:
            gamma = nu + B * gamma
            Z = dns_loadings(gamma[0], maturities)
        Z_traj[t] = Z
        b_traj[t] = beta
        beta = mu + Phi @ beta
        preds[:, t] = Z @ beta
    if record_traj:
        return preds, {"Z_next": Z_traj, "beta_obs": b_traj}
    return preds


def neural_struct_from_flat(p, random_walk=False):
    """Oracle param-struct from a flat scalar-dynamics neural-MSED vector
    ([A(2) | B(2 unless RW) | ω(18) | δ(3) | vec_colmajor Φ(9)]).  Encodes
    the scalar duplicator [0]×9+[1]×9 (mseneural.jl:33-51) and the
    col-major Φ unpack ONCE for every oracle-parity test — deliberately
    independent of the library's spec machinery."""
    p = np.asarray(p)
    expand = lambda u: np.concatenate([np.full(9, u[0]), np.full(9, u[1])])
    k = 2 if random_walk else 4
    return {"A": expand(p[0:2]),
            "B": None if random_walk else expand(p[2:4]),
            "omega": p[k:k + 18], "delta": p[k + 18:k + 21],
            "Phi": p[k + 21:k + 30].reshape(3, 3).T}


def closed_delta_phi_from_traj(traj, data):
    """Normal-equation solve of the (δ, Φ) block optimum from a recorded
    per-step (Z_next, β_obs) trajectory (fully-observed data): lstsq over
    Σₜ ‖y_{t+1} − Z_{t+1}(μ + Φ β̄_t)‖² in θ = (μ, vec_rowmajor Φ),
    then δ = (I − Φ)⁻¹μ.  Shared by the λ/neural/static closed-form
    oracles (CLAUDE.md parity rule)."""
    N, T = data.shape
    rows, rhs = [], []
    for t in range(T - 1):  # contributions t = 0 .. T−2
        Z = traj["Z_next"][t]          # (N, 3)
        b = traj["beta_obs"][t]        # (3,)
        D = np.concatenate([Z, np.einsum("nm,k->nmk", Z, b).reshape(N, 9)], 1)
        rows.append(D)
        rhs.append(data[:, t + 1])
    D = np.concatenate(rows, axis=0)
    y = np.concatenate(rhs, axis=0)
    theta, *_ = np.linalg.lstsq(D, y, rcond=None)
    mu, Phi = theta[:3], theta[3:].reshape(3, 3)
    delta = np.linalg.solve(np.eye(3) - Phi, mu)
    return delta, Phi


def msed_lambda_closed_delta_phi(params_struct, maturities, data):
    """Independent NumPy solve of the (δ, Φ) block optimum for the λ-MSED
    model on fully-observed data — the oracle for
    ``optimize._jitted_group_opt_msed_closed``."""
    _, traj = msed_lambda_filter(params_struct, maturities, data,
                                 record_traj=True)
    return closed_delta_phi_from_traj(traj, data)


def _neural_score_fd(gamma18, beta, y, maturities, transform_bool, eps=1e-6):
    """∇_γ −‖y − Z(γ)β‖² for the neural model via central finite differences —
    shares no AD machinery with the library (β treated as a constant, matching
    the reference's ForwardDiff.value. detach, filter.jl:173-175)."""
    def obj(gam):
        Z = neural_loadings(gam, maturities, transform_bool)
        v = y - Z @ beta
        return -float(v @ v)

    g = np.zeros(18)
    for i in range(18):
        e = np.zeros(18)
        e[i] = eps
        g[i] = (obj(gamma18 + e) - obj(gamma18 - e)) / (2.0 * eps)
    return g


def msed_neural_filter(params_struct, maturities, data, transform_bool,
                       scale_grad=False, forget_factor=0.98,
                       dtype_eps=np.finfo(np.float64).eps, record_traj=False):
    """Per-step neural MSED loop (models/filter.jl:52-91 with the two-MLP
    loadings of mseneural.jl:137-163).  ``params_struct``: dict with A (18,)
    and B (18,) (or None for random-walk dynamics) already expanded through
    the duplicator, omega (18,), delta (3,), Phi (3,3).

    ``record_traj=True`` additionally returns the per-step (Z_next, β_obs)
    trajectory for the closed-form (δ, Φ) parity check (same contract as
    :func:`msed_lambda_filter`)."""
    A = params_struct["A"]
    B = params_struct["B"]
    omega = params_struct["omega"]
    delta = params_struct["delta"]
    Phi = params_struct["Phi"]
    mu = (np.eye(3) - Phi) @ delta
    nu = np.zeros_like(omega) if B is None else (1 - B) * omega

    gamma = omega.copy()
    beta = delta.copy()
    ewma = np.zeros_like(gamma)
    count = 0

    N, T = data.shape
    preds = np.zeros((N, T))
    Z_traj = np.zeros((T, N, 3))
    b_traj = np.zeros((T, 3))
    for t in range(T):
        y = data[:, t]
        if np.isnan(y[0]):
            if B is not None:
                gamma = nu + B * gamma
            beta = mu + Phi @ beta
            Z = neural_loadings(gamma, maturities, transform_bool)
            preds[:, t] = Z @ beta
            continue
        Z = neural_loadings(gamma, maturities, transform_bool)
        beta = _ols(Z, y)
        g = _neural_score_fd(gamma, beta, y, maturities, transform_bool)
        if scale_grad:
            ewma = forget_factor * ewma + (1 - forget_factor) * g * g
            count += 1
            denom = 1 - forget_factor ** count
            g = g / (np.sqrt(ewma / denom) + dtype_eps)
        gamma = gamma + g * A
        Z = neural_loadings(gamma, maturities, transform_bool)
        beta = _ols(Z, y)
        if B is not None:
            gamma = nu + B * gamma
            Z = neural_loadings(gamma, maturities, transform_bool)
        Z_traj[t] = Z
        b_traj[t] = beta
        beta = mu + Phi @ beta
        preds[:, t] = Z @ beta
    if record_traj:
        return preds, {"Z_next": Z_traj, "beta_obs": b_traj}
    return preds


def msed_loss_from_preds(preds, data):
    N, T = data.shape
    mse = 0.0
    for t in range(T - 1):
        v = data[:, t + 1] - preds[:, t]
        mse -= v @ v
    return mse / N / T


def linearized_score_filter(params_struct, maturities, data, sweeps=2,
                            chunk=128, detach_inner_beta=True, fd_eps=1e-6):
    """Independent NumPy mirror of the two-scale score-tree engine
    (ops/score_scan.py) for the λ model — pass A composes per-step affine
    surrogates of the TRUE γ map linearized at ω (central finite
    differences here vs the engine's ``jacfwd`` — an independent route; the
    β chain is exactly affine given the γ path), pass B re-runs ``sweeps``
    chunked exact-recursion refinements with the Jacobi entry shift.  At
    the fixed point this is :func:`msed_lambda_filter` (plain-gradient
    path), step for step.

    ``detach_inner_beta`` mirrors the spec flag: the engine's surrogate
    Jacobian sees β̄ through ``stop_gradient`` when set, so the FD map here
    freezes β̄ at the reference point; False re-fits β̄ at each FD point.

    Returns ``(preds (N, T), gammas (T, 1), betas (T, 3))`` — the
    post-transition trajectories of the final sweep."""
    A = params_struct["A"]
    B = params_struct["B"]
    omega = np.asarray(params_struct["omega"], dtype=np.float64)
    delta = np.asarray(params_struct["delta"], dtype=np.float64)
    Phi = params_struct["Phi"]
    mu = (np.eye(3) - Phi) @ delta
    nu = np.zeros_like(omega) if B is None else (1 - B) * omega
    N, T = data.shape
    L_g = omega.shape[0]

    def gamma_update(g, ysafe, obs, beta_fixed=None):
        """plain_gamma_update: OLS β̄ (or a frozen one), analytic score."""
        if not obs:
            return g
        bb = beta_fixed
        if bb is None:
            bb = _ols(dns_loadings(g[0], maturities), ysafe)
        return g + _dns_score(g, bb, ysafe, maturities) * A

    def transition(g):
        return g if B is None else nu + B * g

    # --- pass A, γ: FD-linearized elements of the post-transition map at ω
    J_el = np.zeros((T, L_g, L_g))
    b_el = np.zeros((T, L_g))
    for t in range(T):
        y = data[:, t]
        obs = bool(np.isfinite(y[0]))
        ysafe = np.where(np.isfinite(y), y, 0.0)
        if not obs:
            J_el[t] = np.eye(L_g) if B is None else np.diag(B)
            b_el[t] = nu
            continue
        b_ref = (_ols(dns_loadings(omega[0], maturities), ysafe)
                 if detach_inner_beta else None)
        Ju = np.zeros((L_g, L_g))
        for j in range(L_g):
            e = np.zeros(L_g)
            e[j] = fd_eps
            Ju[:, j] = (gamma_update(omega + e, ysafe, obs, b_ref)
                        - gamma_update(omega - e, ysafe, obs, b_ref)) \
                / (2 * fd_eps)
        Jt = Ju if B is None else B[:, None] * Ju
        val = transition(gamma_update(omega, ysafe, obs))
        J_el[t] = Jt
        b_el[t] = val - Jt @ omega
    gs = np.zeros((T, L_g))  # composed prefix == sequential affine recursion
    g_run = omega.copy()
    for t in range(T):
        g_run = J_el[t] @ g_run + b_el[t]
        gs[t] = g_run

    # --- pass A, β: exact affine chain given the surrogate γ path
    bs = np.zeros((T, 3))
    b_run = delta.copy()
    for t in range(T):
        y = data[:, t]
        obs = bool(np.isfinite(y[0]))
        ysafe = np.where(np.isfinite(y), y, 0.0)
        poison = np.nan if (obs and not np.all(np.isfinite(y))) else 1.0
        gprev = omega if t == 0 else gs[t - 1]
        g_obs = gamma_update(gprev, ysafe, obs)
        beta_reols = _ols(dns_loadings(g_obs[0], maturities), ysafe)
        of = 1.0 if obs else 0.0
        b_run = ((1.0 - of) * poison) * (Phi @ b_run) \
            + mu + (of * poison) * (Phi @ beta_reols)
        bs[t] = b_run

    # --- pass B: K exact-recursion sweeps over NaN-padded chunks
    L = min(chunk, T)
    Cn = -(-T // L)
    pad = Cn * L - T
    data_p = np.concatenate(
        [data, np.full((N, pad), np.nan)], axis=1) if pad else data
    in_win_p = np.concatenate([np.ones(T, bool), np.zeros(pad, bool)])

    def true_step(gamma, beta, y, in_win):
        obs = bool(in_win) and bool(np.isfinite(y[0]))
        ysafe = np.where(np.isfinite(y), y, 0.0)
        poison = np.nan if (obs and not np.all(np.isfinite(y))) else 1.0
        gamma_obs = gamma_update(gamma, ysafe, obs)
        beta_reols = _ols(dns_loadings(gamma_obs[0], maturities), ysafe)
        beta_obs = (beta_reols if obs else beta) * poison
        gamma_next = transition(gamma_obs)
        beta_next = mu + Phi @ beta_obs
        pred = dns_loadings(gamma_next[0], maturities) @ beta_next
        return gamma_next, beta_next, pred

    entry_g = np.concatenate([omega[None],
                              gs[np.arange(1, Cn) * L - 1]], axis=0)
    entry_b = np.concatenate([delta[None],
                              bs[np.arange(1, Cn) * L - 1]], axis=0)
    preds = np.zeros((Cn * L, N))
    gam = np.zeros((Cn * L, L_g))
    bet = np.zeros((Cn * L, 3))
    for k in range(sweeps):
        if k > 0:  # Jacobi shift: previous sweep's chunk exits
            exits = np.arange(Cn - 1) * L + (L - 1)
            entry_g = np.concatenate([omega[None], gam[exits]], axis=0)
            entry_b = np.concatenate([delta[None], bet[exits]], axis=0)
        for c in range(Cn):
            g_c, b_c = entry_g[c].copy(), entry_b[c].copy()
            for i in range(L):
                t = c * L + i
                g_c, b_c, p = true_step(g_c, b_c, data_p[:, t], in_win_p[t])
                gam[t], bet[t], preds[t] = g_c, b_c, p
    return preds[:T].T, gam[:T], bet[:T]


def static_filter(gamma_Z, delta, Phi, data):
    """models/filter.jl:93-110 with fixed Z."""
    Z = gamma_Z
    mu = (np.eye(3) - Phi) @ delta
    beta = delta.copy()
    N, T = data.shape
    preds = np.zeros((N, T))
    for t in range(T):
        y = data[:, t]
        if np.isnan(y[0]):
            beta = mu + Phi @ beta
        else:
            beta = mu + Phi @ _ols(Z, y)
        preds[:, t] = Z @ beta
    return preds


def static_closed_delta_phi(Z, data):
    """Independent NumPy solve of the (δ, Φ) block optimum for a static
    model with fixed loadings Z on fully-observed data — the oracle for the
    static branch of ``optimize._jitted_group_opt_msed_closed`` (β̄_t is
    per-column OLS; Z constant ⇒ same quadratic structure)."""
    T = data.shape[1]
    traj = {"Z_next": np.broadcast_to(Z, (T,) + Z.shape),
            "beta_obs": np.stack([_ols(Z, data[:, t]) for t in range(T)])}
    return closed_delta_phi_from_traj(traj, data)


# ---------------------------------------------------------------------------
# AFNS3 closed-form yield adjustment (Christensen–Diebold–Rudebusch)
# ---------------------------------------------------------------------------

def afns3_yield_adjustment_cdr(lam, Omega, maturities):
    """Closed-form AFNS3 yield-adjustment term −A(τ)/τ for a general state
    covariance Ω — the Christensen–Diebold–Rudebusch (2011) formula,
    independently re-derived here by symbolic integration of
    A(τ) = ½∫₀^τ B(s)ᵀΩB(s) ds with the bond-price loadings written from the
    model primitives (B₁ = −s, B₂ = −(1−e^{−λs})/λ, B₃ = s·e^{−λs} + B₂ —
    NOT the library's _price_loadings), so a sign error there cannot cancel.

    Returns the per-maturity adjustment α(τ) = −A(τ)/τ (the quantity
    models/afns.py:yield_adjustment evaluates by quadrature).
    """
    tau = np.asarray(maturities, dtype=np.float64)
    L = lam
    e1 = np.exp(-L * tau)
    e2 = np.exp(-2.0 * L * tau)

    # ∫₀^τ B_i B_j ds / τ, from sympy integration of the primitives above
    I11 = tau ** 2 / 3.0
    I22 = (1.0 / L**2
           - 3.0 / (2.0 * L**3 * tau)
           + 2.0 * e1 / (L**3 * tau)
           - e2 / (2.0 * L**3 * tau))
    I33 = ((-2.0 * L**2 * tau**2
            + 4.0 * L * tau / e2
            - 6.0 * L * tau
            + 8.0 * (L * tau + 2.0) / e1
            - 11.0 / e2
            - 5.0) * e2 / (4.0 * L**3 * tau))
    I12 = ((L**2 * tau**2 / e1 / 2.0
            + L * tau
            - 1.0 / e1
            + 1.0) * e1 / (L**3 * tau))
    I13 = (tau / (2.0 * L)
           + tau * e1 / L
           + 3.0 * e1 / L**2
           - 3.0 / (L**3 * tau)
           + 3.0 * e1 / (L**3 * tau))
    I23 = ((4.0 * L * tau / e2
            - 2.0 * L * tau
            + 4.0 * (L * tau + 3.0) / e1
            - 9.0 / e2
            - 3.0) * e2 / (4.0 * L**3 * tau))

    O = np.asarray(Omega, dtype=np.float64)
    total = (O[0, 0] * I11 + O[1, 1] * I22 + O[2, 2] * I33
             + 2.0 * O[0, 1] * I12 + 2.0 * O[0, 2] * I13 + 2.0 * O[1, 2] * I23)
    return -0.5 * total


# ---------------------------------------------------------------------------
# synthetic data
# ---------------------------------------------------------------------------

def simulate_dns_panel(rng, maturities, T=80, lam=0.5):
    """Yields from a stationary 3-factor DNS DGP + small noise."""
    N = len(maturities)
    Z = dns_loadings(np.log(lam - LAMBDA_FLOOR), maturities)
    Phi = np.diag([0.95, 0.9, 0.85])
    delta = np.array([0.3, -0.1, 0.05])
    beta = np.linalg.solve(np.eye(3) - Phi, delta)
    data = np.zeros((N, T))
    for t in range(T):
        beta = delta + Phi @ beta + 0.1 * rng.standard_normal(3)
        data[:, t] = Z @ beta + 0.02 * rng.standard_normal(N)
    return data + 5.0


def simulate_sv_panel(rng, maturities, T, sv_phi, sv_sigma, lam=0.5,
                      obs_var=4e-4):
    """Panel from the stochastic-volatility measurement-error DGP matched to
    ops/particle.py AND to ``stable_1c_params`` (same λ, Φ = 0.9 I, δ,
    chol = 0.05 I, σ² = 4e-4), so the PF's model is exactly the simulator's:
    a single log-volatility state h_t = φ_h h_{t−1} + σ_h η_t (h before the
    first observation is one AR step from h₀ = 0, mirroring the filter's
    draw-then-observe order) scales the common measurement variance,
    y_t = Z β_t + ε_t with ε_t ~ N(0, σ² e^{h_t} I)."""
    N = len(maturities)
    Z = dns_loadings(np.log(lam - LAMBDA_FLOOR), maturities)
    Phi = np.diag([0.9, 0.9, 0.9])
    delta = np.array([5.0, -1.0, 0.5])
    beta = np.linalg.solve(np.eye(3) - Phi, delta)
    h = 0.0
    data = np.zeros((N, T))
    for t in range(T):
        beta = delta + Phi @ beta + 0.05 * rng.standard_normal(3)
        h = sv_phi * h + sv_sigma * rng.standard_normal()
        data[:, t] = Z @ beta + np.sqrt(obs_var) * np.exp(0.5 * h) \
            * rng.standard_normal(N)
    return data


def stable_1c_params(spec, dtype=np.float32):
    """A stationary, finite-loglik parameter point for the 1C (DNS Kalman)
    spec — λ = 0.5, small obs/state noise, Φ = 0.9 I.  Shared by the sharded
    particle-filter test and the driver dry run so the chosen stable point
    lives in exactly one place."""
    p = np.zeros(spec.n_params, dtype=dtype)
    p[spec.layout["gamma"][0]] = np.log(0.5)
    p[spec.layout["obs_var"][0]] = 4e-4
    a, _ = spec.layout["chol"]
    rows, cols = spec.chol_indices
    for k, (r, c) in enumerate(zip(rows, cols)):
        p[a + k] = 0.05 if r == c else 0.0
    a, b = spec.layout["delta"]
    p[a:b] = [5.0, -1.0, 0.5]
    a, b = spec.layout["phi"]
    p[a:b] = np.diag([0.9, 0.9, 0.9]).reshape(-1)
    return p


def stable_svensson_params(spec, dtype=np.float64):
    """A stationary, finite-loglik parameter point for the ``svensson4``
    program spec (program/library.py) — λ₁ = 0.5, λ₂ − λ₁ = 0.25 (RAW head
    slot ln 0.25: the block's R_TO_POS transform maps it to the gap), obs
    var 4e-4, chol 0.05 I, Φ = 0.9 I, δ the 1C steady state plus a small
    second-curvature factor.  Shared by the program-layer parity/e2e tests
    (one copy, CLAUDE.md rule).  NOTE: constrained-space values — the gap
    slot here is the POSITIVE gap itself, as the engines consume it."""
    p = np.zeros(spec.n_params, dtype=dtype)
    p[spec.layout["lambda1"][0]] = np.log(0.5 - LAMBDA_FLOOR)
    p[spec.layout["lambda2_gap"][0]] = 0.25
    p[spec.layout["obs_var"][0]] = 4e-4
    a, _ = spec.layout["chol"]
    rows, cols = spec.chol_indices
    for k, (r, c) in enumerate(zip(rows, cols)):
        p[a + k] = 0.05 if r == c else 0.0
    a, b = spec.layout["delta"]
    p[a:b] = [5.0, -1.0, 0.5, 0.2]
    a, b = spec.layout["phi"]
    p[a:b] = np.diag([0.9, 0.9, 0.9, 0.9]).reshape(-1)
    return p


def generic_stable_params(spec, rng):
    """A finite-loss parameter point for ANY family, driven by spec.layout —
    the generalization of the named points below (same gamma/obs_var/chol/
    phi choices), used by the all-codes zoo smoke.  Lives here so stable
    test points stay in one file (CLAUDE.md rule)."""
    p = np.zeros(spec.n_params)
    lo, hi = spec.layout.get("gamma", (0, 0))
    n = hi - lo
    if n == 1:
        p[lo] = np.log(0.5 - LAMBDA_FLOOR)
    elif n == 2:  # AFNS5 double decay
        p[lo:hi] = [np.log(0.5), np.log(0.15)]
    elif n > 2:   # neural loading weights
        p[lo:hi] = rng.standard_normal(n) / 10
    lo, hi = spec.layout.get("obs_var", (0, 0))
    p[lo:hi] = 4e-4
    if "chol" in spec.layout:
        a, _ = spec.layout["chol"]
        rows, cols = spec.chol_indices
        for k, (r, c) in enumerate(zip(rows, cols)):
            p[a + k] = 0.05 if r == c else 0.0
    lo, hi = spec.layout.get("A", (0, 0))
    p[lo:hi] = 1e-4
    lo, hi = spec.layout.get("B", (0, 0))
    p[lo:hi] = 0.97
    lo, hi = spec.layout.get("omega", (0, 0))
    p[lo:hi] = rng.standard_normal(hi - lo) / 10
    lo, hi = spec.layout.get("delta", (0, 0))
    vals = [0.3, -0.1, 0.05] + [-0.07] * max(0, hi - lo - 3)
    p[lo:hi] = vals[: hi - lo]
    lo, hi = spec.layout.get("phi", (0, 0))
    m = int(round((hi - lo) ** 0.5))
    p[lo:hi] = (0.9 * np.eye(m)).reshape(-1)
    return p


def stable_msed_params(spec, dtype=np.float64):
    """A finite-loss parameter point for the plain-gradient λ-MSED specs
    (SD-NS / RWSD-NS) — A = 1e-3, B = 0.97, ω = ln 0.5 (γ's transition
    fixed point), δ = level/slope/curve start, Φ mildly coupled.  Shared by
    the score-tree parity tests (tests/test_score_scan.py) and the
    BENCH_LONGT MSED column (one copy, CLAUDE.md rule)."""
    vals = [1e-3]
    if not spec.random_walk:
        vals.append(0.97)
    vals.append(np.log(0.5))
    vals.extend([0.3, -0.1, 0.05])
    Phi = np.array([[0.95, 0.02, 0.0], [0.01, 0.9, 0.03],
                    [0.0, 0.02, 0.85]])
    vals.extend(Phi.T.reshape(-1))
    p = np.asarray(vals, dtype=dtype)
    assert p.shape[0] == spec.n_params
    return p


def stable_tvl_params(spec, dtype=np.float64):
    """A stationary, finite-loglik parameter point for the TVλ EKF spec —
    obs var 4e-4, chol 0.05 I, Φ = 0.9 I, δ giving a steady state near
    (5, −1, 0.5) with λ ≈ 0.5 (β₄ = ln(0.49)·0.1 per component).  Shared by
    the smoother-engine and fused-MLE tests (one copy, CLAUDE.md rule)."""
    p = np.zeros(spec.n_params, dtype=dtype)
    p[spec.layout["obs_var"][0]] = 4e-4
    a, _ = spec.layout["chol"]
    rows, cols = spec.chol_indices
    for k, (r, c) in enumerate(zip(rows, cols)):
        p[a + k] = 0.05 if r == c else 0.0
    a, b = spec.layout["delta"]
    p[a:b] = [0.5, -0.1, 0.05, 0.1 * np.log(0.49)]
    a, b = spec.layout["phi"]
    p[a:b] = np.diag([0.9, 0.9, 0.9, 0.9]).reshape(-1)
    return p


def stable_ns_params(spec, dtype=np.float32):
    """A stable parameter point for the NS (static-λ) spec — λ = 0.5, level
    curve deltas, Φ diag (0.9, 0.85, 0.8).  Shared by the bootstrap parity
    tests and benchmarks/hw_verify.py so the point lives in exactly one
    place (same rationale as stable_1c_params)."""
    p = np.zeros(spec.n_params, dtype=dtype)
    a, b = spec.layout["gamma"]
    p[a:b] = np.log(0.5)
    a, b = spec.layout["delta"]
    p[a:b] = [0.3, -0.1, 0.05]
    a, b = spec.layout["phi"]
    p[a:b] = np.diag([0.9, 0.85, 0.8]).T.reshape(-1)
    return p


def online_filter(Z, d, Phi, delta, Omega_state, obs_var, data):
    """Element-masked sequential (univariate) Kalman filter — the online
    serving recursion (serving/online.py): per column, PREDICT (β ← δ + Φβ,
    P ← ΦPΦᵀ + Ω) then N scalar measurement updates skipping NaN elements
    individually (a partially-quoted curve conditions on the observed subset
    only — the offline filter would drop the whole column).  Starts from the
    unconditional moments; returns the FILTERED (β_{t|t}, P_{t|t}) per column
    and per-column loglik contributions.  Straight float64 loops, no JAX."""
    N, T = data.shape
    beta, P = kalman_init(Phi, delta, Omega_state)
    betas, Ps, lls = [], [], []
    for t in range(T):
        # predict from the previous filtered state (t=0: kalman_init moments
        # are the transition's fixed point, so predict is a no-op — identical
        # to the library's predicted-state start)
        beta = delta + Phi @ beta
        P = Phi @ P @ Phi.T + Omega_state
        ll = 0.0
        for i in range(N):
            y_i = data[i, t]
            if np.isnan(y_i):
                continue
            z = Z[i]
            zP = z @ P
            f = zP @ z + obs_var
            v = (y_i - d[i]) - z @ beta
            K = zP / f
            beta = beta + K * v
            P = P - np.outer(K, zP)
            ll -= 0.5 * (np.log(f) + v * v / f + LOG_2PI)
        betas.append(beta.copy())
        Ps.append(P.copy())
        lls.append(ll)
    return np.asarray(betas), np.asarray(Ps), np.asarray(lls)


def online_filter_tvl(Phi, delta, Omega_state, obs_var, maturities, data,
                      exact_jacobian=False):
    """Element-masked sequential TVλ EKF — the online serving recursion for
    the ``kalman_tvl`` family (serving/online.py): per column, PREDICT, then
    linearize ONCE at β_pred (λ = 1e-2 + e^{β₄}, Jacobian column as
    kalman/filter.jl:38-46) and form the fixed-linearization effective
    observation y_eff = y + jac·β₄_pred; the N scalar updates then move β
    against that frozen (Z, y_eff) pair, skipping NaN elements individually.
    Straight float64 loops, no JAX."""
    N, T = data.shape
    beta, P = kalman_init(Phi, delta, Omega_state)
    betas, Ps, lls = [], [], []
    for t in range(T):
        beta = delta + Phi @ beta
        P = Phi @ P @ Phi.T + Omega_state
        lam = LAMBDA_FLOOR + np.exp(beta[3])
        tau = lam * maturities
        z = np.exp(-tau)
        z2 = (1 - z) / tau
        z3 = z2 - z
        dlam = lam - LAMBDA_FLOOR
        if exact_jacobian:
            dz2 = z / lam - (1 - z) / (lam * lam * maturities)
        else:
            dz2 = z / lam - z / (lam * lam * maturities)
        jac = ((beta[1] + beta[2]) * dz2 + beta[2] * maturities * z) * dlam
        Zd = np.column_stack([np.ones(N), z2, z3, jac])
        y_eff = data[:, t] + jac * beta[3]  # fixed-linearization offset
        ll = 0.0
        for i in range(N):
            if np.isnan(data[i, t]):
                continue
            zi = Zd[i]
            zP = zi @ P
            f = zP @ zi + obs_var
            v = y_eff[i] - zi @ beta
            K = zP / f
            beta = beta + K * v
            P = P - np.outer(K, zP)
            ll -= 0.5 * (np.log(f) + v * v / f + LOG_2PI)
        betas.append(beta.copy())
        Ps.append(P.copy())
        lls.append(ll)
    return np.asarray(betas), np.asarray(Ps), np.asarray(lls)


def rts_smoother(Z, Phi, delta, Omega_state, obs_var, data):
    """Forward KF (library scan conventions: one step per column, masked
    update on NaN columns) + RTS backward pass.  Returns (beta_smooth (T, Ms),
    P_smooth (T, Ms, Ms), beta_filt, P_filt)."""
    N, T = data.shape
    Ms = Phi.shape[0]
    Omega_obs = obs_var * np.eye(N)
    beta, P = kalman_init(Phi, delta, Omega_state)
    b_pred, P_pred, b_upd, P_upd = [], [], [], []
    for t in range(T):
        y = data[:, t]
        b_pred.append(beta.copy())
        P_pred.append(P.copy())
        if np.all(np.isfinite(y)):
            v = y - Z @ beta
            F = Z @ P @ Z.T + Omega_obs
            K = P @ Z.T @ np.linalg.inv(F)
            bu = beta + K @ v
            Pu = (np.eye(Ms) - K @ Z) @ P
        else:
            bu, Pu = beta.copy(), P.copy()
        b_upd.append(bu)
        P_upd.append(Pu)
        beta = delta + Phi @ bu
        P = Phi @ Pu @ Phi.T + Omega_state
    bs = [None] * T
    Ps = [None] * T
    bs[T - 1], Ps[T - 1] = b_upd[T - 1], P_upd[T - 1]
    for t in range(T - 2, -1, -1):
        G = P_upd[t] @ Phi.T @ np.linalg.inv(P_pred[t + 1])
        bs[t] = b_upd[t] + G @ (bs[t + 1] - b_pred[t + 1])
        Ps[t] = P_upd[t] + G @ (Ps[t + 1] - P_pred[t + 1]) @ G.T
    return (np.asarray(bs), np.asarray(Ps),
            np.asarray(b_upd), np.asarray(P_upd))


def kalman_filter_loglik_steps(Z, Phi, delta, Omega_state, obs_var, data):
    """Per-step loglik contributions ℓ_t aligned with the library scan
    (T entries; zero where a step does not contribute) — used to validate
    the per-step score kernel (estimation/inference.py) by finite
    differences against THIS independent NumPy path."""
    N, T = data.shape
    Ms = Phi.shape[0]
    Omega_obs = obs_var * np.eye(N)
    beta, P = kalman_init(Phi, delta, Omega_state)
    lls = np.zeros(T)
    for t in range(T):
        y = data[:, t]
        if np.any(np.isnan(y)):
            beta = delta + Phi @ beta
            P = Phi @ P @ Phi.T + Omega_state
            continue
        v = y - Z @ beta
        F = Z @ P @ Z.T + Omega_obs
        F_inv = np.linalg.inv(F)
        K = P @ Z.T @ F_inv
        if 0 < t < T - 1:  # library mask: contributing steps 1 .. T−2
            sign, logdet = np.linalg.slogdet(F)
            lls[t] = -0.5 * (logdet + v @ F_inv @ v + N * LOG_2PI)
        beta = delta + Phi @ (beta + K @ v)
        P = Phi @ ((np.eye(Ms) - K @ Z) @ P) @ Phi.T + Omega_state
    return lls


def rbpf_loglik(Z, Phi, delta, Omega_state, obs_var, data, normals, uniforms,
                sv_phi, sv_sigma, ess_frac=0.5, d=None):
    """Rao-Blackwellized SV particle filter, independent NumPy float64 loops.

    Oracle for ``ops/particle.particle_filter_loglik`` and
    ``ops/pallas_pf.pf_loglik_batch`` in their common-noise mode: ``normals``
    (T−1, Pn) drive the log-vol AR(1) proposal, ``uniforms`` (T−1,) the
    systematic-resampling offsets.  Deliberately a DIFFERENT algebraic route
    than the engines — the exact per-particle Kalman step runs the plain-
    covariance JOINT N-dimensional update (inv/slogdet per particle), which
    equals the engines' sequential scalar Potter updates by block
    factorization of the Gaussian likelihood; agreement is therefore a real
    cross-check of the filter algebra, not a transliteration.  Conventions
    mirrored from the engines (citations there): skip the first innovation
    (reference kalman/filter.jl:190-195), predict-only NaN columns, ESS-gated
    systematic resampling with searchsorted-left + index clamp, initial
    moments with the engines' +1e-9 / +1e-12 jitters.
    """
    N, T = data.shape
    Ms = Phi.shape[0]
    Pn = normals.shape[1]
    if d is None:
        d = np.zeros(N)
    beta0, P0 = kalman_init(Phi, delta, Omega_state)
    P0 = 0.5 * (P0 + P0.T) + 1e-9 * np.eye(Ms)
    Om = 0.5 * (Omega_state + Omega_state.T) + 1e-12 * np.eye(Ms)
    x = np.repeat(beta0[:, None], Pn, axis=1)          # (Ms, Pn)
    Pc = np.repeat(P0[:, :, None], Pn, axis=2)         # (Ms, Ms, Pn)
    h = np.zeros(Pn)
    logw = np.full(Pn, -np.log(Pn))
    total = 0.0
    for t in range(T - 1):
        y = data[:, t]
        h = sv_phi * h + sv_sigma * normals[t]
        obs = bool(np.all(np.isfinite(y)))
        r = obs_var * np.exp(h)
        ll = np.zeros(Pn)
        if obs:
            x_new = np.empty_like(x)
            P_new = np.empty_like(Pc)
            for p in range(Pn):
                F = Z @ Pc[:, :, p] @ Z.T + r[p] * np.eye(N)
                F_inv = np.linalg.inv(F)
                v = y - d - Z @ x[:, p]
                K = Pc[:, :, p] @ Z.T @ F_inv
                x_new[:, p] = x[:, p] + K @ v
                P_new[:, :, p] = (np.eye(Ms) - K @ Z) @ Pc[:, :, p]
                _, logdet = np.linalg.slogdet(F)
                ll[p] = -0.5 * (logdet + v @ F_inv @ v + N * LOG_2PI)
            x, Pc = x_new, P_new
        x = delta[:, None] + Phi @ x
        Pc = np.einsum("ij,jkp,lk->ilp", Phi, Pc, Phi) + Om[:, :, None]
        contributes = obs and t > 0
        if contributes:
            logw = logw + ll
            m = logw.max()
            step_ll = m + np.log(np.exp(logw - m).sum())
            total += step_ll
            logw = logw - step_ll
            w = np.exp(logw)
            if 1.0 / np.sum(w * w) < ess_frac * Pn:
                pos = (np.arange(Pn) + uniforms[t]) / Pn
                idx = np.clip(np.searchsorted(np.cumsum(w), pos), 0, Pn - 1)
                x, Pc, h = x[:, idx], Pc[:, :, idx], h[idx]
                logw = np.full(Pn, -np.log(Pn))
    return total


def gaussian_log_score(mean, cov, y):
    """Multivariate Gaussian log density log N(y; mean, cov) by the direct
    textbook formula (explicit inverse + slogdet — a DIFFERENT algebraic
    route than the library's Cholesky-whitened form, so agreement checks the
    density, not a transliteration).  Oracle for
    ``utils/evaluation.log_predictive_score``; one point per call."""
    mean = np.asarray(mean, dtype=np.float64)
    cov = np.asarray(cov, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    N = mean.shape[0]
    v = y - mean
    sign, logdet = np.linalg.slogdet(cov)
    if sign <= 0:
        return float("nan")
    return float(-0.5 * (N * LOG_2PI + logdet + v @ np.linalg.inv(cov) @ v))


def crps_sample_naive(samples, y):
    """Ensemble CRPS by the defining double loop (Gneiting & Raftery 2007,
    eq. 20): mean |x_i - y| - (1/2m^2) sum_ij |x_i - x_j|.  Oracle for
    ``utils/evaluation.crps_sample``; 1-D draws per call."""
    x = np.asarray(samples, dtype=np.float64)
    m = x.shape[0]
    t1 = np.mean([abs(xi - y) for xi in x])
    t2 = sum(abs(xi - xj) for xi in x for xj in x) / (2.0 * m * m)
    return float(t1 - t2)


def _tvl_linearize(beta, maturities, exact_jacobian=False):
    """(Z (N, 4), d (N,)) — the TVλ measurement's affine surrogate
    y ≈ Z x + d linearized at ``beta`` (first-order Taylor with the
    reference's analytic Jacobian column, kalman/filter.jl:38-46), shared by
    the iterated-SLR oracle below."""
    lam = LAMBDA_FLOOR + np.exp(beta[3])
    tau = lam * maturities
    z = np.exp(-tau)
    z2 = (1 - z) / tau
    z3 = z2 - z
    dlam = lam - LAMBDA_FLOOR
    if exact_jacobian:
        dz2 = z / lam - (1 - z) / (lam * lam * maturities)
    else:
        dz2 = z / lam - z / (lam * lam * maturities)
    jac = ((beta[1] + beta[2]) * dz2 + beta[2] * maturities * z) * dlam
    Z = np.column_stack([np.ones_like(z), z2, z3, jac])
    h = beta[0] + z2 * beta[1] + z3 * beta[2]
    return Z, h - Z @ beta


def iterated_slr_filter(Phi, delta, Omega_state, obs_var, maturities, data,
                        sweeps=2, chunk=128, exact_jacobian=False):
    """Iterated two-scale SLR filter for the TVλ family — independent NumPy
    float64 loops, the oracle for ``ops/slr_scan.py`` (docs/DESIGN.md §19).

    Deliberately a DIFFERENT algebraic route than the engine: pass A here is
    a plain SEQUENTIAL affine Kalman recursion under the surrogate
    linearized on the prediction-only (constant unconditional-mean) path,
    where the engine composes per-step Woodbury-assembled elements on the
    parallel-prefix tree — agreement therefore checks the element algebra
    and the combine composition, not a transliteration.  The K refinement
    sweeps mirror the engine's semantics exactly: each chunk of ``chunk``
    steps re-runs the TRUE EKF recursion (predict, linearize at the chunk's
    own predicted mean, joint update via explicit inverses) from its entry
    moments — pass A's filtered moments at the chunk boundaries for sweep 1,
    the previous sweep's chunk-exit moments (Jacobi shift, chunk 0 keeps the
    stationary prior) after.  Whole columns with any NaN are predict-only.

    Returns ``(betas (T, Ms) filtered means, Ps (T, Ms, Ms), lls (T,),
    loglik)`` with ``lls`` the per-step contributions (0 on unobserved
    steps) and ``loglik`` their sum over the engines' contributing window
    t = 1 .. T−2 — the value that converges to :func:`ekf_tvl_loglik` in K.
    """
    N, T = data.shape
    Ms = Phi.shape[0]
    beta0, P0 = kalman_init(Phi, delta, Omega_state)
    Omega_obs = obs_var * np.eye(N)

    # pass A — sequential affine filter under the constant-path surrogate
    Zc, dc = _tvl_linearize(Phi @ beta0 + delta, maturities, exact_jacobian)
    beta, P = beta0.copy(), P0.copy()
    filt = []
    for t in range(T):
        beta = delta + Phi @ beta
        P = Phi @ P @ Phi.T + Omega_state
        y = data[:, t]
        if np.all(np.isfinite(y)):
            v = y - (Zc @ beta + dc)
            F = Zc @ P @ Zc.T + Omega_obs
            K = P @ Zc.T @ np.linalg.inv(F)
            beta = beta + K @ v
            P = (np.eye(Ms) - K @ Zc) @ P
        filt.append((beta.copy(), P.copy()))

    L = min(chunk, T)
    n_chunks = -(-T // L)
    entries = [(beta0.copy(), P0.copy())]
    entries += [tuple(np.copy(a) for a in filt[c * L - 1])
                for c in range(1, n_chunks)]

    # K refinement sweeps — exact EKF within chunks, Jacobi boundary shift
    for _ in range(sweeps):
        betas = np.zeros((T, Ms))
        Ps = np.zeros((T, Ms, Ms))
        lls = np.zeros(T)
        exits = []
        for c in range(n_chunks):
            beta, P = (np.copy(a) for a in entries[c])
            for j in range(c * L, min((c + 1) * L, T)):
                beta = delta + Phi @ beta
                P = Phi @ P @ Phi.T + Omega_state
                y = data[:, j]
                if np.all(np.isfinite(y)):
                    Z, d = _tvl_linearize(beta, maturities, exact_jacobian)
                    v = y - (Z @ beta + d)
                    F = Z @ P @ Z.T + Omega_obs
                    F_inv = np.linalg.inv(F)
                    K = P @ Z.T @ F_inv
                    _, logdet = np.linalg.slogdet(F)
                    lls[j] = -0.5 * (logdet + v @ F_inv @ v + N * LOG_2PI)
                    beta = beta + K @ v
                    P = (np.eye(Ms) - K @ Z) @ P
                betas[j] = beta
                Ps[j] = P
            exits.append((beta.copy(), P.copy()))
        entries = [(beta0.copy(), P0.copy())] + exits[:-1]

    obs = np.all(np.isfinite(data), axis=0)
    contrib = (np.arange(T) >= 1) & (np.arange(T) <= T - 2) & obs
    return betas, Ps, lls, float(np.sum(np.where(contrib, lls, 0.0)))


def _tvl_sigma_linearize(m, P, maturities):
    """(Z (N, Ms), d (N,), mu (N,)) — sigma-point STATISTICAL linearization
    of the TVλ measurement at (m, P): the oracle definition of the ``"ukf"``
    rule in ``config.SLR_ENGINES`` (ops/slr_scan._sigma_linearize).

    Unscented cubature with κ = 1 (c = Ms+1, w₀ = 1/c, wᵢ = 1/(2c), points
    m ± √c·L·eᵢ with P = LLᵀ); the regression slope here goes the textbook
    route — accumulate Ψ = Σ wᵢ (χᵢ−m)(h(χᵢ)−μ)ᵀ point by point and solve
    against the FULL P — where the engine collapses Ψ to a triangular solve
    against L, so agreement checks the statistics, not a transliteration.
    Same deliberate divergence as the engine: the SLR residual covariance Ω
    is omitted (R stays diagonal), so the fixed point both define is the
    statistically linearized filter with unmodified R."""
    Ms = m.shape[0]
    c = Ms + 1.0
    sc = np.sqrt(c)
    Lc = np.linalg.cholesky(P)

    def h(b):
        lam = LAMBDA_FLOOR + np.exp(b[3])
        tau = lam * maturities
        z = np.exp(-tau)
        z2 = (1 - z) / tau
        z3 = z2 - z
        return b[0] + z2 * b[1] + z3 * b[2]

    pts = [m] + [m + sc * Lc[:, i] for i in range(Ms)] \
        + [m - sc * Lc[:, i] for i in range(Ms)]
    hs = [h(p) for p in pts]
    w0, wi = 1.0 / c, 1.0 / (2.0 * c)
    mu = w0 * hs[0]
    for hv in hs[1:]:
        mu = mu + wi * hv
    Psi = np.zeros((Ms, len(maturities)))
    for i, p in enumerate(pts):
        w = w0 if i == 0 else wi
        Psi += w * np.outer(p - m, hs[i] - mu)
    Z = np.linalg.solve(P, Psi).T
    d = mu - Z @ m
    return Z, d, mu


def sigma_point_filter(Phi, delta, Omega_state, obs_var, maturities, data):
    """Sequential statistically-linearized (sigma-point, diagonal-R) filter
    for the TVλ family — independent NumPy float64 loop, the FIXED POINT the
    ``"ukf"`` iterated-SLR engine converges to (each step linearizes at its
    own predicted moments, exactly what the engine's chunk refinement does).
    Same windowing/NaN conventions as :func:`iterated_slr_filter`.

    Returns ``(betas (T, Ms), Ps (T, Ms, Ms), lls (T,), loglik)``."""
    N, T = data.shape
    Ms = Phi.shape[0]
    beta, P = kalman_init(Phi, delta, Omega_state)
    beta0, P0 = beta.copy(), P.copy()
    Omega_obs = obs_var * np.eye(N)
    betas = np.zeros((T, Ms))
    Ps = np.zeros((T, Ms, Ms))
    lls = np.zeros(T)
    for t in range(T):
        beta = delta + Phi @ beta
        P = Phi @ P @ Phi.T + Omega_state
        y = data[:, t]
        if np.all(np.isfinite(y)):
            Z, d, _ = _tvl_sigma_linearize(beta, P, maturities)
            v = y - (Z @ beta + d)
            F = Z @ P @ Z.T + Omega_obs
            F_inv = np.linalg.inv(F)
            K = P @ Z.T @ F_inv
            _, logdet = np.linalg.slogdet(F)
            lls[t] = -0.5 * (logdet + v @ F_inv @ v + N * LOG_2PI)
            beta = beta + K @ v
            P = (np.eye(Ms) - K @ Z) @ P
        betas[t] = beta
        Ps[t] = P
    obs = np.all(np.isfinite(data), axis=0)
    contrib = (np.arange(T) >= 1) & (np.arange(T) <= T - 2) & obs
    del beta0, P0
    return betas, Ps, lls, float(np.sum(np.where(contrib, lls, 0.0)))


def iterated_sigma_slr_filter(Phi, delta, Omega_state, obs_var, maturities,
                              data, sweeps=2, chunk=128):
    """Iterated two-scale SLR filter under the SIGMA-POINT rule — the
    ``"ukf"`` twin of :func:`iterated_slr_filter`, mirroring the engine's
    sweep semantics step for step: pass A linearizes ONCE at the stationary
    predicted moments (constant reference mean AND covariance) and runs a
    sequential affine filter under that frozen surrogate (a different
    algebraic route than the engine's Woodbury-element combine tree); the K
    refinement sweeps re-run the TRUE statistically-linearized recursion
    within chunks (predict, sigma-point linearize at the chunk's own
    predicted moments, joint update via explicit inverses) with the Jacobi
    boundary shift.  Converges to :func:`sigma_point_filter` in K."""
    N, T = data.shape
    Ms = Phi.shape[0]
    beta0, P0 = kalman_init(Phi, delta, Omega_state)
    Omega_obs = obs_var * np.eye(N)

    # pass A — sequential affine filter under the constant-moment surrogate
    Ppred1 = Phi @ P0 @ Phi.T + Omega_state
    Zc, dc, _ = _tvl_sigma_linearize(Phi @ beta0 + delta, Ppred1, maturities)
    beta, P = beta0.copy(), P0.copy()
    filt = []
    for t in range(T):
        beta = delta + Phi @ beta
        P = Phi @ P @ Phi.T + Omega_state
        y = data[:, t]
        if np.all(np.isfinite(y)):
            v = y - (Zc @ beta + dc)
            F = Zc @ P @ Zc.T + Omega_obs
            K = P @ Zc.T @ np.linalg.inv(F)
            beta = beta + K @ v
            P = (np.eye(Ms) - K @ Zc) @ P
        filt.append((beta.copy(), P.copy()))

    L = min(chunk, T)
    n_chunks = -(-T // L)
    entries = [(beta0.copy(), P0.copy())]
    entries += [tuple(np.copy(a) for a in filt[c * L - 1])
                for c in range(1, n_chunks)]

    # K refinement sweeps — exact sigma-point recursion within chunks
    for _ in range(sweeps):
        betas = np.zeros((T, Ms))
        Ps = np.zeros((T, Ms, Ms))
        lls = np.zeros(T)
        exits = []
        for c in range(n_chunks):
            beta, P = (np.copy(a) for a in entries[c])
            for j in range(c * L, min((c + 1) * L, T)):
                beta = delta + Phi @ beta
                P = Phi @ P @ Phi.T + Omega_state
                y = data[:, j]
                if np.all(np.isfinite(y)):
                    Z, d, _ = _tvl_sigma_linearize(beta, P, maturities)
                    v = y - (Z @ beta + d)
                    F = Z @ P @ Z.T + Omega_obs
                    F_inv = np.linalg.inv(F)
                    K = P @ Z.T @ F_inv
                    _, logdet = np.linalg.slogdet(F)
                    lls[j] = -0.5 * (logdet + v @ F_inv @ v + N * LOG_2PI)
                    beta = beta + K @ v
                    P = (np.eye(Ms) - K @ Z) @ P
                betas[j] = beta
                Ps[j] = P
            exits.append((beta.copy(), P.copy()))
        entries = [(beta0.copy(), P0.copy())] + exits[:-1]

    obs = np.all(np.isfinite(data), axis=0)
    contrib = (np.arange(T) >= 1) & (np.arange(T) <= T - 2) & obs
    return betas, Ps, lls, float(np.sum(np.where(contrib, lls, 0.0)))


def fd_hessian(fun, x, eps=1e-4):
    """Central-difference Hessian of a scalar callable — independent NumPy
    loops, the second-order parity oracle (tests/test_newton.py pins the
    HVP recursions of ops/newton.py against it at ``stable_1c_params`` /
    ``stable_ns_params``).

    H[i, j] = (f(x+e_i+e_j) - f(x+e_i-e_j) - f(x-e_i+e_j) + f(x-e_i-e_j))
              / (4 eps_i eps_j)

    with per-coordinate steps eps_i = eps * max(1, |x_i|); the result is
    symmetrized.  ``fun`` must be float64-evaluable at every probe (pass a
    penalty-clamped objective if the region is fragile).
    """
    x = np.asarray(x, dtype=np.float64)
    P = x.shape[0]
    h = eps * np.maximum(1.0, np.abs(x))
    H = np.zeros((P, P))
    for i in range(P):
        for j in range(i, P):
            ei = np.zeros(P); ei[i] = h[i]
            ej = np.zeros(P); ej[j] = h[j]
            H[i, j] = (fun(x + ei + ej) - fun(x + ei - ej)
                       - fun(x - ei + ej) + fun(x - ei - ej)) \
                / (4.0 * h[i] * h[j])
            H[j, i] = H[i, j]
    return H


def amortizer_forward(params, Y):
    """Independent NumPy mirror of the amortized-estimation surrogate's
    forward pass (estimation/amortize._forward_core, "deepset" architecture,
    docs/DESIGN.md §20) for ONE (N, T) panel: per-step loops, no JAX.

    Per step t ≥ 1 with BOTH columns fully finite: the shared MLP features
    tanh(W1 [ (y_t−μ)/σ ; (y_t−y_{t−1})/σ_Δ ] + b1) enter masked mean /
    second-moment pools; per-maturity panel mean/std pool over all valid
    columns; the pooled summary is soft-clipped at ±4 and mapped through
    the tanh head + linear skip.  An all-invalid panel returns all-NaN (the
    sentinel the library's forward emits).  Output is in NET space (δ slots
    carry the steady state μ — ``raw_from_net`` is the library-side
    inverse, round-trip-tested separately)."""
    Y = np.asarray(Y, dtype=np.float64)
    N, T = Y.shape
    y_mu = np.asarray(params["y_mu"], dtype=np.float64)
    y_sd = np.asarray(params["y_sd"], dtype=np.float64)
    dy_sd = np.asarray(params["dy_sd"], dtype=np.float64)
    W1 = np.asarray(params["W1"], dtype=np.float64)
    b1 = np.asarray(params["b1"], dtype=np.float64)
    H = W1.shape[0]
    valid = [bool(np.all(np.isfinite(Y[:, t]))) for t in range(T)]
    m1 = np.zeros(H)
    m2 = np.zeros(H)
    n_pairs = 0
    for t in range(1, T):
        if not (valid[t] and valid[t - 1]):
            continue
        yn = (Y[:, t] - y_mu) / y_sd
        dy = (Y[:, t] - Y[:, t - 1]) / dy_sd
        h = np.tanh(W1 @ np.concatenate([yn, dy]) + b1)
        m1 += h
        m2 += h * h
        n_pairs += 1
    my = np.zeros(N)
    s2 = np.zeros(N)
    n_cols = 0
    for t in range(T):
        if not valid[t]:
            continue
        yn = (Y[:, t] - y_mu) / y_sd
        my += yn
        s2 += yn * yn
        n_cols += 1
    if n_pairs == 0 or n_cols == 0:
        return np.full(np.asarray(params["b3"]).shape[0], np.nan)
    m1, m2 = m1 / n_pairs, m2 / n_pairs
    my = my / n_cols
    sy = np.sqrt(np.maximum(s2 / n_cols - my * my, 0.0))
    Z = np.concatenate([m1, m2, my, sy])
    Z = 4.0 * np.tanh(Z / 4.0)
    G = np.tanh(np.asarray(params["W2"], dtype=np.float64) @ Z
                + np.asarray(params["b2"], dtype=np.float64))
    return np.asarray(params["W3"], dtype=np.float64) @ G \
        + np.asarray(params["Ws"], dtype=np.float64) @ Z \
        + np.asarray(params["b3"], dtype=np.float64)


def amortizer_loss(params, panels, targets):
    """NumPy mirror of the amortizer's masked training loss
    (estimation/amortize._loss_core): mean squared error on the NET-space
    targets over the batch, a sample weighted ZERO when its panel's forward
    pass is non-finite (failed simulation → NaN panel) or its target row is
    — bad samples are masked, never raised.  ``panels`` (B, N, T),
    ``targets`` (B, P)."""
    panels = np.asarray(panels, dtype=np.float64)
    targets = np.asarray(targets, dtype=np.float64)
    B, P = targets.shape
    total = 0.0
    n_ok = 0
    for b in range(B):
        pred = amortizer_forward(params, panels[b])
        if not (np.all(np.isfinite(pred)) and np.all(np.isfinite(targets[b]))):
            continue
        total += float(np.sum((pred - targets[b]) ** 2))
        n_ok += 1
    return total / (max(n_ok, 1) * P)


def fan_refresh(Z, d, Phi, delta, Omega_state, obs_var, beta, P, shifts,
                vol_scales, horizon):
    """Constant-Z stress-fan densities by the defining per-shock loop — the
    oracle for ``ops/forecast.density_fan`` and the streaming hub's delta
    refresh (serving/streams.py): for every shock s the filtered state is
    displaced (β + shifts[s], P · vol_scales[s]²) and the textbook
    propagate-then-emit recursion runs h steps (b ← δ + Φb, Pm ← ΦPmΦᵀ + Ω;
    mean = Zb + d, cov = ZPmZᵀ + σ²I).  Straight float64 loops, no JAX;
    returns means (S, h, N) and covs (S, h, N, N)."""
    Z = np.asarray(Z, dtype=np.float64)
    d = np.asarray(d, dtype=np.float64)
    N = Z.shape[0]
    S = len(vol_scales)
    means = np.zeros((S, horizon, N))
    covs = np.zeros((S, horizon, N, N))
    for s in range(S):
        b = np.asarray(beta, dtype=np.float64) + np.asarray(shifts[s],
                                                            dtype=np.float64)
        Pm = np.asarray(P, dtype=np.float64) * float(vol_scales[s]) ** 2
        for k in range(horizon):
            b = delta + Phi @ b
            Pm = Phi @ Pm @ Phi.T + Omega_state
            means[s, k] = Z @ b + d
            covs[s, k] = Z @ Pm @ Z.T + obs_var * np.eye(N)
    return means, covs
