"""Simulation-based parameter recovery (SURVEY.md §4: 'simulation-based
recovery tests — estimate on DGP-simulated data').

The reference validates only through its external simulation mode with no
assertions; here the MLE must actually recover the DGP's decay rate and
persistence from a simulated panel.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from yieldfactormodels_jl_tpu import create_model, get_loss
from yieldfactormodels_jl_tpu.estimation import optimize
from yieldfactormodels_jl_tpu.models.params import unpack_kalman
from tests.oracle import simulate_dns_panel

MATS = np.array([3, 6, 9, 12, 18, 24, 36, 48, 60, 84, 120, 240, 360]) / 12.0
TRUE_LAM = 0.5


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(42)
    data = simulate_dns_panel(rng, MATS, T=300, lam=TRUE_LAM)
    spec, _ = create_model("1C", tuple(MATS), float_type="float64")
    p0 = np.zeros(spec.n_params)
    p0[0] = np.log(0.3)          # start λ well away from the truth
    p0[1] = 1e-3
    k = 2
    for j in range(3):
        for i in range(j + 1):
            p0[k] = 0.1 if i == j else 0.0
            k += 1
    p0[8:11] = [0.3, -0.1, 0.05]
    p0[11:20] = (0.9 * np.eye(3)).reshape(-1)
    starts = np.stack([p0, p0 * 1.1], axis=1)  # (P, S)
    _, ll, best, _ = optimize.estimate(spec, data, starts, max_iters=400)
    return spec, data, ll, best


def test_loglik_beats_start(fitted):
    spec, data, ll, best = fitted
    assert np.isfinite(ll)
    assert float(get_loss(spec, jnp.asarray(best), jnp.asarray(data))) == \
        pytest.approx(ll, rel=1e-6)


def test_lambda_recovered(fitted):
    spec, _, _, best = fitted
    lam_hat = 1e-2 + np.exp(best[0])
    assert abs(lam_hat - TRUE_LAM) / TRUE_LAM < 0.15, lam_hat


def test_persistence_recovered(fitted):
    spec, _, _, best = fitted
    kp = unpack_kalman(spec, jnp.asarray(best))
    eig = np.abs(np.linalg.eigvals(np.asarray(kp.Phi)))
    # DGP diag(0.95, 0.9, 0.85): stationary and strongly persistent
    assert np.all(eig < 1.0)
    assert eig.max() > 0.8


def test_obs_variance_recovered(fitted):
    spec, _, _, best = fitted
    # DGP measurement noise sd = 0.02 ⇒ variance 4e-4
    kp = unpack_kalman(spec, jnp.asarray(best))
    assert 4e-5 < float(kp.obs_var) < 4e-3
