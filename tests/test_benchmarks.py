"""Smoke the five BASELINE benchmark configs at reduced scale.

The driver and the judge rely on ``benchmarks/run_all.py``; this guards the
harness against rot (import drift, API changes in the kernels it drives)
without paying full-scale runtimes.  Each config runs in a SUBPROCESS with
the production environment (f32, no jax_enable_x64) — the same way
``run_all._orchestrate`` launches them; the suite's in-process x64 mode
would otherwise trip an optax-linesearch weak-type issue that never occurs
in the real runs.  (This smoke is what caught the f64 quadrature leak into
the f32 PF scan carry — ops/particle._measurement now casts.)
"""

import json
import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

_SNIPPET = """
import json, sys
sys.path.insert(0, {bench!r}); sys.path.insert(0, {root!r})
import run_all
wall, descr = run_all._run_config({name!r}, {scale})
print("RESULT " + json.dumps([wall, descr]))
"""


@pytest.mark.parametrize("name,scale", [
    ("dns3-mle", 1),          # batch axis is already 1; full config
    ("afns5-mle64", 64),      # 1 start
    ("afns5-sv-pf", 250),     # 4 draws
    ("rolling-240", 48),      # 5 windows
    ("bootstrap-2000", 100),  # 20 resamples
    ("ssd-nns-m3", 10),       # 1 start x 1 group iter
    ("bootstrap-xl", 1600),   # 5 resamples (the 16× throughput-scaled row)
])
def test_benchmark_config_runs(name, scale):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "JAX_ENABLE_X64")}
    env.update({"JAX_PLATFORMS": "cpu", "BENCH_PF_CHUNK": "4",
                "OMP_NUM_THREADS": "1"})
    code = _SNIPPET.format(bench=os.path.join(ROOT, "benchmarks"),
                           root=ROOT, name=name, scale=scale)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")]
    assert lines, proc.stdout[-500:]
    wall, descr = json.loads(lines[-1][len("RESULT "):])
    assert wall > 0 and isinstance(descr, str) and descr
    if name == "afns5-sv-pf":
        # the finite-draw count is part of the work string; all must survive
        assert "finite 4/4" in descr, descr


def test_device_recover_rejects_unknown_steps(monkeypatch, tmp_path):
    """A RECOVER_STEPS typo must fail loudly, not no-op to 'success'."""
    import importlib.util

    spec_ = importlib.util.spec_from_file_location(
        "device_recover",
        os.path.join(os.path.dirname(__file__), os.pardir, "benchmarks",
                     "device_recover.py"))
    mod = importlib.util.module_from_spec(spec_)
    spec_.loader.exec_module(mod)
    monkeypatch.setenv("RECOVER_STEPS", "pf-race")  # typo: dash not underscore
    monkeypatch.setattr(mod, "WORKDIR", str(tmp_path))
    monkeypatch.setattr(mod, "LOG", str(tmp_path / "log"))
    with pytest.raises(SystemExit, match="unknown RECOVER_STEPS"):
        mod.device_sequence()
