"""Streaming scenario subscriptions (serving/streams.py, docs/DESIGN.md §23).

Acceptance coverage for the delta-refresh tentpole:

- subscription answers match BOTH the independent NumPy oracle
  (``oracle.fan_refresh`` — straight float64 loops) and the full
  ``stress_fan`` recompute from the same posterior, before and after online
  updates (the delta chain is numerically the full recompute);
- one compiled refresh program and zero donation warnings across whole
  subscribe/update/answer lifecycles (two subscribers, several updates);
- refilter/refit events fall back to the full-recompute path and the fan
  tracks the rebuilt posterior;
- the ``refresh_storm``/``fan_stale`` chaos seams: degraded answers from the
  last promoted fan, healed by the next accepted update;
- the ``YFM_FAN_STALE_MS`` staleness budget under an injected clock (stale
  answers are served-and-flagged, never recomputed inline);
- the sharded-gateway mode: per-key dirty marking through the pump, an
  untouched key's fan stays bit-identical;
- the shock grammar (``program.shocks``) and ``replay_episodes`` end-to-end,
  plus the slot lifecycle (duplicate keys, unsubscribe/reuse, growth).
"""

import warnings

import numpy as np
import pytest

import yieldfactormodels_jl_tpu as yfm
from tests import oracle
from yieldfactormodels_jl_tpu import serving
from yieldfactormodels_jl_tpu.estimation import scenario as sc
from yieldfactormodels_jl_tpu.models.params import unpack_kalman
from yieldfactormodels_jl_tpu.orchestration import chaos
from yieldfactormodels_jl_tpu.program import ShockRule, compile_shocks
from yieldfactormodels_jl_tpu.robustness import taxonomy as tax
from yieldfactormodels_jl_tpu.serving import streams

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)
T_PANEL = 48
T_ORIGIN = 40
H = 4


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.reset()
    yield
    chaos.reset()


@pytest.fixture(scope="module")
def dns_setup():
    rng = np.random.default_rng(17)
    spec, _ = yfm.create_model("1C", MATS, float_type="float64")
    p = oracle.stable_1c_params(spec, np.float64)
    data = oracle.simulate_dns_panel(rng, np.asarray(MATS), T=T_PANEL)
    return spec, p, data


@pytest.fixture()
def service(dns_setup):
    spec, p, data = dns_setup
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    return serving.YieldCurveService(snap)


def _donation_warnings(w):
    return [str(i.message) for i in w
            if "donated" in str(i.message).lower()]


def _oracle_fan(spec, p, snap, shocks, horizon):
    """The independent NumPy fan from a snapshot's posterior."""
    kp = unpack_kalman(spec, np.asarray(p))
    Z = oracle.dns_loadings(float(np.asarray(p)[spec.layout["gamma"][0]]),
                            np.asarray(MATS))
    shifts, vols, _, _ = sc._shock_arrays(shocks, spec.state_dim, np.float64)
    return oracle.fan_refresh(
        Z, np.zeros(spec.N), np.asarray(kp.Phi), np.asarray(kp.delta),
        np.asarray(kp.Omega_state), float(kp.obs_var),
        np.asarray(snap.beta), np.asarray(snap.P),
        np.asarray(shifts), np.asarray(vols), horizon)


# ---------------------------------------------------------------------------
# oracle + full-recompute parity
# ---------------------------------------------------------------------------

def test_subscribe_matches_oracle_and_stress_fan(dns_setup, service):
    spec, p, _ = dns_setup
    hub = serving.ScenarioStreamHub(service)
    hub.subscribe("alice", horizon=H)
    ans = hub.fan("alice")
    # independent NumPy loops (CLAUDE.md parity rule)
    o_means, o_covs = _oracle_fan(spec, p, service.snapshot,
                                  sc.standard_fan(spec), H)
    np.testing.assert_allclose(ans["means"], o_means, rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(ans["covs"], o_covs, rtol=1e-9, atol=1e-12)
    # and the full recompute from the same posterior
    full = service.stress_fan(h=H)
    np.testing.assert_allclose(ans["means"], full["means"], rtol=1e-12)
    np.testing.assert_allclose(ans["covs"], full["covs"], rtol=1e-12)
    assert ans["version"] == service.version == full["version"]
    assert "computed_at" in full and full["computed_at"] is not None
    assert not ans["degraded"] and not ans["stale"]
    assert np.all(ans["codes"] == tax.OK)
    assert ans["names"] == tuple(s.name for s in sc.standard_fan(spec))


def test_delta_refresh_tracks_updates(dns_setup, service):
    """After every accepted update the delta-refreshed fan equals the full
    stress_fan recomputed from the CURRENT posterior — the delta chain
    never drifts from the from-scratch answer."""
    spec, p, data = dns_setup
    hub = serving.ScenarioStreamHub(service)
    hub.subscribe("alice", horizon=H)
    for t in range(T_ORIGIN, T_ORIGIN + 4):
        service.update(t, data[:, t])
        ans = hub.fan("alice")
        full = service.stress_fan(h=H)
        np.testing.assert_allclose(ans["means"], full["means"], rtol=1e-12)
        np.testing.assert_allclose(ans["covs"], full["covs"], rtol=1e-12)
        assert ans["version"] == service.version
        assert not ans["degraded"]
        assert ans["age_ms"] is not None and ans["age_ms"] >= 0.0
    o_means, _ = _oracle_fan(spec, p, service.snapshot,
                             sc.standard_fan(spec), H)
    np.testing.assert_allclose(ans["means"], o_means, rtol=1e-9, atol=1e-12)
    assert hub.counters.refreshes >= 4 and hub.counters.full_recomputes == 0


def test_one_program_zero_donation_warnings(dns_setup, service):
    """Whole subscribe → update → answer lifecycles compile the refresh
    program exactly ONCE, with zero buffer-not-donated warnings — two
    subscribers share one block/wave."""
    _, _, data = dns_setup
    streams.reset_trace_counts()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        hub = serving.ScenarioStreamHub(service, capacity=4)
        hub.subscribe("alice", horizon=H)
        hub.subscribe("bob", horizon=H)
        for t in range(T_ORIGIN, T_ORIGIN + 3):
            service.update(t, data[:, t])
            hub.fan("alice")
            hub.fan("bob")
    assert streams.trace_counts["fan_refresh"] == 1
    assert not _donation_warnings(w)
    a, b = hub.fan("alice"), hub.fan("bob")
    np.testing.assert_allclose(a["means"], b["means"], rtol=1e-12)
    assert hub.health()["blocks"][0]["subscribed"] == 2


def test_refilter_falls_back_to_full_recompute(dns_setup, service):
    """A rebuild event (refilter) breaks the delta chain: the hub recomputes
    from scratch and the fan matches the rebuilt posterior."""
    _, _, data = dns_setup
    hub = serving.ScenarioStreamHub(service)
    hub.subscribe("alice", horizon=H)
    assert hub.counters.full_recomputes == 0
    service.refilter(data[:, :T_ORIGIN + 3])
    assert hub.counters.full_recomputes == 1
    ans = hub.fan("alice")
    full = service.stress_fan(h=H)
    np.testing.assert_allclose(ans["means"], full["means"], rtol=1e-12)
    assert ans["version"] == service.version
    assert not ans["degraded"]


# ---------------------------------------------------------------------------
# chaos seams + staleness budget
# ---------------------------------------------------------------------------

def test_refresh_storm_degrades_then_heals(dns_setup, service):
    """A dropped wave leaves the fan at the last promoted version, answers
    degraded, and the NEXT accepted update heals it — the update path is
    never blocked."""
    _, _, data = dns_setup
    hub = serving.ScenarioStreamHub(service)
    hub.subscribe("alice", horizon=H)
    v0 = hub.fan("alice")["version"]
    chaos.configure("refresh_storm:@1")
    service.update(T_ORIGIN, data[:, T_ORIGIN])
    ans = hub.fan("alice")
    assert ans["degraded"] and ans["version"] == v0
    assert hub.counters.dropped_waves == 1
    assert np.all(np.isfinite(ans["means"]))   # last fan, not garbage
    service.update(T_ORIGIN + 1, data[:, T_ORIGIN + 1])
    healed = hub.fan("alice")
    full = service.stress_fan(h=H)
    np.testing.assert_allclose(healed["means"], full["means"], rtol=1e-12)
    assert not healed["degraded"] and healed["version"] == service.version


def test_fan_stale_chaos_degrades_one_answer(dns_setup, service):
    hub = serving.ScenarioStreamHub(service)
    hub.subscribe("alice", horizon=H)
    chaos.configure("fan_stale:@1")
    bad = hub.fan("alice")
    assert bad["degraded"] and np.all(np.isfinite(bad["means"]))
    good = hub.fan("alice")
    assert not good["degraded"]
    assert hub.counters.degraded_answers == 1


def test_stale_budget_flags_but_serves(dns_setup, service):
    """Past the YFM_FAN_STALE_MS budget the answer is stale-flagged and
    counted degraded but still served from the resident fan — never an
    inline recompute (the injected clock proves no refresh ran)."""
    now = [0.0]
    hub = serving.ScenarioStreamHub(service, stale_ms=5.0,
                                    clock=lambda: now[0])
    hub.subscribe("alice", horizon=H)
    fresh = hub.fan("alice")
    assert not fresh["stale"]
    now[0] += 1.0   # 1000 ms on a 5 ms budget
    stale = hub.fan("alice")
    assert stale["stale"] and stale["degraded"]
    assert stale["age_ms"] == pytest.approx(1000.0)
    np.testing.assert_allclose(stale["means"], fresh["means"], rtol=0)
    assert hub.counters.full_recomputes == 0


def test_stale_budget_reads_env(dns_setup, service, monkeypatch):
    monkeypatch.setenv("YFM_FAN_STALE_MS", "250")
    hub = serving.ScenarioStreamHub(service)
    assert hub.stale_ms == 250.0


# ---------------------------------------------------------------------------
# sharded-gateway mode
# ---------------------------------------------------------------------------

def test_sharded_gateway_per_key_refresh(dns_setup):
    import dataclasses

    from yieldfactormodels_jl_tpu.parallel import mesh as pmesh

    spec, p, data = dns_setup
    snap = serving.freeze_snapshot(spec, p, data, end=T_ORIGIN)
    lattice = serving.BucketLattice(horizons=(4,), batch_sizes=(1,),
                                    scenario_counts=(4,),
                                    update_batch_sizes=(1, 4))
    store = serving.ShardedStateStore(spec, mesh=pmesh.make_mesh(8),
                                      shard_capacity=4, lattice=lattice)
    keys = store.register_many(
        dataclasses.replace(snap, meta=dataclasses.replace(snap.meta,
                                                           task_id=i))
        for i in range(3))
    gw = serving.ShardedGateway(store, queue_max=64, queue_age_ms=0.0)
    hub = serving.ScenarioStreamHub(gw)
    for k in keys:
        hub.subscribe(k, horizon=H)
    before = hub.fan(keys[1])
    t = gw.submit_update(0, data[:, T_ORIGIN], key=keys[0])
    assert gw.pump() == 1
    assert np.isfinite(gw.poll(t)["ll"])
    # the touched key tracks its NEW mesh-resident posterior...
    s0 = store.snapshot_of(keys[0])
    ref = sc.stress_fan(spec, np.asarray(s0.params), np.asarray(s0.beta),
                        np.asarray(s0.P), sc.standard_fan(spec), H, 0)
    touched = hub.fan(keys[0])
    np.testing.assert_allclose(touched["means"], ref["means"], rtol=1e-12)
    assert touched["version"] == s0.meta.version
    assert not touched["degraded"]
    # ...and the untouched key's fan is bit-identical to before
    after = hub.fan(keys[1])
    np.testing.assert_array_equal(after["means"], before["means"])
    assert after["version"] == before["version"]


# ---------------------------------------------------------------------------
# shock grammar + replay + slot lifecycle
# ---------------------------------------------------------------------------

def test_shock_grammar_and_replay_subscriptions(dns_setup, service):
    spec, p, data = dns_setup
    hub = serving.ScenarioStreamHub(service)
    rules = (ShockRule("steep", kind="factor", factor="slope", size=-0.5),
             ShockRule("calm", kind="vol", vol_scale=0.5),
             ShockRule("steep_calm", kind="combo",
                       of=(("steep", 1.0), ("calm", 1.0))))
    hub.subscribe("grammar", shocks=rules, horizon=H)
    ans = hub.fan("grammar")
    assert ans["names"] == ("steep", "calm", "steep_calm")
    compiled = compile_shocks(rules, spec)
    snap = service.snapshot
    ref = sc.stress_fan(spec, snap.params, snap.beta, snap.P, compiled, H, 0)
    np.testing.assert_allclose(ans["means"], ref["means"], rtol=1e-12)
    # the combo is the sum of its parts' displacements
    assert compiled[2].beta_shift == compiled[0].beta_shift
    assert compiled[2].vol_scale == pytest.approx(0.5)
    # replay episodes: shocks read from the panel's own filtered history
    eps = sc.replay_episodes(spec, p, data, [(5, 12), (20, 30, "taper")])
    assert [e.name for e in eps] == ["replay_5_12", "taper"]
    hub.subscribe("replay", shocks=eps, horizon=H)
    rep = hub.fan("replay")
    assert rep["names"] == ("replay_5_12", "taper")
    assert np.all(np.isfinite(rep["means"]))


def test_shock_grammar_rejects_malformed(dns_setup, service):
    spec, _, _ = dns_setup
    hub = serving.ScenarioStreamHub(service)
    with pytest.raises(serving.ServingError):
        hub.subscribe("x", shocks="weird")
    with pytest.raises(serving.ServingError):
        hub.subscribe("x", shocks=())
    with pytest.raises(serving.ServingError):   # mixed rule/spec tuple
        hub.subscribe("x", shocks=(sc.standard_fan(spec)[0],
                                   ShockRule("a", size=0.1)))
    with pytest.raises(serving.ServingError):
        hub.subscribe("x", horizon=0)
    with pytest.raises(ValueError):   # combo referencing a LATER rule
        compile_shocks((ShockRule("c", kind="combo", of=(("a", 1.0),)),
                        ShockRule("a", size=0.1)), spec)
    with pytest.raises(ValueError):   # unknown kind is loud, driver-layer
        compile_shocks((ShockRule("z", kind="nope"),), spec)
    assert hub.subscriptions() == ()


def test_slot_lifecycle_reuse_and_growth(dns_setup, service):
    _, _, data = dns_setup
    hub = serving.ScenarioStreamHub(service, capacity=1)
    hub.subscribe("a", horizon=H)
    with pytest.raises(serving.ServingError):
        hub.subscribe("a", horizon=H)   # duplicate key
    hub.subscribe("b", horizon=H)       # overflow → block doubles
    assert hub.health()["blocks"][0]["capacity"] == 2
    assert set(hub.subscriptions()) == {"a", "b"}
    hub.unsubscribe("a")
    with pytest.raises(serving.ServingError):
        hub.fan("a")
    with pytest.raises(serving.ServingError):
        hub.unsubscribe("a")
    hub.subscribe("c", horizon=H)       # freed slot is reused, no growth
    assert hub.health()["blocks"][0]["capacity"] == 2
    service.update(T_ORIGIN, data[:, T_ORIGIN])
    ans = hub.fan("c")
    full = service.stress_fan(h=H)
    np.testing.assert_allclose(ans["means"], full["means"], rtol=1e-12)
