"""Univariate (sequential-observation) Kalman loglik equals the joint form.

The innovations decomposition makes the two algebraically identical for
diagonal measurement error; these tests pin that equality across families,
windows, NaN forecasting columns, gradients, and vmap batches.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tests.test_kalman import _dns_params
from yieldfactormodels_jl_tpu import create_model
from yieldfactormodels_jl_tpu.models import kalman as K
from yieldfactormodels_jl_tpu.ops import univariate_kf as U


def _afns5_params(spec, seed=3):
    rng = np.random.default_rng(seed)
    p = np.zeros(spec.n_params)
    p[0], p[1] = np.log(0.5), np.log(0.15)
    p[2] = 4e-4
    k = 3
    for j in range(5):
        for i in range(j + 1):
            p[k] = 0.05 + 0.01 * i if i == j else 0.002
            k += 1
    p[18:23] = [4.0, -1.0, 0.5, -0.3, 0.2]
    p[23:48] = np.diag([0.98, 0.94, 0.9, 0.92, 0.88]).reshape(-1)
    p[23:48] += 0.001 * rng.standard_normal(25)
    return p


def test_univariate_equals_joint_dns(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, *_ = _dns_params()
    data = jnp.asarray(yields_panel)
    want = float(K.get_loss(spec, jnp.asarray(p), data))
    got = float(U.get_loss(spec, jnp.asarray(p), data))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_univariate_equals_joint_afns5(maturities, yields_panel):
    spec, _ = create_model("AFNS5", tuple(maturities), float_type="float64")
    p = _afns5_params(spec)
    data = jnp.asarray(yields_panel)
    want = float(K.get_loss(spec, jnp.asarray(p), data))
    got = float(U.get_loss(spec, jnp.asarray(p), data))
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_univariate_equals_joint_tvl(maturities, yields_panel):
    spec, _ = create_model("TVλ", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(7)
    p = np.zeros(spec.n_params)
    p[0] = 1e-3
    k = 1
    for j in range(4):
        for i in range(j + 1):
            p[k] = 0.08 + 0.01 * i if i == j else 0.003
            k += 1
    p[11:15] = [0.3, -0.1, 0.05, np.log(0.5)]
    p[15:31] = (np.diag([0.95, 0.9, 0.85, 0.9])
                + 0.002 * rng.standard_normal((4, 4))).reshape(-1)
    data = jnp.asarray(yields_panel)
    want = float(K.get_loss(spec, jnp.asarray(p), data))
    got = float(U.get_loss(spec, jnp.asarray(p), data))
    np.testing.assert_allclose(got, want, rtol=1e-8)


def test_univariate_windows_and_nan_padding(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, *_ = _dns_params()
    padded = np.concatenate(
        [yields_panel, np.full((yields_panel.shape[0], 11), np.nan)], axis=1)
    data = jnp.asarray(padded)
    for lo, hi in [(0, padded.shape[1]), (10, 60), (0, 40)]:
        want = float(K.get_loss(spec, jnp.asarray(p), data, lo, hi))
        got = float(U.get_loss(spec, jnp.asarray(p), data, lo, hi))
        np.testing.assert_allclose(got, want, rtol=1e-9, err_msg=f"window {lo}:{hi}")


def test_univariate_neg_inf_sentinel(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, *_ = _dns_params()
    p[11] = 1.5  # explosive Phi ⇒ invalid unconditional start
    got = float(U.get_loss(spec, jnp.asarray(p), jnp.asarray(yields_panel)))
    assert got == -np.inf


def test_univariate_gradient_matches_joint(maturities, yields_panel):
    spec, _ = create_model("1C", tuple(maturities), float_type="float64")
    p, *_ = _dns_params()
    data = jnp.asarray(yields_panel)
    g_joint = jax.grad(lambda q: K.get_loss(spec, q, data))(jnp.asarray(p))
    g_uni = jax.grad(lambda q: U.get_loss(spec, q, data))(jnp.asarray(p))
    np.testing.assert_allclose(np.asarray(g_uni), np.asarray(g_joint),
                               rtol=1e-6, atol=1e-8)


def test_univariate_vmap_batch(maturities, yields_panel):
    spec, _ = create_model("AFNS5", tuple(maturities), float_type="float64")
    rng = np.random.default_rng(11)
    base = _afns5_params(spec)
    batch = np.tile(base, (8, 1))
    batch[:, 0:2] += 0.05 * rng.standard_normal((8, 2))
    data = jnp.asarray(yields_panel)
    got = jax.vmap(lambda q: U.get_loss(spec, q, data))(jnp.asarray(batch))
    want = jax.vmap(lambda q: K.get_loss(spec, q, data))(jnp.asarray(batch))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-8)
