"""Direct unit tests of ``config.engines_for`` / ``config.tree_engine_for``
— THE engine-applicability introspection seam (docs/DESIGN.md §19).

Two layers: the capability-flag matrix on synthetic stub specs (every flag
combination → its exact engine tuple, so the seam's contract is pinned
independently of any family), and the real-spec rows including the
program-compiled specs (program/), plus the ``api.get_loss`` validation
errors that must name the valid set.
"""

import types

import numpy as np
import pytest

from yieldfactormodels_jl_tpu import config

MATS = tuple(np.array([3, 12, 24, 60, 120, 240, 360]) / 12.0)


def _stub(is_kalman=False, constant=False, is_msed=False, score_tree=False):
    """A synthetic spec carrying ONLY the capability flags engines_for
    reads — proof the seam is property-driven, never family-string-driven."""
    return types.SimpleNamespace(
        is_kalman=is_kalman, has_constant_measurement=constant,
        is_msed=is_msed, supports_score_tree=score_tree)


@pytest.mark.parametrize("flags,want", [
    # constant-Z Kalman: the full registry, assoc included
    (dict(is_kalman=True, constant=True), config.KALMAN_ENGINES),
    # state-dependent-Z Kalman: everything but assoc (slr is the tree)
    (dict(is_kalman=True, constant=False),
     tuple(e for e in config.KALMAN_ENGINES if e != "assoc")),
    # plain-gradient score-driven: scan + score_tree
    (dict(is_msed=True, score_tree=True), config.MSED_ENGINES),
    # EWMA scale_grad lineage: sequential scan only
    (dict(is_msed=True, score_tree=False),
     tuple(e for e in config.MSED_ENGINES if e != "score_tree")),
    # static families: no state recursion, no engine choice
    (dict(), ()),
])
def test_engines_for_capability_matrix(flags, want):
    assert config.engines_for(_stub(**flags)) == want


@pytest.mark.parametrize("flags,want", [
    (dict(is_kalman=True, constant=True), "assoc"),
    (dict(is_kalman=True, constant=False), "slr"),
    (dict(is_msed=True, score_tree=True), "score_tree"),
    (dict(is_msed=True, score_tree=False), None),
    (dict(), None),
])
def test_tree_engine_for_capability_matrix(flags, want):
    assert config.tree_engine_for(_stub(**flags)) == want


def test_engines_for_real_spec_rows():
    """The matrix on real compiled specs — zoo families and both shipped
    programs resolve through the same properties."""
    import yieldfactormodels_jl_tpu as yfm

    no_assoc = tuple(e for e in config.KALMAN_ENGINES if e != "assoc")
    no_tree = tuple(e for e in config.MSED_ENGINES if e != "score_tree")
    rows = {
        "1C": config.KALMAN_ENGINES,
        "AFNS3": config.KALMAN_ENGINES,
        "TVλ": no_assoc,
        "SD-NS": config.MSED_ENGINES,      # plain-gradient λ-MSED
        "SSD-NS": no_tree,                 # scale_grad lineage
        "NS": (),                          # static: closed-form regression
        "prog-dns": config.KALMAN_ENGINES,
        "svensson4": config.KALMAN_ENGINES,
    }
    for code, want in rows.items():
        spec, _ = yfm.create_model(code, MATS, float_type="float64")
        assert config.engines_for(spec) == want, code


def test_get_loss_rejects_inapplicable_engine_naming_valid_set():
    import yieldfactormodels_jl_tpu as yfm
    from yieldfactormodels_jl_tpu.models import api

    spec, _ = yfm.create_model("TVλ", MATS, float_type="float64")
    p = np.zeros(spec.n_params)
    data = np.zeros((len(MATS), 8))
    with pytest.raises(ValueError, match="engines_for lists"):
        api.get_loss(spec, p, data, engine="assoc")
    with pytest.raises(ValueError, match="unknown kalman engine"):
        api.get_loss(spec, p, data, engine="bogus")
    static_spec, _ = yfm.create_model("NS", MATS, float_type="float64")
    ps = np.zeros(static_spec.n_params)
    with pytest.raises(ValueError, match="engines_for lists"):
        api.get_loss(static_spec, ps, data, engine="assoc")


def test_get_loss_rejects_score_tree_with_k_replay():
    import yieldfactormodels_jl_tpu as yfm
    from yieldfactormodels_jl_tpu.models import api

    spec, _ = yfm.create_model("SD-NS", MATS, float_type="float64")
    p = np.zeros(spec.n_params)
    data = np.zeros((len(MATS), 8))
    with pytest.raises(ValueError, match="K=1"):
        api.get_loss(spec, p, data, K=2, engine="score_tree")
